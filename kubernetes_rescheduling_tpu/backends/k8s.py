"""Live-Kubernetes adapter — the thin host-side shell around the TPU core.

Clean-room implementation of the reference's cluster I/O semantics
(SURVEY.md §5.3, §2):

- snapshot: node list (control-plane excluded), node capacity + usage from
  ``metrics.k8s.io/v1beta1``, per-pod usage with containers summed, and the
  Pod→ReplicaSet→Deployment owner-chain walk
  (reference podmonitor.py:7-125, get_resource_usage.py:5-68,
  delete_replaced_pod.py:25-38);
- teardown: foreground cascade delete then poll for the 404 up to 180 s at
  1.5 s (reference delete_replaced_pod.py:8-22, 173-177);
- re-create: a minimal re-deployable spec (kept container keys, forced
  ``imagePullPolicy: IfNotPresent``, ``schedulerName: default-scheduler`` —
  reference delete_replaced_pod.py:64-142), patched with a NodeAffinity
  ``NotIn <hazard nodes>`` rule (reference rescheduling.py:42-55) and pinned
  per the policy's mechanism: ``nodeSelector`` for spread/binpack
  (rescheduling.py:103,135), ``nodeName`` for random/CAR
  (rescheduling.py:155,216), affinity-only for kubescheduling
  (rescheduling.py:167-171).

The adapter never imports jax and is never traced. It works against any
object exposing the small slice of the Kubernetes client API it touches, so
tests run with fakes and production runs with the real ``kubernetes``
package (constructed lazily — the package is optional).
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Any, Callable

from kubernetes_rescheduling_tpu.backends.base import MoveRequest
from kubernetes_rescheduling_tpu.core.quantities import cpu_to_millicores, mem_to_bytes
from kubernetes_rescheduling_tpu.core.state import ClusterState, CommGraph, UNASSIGNED
from kubernetes_rescheduling_tpu.core.workmodel import Workmodel

# telemetry.accounting is jax-free by design — safe here despite the
# adapter's never-imports-jax contract
from kubernetes_rescheduling_tpu.telemetry.accounting import (
    count_reconcile,
    timed_call,
)
from kubernetes_rescheduling_tpu.telemetry.registry import get_registry
# utils.logging / utils.retry likewise use no jax themselves (the utils
# package resolves its jax-importing members lazily)
from kubernetes_rescheduling_tpu.utils.logging import StructuredLogger, get_logger
from kubernetes_rescheduling_tpu.utils.retry import (
    RetryPolicy,
    call_with_retry,
    is_transient,
)

logger = logging.getLogger(__name__)


def _is_api_error(e: BaseException) -> bool:
    """What the adapter may swallow: transport-level failures plus anything
    carrying an HTTP ``status`` (the real client's ``ApiException`` and the
    test fakes' stand-in). ``RuntimeError`` is included because the
    kubernetes client surfaces some config/transport failures as plain
    ``RuntimeError`` — but its interpreter-level subclasses
    (``RecursionError``/``NotImplementedError``) are coding bugs, not API
    weather, and stay fatal, as do ``TypeError``/``KeyError``/… — the bare
    ``except Exception`` blocks this replaces hid all of those."""
    if isinstance(e, (RecursionError, NotImplementedError)):
        return False
    return isinstance(
        e, (ConnectionError, TimeoutError, OSError, RuntimeError)
    ) or hasattr(e, "status")


# worth another attempt = the SHARED transient predicate (utils.retry):
# transport errors and throttling/server-side statuses; a definitive API
# answer (404, 403, 422, …) never is. One definition with the controller
# boundary, so the two layers can't disagree on what retries.
_retryable = is_transient

# policy name -> how the reference pins the re-created Deployment
PlacementMechanism: dict[str, str] = {
    "spread": "nodeSelector",
    "binpack": "nodeSelector",
    "random": "nodeName",
    "communication": "nodeName",
    "kubescheduling": "affinityOnly",
    "global": "nodeName",
}


def _get(obj: Any, *names: str, default=None):
    """Attribute-or-key access tolerant of client models and plain dicts."""
    for name in names:
        if obj is None:
            return default
        if isinstance(obj, dict):
            if name in obj:
                obj = obj[name]
                continue
            return default
        if hasattr(obj, name):
            obj = getattr(obj, name)
            continue
        return default
    return obj if obj is not None else default


def exclude_hazard_affinity(hazard_nodes: list[str]) -> dict:
    """NodeAffinity NotIn rule (reference rescheduling.py:42-55)."""
    return {
        "nodeAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": {
                "nodeSelectorTerms": [
                    {
                        "matchExpressions": [
                            {
                                "key": "kubernetes.io/hostname",
                                "operator": "NotIn",
                                "values": list(hazard_nodes),
                            }
                        ]
                    }
                ]
            }
        }
    }


def merge_affinity(orig: dict | None, patch: dict) -> dict:
    """Merge an affinity patch into an existing affinity dict.

    One rule, applied recursively at every depth: two dicts merge key-wise,
    two lists concatenate (extra ``nodeSelectorTerms``/``matchExpressions``
    accumulate instead of clobbering what the Deployment already had), and
    any other collision resolves to the patch value. This is deliberately
    MORE general than reference rescheduling.py:21-40 (a hand-rolled merge
    fixed at the hazard patch's exact 3-level nesting); for that patch shape
    the two agree, but at other depths this rule keeps merging/concatenating
    where the reference would clobber with the patch value.
    """
    import copy

    def merge(a, b):
        if isinstance(a, dict) and isinstance(b, dict):
            out = dict(a)
            for k, v in b.items():
                out[k] = merge(a[k], v) if k in a else v
            return out
        if isinstance(a, list) and isinstance(b, list):
            return [*a, *b]
        return b

    return merge(copy.deepcopy(orig) if orig else {}, copy.deepcopy(patch))


def _strip_placement(tmpl_spec: dict) -> None:
    """Remove placement state a PREVIOUS move wrote into the pod template:
    the hostname nodeSelector key and any hostname-keyed matchExpressions
    in the required nodeAffinity (the hazard NotIn rules). User-authored
    constraints on other keys (e.g. ``disktype: ssd``) are left
    untouched."""
    selector = dict(tmpl_spec.get("nodeSelector") or {})
    selector.pop("kubernetes.io/hostname", None)
    tmpl_spec["nodeSelector"] = selector or None
    affinity = tmpl_spec.get("affinity")
    node_aff = (affinity or {}).get("nodeAffinity") or {}
    req = node_aff.get("requiredDuringSchedulingIgnoredDuringExecution") or {}
    terms = req.get("nodeSelectorTerms") or []
    new_terms = []
    for term in terms:
        exprs = [
            e
            for e in (term.get("matchExpressions") or [])
            if e.get("key") != "kubernetes.io/hostname"
        ]
        if exprs or term.get("matchFields"):
            new_terms.append({**term, "matchExpressions": exprs})
    if terms and not new_terms:
        node_aff.pop("requiredDuringSchedulingIgnoredDuringExecution", None)
    elif new_terms:
        req["nodeSelectorTerms"] = new_terms
    if affinity and not node_aff:
        affinity.pop("nodeAffinity", None)
    if affinity is not None and not affinity:
        tmpl_spec["affinity"] = None


_KEPT_CONTAINER_KEYS = (
    "name",
    "image",
    "imagePullPolicy",
    "ports",
    "env",
    "resources",
    "volumeMounts",
)


def extract_redeployable_spec(dep: dict) -> dict:
    """Minimal dict body that re-creates a Deployment (reference
    delete_replaced_pod.py:64-142). Input must be dict-shaped (the real
    client's ``sanitize_for_serialization`` output)."""
    meta = dep.get("metadata", {}) or {}
    spec = dep.get("spec", {}) or {}
    tmpl = spec.get("template", {}) or {}
    tmpl_meta = tmpl.get("metadata", {}) or {}
    tmpl_spec = tmpl.get("spec", {}) or {}
    containers = []
    for c in tmpl_spec.get("containers", []) or []:
        kept = {k: v for k, v in c.items() if k in _KEPT_CONTAINER_KEYS}
        kept["imagePullPolicy"] = "IfNotPresent"
        containers.append(kept)
    return {
        "apiVersion": dep.get("apiVersion", "apps/v1"),
        "kind": dep.get("kind", "Deployment"),
        "metadata": {
            "name": meta.get("name"),
            "namespace": meta.get("namespace", "default"),
            "labels": dict(meta.get("labels") or {}),
        },
        "spec": {
            "replicas": spec.get("replicas", 1),
            "selector": spec.get("selector"),
            "strategy": spec.get("strategy"),
            "template": {
                "metadata": {
                    "labels": dict(tmpl_meta.get("labels") or {}),
                    "annotations": dict(tmpl_meta.get("annotations") or {}),
                },
                "spec": {
                    "containers": containers,
                    "volumes": tmpl_spec.get("volumes") or None,
                    "restartPolicy": "Always",
                    "terminationGracePeriodSeconds": tmpl_spec.get(
                        "terminationGracePeriodSeconds"
                    ),
                    "dnsPolicy": "ClusterFirst",
                    "nodeSelector": tmpl_spec.get("nodeSelector") or None,
                    "affinity": tmpl_spec.get("affinity"),
                    "schedulerName": "default-scheduler",
                },
            },
        },
    }


@dataclass
class K8sBackend:
    """Adapter over a live cluster (or a fake implementing the same calls)."""

    # the Deployment mechanism cannot pin ONE replica (_apply_move raises
    # for pod-granular moves); the reconcile plane reads this and issues
    # Deployment-scoped repairs instead of crashing on a ValueError
    supports_pod_moves = False

    workmodel: Workmodel
    core_api: Any = None
    apps_api: Any = None
    custom_api: Any = None
    namespace: str = "default"
    control_plane_names: tuple[str, ...] = ("master",)  # reference podmonitor.py:45
    delete_timeout_s: float = 180.0
    delete_poll_interval_s: float = 1.5
    node_capacity: int | None = None
    pod_capacity: int | None = None
    # teardown outage estimate for disruption accounting (the window in
    # which a moved Deployment serves nothing). Starts as a conservative
    # default and is replaced by the MEASURED delete→404→recreate wall time
    # after each successful move, so the harness's release2-style outage
    # windows track what the cluster actually does rather than zero.
    reconcile_delay_s: float = 10.0
    sleeper: Callable[[float], None] = field(default=time.sleep)
    # every API call below routes through this policy (transport errors and
    # 429/5xx retried with backoff + jitter; definitive statuses never).
    # Deliberately SHORT: run_controller's BoundaryClient retries the whole
    # boundary call one layer up, so the layers multiply — this inner
    # policy handles single-request blips (one quick re-send), the outer
    # one call-level failures, and a dead cluster still reaches the
    # circuit breaker in seconds, not minutes.
    retry: RetryPolicy = field(
        default_factory=lambda: RetryPolicy(
            max_attempts=2, base_delay_s=0.5, max_delay_s=2.0, deadline_s=10.0
        )
    )
    slog: StructuredLogger = field(default_factory=lambda: get_logger("k8s"))

    def _api(self, label: str, fn: Callable[[], Any]) -> Any:
        """One cluster API call under the shared retry policy."""
        return call_with_retry(
            fn,
            policy=self.retry,
            label=f"k8s.{label}",
            retryable=_retryable,
            sleeper=self.sleeper,
        )

    def _swallow(self, call: str, exc: BaseException) -> None:
        """An API error this adapter deliberately absorbs: logged through
        the structured logger and counted — never silent."""
        self.slog.warn("swallowed_error", call=call, error=repr(exc))
        get_registry().counter(
            "backend_swallowed_errors_total",
            "API errors a backend absorbed instead of raising",
            labelnames=("backend", "call"),
        ).labels(backend="k8s", call=call).inc()

    def __post_init__(self) -> None:
        if self.core_api is None or self.apps_api is None or self.custom_api is None:
            # lazy: only needed for a real cluster
            from kubernetes import client, config  # type: ignore

            config.load_kube_config()
            self.core_api = self.core_api or client.CoreV1Api()
            self.apps_api = self.apps_api or client.AppsV1Api()
            self.custom_api = self.custom_api or client.CustomObjectsApi()
        self._graph = self.workmodel.comm_graph()
        self._svc_index = {n: i for i, n in enumerate(self.workmodel.names)}
        # monitor short-circuit memo (first concrete step toward the
        # watch-driven snapshot path): the parsed cluster STRUCTURE —
        # node table + capacities + the pod→Deployment owner mapping —
        # keyed by the (node list, pod list) resourceVersion pair. While
        # neither list object changed between polls, the per-pod
        # owner-chain walks (one ReplicaSet read per pod) are skipped
        # and only usage metrics are re-fetched; clients that expose no
        # resourceVersion (older fakes) never engage it.
        self._struct_memo: tuple[tuple[str, str], dict] | None = None
        # per-pod owner memo: a pod name's owner chain is immutable for
        # that pod's lifetime (a re-created pod gets a new hash-suffixed
        # name), so the ReplicaSet walk is cached by pod name even when
        # the LIST resourceVersions churn — on a busy apiserver the list
        # RV advances with the cluster-global storage revision (Lease
        # heartbeats, events), so without this the struct memo alone
        # would ~never save the walks in production. Pruned to the
        # current listing each rebuild, so deleted pods don't accumulate.
        self._owner_memo: dict[str, str | None] = {}

    def comm_graph(self) -> CommGraph:
        return self._graph

    # ---- snapshot ----

    def _deployment_for_pod(self, pod: Any) -> str | None:
        """Pod→ReplicaSet→Deployment owner walk (reference
        delete_replaced_pod.py:25-38)."""
        owners = _get(pod, "metadata", "owner_references") or _get(
            pod, "metadata", "ownerReferences", default=[]
        ) or []
        for o in owners:
            kind = _get(o, "kind")
            if kind == "Deployment":
                return _get(o, "name")
            if kind == "ReplicaSet":
                rs = self._api(
                    "read_replica_set",
                    lambda: self.apps_api.read_namespaced_replica_set(
                        _get(o, "name"), self.namespace
                    ),
                )
                for ro in (
                    _get(rs, "metadata", "owner_references")
                    or _get(rs, "metadata", "ownerReferences", default=[])
                    or []
                ):
                    if _get(ro, "kind") == "Deployment":
                        return _get(ro, "name")
        return None

    def monitor(self) -> ClusterState:
        """Build the padded snapshot (reference podmonitor.py:7-125)."""
        with timed_call("k8s", "monitor"):
            return self._monitor()

    @staticmethod
    def _list_rv(obj) -> str | None:
        rv = _get(obj, "metadata", "resource_version") or _get(
            obj, "metadata", "resourceVersion"
        )
        return str(rv) if rv else None

    def _monitor(self) -> ClusterState:
        nodes = self._api("list_node", lambda: self.core_api.list_node(watch=False))
        pods_items, pods_rv = self._list_namespace_pods_rv()
        nodes_rv = self._list_rv(nodes)
        struct = None
        if (
            nodes_rv is not None
            and pods_rv is not None
            and self._struct_memo is not None
            and self._struct_memo[0] == (nodes_rv, pods_rv)
        ):
            # nothing changed between polls: reuse the parsed structure,
            # skip the owner-chain walks, fetch only fresh usage metrics
            struct = self._struct_memo[1]
            get_registry().counter(
                "backend_monitor_short_circuits_total",
                "monitor polls that reused the previous poll's parsed "
                "cluster structure because both list resourceVersions "
                "were unchanged (per-pod owner-chain walks skipped; "
                "usage metrics stay fresh)",
                labelnames=("backend",),
            ).labels(backend="k8s").inc()
        if struct is None:
            node_names = self._worker_names(nodes)
            cap_cpu: dict[str, float] = {}
            cap_mem: dict[str, float] = {}
            for n in _get(nodes, "items", default=[]):
                name = _get(n, "metadata", "name")
                capacity = _get(n, "status", "capacity", default={}) or {}
                cap_cpu[name] = float(
                    cpu_to_millicores(str(capacity.get("cpu", "0")))
                )
                cap_mem[name] = float(
                    mem_to_bytes(str(capacity.get("memory", "0")))
                )
            entries: list[tuple[str, int, str | None]] = []
            owner_memo: dict[str, str | None] = {}
            for p in pods_items:
                name = _get(p, "metadata", "name")
                if name in self._owner_memo:
                    dep = self._owner_memo[name]
                else:
                    dep = self._deployment_for_pod(p)
                owner_memo[name] = dep
                if dep is None or dep not in self._svc_index:
                    continue
                node = _get(p, "spec", "node_name") or _get(
                    p, "spec", "nodeName"
                )
                entries.append((name, self._svc_index[dep], node))
            self._owner_memo = owner_memo  # pruned to the live listing
            struct = {
                "node_names": node_names,
                "cap_cpu": cap_cpu,
                "cap_mem": cap_mem,
                "pods": entries,
            }
            if nodes_rv is not None and pods_rv is not None:
                self._struct_memo = ((nodes_rv, pods_rv), struct)
        node_names = struct["node_names"]
        cap_cpu = struct["cap_cpu"]
        cap_mem = struct["cap_mem"]

        # node usage (metrics-server) — used to derive per-node base load
        node_used: dict[str, float] = {}
        node_used_mem: dict[str, float] = {}
        try:
            res = self._api(
                "node_metrics",
                lambda: self.custom_api.list_cluster_custom_object(
                    "metrics.k8s.io", "v1beta1", "nodes"
                ),
            )
            for item in res.get("items", []):
                name = item["metadata"]["name"]
                node_used[name] = float(cpu_to_millicores(item["usage"]["cpu"]))
                node_used_mem[name] = float(mem_to_bytes(item["usage"]["memory"]))
        except Exception as e:
            if not _is_api_error(e):
                raise
            # metrics-server absent → usage stays 0 (reference podmonitor.py:86-87)
            self._swallow("monitor.node_metrics", e)

        # pod usage, containers summed (reference get_resource_usage.py:48-68)
        pod_usage: dict[str, tuple[float, float]] = {}
        try:
            res = self._api(
                "pod_metrics",
                lambda: self.custom_api.list_namespaced_custom_object(
                    "metrics.k8s.io", "v1beta1", self.namespace, "pods"
                ),
            )
            for item in res.get("items", []):
                cpu = sum(
                    cpu_to_millicores(c["usage"]["cpu"])
                    for c in item.get("containers", [])
                )
                mem = sum(
                    mem_to_bytes(c["usage"]["memory"])
                    for c in item.get("containers", [])
                )
                pod_usage[item["metadata"]["name"]] = (float(cpu), float(mem))
        except Exception as e:
            if not _is_api_error(e):
                raise
            self._swallow("monitor.pod_metrics", e)

        services, pod_nodes, pod_cpu, pod_mem, pod_names = [], [], [], [], []
        tracked_cpu = {n: 0.0 for n in node_names}
        tracked_mem = {n: 0.0 for n in node_names}
        for name, svc_idx, node in struct["pods"]:
            cpu, mem = pod_usage.get(name, (0.0, 0.0))
            services.append(svc_idx)
            pod_nodes.append(node_names.index(node) if node in node_names else UNASSIGNED)
            pod_cpu.append(cpu)
            pod_mem.append(mem)
            pod_names.append(name)
            if node in tracked_cpu:
                tracked_cpu[node] += cpu
                tracked_mem[node] += mem

        # base = measured node usage minus tracked pod usage (system daemons)
        base_cpu = [
            max(node_used.get(n, 0.0) - tracked_cpu[n], 0.0) for n in node_names
        ]
        base_mem = [
            max(node_used_mem.get(n, 0.0) - tracked_mem[n], 0.0) for n in node_names
        ]
        return ClusterState.build(
            node_names=node_names,
            node_cpu_cap=[cap_cpu.get(n, 0.0) for n in node_names],
            node_mem_cap=[cap_mem.get(n, 0.0) for n in node_names],
            pod_services=services,
            pod_nodes=pod_nodes,
            pod_cpu=pod_cpu,
            pod_mem=pod_mem,
            pod_names=pod_names,
            node_base_cpu=base_cpu,
            node_base_mem=base_mem,
            node_capacity=self.node_capacity,
            pod_capacity=self.pod_capacity,
        )

    def _worker_names(self, nodes) -> list[str]:
        """Control-plane filter shared by monitor() and node_names."""
        return [
            _get(n, "metadata", "name")
            for n in _get(nodes, "items", default=[]) or []
            if _get(n, "metadata", "name") not in self.control_plane_names
        ]

    @property
    def node_names(self) -> list[str]:
        """Worker node names (control plane excluded), freshly listed."""
        return self._worker_names(
            self._api("list_node", lambda: self.core_api.list_node(watch=False))
        )

    def cordon(self, node: str) -> bool:
        """``kubectl cordon``: mark the node unschedulable (reference
        auto_full_pipeline_repeat.sh:48-50 cordons worker2/worker3 before
        deploying so everything lands on worker1)."""
        return self._set_unschedulable(node, True)

    def uncordon(self, node: str) -> bool:
        return self._set_unschedulable(node, False)

    def _set_unschedulable(self, node: str, value: bool) -> bool:
        try:
            self.core_api.patch_node(node, {"spec": {"unschedulable": value}})
            return True
        except Exception as e:
            logger.warning("cordon(%s, %s) failed: %s", node, value, e)
            return False

    def inject_imbalance(self, node: str) -> None:
        """The reference pipeline's "Before" construction on a live
        cluster: cordon every OTHER worker, re-create each tracked
        Deployment unpinned (the scheduler can only choose ``node``), then
        uncordon (reference auto_full_pipeline_repeat.sh:48-58 — cordon,
        redeploy µBench, continue). Same call shape as the simulator's
        ``inject_imbalance``, so the harness drives both backends
        identically."""
        workers = self.node_names
        if node not in workers:
            # matching the simulator's behavior: a typo'd target must fail
            # loudly, not cordon EVERY worker and strand the pods Pending
            raise ValueError(f"unknown node {node!r}; workers: {workers}")
        others = [n for n in workers if n != node]
        cordoned = [n for n in others if self.cordon(n)]
        try:
            for svc in self.workmodel.names:
                # affinityOnly with no hazard list = plain delete+recreate
                # with the scheduler choosing; only `node` is schedulable
                self.apply_move(
                    MoveRequest(
                        service=svc, target_node=node, mechanism="affinityOnly"
                    )
                )
        finally:
            for n in cordoned:
                self.uncordon(n)

    def _list_namespace_pods_rv(self) -> tuple[list, str | None]:
        """This namespace's pods plus the LIST object's resourceVersion
        (the short-circuit memo key; None when the client exposes none):
        server-side filtering when the client offers
        ``list_namespaced_pod``, else the all-namespaces listing
        filtered here — ONE shared convention for every pod-listing
        caller (snapshot and restart probe alike)."""
        lister = getattr(self.core_api, "list_namespaced_pod", None)
        if lister is not None:
            pods = self._api(
                "list_pods", lambda: lister(self.namespace, watch=False)
            )
            return (_get(pods, "items", default=[]) or [], self._list_rv(pods))
        pods = self._api(
            "list_pods",
            lambda: self.core_api.list_pod_for_all_namespaces(watch=False),
        )
        items = [
            p
            for p in (_get(pods, "items", default=[]) or [])
            if _get(p, "metadata", "namespace") == self.namespace
        ]
        return (items, self._list_rv(pods))

    def _list_namespace_pods(self) -> list:
        return self._list_namespace_pods_rv()[0]

    def pod_restart_counts(self) -> dict[str, int] | None:
        """Per-pod container ``restartCount`` sums over the namespace —
        the raw data of the reference's experiment-health metric
        (release1.sh:101-102: kubectl jsonpath over
        ``status.containerStatuses[*].restartCount``). Per-pod, not a
        cluster total, so the harness can compute a crash delta that
        survives delete+recreate (a moved Deployment's fresh pods start at
        0; a single cluster-wide total would go NEGATIVE and mask real
        crashes). ``None`` when the listing fails."""
        try:
            items = self._list_namespace_pods()
        except Exception as e:
            if not _is_api_error(e):
                raise
            self._swallow("pod_restart_counts", e)
            return None
        out: dict[str, int] = {}
        for p in items:
            name = _get(p, "metadata", "name")
            statuses = (
                _get(p, "status", "container_statuses")
                or _get(p, "status", "containerStatuses", default=[])
                or []
            )
            total = 0
            for cs in statuses:
                count = _get(cs, "restart_count")
                if count is None:
                    count = _get(cs, "restartCount", default=0)
                total += int(count or 0)
            out[str(name)] = total
        return out

    # ---- reconcile ----

    def _wait_deleted(self, name: str) -> bool:
        """Poll for the 404 (reference delete_replaced_pod.py:8-22).

        Transient non-404 errors are logged and retried until the poll
        budget runs out instead of raised: at this point the Deployment has
        already been foreground-deleted, and crashing the controller here
        would lose the workload — the exact reference flaw the round loop
        is built to avoid. The wait is bounded both ways: a poll budget
        (timeout / interval) so an injected fast/no-op sleeper shortens the
        wait instead of busy-spinning the API server for the full real-time
        window, AND the wall-clock deadline so slow API calls can never
        stretch the stall past ``delete_timeout_s``.
        """
        interval = max(self.delete_poll_interval_s, 1e-9)
        polls = max(1, int(round(self.delete_timeout_s / interval)))
        deadline = time.monotonic() + self.delete_timeout_s
        for _ in range(polls):
            if time.monotonic() > deadline:
                return False
            try:
                self.apps_api.read_namespaced_deployment(
                    name=name, namespace=self.namespace
                )
            except Exception as e:
                if getattr(e, "status", None) == 404:
                    return True
                logger.warning(
                    "wait_deleted(%s): non-404 error while polling: %s", name, e
                )
            self.sleeper(interval)
        return False

    def _wait_ready(self, name: str) -> bool:
        """Poll until the re-created Deployment reports every replica ready —
        the true end of the serving outage. ``create_namespaced_deployment``
        returning only means the API accepted the object; scheduling, image
        pull, and readiness gates dominate the real restoration time, so
        stamping the teardown measurement at create-acceptance would
        systematically understate disruption. Bounded exactly like
        :meth:`_wait_deleted` (poll budget + wall-clock deadline)."""
        interval = max(self.delete_poll_interval_s, 1e-9)
        polls = max(1, int(round(self.delete_timeout_s / interval)))
        deadline = time.monotonic() + self.delete_timeout_s
        for _ in range(polls):
            if time.monotonic() > deadline:
                return False
            try:
                dep = self.apps_api.read_namespaced_deployment(
                    name=name, namespace=self.namespace
                )
                want = _get(dep, "spec", "replicas")
                want = 1 if want is None else int(want)
                if want <= 0:
                    return True  # scaled to zero: nothing to wait for
                ready = (
                    _get(dep, "status", "ready_replicas")
                    or _get(dep, "status", "readyReplicas")
                    or 0
                )
                if int(ready) >= want:
                    return True
            except Exception as e:
                logger.warning("wait_ready(%s): error while polling: %s", name, e)
            self.sleeper(interval)
        return False

    def apply_move(self, move: MoveRequest) -> str | None:
        """Foreground delete + pinned re-create (reference
        delete_replaced_pod.py:144-185 + rescheduling.py:57-73). Returns the
        landing node on success (the advisory target for ``affinityOnly`` —
        the live scheduler's pick is only observable at the next monitor)."""
        with timed_call("k8s", "apply_move"):
            return self._apply_move(move)

    def _apply_move(self, move: MoveRequest) -> str | None:
        if move.pod is not None:
            # deleting one pod of a Deployment only makes its ReplicaSet
            # re-create it wherever the scheduler likes — there is no
            # Deployment-level mechanism to pin a single replica. Honest
            # failure beats silently moving every replica.
            raise ValueError(
                "per-pod moves are not expressible through the k8s "
                "Deployment mechanism (a deleted replica is re-created "
                "unpinned by its ReplicaSet); run placement_unit='pod' "
                "against the sim backend, or manage bare pods"
            )
        name = move.service
        try:
            dep = self._api(
                "read_deployment",
                lambda: self.apps_api.read_namespaced_deployment(
                    name=name, namespace=self.namespace
                ),
            )
        except Exception as e:
            if not _is_api_error(e):
                raise
            self._swallow("apply_move.read_deployment", e)
            return None
        if not isinstance(dep, dict):
            # real client model → plain dict
            from kubernetes.client import ApiClient  # type: ignore

            dep = ApiClient().sanitize_for_serialization(dep)
        body = extract_redeployable_spec(dep)

        tmpl_spec = body["spec"]["template"]["spec"]
        # each move expresses the CURRENT decision only: leftover pins from
        # a previous move's mechanism (a nodeSelector, or a stale
        # hostname-NotIn hazard rule) would otherwise survive re-creation
        # and silently override this round's placement — e.g. an
        # affinityOnly re-create staying pinned to a cordoned node
        _strip_placement(tmpl_spec)
        if move.hazard_nodes:
            tmpl_spec["affinity"] = merge_affinity(
                tmpl_spec.get("affinity"), exclude_hazard_affinity(list(move.hazard_nodes))
            )
        if move.mechanism == "nodeSelector":
            tmpl_spec["nodeSelector"] = {"kubernetes.io/hostname": move.target_node}
        elif move.mechanism == "nodeName":
            tmpl_spec["nodeName"] = move.target_node
        elif move.mechanism != "affinityOnly":
            raise ValueError(f"unknown mechanism {move.mechanism!r}")

        t0 = time.monotonic()
        try:
            self._api(
                "delete_deployment",
                lambda: self.apps_api.delete_namespaced_deployment(
                    name=name,
                    namespace=self.namespace,
                    body={"propagationPolicy": "Foreground"},
                ),
            )
        except Exception as e:
            if not _is_api_error(e):
                raise
            if getattr(e, "status", None) != 404:  # already gone = fine
                # transient failure: skip the round, keep the loop alive
                self._swallow("apply_move.delete_deployment", e)
                return None
        if not self._wait_deleted(name):
            return None  # timeout → skip round (reference delete_replaced_pod.py:178-180)
        try:
            self._api(
                "create_deployment",
                lambda: self.apps_api.create_namespaced_deployment(
                    namespace=self.namespace, body=body
                ),
            )
        except Exception as e:
            if not _is_api_error(e):
                raise
            if getattr(e, "status", None) != 409:
                self._swallow("apply_move.create_deployment", e)
                return None
            # 409 AlreadyExists after our own delete→404 wait: the first
            # create attempt landed but its response was lost and the
            # retry collided with it — the move SUCCEEDED (mirror of the
            # "404 on delete = already gone" rule above); reporting None
            # here would undercount services_moved and feed the breaker
            # for a move the cluster actually applied
        # outage window = delete → 404 → re-create → pods READY (a ready
        # timeout still stamps the elapsed budget — conservative, not zero);
        # the floor keeps a fake-client test run from zeroing the accounting
        self._wait_ready(name)
        self.reconcile_delay_s = max(time.monotonic() - t0, 1e-3)
        # a whole-Deployment move restarts every replica
        count_reconcile("k8s", int(body["spec"].get("replicas") or 1))
        return move.target_node

    def advance(self, seconds: float) -> None:
        self.sleeper(seconds)
