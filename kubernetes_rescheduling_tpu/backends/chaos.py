"""Fault-injecting wrapper around any ``Backend`` — the chaos-engineering
treatment (Chaos Monkey / chaos-mesh style) for the control loop.

``ChaosBackend`` wraps a real backend and injects seeded, configurable
faults at the exact surface the controller consumes:

- ``monitor()`` exceptions (:class:`ChaosError`), stale snapshots (the
  previous round's state served again), partial snapshots (a random
  subset of pods dropped from validity — a watch cache that lags), and
  transient ``None`` returns;
- ``apply_move`` exceptions, timeouts (:class:`ChaosTimeoutError`, after
  the move's wall budget has visibly been consumed on the inner clock),
  transient ``None`` returns (the protocol's "move failed" signal), and
  moves that land on the WRONG node (a scheduler override / race);
- node crash/flap sequences: every ``node_flap_period`` monitors a worker
  is killed and revived ``node_flap_down_calls`` monitors later (needs an
  inner backend exposing ``kill_node``/``revive_node`` — the simulator).

Every injected fault is counted twice: in the process telemetry registry
as ``chaos_faults_total{kind=...}`` and in the wrapper's own
``fault_counts`` dict — the chaos soak test asserts the two agree, which
pins the telemetry wiring end to end.

Faults draw from one seeded ``random.Random``, so a chaos run is exactly
reproducible; everything the profile does not inject passes straight
through (``__getattr__`` forwards ``node_names``, ``inject_imbalance``,
``restore_placement``, ``events``, …).
"""

from __future__ import annotations

import dataclasses
import random
from dataclasses import dataclass
from typing import Any

import numpy as np

from kubernetes_rescheduling_tpu.backends.base import Backend, MoveRequest
from kubernetes_rescheduling_tpu.core.state import ClusterState, CommGraph
from kubernetes_rescheduling_tpu.telemetry.registry import get_registry


class ChaosError(ConnectionError):
    """Injected boundary failure (transient by construction)."""


class ChaosTimeoutError(TimeoutError):
    """Injected boundary timeout; the inner clock has already advanced."""


@dataclass(frozen=True)
class ChaosProfile:
    """Per-call fault probabilities plus the node-flap schedule."""

    name: str = "custom"
    monitor_error_rate: float = 0.0    # monitor() raises ChaosError
    monitor_stale_rate: float = 0.0    # previous snapshot served again
    monitor_partial_rate: float = 0.0  # a random pod subset goes invalid
    monitor_none_rate: float = 0.0     # transient None return
    move_error_rate: float = 0.0       # apply_move raises ChaosError
    move_timeout_rate: float = 0.0     # apply_move raises ChaosTimeoutError
    move_none_rate: float = 0.0        # transient None return (move "failed")
    move_wrong_node_rate: float = 0.0  # lands on a different node
    move_timeout_s: float = 30.0       # clock consumed by an injected timeout
    partial_drop_frac: float = 0.2     # pod fraction dropped by a partial snapshot
    node_flap_period: int = 0          # kill a worker every N monitor calls (0 = off)
    node_flap_down_calls: int = 2      # monitors the worker stays dead
    # reconciliation-plane faults (drawn from a DEDICATED seeded stream —
    # see ChaosBackend._rng_aux — so enabling them never shifts the
    # pre-existing kinds' seeded fault sequence):
    monitor_corrupt_rate: float = 0.0  # NaN/Inf/negative/over-capacity loads
    external_drift_rate: float = 0.0   # a pod moves behind the controller's back
    move_lost_rate: float = 0.0        # apply_move reports success, moves nothing
    corrupt_max_pods: int = 3          # entries poisoned per corrupt snapshot

    def validate(self) -> "ChaosProfile":
        for f in dataclasses.fields(self):
            if f.name.endswith("_rate") or f.name.endswith("_frac"):
                v = getattr(self, f.name)
                if not (0.0 <= v <= 1.0):
                    raise ValueError(f"{f.name} must be in [0, 1], got {v}")
        if self.node_flap_period < 0 or self.node_flap_down_calls < 1:
            raise ValueError("node flap schedule must be non-negative / >= 1")
        if self.corrupt_max_pods < 1:
            raise ValueError("corrupt_max_pods must be >= 1")
        return self


# Named profiles the CLI exposes (``--chaos-profile``). "soak" is the one
# the acceptance soak test runs: monitor failures + move timeouts + node
# flap, hot enough that a 30-round run exercises every degraded path.
PROFILES: dict[str, ChaosProfile] = {
    "none": ChaosProfile(name="none"),
    "flaky-monitor": ChaosProfile(
        name="flaky-monitor",
        monitor_error_rate=0.2,
        monitor_stale_rate=0.1,
        monitor_none_rate=0.05,
    ),
    "flaky-moves": ChaosProfile(
        name="flaky-moves",
        move_error_rate=0.15,
        move_timeout_rate=0.1,
        move_none_rate=0.1,
        move_wrong_node_rate=0.1,
    ),
    "node-flap": ChaosProfile(
        name="node-flap", node_flap_period=5, node_flap_down_calls=2
    ),
    "soak": ChaosProfile(
        name="soak",
        monitor_error_rate=0.25,
        monitor_stale_rate=0.10,
        monitor_partial_rate=0.05,
        monitor_none_rate=0.05,
        move_error_rate=0.15,
        move_timeout_rate=0.15,
        move_none_rate=0.10,
        move_wrong_node_rate=0.10,
        node_flap_period=7,
        node_flap_down_calls=2,
        # reconciliation-plane faults at low rates (dedicated rng stream:
        # the pre-existing kinds' seeded sequence above is unchanged)
        monitor_corrupt_rate=0.08,
        external_drift_rate=0.08,
        move_lost_rate=0.05,
    ),
    # the reconciliation plane's own soak: corrupt metrics + external
    # drift + lost/wrong-node moves + node flap, hot enough that a
    # 30-round run exercises every divergence kind while the boundary
    # stays healthy enough to keep executing rounds (monitor transport
    # faults stay off so every round's snapshot is reconciled)
    "reconcile": ChaosProfile(
        name="reconcile",
        monitor_corrupt_rate=0.30,
        external_drift_rate=0.35,
        move_lost_rate=0.30,
        move_wrong_node_rate=0.30,
        node_flap_period=9,
        node_flap_down_calls=2,
    ),
}


class ChaosBackend:
    """Wrap ``inner`` with the faults of ``profile`` (seeded)."""

    def __init__(
        self,
        inner: Backend,
        profile: ChaosProfile,
        seed: int = 0,
        registry=None,
    ):
        self.inner = inner
        self.profile = profile.validate()
        self.seed = seed
        self.registry = registry  # None = the process default, per call
        self._rng = random.Random(seed)
        # the reconciliation-plane kinds (corrupt/drift/lost) draw from
        # their OWN seeded stream: seeded soaks pinned before those kinds
        # existed must keep their exact fault sequence when a profile
        # turns the new rates on (test-pinned stream stability)
        self._rng_aux = random.Random((seed << 1) ^ 0x5EED)
        self._last_state: ClusterState | None = None
        self._monitor_calls = 0
        self._flapped_node: str | None = None
        self._flap_revive_at = 0
        self.fault_counts: dict[str, int] = {}

    # ---- fault bookkeeping ----

    def _count(self, kind: str) -> None:
        self.fault_counts[kind] = self.fault_counts.get(kind, 0) + 1
        reg = self.registry if self.registry is not None else get_registry()
        reg.counter(
            "chaos_faults_total",
            "faults injected by the chaos backend",
            labelnames=("kind",),
        ).labels(kind=kind).inc()

    def _hit(self, rate: float) -> bool:
        return rate > 0 and self._rng.random() < rate

    def _hit_aux(self, rate: float) -> bool:
        """The new kinds' dedicated stream (see ``_rng_aux``)."""
        return rate > 0 and self._rng_aux.random() < rate

    # ---- Backend protocol ----

    def comm_graph(self) -> CommGraph:
        return self.inner.comm_graph()

    def _flap(self) -> None:
        """Kill/revive sequencing, driven by the monitor-call counter."""
        p = self.profile
        if p.node_flap_period <= 0:
            return
        kill = getattr(self.inner, "kill_node", None)
        revive = getattr(self.inner, "revive_node", None)
        if kill is None or revive is None:
            return  # inner backend cannot express node death
        if (
            self._flapped_node is not None
            and self._monitor_calls >= self._flap_revive_at
        ):
            revive(self._flapped_node)
            self._count("node_revive")
            self._flapped_node = None
        if (
            self._flapped_node is None
            and self._monitor_calls % p.node_flap_period == 0
            and self._monitor_calls > 0
        ):
            names = list(self.inner.node_names)
            if names:
                self._flapped_node = names[self._rng.randrange(len(names))]
                self._flap_revive_at = (
                    self._monitor_calls + p.node_flap_down_calls
                )
                kill(self._flapped_node)
                self._count("node_kill")

    def monitor(self) -> ClusterState | None:
        p = self.profile
        self._monitor_calls += 1
        self._flap()
        if self._hit(p.monitor_error_rate):
            self._count("monitor_error")
            raise ChaosError("chaos: injected monitor failure")
        if self._hit(p.monitor_none_rate):
            self._count("monitor_none")
            return None
        if self._hit(p.monitor_stale_rate) and self._last_state is not None:
            self._count("monitor_stale")
            return self._last_state
        if self._hit_aux(p.external_drift_rate):
            # another actor moves a pod BEFORE the snapshot is taken, so
            # the drift is visible in what this call returns — the
            # reconciliation plane's detect-at-next-snapshot contract
            drift = getattr(self.inner, "external_move_random", None)
            if drift is not None and drift(self._rng_aux) is not None:
                self._count("external_drift")
        state = self.inner.monitor()
        partial = self._hit(p.monitor_partial_rate)
        if partial:
            self._count("monitor_partial")
            state = self._partial(state)
        if self._hit_aux(p.monitor_corrupt_rate):
            self._count("monitor_corrupt")
            # a lying Metrics API: poisoned readings, NOT cached as last
            # good (the admission guard's quarantine reuses last good)
            return self._corrupt(state)
        if partial:
            return state  # deliberately NOT cached as last good
        self._last_state = state
        return state

    def _partial(self, state: ClusterState) -> ClusterState:
        """Drop a random ``partial_drop_frac`` of valid pods — the lagging
        watch-cache snapshot. Shapes are untouched (only validity flips),
        so the decision kernels never retrace."""
        valid = np.asarray(state.pod_valid).copy()
        idx = np.flatnonzero(valid)
        n_drop = int(len(idx) * self.profile.partial_drop_frac)
        if n_drop > 0:
            drop = self._rng.sample(list(idx), n_drop)
            valid[np.asarray(drop, dtype=np.int64)] = False
        import jax.numpy as jnp

        return state.replace(pod_valid=jnp.asarray(valid))

    # the metrics-corruption menu: each poisoned entry draws one of these
    # (the admission guard must classify every class — quarantine for the
    # first three, clamp-and-count for the impossibly-large reading)
    _CORRUPT_MODES = ("nan", "inf", "negative", "huge")

    def _corrupt(self, state: ClusterState) -> ClusterState:
        """Poison 1..corrupt_max_pods valid pod USAGE readings — cpu or
        memory, the two fields the Metrics API actually reports (node
        capacities come from the API server's Node objects, not the
        metrics pipeline, so they stay honest here) — with NaN/Inf/
        negative/over-capacity values. Shapes are untouched; only
        values go bad."""
        idx = np.flatnonzero(np.asarray(state.pod_valid))
        if idx.size == 0:
            return state
        arrays = {
            "pod_cpu": np.asarray(state.pod_cpu).copy(),
            "pod_mem": np.asarray(state.pod_mem).copy(),
        }
        caps = {
            "pod_cpu": float(
                np.max(np.asarray(state.node_cpu_cap), initial=0.0)
            ),
            "pod_mem": float(
                np.max(np.asarray(state.node_mem_cap), initial=0.0)
            ),
        }
        n = self._rng_aux.randint(
            1, min(self.profile.corrupt_max_pods, int(idx.size))
        )
        touched: set[str] = set()
        for i in self._rng_aux.sample(list(idx), n):
            field = (
                "pod_cpu" if self._rng_aux.random() < 0.7 else "pod_mem"
            )
            arr, cap = arrays[field], caps[field]
            mode = self._CORRUPT_MODES[
                self._rng_aux.randrange(len(self._CORRUPT_MODES))
            ]
            if mode == "nan":
                arr[i] = np.nan
            elif mode == "inf":
                arr[i] = np.inf
            elif mode == "negative":
                arr[i] = -abs(arr[i]) - 1.0
            else:  # impossibly above any node's capacity
                arr[i] = (cap if cap > 0 else 1.0) * 50.0
            touched.add(field)
        import jax.numpy as jnp

        return state.replace(
            **{f: jnp.asarray(arrays[f]) for f in touched}
        )

    def apply_move(self, move: MoveRequest) -> str | None:
        p = self.profile
        if self._hit(p.move_error_rate):
            self._count("move_error")
            raise ChaosError(f"chaos: injected apply_move failure ({move.service})")
        if self._hit(p.move_timeout_rate):
            self._count("move_timeout")
            # the budget was really consumed: the inner clock moves first
            self.inner.advance(p.move_timeout_s)
            raise ChaosTimeoutError(
                f"chaos: apply_move({move.service}) exceeded "
                f"{p.move_timeout_s}s"
            )
        if self._hit(p.move_none_rate):
            self._count("move_none")
            return None
        if self._hit(p.move_wrong_node_rate):
            names = [
                n
                for n in getattr(self.inner, "node_names", [])
                if n != move.target_node
            ]
            if names:
                self._count("move_wrong_node")
                wrong = names[self._rng.randrange(len(names))]
                return self.inner.apply_move(
                    dataclasses.replace(move, target_node=wrong)
                )
        if self._hit_aux(p.move_lost_rate):
            # the classic lost write: the API acknowledged the move and
            # the controller records it as landed, but nothing in the
            # cluster actually changed — only the reconciliation plane's
            # intent-vs-observed diff can see this one
            self._count("move_lost")
            return move.target_node
        return self.inner.apply_move(move)

    def apply_pod_moves(self, moves):
        """The per-replica batch wave gets the LANDING fault menu, per
        move: a wrong-node redirect stays in the wave aimed elsewhere,
        an acknowledged-but-lost move is reported landed while nothing
        is sent. Transport faults (error/timeout/None) stay on
        :meth:`apply_move` — the wave is a sim-only extension outside
        the boundary's retry protection, so raising here would crash
        the loop rather than exercise degradation. Survivors land as
        ONE inner wave (the single clock-advance contract)."""
        p = self.profile
        send, lost = [], []
        names_all = list(getattr(self.inner, "node_names", []))
        for mv in moves:
            if self._hit(p.move_wrong_node_rate):
                names = [n for n in names_all if n != mv.target_node]
                if names:
                    self._count("move_wrong_node")
                    send.append(
                        dataclasses.replace(
                            mv,
                            target_node=names[
                                self._rng.randrange(len(names))
                            ],
                        )
                    )
                    continue
            if self._hit_aux(p.move_lost_rate):
                self._count("move_lost")
                if mv.pod is not None:
                    lost.append((mv.pod, mv.target_node))
                continue
            send.append(mv)
        # the inner wave ALWAYS runs — even all-lost, the API call was
        # acknowledged, so the wave's single clock advance must be paid
        # (time passes; only the placement is a lie)
        landed = dict(self.inner.apply_pod_moves(send))
        for pod, target in lost:
            # acknowledged at the requested target: the controller
            # records it as landed there, and only the reconcile plane's
            # intent-vs-observed diff sees the truth
            landed.setdefault(pod, target)
        return landed

    def advance(self, seconds: float) -> None:
        self.inner.advance(seconds)

    def __getattr__(self, name: str) -> Any:
        # everything un-injected (node_names, inject_imbalance,
        # restore_placement, events, reconcile_delay_s, …) passes through
        return getattr(self.inner, name)


def with_chaos(
    backend: Backend,
    profile: str | ChaosProfile,
    seed: int = 0,
    registry=None,
):
    """Wrap ``backend`` unless the profile is "none" (then return it as-is).
    ``profile`` is a name from :data:`PROFILES` or an explicit
    :class:`ChaosProfile`; ``registry`` receives the fault counters
    (default: the process registry, resolved per call)."""
    if isinstance(profile, str):
        if profile not in PROFILES:
            raise ValueError(
                f"unknown chaos profile {profile!r}; expected one of "
                f"{sorted(PROFILES)}"
            )
        profile = PROFILES[profile]
    if (
        profile.name == "none"
        or profile == ChaosProfile(name=profile.name)
    ):
        return backend
    return ChaosBackend(backend, profile, seed=seed, registry=registry)
