"""Fault-injecting wrapper around any ``Backend`` — the chaos-engineering
treatment (Chaos Monkey / chaos-mesh style) for the control loop.

``ChaosBackend`` wraps a real backend and injects seeded, configurable
faults at the exact surface the controller consumes:

- ``monitor()`` exceptions (:class:`ChaosError`), stale snapshots (the
  previous round's state served again), partial snapshots (a random
  subset of pods dropped from validity — a watch cache that lags), and
  transient ``None`` returns;
- ``apply_move`` exceptions, timeouts (:class:`ChaosTimeoutError`, after
  the move's wall budget has visibly been consumed on the inner clock),
  transient ``None`` returns (the protocol's "move failed" signal), and
  moves that land on the WRONG node (a scheduler override / race);
- node crash/flap sequences: every ``node_flap_period`` monitors a worker
  is killed and revived ``node_flap_down_calls`` monitors later (needs an
  inner backend exposing ``kill_node``/``revive_node`` — the simulator).

Every injected fault is counted twice: in the process telemetry registry
as ``chaos_faults_total{kind=...}`` and in the wrapper's own
``fault_counts`` dict — the chaos soak test asserts the two agree, which
pins the telemetry wiring end to end.

Faults draw from one seeded ``random.Random``, so a chaos run is exactly
reproducible; everything the profile does not inject passes straight
through (``__getattr__`` forwards ``node_names``, ``inject_imbalance``,
``restore_placement``, ``events``, …).
"""

from __future__ import annotations

import dataclasses
import random
from dataclasses import dataclass
from typing import Any

import numpy as np

from kubernetes_rescheduling_tpu.backends.base import Backend, MoveRequest
from kubernetes_rescheduling_tpu.core.state import ClusterState, CommGraph
from kubernetes_rescheduling_tpu.telemetry.registry import get_registry


class ChaosError(ConnectionError):
    """Injected boundary failure (transient by construction)."""


class ChaosTimeoutError(TimeoutError):
    """Injected boundary timeout; the inner clock has already advanced."""


@dataclass(frozen=True)
class ChaosProfile:
    """Per-call fault probabilities plus the node-flap schedule."""

    name: str = "custom"
    monitor_error_rate: float = 0.0    # monitor() raises ChaosError
    monitor_stale_rate: float = 0.0    # previous snapshot served again
    monitor_partial_rate: float = 0.0  # a random pod subset goes invalid
    monitor_none_rate: float = 0.0     # transient None return
    move_error_rate: float = 0.0       # apply_move raises ChaosError
    move_timeout_rate: float = 0.0     # apply_move raises ChaosTimeoutError
    move_none_rate: float = 0.0        # transient None return (move "failed")
    move_wrong_node_rate: float = 0.0  # lands on a different node
    move_timeout_s: float = 30.0       # clock consumed by an injected timeout
    partial_drop_frac: float = 0.2     # pod fraction dropped by a partial snapshot
    node_flap_period: int = 0          # kill a worker every N monitor calls (0 = off)
    node_flap_down_calls: int = 2      # monitors the worker stays dead

    def validate(self) -> "ChaosProfile":
        for f in dataclasses.fields(self):
            if f.name.endswith("_rate") or f.name.endswith("_frac"):
                v = getattr(self, f.name)
                if not (0.0 <= v <= 1.0):
                    raise ValueError(f"{f.name} must be in [0, 1], got {v}")
        if self.node_flap_period < 0 or self.node_flap_down_calls < 1:
            raise ValueError("node flap schedule must be non-negative / >= 1")
        return self


# Named profiles the CLI exposes (``--chaos-profile``). "soak" is the one
# the acceptance soak test runs: monitor failures + move timeouts + node
# flap, hot enough that a 30-round run exercises every degraded path.
PROFILES: dict[str, ChaosProfile] = {
    "none": ChaosProfile(name="none"),
    "flaky-monitor": ChaosProfile(
        name="flaky-monitor",
        monitor_error_rate=0.2,
        monitor_stale_rate=0.1,
        monitor_none_rate=0.05,
    ),
    "flaky-moves": ChaosProfile(
        name="flaky-moves",
        move_error_rate=0.15,
        move_timeout_rate=0.1,
        move_none_rate=0.1,
        move_wrong_node_rate=0.1,
    ),
    "node-flap": ChaosProfile(
        name="node-flap", node_flap_period=5, node_flap_down_calls=2
    ),
    "soak": ChaosProfile(
        name="soak",
        monitor_error_rate=0.25,
        monitor_stale_rate=0.10,
        monitor_partial_rate=0.05,
        monitor_none_rate=0.05,
        move_error_rate=0.15,
        move_timeout_rate=0.15,
        move_none_rate=0.10,
        move_wrong_node_rate=0.10,
        node_flap_period=7,
        node_flap_down_calls=2,
    ),
}


class ChaosBackend:
    """Wrap ``inner`` with the faults of ``profile`` (seeded)."""

    def __init__(
        self,
        inner: Backend,
        profile: ChaosProfile,
        seed: int = 0,
        registry=None,
    ):
        self.inner = inner
        self.profile = profile.validate()
        self.seed = seed
        self.registry = registry  # None = the process default, per call
        self._rng = random.Random(seed)
        self._last_state: ClusterState | None = None
        self._monitor_calls = 0
        self._flapped_node: str | None = None
        self._flap_revive_at = 0
        self.fault_counts: dict[str, int] = {}

    # ---- fault bookkeeping ----

    def _count(self, kind: str) -> None:
        self.fault_counts[kind] = self.fault_counts.get(kind, 0) + 1
        reg = self.registry if self.registry is not None else get_registry()
        reg.counter(
            "chaos_faults_total",
            "faults injected by the chaos backend",
            labelnames=("kind",),
        ).labels(kind=kind).inc()

    def _hit(self, rate: float) -> bool:
        return rate > 0 and self._rng.random() < rate

    # ---- Backend protocol ----

    def comm_graph(self) -> CommGraph:
        return self.inner.comm_graph()

    def _flap(self) -> None:
        """Kill/revive sequencing, driven by the monitor-call counter."""
        p = self.profile
        if p.node_flap_period <= 0:
            return
        kill = getattr(self.inner, "kill_node", None)
        revive = getattr(self.inner, "revive_node", None)
        if kill is None or revive is None:
            return  # inner backend cannot express node death
        if (
            self._flapped_node is not None
            and self._monitor_calls >= self._flap_revive_at
        ):
            revive(self._flapped_node)
            self._count("node_revive")
            self._flapped_node = None
        if (
            self._flapped_node is None
            and self._monitor_calls % p.node_flap_period == 0
            and self._monitor_calls > 0
        ):
            names = list(self.inner.node_names)
            if names:
                self._flapped_node = names[self._rng.randrange(len(names))]
                self._flap_revive_at = (
                    self._monitor_calls + p.node_flap_down_calls
                )
                kill(self._flapped_node)
                self._count("node_kill")

    def monitor(self) -> ClusterState | None:
        p = self.profile
        self._monitor_calls += 1
        self._flap()
        if self._hit(p.monitor_error_rate):
            self._count("monitor_error")
            raise ChaosError("chaos: injected monitor failure")
        if self._hit(p.monitor_none_rate):
            self._count("monitor_none")
            return None
        if self._hit(p.monitor_stale_rate) and self._last_state is not None:
            self._count("monitor_stale")
            return self._last_state
        state = self.inner.monitor()
        if self._hit(p.monitor_partial_rate):
            self._count("monitor_partial")
            state = self._partial(state)
            return state  # deliberately NOT cached as last good
        self._last_state = state
        return state

    def _partial(self, state: ClusterState) -> ClusterState:
        """Drop a random ``partial_drop_frac`` of valid pods — the lagging
        watch-cache snapshot. Shapes are untouched (only validity flips),
        so the decision kernels never retrace."""
        valid = np.asarray(state.pod_valid).copy()
        idx = np.flatnonzero(valid)
        n_drop = int(len(idx) * self.profile.partial_drop_frac)
        if n_drop > 0:
            drop = self._rng.sample(list(idx), n_drop)
            valid[np.asarray(drop, dtype=np.int64)] = False
        import jax.numpy as jnp

        return state.replace(pod_valid=jnp.asarray(valid))

    def apply_move(self, move: MoveRequest) -> str | None:
        p = self.profile
        if self._hit(p.move_error_rate):
            self._count("move_error")
            raise ChaosError(f"chaos: injected apply_move failure ({move.service})")
        if self._hit(p.move_timeout_rate):
            self._count("move_timeout")
            # the budget was really consumed: the inner clock moves first
            self.inner.advance(p.move_timeout_s)
            raise ChaosTimeoutError(
                f"chaos: apply_move({move.service}) exceeded "
                f"{p.move_timeout_s}s"
            )
        if self._hit(p.move_none_rate):
            self._count("move_none")
            return None
        if self._hit(p.move_wrong_node_rate):
            names = [
                n
                for n in getattr(self.inner, "node_names", [])
                if n != move.target_node
            ]
            if names:
                self._count("move_wrong_node")
                wrong = names[self._rng.randrange(len(names))]
                return self.inner.apply_move(
                    dataclasses.replace(move, target_node=wrong)
                )
        return self.inner.apply_move(move)

    def advance(self, seconds: float) -> None:
        self.inner.advance(seconds)

    def __getattr__(self, name: str) -> Any:
        # everything un-injected (node_names, inject_imbalance,
        # restore_placement, events, reconcile_delay_s, …) passes through
        return getattr(self.inner, name)


def with_chaos(
    backend: Backend,
    profile: str | ChaosProfile,
    seed: int = 0,
    registry=None,
):
    """Wrap ``backend`` unless the profile is "none" (then return it as-is).
    ``profile`` is a name from :data:`PROFILES` or an explicit
    :class:`ChaosProfile`; ``registry`` receives the fault counters
    (default: the process registry, resolved per call)."""
    if isinstance(profile, str):
        if profile not in PROFILES:
            raise ValueError(
                f"unknown chaos profile {profile!r}; expected one of "
                f"{sorted(PROFILES)}"
            )
        profile = PROFILES[profile]
    if (
        profile.name == "none"
        or profile == ChaosProfile(name=profile.name)
    ):
        return backend
    return ChaosBackend(backend, profile, seed=seed, registry=registry)
