"""Hermetic cluster simulator.

The reference has **no** offline backend — every experiment needs a live
4-node cluster plus the µBench deployer and ~1000 curl clients
(SURVEY.md §4). This simulator reproduces that environment's dynamics so the
whole experiment matrix runs deterministically in-process:

- **Load model**: requests enter at an entry service (µBench's ``s0`` behind
  the NodePort, reference release1.sh:7) and fan out along the *directed*
  call graph — every request to a service triggers one request to each of
  its callees (µBench ``external_services`` semantics, workmodelC.json).
  Per-pod CPU = idle + (service rps / replicas) · per-request cost, plus
  optional noise — so hazard detection sees realistic, load-dependent usage.
- **Fault injection**: the cordon-induced imbalance the reference uses as its
  "Before" state (auto_full_pipeline_repeat.sh:48-51) plus node kill, CPU
  spike, and pod churn — the failure-detection surface of SURVEY.md §5.3.
- **Reconcile model**: deployment teardown takes simulated time (the
  reference polls up to 180 s for the 404, delete_replaced_pod.py:8-22);
  ``advance`` moves the simulated clock, never the wall clock.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field

import numpy as np

from kubernetes_rescheduling_tpu.backends.base import MoveRequest
from kubernetes_rescheduling_tpu.core.state import ClusterState, CommGraph, UNASSIGNED
from kubernetes_rescheduling_tpu.core.workmodel import (
    Workmodel,
    propagate_entry_rate,
)
from kubernetes_rescheduling_tpu.telemetry.accounting import (
    count_reconcile,
    timed_call,
)


@dataclass
class LoadModel:
    """Deterministic µBench-like load propagation."""

    entry_service: str = "s0"
    entry_rps: float = 100.0          # ~1000 concurrent curl clients (release1.sh:9)
    cost_per_req_m: float = 2.0       # millicores per request/s (cpu_stress, workmodelC.json)
    idle_m: float = 20.0              # baseline per-pod usage
    noise_frac: float = 0.0           # gaussian noise on per-pod usage
    # probability a request to a service calls each callee. µBench calls every
    # callee every time (=1.0, workmodelC.json external_services); synthetic
    # multi-parent meshes need <1 or path-count multiplication saturates
    # every node (each of k parents forwards the full upstream rate)
    fanout_frac: float = 1.0

    def service_rps(self, wm: Workmodel) -> dict[str, float]:
        """Propagate entry rps through the directed call graph: each request
        to a service triggers one request to each of its callees.

        Delegates to the shared :func:`core.workmodel.propagate_entry_rate`
        (also behind the load generator's autoscaling rate series), whose
        edges come from the cycle-broken ``kahn_traversal`` — CPU load,
        latency, and autoscaling all agree on which edges exist and how
        rate accumulates through them.
        """
        return propagate_entry_rate(
            wm,
            entry_service=self.entry_service,
            entry_rps=self.entry_rps,
            fanout_frac=self.fanout_frac,
        )


def workload_layout(
    workmodel: Workmodel, service_capacity: int | None
) -> tuple[CommGraph, dict[str, int]]:
    """THE derived workload layout — capacity padding + service index —
    shared by :meth:`SimBackend._refresh_workload` and the device twin
    (``backends.sim_device.twin_of``). One definition, two consumers:
    the Python simulator and the jittable twin must agree on how the
    comm graph pads to the service bucket and how service names map to
    indices (teardown compaction renumbers them), or a post-churn twin
    would silently score a different topology than the backend serves
    (regression-pinned in tests/test_scan.py).
    """
    cap = service_capacity
    if cap is not None:
        # never let a mid-step deploy outrun a stale bucket: the
        # churn engine promotes capacities before applying events,
        # but the graph build itself must stay safe regardless
        cap = max(cap, len(workmodel.services))
    graph = workmodel.comm_graph(capacity=cap)
    svc_index = {n: i for i, n in enumerate(workmodel.names)}
    return graph, svc_index


@dataclass
class SimBackend:
    """In-memory cluster with dynamics. All mutation host-side numpy; the
    ``monitor`` snapshot is a fresh padded ``ClusterState``."""

    workmodel: Workmodel
    node_names: list[str]
    node_cpu_cap_m: float = 20_000.0
    node_mem_cap_b: float = 32 * 1024**3
    load: LoadModel = field(default_factory=LoadModel)
    seed: int = 0
    node_capacity: int | None = None
    pod_capacity: int | None = None
    service_capacity: int | None = None  # comm-graph padding (shape buckets)
    reconcile_delay_s: float = 3.0     # simulated teardown+recreate latency
    pacing_s: float = 15.0             # reference main.py:27

    def __post_init__(self) -> None:
        self._rng = np.random.default_rng(self.seed)
        self.clock_s = 0.0
        self.events: list[dict] = []
        n = len(self.node_names)
        self._node_alive = np.ones(n, dtype=bool)
        self._cpu_spike: dict[str, float] = {}
        # pod table: (service_idx, node_idx, name); deployment = service
        self._pods: list[list] = []
        for idx, svc in enumerate(self.workmodel.services):
            for r in range(svc.replicas):
                node = int(self._rng.integers(0, n))
                self._pods.append([idx, node, f"{svc.name}-{r}"])
        self._refresh_workload()

    def _refresh_workload(self) -> None:
        """THE derived-state rebuild: everything computed from the
        service/node sets funnels through here, so the elastic mutators
        below can change either set between rounds and every consumer
        (comm graph, service index, rps cache) follows. The no-churn
        path calls it exactly once, from ``__post_init__`` — a static
        run is bit-identical to the pre-elastic simulator
        (regression-pinned in tests/test_elastic.py). Delegates to the
        module-level :func:`workload_layout` — the one source of truth
        the device twin shares."""
        self._graph, self._svc_index = workload_layout(
            self.workmodel, self.service_capacity
        )
        self._rps_cache: dict[str, float] | None = None

    # ---- Backend protocol ----

    def comm_graph(self) -> CommGraph:
        return self._graph

    def monitor(self) -> ClusterState:
        """Snapshot with load-model CPU usage (reference podmonitor.monitor)."""
        with timed_call("sim", "monitor"):
            return self._monitor()

    def _stable_names(self, attr: str, names: list) -> tuple:
        """Content-memoized name tuple: successive monitors hand out the
        SAME tuple object while the names are unchanged, so identity-keyed
        memos downstream (the admission guard's duplicate scan and
        name→index maps) hit instead of rebuilding O(P) state per round.
        Content-compared, so no mutation path needs an invalidation hook."""
        t = tuple(names)
        cached = getattr(self, attr, None)
        if cached is not None and cached == t:
            return cached
        setattr(self, attr, t)
        return t

    def _monitor(self) -> ClusterState:
        rps = self.load.service_rps(self.workmodel)
        replicas = {s.name: max(1, s.replicas) for s in self.workmodel.services}
        services, nodes, cpus, mems, names = [], [], [], [], []
        for svc_idx, node, name in self._pods:
            spec = self.workmodel.services[svc_idx]
            per_pod = (
                self.load.idle_m
                + rps.get(spec.name, 0.0)
                / replicas[spec.name]
                * self.load.cost_per_req_m
                * spec.proc_cost  # per-service cpu_stress weight (workmodelC)
            )
            per_pod *= self._cpu_spike.get(spec.name, 1.0)
            if self.load.noise_frac > 0:
                per_pod *= 1.0 + self._rng.normal(0.0, self.load.noise_frac)
            services.append(svc_idx)
            nodes.append(node if (node >= 0 and self._node_alive[node]) else UNASSIGNED)
            cpus.append(max(per_pod, 0.0))
            mems.append(float(spec.mem_request_bytes))
            names.append(name)
        return ClusterState.build(
            # stable tuples: tuple() of a tuple is the same object, so
            # the built state carries THE memoized tuple across rounds
            node_names=self._stable_names("_node_names_memo", self.node_names),
            node_cpu_cap=[
                self.node_cpu_cap_m if a else 0.0 for a in self._node_alive
            ],
            node_mem_cap=[self.node_mem_cap_b] * len(self.node_names),
            node_alive=self._node_alive.tolist(),
            pod_services=services,
            pod_nodes=nodes,
            pod_cpu=cpus,
            pod_mem=mems,
            pod_names=self._stable_names("_pod_names_memo", names),
            node_capacity=self.node_capacity,
            pod_capacity=self.pod_capacity,
        )

    def apply_move(self, move: MoveRequest) -> str | None:
        """Foreground delete + re-create of one service's Deployment
        (reference delete_replaced_pod.py:173-177 + rescheduling.py:57-73).

        ``mechanism`` is honored the way the cluster would: ``nodeName`` and
        ``nodeSelector`` pin to the requested target, while ``affinityOnly``
        (the kubescheduling policy, reference rescheduling.py:159-171) only
        excludes the anti-affinity nodes and lets the *simulated scheduler*
        choose — least-allocated CPU, tie → first node in order, the same
        model the kubescheduling policy kernel implements. The requested
        target is advisory for that mechanism, exactly as on a real cluster.
        """
        with timed_call("sim", "apply_move"):
            return self._apply_move(move)

    def _apply_move(self, move: MoveRequest) -> str | None:
        if move.service not in self._svc_index:
            return None
        if move.mechanism == "affinityOnly":
            target = self._scheduler_choice(exclude=move.hazard_nodes)
            if target is None:
                return None
        else:
            if move.target_node not in self.node_names:
                return None
            target = self.node_names.index(move.target_node)
        if not self._node_alive[target]:
            return None
        svc_idx = self._svc_index[move.service]
        moved = 0
        for pod in self._pods:
            if pod[0] == svc_idx and (move.pod is None or pod[2] == move.pod):
                pod[1] = target
                moved += 1
                if move.pod is not None:
                    break  # a pod name matches at most one entry
        self.clock_s += self.reconcile_delay_s
        if moved:
            count_reconcile("sim", moved)
        landed = self.node_names[target]
        self.events.append(
            {
                "t": self.clock_s,
                "event": "move",
                "service": move.service,
                "target": landed,  # where pods actually went
                "requested": move.target_node,
                "pods": moved,
                "mechanism": move.mechanism,
            }
        )
        return landed if moved > 0 else None

    def advance(self, seconds: float) -> None:
        self.clock_s += seconds

    def _scheduler_choice(self, exclude: tuple[str, ...] = ()) -> int | None:
        """The sim's stand-in for the default kube-scheduler: least-allocated
        CPU among alive, non-excluded nodes; tie → first in node order.

        Computed host-side from the pod table (no full monitor() snapshot);
        the rps propagation is cached since workmodel and load are fixed
        per backend."""
        if self._rps_cache is None:
            self._rps_cache = self.load.service_rps(self.workmodel)
        rps = self._rps_cache
        replicas = {s.name: max(1, s.replicas) for s in self.workmodel.services}
        used = np.zeros(len(self.node_names))
        for svc_idx, node, _name in self._pods:
            if node < 0:
                continue
            spec = self.workmodel.services[svc_idx]
            per_pod = (
                self.load.idle_m
                + rps.get(spec.name, 0.0)
                / replicas[spec.name]
                * self.load.cost_per_req_m
                * spec.proc_cost
            )
            used[node] += per_pod * self._cpu_spike.get(spec.name, 1.0)
        best, best_used = None, np.inf
        for i, name in enumerate(self.node_names):
            if not self._node_alive[i] or name in exclude:
                continue
            if used[i] < best_used:
                best, best_used = i, float(used[i])
        return best

    def apply_pod_moves(self, moves) -> dict[str, str]:
        """Apply a batch of per-pod moves as ONE reconcile wave: a single
        indexed pass over the pod table and one clock advance. Per-replica
        placement moves many pods per round; issuing them as individual
        ``apply_move`` calls would both cost O(moves × pods) host time and
        charge one reconcile delay per replica — a clock model no real
        cluster has (kubelets reconcile in parallel). Returns the moved
        pods as ``{pod name: landed node name}`` (``set()`` of it gives
        the landed names, so set-consumers keep working)."""
        node_idx = {n: i for i, n in enumerate(self.node_names)}
        target_of: dict[str, int] = {}
        for mv in moves:
            t = node_idx.get(mv.target_node)
            if t is not None and self._node_alive[t] and mv.pod is not None:
                target_of[mv.pod] = t
        landed: dict[str, str] = {}
        for pod in self._pods:
            t = target_of.get(pod[2])
            if t is not None:
                pod[1] = t
                landed[pod[2]] = self.node_names[t]
        self.clock_s += self.reconcile_delay_s
        if landed:
            count_reconcile("sim", len(landed))
        self.events.append(
            {
                "t": self.clock_s,
                "event": "pod_moves",
                "pods": len(landed),
                "requested": len(moves),
            }
        )
        return landed

    def external_move(self, pod_name: str, node: str) -> bool:
        """Move ONE named pod to ``node`` behind the controller's back —
        another actor's write (a second scheduler, a human `kubectl`, a
        descheduler). Deliberately NOT ``apply_move``: no reconcile
        count, no clock charge on the controller's simulated time — the
        controller never sees this happen except through its next
        snapshot, which is exactly what the reconciliation plane exists
        to detect. Returns whether the pod existed and the node is
        alive."""
        if node not in self.node_names:
            return False
        target = self.node_names.index(node)
        if not self._node_alive[target]:
            return False
        for pod in self._pods:
            if pod[2] == pod_name:
                pod[1] = target
                self.events.append(
                    {
                        "t": self.clock_s,
                        "event": "external_move",
                        "pod": pod_name,
                        "node": node,
                    }
                )
                return True
        return False

    def external_move_random(self, rng) -> dict | None:
        """Drift one seeded-random placed pod to a random OTHER alive
        node via :meth:`external_move` (the chaos backend's
        ``external_drift_rate`` hook; ``rng`` is the caller's seeded
        ``random.Random`` so drift streams stay reproducible)."""
        placed = [
            p for p in self._pods
            if p[1] >= 0 and self._node_alive[p[1]]
        ]
        if not placed:
            return None
        pod = placed[rng.randrange(len(placed))]
        others = [
            n
            for i, n in enumerate(self.node_names)
            if self._node_alive[i] and i != pod[1]
        ]
        if not others:
            return None
        src = self.node_names[pod[1]]
        dst = others[rng.randrange(len(others))]
        if not self.external_move(pod[2], dst):
            return None
        return {"pod": pod[2], "from": src, "to": dst}

    def restore_placement(self, state: ClusterState) -> int:
        """Pin pods back to the placement recorded in a checkpoint snapshot
        (crash-resume support; pods are matched by name)."""
        node_of: dict[str, int] = {}
        pod_node = np.asarray(state.pod_node)
        valid = np.asarray(state.pod_valid)
        for i, name in enumerate(state.pod_names):
            if valid[i]:
                node_of[name] = int(pod_node[i])
        restored = 0
        for pod in self._pods:
            if pod[2] in node_of:
                pod[1] = node_of[pod[2]]
                restored += 1
        self.events.append({"t": self.clock_s, "event": "restore", "pods": restored})
        return restored

    # ---- elastic topology mutators (elastic/engine.py drives these) ----

    def live_counts(self) -> dict[str, int]:
        """Live (unpadded) sizes the shape buckets quantize: services,
        node SLOTS (drained nodes keep their slot, like real Node
        objects), and pods."""
        return {
            "services": len(self.workmodel.services),
            "nodes": len(self.node_names),
            "pods": len(self._pods),
        }

    def alive_node_names(self) -> list[str]:
        return [
            n for n, a in zip(self.node_names, self._node_alive) if bool(a)
        ]

    def set_capacities(
        self,
        *,
        node: int | None = None,
        pod: int | None = None,
        service: int | None = None,
    ) -> None:
        """Pin snapshot padding to bucket capacities: every ``monitor``
        builds at these shapes until the churn engine promotes them."""
        if node is not None:
            self.node_capacity = node
        if pod is not None:
            self.pod_capacity = pod
        if service is not None and service != self.service_capacity:
            self.service_capacity = service
            self._refresh_workload()

    def deploy_service(self, spec) -> None:
        """A new Deployment lands: the workmodel grows, its replicas are
        placed by the simulated scheduler (least-allocated CPU — the
        same model ``_scheduler_choice`` uses for affinityOnly moves)."""
        if spec.name in self._svc_index:
            raise ValueError(f"service {spec.name!r} already deployed")
        self.workmodel = Workmodel(
            services=self.workmodel.services + (spec,),
            source=self.workmodel.source,
        )
        self._refresh_workload()
        idx = self._svc_index[spec.name]
        for r in range(max(1, spec.replicas)):
            target = self._scheduler_choice()
            self._pods.append(
                [idx, target if target is not None else UNASSIGNED,
                 f"{spec.name}-{r}"]
            )
        # NO per-event clock charge: the churn engine advances the clock
        # once per round's event wave (kubelets reconcile in parallel —
        # the apply_pod_moves rule; serial charging would jump simulated
        # time by minutes on a busy autoscale round)
        self.events.append(
            {"t": self.clock_s, "event": "deploy", "service": spec.name,
             "replicas": max(1, spec.replicas)}
        )

    def teardown_service(self, name: str) -> None:
        """A Deployment leaves: its pods disappear and every later
        service index compacts down by one (the comm graph, service
        index, and pod table stay aligned via the shared rebuild)."""
        if name not in self._svc_index:
            raise ValueError(f"service {name!r} not deployed")
        idx = self._svc_index[name]
        self.workmodel = type(self.workmodel)(
            services=tuple(
                s for s in self.workmodel.services if s.name != name
            ),
            source=self.workmodel.source,
        )
        self._pods = [
            [s - 1 if s > idx else s, node, pname]
            for s, node, pname in self._pods
            if s != idx
        ]
        self._cpu_spike.pop(name, None)
        self._refresh_workload()
        # no per-event clock charge (see deploy_service)
        self.events.append(
            {"t": self.clock_s, "event": "teardown", "service": name}
        )

    def scale_replicas(self, name: str, replicas: int) -> None:
        """Autoscale one service to ``replicas``: scale-up places new
        pods via the simulated scheduler, scale-down removes the most
        recently created pods first (a Deployment's newest ReplicaSet
        pods die first under scale-down)."""
        if name not in self._svc_index:
            raise ValueError(f"service {name!r} not deployed")
        target = max(1, int(replicas))
        idx = self._svc_index[name]
        mine = [i for i, p in enumerate(self._pods) if p[0] == idx]
        cur = len(mine)
        if target == cur:
            return
        if target > cur:
            suffix = cur
            for _ in range(target - cur):
                node = self._scheduler_choice()
                self._pods.append(
                    [idx, node if node is not None else UNASSIGNED,
                     f"{name}-{suffix}"]
                )
                suffix += 1
        else:
            for i in sorted(mine[target:], reverse=True):
                del self._pods[i]
        self.workmodel = type(self.workmodel)(
            services=tuple(
                dataclasses.replace(s, replicas=target) if s.name == name else s
                for s in self.workmodel.services
            ),
            source=self.workmodel.source,
        )
        # NO _refresh_workload: scaling changes neither the call graph
        # (comm_graph ignores replicas) nor the name→index map, and the
        # rps propagation is replica-independent — rebuilding the S×S
        # adjacency per autoscale event would make a busy diurnal round
        # O(events · S²) for nothing. No per-event clock charge either
        # (see deploy_service).
        self.events.append(
            {"t": self.clock_s, "event": "scale", "service": name,
             "from": cur, "to": target}
        )

    def add_node(self, name: str) -> None:
        """A node joins the pool: a drained slot of this name revives;
        a new name grows the cluster (same uniform capacity)."""
        if name in self.node_names:
            self.revive_node(name)
            return
        self.node_names.append(name)
        self._node_alive = np.append(self._node_alive, True)
        self.events.append(
            {"t": self.clock_s, "event": "node_add", "node": name}
        )

    def drain_node(self, name: str) -> None:
        """Cordon+drain: capacity leaves the pool and the node's pods
        are rescheduled onto the remaining alive nodes (kube-scheduler's
        job, modeled by ``schedule_pending``). Differs from
        ``kill_node`` — a crash strands pods pending; a drain re-places
        them."""
        self.kill_node(name)
        self.schedule_pending()
        self.events.append(
            {"t": self.clock_s, "event": "node_drain", "node": name}
        )

    # ---- fault injection (SURVEY.md §5.3) ----

    def inject_imbalance(self, node: str) -> None:
        """The cordon trick: pile every pod onto one node
        (reference auto_full_pipeline_repeat.sh:48-51)."""
        idx = self.node_names.index(node)
        for pod in self._pods:
            pod[1] = idx
        self.events.append({"t": self.clock_s, "event": "imbalance", "node": node})

    def kill_node(self, node: str) -> None:
        """Node failure: capacity gone, its pods evicted to pending."""
        idx = self.node_names.index(node)
        self._node_alive[idx] = False
        for pod in self._pods:
            if pod[1] == idx:
                pod[1] = UNASSIGNED
        self.events.append({"t": self.clock_s, "event": "node_kill", "node": node})

    def revive_node(self, node: str) -> None:
        self._node_alive[self.node_names.index(node)] = True
        self.events.append({"t": self.clock_s, "event": "node_revive", "node": node})

    def cpu_spike(self, service: str, factor: float) -> None:
        """Multiply one service's CPU usage (hot-spot injection)."""
        self._cpu_spike[service] = factor
        self.events.append(
            {"t": self.clock_s, "event": "cpu_spike", "service": service, "factor": factor}
        )

    def churn(self, n_restarts: int) -> None:
        """Random pod restarts onto random nodes (background churn)."""
        alive = np.flatnonzero(self._node_alive)
        for _ in range(n_restarts):
            pod = self._pods[int(self._rng.integers(len(self._pods)))]
            pod[1] = int(self._rng.choice(alive))
        self.events.append({"t": self.clock_s, "event": "churn", "n": n_restarts})

    def schedule_pending(self) -> int:
        """Place UNASSIGNED pods on the least-loaded alive node (what
        kube-scheduler would do for evicted pods)."""
        counts = np.zeros(len(self.node_names))
        for pod in self._pods:
            if pod[1] >= 0:
                counts[pod[1]] += 1
        counts[~self._node_alive] = np.inf
        placed = 0
        for pod in self._pods:
            if pod[1] == UNASSIGNED:
                pod[1] = int(np.argmin(counts))
                counts[pod[1]] += 1
                placed += 1
        return placed
