"""Hermetic cluster simulator.

The reference has **no** offline backend — every experiment needs a live
4-node cluster plus the µBench deployer and ~1000 curl clients
(SURVEY.md §4). This simulator reproduces that environment's dynamics so the
whole experiment matrix runs deterministically in-process:

- **Load model**: requests enter at an entry service (µBench's ``s0`` behind
  the NodePort, reference release1.sh:7) and fan out along the *directed*
  call graph — every request to a service triggers one request to each of
  its callees (µBench ``external_services`` semantics, workmodelC.json).
  Per-pod CPU = idle + (service rps / replicas) · per-request cost, plus
  optional noise — so hazard detection sees realistic, load-dependent usage.
- **Fault injection**: the cordon-induced imbalance the reference uses as its
  "Before" state (auto_full_pipeline_repeat.sh:48-51) plus node kill, CPU
  spike, and pod churn — the failure-detection surface of SURVEY.md §5.3.
- **Reconcile model**: deployment teardown takes simulated time (the
  reference polls up to 180 s for the 404, delete_replaced_pod.py:8-22);
  ``advance`` moves the simulated clock, never the wall clock.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from kubernetes_rescheduling_tpu.backends.base import MoveRequest
from kubernetes_rescheduling_tpu.core.state import ClusterState, CommGraph, UNASSIGNED
from kubernetes_rescheduling_tpu.core.workmodel import Workmodel, kahn_traversal
from kubernetes_rescheduling_tpu.telemetry.accounting import (
    count_reconcile,
    timed_call,
)


@dataclass
class LoadModel:
    """Deterministic µBench-like load propagation."""

    entry_service: str = "s0"
    entry_rps: float = 100.0          # ~1000 concurrent curl clients (release1.sh:9)
    cost_per_req_m: float = 2.0       # millicores per request/s (cpu_stress, workmodelC.json)
    idle_m: float = 20.0              # baseline per-pod usage
    noise_frac: float = 0.0           # gaussian noise on per-pod usage
    # probability a request to a service calls each callee. µBench calls every
    # callee every time (=1.0, workmodelC.json external_services); synthetic
    # multi-parent meshes need <1 or path-count multiplication saturates
    # every node (each of k parents forwards the full upstream rate)
    fanout_frac: float = 1.0

    def service_rps(self, wm: Workmodel) -> dict[str, float]:
        """Propagate entry rps through the directed call graph: each request
        to a service triggers one request to each of its callees.

        Edges come from the shared cycle-broken traversal
        (``core.workmodel.kahn_traversal`` — also used by the request-level
        load generator, so CPU load and latency agree on which edges exist);
        processing in its topological order means every upstream contribution
        accumulates before a service's outgoing edges fire.
        """
        rps = {name: 0.0 for name in wm.names}
        if self.entry_service not in rps:
            return rps
        rps[self.entry_service] = self.entry_rps
        order, edges = kahn_traversal(wm.directed_relation(), wm.names)
        out_edges: dict[str, list[str]] = {}
        for s, d in edges:
            out_edges.setdefault(s, []).append(d)
        for svc in order:
            for callee in out_edges.get(svc, ()):
                rps[callee] += rps[svc] * self.fanout_frac
        return rps


@dataclass
class SimBackend:
    """In-memory cluster with dynamics. All mutation host-side numpy; the
    ``monitor`` snapshot is a fresh padded ``ClusterState``."""

    workmodel: Workmodel
    node_names: list[str]
    node_cpu_cap_m: float = 20_000.0
    node_mem_cap_b: float = 32 * 1024**3
    load: LoadModel = field(default_factory=LoadModel)
    seed: int = 0
    node_capacity: int | None = None
    pod_capacity: int | None = None
    reconcile_delay_s: float = 3.0     # simulated teardown+recreate latency
    pacing_s: float = 15.0             # reference main.py:27

    def __post_init__(self) -> None:
        self._rng = np.random.default_rng(self.seed)
        self._graph = self.workmodel.comm_graph()
        self._svc_index = {n: i for i, n in enumerate(self.workmodel.names)}
        self._rps_cache: dict[str, float] | None = None
        self.clock_s = 0.0
        self.events: list[dict] = []
        n = len(self.node_names)
        self._node_alive = np.ones(n, dtype=bool)
        self._cpu_spike: dict[str, float] = {}
        # pod table: (service_idx, node_idx, name); deployment = service
        self._pods: list[list] = []
        for idx, svc in enumerate(self.workmodel.services):
            for r in range(svc.replicas):
                node = int(self._rng.integers(0, n))
                self._pods.append([idx, node, f"{svc.name}-{r}"])

    # ---- Backend protocol ----

    def comm_graph(self) -> CommGraph:
        return self._graph

    def monitor(self) -> ClusterState:
        """Snapshot with load-model CPU usage (reference podmonitor.monitor)."""
        with timed_call("sim", "monitor"):
            return self._monitor()

    def _monitor(self) -> ClusterState:
        rps = self.load.service_rps(self.workmodel)
        replicas = {s.name: max(1, s.replicas) for s in self.workmodel.services}
        services, nodes, cpus, mems, names = [], [], [], [], []
        for svc_idx, node, name in self._pods:
            spec = self.workmodel.services[svc_idx]
            per_pod = (
                self.load.idle_m
                + rps.get(spec.name, 0.0)
                / replicas[spec.name]
                * self.load.cost_per_req_m
                * spec.proc_cost  # per-service cpu_stress weight (workmodelC)
            )
            per_pod *= self._cpu_spike.get(spec.name, 1.0)
            if self.load.noise_frac > 0:
                per_pod *= 1.0 + self._rng.normal(0.0, self.load.noise_frac)
            services.append(svc_idx)
            nodes.append(node if (node >= 0 and self._node_alive[node]) else UNASSIGNED)
            cpus.append(max(per_pod, 0.0))
            mems.append(float(spec.mem_request_bytes))
            names.append(name)
        return ClusterState.build(
            node_names=self.node_names,
            node_cpu_cap=[
                self.node_cpu_cap_m if a else 0.0 for a in self._node_alive
            ],
            node_mem_cap=[self.node_mem_cap_b] * len(self.node_names),
            node_alive=self._node_alive.tolist(),
            pod_services=services,
            pod_nodes=nodes,
            pod_cpu=cpus,
            pod_mem=mems,
            pod_names=names,
            node_capacity=self.node_capacity,
            pod_capacity=self.pod_capacity,
        )

    def apply_move(self, move: MoveRequest) -> str | None:
        """Foreground delete + re-create of one service's Deployment
        (reference delete_replaced_pod.py:173-177 + rescheduling.py:57-73).

        ``mechanism`` is honored the way the cluster would: ``nodeName`` and
        ``nodeSelector`` pin to the requested target, while ``affinityOnly``
        (the kubescheduling policy, reference rescheduling.py:159-171) only
        excludes the anti-affinity nodes and lets the *simulated scheduler*
        choose — least-allocated CPU, tie → first node in order, the same
        model the kubescheduling policy kernel implements. The requested
        target is advisory for that mechanism, exactly as on a real cluster.
        """
        with timed_call("sim", "apply_move"):
            return self._apply_move(move)

    def _apply_move(self, move: MoveRequest) -> str | None:
        if move.service not in self._svc_index:
            return None
        if move.mechanism == "affinityOnly":
            target = self._scheduler_choice(exclude=move.hazard_nodes)
            if target is None:
                return None
        else:
            if move.target_node not in self.node_names:
                return None
            target = self.node_names.index(move.target_node)
        if not self._node_alive[target]:
            return None
        svc_idx = self._svc_index[move.service]
        moved = 0
        for pod in self._pods:
            if pod[0] == svc_idx and (move.pod is None or pod[2] == move.pod):
                pod[1] = target
                moved += 1
                if move.pod is not None:
                    break  # a pod name matches at most one entry
        self.clock_s += self.reconcile_delay_s
        if moved:
            count_reconcile("sim", moved)
        landed = self.node_names[target]
        self.events.append(
            {
                "t": self.clock_s,
                "event": "move",
                "service": move.service,
                "target": landed,  # where pods actually went
                "requested": move.target_node,
                "pods": moved,
                "mechanism": move.mechanism,
            }
        )
        return landed if moved > 0 else None

    def advance(self, seconds: float) -> None:
        self.clock_s += seconds

    def _scheduler_choice(self, exclude: tuple[str, ...] = ()) -> int | None:
        """The sim's stand-in for the default kube-scheduler: least-allocated
        CPU among alive, non-excluded nodes; tie → first in node order.

        Computed host-side from the pod table (no full monitor() snapshot);
        the rps propagation is cached since workmodel and load are fixed
        per backend."""
        if self._rps_cache is None:
            self._rps_cache = self.load.service_rps(self.workmodel)
        rps = self._rps_cache
        replicas = {s.name: max(1, s.replicas) for s in self.workmodel.services}
        used = np.zeros(len(self.node_names))
        for svc_idx, node, _name in self._pods:
            if node < 0:
                continue
            spec = self.workmodel.services[svc_idx]
            per_pod = (
                self.load.idle_m
                + rps.get(spec.name, 0.0)
                / replicas[spec.name]
                * self.load.cost_per_req_m
                * spec.proc_cost
            )
            used[node] += per_pod * self._cpu_spike.get(spec.name, 1.0)
        best, best_used = None, np.inf
        for i, name in enumerate(self.node_names):
            if not self._node_alive[i] or name in exclude:
                continue
            if used[i] < best_used:
                best, best_used = i, float(used[i])
        return best

    def apply_pod_moves(self, moves) -> int:
        """Apply a batch of per-pod moves as ONE reconcile wave: a single
        indexed pass over the pod table and one clock advance. Per-replica
        placement moves many pods per round; issuing them as individual
        ``apply_move`` calls would both cost O(moves × pods) host time and
        charge one reconcile delay per replica — a clock model no real
        cluster has (kubelets reconcile in parallel). Returns the number
        of pods moved."""
        node_idx = {n: i for i, n in enumerate(self.node_names)}
        target_of: dict[str, int] = {}
        for mv in moves:
            t = node_idx.get(mv.target_node)
            if t is not None and self._node_alive[t] and mv.pod is not None:
                target_of[mv.pod] = t
        landed: list[str] = []
        for pod in self._pods:
            t = target_of.get(pod[2])
            if t is not None:
                pod[1] = t
                landed.append(pod[2])
        self.clock_s += self.reconcile_delay_s
        if landed:
            count_reconcile("sim", len(landed))
        self.events.append(
            {
                "t": self.clock_s,
                "event": "pod_moves",
                "pods": len(landed),
                "requested": len(moves),
            }
        )
        return landed

    def restore_placement(self, state: ClusterState) -> int:
        """Pin pods back to the placement recorded in a checkpoint snapshot
        (crash-resume support; pods are matched by name)."""
        node_of: dict[str, int] = {}
        pod_node = np.asarray(state.pod_node)
        valid = np.asarray(state.pod_valid)
        for i, name in enumerate(state.pod_names):
            if valid[i]:
                node_of[name] = int(pod_node[i])
        restored = 0
        for pod in self._pods:
            if pod[2] in node_of:
                pod[1] = node_of[pod[2]]
                restored += 1
        self.events.append({"t": self.clock_s, "event": "restore", "pods": restored})
        return restored

    # ---- fault injection (SURVEY.md §5.3) ----

    def inject_imbalance(self, node: str) -> None:
        """The cordon trick: pile every pod onto one node
        (reference auto_full_pipeline_repeat.sh:48-51)."""
        idx = self.node_names.index(node)
        for pod in self._pods:
            pod[1] = idx
        self.events.append({"t": self.clock_s, "event": "imbalance", "node": node})

    def kill_node(self, node: str) -> None:
        """Node failure: capacity gone, its pods evicted to pending."""
        idx = self.node_names.index(node)
        self._node_alive[idx] = False
        for pod in self._pods:
            if pod[1] == idx:
                pod[1] = UNASSIGNED
        self.events.append({"t": self.clock_s, "event": "node_kill", "node": node})

    def revive_node(self, node: str) -> None:
        self._node_alive[self.node_names.index(node)] = True
        self.events.append({"t": self.clock_s, "event": "node_revive", "node": node})

    def cpu_spike(self, service: str, factor: float) -> None:
        """Multiply one service's CPU usage (hot-spot injection)."""
        self._cpu_spike[service] = factor
        self.events.append(
            {"t": self.clock_s, "event": "cpu_spike", "service": service, "factor": factor}
        )

    def churn(self, n_restarts: int) -> None:
        """Random pod restarts onto random nodes (background churn)."""
        alive = np.flatnonzero(self._node_alive)
        for _ in range(n_restarts):
            pod = self._pods[int(self._rng.integers(len(self._pods)))]
            pod[1] = int(self._rng.choice(alive))
        self.events.append({"t": self.clock_s, "event": "churn", "n": n_restarts})

    def schedule_pending(self) -> int:
        """Place UNASSIGNED pods on the least-loaded alive node (what
        kube-scheduler would do for evicted pods)."""
        counts = np.zeros(len(self.node_names))
        for pod in self._pods:
            if pod[1] >= 0:
                counts[pod[1]] += 1
        counts[~self._node_alive] = np.inf
        placed = 0
        for pod in self._pods:
            if pod[1] == UNASSIGNED:
                pod[1] = int(np.argmin(counts))
                counts[pod[1]] += 1
                placed += 1
        return placed
