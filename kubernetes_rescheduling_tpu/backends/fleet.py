"""Fleet backend: N per-tenant cluster backends behind one handle.

Fleet mode multiplexes ONE device plane over MANY clusters; on the host
side each tenant keeps its own backend (its own pod table, clock,
events, faults). :class:`FleetBackend` is deliberately NOT a
``Backend`` — the multiplexed controller talks to every tenant through
that tenant's OWN :class:`~bench.boundary.BoundaryClient` (retry +
breaker per tenant, the isolation the fleet loop is built around), so an
aggregate ``monitor()`` would be a trap: it would couple tenants' failure
domains back together. What the aggregate owns is construction, naming,
and fleet-wide conveniences (imbalance injection, event collection).

Chaos composes per tenant: ``chaos_tenants`` wraps ONLY those tenants'
backends in the named :mod:`backends.chaos` profile (seeded per tenant),
which is how the isolation acceptance test arranges "tenant 3 is on
fire, tenants 0-2 must not notice".
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kubernetes_rescheduling_tpu.backends.base import Backend


@dataclass
class FleetBackend:
    """N tenant backends, index-aligned with ``tenant_names``."""

    backends: list[Backend]
    tenant_names: list[str] = field(default_factory=list)

    def __post_init__(self) -> None:
        if not self.backends:
            raise ValueError("a fleet needs at least one tenant backend")
        if not self.tenant_names:
            self.tenant_names = [
                f"tenant{i}" for i in range(len(self.backends))
            ]
        if len(self.tenant_names) != len(self.backends):
            raise ValueError(
                f"{len(self.tenant_names)} tenant names for "
                f"{len(self.backends)} backends"
            )
        if len(set(self.tenant_names)) != len(self.tenant_names):
            raise ValueError("tenant names must be unique")

    @property
    def num_tenants(self) -> int:
        return len(self.backends)

    def __iter__(self):
        return iter(zip(self.tenant_names, self.backends))

    def inject_imbalance(self) -> None:
        """The cordon trick, per tenant (each onto its own first node) —
        the fleet twin of the harness's per-cell injection."""
        for b in self.backends:
            inject = getattr(b, "inject_imbalance", None)
            if inject is not None:
                inject(b.node_names[0])

    def events(self) -> dict[str, list[dict]]:
        """Per-tenant backend event logs (sim backends only)."""
        return {
            name: list(getattr(b, "events", ()))
            for name, b in zip(self.tenant_names, self.backends)
        }


def make_fleet(
    scenario: str,
    tenants: int,
    *,
    seed: int = 0,
    workmodel_path: str | None = None,
) -> FleetBackend:
    """Build an N-tenant fleet of hermetic simulators for a scenario.

    Every tenant gets the scenario's cluster shape with its OWN seed
    (``seed*1000 + t`` — the harness's per-run seeding convention), so
    tenants share array shapes (the fleet-stacking requirement: one
    compiled program serves the whole fleet) while their topologies,
    initial placements, and load noise differ.
    """
    from kubernetes_rescheduling_tpu.bench.harness import make_backend

    if tenants < 1:
        raise ValueError(f"tenants must be >= 1, got {tenants}")
    backends = [
        make_backend(scenario, seed * 1000 + t, workmodel_path=workmodel_path)
        for t in range(tenants)
    ]
    return FleetBackend(backends=list(backends))
