"""Device-resident sim twin: the simulator's steady-state round update
as pure, jittable array math.

``SimBackend`` is host-side numpy by design — its pod table mutates, its
clock advances, its events list grows. But the STEADY-STATE round (no
churn, no chaos, no load noise) touches none of that richness: the
monitor snapshot is a pure function of the placement (per-pod CPU comes
from the load model, which depends only on the service — never on the
node), and a round's only mutation is "move the victim Deployment's pods
to the landing node". That update is what this module extracts, so the
scanned round loop (``bench/scan.py``) can run K whole rounds — decide →
apply → monitor → round-end metrics — inside ONE ``lax.scan`` without a
host round trip.

The contract, pinned by the bit-parity oracle test (tests/test_scan.py):
seeded multi-round trajectories through the jitted :func:`sim_step` and
the Python ``SimBackend`` produce bit-identical placements and loads —
including moves that land on over-capacity nodes (the simulator never
rejects on capacity, and neither does the twin) and the
``affinityOnly`` scheduler-choice fallback (:func:`scheduler_choice`,
the twin of ``SimBackend._scheduler_choice``). The Python backend stays
the oracle: the scanned controller replays every scanned move back into
it through the boundary, so anything the twin cannot express (churn,
faults, noise) simply drains to the per-round path.

Twin construction goes through :func:`twin_of`, which reuses the
monitor snapshot and the backend's OWN :func:`~backends.sim.workload_layout`
— capacity padding and service-index compaction have exactly one
definition, so a post-churn rebuild cannot drift from what the backend
serves (regression-pinned).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from kubernetes_rescheduling_tpu.backends.sim import SimBackend, workload_layout
from kubernetes_rescheduling_tpu.core.state import (
    UNASSIGNED,
    ClusterState,
    CommGraph,
)
from kubernetes_rescheduling_tpu.policies.victim import deployment_group


def scheduler_choice(
    state: ClusterState, exclude_mask: jax.Array
) -> jax.Array:
    """The jittable twin of ``SimBackend._scheduler_choice``: the node
    the simulated default scheduler would pick — least-allocated CPU
    among valid (alive), non-excluded nodes; tie → first in node order
    (``argmin`` returns the first minimum, matching the Python loop's
    strict ``<``). Returns -1 when no candidate exists.

    Allocation is the sum of tracked pod CPU per node — the snapshot's
    ``pod_cpu`` IS the load model's per-pod usage in the steady state,
    and the sim's nodes carry no base load — computed in f32 where the
    Python oracle sums f64 (the parity test pins agreement on seeded
    scenarios; a disagreement would need two nodes within one f32 ulp).
    """
    n = state.num_nodes
    assign = jnp.where(
        state.pod_valid & (state.pod_node >= 0), state.pod_node, n
    )
    used = (
        jnp.zeros((n + 1,), jnp.float32)
        .at[assign]
        .add(jnp.where(state.pod_valid, state.pod_cpu, 0.0))
    )[:n]
    cand = state.node_valid & ~exclude_mask
    masked = jnp.where(cand, used, jnp.inf)
    best = jnp.argmin(masked).astype(jnp.int32)
    return jnp.where(jnp.any(cand), best, -1)


def apply_decision(
    state: ClusterState,
    victim: jax.Array,
    service: jax.Array,
    target: jax.Array,
    hazard_mask: jax.Array,
    *,
    pinned: bool = True,
) -> tuple[ClusterState, jax.Array, jax.Array]:
    """Apply one round's decision to the twin state — the device half of
    ``SimBackend.apply_move`` + the steady-state monitor rebuild.

    ``pinned=True`` models the ``nodeName``/``nodeSelector`` mechanisms
    (the move lands exactly on ``target``); ``pinned=False`` models
    ``affinityOnly`` — the requested target is advisory and the landing
    is :func:`scheduler_choice` excluding the hazard nodes, exactly as
    the Python simulator honors that mechanism. A dead/invalid landing
    (or a no-op decision: ``victim``/``target`` -1) moves nothing, the
    simulator's ``return None`` path.

    Returns ``(new_state, landed, moved)``: the post-move twin state
    (bit-equal to what the next ``monitor()`` would build — per-pod CPU
    never depends on placement), the i32 landing node index (-1 when no
    move happened), and the bool moved flag.
    """
    # ``service`` is implied by the victim's deployment_group (the same
    # rule the sequential loop applies); it stays in the signature so
    # decide's output tuple threads through unchanged
    del service
    if pinned:
        landing = target
    else:
        landing = scheduler_choice(state, hazard_mask)
    safe = jnp.clip(landing, 0, state.num_nodes - 1)
    alive = state.node_valid[safe] & (landing >= 0)
    do = (victim >= 0) & (target >= 0) & alive
    group = deployment_group(state, victim)
    new_pod_node = jnp.where(do & group, safe, state.pod_node)
    new_state = state.replace(pod_node=new_pod_node)
    return new_state, jnp.where(do, landing, -1), do


def sim_step(
    state: ClusterState,
    decision: tuple[jax.Array, jax.Array, jax.Array, jax.Array],
    *,
    pinned: bool = True,
) -> tuple[ClusterState, ClusterState]:
    """One steady-state simulator round: apply ``decision`` — a
    ``(victim, service, target, hazard_mask)`` tuple, the decide
    kernel's outputs — and return ``(new_sim_state, snapshot)``.

    In the steady state the monitor is the identity on the post-move
    state (loads are placement-independent), so the snapshot IS the new
    state; the pair return keeps the monitor's role explicit for
    callers that treat the two differently (the scanned loop's round-end
    metrics run on the snapshot half)."""
    victim, service, target, hazard_mask = decision
    new_state, _landed, _moved = apply_decision(
        state, victim, service, target, hazard_mask, pinned=pinned
    )
    return new_state, new_state


def twin_of(backend: SimBackend) -> tuple[ClusterState, CommGraph]:
    """Build the device twin of a ``SimBackend``: the current monitor
    snapshot (the twin's carried state) plus the comm graph from the
    SHARED :func:`~backends.sim.workload_layout` — the same padding and
    service-index compaction the backend itself serves, so a twin built
    after arbitrary churn (deploys, teardowns, autoscaling) scores the
    exact topology the backend's next snapshot will carry."""
    graph, _svc_index = workload_layout(
        backend.workmodel, backend.service_capacity
    )
    return backend.monitor(), graph


def scan_compatible(backend) -> bool:
    """Whether the scanned schedule's steady-state assumptions hold for
    this backend: a RAW hermetic simulator (chaos wrappers, replay
    backends, and live adapters inject behavior only the per-round path
    can honor) with a noise-free load model (monitor must be a pure
    function of placement) and no pending CPU-spike injections beyond
    what the snapshot already reflects (spikes are static multipliers —
    they bake into ``pod_cpu`` and stay steady unless mutated mid-run,
    which only ``on_round`` could do; the controller gates on that
    separately)."""
    return (
        type(backend) is SimBackend
        and float(backend.load.noise_frac) == 0.0
    )


__all__ = [
    "apply_decision",
    "scan_compatible",
    "scheduler_choice",
    "sim_step",
    "twin_of",
    "UNASSIGNED",
]
