"""Cluster backends.

- ``SimBackend`` — hermetic in-memory cluster with µBench-like load dynamics
  and fault injection; what the reference validates only on live hardware
  (SURVEY.md §4) runs here deterministically.
- ``K8sBackend`` — thin host-side adapter with the reference's reconcile
  semantics (foreground delete + wait-404, anti-affinity patch, pinned
  re-create). Never traced; works against any object implementing the small
  client protocol (the real ``kubernetes`` package or a fake).
- ``ChaosBackend`` — fault-injecting wrapper over any backend (seeded
  monitor failures, stale/partial snapshots, move timeouts/mis-lands,
  node flap), the chaos-engineering surface the resilience layer is
  tested against.
- ``FleetBackend`` — N per-tenant backends behind one handle for the
  multiplexed fleet controller (each tenant keeps its own failure
  domain; chaos composes per tenant).
- ``ReplayBackend`` — a recorded real-cluster trace (``traces/``)
  served through the same surface; ``apply_move`` records
  recommendations instead of mutating anything (shadow mode's
  transport).
"""

from kubernetes_rescheduling_tpu.backends.base import Backend, MoveRequest
from kubernetes_rescheduling_tpu.backends.sim import LoadModel, SimBackend
from kubernetes_rescheduling_tpu.backends.k8s import K8sBackend, PlacementMechanism
from kubernetes_rescheduling_tpu.backends.chaos import (
    ChaosBackend,
    ChaosError,
    ChaosProfile,
    ChaosTimeoutError,
    PROFILES as CHAOS_PROFILES,
    with_chaos,
)
from kubernetes_rescheduling_tpu.backends.fleet import FleetBackend, make_fleet
from kubernetes_rescheduling_tpu.backends.replay import ReplayBackend

__all__ = [
    "Backend",
    "MoveRequest",
    "LoadModel",
    "SimBackend",
    "K8sBackend",
    "PlacementMechanism",
    "ChaosBackend",
    "ChaosError",
    "ChaosProfile",
    "ChaosTimeoutError",
    "CHAOS_PROFILES",
    "with_chaos",
    "FleetBackend",
    "make_fleet",
    "ReplayBackend",
]
