"""Replay backend: a recorded cluster trace behind the Backend surface.

The shadow plane's transport (ROADMAP item 3): ``monitor()`` serves the
trace's snapshot windows one per call — the TRACE drives the clock, the
controller paces against recorded time, and each post-move monitor
observes what the real cluster (and its real scheduler) actually did
next. ``apply_move`` is **advisory-only by construction**: it records
the recommendation in the shadow ledger (``recommendations``) and
returns the requested target, but there is NO mutation path — the class
holds no mutable cluster state to mutate, which is the strongest form of
"asserts no applies". The controller marks replay intents advisory
(``advisory_only``), so the PR-10 intent ledger adopts the observed
(recorded) placement at the first diff instead of charging the real
scheduler's choices as drift.

Static shapes for free: every window builds at the trace-wide node
table and max-window pod count (``traces.corpus.ClusterTrace``), so the
decision kernels hold the 1-steady-state-trace invariant across the
whole replay. Snapshot states are built FRESH per ``monitor`` (see that
method — a memoized window object re-served on the clamped tail would
hand the donated global carry deleted buffers); the trace itself is the
only state, so fresh builds are bit-identical and the determinism pin
(bit-identical recommendations across runs) has no hidden host state to
drift on.
"""

from __future__ import annotations

from kubernetes_rescheduling_tpu.backends.base import MoveRequest
from kubernetes_rescheduling_tpu.core.state import ClusterState, CommGraph
from kubernetes_rescheduling_tpu.telemetry.accounting import timed_call
from kubernetes_rescheduling_tpu.telemetry.registry import get_registry
from kubernetes_rescheduling_tpu.traces.corpus import ClusterTrace, window_state


class ReplayBackend:
    """Serve a :class:`~traces.corpus.ClusterTrace` as a cluster."""

    # the controller reads this and marks every intent advisory: a
    # recommendation is definitionally advisory, and the recorded
    # scheduler's placement is the ground truth the ledger adopts
    advisory_only = True
    supports_pod_moves = True  # recommendations may be pod-granular

    def __init__(
        self,
        trace: ClusterTrace,
        *,
        pod_capacity: int | None = None,
        registry=None,
    ) -> None:
        windows = trace.windows()
        if not windows:
            raise ValueError(f"empty trace: {trace.source}")
        if not any(w.pods for w in windows):
            raise ValueError(
                f"trace {trace.source} carries no pod records — nothing "
                f"to replay (rounds.jsonl-converted traces are usage/"
                f"placement corpora for the schema tooling, not replay "
                f"inputs; use an external-format or native trace)"
            )
        self.trace = trace
        self.registry = registry
        self._windows = windows
        self._pod_capacity = pod_capacity or trace.max_window_pods
        self._graph = trace.comm_graph()
        self._idx = -1
        # phantom node references count ONCE, at load: monitor() rebuilds
        # windows fresh every serve (clamped tail included), and the
        # quarantine metric is documented as load-time row counts
        declared = set(trace.node_names)
        unknown = sum(
            1
            for w in windows
            for rec in w.pods
            if rec.get("node") is not None and rec["node"] not in declared
        )
        if unknown:
            from kubernetes_rescheduling_tpu.traces.corpus import (
                REASON_UNKNOWN_NODE_REF,
                _count_quarantine,
            )

            _count_quarantine(registry, REASON_UNKNOWN_NODE_REF, unknown)
        self.clock_s = 0.0
        # the raw shadow ledger: every recommendation the controller
        # issued, in order, with the window it was decided against
        self.recommendations: list[dict] = []

    # ---- Backend protocol ----

    def comm_graph(self) -> CommGraph:
        return self._graph

    @property
    def window(self) -> int:
        """Index of the most recently served window."""
        return max(self._idx, 0)

    @property
    def exhausted(self) -> bool:
        """True once the last window has been served (further monitors
        re-serve it — the steady tail)."""
        return self._idx >= len(self._windows) - 1

    def monitor(self) -> ClusterState:
        """Serve the next snapshot window (clamped at the trace end).

        Built FRESH per call, like the sim backend's monitor — the
        global solver's donated carry consumes snapshot buffers, so a
        memoized window object re-served on the clamped tail would hand
        the controller deleted arrays. The trace itself is immutable;
        fresh builds from it are bit-identical by construction (the
        determinism pin in tests/test_shadow.py rides on this)."""
        with timed_call("replay", "monitor"):
            self._idx = min(self._idx + 1, len(self._windows) - 1)
            self.clock_s = float(self._windows[self._idx].t)
            return window_state(
                self.trace,
                self._idx,
                pod_capacity=self._pod_capacity,
                registry=self.registry,
                count_refs=False,  # counted once at construction
            )

    def apply_move(self, move: MoveRequest) -> str | None:
        """Record the recommendation; mutate nothing. Returns the
        requested target (the advisory echo — the recorded scheduler's
        actual choice shows at the next monitor)."""
        with timed_call("replay", "apply_move"):
            self.recommendations.append(
                {
                    "t": self.clock_s,
                    "window": self.window,
                    "service": move.service,
                    "pod": move.pod,
                    "target": move.target_node,
                    "mechanism": move.mechanism,
                }
            )
            reg = (
                self.registry if self.registry is not None else get_registry()
            )
            reg.counter(
                "shadow_recommendations_total",
                "rescheduling moves recommended (never applied) by the "
                "shadow plane's replay backend",
            ).inc()
            return move.target_node

    def advance(self, seconds: float) -> None:
        """Pacing is informational: the trace drives the clock (each
        monitor stamps the served window's timestamp)."""
        self.clock_s += float(seconds)
