"""Typed churn events and seeded churn profiles.

The reference paper only ever studied a static 10-service µBench graph
on a fixed 4-node cluster; real clusters continuously deploy and tear
down services, autoscale replicas with traffic (Autopilot makes
autoscaling the dominant source of placement change), and lose/gain
node pools. This module is the event vocabulary for that churn plus the
named, seeded profiles that generate it — the elastic analogue of
``backends.chaos``'s fault profiles:

- ``steady``          — background replica jitter: the quiet cluster
                        that still never stops moving.
- ``diurnal-autoscale`` — per-service replica targets track the request
                        -rate series the load generator exposes
                        (``bench.loadgen.service_rate_series``), ×0.5–×2
                        over the horizon, plus one node drain/add cycle.
- ``deploy-waves``    — periodic waves of new services wired into the
                        live call graph, oldest wave torn down as new
                        ones land.
- ``node-flap``       — a rotating node drains and returns, with one
                        mid-horizon spot-preemption burst.

Events are plain frozen dataclasses (``as_dict`` for telemetry); the
:class:`~elastic.engine.ChurnEngine` applies them to a backend between
rounds. Profiles are deterministic under their seed: the same
``(profile, seed, horizon, workload)`` always yields the same event
stream — churn soaks are reproducible, like chaos soaks.

jax-free: profiles run host-side between rounds, never in traced code.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Mapping

import numpy as np

from kubernetes_rescheduling_tpu.config import ELASTIC_PROFILES
from kubernetes_rescheduling_tpu.core.workmodel import ServiceSpec


@dataclass(frozen=True)
class ServiceDeploy:
    """A new service lands (one deploy of a wave): its spec carries the
    callees wiring it into the live call graph."""

    spec: ServiceSpec
    kind: str = field(default="service_deploy", init=False)

    def as_dict(self) -> dict:
        return {
            "kind": self.kind,
            "service": self.spec.name,
            "replicas": self.spec.replicas,
            "callees": list(self.spec.callees),
        }


@dataclass(frozen=True)
class ServiceTeardown:
    service: str
    kind: str = field(default="service_teardown", init=False)

    def as_dict(self) -> dict:
        return {"kind": self.kind, "service": self.service}


@dataclass(frozen=True)
class ReplicaScale:
    """Autoscale one service to a new replica target (up or down)."""

    service: str
    replicas: int
    kind: str = field(default="replica_scale", init=False)

    def as_dict(self) -> dict:
        return {"kind": self.kind, "service": self.service, "replicas": self.replicas}


@dataclass(frozen=True)
class NodeDrain:
    """Cordon+drain: the node leaves the pool, its pods reschedule."""

    node: str
    kind: str = field(default="node_drain", init=False)

    def as_dict(self) -> dict:
        return {"kind": self.kind, "node": self.node}


@dataclass(frozen=True)
class NodeAdd:
    """A node (re)joins the pool: a drained slot revives, or a brand-new
    node name grows the cluster."""

    node: str
    kind: str = field(default="node_add", init=False)

    def as_dict(self) -> dict:
        return {"kind": self.kind, "node": self.node}


@dataclass(frozen=True)
class SpotPreemption:
    """A burst of simultaneous node losses (spot/preemptible reclaim)."""

    nodes: tuple[str, ...]
    kind: str = field(default="spot_preemption", init=False)

    def as_dict(self) -> dict:
        return {"kind": self.kind, "nodes": list(self.nodes)}


ChurnEvent = (
    ServiceDeploy
    | ServiceTeardown
    | ReplicaScale
    | NodeDrain
    | NodeAdd
    | SpotPreemption
)

# event kinds that change the communication graph (service set / edges):
# the controller refreshes its decision+metric graphs when one applies
GRAPH_EVENTS = ("service_deploy", "service_teardown")


@dataclass(frozen=True)
class WorkloadView:
    """What a profile may read about the live cluster each round —
    assembled by the engine so profiles never touch backend internals."""

    services: tuple[str, ...]                 # live service names, index order
    replicas: Mapping[str, int]               # live replica targets
    base_replicas: Mapping[str, int]          # replica targets at bind time
    nodes: tuple[str, ...]                    # every node slot (incl. drained)
    alive: tuple[bool, ...]                   # index-aligned with ``nodes``

    @property
    def alive_nodes(self) -> tuple[str, ...]:
        return tuple(n for n, a in zip(self.nodes, self.alive) if a)


class ChurnProfileBase:
    """One named churn source. Stateful where the schedule needs memory
    (deployed waves, drained nodes); all randomness flows through the
    engine's seeded rng argument, so state never hides a seed."""

    name: str = "base"

    def events(
        self,
        rng: np.random.Generator,
        rnd: int,
        horizon: int,
        view: WorkloadView,
    ) -> list:
        raise NotImplementedError


class SteadyProfile(ChurnProfileBase):
    """Background churn: roughly every third round one service's replica
    count jitters ±1 around its bind-time target. Structural shapes never
    change — the profile that pins "a quiet cluster stays at 1 trace"."""

    name = "steady"

    def __init__(self, rate: float = 0.35):
        self.rate = rate

    def events(self, rng, rnd, horizon, view):
        if not view.services or rng.random() >= self.rate:
            return []
        svc = str(view.services[int(rng.integers(len(view.services)))])
        base = int(view.base_replicas.get(svc, 1))
        cur = int(view.replicas.get(svc, base))
        target = max(1, base + int(rng.integers(-1, 2)))
        if target == cur:
            return []
        return [ReplicaScale(service=svc, replicas=target)]


class DiurnalAutoscaleProfile(ChurnProfileBase):
    """Traffic-driven autoscaling: each service's replica target follows
    its request-rate factor from the load generator's rate series
    (``bench.loadgen.service_rate_series`` — the engine binds one over
    the live workmodel), swinging ×1/amplitude–×amplitude across the
    horizon, plus ONE node drain/add cycle (a pool scale-down that comes
    back) — the acceptance-soak scenario.
    """

    name = "diurnal-autoscale"

    def __init__(
        self,
        amplitude: float = 2.0,
        drain_frac: float = 1 / 3,
        revive_frac: float = 2 / 3,
    ):
        self.amplitude = amplitude
        self.drain_frac = drain_frac
        self.revive_frac = revive_frac
        self.rates = None          # bound by the engine (RateProfile)
        self._drained: str | None = None

    def _default_factor(self, rnd: int, horizon: int) -> float:
        # no rate series (service not in it, or none bound): the plain
        # shared diurnal sinusoid
        phase = (rnd - 1) / max(horizon, 1)
        return float(self.amplitude ** math.sin(2.0 * math.pi * phase))

    def events(self, rng, rnd, horizon, view):
        out: list = []
        # ONE factors build per round — RateProfile.factors interpolates
        # all S services at once, and re-deriving it per service would
        # make a churn round O(S^2) host-side
        factors = (
            self.rates.factors(rnd, horizon) if self.rates is not None else {}
        )
        fallback = self._default_factor(rnd, horizon)
        for svc in view.services:
            base = int(view.base_replicas.get(svc, 1))
            factor = float(factors.get(svc, fallback))
            target = max(1, int(round(base * factor)))
            if target != int(view.replicas.get(svc, base)):
                out.append(ReplicaScale(service=svc, replicas=target))
        drain_rnd = max(1, int(math.ceil(horizon * self.drain_frac)))
        revive_rnd = max(drain_rnd + 1, int(math.ceil(horizon * self.revive_frac)))
        if rnd == drain_rnd and self._drained is None and len(view.alive_nodes) > 1:
            self._drained = str(view.alive_nodes[-1])
            out.append(NodeDrain(node=self._drained))
        if rnd == revive_rnd and self._drained is not None:
            out.append(NodeAdd(node=self._drained))
            self._drained = None
        return out


class DeployWavesProfile(ChurnProfileBase):
    """Deploy/teardown waves: every ``every`` rounds a wave of ``wave``
    new services lands, each calling up to two seeded-random live
    services; once more than ``max_waves`` waves are live the oldest
    tears down. The service set — and the comm graph — genuinely grows
    and shrinks."""

    name = "deploy-waves"

    def __init__(self, every: int = 5, wave: int = 2, max_waves: int = 2):
        self.every = max(1, every)
        self.wave = max(1, wave)
        self.max_waves = max(1, max_waves)
        self._waves: list[list[str]] = []
        self._counter = 0

    def events(self, rng, rnd, horizon, view):
        if (rnd - 1) % self.every != 0:
            return []
        out: list = []
        names: list[str] = []
        live = list(view.services)
        for _ in range(self.wave):
            self._counter += 1
            name = f"churn{self._counter}"
            callees = []
            if live:
                k = min(2, len(live))
                idx = rng.choice(len(live), size=k, replace=False)
                callees = [str(live[int(i)]) for i in idx]
            names.append(name)
            out.append(
                ServiceDeploy(
                    spec=ServiceSpec(
                        name=name,
                        callees=tuple(callees),
                        cpu_request_millicores=100,
                        replicas=1,
                    )
                )
            )
        self._waves.append(names)
        if len(self._waves) > self.max_waves:
            for gone in self._waves.pop(0):
                if gone in view.services:
                    out.append(ServiceTeardown(service=gone))
        return out


class NodeFlapProfile(ChurnProfileBase):
    """Node-pool churn: every ``period`` rounds the next node in
    rotation drains for ``down_for`` rounds, and at mid-horizon a
    spot-preemption burst takes two nodes at once (back the round
    after). At least two nodes always stay alive."""

    name = "node-flap"

    def __init__(self, period: int = 4, down_for: int = 2):
        self.period = max(1, period)
        self.down_for = max(1, down_for)
        self._down: dict[str, int] = {}   # node -> revive round
        self._rotation = 0
        self._preempted: tuple[str, ...] = ()

    def events(self, rng, rnd, horizon, view):
        out: list = []
        for node, back in sorted(self._down.items()):
            if rnd >= back:
                out.append(NodeAdd(node=node))
        self._down = {n: b for n, b in self._down.items() if rnd < b}
        if self._preempted:
            for node in self._preempted:
                out.append(NodeAdd(node=node))
            self._preempted = ()
        alive = [n for n in view.alive_nodes if n not in self._down]
        if (rnd - 1) % self.period == 0 and len(alive) > 2:
            node = alive[self._rotation % len(alive)]
            self._rotation += 1
            self._down[str(node)] = rnd + self.down_for
            out.append(NodeDrain(node=str(node)))
        alive = [n for n in view.alive_nodes if n not in self._down]
        if rnd == max(1, horizon // 2) and len(alive) > 3:
            burst = tuple(str(n) for n in alive[-2:])
            self._preempted = burst
            out.append(SpotPreemption(nodes=burst))
        return out


def make_profile(name: str) -> ChurnProfileBase:
    """Profile factory — the churn twin of ``backends.chaos.PROFILES``."""
    table = {
        "steady": SteadyProfile,
        "diurnal-autoscale": DiurnalAutoscaleProfile,
        "deploy-waves": DeployWavesProfile,
        "node-flap": NodeFlapProfile,
    }
    if name not in table:
        raise ValueError(
            f"unknown churn profile {name!r}; expected one of {sorted(table)}"
        )
    return table[name]()


# the config module mirrors this registry so TOML validation stays light;
# the two must never drift
assert tuple(sorted(ELASTIC_PROFILES)) == tuple(
    sorted(("steady", "diurnal-autoscale", "deploy-waves", "node-flap"))
)
