"""Shape-bucket plane: absorb churn without retraces.

Every device kernel in this codebase is already mask-native — states and
graphs carry ``pod_valid``/``node_valid``/``service_valid`` and padded
slots never emit moves or contribute cost (statically enforced by
``scripts/check_mask_threading.py``, bit-exactness pinned by the
mask-twin tests). What churn therefore threatens is not correctness but
COMPILATION: a jit cache keys on array shapes AND on the pytree's static
metadata, so a cluster that grows by one pod — or merely renames one —
would retrace every kernel every round.

Two mechanisms close that hole:

- **Quantized capacity buckets** (:func:`bucket_capacity`,
  :class:`ShapeBuckets`): live S×N×P counts are padded up to the next
  power-of-two bucket (with a floor), so arbitrary churn WITHIN a bucket
  reuses the compiled program; only a bucket **promotion** — live counts
  outgrowing a capacity — changes shapes, and promotions are counted
  (``bucket_promotions_total``) and test-pinned: steady state is exactly
  1 trace per kernel plus one per promotion.
- **Device views** (:func:`device_view`, :func:`device_graph`): the
  name tuples on :class:`~core.state.ClusterState` /
  :class:`~core.state.CommGraph` are static (non-pytree) metadata, so a
  new pod name would be a new treedef — a silent retrace the shape
  buckets cannot absorb. The controller hands kernels a view with the
  name tuples stripped (they are host-side bookkeeping no traced code
  reads); the full snapshot keeps the live names for everything
  host-side. Stripping changes the jit key, never a value: the arrays
  are the same objects.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kubernetes_rescheduling_tpu.core.state import ClusterState, CommGraph


def bucket_capacity(n: int, *, floor: int = 8) -> int:
    """The quantized capacity for a live count: the next power of two at
    or above ``n``, never below ``floor``. Power-of-two growth keeps the
    number of distinct compiled shapes logarithmic in cluster size."""
    if n < 0:
        raise ValueError(f"live count must be >= 0, got {n}")
    cap = max(int(floor), 1)
    while cap < n:
        cap *= 2
    return cap


@dataclass
class ShapeBuckets:
    """Current capacity bucket per axis, with promotion accounting.

    ``fit`` grows whichever axes a new set of live counts has outgrown
    and reports whether anything grew — the ONE legal retrace trigger
    under churn. Buckets never shrink: demotion would trade a retrace
    for memory the next scale-up immediately re-pays.
    """

    floor: int = 8
    services: int = 0
    nodes: int = 0
    pods: int = 0
    # promoting fit() calls (NOT per-axis growths): one fit that grows
    # two axes produces one new compiled signature, hence counts once
    promotions: int = 0
    history: list[dict] = field(default_factory=list)

    def fit(self, *, services: int, nodes: int, pods: int) -> bool:
        """Grow buckets to cover the live counts; True iff promoted."""
        new = {
            "services": max(self.services, bucket_capacity(services, floor=self.floor)),
            "nodes": max(self.nodes, bucket_capacity(nodes, floor=self.floor)),
            "pods": max(self.pods, bucket_capacity(pods, floor=self.floor)),
        }
        promoted = (
            new["services"] > self.services
            or new["nodes"] > self.nodes
            or new["pods"] > self.pods
        )
        first = self.services == 0 and self.nodes == 0 and self.pods == 0
        self.services, self.nodes, self.pods = (
            new["services"], new["nodes"], new["pods"],
        )
        if first:
            return False  # initial sizing is a compile, not a promotion
        if promoted:
            self.promotions += 1
            self.history.append(dict(new))
        return promoted

    def as_dict(self) -> dict:
        return {
            "services": self.services,
            "nodes": self.nodes,
            "pods": self.pods,
            "promotions": self.promotions,
        }


def device_view(state: ClusterState) -> ClusterState:
    """The kernel-facing view of a snapshot: same arrays, name tuples
    stripped so pod/node churn cannot change the jit treedef."""
    if not state.node_names and not state.pod_names:
        return state
    return state.replace(node_names=(), pod_names=())


def device_graph(graph: CommGraph) -> CommGraph:
    """The kernel-facing view of a comm graph: same adjacency, the
    static service-name tuple stripped (service deploy/teardown renames
    slots; the kernels only ever read ``adj``/``service_valid``)."""
    if not graph.names:
        return graph
    return graph.replace(names=())
