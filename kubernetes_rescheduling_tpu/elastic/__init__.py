"""Elastic topologies: churn, autoscaling, and node-pool events absorbed
without retraces.

- :mod:`elastic.events` — typed churn events + seeded named profiles
  (``steady`` / ``diurnal-autoscale`` / ``deploy-waves`` / ``node-flap``);
- :mod:`elastic.buckets` — quantized shape buckets + the name-stripped
  device views that keep the jit cache stable under arbitrary churn
  within a bucket (retrace only on a counted promotion);
- :mod:`elastic.engine` — the :class:`ChurnEngine` that applies a
  profile's events to a backend between controller rounds.
"""

from kubernetes_rescheduling_tpu.elastic.buckets import (
    ShapeBuckets,
    bucket_capacity,
    device_graph,
    device_view,
)
from kubernetes_rescheduling_tpu.elastic.engine import (
    ChurnEngine,
    make_fleet_churn,
)
from kubernetes_rescheduling_tpu.elastic.events import (
    GRAPH_EVENTS,
    NodeAdd,
    NodeDrain,
    ReplicaScale,
    ServiceDeploy,
    ServiceTeardown,
    SpotPreemption,
    WorkloadView,
    make_profile,
)

__all__ = [
    "ShapeBuckets",
    "bucket_capacity",
    "device_graph",
    "device_view",
    "ChurnEngine",
    "make_fleet_churn",
    "GRAPH_EVENTS",
    "NodeAdd",
    "NodeDrain",
    "ReplicaScale",
    "ServiceDeploy",
    "ServiceTeardown",
    "SpotPreemption",
    "WorkloadView",
    "make_profile",
]
