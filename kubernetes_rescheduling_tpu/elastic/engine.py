"""The churn engine: applies a seeded churn profile to a backend between
controller rounds, padding live shapes into quantized buckets so the
device plane never retraces except on a counted bucket promotion.

Per round (:meth:`ChurnEngine.step`):

1. build a :class:`~elastic.events.WorkloadView` of the live cluster;
2. ask the profile for this round's events (seeded rng — the stream is
   a pure function of ``(profile, seed, horizon, workload)``);
3. pre-fit the shape buckets against the POST-event live counts and push
   the (possibly promoted) capacities into the backend FIRST — snapshots
   are built padded, so capacity must lead the mutation, and a promotion
   invalidates the tenant-aware solver caches (stale-shaped cached
   graphs must not leak into the next solve);
4. apply the events through the backend's elastic mutators
   (``deploy_service`` / ``teardown_service`` / ``scale_replicas`` /
   ``add_node`` / ``drain_node`` — the boundary and chaos wrappers pass
   them through untouched);
5. count everything: ``churn_events_total{kind}``, the ``live_services``
   / ``live_nodes`` vs ``bucket_capacity{axis}`` gauges, and
   ``bucket_promotions_total``.

The engine is deliberately ignorant of jax — it mutates host state and
counts; the controller decides what to re-monitor and re-mask.
"""

from __future__ import annotations

from typing import Mapping

import numpy as np

from kubernetes_rescheduling_tpu.elastic.buckets import ShapeBuckets
from kubernetes_rescheduling_tpu.elastic.events import (
    GRAPH_EVENTS,
    WorkloadView,
    make_profile,
)
from kubernetes_rescheduling_tpu.telemetry.registry import get_registry

# the elastic mutator surface a backend must expose (the simulator's;
# chaos/boundary wrappers pass these through via __getattr__)
REQUIRED_MUTATORS = (
    "live_counts",
    "set_capacities",
    "deploy_service",
    "teardown_service",
    "scale_replicas",
    "add_node",
    "drain_node",
    "alive_node_names",
)


class ChurnEngine:
    """One profile's churn stream against one backend.

    ``buckets`` may be shared across engines (fleet mode: every tenant
    must stay stackable, so one promotion promotes the whole fleet —
    ``capacity_sinks`` lists every backend whose capacities follow the
    shared buckets)."""

    def __init__(
        self,
        profile: str,
        seed: int = 0,
        *,
        bucket_floor: int = 8,
        buckets: ShapeBuckets | None = None,
        capacity_sinks: list | None = None,
        registry=None,
    ) -> None:
        self.profile_name = profile
        self.profile = make_profile(profile)
        self.seed = seed
        self._rng = np.random.default_rng(seed)
        self.buckets = buckets if buckets is not None else ShapeBuckets(floor=bucket_floor)
        self.capacity_sinks = capacity_sinks if capacity_sinks is not None else []
        self.registry = registry
        self.horizon = 0
        self.backend = None
        self._base_replicas: dict[str, int] = {}
        self.events_log: list[dict] = []
        self.events_applied = 0
        # per-step outcome flags the controller reads after step()
        self.graph_changed = False
        self.promoted = False

    # ---- wiring ----

    def _reg(self):
        return self.registry if self.registry is not None else get_registry()

    def bind(self, backend, max_rounds: int, *, registry=None) -> None:
        """Attach to a backend: verify the mutator surface, size the
        initial buckets from the live counts (initial sizing is a
        compile, not a promotion), and push capacities so even round 1's
        snapshot is bucket-padded."""
        missing = [m for m in REQUIRED_MUTATORS if not hasattr(backend, m)]
        if missing:
            raise TypeError(
                f"backend {type(getattr(backend, 'raw_backend', backend)).__name__} "
                f"cannot absorb churn: missing elastic mutators {missing} "
                "(churn injection requires the hermetic simulator)"
            )
        if registry is not None:
            self.registry = registry
        self.backend = backend
        self.horizon = max(int(max_rounds), 1)
        live = backend.live_counts()
        self.buckets.fit(**live)
        self._push_capacities()
        wm = backend.workmodel
        self._base_replicas = {s.name: max(1, s.replicas) for s in wm.services}
        # the autoscale profile consumes the load generator's per-service
        # request-rate series — built over the bind-time workmodel from
        # the backend's OWN load model so offered load and autoscaling
        # agree on which services are hot
        if getattr(self.profile, "rates", "absent") is None:
            from kubernetes_rescheduling_tpu.bench.loadgen import (
                service_rate_series,
            )

            load = getattr(backend, "load", None)
            self.profile.rates = service_rate_series(
                wm,
                entry_rps=getattr(load, "entry_rps", 100.0),
                fanout_frac=getattr(load, "fanout_frac", 1.0),
                entry_service=getattr(load, "entry_service", "s0"),
                amplitude=getattr(self.profile, "amplitude", 2.0),
                seed=self.seed,
            )
        self._publish_gauges(live)

    def _push_capacities(self) -> None:
        sinks = self.capacity_sinks or [self.backend]
        for sink in sinks:
            sink.set_capacities(
                node=self.buckets.nodes,
                pod=self.buckets.pods,
                service=self.buckets.services,
            )

    # ---- per-round step ----

    def _view(self) -> WorkloadView:
        backend = self.backend
        wm = backend.workmodel
        alive = set(backend.alive_node_names())
        nodes = tuple(backend.node_names)
        return WorkloadView(
            services=tuple(wm.names),
            replicas={s.name: max(1, s.replicas) for s in wm.services},
            base_replicas=dict(self._base_replicas),
            nodes=nodes,
            alive=tuple(n in alive for n in nodes),
        )

    def _count_delta(self, events, view: WorkloadView) -> dict:
        """Post-event live counts, computed BEFORE mutation so bucket
        promotion (and the capacity push) precedes the first oversized
        snapshot."""
        services = dict(view.replicas)
        nodes = set(view.nodes)
        for ev in events:
            k = ev.kind
            if k == "service_deploy":
                services[ev.spec.name] = max(1, ev.spec.replicas)
            elif k == "service_teardown":
                services.pop(ev.service, None)
            elif k == "replica_scale":
                if ev.service in services:
                    services[ev.service] = max(1, ev.replicas)
            elif k == "node_add":
                nodes.add(ev.node)
        return {
            "services": len(services),
            "nodes": len(nodes),
            "pods": sum(services.values()),
        }

    def _apply(self, ev) -> None:
        backend = self.backend
        k = ev.kind
        if k == "service_deploy":
            backend.deploy_service(ev.spec)
        elif k == "service_teardown":
            backend.teardown_service(ev.service)
        elif k == "replica_scale":
            backend.scale_replicas(ev.service, ev.replicas)
        elif k == "node_drain":
            backend.drain_node(ev.node)
        elif k == "node_add":
            backend.add_node(ev.node)
        elif k == "spot_preemption":
            for node in ev.nodes:
                backend.drain_node(node)
        else:  # pragma: no cover - the event union is closed
            raise ValueError(f"unknown churn event kind {k!r}")

    def step(self, rnd: int) -> list[dict]:
        """Generate and apply this round's events. Returns their dicts
        (also appended to ``events_log`` and counted). Sets
        ``graph_changed`` / ``promoted`` for the controller to react."""
        if self.backend is None:
            raise RuntimeError("ChurnEngine.step before bind()")
        view = self._view()
        events = self.profile.events(self._rng, rnd, self.horizon, view)
        self.graph_changed = any(ev.kind in GRAPH_EVENTS for ev in events)
        self.promoted = False
        if not events:
            return []
        post = self._count_delta(events, view)
        if self.buckets.fit(**post):
            self.promoted = True
            self._reg().counter(
                "bucket_promotions_total",
                "shape-bucket promotions (live counts outgrew a capacity "
                "bucket — the only legal churn retrace)",
            ).inc()
            # stale-shaped cached solver structures (sparse graph, pod
            # graph) must not survive a promotion; within a bucket the
            # caches' own identity keys handle value churn
            caches = getattr(self.backend, "_solver_caches", None)
            if isinstance(caches, dict):
                caches.clear()
        self._push_capacities()
        reg = self._reg()
        dicts = []
        for ev in events:
            self._apply(ev)
            d = ev.as_dict()
            d["round"] = rnd
            dicts.append(d)
            reg.counter(
                "churn_events_total",
                "churn events applied to the cluster, by kind",
                labelnames=("kind",),
            ).labels(kind=ev.kind).inc()
        self.events_applied += len(dicts)
        self.events_log.extend(dicts)
        # the whole wave reconciles as ONE clock advance (kubelets work
        # in parallel — the sim's apply_pod_moves rule): a busy autoscale
        # round costs one reconcile delay, not events × delay, so the
        # harness's clock-driven load segments stay comparable to static
        # cells
        advance = getattr(self.backend, "advance", None)
        if advance is not None:
            advance(float(getattr(self.backend, "reconcile_delay_s", 0.0)))
        self._publish_gauges(self.backend.live_counts())
        return dicts

    def _publish_gauges(self, live: Mapping[str, int]) -> None:
        reg = self._reg()
        reg.gauge(
            "live_services", "live (non-padding) services in the cluster"
        ).set(live["services"])
        reg.gauge(
            "live_nodes", "alive schedulable nodes in the cluster"
        ).set(len(self.backend.alive_node_names()))
        cap = reg.gauge(
            "bucket_capacity",
            "current shape-bucket capacity per padded axis",
            labelnames=("axis",),
        )
        for axis, value in (
            ("services", self.buckets.services),
            ("nodes", self.buckets.nodes),
            ("pods", self.buckets.pods),
        ):
            cap.labels(axis=axis).set(value)

    # ---- record plumbing ----

    def round_info(self, events: list[dict]) -> dict:
        """The ``RoundRecord.churn`` payload for one executed round."""
        live = self.backend.live_counts()
        return {
            "events": events,
            "live_services": live["services"],
            "live_nodes": len(self.backend.alive_node_names()),
            "live_pods": live["pods"],
            "bucket": self.buckets.as_dict(),
            "promotions": self.buckets.promotions,
        }


def make_fleet_churn(
    fleet,
    elastic,
    *,
    registry=None,
) -> dict[int, ChurnEngine]:
    """Per-tenant churn engines over ONE shared :class:`ShapeBuckets`.

    Fleet tenants must stay stackable (``solver.fleet.stack_tenants``
    requires identical shapes), so every engine pushes the shared
    buckets' capacities into EVERY tenant backend — churn on tenant 0
    that promotes a bucket re-pads the whole fleet (one retrace), while
    the untouched tenants' decisions stay bit-identical (the mask-twin
    invariant). ``elastic.tenants`` selects which tenant indices churn
    (empty = all), each seeded ``elastic.seed + index`` so streams stay
    independent — the chaos convention.
    """
    elastic = elastic.validate()
    if elastic.profile == "none":
        return {}
    hit = set(elastic.tenants) or set(range(fleet.num_tenants))
    for t in hit:
        if t >= fleet.num_tenants:
            raise ValueError(
                f"elastic tenant {t} out of range for {fleet.num_tenants} tenants"
            )
    shared = ShapeBuckets(floor=elastic.bucket_floor)
    sinks = list(fleet.backends)
    return {
        t: ChurnEngine(
            elastic.profile,
            seed=elastic.seed + t,
            buckets=shared,
            capacity_sinks=sinks,
            registry=registry,
        )
        for t in sorted(hit)
    }
