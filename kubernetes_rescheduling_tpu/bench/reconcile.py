"""Intent reconciliation: the controller closes the loop on its actions.

Until now the control loop trusted that a delete/re-create landed where
it was aimed and that nothing else ever moved a pod: the boundary's
``landed`` return was recorded and never checked against reality, so a
lost move, a scheduler override, or another actor's write (a second
scheduler, a human ``kubectl``, a descheduler) stayed invisible forever.
This module is the **intent ledger** that ends that:

- after each round's applies the controller records where every pod
  SHOULD be (:meth:`IntentLedger.record_moves` — the requested target,
  plus what the boundary CLAIMED happened);
- at the next admitted snapshot the ledger diffs observed vs intended
  (:meth:`IntentLedger.observe`) and classifies each divergence:

  ========================  =====================================================
  ``wrong_node``            a PINNING move landed where the boundary said —
                            which was not where the controller aimed (a race;
                            the chaos ``move_wrong_node`` fault). Advisory
                            moves (``affinityOnly``) record the landed node as
                            intent at apply time AND adopt the observed node
                            at the next diff (a backend may only echo the
                            advisory target — the live scheduler's pick shows
                            at the next monitor): a scheduler override is
                            legitimate placement, never charged or repaired
  ``lost_move``             the boundary reported success but the pod still sits
                            on its old node (the chaos ``move_lost`` fault — the
                            classic acknowledged-but-lost write)
  ``external_drift``        a pod moved with no move of ours in flight (the
                            chaos ``external_drift`` fault; any other actor)
  ``phantom_pod``           a pod present in the snapshot that no intent — and
                            no churn event — explains (debounced: two
                            consecutive sightings, so a lagging watch cache
                            blip never counts)
  ``missing_pod``           an intended pod absent from the snapshot with no
                            churn/node event explaining it (same debounce)
  ``unknown_landing``       a move landed on a node the working snapshot does
                            not even know (counted at apply time by the greedy
                            round — see ``bench/controller.py``)
  ========================  =====================================================

  Churn events (PR 7's ``RoundRecord.churn``) are consumed FIRST:
  deploys/teardowns/autoscales and node drain/add re-anchor the affected
  intent instead of reading as drift, and a pod whose intended node died
  (chaos node flap) is consumed as a node event, never charged.

- divergences queue **rate-limited corrective moves**
  (:meth:`IntentLedger.issue_repairs` — pod-granular ``MoveRequest``s,
  or Deployment-scoped ones on a backend that cannot pin one replica
  (``supports_pod_moves = False``, the k8s mechanism),
  through the normal boundary retry/breaker/budget machinery, at most
  ``reconcile.repair_budget_per_round`` per round) until observed state
  converges back to intent. The pending-repair count is the
  ``reconcile_drift_pods`` gauge and the ``reconcile_divergence``
  watchdog rule's input.

The ledger is host-side (no jitted compute; snapshot fields come home
in one batched ``device_get`` per diff) and persists through checkpoints
(:meth:`snapshot` / :meth:`restore`): a resumed controller reconciles
its restored intent against the first admitted snapshot instead of
trusting it blindly — whatever moved while the controller was down is a
counted, repairable divergence.
"""

from __future__ import annotations

from collections import deque

import jax
import numpy as np

from kubernetes_rescheduling_tpu.backends.base import MoveRequest
from kubernetes_rescheduling_tpu.telemetry.registry import get_registry

KIND_WRONG_NODE = "wrong_node"
KIND_LOST_MOVE = "lost_move"
KIND_EXTERNAL_DRIFT = "external_drift"
KIND_PHANTOM_POD = "phantom_pod"
KIND_MISSING_POD = "missing_pod"
KIND_UNKNOWN_LANDING = "unknown_landing"

# sightings before a phantom/missing pod is charged: one absent-then-back
# snapshot is a lagging watch cache (the chaos `monitor_partial` fault),
# not a divergence
_DEBOUNCE = 2


def count_divergence(registry, kind: str) -> None:
    """THE ``reconcile_divergences_total`` declaration — the ledger and
    the greedy round's unknown-landing patch share it so the family can
    never fork."""
    reg = registry if registry is not None else get_registry()
    reg.counter(
        "reconcile_divergences_total",
        "intent-vs-observed divergences detected by the reconciliation "
        "plane, by kind",
        labelnames=("kind",),
    ).labels(kind=kind).inc()


def move_intent(
    mechanism: str,
    service: str,
    requested: str,
    landed: str | None,
    *,
    pod: str | None = None,
) -> tuple:
    """THE intent-capture rule for an applied move — both control loops
    build their ledger entries through it so the advisory contract can
    never drift between planes: under the advisory mechanism
    (``affinityOnly``) the scheduler's choice IS legitimate placement —
    intent adopts where the move landed, and the advisory flag makes the
    ledger adopt the OBSERVED node at the next diff too (a backend may
    only echo the advisory target at apply time); pinning mechanisms
    keep the requested target so an override reads as a ``wrong_node``
    divergence."""
    advisory = mechanism == "affinityOnly"
    intended = landed if advisory and landed is not None else requested
    return (service, pod, intended, landed, advisory)


class IntentLedger:
    """Per-pod intended placement + divergence classification + repairs.

    One ledger per control loop (``tenant=None``) or per fleet tenant
    (``tenant=<name>`` — the drift gauge then lands on the tenant-labeled
    ``fleet_reconcile_drift_pods`` family, mirroring the fleet's other
    per-tenant gauges; the divergence/repair counters are shared families
    like ``chaos_faults_total``).
    """

    def __init__(
        self, cfg, *, registry=None, logger=None, tenant=None,
        adopt_observed=False, tenant_series=None,
    ):
        self.cfg = cfg
        self.registry = registry
        self.logger = logger
        self.tenant = tenant
        # the budget-gated gateway for the per-tenant drift gauge
        # (telemetry.fleet_rollup.TenantSeries — the only legal way to
        # register a tenant label key); the fleet loop injects its
        # budget-aware instance, a bare fleet ledger gets an ungated one
        self.tenant_series = tenant_series
        # advisory-backend mode (the shadow plane's replay backend): the
        # snapshot stream IS ground truth — the recorded cluster's own
        # scheduler moving pods is the baseline under study, not another
        # actor drifting state. Every diff ADOPTS the observed placement
        # (advisory intents resolve exactly as PR 10's affinityOnly rule)
        # and no divergence is charged or repaired: charging the real
        # scheduler as external_drift — and issuing "corrective" moves
        # that the replay backend would dutifully record as shadow
        # recommendations — would poison both the divergence metrics and
        # the shadow ledger.
        self.adopt_observed = adopt_observed
        self.intent: dict[str, str | None] = {}  # pod name -> node name
        self.pod_service: dict[str, str] = {}
        # moves since the last observe: pod -> {service, requested,
        # landed, old} (what the boundary claimed, for classification)
        self.moves: dict[str, dict] = {}
        # pending corrective moves: pod -> {service, target, kind}
        self.repairs: dict[str, dict] = {}
        # churn events noted but not yet consumed by an observe(): a
        # degraded round has no admitted snapshot to diff, so its events
        # must SURVIVE here until the next fresh diff — otherwise a
        # legitimate teardown applied on a degraded round would read as
        # missing_pod divergences two rounds later
        self.pending_events: list[dict] = []
        self._phantom_streak: dict[str, int] = {}
        self._missing_streak: dict[str, int] = {}
        self._primed = False
        # recently diffed snapshot OBJECTS (identity ring): observe()
        # skips any of them — a fresh monitor always builds a new
        # object, so an already-seen one is a stale re-serve, not a new
        # read. A ring, not one slot: the chaos stale fault can re-serve
        # a snapshot from SEVERAL reads back when corrupt/partial rounds
        # sat in between (those aren't cached by the wrapper). Bounded,
        # and snapshots are small, so the held refs are negligible.
        self._recent_states: deque = deque(maxlen=8)

    # ---- bookkeeping ----

    def _reg(self):
        return self.registry if self.registry is not None else get_registry()

    def _set_gauge(self) -> None:
        reg = self._reg()
        if self.tenant is None:
            reg.gauge(
                "reconcile_drift_pods",
                "pods whose observed placement currently diverges from "
                "the controller's intent (corrective moves pending)",
            ).set(len(self.repairs))
        else:
            series = self.tenant_series
            if series is None:
                from kubernetes_rescheduling_tpu.telemetry.fleet_rollup import (
                    TenantSeries,
                )

                # ungated (budget=None): the historical always-publish
                # behavior for ledgers built outside the fleet loop —
                # built per call, NOT cached, so it follows _reg()'s
                # per-call registry resolution (set_registry swaps must
                # keep reaching the live registry)
                series = TenantSeries(reg, tenants=1, budget=None)
            series.gauge_set(
                "fleet_reconcile_drift_pods",
                "per-tenant pods whose observed placement currently "
                "diverges from that tenant's intent",
                self.tenant,
                len(self.repairs),
            )

    @property
    def pending_repairs(self) -> bool:
        return bool(self.repairs)

    @property
    def drift_pods(self) -> int:
        return len(self.repairs)

    # ---- persistence (checkpoint extra) ----

    def snapshot(self) -> dict:
        """JSON-able intent for the checkpoint sidecar (pending churn
        events included: a checkpoint taken on a degraded round must not
        lose the events its next observe owes a consume)."""
        return {
            "intent": dict(self.intent),
            "pod_service": dict(self.pod_service),
            "pending_events": [dict(e) for e in self.pending_events],
        }

    def restore(self, snap: dict | None) -> None:
        """Adopt a checkpointed intent: the next :meth:`observe` then
        reconciles the resumed cluster against it instead of trusting
        the first snapshot blindly."""
        if not snap or not snap.get("intent"):
            return
        self.intent = dict(snap["intent"])
        self.pod_service = dict(snap.get("pod_service") or {})
        self.pending_events = [
            dict(e) for e in snap.get("pending_events") or []
        ]
        self._primed = True

    # ---- intent sources ----

    @staticmethod
    def _observed(state, service_names, arrays=None) -> tuple[dict, dict]:
        """``pod name -> node name (None = unscheduled)`` plus the pod's
        service name, from one admitted snapshot. ``arrays`` lets a
        caller that already pulled ``(pod_valid, pod_node, pod_service)``
        hand them over; otherwise they come home in one batched
        ``device_get`` (never per-field pulls in the hot monitor path)."""
        obs: dict[str, str | None] = {}
        svc_of: dict[str, str] = {}
        valid, nodes, svcs = (
            arrays
            if arrays is not None
            else jax.device_get(
                (state.pod_valid, state.pod_node, state.pod_service)
            )
        )
        pod_names = state.pod_names
        node_names = state.node_names
        n_pod = len(pod_names)
        n_node = len(node_names)
        n_svc = len(service_names)
        vidx = np.flatnonzero(valid)
        # bulk tolist() beats per-element numpy scalar indexing by ~an
        # order of magnitude — this runs once per fresh round over every
        # valid pod, in the foreground close path
        for i, n, s in zip(
            vidx.tolist(),
            np.asarray(nodes)[vidx].tolist(),
            np.asarray(svcs)[vidx].tolist(),
        ):
            if i >= n_pod:
                continue
            name = pod_names[i]
            obs[name] = node_names[n] if 0 <= n < n_node else None
            if 0 <= s < n_svc:
                svc_of[name] = service_names[s]
        return obs, svc_of

    def rebase(self, state, *, service_names=()) -> None:
        """Intent := observed (startup baseline, or a wholesale
        re-anchor)."""
        self.intent, self.pod_service = self._observed(state, service_names)
        self.moves.clear()
        self.repairs.clear()
        self.pending_events.clear()
        self._phantom_streak.clear()
        self._missing_streak.clear()
        self._primed = True
        self._recent_states.append(state)
        self._set_gauge()

    def note_churn(self, events) -> None:
        """Queue churn events for the NEXT observe — the loops call this
        every round, whether or not the round produced an admitted
        snapshot, so events applied on a degraded round survive until
        there is a diff that can consume them."""
        self.pending_events.extend(events)

    def record_moves(self, intents) -> None:
        """One entry per boundary move this round:
        ``(service, pod | None, requested_node, landed_node[, advisory])``
        — ``pod=None`` means the whole Deployment moved (the service-unit
        mechanisms), a name means one replica (pod mode / repairs). A
        failed move (``landed is None``) changes no intent. ``advisory``
        marks an ``affinityOnly`` move whose true landing the backend
        could NOT report at apply time (k8s returns the advisory target —
        the live scheduler's pick is only observable at the next
        monitor): the next :meth:`observe` adopts wherever the pod sits
        instead of charging a scheduler override as drift."""
        for entry in intents:
            service, pod, requested, landed = entry[:4]
            advisory = bool(entry[4]) if len(entry) > 4 else False
            if landed is None:
                continue
            pods = (
                [pod]
                if pod is not None
                else [
                    p
                    for p, s in self.pod_service.items()
                    if s == service
                ]
            )
            for p in pods:
                self.moves[p] = {
                    "service": service,
                    "requested": requested,
                    "landed": landed,
                    "old": self.intent.get(p),
                    "advisory": advisory,
                }
                self.intent[p] = requested
                # an explicit move supersedes any queued repair
                self.repairs.pop(p, None)
        self._set_gauge()

    # ---- the reconcile diff ----

    def observe(
        self, state, *, service_names=(), churn_events=(), host_arrays=None
    ) -> dict:
        """Diff one admitted snapshot against intent: classify + count
        divergences, queue corrective moves, return the round's
        ``reconcile`` payload piece (``{"divergences": [...]}``).

        Churn events come from ``churn_events`` plus anything queued via
        :meth:`note_churn` (consumed here either way). ``host_arrays``
        lets the admission guard hand over the snapshot fields it already
        pulled for THIS state object (``AdmissionGuard.host_arrays``) so
        the hot monitor path pays one device->host transfer, not two."""
        if not self._primed:
            self.rebase(state, service_names=service_names)
            return {"divergences": []}
        if any(s is state for s in self._recent_states):
            # an already-diffed snapshot OBJECT: a stale monitor
            # re-serving an earlier read (the chaos monitor_stale fault
            # returns its cached state — possibly from several reads
            # back) carries no new observation — re-diffing it would
            # misread every in-flight move as lost (the pre-move
            # placement shows again) and rewind confirmed moves into
            # phantom drift. Moves and pending churn stay queued for
            # the next genuinely fresh diff. (A live API serving stale
            # DATA in a fresh object is undetectable here by
            # construction — that is what the debounce and the repair
            # loop's convergence absorb.)
            return {"divergences": []}

        if self.adopt_observed:
            # advisory backend: observed IS intent (see __init__) — one
            # wholesale rebase, no classification, no repairs
            self.rebase(state, service_names=service_names)
            return {"divergences": []}

        if host_arrays is not None:
            pv = host_arrays["pod_valid"]
            pn = host_arrays["pod_node"]
            ps = host_arrays["pod_service"]
            node_valid = host_arrays["node_valid"]
        else:
            pv, pn, ps, node_valid = jax.device_get(
                (
                    state.pod_valid,
                    state.pod_node,
                    state.pod_service,
                    state.node_valid,
                )
            )
        obs, svc_of = self._observed(state, service_names, arrays=(pv, pn, ps))
        events = (*self.pending_events, *churn_events)
        self.pending_events = []
        ev_services: set[str] = set()
        ev_nodes: set[str] = set()
        for ev in events:
            kind = ev.get("kind")
            if kind in ("service_deploy", "service_teardown", "replica_scale"):
                if ev.get("service"):
                    ev_services.add(ev["service"])
            elif kind in ("node_drain", "node_add"):
                if ev.get("node"):
                    ev_nodes.add(ev["node"])
            elif kind == "spot_preemption":
                ev_nodes.update(ev.get("nodes") or ())

        known_nodes = set(state.node_names)
        alive = {
            state.node_names[int(i)]
            for i in np.flatnonzero(node_valid)
            if int(i) < len(state.node_names)
        }

        moves, self.moves = self.moves, {}
        divergences: list[dict] = []

        def diverge(kind: str, pod: str, expected, observed) -> None:
            d = {
                "kind": kind,
                "pod": pod,
                "service": self.pod_service.get(pod) or svc_of.get(pod),
                "expected": expected,
                "observed": observed,
            }
            divergences.append(d)
            count_divergence(self.registry, kind)
            if self.logger is not None:
                self.logger.warn("reconcile_divergence", tenant=self.tenant, **d)

        for pod, expected in list(self.intent.items()):
            service = self.pod_service.get(pod)
            if pod not in obs:
                # gone from the snapshot: legitimate teardown/scale-down
                # (churn events) and node events consume; a lagging watch
                # cache gets one round of grace (debounce); anything left
                # is a missing pod — counted once, then re-anchored
                if service in ev_services or (expected in ev_nodes):
                    self._drop(pod)
                    continue
                streak = self._missing_streak.get(pod, 0) + 1
                if streak < _DEBOUNCE:
                    self._missing_streak[pod] = streak
                    if pod in moves:
                        # the deferred diff still needs this move's meta
                        # (advisory flag, true old node): without it a
                        # debounced pod's scheduler override would read
                        # as external_drift, and a lost pinning move as
                        # drift instead of lost_move
                        self.moves[pod] = moves[pod]
                    continue
                diverge(KIND_MISSING_POD, pod, expected, None)
                self._drop(pod)
                continue
            self._missing_streak.pop(pod, None)
            observed = obs[pod]
            if observed == expected:
                self.repairs.pop(pod, None)  # converged (repair landed)
                continue
            meta = moves.get(pod)
            if meta is not None and meta.get("advisory"):
                # advisory mechanism: this monitor is the FIRST time the
                # live scheduler's pick is observable (the backend's
                # apply_move could only echo the advisory target) — the
                # pick is legitimate placement, adopted, never charged
                # or repaired
                self.intent[pod] = observed
                self.repairs.pop(pod, None)
                continue
            if observed is None:
                if expected is None or expected not in alive:
                    # evicted by a node death the snapshot itself shows —
                    # consumed, adopt the unscheduled state as intent
                    self.intent[pod] = None
                    self.repairs.pop(pod, None)
                    continue
                kind = KIND_EXTERNAL_DRIFT  # unscheduled under a live node
            elif (
                meta is not None
                and observed == meta.get("landed")
                and meta.get("landed") != meta.get("requested")
            ):
                kind = KIND_WRONG_NODE
            elif meta is not None and observed == meta.get("old"):
                kind = KIND_LOST_MOVE
            elif expected not in known_nodes or expected not in alive:
                # the intended node left the cluster (or died) and the
                # scheduler re-placed the pod — a node event, not drift
                self.intent[pod] = observed
                self.repairs.pop(pod, None)
                continue
            elif service in ev_services or observed in ev_nodes:
                # churn re-placed it (deploy wave / drain rescheduling)
                self.intent[pod] = observed
                self.repairs.pop(pod, None)
                continue
            else:
                kind = KIND_EXTERNAL_DRIFT
            rep = self.repairs.get(pod)
            if (
                rep is not None
                and observed == rep.get("from")
                and expected == rep.get("target")
            ):
                # the SAME divergence, already counted, still awaiting
                # repair budget (or running detect-and-count-only) — one
                # fault, one count, and the queued repair keeps the kind
                # it was classified with (by now the in-flight move meta
                # is gone, so re-classifying here would mislabel it
                # external_drift)
                continue
            diverge(kind, pod, expected, observed)
            svc = service or svc_of.get(pod)
            # a repair needs a live target and a resolvable service name
            # (the boundary's MoveRequest is service-scoped even for a
            # single replica); anything else stays detect-and-count
            if expected is not None and expected in alive and svc:
                self.repairs[pod] = {
                    "service": svc,
                    "pod": pod,
                    "target": expected,
                    "kind": kind,
                    # where the pod actually sits — the repair move's true
                    # "old" (intent already equals the target, so without
                    # this a LOST repair would re-classify as
                    # external_drift instead of lost_move on every retry)
                    "from": observed,
                }

        for pod, observed in obs.items():
            if pod in self.intent:
                continue
            service = svc_of.get(pod)
            if service in ev_services or (observed in ev_nodes):
                self._adopt(pod, observed, service)
                continue
            streak = self._phantom_streak.get(pod, 0) + 1
            if streak < _DEBOUNCE:
                self._phantom_streak[pod] = streak
                continue
            diverge(KIND_PHANTOM_POD, pod, None, observed)
            self._adopt(pod, observed, service)

        # streaks only survive while their condition persists
        self._phantom_streak = {
            p: s for p, s in self._phantom_streak.items()
            if p in obs and p not in self.intent
        }
        self._missing_streak = {
            p: s for p, s in self._missing_streak.items() if p not in obs
        }
        self._recent_states.append(state)
        self._set_gauge()
        return {"divergences": divergences}

    def _drop(self, pod: str) -> None:
        self.intent.pop(pod, None)
        self.pod_service.pop(pod, None)
        self.repairs.pop(pod, None)
        self._missing_streak.pop(pod, None)

    def _adopt(self, pod: str, node, service) -> None:
        self.intent[pod] = node
        if service is not None:
            self.pod_service[pod] = service
        self._phantom_streak.pop(pod, None)

    # ---- corrective moves ----

    def issue_repairs(self, boundary, budget: int) -> list[dict]:
        """Issue up to ``budget`` corrective moves through the boundary
        (retry/breaker/failure budget all apply — a repair is a move
        like any other): pod-granular where the backend supports it,
        Deployment-scoped where it cannot pin one replica. Issued repairs leave the queue and are
        re-recorded as intent, so the next :meth:`observe` either sees
        convergence or re-detects and re-queues; a boundary-failed repair
        re-queues immediately. ``budget == 0`` disables repairs (detect
        and count only). Returns the issued repair dicts (with their
        ``landed`` outcome) for the round record."""
        if budget <= 0 or not self.repairs:
            return []
        # the k8s Deployment mechanism cannot pin ONE replica (its
        # backend raises for pod-granular moves — a deleted replica is
        # re-created unpinned by its ReplicaSet); such backends run
        # service-unit placement, so every pod of a service shares the
        # intent node and a Deployment-wide pin IS the corrective move
        pod_scoped = getattr(
            getattr(boundary, "raw_backend", None), "supports_pod_moves", True
        )
        issued: list[dict] = []
        for pod in list(self.repairs):
            if len(issued) >= budget:
                break
            # a service-scoped repair's record_moves pops sibling repairs
            rep = self.repairs.pop(pod, None)
            if rep is None:
                continue
            landed = boundary.apply_move(
                MoveRequest(
                    service=rep["service"] or "",
                    pod=pod if pod_scoped else None,
                    target_node=rep["target"],
                    # a corrective move PINS: the whole point is landing
                    # exactly where the intent says
                    mechanism="nodeName",
                )
            )
            out = {**rep, "landed": landed}
            issued.append(out)
            if landed is not None:
                # counted only when the move actually went out: a frozen
                # boundary returning None re-queues the SAME repair — one
                # convergence-comparable count, not one per retry round
                self._reg().counter(
                    "reconcile_repair_moves_total",
                    "corrective moves applied by the reconciliation "
                    "plane to converge observed placement back to "
                    "intent, by the divergence kind they repair",
                    labelnames=("kind",),
                ).labels(kind=rep["kind"]).inc()
                self.record_moves(
                    [
                        (
                            rep["service"],
                            pod if pod_scoped else None,
                            rep["target"],
                            landed,
                        )
                    ]
                )
                if rep.get("from") is not None and pod in self.moves:
                    # record_moves captured old=intent (== the repair
                    # target); the classifying diff needs the node the
                    # pod REALLY came from, so a swallowed repair reads
                    # as the lost_move it is
                    self.moves[pod]["old"] = rep["from"]
            else:
                # boundary failure (or frozen moves): keep the debt
                self.repairs[pod] = rep
            if self.logger is not None:
                self.logger.info(
                    "reconcile_repair", tenant=self.tenant, **out
                )
        self._set_gauge()
        return issued


def reconcile_round_block(
    guard,
    ledger,
    *,
    state,
    service_names,
    churn_events,
    fresh: bool,
    last_drift: int,
    boundary,
    repair_budget: int,
) -> tuple[dict | None, int]:
    """One round of the reconciliation plane — THE implementation both
    the solo and the fleet loop call (one copy, so the contracts below
    can never drift between planes):

    - the admission guard's per-round counts always ride the block;
    - churn events are NOTED every round — a degraded round
      (``fresh=False``) has no admitted snapshot to diff, so its events
      wait in the ledger until the next fresh observe consumes them
      (legitimate churn never reads as phantom/missing divergences);
    - a fresh round diffs observed vs intent (reusing the guard's
      already-pulled host arrays — no second transfer) and issues
      rate-limited repairs through the boundary;
    - the round drift RESOLVED on still carries an explicit
      ``drift_pods=0``: the watchdog's ``reconcile_divergence`` rule
      judges the latest round with reconcile data, so the recovery must
      be visible, not silent.

    Returns ``(record.reconcile payload | None, new last_drift)``.
    """
    block: dict = {}
    if guard is not None:
        adm = guard.take_info()
        if adm:
            block["admission"] = adm
    drift = last_drift
    if ledger is not None:
        ledger.note_churn(churn_events)
        if fresh:
            diff = ledger.observe(
                state,
                service_names=service_names,
                host_arrays=(
                    guard.host_arrays(state) if guard is not None else None
                ),
            )
            if diff["divergences"]:
                block["divergences"] = diff["divergences"]
            repairs = ledger.issue_repairs(boundary, repair_budget)
            if repairs:
                block["repairs"] = repairs
        drift = ledger.drift_pods
        if block or drift or last_drift:
            block["drift_pods"] = drift
    return (block or None), drift
