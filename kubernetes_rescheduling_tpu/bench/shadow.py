"""Shadow plane: score our recommendations against the real scheduler.

In shadow mode (``config.shadow`` / ``--shadow``) the normal decide
kernels run on each admitted REAL snapshot (a replayed trace window) and
their moves land in a shadow ledger instead of the cluster
(``backends.replay``). This module is the scoring half: a device-side
**counterfactual twin** — the admitted snapshot's loads/capacities with
``pod_node`` replaced by OUR cumulative placement (the trace's recorded
placement plus every recommendation issued so far) — evaluated by the
SAME compiled ``controller_round_end`` kernel the round already
dispatches, with the result riding the round's ONE ``round_end``
transfer (the PR-9 discipline: shadow scoring adds a device piece to the
existing ``RoundCloser``, never a second pull).

Per scored round the record grows a ``shadow`` block: comm-cost/load-std
for the actual and counterfactual placements, the delta, the running
win-rate, and — when attribution is on — the twin's full attribution
record (sum-consistent by construction: the same kernel that makes the
actual attribution consistent) plus per-edge deltas naming WHERE we beat
the real scheduler. Gauges ``shadow_win_rate``/``shadow_cost_delta`` and
the per-outcome ``shadow_rounds_total`` counter publish the head-to-head
live; the watchdog's ``shadow_win_rate`` rule (``ObsConfig.
slo_shadow_min_win_rate``) makes a losing shadow run a visible SLO.

Host-side identity is name-keyed (pods shift index between windows);
host arrays come from the admission guard's already-pulled copies — the
plane pays no device→host transfer of its own.
"""

from __future__ import annotations

import numpy as np

from kubernetes_rescheduling_tpu.bench.round_end import (
    METRIC_COST,
    METRIC_HEAD,
    METRIC_LOAD_STD,
    dispatch_round_end,
    fence,
)
from kubernetes_rescheduling_tpu.core.state import UNASSIGNED
from kubernetes_rescheduling_tpu.elastic.buckets import device_graph, device_view
from kubernetes_rescheduling_tpu.telemetry import attribution as attribution_mod
from kubernetes_rescheduling_tpu.telemetry.registry import get_registry

# edges reported in the per-round delta table (where we beat / lose)
_DELTA_EDGES = 8


class ShadowPlane:
    """Counterfactual twin + head-to-head accounting (one per run)."""

    def __init__(self, cfg, *, registry=None, logger=None) -> None:
        self.cfg = cfg
        self.registry = registry
        self.logger = logger
        # OUR cumulative placement: pod name -> node name (None =
        # unscheduled). Pods the controller never moved track the
        # observed (recorded) placement — the honest counterfactual:
        # only our recommendations diverge from reality. ``_owned`` is
        # the set of pod names a recommendation ever re-homed; only
        # those keep our node through realignment (a recorded scheduler
        # reshuffling pods we never touched happens in our world too).
        self.twin: dict[str, str | None] = {}
        self._owned: set[str] = set()
        self.wins = 0
        self.scored = 0
        self.ledger: list[dict] = []  # per-round shadow blocks, in order
        self._svc_index_memo: tuple[tuple, dict] | None = None

    # ---- bookkeeping ----

    def _reg(self):
        return self.registry if self.registry is not None else get_registry()

    def _svc_index(self, graph) -> dict[str, int]:
        memo = self._svc_index_memo
        if memo is None or memo[0] is not graph.names:
            memo = (graph.names, {n: i for i, n in enumerate(graph.names)})
            self._svc_index_memo = memo
        return memo[1]

    @staticmethod
    def _observed(state, arrays) -> dict[str, str | None]:
        """pod name -> node name from one admitted snapshot — THE
        ledger's decode (``IntentLedger._observed``), shared so the
        reconcile plane's and the twin's views of 'observed placement'
        can never drift apart. ``arrays`` is the guard's already-pulled
        host dict (``fence`` fallback for a guard-less caller — one
        batched read, the designated idiom)."""
        from kubernetes_rescheduling_tpu.bench.reconcile import IntentLedger

        if arrays is None:
            arrays = dict(
                zip(
                    ("pod_valid", "pod_node", "pod_service"),
                    fence((state.pod_valid, state.pod_node, state.pod_service)),
                )
            )
        obs, _svc_of = IntentLedger._observed(
            state,
            (),
            arrays=(
                arrays["pod_valid"], arrays["pod_node"], arrays["pod_service"]
            ),
        )
        return obs

    def bind(self, state, graph, arrays=None) -> None:
        """Startup baseline: twin := the first admitted snapshot's
        recorded placement (we diverge only by recommending)."""
        self.twin = self._observed(state, arrays)

    # ---- per-round step ----

    def observe_round(
        self, rnd, record, state, graph, closer, *, arrays, fresh, top_k
    ) -> None:
        """Fold this round's recommendations into the twin and (on fresh
        rounds) defer the counterfactual scoring onto the round closer.

        Called from ``begin_close`` AFTER the actual metrics piece is
        attached: decode order inside the single flush guarantees
        ``record.communication_cost`` is set before the shadow decode
        compares against it.
        """
        svc_index = self._svc_index(graph)
        if arrays is None:
            # guard-less caller: one batched read, the designated idiom
            arrays = dict(
                zip(
                    ("pod_valid", "pod_node", "pod_service", "node_valid"),
                    fence(
                        (
                            state.pod_valid,
                            state.pod_node,
                            state.pod_service,
                            state.node_valid,
                        )
                    ),
                )
            )
        if not fresh:
            # degraded round: no admitted snapshot to realign or score
            # against — recommendations still accumulate on the twin,
            # keyed by the carried snapshot's (unchanged) pod table
            for service, landed in record.applied_moves:
                self._rehome(state, arrays, svc_index, service, landed)
            return

        if not bool(np.asarray(arrays["pod_valid"]).any()):
            # a pods-free window (machine-events-only stretch of a real
            # corpus): both placements cost 0 by vacuity — scoring it
            # would credit a free "win" and inflate shadow_win_rate /
            # the SLO input. Recommendations cannot exist either (no
            # pods to move); skip the round entirely.
            return

        obs = self._observed(state, arrays)
        # realign to this window's pod table: new and never-re-homed
        # pods track the recorded placement (the real scheduler's moves
        # on pods we never touched happen in our world too), vanished
        # pods drop, and only pods a recommendation re-homed keep our
        # node — the counterfactual diverges by OUR moves alone. A
        # recommended node that since DIED in the trace releases
        # ownership: in our world it died too, and the recorded
        # re-placement is the honest stand-in for the rescheduling any
        # scheduler must then perform — scoring pods on a dead node
        # would credit physically infeasible placements.
        nv = arrays.get("node_valid")
        if nv is None:
            nv = fence(state.node_valid)
        alive = {
            state.node_names[i]
            for i in np.flatnonzero(np.asarray(nv)).tolist()
            if i < len(state.node_names)
        }

        def twin_node(name: str, observed_node: str | None) -> str | None:
            if name in self._owned:
                ours = self.twin.get(name, observed_node)
                if ours is None or ours in alive:
                    return ours
                self._owned.discard(name)
            return observed_node

        self.twin = {
            name: twin_node(name, node) for name, node in obs.items()
        }
        for service, landed in record.applied_moves:
            self._rehome(state, arrays, svc_index, service, landed)

        # the counterfactual twin: this snapshot's loads under OUR
        # cumulative placement — same arrays, pod_node swapped
        import jax.numpy as jnp

        pv = np.asarray(arrays["pod_valid"])
        node_index = {n: i for i, n in enumerate(state.node_names)}
        twin_arr = np.array(np.asarray(arrays["pod_node"]))
        pod_names = state.pod_names
        for i in np.flatnonzero(pv).tolist():
            if i >= len(pod_names):
                continue
            target = self.twin.get(pod_names[i])
            ti = node_index.get(target) if target is not None else None
            twin_arr[i] = ti if ti is not None else UNASSIGNED
        twin_state = state.replace(pod_node=jnp.asarray(twin_arr))
        dev = dispatch_round_end(
            device_view(twin_state), device_graph(graph), top_k=top_k
        )
        ctx = {
            "node_names": state.node_names,
            "svc_names": graph.names,
            "num_nodes": state.num_nodes,
            "num_services": graph.num_services,
        }
        closer.defer(dev, lambda flat: self._score(rnd, record, ctx, top_k, flat))

    def _rehome(self, state, arrays, svc_index, service, landed) -> None:
        """Apply one service-unit recommendation to the twin: every
        valid pod of the service moves to the recommended node."""
        si = svc_index.get(service)
        if si is None or arrays is None:
            return
        pv = np.asarray(arrays["pod_valid"])
        ps = np.asarray(arrays["pod_service"])
        pod_names = state.pod_names
        for i in np.flatnonzero(pv & (ps == si)).tolist():
            if i < len(pod_names):
                self.twin[pod_names[i]] = landed
                self._owned.add(pod_names[i])

    # ---- the flush-time decode ----

    def _score(self, rnd, record, ctx, top_k, flat) -> None:
        cost_shadow = float(flat[METRIC_COST])
        lstd_shadow = float(flat[METRIC_LOAD_STD])
        cost_actual = float(record.communication_cost)
        lstd_actual = float(record.load_std)
        delta = cost_actual - cost_shadow
        eps = 1e-6 * max(1.0, abs(cost_actual))
        win = cost_shadow <= cost_actual * (1.0 - self.cfg.win_margin) + eps
        self.scored += 1
        if win:
            self.wins += 1
        win_rate = self.wins / self.scored

        block: dict = {
            "round": rnd,
            "recommended": len(record.applied_moves),
            "cost_actual": cost_actual,
            "cost_shadow": cost_shadow,
            "cost_delta": delta,
            "load_std_actual": lstd_actual,
            "load_std_shadow": lstd_shadow,
            "win": bool(win),
            "wins": self.wins,
            "scored": self.scored,
            "win_rate": win_rate,
        }
        if top_k > 0:
            attr = attribution_mod.decode_attribution(
                flat[METRIC_HEAD:],
                node_names=ctx["node_names"],
                service_names=ctx["svc_names"],
                top_k=top_k,
                num_nodes=ctx["num_nodes"],
                num_services=ctx["num_services"],
            )
            block["attribution"] = attr
            actual_attr = record.attribution
            if isinstance(actual_attr, dict):
                block["edges_delta"] = _edge_deltas(actual_attr, attr)
        record.shadow = block
        self.ledger.append(block)

        reg = self._reg()
        reg.gauge(
            "shadow_win_rate",
            "fraction of scored shadow rounds where the counterfactual "
            "placement's communication cost was at or below the real "
            "scheduler's (running, this run)",
        ).set(win_rate)
        reg.gauge(
            "shadow_cost_delta",
            "actual minus counterfactual communication cost of the most "
            "recent scored shadow round (positive = we beat the real "
            "scheduler)",
        ).set(delta)
        reg.counter(
            "shadow_rounds_total",
            "scored shadow rounds by head-to-head outcome against the "
            "trace's actual scheduler",
            labelnames=("outcome",),
        ).labels(outcome="win" if win else "loss").inc()
        if self.logger is not None:
            self.logger.info(
                "shadow_round",
                round=rnd,
                cost_actual=cost_actual,
                cost_shadow=cost_shadow,
                cost_delta=delta,
                win=bool(win),
                win_rate=win_rate,
            )

def _edge_deltas(actual: dict, shadow: dict) -> list[dict]:
    """Per-service-edge head-to-head: actual minus counterfactual cost
    for every edge either attribution recorded, best-for-us first. Only
    edges in a top-k are visible — the tail is already carried in each
    attribution's sum-consistent ``tail``."""

    def by_pair(attr: dict) -> dict[tuple[str, str], float]:
        out: dict[tuple[str, str], float] = {}
        for e in attr.get("edges") or ():
            key = (e.get("src_service"), e.get("dst_service"))
            out[key] = out.get(key, 0.0) + float(e.get("cost", 0.0))
        return out

    a, s = by_pair(actual), by_pair(shadow)
    rows = [
        {
            "src_service": src,
            "dst_service": dst,
            "actual": a.get((src, dst), 0.0),
            "shadow": s.get((src, dst), 0.0),
            "delta": a.get((src, dst), 0.0) - s.get((src, dst), 0.0),
        }
        for src, dst in set(a) | set(s)
    ]
    rows.sort(key=lambda r: r["delta"], reverse=True)
    return rows[:_DELTA_EDGES]
