"""The experiment matrix — the reference's ``auto_full_pipeline_repeat.sh``
(5 algorithms × 5 repeats, cordon-induced imbalance, three measurement
phases) rebuilt as a hermetic, seed-reproducible harness over the simulator.

Per (algorithm, run): a fresh seeded ``SimBackend``, the imbalance injection
(reference auto_full_pipeline_repeat.sh:48-51), a "before" measurement
(phase r1 = release1.sh), the rescheduling loop under measurement (phase r2 =
release2.sh + main.py), and an "after" measurement (phase r3). Results land
in ``<out>/session_<ts>/<algo>/run_<n>/`` (reference
auto_full_pipeline_repeat.sh:13-16, 32-45) with the reference's CSV schemas
plus structured JSONL and a machine-readable ``summary.json``.

Response time is modeled, not curl-measured: every cross-node call edge pays
a network penalty and overloaded nodes pay a queueing penalty — the two
effects the reference's experiments attribute response-time differences to
(README.md:55-59).
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field
from pathlib import Path

import jax
import numpy as np

from kubernetes_rescheduling_tpu.backends.sim import LoadModel, SimBackend
from kubernetes_rescheduling_tpu.bench.controller import run_controller
from kubernetes_rescheduling_tpu.bench.sinks import (
    JsonlSink,
    communication_cost_sink,
    node_std_sink,
)
from kubernetes_rescheduling_tpu.config import RescheduleConfig
from kubernetes_rescheduling_tpu.core.state import ClusterState, CommGraph
from kubernetes_rescheduling_tpu.core.topology import _random_workmodel
from kubernetes_rescheduling_tpu.core.workmodel import Workmodel, mubench_workmodel_c
from kubernetes_rescheduling_tpu.objectives.metrics import communication_cost, load_std


@dataclass(frozen=True)
class ExperimentConfig:
    algorithms: tuple[str, ...] = (
        "spread",
        "binpack",
        "random",
        "kubescheduling",
        "communication",
        "global",
    )
    repeats: int = 5                   # reference auto_full_pipeline_repeat.sh:10
    rounds: int = 10                   # reference main.py:28
    scenario: str = "mubench"          # mubench | dense | powerlaw | large
    out_dir: str = "result"
    seed: int = 0
    hazard_threshold_pct: float = 30.0
    inject_imbalance: bool = True      # the cordon trick


# response-time model constants (documented, not measured)
_RESP_BASE_MS = 20.0   # in-node call path
_RESP_NET_MS = 25.0    # added per fully-remote call graph
_RESP_QUEUE_MS = 30.0  # M/M/1 queueing coefficient
_RHO_CAP = 0.95


def modeled_response_time_ms(state: ClusterState, graph: CommGraph) -> float:
    """base + net·(cross-node edge fraction) + queueing.

    Queueing is M/M/1-shaped — ρ/(1−ρ) of each pod's node, pod-weighted — so
    piling every pod on one node (the reference's cordon-induced 'Before'
    state) is penalized well before 100% utilization, matching the
    experiment's observed Before-is-worst response times (SURVEY.md §6).
    """
    adj = np.asarray(graph.adj)
    valid = np.asarray(graph.service_valid)
    total_edges = adj[valid][:, valid].sum() / 2
    cost = float(communication_cost(state, graph))
    cross_frac = cost / total_edges if total_edges else 0.0
    rho = np.clip(np.asarray(state.node_cpu_pct()) / 100.0, 0.0, _RHO_CAP)
    queue_by_node = rho / (1.0 - rho)
    pod_valid = np.asarray(state.pod_valid)
    pod_node = np.asarray(state.pod_node)
    placed = pod_valid & (pod_node >= 0)
    queue = float(queue_by_node[pod_node[placed]].mean()) if placed.any() else 0.0
    return _RESP_BASE_MS + _RESP_NET_MS * cross_frac + _RESP_QUEUE_MS * queue


def make_backend(scenario: str, seed: int) -> SimBackend:
    """Scenario factory covering the BASELINE.md benchmark configs."""
    rng = np.random.default_rng(seed)
    if scenario == "mubench":
        # reference cluster: 3 workers, i9-10900K = 20 threads (README.md:44-46)
        return SimBackend(
            workmodel=mubench_workmodel_c(),
            node_names=["worker1", "worker2", "worker3"],
            node_cpu_cap_m=20_000.0,
            seed=seed,
            load=LoadModel(entry_rps=100.0, cost_per_req_m=4.0, idle_m=50.0),
        )
    if scenario == "dense":
        wm = _random_workmodel(200, rng, powerlaw=False, mean_degree=8.0)
        return SimBackend(
            workmodel=wm,
            node_names=[f"worker{i:04d}" for i in range(20)],
            node_cpu_cap_m=20_000.0,
            seed=seed,
        )
    if scenario == "powerlaw":
        wm = _random_workmodel(2000, rng, powerlaw=True, mean_degree=4.0)
        return SimBackend(
            workmodel=wm,
            node_names=[f"worker{i:04d}" for i in range(200)],
            node_cpu_cap_m=20_000.0,
            seed=seed,
        )
    if scenario == "large":
        wm = _random_workmodel(10_000, rng, powerlaw=True, mean_degree=4.0)
        return SimBackend(
            workmodel=wm,
            node_names=[f"worker{i:04d}" for i in range(1000)],
            node_cpu_cap_m=2_000.0,
            seed=seed,
            load=LoadModel(entry_rps=10.0, cost_per_req_m=0.1, idle_m=50.0),
        )
    raise ValueError(f"unknown scenario {scenario!r}")


def run_experiment(cfg: ExperimentConfig) -> dict:
    """Run the full matrix; returns (and writes) the summary."""
    session = Path(cfg.out_dir) / f"session_{time.strftime('%Y%m%d_%H%M%S')}"
    summary: dict = {"config": cfg.__dict__ | {"algorithms": list(cfg.algorithms)}, "runs": []}

    for algo in cfg.algorithms:
        for run_i in range(1, cfg.repeats + 1):
            run_dir = session / algo / f"run_{run_i}"
            run_dir.mkdir(parents=True, exist_ok=True)
            seed = cfg.seed * 1000 + run_i
            backend = make_backend(cfg.scenario, seed)
            if cfg.inject_imbalance:
                backend.inject_imbalance(backend.node_names[0])

            graph = backend.comm_graph()
            std_sink = node_std_sink(run_dir)
            cost_sink = communication_cost_sink(run_dir)
            rounds_sink = JsonlSink(run_dir / "rounds.jsonl")

            before = backend.monitor()
            before_metrics = {
                "communication_cost": float(communication_cost(before, graph)),
                "load_std": float(load_std(before)),
                "response_time_ms": modeled_response_time_ms(before, graph),
            }
            std_sink.append(before_metrics["load_std"])

            rcfg = RescheduleConfig(
                algorithm=algo,
                max_rounds=cfg.rounds,
                hazard_threshold_pct=cfg.hazard_threshold_pct,
                sleep_after_action_s=0.0,  # simulated pacing only
                seed=seed,
            )
            t0 = time.perf_counter()
            result = run_controller(backend, rcfg, key=jax.random.PRNGKey(seed))
            wall_s = time.perf_counter() - t0
            for rec in result.rounds:
                std_sink.append(rec.load_std)
                rounds_sink.append(rec.__dict__)

            after = backend.monitor()
            after_metrics = {
                "communication_cost": float(communication_cost(after, graph)),
                "load_std": float(load_std(after)),
                "response_time_ms": modeled_response_time_ms(after, graph),
            }
            cost_sink.append(after_metrics["communication_cost"])

            summary["runs"].append(
                {
                    "algorithm": algo,
                    "run": run_i,
                    "seed": seed,
                    "before": before_metrics,
                    "after": after_metrics,
                    "moves": result.moves,
                    "decisions_per_sec": result.decisions_per_sec,
                    "wall_s": wall_s,
                    "sim_clock_s": backend.clock_s,
                }
            )

    # per-algorithm aggregates (mean over runs)
    agg: dict[str, dict] = {}
    for algo in cfg.algorithms:
        runs = [r for r in summary["runs"] if r["algorithm"] == algo]
        agg[algo] = {
            "communication_cost": float(
                np.mean([r["after"]["communication_cost"] for r in runs])
            ),
            "load_std": float(np.mean([r["after"]["load_std"] for r in runs])),
            "response_time_ms": float(
                np.mean([r["after"]["response_time_ms"] for r in runs])
            ),
            "decisions_per_sec": float(
                np.mean([r["decisions_per_sec"] for r in runs])
            ),
        }
    summary["aggregate"] = agg

    session.mkdir(parents=True, exist_ok=True)
    (session / "summary.json").write_text(json.dumps(summary, indent=2, default=float))
    return summary
