"""The experiment matrix — the reference's ``auto_full_pipeline_repeat.sh``
(5 algorithms × 5 repeats, cordon-induced imbalance, three measurement
phases) rebuilt as a hermetic, seed-reproducible harness over the simulator.

Per (algorithm, run): a fresh seeded ``SimBackend``, the imbalance injection
(reference auto_full_pipeline_repeat.sh:48-51), a "before" measurement
(phase r1 = release1.sh), the rescheduling loop under measurement (phase r2 =
release2.sh + main.py), and an "after" measurement (phase r3). Results land
in ``<out>/session_<ts>/<algo>/run_<n>/`` (reference
auto_full_pipeline_repeat.sh:13-16, 32-45) with the reference's CSV schemas
plus structured JSONL and a machine-readable ``summary.json``.

Response time is *measured from simulated requests*, not modeled with
constants: a request-level load generator (``bench.loadgen``) replays the
reference's curl fleet against each placement — phase r1 before rescheduling
(release1.sh), phase r2 sustained while the control loop runs with teardown
outages per move (release2.sh:50-59), phase r3 after — yielding
success/error counts, min/avg/max latency, and a restart/disruption total,
the same stat block the reference aggregates (release1.sh:74-117).
"""

from __future__ import annotations

import dataclasses
import json
import time
from dataclasses import dataclass, field
from pathlib import Path

import jax
import numpy as np

from kubernetes_rescheduling_tpu.backends.sim import LoadModel, SimBackend
from kubernetes_rescheduling_tpu.bench.controller import run_controller
from kubernetes_rescheduling_tpu.bench.loadgen import (
    LoadGenConfig,
    LoadGenerator,
    RequestStats,
    new_samples,
)
from kubernetes_rescheduling_tpu.bench.sinks import (
    JsonlSink,
    communication_cost_sink,
    node_std_sink,
)
from kubernetes_rescheduling_tpu.config import (
    SCAN_POLICIES,
    ChaosConfig,
    ControllerConfig,
    ElasticConfig,
    ForecastConfig,
    PerfConfig,
    ReconcileConfig,
    RescheduleConfig,
)
from kubernetes_rescheduling_tpu.core.topology import _random_workmodel
from kubernetes_rescheduling_tpu.core.workmodel import Workmodel, mubench_workmodel_c
from kubernetes_rescheduling_tpu.objectives.metrics import communication_cost, load_std
from kubernetes_rescheduling_tpu.telemetry import get_registry, span, write_manifest
from kubernetes_rescheduling_tpu.utils.logging import StructuredLogger


@dataclass(frozen=True)
class ExperimentConfig:
    algorithms: tuple[str, ...] = (
        "spread",
        "binpack",
        "random",
        "kubescheduling",
        "communication",
        "global",
    )
    repeats: int = 5                   # reference auto_full_pipeline_repeat.sh:10
    rounds: int = 10                   # reference main.py:28
    scenario: str = "mubench"          # mubench | dense | powerlaw | large
    backend: str = "sim"               # sim | k8s (live cluster, like the
                                       # reference's auto_full_pipeline_repeat.sh)
    namespace: str = "default"         # k8s backend only (reference main.py:68)
    workmodel: str | None = None       # external workmodel JSON (overrides scenario topology)
    out_dir: str = "result"
    # named sessions are resumable: completed (algorithm, run) cells are
    # loaded from their run.json, a crashed cell resumes from its latest
    # per-round checkpoint. None = fresh timestamped session every call.
    session_name: str | None = None
    seed: int = 0
    hazard_threshold_pct: float = 30.0
    inject_imbalance: bool = True      # the cordon trick
    pacing_s: float = 15.0             # simulated seconds per round (main.py:27)
    load: LoadGenConfig = field(default_factory=LoadGenConfig)
    # λ for the global solver: comm-cost edges traded per load-std point.
    # 0 would let the solver "win" by keeping the Before pile-up intact
    # (comm cost 0, load std terrible) — never what an operator wants.
    balance_weight: float = 0.5
    solver_restarts: int = 1           # best-of-N global solves per round
    solver_tp: int = 1                 # node-axis devices per solve (SPMD solver)
    move_cost: float = 0.0             # disruption pricing in the global solve
    solver_backend: str = "dense"      # "dense" | "sparse" pair weights
    placement_unit: str = "service"    # "service" | "pod" (per-replica)
    moves_per_round: int | str = 1     # k per greedy round, or "all"
    global_moves_cap: int | str = "all"  # wave cap for global rounds
    # Packing budget for the global solver's feasibility (fraction of node
    # capacity, with enforcement). On dense meshes the comm objective
    # genuinely prefers total colocation at any moderate λ; the budget is
    # what forces the pile apart — and since queueing delay is convex in
    # utilization, it is also the response-time lever.
    enforce_capacity: bool = False
    capacity_frac: float = 1.0
    # Ground the solver in OBSERVED traffic: estimate edge weights from the
    # phase-r1 request stream's traversal counts (LoadGenerator.
    # observed_graph) and hand the controller that graph instead of the
    # declared workmodel topology (reference README.md:47 — the objective
    # is defined on actual deployed traffic).
    observe_weights: bool = False
    # Chaos soak cells: a named backends.chaos profile ("none" = off)
    # wraps each cell's LOOP backend (measurement phases stay on the raw
    # backend); the breaker threshold feeds the controller's degraded-mode
    # state machine.
    chaos_profile: str = "none"
    chaos_seed: int = 0
    max_consecutive_failures: int = 5
    # Elastic churn cells: a named elastic/events profile ("none" = the
    # historical static topology) mutates each cell's cluster between
    # rounds — service deploy/teardown waves, traffic-driven replica
    # autoscaling, node drain/add — absorbed by shape buckets so the
    # decision kernels stay at 1 steady-state trace (+1 per counted
    # bucket promotion). The load phases keep measuring the cell's
    # INITIAL topology (services deployed mid-run carry no request
    # stream of their own yet).
    churn_profile: str = "none"
    churn_seed: int = 0
    # Forecast plane: the online forecaster behind `proactive` cells
    # (algorithms may include "proactive" — the head-to-head against
    # reactive CAR under churn is run_forecast_headtohead's matrix).
    forecast: ForecastConfig = field(default_factory=ForecastConfig)
    # Software-pipelined control loop ([controller] pipeline): the r2
    # control-loop phase runs the overlapped schedule — decisions are
    # bit-identical to the sequential loop (test-pinned), only wall
    # clock and transfer timing change.
    pipeline: bool = False
    pipeline_depth: int = 2
    # Device-resident round scan ([controller] scan_block): K steady-
    # state rounds per compiled dispatch with one round_end transfer
    # per block; incompatible rounds drain to the per-round path.
    # NOTE: harness cells sustain load through on_round, which the
    # scanned schedule drains on — scan cells are the bench.py
    # BENCH_SCENARIO=scan loop (no load hook), not the matrix.
    scan_block: int = 0
    # Reconciliation & admission plane ([reconcile]): on by default —
    # every cell's r2 loop admits its snapshots and reconciles its own
    # moves; chaos cells therefore self-heal injected drift.
    reconcile: ReconcileConfig = field(default_factory=ReconcileConfig)
    # Live ops plane: serve /metrics, /healthz, /events on this port for
    # the whole session (0 = ephemeral, None = off). One OpsPlane spans
    # every matrix cell; per-cell loggers re-bind as cells start, so
    # /events always follows the running cell. Flight-recorder bundles
    # land in bundle_dir (None = <session>/flight_recorder).
    serve_port: int | None = None
    bundle_dir: str | None = None
    # Perf ledger: every finished cell appends ONE decisions/sec reading
    # (keyed by metric/scenario+algorithm/device kind/config digest) to an
    # append-only JSONL ledger; the rolling-window detector judges each
    # series and feeds the ops plane's perf_regression SLO rule. None =
    # <session>/perf_ledger.jsonl; point it at a shared file to trend
    # across sessions.
    perf_enabled: bool = True
    perf_ledger: str | None = None
    perf_window: int = 5
    perf_regression_frac: float = 0.2
    perf_baseline: str = "median"    # "median" | "best" of the window

    def __post_init__(self):
        # fail invalid solver combinations in milliseconds at construction,
        # not after minutes of phase-r1 load simulation when run_controller
        # first validates its per-run RescheduleConfig
        RescheduleConfig(
            algorithm="global",
            solver_backend=self.solver_backend,
            placement_unit=self.placement_unit,
            solver_restarts=self.solver_restarts,
            solver_tp=self.solver_tp,
            moves_per_round=self.moves_per_round,
            global_moves_cap=self.global_moves_cap,
        ).validate()
        PerfConfig(
            ledger_path=self.perf_ledger,
            window=self.perf_window,
            regression_frac=self.perf_regression_frac,
            baseline=self.perf_baseline,
        ).validate()
        # fail an invalid churn cell in milliseconds, not after phase r1:
        # the profile name must parse, and churn injection is sim-only
        ElasticConfig(profile=self.churn_profile, seed=self.churn_seed).validate()
        self.forecast.validate()
        if self.churn_profile != "none" and self.backend == "k8s":
            raise ValueError(
                "churn_profile requires the sim backend: a live cluster "
                "churns itself"
            )
        if self.churn_profile != "none" and self.observe_weights:
            # the traffic estimator's call plan is frozen at cell start
            # (LoadGenerator compiles one edge list per workmodel) — under
            # churn it would silently steer every solve with the stale
            # pre-churn topology, exactly the phantom-topology class the
            # elastic plane exists to prevent. Estimating weights over a
            # churning service set needs a re-planning estimator first.
            raise ValueError(
                "churn_profile and observe_weights cannot combine yet: the "
                "weight estimator's call plan is fixed at cell start and "
                "cannot observe churned services"
            )
        if self.placement_unit == "pod" and self.backend == "k8s":
            # K8sBackend.apply_move rejects per-pod moves (the Deployment
            # mechanism cannot pin one replica) — fail here, not mid-run
            raise ValueError(
                "placement_unit='pod' requires the sim backend: the k8s "
                "Deployment mechanism cannot pin a single replica"
            )


def mubench_reference_placements():
    """Three placements of the µBench scenario, MONITORED THROUGH the sim
    backend so the load model couples placement to node utilization (the
    queueing/overload regime the latency claims rest on — raw
    request-based states would read a few % everywhere and make total
    colocation trivially "win"): the cordon pile-up, the global solve
    under a 50% packing budget, and a seeded random spread. ONE
    definition shared by the loadgen sensitivity sweep
    (scripts/loadgen_sensitivity.py) and its extreme-corner regression
    test (tests/test_loadgen.py), so the two measure the SAME
    placements."""
    import jax.numpy as jnp

    from kubernetes_rescheduling_tpu.solver import (
        GlobalSolverConfig,
        global_assign,
    )

    def monitored(kind):
        backend = make_backend("mubench", seed=0)
        backend.inject_imbalance(backend.node_names[0])
        st = backend.monitor()
        if kind == "global":
            after, _ = global_assign(
                st, backend.comm_graph(), jax.random.PRNGKey(0),
                GlobalSolverConfig(
                    sweeps=9, balance_weight=0.5, enforce_capacity=True,
                    capacity_frac=0.5,
                ),
            )
            backend.restore_placement(after)
            st = backend.monitor()
        elif kind == "random":
            rng = np.random.default_rng(1)
            rand = st.replace(
                pod_node=jnp.asarray(
                    np.where(
                        np.asarray(st.pod_valid),
                        rng.integers(0, st.num_nodes, st.num_pods),
                        np.asarray(st.pod_node),
                    ),
                    jnp.int32,
                )
            )
            backend.restore_placement(rand)
            st = backend.monitor()
        return st

    return {k: monitored(k) for k in ("pileup", "global", "random")}


def make_backend(
    scenario: str, seed: int, workmodel_path: str | None = None
) -> SimBackend:
    """Scenario factory covering the BASELINE.md benchmark configs.

    ``workmodel_path`` swaps the scenario's builtin *topology* for an
    external µBench workmodel JSON (the reference's externalized workload,
    workmodelC.json) while keeping that scenario's cluster shape and load
    model.
    """
    rng = np.random.default_rng(seed)
    wm_override = (
        Workmodel.from_file(workmodel_path) if workmodel_path is not None else None
    )
    if scenario == "mubench":
        # reference cluster: 3 workers, i9-10900K = 20 threads (README.md:44-46)
        return SimBackend(
            workmodel=wm_override or mubench_workmodel_c(),
            node_names=["worker1", "worker2", "worker3"],
            node_cpu_cap_m=20_000.0,
            seed=seed,
            # sized so the cordon-induced "Before" pile-up drives worker1 to
            # ~85% CPU — the saturation regime the reference's ~1000
            # concurrent clients create (release1.sh:9), where queueing
            # dominates response time until pods spread out
            load=LoadModel(entry_rps=100.0, cost_per_req_m=8.0, idle_m=50.0),
        )
    # synthetic meshes: fanout_frac ≈ 1/(mean forward out-degree) keeps the
    # expected request branching factor at ~1, so the entry rate neither
    # dies out nor multiplies combinatorially through multi-parent DAGs
    if scenario == "dense":
        wm = wm_override or _random_workmodel(200, rng, powerlaw=False, mean_degree=8.0)
        return SimBackend(
            workmodel=wm,
            node_names=[f"worker{i:04d}" for i in range(20)],
            node_cpu_cap_m=20_000.0,
            seed=seed,
            # idle sized so the injected pile-up (200 pods on one node)
            # crosses the 30% hazard threshold and the loop has work to do
            load=LoadModel(idle_m=40.0, cost_per_req_m=5.0, fanout_frac=0.25),
        )
    if scenario == "powerlaw":
        wm = wm_override or _random_workmodel(2000, rng, powerlaw=True, mean_degree=4.0)
        return SimBackend(
            workmodel=wm,
            node_names=[f"worker{i:04d}" for i in range(200)],
            node_cpu_cap_m=20_000.0,
            seed=seed,
            load=LoadModel(fanout_frac=0.5),
        )
    if scenario == "large":
        wm = wm_override or _random_workmodel(10_000, rng, powerlaw=True, mean_degree=4.0)
        return SimBackend(
            workmodel=wm,
            node_names=[f"worker{i:04d}" for i in range(1000)],
            node_cpu_cap_m=2_000.0,
            seed=seed,
            load=LoadModel(
                entry_rps=10.0, cost_per_req_m=0.1, idle_m=50.0, fanout_frac=0.5
            ),
        )
    if scenario == "xlarge":
        # 2× the north star on both axes: validates the documented dense-W
        # scaling numbers (2.3 GiB at 20k services) on real hardware and
        # gives a second perf point past the headline scale
        wm = wm_override or _random_workmodel(20_000, rng, powerlaw=True, mean_degree=4.0)
        return SimBackend(
            workmodel=wm,
            node_names=[f"worker{i:04d}" for i in range(2000)],
            node_cpu_cap_m=2_000.0,
            seed=seed,
            load=LoadModel(
                entry_rps=10.0, cost_per_req_m=0.05, idle_m=50.0, fanout_frac=0.5
            ),
        )
    raise ValueError(f"unknown scenario {scenario!r}")


def make_fleet_problem(
    tenants: int = 16,
    n_services: int = 2000,
    n_nodes: int = 256,
    seed: int = 0,
):
    """The fleet-mode bench problem: N same-shaped power-law tenants.

    Each tenant is its own mesh (per-tenant seed — the fleet seeding
    convention of ``backends.fleet.make_fleet``) over an identical
    cluster shape, so the stacked batch compiles once. Returns
    ``(states, graphs)`` index-aligned lists; ``bench.py``'s fleet cell
    stacks them with ``solver.fleet.stack_tenants`` and measures the
    amortized per-tenant decision cost of ONE batched dispatch against
    N sequential solo dispatches."""
    from kubernetes_rescheduling_tpu.core.topology import state_from_workmodel

    states, graphs = [], []
    for t in range(tenants):
        rng = np.random.default_rng(seed * 1000 + t)
        wm = _random_workmodel(n_services, rng, powerlaw=True, mean_degree=4.0)
        graphs.append(wm.comm_graph())
        states.append(
            state_from_workmodel(
                wm,
                node_names=[f"w{i:03d}" for i in range(n_nodes)],
                node_cpu_cap_m=2_000.0,
                seed=seed * 1000 + t,
            )
        )
    return states, graphs


def make_experiment_backend(cfg: ExperimentConfig, seed: int, **k8s_apis):
    """Backend for one matrix cell: the hermetic simulator, or the live
    cluster adapter when ``cfg.backend == "k8s"`` (the reference's pipeline
    always runs live, auto_full_pipeline_repeat.sh:25-187). ``k8s_apis``
    passes through client objects (tests inject fakes)."""
    if cfg.backend == "k8s":
        from kubernetes_rescheduling_tpu.backends.k8s import K8sBackend

        wm = (
            Workmodel.from_file(cfg.workmodel)
            if cfg.workmodel
            else mubench_workmodel_c()
        )
        return K8sBackend(workmodel=wm, namespace=cfg.namespace, **k8s_apis)
    return make_backend(cfg.scenario, seed, workmodel_path=cfg.workmodel)


def run_experiment(cfg: ExperimentConfig, **backend_kwargs) -> dict:
    """Run the full matrix; returns (and writes) the summary.

    With ``cfg.session_name`` set, the session is resumable after a crash:
    finished (algorithm, run) cells reload from their ``run.json`` marker,
    and a half-finished cell restores the simulator from its latest
    per-round checkpoint and continues (SURVEY §5.4 — the reference restarts
    from round 1, losing the experiment).

    With ``cfg.serve_port`` set, one live ops plane serves the whole
    session: ``/metrics`` scrapes the process registry across cells,
    ``/healthz`` tracks the currently-running cell's breaker/SLO state,
    and flight-recorder bundles land under ``<session>/flight_recorder``.
    """
    from kubernetes_rescheduling_tpu.telemetry import perf_ledger as pl

    stamp = cfg.session_name or time.strftime("%Y%m%d_%H%M%S")
    session = Path(cfg.out_dir) / f"session_{stamp}"
    cfg_dict = dataclasses.asdict(cfg)
    summary: dict = {"config": cfg_dict, "runs": []}

    # one ledger for the session (or a shared cross-session file): every
    # cell appends its decisions/sec reading, keyed so only like-for-like
    # readings (same scenario+algorithm, device kind, config) compare
    ledger = (
        pl.PerfLedger(cfg.perf_ledger or session / "perf_ledger.jsonl")
        if cfg.perf_enabled
        else None
    )
    cell_digest = pl.config_digest(
        {k: v for k, v in cfg_dict.items() if k not in ("out_dir", "session_name")}
    )
    device_kind = jax.devices()[0].platform

    ops = None
    if cfg.serve_port is not None:
        from kubernetes_rescheduling_tpu.config import ObsConfig
        from kubernetes_rescheduling_tpu.telemetry import OpsPlane

        ops = OpsPlane.from_config(
            ObsConfig(serve_port=cfg.serve_port),
            bundle_dir=cfg.bundle_dir or str(session / "flight_recorder"),
        ).start()

    try:
        if cfg.session_name:
            # a resumed session must be the SAME experiment: reloading another
            # config's run.json would silently mix results
            session.mkdir(parents=True, exist_ok=True)
            fingerprint = {k: v for k, v in cfg_dict.items() if k != "out_dir"}
            fp_file = session / "config.json"
            if fp_file.is_file():
                saved = json.loads(fp_file.read_text())
                if saved != json.loads(json.dumps(fingerprint, default=float)):
                    raise ValueError(
                        f"session {cfg.session_name!r} was created with a different "
                        f"config; refusing to mix results (delete {session} or use "
                        "a new session name)"
                    )
            else:
                fp_file.write_text(json.dumps(fingerprint, default=float))

        # provenance next (after the fingerprint gate): even a session that
        # crashes mid-matrix leaves a record of what ran, on which devices,
        # from which commit — but a resume must NOT clobber the manifest of
        # the run that produced the existing cells
        manifest_file = session / "manifest.json"
        if manifest_file.is_file():
            manifest_file = session / "manifest.resume.json"
        write_manifest(manifest_file, json.loads(json.dumps(cfg_dict, default=float)))

        for algo in cfg.algorithms:
            for run_i in range(1, cfg.repeats + 1):
                run_dir = session / algo / f"run_{run_i}"
                run_dir.mkdir(parents=True, exist_ok=True)
                run_marker = run_dir / "run.json"
                if cfg.session_name and run_marker.is_file():
                    summary["runs"].append(json.loads(run_marker.read_text()))
                    continue
                seed = cfg.seed * 1000 + run_i
                backend = make_experiment_backend(cfg, seed, **backend_kwargs)
                if cfg.inject_imbalance and hasattr(backend, "inject_imbalance"):
                    backend.inject_imbalance(backend.node_names[0])

                graph = backend.comm_graph()
                load_model = getattr(backend, "load", None)
                loadgen = LoadGenerator(
                    backend.workmodel,
                    cfg.load,
                    fanout_frac=load_model.fanout_frac if load_model else 1.0,
                )
                key = jax.random.PRNGKey(seed)
                key, k_before, k_during, k_after = jax.random.split(key, 4)
                std_sink = node_std_sink(run_dir)
                cost_sink = communication_cost_sink(run_dir)
                rounds_sink = JsonlSink(run_dir / "rounds.jsonl")
                logger = StructuredLogger(name=f"{algo}/run_{run_i}", path=run_dir / "log.jsonl")

                # phase r1: load against the imbalanced "Before" placement.
                # Persisted immediately so a crash-resume doesn't re-measure
                # "before" against a mid-rescheduling cluster.
                phase1 = run_dir / "phase1.json"
                if cfg.session_name and phase1.is_file():
                    saved = json.loads(phase1.read_text())
                    before_metrics = saved["before"]
                    load_before_dict = saved["load_before"]
                    edge_counts = (
                        np.asarray(saved["edge_counts"], dtype=np.int64)
                        if saved.get("edge_counts") is not None
                        else None
                    )
                    obs_sent = int(saved.get("obs_sent", 0))
                else:
                    before = backend.monitor()
                    samples_before = loadgen.run(before, k_before)
                    load_before = samples_before.stats()
                    load_before_dict = load_before.as_dict()
                    edge_counts = samples_before.edge_counts
                    obs_sent = samples_before.sent
                    before_metrics = {
                        "communication_cost": float(communication_cost(before, graph)),
                        "load_std": float(load_std(before)),
                        "response_time_ms": load_before.latency_avg_ms,
                    }
                    std_sink.append(before_metrics["load_std"])
                    phase1.write_text(
                        json.dumps(
                            {
                                "before": before_metrics,
                                "load_before": load_before_dict,
                                # persisted so a crash-resume can still estimate
                                "edge_counts": (
                                    edge_counts.tolist()
                                    if edge_counts is not None
                                    else None
                                ),
                                "obs_sent": obs_sent,
                            },
                            default=float,
                        )
                    )

                # traffic-estimated weights for the DECISION graph: the solver
                # optimizes what the request stream actually traversed —
                # seeded by phase r1 and RE-ESTIMATED each round from the
                # sustained load's accumulating counts (`during` below), so
                # decisions track drifting traffic. Reported
                # communication_cost metrics stay on the declared graph for
                # comparability across configurations.
                def solve_graph(_counts=edge_counts, _sent=obs_sent):
                    total = _counts
                    n = _sent
                    if during.edge_counts is not None:
                        total = (
                            during.edge_counts
                            if total is None
                            else total + during.edge_counts
                        )
                        n += during.sent
                    return loadgen.observed_graph(total, n, graph)

                # phase r2: the control loop under sustained load — per round,
                # simulate the segment's requests with teardown outages for every
                # Deployment moved that round (reference release2.sh:50-59)
                rcfg = RescheduleConfig(
                    algorithm=algo,
                    max_rounds=cfg.rounds,
                    hazard_threshold_pct=cfg.hazard_threshold_pct,
                    sleep_after_action_s=cfg.pacing_s,  # simulated clock, not wall
                    balance_weight=cfg.balance_weight,
                    move_cost=cfg.move_cost,
                    solver_backend=cfg.solver_backend,
                    placement_unit=cfg.placement_unit,
                    solver_restarts=cfg.solver_restarts,
                    solver_tp=cfg.solver_tp,
                    moves_per_round=cfg.moves_per_round,
                    global_moves_cap=cfg.global_moves_cap,
                    enforce_capacity=cfg.enforce_capacity,
                    capacity_frac=cfg.capacity_frac,
                    seed=seed,
                    # run_controller wraps ITS view of the backend in the chaos
                    # profile; the harness's own phase r1/r3 measurements stay
                    # on the raw backend (faults hit the loop, not the ruler)
                    chaos=ChaosConfig(
                        profile=cfg.chaos_profile, seed=cfg.chaos_seed + run_i
                    ),
                    elastic=ElasticConfig(
                        profile=cfg.churn_profile, seed=cfg.churn_seed + run_i
                    ),
                    forecast=cfg.forecast,
                    max_consecutive_failures=cfg.max_consecutive_failures,
                    controller=ControllerConfig(
                        pipeline=cfg.pipeline, depth=cfg.pipeline_depth,
                        # the matrix mixes algorithms; scan only the
                        # cells whose algorithm the scanned schedule can
                        # express (validation would reject the rest —
                        # the harness's analogue of the runtime drain)
                        scan_block=(
                            cfg.scan_block
                            if algo in SCAN_POLICIES
                            and cfg.moves_per_round == 1
                            else 0
                        ),
                    ),
                    reconcile=cfg.reconcile,
                )
                # solve_graph (above) closes over this accumulator; bound here,
                # before the controller ever calls the estimator
                during = new_samples()

                def clock(_backend=backend):
                    # sim: the simulated clock; live cluster: wall time
                    c = getattr(_backend, "clock_s", None)
                    return time.monotonic() if c is None else c

                seg_state = {"clock": clock(), "i": 0}

                def on_round(rec, state, _ss=seg_state, _during=during):
                    # sinks written in-loop so a crash keeps completed rounds'
                    # rows (the reference CSV schemas) for the resumed session
                    std_sink.append(rec.load_std)
                    rounds_sink.append(rec.as_dict())
                    now = clock()
                    seg_dur = max(now - _ss["clock"], 1e-9)
                    _ss["clock"] = now
                    n_req = max(
                        int(
                            cfg.load.requests_per_phase
                            * seg_dur
                            / max(cfg.load.duration_s, 1e-9)
                        ),
                        64,
                    )
                    # read per round, not once: K8sBackend replaces its initial
                    # estimate with the measured delete→recreate wall time after
                    # each move (sim exposes its simulated teardown latency)
                    reconcile = getattr(backend, "reconcile_delay_s", 10.0)
                    outages = [
                        (svc, i * reconcile, (i + 1) * reconcile)
                        for i, svc in enumerate(rec.services_moved)
                    ]
                    loadgen.run(
                        state,
                        jax.random.fold_in(k_during, _ss["i"]),
                        duration_s=seg_dur,
                        n_requests=n_req,
                        outages=outages,
                        samples=_during,
                    )
                    _ss["i"] += 1

                events = getattr(backend, "events", None)
                events_mark = len(events) if events is not None else 0
                # live cluster: snapshot per-pod restartCount so the loop's
                # container crashes can be MEASURED as a delta that survives
                # delete+recreate (fresh pods start at 0)
                crash_probe = getattr(backend, "pod_restart_counts", None)
                crashes_at_start = crash_probe() if crash_probe else None
                t0 = time.perf_counter()
                with span("bench/run", algorithm=algo, run=run_i):
                    result = run_controller(
                        backend,
                        rcfg,
                        key=jax.random.PRNGKey(seed),
                        on_round=on_round,
                        checkpoint_dir=str(run_dir / "checkpoints") if cfg.session_name else None,
                        logger=logger,
                        graph=solve_graph if cfg.observe_weights else None,
                        ops=ops,
                    )
                wall_s = time.perf_counter() - t0
                # `restarts` = pods recreated by Deployment moves (the
                # disruption the RESCHEDULER causes) — identical semantics on
                # both backends: sim reads its event log, live derives from
                # moved services' replica counts (each moved Deployment's
                # replicas are all recreated, so this is exact, not estimated)
                if events is not None:
                    during.restarts = sum(
                        int(e.get("pods", 0))
                        for e in events[events_mark:]
                        # "move" = whole-Deployment re-creates; "pod_moves" =
                        # a pod-mode round's batched per-replica wave
                        if e.get("event") in ("move", "pod_moves")
                    )
                    restart_source = "event_log"
                else:
                    replicas = {
                        s.name: max(1, s.replicas) for s in backend.workmodel.services
                    }
                    during.restarts = sum(
                        replicas.get(svc, 1)
                        for rec in result.rounds
                        for svc in rec.services_moved
                    )
                    restart_source = "derived_from_moves"
                # `container_crashes` = the reference's restartCount metric
                # (release1.sh:101-102) as a measured per-pod delta: pods in
                # both snapshots contribute max(end-start, 0); pods created
                # during the loop contribute their full count. (Crashes a pod
                # accrued AFTER the start snapshot but before its own
                # teardown are unobservable — restartCount dies with the pod.)
                crashes_at_end = crash_probe() if crash_probe else None
                if crashes_at_start is not None and crashes_at_end is not None:
                    during.container_crashes = sum(
                        max(c - crashes_at_start.get(pod, 0), 0)
                        for pod, c in crashes_at_end.items()
                    )
                load_during = during.stats()

                # phase r3: load against the final placement. A chaos cell's
                # node flap may end the loop with a worker still killed — heal
                # the raw backend first so the "after" ruler measures the
                # recovered cluster, not the last injected fault.
                if cfg.chaos_profile != "none":
                    revive = getattr(backend, "revive_node", None)
                    if revive is not None:
                        for node in backend.node_names:
                            revive(node)
                    pending = getattr(backend, "schedule_pending", None)
                    if pending is not None:
                        pending()
                after = backend.monitor()
                load_after = loadgen.measure(after, k_after)
                after_metrics = {
                    "communication_cost": float(communication_cost(after, graph)),
                    "load_std": float(load_std(after)),
                    "response_time_ms": load_after.latency_avg_ms,
                }
                cost_sink.append(after_metrics["communication_cost"])

                run_record = {
                    "algorithm": algo,
                    "run": run_i,
                    "seed": seed,
                    "before": before_metrics,
                    "after": after_metrics,
                    "load": {
                        "before": load_before_dict,
                        "during": load_during.as_dict(),
                        "after": load_after.as_dict(),
                    },
                    "moves": result.moves,
                    "restart_source": restart_source,
                    "decisions_per_sec": result.decisions_per_sec,
                    "decision_latency": result.latency_summary(),
                    "resumed_from_round": result.resumed_from_round,
                    "skipped_rounds": result.skipped_rounds,
                    "degraded_rounds": result.degraded_rounds,
                    "boundary_failures": result.boundary_failures,
                    "breaker_transitions": result.breaker_transitions,
                    "wall_s": wall_s,
                    "sim_clock_s": getattr(backend, "clock_s", None),
                }
                run_marker.write_text(json.dumps(run_record, default=float))
                logger.info("run_complete", moves=result.moves)
                # cumulative registry snapshot per cell (values are monotone;
                # the telemetry report reads the LAST sample per series), so a
                # crash keeps the counters up to the finished cells
                get_registry().dump_jsonl(run_dir / "metrics.jsonl")
                summary["runs"].append(run_record)
                if ledger is not None:
                    # one ledger entry per cell, then re-judge every series:
                    # a regression arms the ops plane's perf_regression SLO
                    # rule (and /healthz) the moment the cell finishes
                    ledger.append(
                        metric="decisions_per_sec",
                        value=result.decisions_per_sec,
                        unit="1/s",
                        scenario=f"{cfg.scenario}/{algo}",
                        device_kind=device_kind,
                        digest=cell_digest,
                        better="higher",
                        run=run_i,
                        seed=seed,
                    )
                    if ops is not None:
                        # judge only when someone is listening: re-reading
                        # and re-detecting a shared cross-session ledger
                        # per cell is O(history) for nothing otherwise
                        ops.observe_perf(
                            pl.detect(
                                ledger.entries(),
                                window=cfg.perf_window,
                                threshold_frac=cfg.perf_regression_frac,
                                baseline=cfg.perf_baseline,
                            )
                        )

        # per-algorithm aggregates (mean over runs). Final-placement metrics
        # average over every run; loop-phase metrics (decision rate, disruption)
        # only over runs that actually executed rounds — a crash-resumed cell
        # whose loop had already finished contributes zeros that would skew them.
        agg: dict[str, dict] = {}
        for algo in cfg.algorithms:
            runs = [r for r in summary["runs"] if r["algorithm"] == algo]
            looped = [r for r in runs if r["decision_latency"].get("count", 0) > 0]

            def loop_mean(metric_fn):
                return float(np.mean([metric_fn(r) for r in looped])) if looped else 0.0

            agg[algo] = {
                "communication_cost": float(
                    np.mean([r["after"]["communication_cost"] for r in runs])
                ),
                "load_std": float(np.mean([r["after"]["load_std"] for r in runs])),
                "response_time_ms": float(
                    np.mean([r["after"]["response_time_ms"] for r in runs])
                ),
                "error_rate_during": loop_mean(
                    lambda r: r["load"]["during"]["error_rate"]
                ),
                "restarts": loop_mean(lambda r: r["load"]["during"]["restarts"]),
                "decisions_per_sec": loop_mean(lambda r: r["decisions_per_sec"]),
            }
        summary["aggregate"] = agg

        session.mkdir(parents=True, exist_ok=True)
        (session / "summary.json").write_text(json.dumps(summary, indent=2, default=float))
    finally:
        # shut the live endpoint (and restore the SIGUSR1 handler)
        # however the matrix ends — a crashing cell must not leak the
        # server socket into the next session (run_controller already
        # dumped a crash bundle on the way out)
        if ops is not None:
            ops.close()
    return summary


def run_forecast_headtohead(
    profiles: tuple[str, ...] = ("diurnal-autoscale", "deploy-waves"),
    rounds: int = 40,
    *,
    scenario: str = "dense",
    seed: int = 1,
    churn_seed: int = 7,
    load_noise_frac: float = 0.05,
    forecast: ForecastConfig | None = None,
    logger_factory=None,
    registry=None,
) -> dict:
    """The forecast-plane matrix cell: ``proactive`` vs reactive CAR on
    IDENTICALLY seeded churned clusters, one pair per churn profile.

    Both arms see the same backend construction, the same imbalance
    injection, the same churn event stream (profile + seed), the same
    metrics-reading noise stream, and the same controller key — the ONLY
    difference is the algorithm, so the comparison isolates what
    predicting the next window buys. Returns per-profile mean/final
    communication cost for both arms, the proactive arm's final forecast
    block (skill vs persistence), and round accounting — the acceptance
    test pins ``proactive mean ≤ reactive mean`` and ``forecast_skill >
    0`` on this cell.

    ``load_noise_frac`` injects per-pod gaussian reading noise into the
    sim's monitor (real metrics servers are noisy): under observation
    noise the differenced ridge model has a PROVABLE edge over
    persistence (deltas of a noisy level series are negatively
    autocorrelated — the model learns the mean-reversion persistence
    cannot express), which is exactly the regime the skill metric must
    separate the two predictors in.
    """
    out: dict = {"rounds": rounds, "scenario": scenario, "profiles": {}}
    for profile in profiles:
        arms: dict[str, dict] = {}
        for algo in ("proactive", "communication"):
            backend = make_backend(scenario, seed)
            if load_noise_frac:
                backend.load = dataclasses.replace(
                    backend.load, noise_frac=load_noise_frac
                )
            backend.inject_imbalance(backend.node_names[0])
            from kubernetes_rescheduling_tpu.config import ObsConfig

            rcfg = RescheduleConfig(
                algorithm=algo,
                max_rounds=rounds,
                sleep_after_action_s=0.0,
                seed=seed,
                elastic=ElasticConfig(profile=profile, seed=churn_seed),
                forecast=forecast if forecast is not None else ForecastConfig(),
                # attribution is not under test here and would double the
                # per-round device work of both arms, so it is OFF;
                # explain follows the controller's usual gate — active
                # only when the caller supplies a logger_factory (the
                # acceptance test does, and pins bundle re-derivation)
                obs=ObsConfig(attribution=False),
            )
            logger = logger_factory() if logger_factory is not None else None
            with span("bench/forecast_headtohead", profile=profile, algorithm=algo):
                result = run_controller(
                    backend, rcfg, key=jax.random.PRNGKey(seed),
                    logger=logger, registry=registry,
                )
            costs = [r.communication_cost for r in result.rounds]
            arms[algo] = {
                "mean_communication_cost": float(np.mean(costs)) if costs else 0.0,
                "final_communication_cost": costs[-1] if costs else None,
                "mean_load_std": float(
                    np.mean([r.load_std for r in result.rounds])
                ) if result.rounds else 0.0,
                "rounds": len(result.rounds),
                "skipped_rounds": result.skipped_rounds,
                "moves": result.moves,
                "forecast": next(
                    (
                        r.forecast
                        for r in reversed(result.rounds)
                        if r.forecast is not None
                    ),
                    None,
                ),
                "records": result.rounds,
            }
        pro, rea = arms["proactive"], arms["communication"]
        out["profiles"][profile] = {
            **{k: {kk: vv for kk, vv in v.items() if kk != "records"}
               for k, v in arms.items()},
            "proactive_vs_reactive_cost": (
                pro["mean_communication_cost"]
                / rea["mean_communication_cost"]
                if rea["mean_communication_cost"] > 0
                else 1.0
            ),
            "_records": {k: v["records"] for k, v in arms.items()},
        }
    return out


def run_chaos_soak(
    profile: str = "soak",
    rounds: int = 30,
    *,
    scenario: str = "mubench",
    algorithm: str = "communication",
    seed: int = 0,
    chaos_seed: int = 0,
    max_consecutive_failures: int = 3,
    breaker_cooldown_rounds: int = 2,
    failure_budget_per_round: int = 2,
    retry=None,
    logger: StructuredLogger | None = None,
    registry=None,
    ops=None,
) -> dict:
    """The chaos soak cell: one seeded fault profile against one scenario,
    the controller's degraded-mode machinery fully enabled. ``ops``
    optionally attaches a live ops plane (``telemetry.server.OpsPlane``)
    so the soak can be WATCHED: /healthz flips while the breaker is open,
    and breaker-open rounds leave flight-recorder bundles behind — the
    acceptance path the live-observability soak test drives.

    The chaos wrapper is built HERE (not via ``config.chaos``) so the
    report can cross-check the wrapper's own ``fault_counts`` against the
    telemetry registry's ``chaos_faults_total`` counters — the invariant
    the acceptance soak test pins: every injected fault is counted, every
    round is accounted (``rounds == records + skips``), and the loop
    finishes without raising.
    """
    from kubernetes_rescheduling_tpu.backends.chaos import with_chaos
    from kubernetes_rescheduling_tpu.utils.retry import RetryPolicy

    backend = make_backend(scenario, seed)
    backend.inject_imbalance(backend.node_names[0])
    chaos = with_chaos(backend, profile, seed=chaos_seed, registry=registry)
    rcfg = RescheduleConfig(
        algorithm=algorithm,
        max_rounds=rounds,
        sleep_after_action_s=0.0,
        seed=seed,
        retry=retry if retry is not None else RetryPolicy(max_attempts=2, base_delay_s=0.05),
        max_consecutive_failures=max_consecutive_failures,
        breaker_cooldown_rounds=breaker_cooldown_rounds,
        failure_budget_per_round=failure_budget_per_round,
    )
    with span("bench/chaos_soak", profile=profile):
        result = run_controller(
            chaos, rcfg, key=jax.random.PRNGKey(seed), logger=logger,
            registry=registry, ops=ops,
        )
    fault_counts = dict(getattr(chaos, "fault_counts", {}))
    return {
        "profile": profile,
        "rounds": rounds,
        "records": len(result.rounds),
        "skipped_rounds": result.skipped_rounds,
        "degraded_rounds": result.degraded_rounds,
        "boundary_failures": result.boundary_failures,
        "moves": result.moves,
        "breaker_transitions": result.breaker_transitions,
        "breaker_opens": sum(
            1 for t in result.breaker_transitions if t["to"] == "open"
        ),
        "breaker_closes": sum(
            1 for t in result.breaker_transitions if t["to"] == "closed"
        ),
        "fault_counts": fault_counts,
        "faults_injected": sum(fault_counts.values()),
    }
