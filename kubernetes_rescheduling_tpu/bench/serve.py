"""The serving soak: drive a :class:`~kubernetes_rescheduling_tpu.
serving.ServingEngine` with an open-loop arrival process and account for
every request exactly.

One function, shared by the ``BENCH_SCENARIO=serve`` perf cell and the
seeded concurrency soaks in ``tests/test_serving.py``: each request gets
its own submitting thread released at its
:func:`~kubernetes_rescheduling_tpu.bench.loadgen.open_loop_arrivals`
offset (submission never waits on completion — the open-loop regime
where tail latency and shedding mean something), and the returned block
carries the exact-accounting identity the soak tests pin::

    placed + no_candidate + shed + timed_out == submitted

Latency percentiles here are computed from THIS soak's completed
requests only (the engine's own rolling window is cross-traffic), so a
bench cell's reading is not polluted by its warmup.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Sequence

import numpy as np


def run_serve_soak(
    engine,
    services: Sequence[str],
    arrivals: Sequence[float],
    *,
    deadline_ms: float | None = None,
) -> dict[str, Any]:
    """Submit ``len(arrivals)`` requests open-loop (request ``i`` enters
    at offset ``arrivals[i]`` seconds, service round-robin over
    ``services``) and block until every outcome lands. Returns the
    accounting/latency block; raises ``RuntimeError`` if the exact-
    accounting identity fails (a lost or double-counted request is a
    bug, never a reading)."""
    if not services:
        raise ValueError("run_serve_soak needs at least one service name")
    n = len(arrivals)
    results: list[Any] = [None] * n
    start = time.perf_counter()

    def submit(i: int) -> None:
        delay = float(arrivals[i]) - (time.perf_counter() - start)
        if delay > 0:
            time.sleep(delay)
        results[i] = engine.place(
            services[i % len(services)], deadline_ms=deadline_ms
        )

    threads = [
        threading.Thread(target=submit, args=(i,), daemon=True)
        for i in range(n)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall_s = time.perf_counter() - start

    outcomes: dict[str, int] = {}
    shed_reasons: dict[str, int] = {}
    totals_ms: list[float] = []
    for r in results:
        outcomes[r.outcome] = outcomes.get(r.outcome, 0) + 1
        if r.shed_reason is not None:
            shed_reasons[r.shed_reason] = shed_reasons.get(r.shed_reason, 0) + 1
        if r.outcome in ("placed", "no_candidate"):
            totals_ms.append(r.timings_ms["total"])
    placed = outcomes.get("placed", 0)
    answered = placed + outcomes.get("no_candidate", 0)
    shed = outcomes.get("shed", 0)
    timed_out = outcomes.get("timeout", 0)
    if answered + shed + timed_out != n:
        raise RuntimeError(
            f"serving accounting violated: placed+no_candidate={answered} "
            f"+ shed={shed} + timeout={timed_out} != submitted={n}"
        )
    q = (
        np.percentile(np.asarray(totals_ms), [50, 95, 99])
        if totals_ms
        else (0.0, 0.0, 0.0)
    )
    return {
        "submitted": n,
        "outcomes": outcomes,
        "shed_reasons": shed_reasons,
        "placed": placed,
        "answered": answered,
        "shed": shed,
        "timed_out": timed_out,
        "wall_s": wall_s,
        "placements_per_sec": placed / wall_s if wall_s > 0 else 0.0,
        "p50_ms": float(q[0]),
        "p95_ms": float(q[1]),
        "p99_ms": float(q[2]),
        "results": results,
    }
