"""Result charts — the reference publishes three normalized bar charts
(result/*.png: node CPU-std, communication cost, response time; SURVEY.md §6)
but not the script that made them. This module regenerates all three from a
harness ``summary.json``, with the same normalizations:

- node CPU-std:        Before = 1.0   (reference result/Node standard.png)
- communication cost:  spread = 1.0   (reference result/communication cost.png)
- response time:       Before = 1.0   (reference result/responsetime.png)

Design: one measure across algorithms → single-series bars, one neutral hue
with the CAR/global bars accented, direct value labels, no legend (the title
names the single series), light grid behind thin bars.
"""

from __future__ import annotations

import json
from pathlib import Path

import numpy as np

_BAR = "#9aa5b1"      # neutral series hue
_ACCENT = "#4269d0"   # the subject policies (communication/global)
_INK = "#2b2f36"


def _plot_bar(ax, labels, values, title, accent_on=("communication", "global")):
    import matplotlib

    xs = np.arange(len(labels))
    colors = [
        _ACCENT if any(l == a or l.startswith(f"{a} ") for a in accent_on) else _BAR
        for l in labels
    ]
    ax.bar(xs, values, width=0.62, color=colors, zorder=2)
    for x, v in zip(xs, values):
        ax.text(x, v, f"{v:.2f}", ha="center", va="bottom", fontsize=9, color=_INK)
    ax.set_xticks(xs, labels, rotation=20, ha="right", fontsize=9)
    ax.set_title(title, fontsize=11, color=_INK, loc="left")
    ax.grid(axis="y", color="#e3e6ea", linewidth=0.8, zorder=0)
    ax.spines[["top", "right"]].set_visible(False)
    ax.tick_params(colors=_INK)
    ax.margins(y=0.15)


def merge_summaries(base: dict, extras: list[tuple[str, dict]]) -> dict:
    """One summary whose runs include labeled configuration variants.

    ``extras`` entries are ``(label, summary)``; their runs appear as
    ``"<algorithm> <label>"`` bars — how the wave-capped global
    configuration (``global_moves_cap=k``) shows up next to the uncapped
    one in the disruption chart. ``base``'s per-run-derived ``aggregate``
    is dropped rather than copied stale — the merged dict describes its
    runs, nothing else."""
    runs = list(base["runs"])
    for label, s in extras:
        for r in s["runs"]:
            runs.append({**r, "algorithm": f"{r['algorithm']} {label}"})
    return {k: v for k, v in base.items() if k != "aggregate"} | {"runs": runs}


def plot_summary(summary: dict | str | Path, out_dir: str | Path) -> list[Path]:
    """Write the three normalized charts from a harness summary.

    Accepts the summary dict or a path to ``summary.json``. Returns the
    written file paths.
    """
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    if not isinstance(summary, dict):
        summary = json.loads(Path(summary).read_text())

    runs = summary["runs"]
    algos = list(dict.fromkeys(r["algorithm"] for r in runs))

    def mean(algo, phase, metric):
        vals = [r[phase][metric] for r in runs if r["algorithm"] == algo]
        return float(np.mean(vals)) if vals else float("nan")

    before_std = float(np.mean([r["before"]["load_std"] for r in runs]))
    before_rt = float(np.mean([r["before"]["response_time_ms"] for r in runs]))
    spread_cost = mean("spread", "after", "communication_cost") if "spread" in algos else 1.0

    out = Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)
    written: list[Path] = []

    charts = [
        (
            "node_standard.png",
            "Node CPU-usage std-dev (Before = 1.0, lower is better)",
            [mean(a, "after", "load_std") / before_std if before_std else 0 for a in algos],
        ),
        (
            "communication_cost.png",
            "Communication cost (spread = 1.0, lower is better)",
            [
                mean(a, "after", "communication_cost") / spread_cost
                if spread_cost
                else 0
                for a in algos
            ],
        ),
        (
            "responsetime.png",
            "Avg response time (Before = 1.0, lower is better)",
            [mean(a, "after", "response_time_ms") / before_rt if before_rt else 0 for a in algos],
        ),
    ]

    # request-level stats (the reference's release1.sh:74-117 block): tail
    # latency after rescheduling and the disruption paid during it
    def load_mean(algo, phase, metric):
        vals = [
            r["load"][phase][metric]
            for r in runs
            if r["algorithm"] == algo and "load" in r
        ]
        return float(np.mean(vals)) if vals else float("nan")

    if any("load" in r for r in runs):
        charts += [
            (
                "tail_latency.png",
                "p95 response time after rescheduling (ms)",
                [load_mean(a, "after", "latency_p95_ms") for a in algos],
            ),
            (
                "disruption.png",
                "Requests failed while rescheduling (% of phase r2)",
                [100.0 * load_mean(a, "during", "error_rate") for a in algos],
            ),
        ]
    for fname, title, values in charts:
        fig, ax = plt.subplots(figsize=(6.4, 3.6), dpi=120)
        _plot_bar(ax, algos, values, title)
        fig.tight_layout()
        path = out / fname
        fig.savefig(path)
        plt.close(fig)
        written.append(path)
    return written


# the two frontier hues, shared by both panels (and both new charts)
_CAP_COLOR = "#1f77b4"   # wave-cap configs
_MC_COLOR = "#d62728"    # move-cost (disruption pricing) configs


def _is_move_cost(config_name: str) -> bool:
    return config_name.startswith("mc")


def plot_disruption_frontier(rows: list[dict], out_dir: str | Path) -> Path:
    """The disruption/quality frontier: wave capping vs move-cost pricing.

    ``rows`` are the measured µBench-matrix aggregates (scripts/frontier.py
    output): each dict carries config / restarts / error_rate_during /
    communication_cost / response_time_ms. Two panels — restarts vs final
    comm cost (the frontier itself; marker AREA scales with the in-flight
    error rate during rescheduling) and response time (the end-user view;
    a config that avoids all disruption by never moving leaves the
    pile-up's queueing latency in place)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    is_mc = [_is_move_cost(r["config"]) for r in rows]
    colors = [_MC_COLOR if m else _CAP_COLOR for m in is_mc]
    fig, (ax1, ax2) = plt.subplots(1, 2, figsize=(11, 4.2))
    for r, m, color in zip(rows, is_mc, colors):
        ax1.scatter(r["restarts"], r["communication_cost"], c=color,
                    marker="o" if m else "s",
                    s=40 + 600 * r.get("error_rate_during", 0.0), zorder=3)
        ax1.annotate(r["config"], (r["restarts"], r["communication_cost"]),
                     textcoords="offset points", xytext=(6, 4), fontsize=8)
    ax1.set_xlabel("pods restarted during rescheduling")
    ax1.set_ylabel("final communication cost")
    ax1.set_title(
        "disruption vs quality — marker area = error rate during\n"
        "(red: --move-cost, blue: wave cap)"
    )
    ax1.grid(alpha=0.3)

    labels = [r["config"] for r in rows]
    lat = [r["response_time_ms"] for r in rows]
    ax2.bar(range(len(rows)), lat, color=colors)
    ax2.set_xticks(range(len(rows)))
    ax2.set_xticklabels(labels, rotation=30, ha="right", fontsize=8)
    ax2.set_ylabel("response time after (ms)")
    ax2.set_title("what the user sees")
    ax2.grid(axis="y", alpha=0.3)
    fig.tight_layout()
    path = out_dir / "disruption_frontier.png"
    fig.savefig(path, dpi=120)
    plt.close(fig)
    return path


def plot_scale_curve(points: list[dict], out_dir: str | Path) -> Path:
    """Device ms/round vs problem scale for the dense and sparse solvers.

    ``points``: dicts with scale (str label), services (int), solver
    ("dense"/"sparse"), ms (positive float — the y axis is log-scale)
    or None (= cannot allocate)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    fig, ax = plt.subplots(figsize=(6.4, 4.2))
    for solver, color in (("dense", _CAP_COLOR), ("sparse", _MC_COLOR)):
        pts = [p for p in points if p["solver"] == solver and p["ms"] is not None]
        ax.plot(
            [p["services"] for p in pts],
            [p["ms"] for p in pts],
            "o-",
            color=color,
            label=f"{solver} pair weights",
        )
        for p in pts:
            ax.annotate(
                f"{p['scale']}\n{p['ms']:.0f} ms",
                (p["services"], p["ms"]),
                textcoords="offset points", xytext=(6, -2), fontsize=8,
            )
    dead = [p for p in points if p["ms"] is None]
    for i, p in enumerate(dead):
        ax.annotate(
            f"{p['scale']}: {p['solver']} cannot allocate",
            (0.02, 0.93 - 0.05 * i),
            xycoords="axes fraction", fontsize=8, color="gray",
        )
    ax.set_xscale("log")
    ax.set_yscale("log")
    ax.set_xlabel("services")
    ax.set_ylabel("device ms/round (9 sweeps)")
    ax.set_title("solver scale curve (v5e-1)")
    ax.grid(alpha=0.3, which="both")
    ax.legend()
    fig.tight_layout()
    path = out_dir / "scale_curve.png"
    fig.savefig(path, dpi=120)
    plt.close(fig)
    return path


# validated categorical slots (dataviz reference palette, fixed order —
# color follows the CONFIG identity, never its rank in a given chart)
_CAT = ("#2a78d6", "#eb6834", "#1baf7a", "#eda100")


def plot_optimality_gap(rows, out_dir) -> "Path":
    """Round-5 solver-quality chart: % above the MILP optimum/incumbent
    per capacity-binding instance, grouped by solver configuration.

    ``rows``: [{"instance": "40x5", "configs": {label: gap_pct, ...}}, ...]
    with every row carrying the SAME config labels (fixed series order).
    A dashed line marks the 10% target; negative bars mean the solver
    beat the MILP's own incumbent."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    from pathlib import Path

    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    labels = list(rows[0]["configs"].keys())
    n_cfg = len(labels)
    xs = np.arange(len(rows))
    width = 0.8 / n_cfg
    fig, ax = plt.subplots(figsize=(7.2, 3.6))
    for ci, lab in enumerate(labels):
        vals = [r["configs"][lab] for r in rows]
        pos = xs + (ci - (n_cfg - 1) / 2) * width
        ax.bar(pos, vals, width=width * 0.92, color=_CAT[ci], zorder=2,
               label=lab)
        for x, v in zip(pos, vals):
            ax.text(x, v + (0.3 if v >= 0 else -1.2), f"{v:.1f}",
                    ha="center", va="bottom", fontsize=7.5, color=_INK)
    ax.axhline(10.0, color="#9aa5b1", linewidth=1.0, linestyle="--", zorder=1)
    ax.text(len(rows) - 0.5, 10.3, "10% target", fontsize=8, color="#6b7280",
            ha="right")
    ax.axhline(0.0, color=_INK, linewidth=0.8, zorder=1)
    ax.set_xticks(xs, [r["instance"] for r in rows], fontsize=9)
    ax.set_ylabel("% above MILP optimum / incumbent", fontsize=9, color=_INK)
    ax.set_title(
        "optimality gap, capacity-binding instances (round 5)",
        fontsize=11, color=_INK, loc="left",
    )
    ax.grid(axis="y", color="#e3e6ea", linewidth=0.8, zorder=0)
    ax.spines[["top", "right"]].set_visible(False)
    ax.tick_params(colors=_INK)
    ax.legend(fontsize=8, frameon=False, ncols=2)
    ax.margins(y=0.18)
    fig.tight_layout()
    path = out_dir / "optimality_gap.png"
    fig.savefig(path, dpi=120)
    plt.close(fig)
    return path
