"""Experiment harness — the reference's Bash pipeline, rebuilt.

- ``controller`` — drives any Backend round by round (the live analogue of
  ``solver.run_rounds``), with decision-latency measurement.
- ``sinks`` — CSV metric files compatible with the reference's
  ``node_std.csv`` / ``communication_cost.csv`` plus structured JSONL.
- ``loadgen`` — request-level load generation: the reference's curl fleet
  (release1.sh/release2.sh) as a vectorized on-device simulation with
  success/error counts and latency percentiles.
- ``harness`` — the algorithm × repeat experiment matrix with per-session
  result directories (reference auto_full_pipeline_repeat.sh).
- ``fleet`` — the multiplexed fleet round loop: one boundary + breaker
  per tenant, ONE vmap-batched device solve per round for the whole
  fleet (ROADMAP item 1's controller-architecture refactor).
"""

from kubernetes_rescheduling_tpu.bench.controller import ControllerResult, run_controller
from kubernetes_rescheduling_tpu.bench.fleet import FleetResult, run_fleet_controller
from kubernetes_rescheduling_tpu.bench.harness import ExperimentConfig, run_experiment
from kubernetes_rescheduling_tpu.bench.loadgen import (
    LoadGenConfig,
    LoadGenerator,
    RequestStats,
)
from kubernetes_rescheduling_tpu.bench.sinks import CsvSink, JsonlSink

__all__ = [
    "ControllerResult",
    "run_controller",
    "FleetResult",
    "run_fleet_controller",
    "CsvSink",
    "JsonlSink",
    "ExperimentConfig",
    "run_experiment",
    "LoadGenConfig",
    "LoadGenerator",
    "RequestStats",
]
