"""Snapshot admission: the controller stops trusting the Metrics API.

Every ``boundary.monitor()`` result the control loops consume passes
through an :class:`AdmissionGuard` BEFORE it can touch device state
(statically enforced by ``scripts/check_snapshot_admission.py``, the
sibling of ``check_boundary_retry.py``). The reference CAR loop — and
this stack until now — fed whatever the Metrics API said straight into
the solver: one NaN/Inf/negative load silently poisons the solver score,
the forecast RLS state, the attribution sums, and the perf ledger, and
nothing downstream ever complains (NaN compares false everywhere).

The guard classifies every snapshot into one of three outcomes:

- **admit unchanged** — the clean-path contract: a snapshot with nothing
  wrong is returned AS THE SAME OBJECT, so a fault-free run is
  bit-identical to the pre-admission controller (golden-pinned).
- **repair and admit** — per-entry quarantine: non-finite or negative
  readings are replaced with the pod's/node's LAST-GOOD value (matched
  by name across snapshots; 0 for a never-seen entry), and readings
  impossibly above any node's capacity are clamped to it. Every repaired
  entry counts in ``admission_quarantined_total{field,reason}``.
- **reject** — structural breakage no per-entry repair can launder:
  duplicate pod names among valid pods, pod→node references outside the
  node table, or a snapshot needing more than
  ``reconcile.max_quarantine_frac`` of its valid pods quarantined. A
  rejection returns ``None`` — the boundary protocol's existing failure
  signal — and charges the boundary (``on_reject``) so the PR-2
  machinery takes over: the round degrades on the last good snapshot.
  Persistently garbage data reads as counted degraded rounds, NOT an
  open breaker — each delivery succeeded at the transport level, so the
  backend is reachable-but-lying, degraded service rather than dead
  (see ``BoundaryClient.admission_reject``). Counted
  ``admission_rejected_total{reason}``.

Host-side by design: no jitted compute, no tracing — the guard reads
every field it classifies through ONE batched ``jax.device_get`` per
admit (the ``round_end.fence`` idiom; on a real rig per-field
``np.asarray`` would be a stack of tiny tunnel round trips in the hot
monitor path) and, on the repair path, hands numpy arrays to
``state.replace`` (JAX converts at the next dispatch). This is the
designated host-ingest transfer, deliberately outside the
``check_apply_boundary`` round-end budget: it runs on the monitor
result BEFORE the snapshot becomes device state. The device side
carries its own last-resort finite guards on the solver inputs
(``solver.round_loop``), mirroring the forecast plane's never-NaN
discipline — but the host guard is the one that keeps poisoned values
out of last-good caches, telemetry, and the ledger.
"""

from __future__ import annotations

from typing import Callable

import jax
import numpy as np

from kubernetes_rescheduling_tpu.core.state import UNASSIGNED, ClusterState
from kubernetes_rescheduling_tpu.telemetry.registry import get_registry

# snapshot fields the guard quarantines per entry, with their entity axis
POD_FIELDS = ("pod_cpu", "pod_mem")
NODE_FIELDS = ("node_cpu_cap", "node_mem_cap", "node_base_cpu", "node_base_mem")

# classification reasons (the `reason` label values)
REASON_NAN = "nan"
REASON_INF = "inf"
REASON_NEGATIVE = "negative"
REASON_OVER_CAPACITY = "over_capacity"

REJECT_DUPLICATE_POD = "duplicate_pod"
REJECT_UNKNOWN_NODE = "unknown_node"
REJECT_QUARANTINE_OVERFLOW = "quarantine_overflow"


class AdmissionGuard:
    """Classify-and-handle for monitor snapshots (see module docstring).

    One guard per control loop (or per fleet tenant): it carries the
    last-good per-pod/per-node readings the quarantine path reuses, and
    accumulates per-round counts for ``RoundRecord.reconcile`` via
    :meth:`take_info`. ``on_reject(reason)`` — typically
    ``BoundaryClient.admission_reject`` — charges a rejection to the
    boundary's failure machinery.
    """

    def __init__(
        self,
        cfg,
        *,
        registry=None,
        logger=None,
        on_reject: Callable[[str], None] | None = None,
    ) -> None:
        self.cfg = cfg
        self.registry = registry
        self.logger = logger
        self.on_reject = on_reject
        # last-good readings: the previous ADMITTED snapshot's arrays plus
        # a lazily built name→index map (names are the stable identity —
        # pod tables shift index under churn). Stored as arrays, not a
        # per-entry dict, so the clean path costs O(1) python per admit.
        self._last_pod: tuple[tuple, np.ndarray, dict[str, np.ndarray]] | None = None
        self._last_node: tuple[tuple, np.ndarray, dict[str, np.ndarray]] | None = None
        self._pod_index: dict[str, int] | None = None
        self._node_index: dict[str, int] | None = None
        # duplicate-name memo, keyed by names-tuple identity (static
        # between churn waves): duplicates among VALID pods require
        # duplicates in the full tuple, so a unique tuple lets every
        # admit skip the per-valid-pod scan entirely
        self._names_dup: tuple[tuple, bool] | None = None
        # counts since the last take_info(), keyed "field:reason" /
        # "rejected:reason" — the per-round record payload
        self._info: dict[str, int] = {}
        # the last ADMITTED snapshot object and the host arrays already
        # pulled for it — the intent ledger's observe() reuses them
        # (host_arrays) instead of paying a second device->host transfer
        # for the same snapshot in the same round
        self._admitted: tuple[object, dict[str, np.ndarray]] | None = None

    # ---- bookkeeping ----

    def _reg(self):
        return self.registry if self.registry is not None else get_registry()

    def _quarantine_count(self, field: str, reason: str, n: int) -> None:
        if n <= 0:
            return
        self._reg().counter(
            "admission_quarantined_total",
            "snapshot readings repaired by the admission guard "
            "(last-good reuse or capacity clamp), by field and reason",
            labelnames=("field", "reason"),
        ).labels(field=field, reason=reason).inc(n)
        key = f"{field}:{reason}"
        self._info[key] = self._info.get(key, 0) + n

    def _reject(self, reason: str, **detail) -> None:
        self._reg().counter(
            "admission_rejected_total",
            "monitor snapshots rejected whole by the admission guard "
            "(the round degrades on the last good snapshot)",
            labelnames=("reason",),
        ).labels(reason=reason).inc()
        key = f"rejected:{reason}"
        self._info[key] = self._info.get(key, 0) + 1
        if self.logger is not None:
            self.logger.warn("admission_reject", reason=reason, **detail)
        if self.on_reject is not None:
            self.on_reject(reason)

    def take_info(self) -> dict[str, int]:
        """Counts accumulated since the last call (the round's
        ``reconcile["admission"]`` payload); empty dict when clean."""
        info, self._info = self._info, {}
        return info

    # ---- last-good lookup (name-keyed across snapshots) ----

    def _last_good(self, kind: str, name: str | None, field: str) -> float:
        """The previous admitted snapshot's reading for this pod/node, 0.0
        for a never-seen (or then-invalid) entry."""
        stored = self._last_pod if kind == "pod" else self._last_node
        if stored is None or name is None:
            return 0.0
        names, valid, arrays = stored
        index = self._pod_index if kind == "pod" else self._node_index
        if index is None:
            index = {n: i for i, n in enumerate(names)}
            if kind == "pod":
                self._pod_index = index
            else:
                self._node_index = index
        i = index.get(name)
        if i is None or i >= len(valid) or not bool(valid[i]):
            return 0.0
        return float(arrays[field][i])

    # ---- the guard ----

    def admit(self, state: ClusterState | None) -> ClusterState | None:
        """Classify one monitor result. ``None`` passes through (the
        boundary already charged that failure); a clean snapshot returns
        IDENTICALLY (same object — the bit-identity contract); a
        repairable one returns a patched copy; a structurally broken one
        returns ``None`` after charging the boundary."""
        if state is None or not getattr(self.cfg, "admission", True):
            return state

        # ONE batched host materialization for everything the guard
        # classifies (the round_end.fence idiom — per-field np.asarray
        # would be a stack of tiny device->host round trips per monitor)
        host = jax.device_get(
            {
                "pod_valid": state.pod_valid,
                "pod_node": state.pod_node,
                # pod_service rides the same batched pull for the intent
                # ledger's observe() (see host_arrays), not for admission
                "pod_service": state.pod_service,
                "node_valid": state.node_valid,
                **{f: getattr(state, f) for f in POD_FIELDS + NODE_FIELDS},
            }
        )
        pod_valid = host["pod_valid"]
        vidx = np.flatnonzero(pod_valid)
        pod_names = state.pod_names

        # structural rejects first: no per-entry repair can fix identity.
        # The per-valid-pod scan only runs when the (memoized) full names
        # tuple actually contains duplicates — the clean path stays O(1)
        # python here
        if self._names_dup is None or self._names_dup[0] is not pod_names:
            self._names_dup = (
                pod_names, len(pod_names) != len(set(pod_names))
            )
        if self._names_dup[1]:
            names_at = [
                pod_names[int(i)] for i in vidx if int(i) < len(pod_names)
            ]
            if len(names_at) != len(set(names_at)):  # name the culprit
                seen: set[str] = set()
                for name in names_at:
                    if name in seen:
                        self._reject(REJECT_DUPLICATE_POD, pod=name)
                        return None
                    seen.add(name)
        pod_node = host["pod_node"]
        if vidx.size:
            refs = pod_node[vidx]
            # the node TABLE is the name tuple — bucketed capacity pads
            # node arrays beyond it, and a ref into a padded slot is as
            # unknown as one past the array (no such node exists to name)
            n_known = len(state.node_names)
            bad_refs = (refs >= n_known) | (refs < UNASSIGNED)
            if bool(np.any(bad_refs)):
                bad = vidx[bad_refs]
                self._reject(
                    REJECT_UNKNOWN_NODE,
                    pods=[
                        pod_names[int(i)] if int(i) < len(pod_names) else int(i)
                        for i in bad[:4]
                    ],
                )
                return None

        node_valid = host["node_valid"]
        node_names = state.node_names

        # plan every repair BEFORE applying any: the overflow check must
        # see the whole damage picture, and a rejected snapshot must not
        # have half-counted quarantines
        repairs: dict[str, np.ndarray] = {}
        planned: list[tuple[str, str, int]] = []  # (field, reason, count)
        quarantined_pods: set[int] = set()

        def plan_node_field(field: str) -> np.ndarray:
            # classify on the pulled array; copy ONLY when repairing —
            # the clean path must not memcpy every field every monitor
            src = host[field]
            bad = node_valid & (~np.isfinite(src) | (src < 0.0))
            if not bool(bad.any()):
                return src
            arr = np.array(src)
            for reason, mask in (
                (REASON_NAN, np.isnan(arr)),
                (REASON_INF, np.isinf(arr)),
                (REASON_NEGATIVE, np.isfinite(arr) & (arr < 0.0)),
            ):
                n = int((bad & mask).sum())
                if n:
                    planned.append((field, reason, n))
            for i in np.flatnonzero(bad):
                name = node_names[int(i)] if int(i) < len(node_names) else None
                arr[i] = self._last_good("node", name, field)
            repairs[field] = arr
            return arr

        node_arrays = {f: plan_node_field(f) for f in NODE_FIELDS}

        # the physical ceilings an honest reading cannot exceed: one pod
        # cannot use more than the biggest node's whole capacity
        alive = node_valid & (node_arrays["node_cpu_cap"] > 0)
        cpu_ceiling = float(
            np.max(node_arrays["node_cpu_cap"][alive], initial=0.0)
        )
        mem_ceiling = float(
            np.max(
                node_arrays["node_mem_cap"][
                    node_valid & (node_arrays["node_mem_cap"] > 0)
                ],
                initial=0.0,
            )
        )
        ceilings = {"pod_cpu": cpu_ceiling, "pod_mem": mem_ceiling}

        def plan_pod_field(field: str) -> None:
            # same clean-path contract as plan_node_field: classify on
            # the pulled array, copy only when something needs repair
            src = host[field]
            nan = pod_valid & np.isnan(src)
            inf = pod_valid & np.isinf(src)
            neg = pod_valid & np.isfinite(src) & (src < 0.0)
            ceiling = ceilings[field]
            over = (
                pod_valid
                & np.isfinite(src)
                & (src >= 0.0)
                & (src > ceiling)
                if ceiling > 0
                else np.zeros_like(pod_valid)
            )
            if not bool((nan | inf | neg | over).any()):
                return
            arr = np.array(src)
            for reason, mask in (
                (REASON_NAN, nan),
                (REASON_INF, inf),
                (REASON_NEGATIVE, neg),
            ):
                n = int(mask.sum())
                if n:
                    planned.append((field, reason, n))
                for i in np.flatnonzero(mask):
                    name = (
                        pod_names[int(i)] if int(i) < len(pod_names) else None
                    )
                    good = self._last_good("pod", name, field)
                    if ceiling > 0.0 and good > ceiling:
                        # last-good was admitted under a LARGER node pool
                        # (churn since shrank the ceiling): the
                        # replacement must honor the same over-capacity
                        # invariant raw readings do. Still one reading,
                        # one count — under its nan/inf/negative reason
                        good = ceiling
                    arr[i] = good
                    quarantined_pods.add(int(i))
            n_over = int(over.sum())
            if n_over:
                planned.append((field, REASON_OVER_CAPACITY, n_over))
                arr[over] = ceiling
                quarantined_pods.update(int(i) for i in np.flatnonzero(over))
            repairs[field] = arr

        for f in POD_FIELDS:
            plan_pod_field(f)

        if quarantined_pods and vidx.size:
            frac = len(quarantined_pods) / float(vidx.size)
            if frac > self.cfg.max_quarantine_frac:
                # a mostly-fabricated metrics wave: repairing it
                # entry-by-entry would launder garbage into 'last good'
                self._reject(
                    REJECT_QUARANTINE_OVERFLOW,
                    quarantined=len(quarantined_pods),
                    valid_pods=int(vidx.size),
                    frac=round(frac, 4),
                )
                return None

        if repairs:
            for field, reason, n in planned:
                self._quarantine_count(field, reason, n)
            if self.logger is not None:
                self.logger.warn(
                    "admission_quarantine",
                    repaired={f"{f}:{r}": n for f, r, n in planned},
                )
            state = state.replace(**repairs)

        # last-good refreshes from the ADMITTED (post-repair) values —
        # quarantine replacements are by construction values that
        # themselves passed admission — reusing the host arrays already
        # pulled above (repaired fields substitute their patched copy)
        self._remember(
            state, host["pod_valid"], host["node_valid"],
            {f: repairs.get(f, host[f]) for f in POD_FIELDS + NODE_FIELDS},
        )
        # identity fields are never repaired, so the pulled arrays stay
        # valid for the (possibly replaced) admitted object
        self._admitted = (
            state,
            {
                k: host[k]
                for k in ("pod_valid", "pod_node", "pod_service", "node_valid")
            },
        )
        return state

    def host_arrays(self, state) -> dict[str, np.ndarray] | None:
        """The host copies of ``pod_valid``/``pod_node``/``pod_service``/
        ``node_valid`` pulled when ``state`` was admitted — ``None``
        unless ``state`` IS (object identity) the last admitted snapshot,
        so a stale or device-side-mutated state can never match."""
        if self._admitted is not None and self._admitted[0] is state:
            return self._admitted[1]
        return None

    def _remember(
        self,
        state: ClusterState,
        pod_valid: np.ndarray,
        node_valid: np.ndarray,
        arrays: dict[str, np.ndarray],
    ) -> None:
        """Store the admitted snapshot's host arrays as last-good. O(1)
        python: arrays are stored as-is, the name→index maps rebuild
        lazily and only when the name tuples actually change identity
        (they are static between churn waves)."""
        if self._last_pod is None or self._last_pod[0] is not state.pod_names:
            self._pod_index = None
        self._last_pod = (
            state.pod_names,
            pod_valid,
            {f: arrays[f] for f in POD_FIELDS},
        )
        if (
            self._last_node is None
            or self._last_node[0] is not state.node_names
        ):
            self._node_index = None
        self._last_node = (
            state.node_names,
            node_valid,
            {f: arrays[f] for f in NODE_FIELDS},
        )
