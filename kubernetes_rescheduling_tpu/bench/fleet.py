"""The multiplexed fleet round loop — one device plane, N tenants.

``run_controller`` is one-backend-one-loop: per round it dispatches one
decision kernel, pays the per-solve fixed cost once, and serves one
cluster. :func:`run_fleet_controller` refactors that into a MULTIPLEXED
round loop for N same-shaped tenants:

- **one** :class:`~bench.boundary.BoundaryClient` + circuit breaker +
  retry budget **per tenant** — every tenant keeps its own failure
  domain, retry clock (the backend's own ``advance``), and degraded/skip
  semantics;
- **one shared device plane** — per round, ONE batched
  :func:`solver.fleet.fleet_solve` dispatch decides for every active
  tenant (vmap plane; ``parallel.fleet.fleet_solve_dp`` shards the
  tenant axis one-per-device instead), and ONE batched
  :func:`solver.fleet.fleet_metrics` dispatch closes the round's
  reporting — the per-solve fixed cost RESULTS.md round 5 measured as
  the dominant term amortizes across the fleet;
- **per-tenant round streams** — each tenant accumulates its own
  :class:`~bench.controller.RoundRecord` list inside its own
  :class:`~bench.controller.ControllerResult`, with the solo loop's
  accounting invariant per tenant:
  ``max_rounds == len(result.rounds) + result.skipped_rounds``.

Isolation is the design center: a tenant whose breaker is open (or whose
backend is dark) contributes a COUNTED skip and a masked slot in the
batch — the batched kernel's rows are independent per tenant (vmap), so
the other tenants' decisions are bit-exact with what a solo loop would
have made (test-pinned: a seeded chaos soak on one tenant leaves every
other tenant's executed-round counts and comm-cost trajectories
identical to a no-chaos run).

Decision keys derive per tenant as ``fold_in(key, tenant_index)`` and
per round exactly as the solo loop derives them, so
``run_fleet_controller(fleet, cfg, key=k)`` makes the same decisions as
N solo ``run_controller(backend_t, cfg, key=fold_in(k, t))`` runs.

Scope (fleet v2): THREE decision planes batch over the tenant axis —

- the GREEDY kernel (one move per tenant per round, PR 6);
- the PROACTIVE kernel: per-tenant forecast RLS state stacked
  ``[T, N, ...]`` (``forecast.fleet``), ONE forecast dispatch + ONE
  predicted-state decide dispatch per round, the diag matrix riding the
  round's single counted bundle pull;
- the GLOBAL solver (``algorithm='global'`` / ``moves_per_round='all'``,
  dense backend): ONE batched solve re-places every service in every
  tenant (``solver.fleet_global``, restart fan-out included), the
  decided per-tenant move lists coming home in the same single pull.

Tenants may have HETEROGENEOUS shapes: at startup the loop fits one
shared power-of-two shape bucket over every tenant's live counts
(``elastic.buckets.bucket_capacity``) and pins each backend's snapshot
padding to it, so the stacked batch compiles once and padded slots stay
inert (the mask-twin contract — per-tenant decisions bit-exact vs an
unpadded solo run). Pod-unit solves, sparse-backend solves, and integer
wave caps keep the solo loop (``config.validate()`` names the reason
for each). Checkpoint/resume is solo-only for now.
"""

from __future__ import annotations

import dataclasses
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_rescheduling_tpu.backends.base import MoveRequest
from kubernetes_rescheduling_tpu.backends.chaos import with_chaos
from kubernetes_rescheduling_tpu.backends.fleet import FleetBackend
from kubernetes_rescheduling_tpu.backends.k8s import PlacementMechanism
from kubernetes_rescheduling_tpu.bench.admission import AdmissionGuard
from kubernetes_rescheduling_tpu.bench.boundary import (
    HALF_OPEN,
    OPEN,
    BoundaryClient,
    CircuitBreaker,
)
from kubernetes_rescheduling_tpu.bench.controller import (
    ControllerResult,
    RoundRecord,
    observe_wall_round,
    pipeline_depth_gauge,
    pipeline_overlap_gauge,
)
from kubernetes_rescheduling_tpu.bench.reconcile import (
    IntentLedger,
    move_intent,
    reconcile_round_block,
)
from kubernetes_rescheduling_tpu.bench.round_end import block
from kubernetes_rescheduling_tpu.config import RescheduleConfig
from kubernetes_rescheduling_tpu.elastic.buckets import (
    bucket_capacity,
    device_graph,
    device_view,
)
from kubernetes_rescheduling_tpu.elastic.engine import make_fleet_churn
from kubernetes_rescheduling_tpu.policies import POLICY_IDS
from kubernetes_rescheduling_tpu.policies.proactive import scoring_policy
from kubernetes_rescheduling_tpu.solver.fleet import (
    ROW_MOST,
    ROW_SERVICE,
    ROW_TARGET,
    ROW_VICTIM,
    fleet_metrics,
    fleet_solve,
    fleet_solve_proactive,
    stack_tenants,
)
from kubernetes_rescheduling_tpu.solver.fleet_global import (
    decode_fleet_global,
    fleet_global_solve,
)
from kubernetes_rescheduling_tpu.solver.global_solver import (
    GlobalSolverConfig,
)
from kubernetes_rescheduling_tpu.forecast.model import DIAG_SIZE
from kubernetes_rescheduling_tpu.telemetry import get_registry, pull, span
from kubernetes_rescheduling_tpu.telemetry.fleet_rollup import (
    TenantSeries,
    decode_fleet_bundle,
    decode_rollup,
    dispatch_fleet_bundle,
    fleet_health_block,
    publish_rollup,
    rollup_event,
)
from kubernetes_rescheduling_tpu.utils.logging import StructuredLogger


@dataclass
class FleetResult:
    """Per-tenant round streams plus fleet-level accounting."""

    tenants: tuple[str, ...] = ()
    results: dict[str, ControllerResult] = field(default_factory=dict)
    # batched fleet_solve dispatches (== rounds with >= 1 active tenant)
    batched_solves: int = 0
    # total fenced device time across those dispatches
    device_solve_s: float = 0.0

    @property
    def total_rounds(self) -> int:
        return sum(len(r.rounds) for r in self.results.values())

    @property
    def total_skipped(self) -> int:
        return sum(r.skipped_rounds for r in self.results.values())

    @property
    def amortized_solve_ms_per_tenant_round(self) -> float:
        """Fenced batched-solve ms amortized over executed tenant-rounds
        — the fleet headline quantity (one sequential loop pays the whole
        per-dispatch fixed cost per tenant; this is what batching buys)."""
        n = self.total_rounds
        return (self.device_solve_s / n * 1e3) if n else 0.0


class _Tenant:
    """Host-side runtime of one tenant: its boundary, last good snapshot,
    graph, key stream, and result accumulator."""

    def __init__(
        self, name, backend, config, *, logger, registry, key,
        tenant_series=None,
    ):
        self.name = name
        self.breaker = CircuitBreaker(
            max_consecutive_failures=config.max_consecutive_failures,
            cooldown_rounds=config.breaker_cooldown_rounds,
            logger=logger,
            registry=registry,
        )
        self.boundary = BoundaryClient(
            backend,
            policy=config.retry,
            breaker=self.breaker,
            failure_budget_per_round=config.failure_budget_per_round,
            logger=logger,
            registry=registry,
            tenant=name,
        )
        # the reconciliation & admission plane, PER TENANT: each tenant's
        # snapshots pass its own guard (last-good caches must never
        # cross-pollinate between clusters) and each tenant's moves land
        # in its own intent ledger (the drift gauge goes tenant-labeled)
        self.guard = (
            AdmissionGuard(
                config.reconcile,
                registry=registry,
                logger=logger,
                on_reject=self.boundary.admission_reject,
            )
            if config.reconcile.admission
            else None
        )
        self.ledger = (
            IntentLedger(
                config.reconcile,
                registry=registry,
                logger=logger,
                tenant=name,
                # the budget-gated gateway: over-budget fleets suppress
                # the per-tenant drift gauge (the rollup's drift
                # dimension carries the signal instead)
                tenant_series=tenant_series,
            )
            if config.reconcile.enabled
            else None
        )
        self.graph = self.boundary.comm_graph()
        self.key = key
        self.state = None
        # elastic churn debt: this tenant's carried snapshot predates
        # applied churn (or a fleet-wide bucket promotion) and must be
        # re-monitored — behind the breaker gate — before it can run
        self.remask = False
        # previous round's unrepaired drift (the solo loop's _last_drift
        # rule: a convergence round carries an explicit drift_pods=0)
        self.last_drift = 0
        self.result = ControllerResult()

    def health_row(self) -> dict:
        return {
            "breaker": self.breaker.state,
            "rounds": len(self.result.rounds),
            "skipped_rounds": self.result.skipped_rounds,
            "degraded_rounds": self.result.degraded_rounds,
        }


def _admitted_monitor(t: _Tenant):
    """THE fleet loop's monitor wrapper: one tenant's snapshot passes
    that tenant's admission guard before it can touch device state
    (statically enforced by ``scripts/check_snapshot_admission.py`` —
    this is the fleet loop's only legal ``.monitor()`` call site). A
    rejection returns ``None``, charging that tenant's boundary."""
    out = t.boundary.monitor()
    if t.guard is not None:
        out = t.guard.admit(out)
    return out


def _pull_round_bundle(arr, site: str):
    """The fleet loop's designated round-end transfer sites (the
    ``check_apply_boundary`` allowlist): one counted pull per bundle —
    the packed decisions+hazard bundle and the batched metrics pair."""
    return pull(arr, site=site)


# per-round decision keys for the whole fleet in ONE dispatch: each
# tenant's key derives exactly as the solo greedy round derives its first
# decide key (fold_in the round index, then split and take the second
# row) — bit-exact with N solo runs under jax_threefry_partitionable
@jax.jit
def _round_keys(tenant_keys: jax.Array, rnd: jax.Array) -> jax.Array:
    return jax.vmap(
        lambda k: jax.random.split(jax.random.fold_in(k, rnd))[1]
    )(tenant_keys)


# the GLOBAL round's key rule: the solo loop hands fold_in(key, round)
# straight to the solver (no split — _global_round consumes the round
# key whole), so the batched solve must too for restart/sweep parity
@jax.jit
def _round_keys_global(tenant_keys: jax.Array, rnd: jax.Array) -> jax.Array:
    return jax.vmap(lambda k: jax.random.fold_in(k, rnd))(tenant_keys)


def _align_fleet_buckets(backends, *, floor: int, registry) -> dict | None:
    """Heterogeneous tenants: fit ONE shared power-of-two shape bucket
    over every tenant's live counts and pin each backend's snapshot
    padding to it, so ``stack_tenants`` sees one common shape and the
    batch compiles once. Same-shaped fleets are left untouched (the
    historical unpadded behavior — and its test pins — survive). Returns
    the shared capacities, or None when nothing needed aligning.

    Requires the sim mutator surface (``live_counts``/
    ``set_capacities``); a fleet of mismatched backends without it fails
    at ``stack_tenants`` with the existing sizing error."""
    counts = []
    for b in backends:
        raw = b
        while hasattr(raw, "inner"):  # chaos wrappers pass through
            raw = raw.inner
        if not (hasattr(raw, "live_counts") and hasattr(raw, "set_capacities")):
            return None
        counts.append(raw.live_counts())
    if len({tuple(sorted(c.items())) for c in counts}) <= 1:
        return None
    caps = {
        axis: bucket_capacity(max(c[axis] for c in counts), floor=floor)
        for axis in ("services", "nodes", "pods")
    }
    for b in backends:
        raw = b
        while hasattr(raw, "inner"):
            raw = raw.inner
        raw.set_capacities(
            node=caps["nodes"], pod=caps["pods"], service=caps["services"]
        )
    registry.gauge(
        "fleet_bucket_services",
        "shared fleet shape bucket: service capacity every tenant pads to",
    ).set(caps["services"])
    registry.gauge(
        "fleet_bucket_nodes",
        "shared fleet shape bucket: node capacity every tenant pads to",
    ).set(caps["nodes"])
    registry.gauge(
        "fleet_bucket_pods",
        "shared fleet shape bucket: pod capacity every tenant pads to",
    ).set(caps["pods"])
    return caps


def run_fleet_controller(
    fleet: FleetBackend,
    config: RescheduleConfig,
    *,
    key: jax.Array | None = None,
    logger: StructuredLogger | None = None,
    registry=None,
    ops=None,
    on_round=None,
    churn=None,
) -> FleetResult:
    """Run ``config.max_rounds`` multiplexed rounds over a fleet.

    ``config.fleet`` selects the device plane (``vmap`` | ``dp``) and —
    together with ``config.chaos`` — which tenants get fault injection:
    with a profile set, ``fleet.chaos_tenants`` wraps ONLY those tenant
    indices (empty = every tenant, the solo loop's semantics), each
    seeded ``chaos.seed + index`` so fault streams stay independent.

    ``on_round(tenant_name, record, state)`` fires per executed
    tenant-round (the harness's load-sustaining hook, tenant-labeled).

    ``ops`` attaches the live plane: ``/healthz`` grows a ``fleet`` block
    with one row per tenant (breaker state + round counts). A single
    tenant's open breaker reads as degraded service in that block — it
    does not 503 the whole endpoint.

    ``churn`` (``{tenant_index: ChurnEngine}``, or built from
    ``config.elastic`` via ``elastic.engine.make_fleet_churn``) applies
    seeded churn to the selected tenants between rounds. All engines
    share ONE set of shape buckets so the fleet stays stackable: a
    promotion re-pads every tenant (one counted retrace), while the
    untouched tenants' decisions stay bit-identical — the vmap rows are
    independent and padding is masked (test-pinned, like chaos
    isolation).
    """
    config = config.validate()
    if config.fleet.tenants and config.fleet.tenants != fleet.num_tenants:
        raise ValueError(
            f"config.fleet.tenants={config.fleet.tenants} but the fleet "
            f"backend has {fleet.num_tenants} tenants"
        )
    if not config.fleet.tenants:
        # enforce the full fleet gate even when the config's [fleet]
        # block is off (tenants=0) — the caller handed us a fleet
        # regardless, so run the ONE validation rule with the tenant
        # count filled in rather than a drifting local copy of it
        config = dataclasses.replace(
            config,
            fleet=dataclasses.replace(
                config.fleet, tenants=fleet.num_tenants
            ),
        ).validate()
    # which batched decision plane this run dispatches (the config gate
    # above guarantees exactly one of these holds)
    if config.algorithm == "global" or config.moves_per_round == "all":
        fleet_mode = "global"
    elif config.algorithm == "proactive":
        fleet_mode = "proactive"
    else:
        fleet_mode = "greedy"
    registry = registry if registry is not None else get_registry()
    key = key if key is not None else jax.random.PRNGKey(config.seed)

    backends = list(fleet.backends)
    if config.chaos.profile != "none":
        hit = set(config.fleet.chaos_tenants) or set(range(len(backends)))
        backends = [
            with_chaos(
                b, config.chaos.profile, seed=config.chaos.seed + t,
                registry=registry,
            )
            if t in hit
            else b
            for t, b in enumerate(backends)
        ]

    # heterogeneous tenants: align every backend to ONE shared shape
    # bucket BEFORE any tenant reads its graph or snapshot — stacking
    # requires a common shape, and the mask-native kernels keep the
    # padding inert (same-shaped fleets are untouched)
    _align_fleet_buckets(
        backends, floor=config.elastic.bucket_floor, registry=registry
    )

    # the cardinality budget (ObsConfig.tenant_label_budget): at or
    # under budget the legacy per-tenant families emit bit-identically;
    # over budget they suppress (counted) and the bounded rollup
    # families carry the fleet's observability instead
    obs = config.obs
    tseries = TenantSeries(
        registry, tenants=len(backends), budget=obs.tenant_label_budget
    )
    if ops is not None:
        # per-tenant SLO budgets publish through the same gate, so an
        # over-budget fleet suppresses them (counted) instead of forking
        # a second cardinality policy
        ops.bind_tenant_series(tseries)
    tenants = [
        _Tenant(
            name,
            backend,
            config,
            logger=logger,
            registry=registry,
            key=jax.random.fold_in(key, t),
            tenant_series=tseries,
        )
        for t, (name, backend) in enumerate(
            zip(fleet.tenant_names, backends)
        )
    ]
    T = len(tenants)
    names = [t.name for t in tenants]
    rollup_on = obs.fleet_rollup
    rollup_k = min(obs.fleet_rollup_top_k, T)
    # per-tenant last-good (cost, load_std): dark/skipped tenants
    # contribute these to the rollup instead of a filler row's garbage.
    # A tenant that has NEVER produced a round (dark since startup) has
    # no last-good value — until its first executed round it borrows
    # the round's computed row (the filler tenant's live state against
    # its own graph): a representative stand-in, where a zero row would
    # drag every fleet quantile toward a healthier-looking floor
    last_pair = np.zeros((T, 2), np.float32)
    ever_good = np.zeros((T,), bool)
    # the latest rollup's named event payload — the over-budget
    # /healthz summary and breaker-open bundles read it
    last_rollup_event: list = [None]
    prev_logger_state = None
    if logger is not None:
        # fleet ring fairness, armed FOR THE RUN and restored on exit
        # (get_logger memoizes loggers process-wide — a later solo run
        # must not keep counting drops into this run's registry, and a
        # later fleet of a different size must recompute its own fair
        # share): drop accounting lands in THIS run's registry, and the
        # shared ring gets a per-tenant share so one chatty tenant
        # cannot evict every other tenant's events
        prev_logger_state = (logger.registry, logger.max_records_per_tenant)
        logger.registry = registry
        if logger.max_records_per_tenant == 0 and T > 1:
            logger.max_records_per_tenant = max(4, logger.max_records // T)
    if churn is None and config.elastic.profile != "none":
        churn = make_fleet_churn(fleet, config.elastic, registry=registry)
    churn = dict(churn or {})
    for idx in sorted(churn):
        if not (0 <= idx < T):
            raise ValueError(
                f"churn tenant index {idx} out of range for {T} tenants"
            )
        # bind through the tenant's boundary (backend passthrough), so
        # chaos wrappers see the same stream; bind pushes the shared
        # bucket capacities into EVERY tenant backend (capacity sinks)
        churn[idx].bind(
            tenants[idx].boundary, config.max_rounds, registry=registry
        )
    if churn:
        # binding re-padded the comm graphs (service bucket): re-read
        # every tenant's graph before the one-time stack below
        for t in tenants:
            t.graph = t.boundary.comm_graph()
    registry.gauge(
        "fleet_tenants", "tenants served by the multiplexed fleet loop"
    ).set(T)
    def update_fleet_health() -> None:
        """Refresh /healthz's fleet block: per-tenant rows at budget
        (bit-identical to the pre-budget plane), the bounded summary —
        breaker counts + the rollup's worst-k rows — over it."""
        if ops is None:
            return
        ops.health.fleet = fleet_health_block(
            {t.name: t.health_row() for t in tenants},
            budget=obs.tenant_label_budget,
            event=last_rollup_event[0],
        )

    def emit_rollup(rollup: dict, rnd: int) -> None:
        """One fleet round's rollup lands everywhere at once: the
        bounded metric families, the named fleet_rollup event, the
        watchdog's fleet_tail_cost window, and the breaker-open bundle
        cache."""
        publish_rollup(registry, rollup)
        ev = rollup_event(rollup, names, round=rnd)
        last_rollup_event[0] = ev
        if logger is not None:
            logger.info("fleet_rollup", **ev)
        if ops is not None:
            ops.observe_fleet_rollup(rollup, event=ev)

    if ops is not None:
        ops.bind(logger=logger, algorithm=config.algorithm)
        update_fleet_health()
        for t in tenants:
            # a tenant breaker opening is exactly the moment the flight
            # recorder should dump, same as the solo loop's wiring —
            # tagged with the tenant so the bundle ships the rollup plus
            # ONLY the offending tenant's summary ring
            t.breaker.on_transition = (
                lambda rec, _name=t.name: ops.on_breaker_transition(
                    {**rec, "tenant": _name}
                )
            )

    # device-plane selection, per batched decision plane. The dp mesh is
    # resolved ONCE (the global decode needs its dp extent; per-call
    # auto-shaping would also re-key the shard cache for nothing).
    forecast_plane = None
    global_cfg = None
    solve_fn = None
    g_solve = None
    g_dp = 1
    if fleet_mode == "global":
        global_cfg = GlobalSolverConfig(
            sweeps=config.global_solver_iters,
            balance_weight=config.balance_weight,
            enforce_capacity=config.enforce_capacity,
            capacity_frac=config.capacity_frac,
            move_cost=config.move_cost,
        )
        if config.fleet.plane == "dp":
            from kubernetes_rescheduling_tpu.parallel.fleet import (
                _fleet_mesh,
                fleet_global_solve_dp,
            )

            g_mesh = _fleet_mesh(T, None)
            g_dp = g_mesh.shape["dp"]
            g_solve = lambda st, gr, ks, m: fleet_global_solve_dp(  # noqa: E731
                st, gr, ks, m,
                config=global_cfg,
                n_restarts=config.solver_restarts,
                mesh=g_mesh,
            )
        else:
            g_solve = lambda st, gr, ks, m: fleet_global_solve(  # noqa: E731
                st, gr, ks, m,
                config=global_cfg,
                n_restarts=config.solver_restarts,
            )
    elif fleet_mode == "proactive":
        from kubernetes_rescheduling_tpu.forecast.fleet import (
            FleetForecastPlane,
        )

        forecast_plane = FleetForecastPlane(config.forecast, T)
        if config.fleet.plane == "dp":
            from kubernetes_rescheduling_tpu.parallel.fleet import (
                fleet_solve_proactive_dp,
            )

            solve_fn = fleet_solve_proactive_dp
        else:
            solve_fn = fleet_solve_proactive
    else:
        if config.fleet.plane == "dp":
            from kubernetes_rescheduling_tpu.parallel.fleet import (
                fleet_solve_dp,
            )

            solve_fn = fleet_solve_dp
        else:
            solve_fn = fleet_solve

    # the device plane (telemetry.mesh): dp runs attribute each block's
    # host-measured dispatch wall and pulled bytes across the dp devices
    # and publish the bounded rollup + /devices overview. Reads ride the
    # decision/metrics bundles already pulled — zero extra transfers —
    # so turning it off changes observability only (decision parity is
    # test-pinned)
    mesh_plane = None
    if config.fleet.plane == "dp" and getattr(obs, "device_rollup", True):
        from kubernetes_rescheduling_tpu.parallel.fleet import (
            dp_device_names,
        )
        from kubernetes_rescheduling_tpu.telemetry.mesh import MeshPlane

        mesh_plane = MeshPlane(
            registry,
            device_names=dp_device_names(tenants=T),
            budget=getattr(obs, "device_label_budget", 64),
        )
        if ops is not None:
            ops.bind_mesh(mesh_plane)
    # the profiler gate (POST /profile / --profile-rounds): armed
    # captures open just before a dispatch and close after the block's
    # rounds have committed
    prof = getattr(ops, "profiler", None) if ops is not None else None

    def observe_mesh(
        *, dispatch_s, transfer_bytes, weights, rounds, rnd
    ) -> None:
        """One block's device-axis accounting lands everywhere at once:
        the bounded mesh families, the named device_rollup event, the
        /healthz mesh stanza, and the mesh_imbalance watchdog window."""
        if mesh_plane is None:
            return
        summary, ev = mesh_plane.observe_block(
            dispatch_s=dispatch_s,
            transfer_bytes=transfer_bytes,
            weights=weights,
            rounds=rounds,
            round=rnd,
        )
        if logger is not None:
            logger.info("device_rollup", **ev)
        if ops is not None:
            ops.observe_device_rollup(summary, event=ev)

    # pipelined fleet ([controller] pipeline): the per-tenant boundary
    # phases (apply → pace → post-move monitor) run concurrently — each
    # tenant owns its backend/boundary/breaker, so N sequential
    # round-trips collapse to max-of-N wall clock with per-tenant
    # streams bit-identical (test-pinned)
    pool = (
        ThreadPoolExecutor(
            max_workers=min(T, 8), thread_name_prefix="krt-fleet"
        )
        if config.controller.pipeline and T > 1
        else None
    )
    overlap_gauge = None
    if pool is not None:
        pipeline_depth_gauge(registry).set(config.controller.depth)
        overlap_gauge = pipeline_overlap_gauge(registry)

    # the policy a round actually scores with: proactive delegates to its
    # base policy (the forecast moves the STATE, not the policy — the
    # solo loop's scoring_policy rule); global rounds score nothing here
    scoring = (
        scoring_policy(config.algorithm, config.forecast)
        if fleet_mode != "global"
        else None
    )
    pid = (
        jnp.asarray(POLICY_IDS[scoring]) if scoring is not None else None
    )
    thr = jnp.asarray(config.hazard_threshold_pct)
    mech = PlacementMechanism[
        scoring if scoring is not None else "global"
    ]
    # graphs and tenant key roots are static per tenant — stacked ONCE
    # (name-stripped device views, elastic.buckets: static name tuples
    # would put churnable metadata into the jit key); under churn the
    # stack is rebuilt only on rounds whose events changed a graph
    stacked_graphs = stack_tenants([device_graph(t.graph) for t in tenants])
    stacked_keys = jnp.stack([t.key for t in tenants])

    # startup: the solo loop's bounded probe per tenant, WITHOUT the solo
    # loop's hard failure — a tenant that stays dark simply starts with
    # no snapshot (its rounds are counted skips until a monitor lands);
    # only a fleet where EVERY tenant is dark is an error
    for t in tenants:
        for _ in range(max(3, config.max_consecutive_failures + 1)):
            t.state = _admitted_monitor(t)
            if t.state is not None:
                break
        if t.state is not None and t.ledger is not None:
            # startup baseline, per tenant: intent := the first admitted
            # snapshot (a tenant that starts dark rebases at its first
            # successful probe instead — observe() primes lazily)
            t.ledger.rebase(t.state, service_names=t.graph.names)
    if all(t.state is None for t in tenants):
        raise ConnectionError(
            "fleet unavailable: every tenant's initial monitor() failed "
            "after retries"
        )

    result = FleetResult(tenants=tuple(t.name for t in tenants))

    def skip_round(t: _Tenant, rnd: int) -> None:
        t.result.skipped_rounds += 1
        tseries.counter_inc(
            "fleet_rounds_skipped_total",
            "tenant rounds frozen by that tenant's open breaker (or a "
            "dark backend) — counted, never silently lost",
            t.name,
        )
        if ops is not None:
            ops.observe_tenant(
                t.name,
                breaker=t.breaker.state,
                drift=t.last_drift,
                skipped=True,
            )
        # the solo loop's rule: a rejection in this round's gate belongs
        # to this skip, never to the tenant's next executed record
        adm = t.guard.take_info() if t.guard is not None else {}
        if logger is not None:
            logger.info(
                "fleet_round_skipped",
                tenant=t.name,
                round=rnd,
                breaker=t.breaker.state,
                consecutive_failures=t.breaker.consecutive_failures,
                **({"admission": adm} if adm else {}),
            )
        if ops is not None:
            # counted on the plane too: /healthz skip totals move, and
            # mark_round keeps a skip-heavy stretch from reading as a
            # stale loop (the solo loop's observe_skip contract)
            ops.observe_skip(rnd, breaker_state=t.breaker.state)
        t.boundary.advance(config.sleep_after_action_s)

    # events applied while a tenant's rounds are skipped accumulate here
    # and flush into that tenant's next executed record (the solo loop's
    # pending-churn rule, per tenant)
    pending_churn: dict[int, list[dict]] = {idx: [] for idx in churn}

    def emit_tenant_round(t: _Tenant, rec: RoundRecord, rnd: int) -> None:
        """The per-tenant-round epilogue — result stream, fleet metric
        families, the round event, the ops plane, ``on_round`` — shared
        by the sequential round and the scanned block so a scanned
        tenant-round is indistinguishable downstream."""
        t.result.rounds.append(rec)
        tseries.counter_inc(
            "fleet_rounds_total",
            "tenant rounds executed by the multiplexed fleet loop",
            t.name,
        )
        if rec.moved:
            tseries.counter_inc(
                "fleet_moves_total",
                "deployments moved per tenant by fleet rounds",
                t.name,
            )
        if rec.degraded:
            tseries.counter_inc(
                "fleet_degraded_rounds_total",
                "tenant rounds finished on a stale snapshot after "
                "the post-move monitor failed",
                t.name,
            )
        tseries.gauge_set(
            "fleet_communication_cost",
            "per-tenant communication cost after the most recent "
            "fleet round",
            t.name,
            rec.communication_cost,
        )
        tseries.gauge_set(
            "fleet_load_std",
            "per-tenant node CPU-% standard deviation after the "
            "most recent fleet round",
            t.name,
            rec.load_std,
        )
        if rec.forecast is not None:
            # the proactive plane's per-tenant skill (budget-gated like
            # every per-tenant family) plus the solo loop's mode counter
            # — one increment per tenant-round, same family/help so the
            # series never forks between loops
            tseries.gauge_set(
                "fleet_forecast_skill",
                "per-tenant forecast skill (1 - mae_model/"
                "mae_persistence) after the most recent proactive "
                "fleet round",
                t.name,
                rec.forecast["skill"],
            )
            registry.counter(
                "forecast_rounds_total",
                "proactive rounds by forecast path (cold = warming up, "
                "predictive = model steering, degraded = skill gate fell "
                "back to reactive)",
                labelnames=("mode",),
            ).labels(mode=rec.forecast["mode"]).inc()
        round_event = dict(
            tenant=t.name,
            round=rnd,
            moved=rec.moved,
            service=rec.service,
            target=rec.target,
            communication_cost=rec.communication_cost,
            load_std=rec.load_std,
            breaker=rec.breaker_state,
            degraded=rec.degraded,
            boundary_failures=rec.boundary_failures,
        )
        if logger is not None:
            logger.info("fleet_round", **round_event)
        if ops is not None:
            # the solo loop's per-round plane feed, per tenant-round:
            # health counters + mark_round, the watchdog, and the
            # flight-recorder ring (so a breaker-open bundle carries
            # the fleet's recent rounds)
            ops.observe_round(
                rec,
                t.state,
                events=[{"event": "fleet_round", **round_event}],
                # per-source watchdog state (the reconcile rule)
                # keys on the tenant so interleaved tenant rounds
                # never mask each other's drift
                tenant=t.name,
            )
            # the /tenants drill-down ring: per-tenant detail lives
            # HERE (bounded, LRU), not in metric label space
            ops.observe_tenant(
                t.name,
                record={
                    "round": rnd,
                    "moved": rec.moved,
                    "service": rec.service,
                    "target": rec.target,
                    "communication_cost": rec.communication_cost,
                    "load_std": rec.load_std,
                    "degraded": rec.degraded,
                },
                breaker=rec.breaker_state,
                drift=t.last_drift,
            )
        if on_round is not None:
            on_round(t.name, rec, t.state)

    def apply_tenant_move(
        t: _Tenant, decisions_row, hazard_row, *, apply: bool = True
    ):
        """The per-tenant apply half BOTH schedules share: decode the
        packed decision row, issue the boundary move, record the ledger
        intent — one definition, so the per-round path and the scanned
        replay can never diverge at the apply site. Returns
        ``(service_name, first_hazard, landed, attempted)``."""
        state = t.state
        most_i = int(decisions_row[ROW_MOST])
        victim_i = int(decisions_row[ROW_VICTIM])
        svc_i = int(decisions_row[ROW_SERVICE])
        target_i = int(decisions_row[ROW_TARGET])
        service_name = t.graph.names[svc_i] if victim_i >= 0 else None
        first_hazard = state.node_names[most_i] if most_i >= 0 else None
        landed: str | None = None
        attempted = (
            apply and most_i >= 0 and victim_i >= 0 and target_i >= 0
        )
        if attempted:
            hazard_names = tuple(
                state.node_names[j]
                for j in range(state.num_nodes)
                if bool(hazard_row[j])
            )
            landed = t.boundary.apply_move(
                MoveRequest(
                    service=service_name,
                    target_node=state.node_names[target_i],
                    hazard_nodes=hazard_names,
                    # proactive resolves to its base policy's mechanism
                    # (the forecast changes the state scored, not how
                    # the move pins) — the solo loop's rule
                    mechanism=mech,
                )
            )
            if t.ledger is not None and landed is not None:
                # intent recorded at apply time: the ledger diffs it
                # against the next admitted snapshot. The advisory/
                # pinning rule lives in move_intent — ONE definition
                # shared with the solo loop
                t.ledger.record_moves(
                    [
                        move_intent(
                            mech,
                            service_name,
                            state.node_names[target_i],
                            landed,
                        )
                    ]
                )
        return service_name, first_hazard, landed, attempted

    def apply_tenant_global_moves(t: _Tenant, moves_t):
        """The GLOBAL round's apply half: the decoded per-tenant move
        list — ``(service_index, target_node_index)`` in the solo loop's
        first-moved-pod order — issued through that tenant's boundary
        with the solo ``_global_round``'s intent rule. Returns
        ``(moved_names, applied_moves)``."""
        state = t.state
        moved_names: list[str] = []
        applied_moves: list[tuple[str, str]] = []
        for s, target_i in moves_t:
            service_name = t.graph.names[s]
            landed = t.boundary.apply_move(
                MoveRequest(
                    service=service_name,
                    target_node=state.node_names[target_i],
                    mechanism=mech,
                )
            )
            if t.ledger is not None:
                t.ledger.record_moves(
                    [
                        move_intent(
                            mech,
                            service_name,
                            state.node_names[target_i],
                            landed,
                        )
                    ]
                )
            if landed is not None:
                moved_names.append(service_name)
                applied_moves.append((service_name, landed))
        return moved_names, applied_moves

    def round_once(rnd: int) -> None:
        nonlocal stacked_graphs
        churn_applied: dict[int, list[dict]] = {}
        if churn:
            promoted = False
            graphs_changed = False
            for idx in sorted(churn):
                applied = churn[idx].step(rnd)
                if applied:
                    churn_applied[idx] = applied
                    pending_churn.setdefault(idx, []).extend(applied)
                    promoted = promoted or churn[idx].promoted
                    graphs_changed = graphs_changed or churn[idx].graph_changed
                    tenants[idx].remask = True
            if promoted:
                # a shared-bucket promotion re-pads EVERY tenant:
                # graphs refresh host-side (no boundary traffic) and
                # every tenant owes a re-monitor — settled below,
                # BEHIND its own breaker gate, so an ailing tenant is
                # neither hammered while OPEN nor double-charged. Every
                # tenant's derived-graph caches are stale (their keyed
                # graph objects are gone) — evict, counted, so a long
                # deploy-waves soak never accretes stale generations
                for t in tenants:
                    t.graph = t.boundary.comm_graph()
                    t.remask = True
                    t.boundary.evict_solver_caches(reason="promotion")
                stacked_graphs = stack_tenants(
                    [device_graph(t.graph) for t in tenants]
                )
            elif graphs_changed:
                for idx in churn_applied:
                    if churn[idx].graph_changed:
                        tenants[idx].graph = (
                            tenants[idx].boundary.comm_graph()
                        )
                        # churn rewrote this tenant's graph: its cached
                        # derived values (sparse/pod graphs) can never
                        # be hit again — drop them now, counted
                        tenants[idx].boundary.evict_solver_caches(
                            reason="churn"
                        )
                stacked_graphs = stack_tenants(
                    [device_graph(t.graph) for t in tenants]
                )
        active: list[int] = []
        for i, t in enumerate(tenants):
            mode = t.boundary.begin_round(rnd)
            if mode == OPEN:
                skip_round(t, rnd)
                continue
            if mode == HALF_OPEN or t.state is None or t.remask:
                # half-open probe, a tenant that has never produced a
                # snapshot, or one whose snapshot predates applied
                # churn: ONE monitor — behind the gate — decides
                # whether this round runs (a dark backend is a single
                # counted failure; the re-mask debt carries forward)
                probe = _admitted_monitor(t)
                if probe is None:
                    skip_round(t, rnd)
                    continue
                t.state = probe
                t.remask = False
            active.append(i)
        if not active:
            # the whole fleet skipped — nothing to dispatch this round
            update_fleet_health()
            return

        # ONE batched solve for every tenant slot: inactive slots carry a
        # placeholder snapshot (shapes must stay static — 1 trace) and
        # are masked so they can never emit a move. ALWAYS the filler
        # for inactive slots: a skipped tenant's carried snapshot may
        # predate a bucket promotion (stale shapes would break the
        # stack), and masked rows never read their values anyway
        filler = tenants[active[0]].state
        active_set = set(active)
        stacked_states = stack_tenants(
            [
                device_view(t.state if i in active_set else filler)
                for i, t in enumerate(tenants)
            ]
        )
        mask = np.zeros((T,), dtype=bool)
        mask[active] = True
        fc_rows = None
        g_moves = g_objs = None
        if prof is not None:
            # an armed capture opens HERE — just before the round's
            # dispatch — so the trace holds exactly the rounds asked for
            prof.maybe_start(label="fleet_rounds", round=rnd)
        t0 = time.perf_counter()
        if fleet_mode == "global":
            # ONE batched global solve re-places every service in every
            # active tenant; the decided per-tenant move lists, the solo
            # loop's move ORDER, and the solver objective rows all come
            # home in ONE counted transfer
            keys = _round_keys_global(stacked_keys, jnp.asarray(rnd))
            with span("fleet/global_solve", round=rnd, tenants=len(active)):
                flat_dev = block(
                    g_solve(
                        stacked_states, stacked_graphs, keys,
                        jnp.asarray(mask),
                    )
                )
            solve_s = time.perf_counter() - t0
            flat = _pull_round_bundle(flat_dev, "fleet_decision")
            num_services = int(stacked_graphs.adj.shape[1])
            if g_dp > 1:
                from kubernetes_rescheduling_tpu.parallel.fleet import (
                    decode_fleet_global_dp,
                )

                g_moves, g_objs = decode_fleet_global_dp(
                    flat, tenants=T, num_services=num_services, dp=g_dp
                )
            else:
                g_moves, g_objs = decode_fleet_global(
                    flat, tenants=T, num_services=num_services
                )
        else:
            keys = _round_keys(stacked_keys, jnp.asarray(rnd))
            diag_dev = None
            if fleet_mode == "proactive":
                # fold every active tenant's observed loads into its
                # model and predict the next window — one batched
                # forecast dispatch; the diag matrix stays device-side
                # and rides the decision bundle below (the solo plane's
                # round_end discipline, fleet-shaped)
                with span("fleet/forecast", round=rnd, tenants=len(active)):
                    deltas, diag_dev = forecast_plane.observe_and_predict(
                        stacked_states, jnp.asarray(mask)
                    )
            with span("fleet/solve", round=rnd, tenants=len(active)):
                if fleet_mode == "proactive":
                    decisions_dev, hazard_dev = block(
                        solve_fn(
                            stacked_states, stacked_graphs, pid, thr,
                            keys, jnp.asarray(mask), deltas,
                        )
                    )
                else:
                    decisions_dev, hazard_dev = block(
                        solve_fn(
                            stacked_states, stacked_graphs, pid, thr,
                            keys, jnp.asarray(mask),
                        )
                    )
            solve_s = time.perf_counter() - t0
            # the whole fleet's round comes home in ONE counted
            # transfer: decisions (i32[T,4] — small indices, exact in
            # f32), the hazard masks, and — proactive — the forecast
            # diag matrix, packed into a single flat bundle
            n_nodes = int(hazard_dev.shape[1])
            parts = [
                jnp.ravel(decisions_dev).astype(jnp.float32),
                jnp.ravel(hazard_dev).astype(jnp.float32),
            ]
            if diag_dev is not None:
                parts.append(jnp.ravel(diag_dev))
            flat = _pull_round_bundle(
                jnp.concatenate(parts), "fleet_decision"
            )
            decisions = flat[: T * 4].reshape(T, 4).astype(np.int64)
            hazard = flat[T * 4: T * 4 + T * n_nodes].reshape(T, n_nodes) > 0.5
            if diag_dev is not None:
                fc_rows = flat[T * 4 + T * n_nodes:].reshape(T, DIAG_SIZE)
        # device-plane byte accounting rides the bundles ALREADY pulled:
        # the decision bundle here, the metrics bundle below — never a
        # new transfer (check_apply_boundary keeps it that way)
        mesh_bytes = int(flat.nbytes) if mesh_plane is not None else 0
        result.batched_solves += 1
        result.device_solve_s += solve_s
        # the shared dispatch's cost, attributed evenly to the tenants
        # that used it — the amortization IS the fleet-mode story
        per_tenant_s = solve_s / len(active)

        def tenant_round_global(i: int) -> tuple[RoundRecord, float]:
            """One tenant's GLOBAL boundary phase — the move-list apply,
            pace, post-move monitor, record construction. The per-tenant
            isolation contract of ``tenant_round`` holds unchanged."""
            t_bg = time.perf_counter()
            t = tenants[i]
            moved_names, applied_moves = apply_tenant_global_moves(
                t, g_moves[i]
            )
            t.boundary.advance(config.sleep_after_action_s)
            new_state = _admitted_monitor(t)
            degraded = new_state is None
            if not degraded:
                t.state = new_state
            churn_info = (
                churn[i].round_info(pending_churn.pop(i, []))
                if i in churn
                else None
            )
            reconcile_block, t.last_drift = reconcile_round_block(
                t.guard,
                t.ledger,
                state=t.state,
                service_names=t.graph.names,
                churn_events=(churn_info or {}).get("events") or (),
                fresh=not degraded,
                last_drift=t.last_drift,
                boundary=t.boundary,
                repair_budget=config.reconcile.repair_budget_per_round,
            )
            obj_before, obj_after, improved, _pen = g_objs[i]
            rec = RoundRecord(
                round=rnd,
                moved=bool(moved_names),
                most_hazard=None,
                service=None,
                target=None,
                communication_cost=0.0,  # filled from the batched metrics
                load_std=0.0,
                services_moved=tuple(moved_names),
                decision_latencies_s=(per_tenant_s,),
                objective_before=obj_before,
                objective_after=obj_after,
                solver_improved=improved,
                breaker_state=t.breaker.state,
                degraded=degraded,
                boundary_failures=t.boundary.round_failures,
                applied_moves=tuple(applied_moves),
                churn=churn_info,
                reconcile=reconcile_block,
            )
            return rec, time.perf_counter() - t_bg

        def tenant_round(i: int) -> tuple[RoundRecord, float]:
            """One tenant's boundary phase — apply, pace, post-move
            monitor, record construction. Touches ONLY tenant i's
            backend/boundary/breaker (plus the thread-safe registry),
            which is what makes the pipelined fleet's concurrent
            execution bit-identical per tenant."""
            t_bg = time.perf_counter()
            t = tenants[i]
            service_name, first_hazard, landed, _attempted = (
                apply_tenant_move(t, decisions[i], hazard[i])
            )
            moved_name = service_name if landed is not None else None
            t.boundary.advance(config.sleep_after_action_s)
            new_state = _admitted_monitor(t)
            degraded = new_state is None
            if not degraded:
                t.state = new_state
            # elastic events consumed BEFORE the reconcile diff so
            # legitimate churn never reads as drift (pending, not just
            # this round's: a skipped tenant round's events flush into
            # the next executed record)
            churn_info = (
                churn[i].round_info(pending_churn.pop(i, []))
                if i in churn
                else None
            )
            reconcile_block, t.last_drift = reconcile_round_block(
                t.guard,
                t.ledger,
                state=t.state,
                service_names=t.graph.names,
                churn_events=(churn_info or {}).get("events") or (),
                fresh=not degraded,
                last_drift=t.last_drift,
                boundary=t.boundary,
                repair_budget=config.reconcile.repair_budget_per_round,
            )
            rec = RoundRecord(
                round=rnd,
                moved=moved_name is not None,
                most_hazard=first_hazard,
                service=moved_name,
                target=landed,
                communication_cost=0.0,  # filled from the batched metrics
                load_std=0.0,
                services_moved=(moved_name,) if moved_name else (),
                decision_latencies_s=(per_tenant_s,),
                breaker_state=t.breaker.state,
                degraded=degraded,
                boundary_failures=t.boundary.round_failures,
                applied_moves=(
                    ((moved_name, landed),) if moved_name else ()
                ),
                churn=churn_info,
                reconcile=reconcile_block,
                # proactive: this tenant's decoded forecast block (skill,
                # MAEs, cold/predictive/degraded path) — the solo plane's
                # round_info, from the diag row that rode the bundle
                forecast=(
                    FleetForecastPlane.decode_diag(fc_rows[i])
                    if fc_rows is not None
                    else None
                ),
            )
            return rec, time.perf_counter() - t_bg

        round_fn = (
            tenant_round_global if fleet_mode == "global" else tenant_round
        )
        records: dict[int, RoundRecord] = {}
        if pool is not None and len(active) > 1:
            # pipelined fleet: every tenant's apply→pace→monitor chain
            # is independent (own backend clock, own breaker, own
            # chaos stream), so the N sequential boundary round-trips
            # collapse to max-of-N wall clock. The registry locks its
            # series; per-tenant results are bit-identical to the
            # sequential interleaving (test-pinned).
            t_par = time.perf_counter()
            futs = {i: pool.submit(round_fn, i) for i in active}
            durs = []
            for i in active:
                records[i], d = futs[i].result()
                durs.append(d)
            par_wall = time.perf_counter() - t_par
            total = sum(durs)
            ratio = (
                max(0.0, min(1.0, 1.0 - par_wall / total))
                if total > 1e-9
                else 0.0
            )
            overlap_gauge.set(ratio)
        else:
            for i in active:
                records[i], _ = round_fn(i)

        # ONE batched metrics dispatch + ONE transfer closes the round's
        # reporting for every active tenant (the solo loop pays 2 scalar
        # pulls per tenant here). With rollups on, the device-side
        # tenant rollup CONCATENATES into the same bundle — the fleet's
        # whole observability plane still costs zero extra transfers
        # same filler rule as the solve stack: only active tenants'
        # rows are read, and only active tenants are guaranteed to
        # hold post-promotion shapes
        filler = tenants[active[0]].state
        stacked_after = stack_tenants(
            [
                device_view(t.state if i in active_set else filler)
                for i, t in enumerate(tenants)
            ]
        )
        rollup = None
        if rollup_on:
            flags = np.zeros((T, 3), np.float32)
            for i, t in enumerate(tenants):
                if i in active_set:
                    if records[i].degraded:
                        flags[i, 0] = 1.0
                else:
                    flags[i, 1] = 1.0
                flags[i, 2] = float(t.last_drift)
            flat = _pull_round_bundle(
                dispatch_fleet_bundle(
                    stacked_after,
                    stacked_graphs,
                    jnp.asarray(last_pair),
                    jnp.asarray(flags),
                    # merge mask: active rows take the fresh pair; so do
                    # never-good rows (their last_pair is no value at
                    # all — the computed stand-in beats a zero row)
                    jnp.asarray(mask | ~ever_good),
                    top_k=rollup_k,
                ),
                "fleet_metrics",
            )
            metrics, rollup = decode_fleet_bundle(
                flat, tenants=T, top_k=rollup_k
            )
            if mesh_plane is not None:
                mesh_bytes += int(flat.nbytes)
        else:
            metrics = _pull_round_bundle(
                fleet_metrics(stacked_after, stacked_graphs),
                "fleet_metrics",
            )
            if mesh_plane is not None:
                mesh_bytes += int(metrics.nbytes)
        observe_wall_round(registry, "fleet", time.perf_counter() - t0)
        for i in range(T):
            if i in active_set:
                continue
            if not ever_good[i]:
                # never-good tenant: adopt the computed stand-in row so
                # the NEXT round's rollup carries it instead of zeros
                last_pair[i] = metrics[i]
        for i in active:
            t = tenants[i]
            rec = records[i]
            rec.communication_cost = float(metrics[i, 0])
            rec.load_std = float(metrics[i, 1])
            last_pair[i] = metrics[i]
            ever_good[i] = True
            emit_tenant_round(t, rec, rnd)
        observe_mesh(
            dispatch_s=solve_s,
            transfer_bytes=mesh_bytes,
            # attribution weights: this round's per-tenant comm cost —
            # tenant block i's share of the dispatch lands on device i
            weights=metrics[:, 0],
            rounds=1,
            rnd=rnd,
        )
        if rollup is not None:
            emit_rollup(rollup, rnd)
        update_fleet_health()
        if prof is not None:
            # one fleet round committed — an open capture burns one of
            # its budgeted rounds and closes at zero
            prof.advance(1)

    scan_k = config.controller.scan_block
    if scan_k:
        from kubernetes_rescheduling_tpu.backends.sim_device import (
            scan_compatible,
        )
        from kubernetes_rescheduling_tpu.bench import scan as scan_mod
        from kubernetes_rescheduling_tpu.telemetry import (
            tripwire as tripwire_mod,
        )
    # in-block tripwires: per-tenant latches inside the fleet scan body
    trip_on = bool(scan_k) and getattr(obs, "scan_tripwires", True)

    def scan_static_reason() -> str | None:
        """Run-level conditions the fleet scan can never honor (the solo
        loop's rule, fleet-shaped): the whole fleet must be raw
        noise-free simulators with no churn engines and no load hook."""
        if on_round is not None:
            return "on-round"
        if churn:
            return "churn"
        if any(not scan_compatible(t.boundary.backend) for t in tenants):
            return "backend"
        return None

    def scan_block(start: int, k: int) -> int:
        """One fleet scan block: ONE compiled dispatch advances EVERY
        tenant ``k`` rounds (``bench.scan.fleet_scan_rounds`` — decide,
        sim-twin apply, and the metrics pair vmapped over the tenant
        axis inside one ``lax.scan``), the whole block pulled as ONE
        counted ``round_end`` transfer, then the decided moves replayed
        per tenant in the sequential call order. Per-tenant records are
        bit-identical to the sequential fleet loop's (test-pinned).
        Returns the rounds committed: ``k``, or — when a tenant's
        in-block tripwire latched — the EARLIEST trip round across
        tenants (only rounds every tenant ran healthy commit; the
        un-tripped tenants' discarded rounds re-decide bit-identically
        on the per-round path by key parity, so fleet-wide truncation
        costs correctness nothing)."""
        n_nodes = tenants[0].state.num_nodes
        stacked_states = stack_tenants(
            [device_view(t.state) for t in tenants]
        )
        scan_rollup_k = rollup_k if rollup_on else 0
        # drift is host state the scan body cannot compute: the vector
        # AT BLOCK START rides the dispatch as an argument (uploads are
        # free of the one-counted-transfer budget, which covers
        # device→host pulls). The replay's reconcile below CAN move
        # drift mid-block (fresh diff + repairs on the block's last
        # round), so a block's rollups carry drift at most one block
        # stale — the per-round RoundRecord.reconcile stays exact
        drift_vec = (
            jnp.asarray(
                np.asarray(
                    [float(t.last_drift) for t in tenants], np.float32
                )
            )
            if scan_rollup_k
            else None
        )
        if ops is not None:
            ops.health.mark_block_inflight(k)
        if prof is not None:
            # an armed capture wraps EXACTLY this block's dispatch: the
            # trace opens here and closes after the block's k rounds
            prof.maybe_start(label="fleet_scan_block", rounds=k, round=start)
        t0 = time.perf_counter()
        with span("fleet/scan_block", round=start, rounds=k, tenants=T):
            flat = _pull_round_bundle(
                scan_mod.fleet_scan_rounds(
                    stacked_states,
                    stacked_graphs,
                    pid,
                    thr,
                    stacked_keys,
                    jnp.asarray(start, jnp.int32),
                    drift_vec,
                    (
                        tripwire_mod.trip_config_array(obs)
                        if trip_on
                        else None
                    ),
                    rounds=k,
                    pinned=True,
                    rollup_k=scan_rollup_k,
                    tripwire=trip_on,
                ),
                scan_mod.ROUND_END_SITE,
            )
        fence_s = time.perf_counter() - t0
        scan_mod.count_scan_block(registry, k)
        result.batched_solves += 1
        result.device_solve_s += fence_s
        # the WHOLE block bundle's bytes, read before the tripwire split
        # reassigns `flat` — the device plane attributes what actually
        # crossed the fence, tripwire lanes included
        block_bytes = int(flat.nbytes) if mesh_plane is not None else 0
        trip = None
        if trip_on:
            flat, trip = tripwire_mod.split_fleet_tripwire(
                flat, rounds=k, tenants=T
            )
        decoded = scan_mod.decode_fleet_block(
            flat, rounds=k, tenants=T, num_nodes=n_nodes,
            rollup_k=scan_rollup_k,
        )
        if scan_rollup_k:
            decisions, hazard, landed_idx, metrics, rollups = decoded
        else:
            decisions, hazard, landed_idx, metrics = decoded
            rollups = None
        observe_mesh(
            dispatch_s=fence_s,
            transfer_bytes=block_bytes,
            # per-tenant comm cost summed over the block's rounds —
            # tenant block i's share of the fence lands on device i
            weights=metrics[..., 0].sum(axis=0),
            rounds=k,
            rnd=start,
        )
        commit = k
        trip_info = None
        if trip is not None and trip.tripped:
            # fleet-wide truncation at the EARLIEST trip: each tenant's
            # latch froze only its own lane in-trace, but the host
            # commits one shared prefix so every tenant's round ledger
            # advances in lockstep (max_rounds accounting holds); the
            # tripped round itself re-runs per-round via the drain
            trip_rounds = np.asarray(trip.trip_round)
            commit = int(trip_rounds[trip_rounds >= 0].min())
            tripped_tenants: dict[str, dict] = {}
            for i, t in enumerate(tenants):
                if trip_rounds[i] < 0:
                    continue
                t_rules = tripwire_mod.rules_from_mask(
                    int(trip.trip_mask[i])
                )
                tripwire_mod.count_tripwire(registry, t_rules)
                tseries.counter_inc(
                    "fleet_scan_tripwires_total",
                    "scan blocks tripped by this tenant's in-block "
                    "tripwire lane (budget-gated per-tenant twin of "
                    "scan_tripwires_total)",
                    t.name,
                )
                tripped_tenants[t.name] = {
                    "round": start + int(trip_rounds[i]),
                    "block_round": int(trip_rounds[i]),
                    "rules": list(t_rules),
                    "mask": int(trip.trip_mask[i]),
                }
            trip_info = {
                "round": start + commit,
                "block_start": start,
                "block_round": commit,
                "rules": list(trip.rules),
                "mask": int(
                    np.bitwise_or.reduce(np.asarray(trip.trip_mask))
                ),
                "tenants": tripped_tenants,
            }
            if logger is not None:
                logger.warn("scan_tripwire", **trip_info)
        per_tenant_s = fence_s / (k * T)
        resync: set[int] = set()  # tenants whose replay diverged
        for r in range(commit):
            rnd = start + r
            t_r0 = time.perf_counter()
            last = r == commit - 1
            for t in tenants:
                t.boundary.begin_round(rnd)  # CLOSED stays CLOSED
            for i, t in enumerate(tenants):
                state = t.state
                service_name, first_hazard, landed, attempted = (
                    apply_tenant_move(
                        t, decisions[r, i], hazard[r, i],
                        apply=i not in resync,
                    )
                )
                moved_name = service_name if landed is not None else None
                if attempted:
                    expected = (
                        state.node_names[int(landed_idx[r, i])]
                        if landed_idx[r, i] >= 0
                        else None
                    )
                    if landed != expected:
                        # the backend disagreed with the twin: this
                        # tenant's remaining scanned decisions were made
                        # against a diverged state — stop applying them,
                        # degrade its rounds, and force a re-monitor
                        # before its next block (defensive; a
                        # scan-compatible backend cannot reach this)
                        resync.add(i)
                        t.remask = True
                        if logger is not None:
                            logger.warn(
                                "scan_twin_divergence",
                                tenant=t.name,
                                round=rnd,
                                service=service_name,
                                expected=expected,
                                landed=landed,
                            )
                t.boundary.advance(config.sleep_after_action_s)
                degraded = i in resync
                fresh = False
                if last and i not in resync:
                    new_state = _admitted_monitor(t)
                    degraded = new_state is None
                    if not degraded:
                        t.state = new_state
                        fresh = True
                reconcile_block, t.last_drift = reconcile_round_block(
                    t.guard,
                    t.ledger,
                    state=t.state,
                    service_names=t.graph.names,
                    churn_events=(),
                    fresh=fresh,
                    last_drift=t.last_drift,
                    boundary=t.boundary,
                    repair_budget=config.reconcile.repair_budget_per_round,
                )
                rec = RoundRecord(
                    round=rnd,
                    moved=moved_name is not None,
                    most_hazard=first_hazard,
                    service=moved_name,
                    target=landed,
                    communication_cost=float(metrics[r, i, 0]),
                    load_std=float(metrics[r, i, 1]),
                    services_moved=(moved_name,) if moved_name else (),
                    decision_latencies_s=(per_tenant_s,),
                    breaker_state=t.breaker.state,
                    degraded=degraded,
                    boundary_failures=t.boundary.round_failures,
                    applied_moves=(
                        ((moved_name, landed),) if moved_name else ()
                    ),
                    churn=None,
                    reconcile=reconcile_block,
                )
                last_pair[i] = metrics[r, i]
                ever_good[i] = True
                emit_tenant_round(t, rec, rnd)
            if rollups is not None:
                emit_rollup(
                    decode_rollup(rollups[r], top_k=scan_rollup_k), rnd
                )
            observe_wall_round(
                registry, "scanned",
                fence_s / k + time.perf_counter() - t_r0,
            )
            update_fleet_health()
        if ops is not None:
            # every block reports: clean blocks clear the scan_tripwire
            # SLO rule and the in-flight staleness scaling; a tripped
            # one flips /healthz and dumps a partial-block bundle
            ops.observe_scan_block(rounds=k, trip=trip_info)
        if prof is not None:
            # the dispatch ran all k rounds device-side (tripwire lanes
            # freeze in-trace, the program shape is fixed) — the capture
            # armed for this block closes with it
            prof.advance(k)
        return commit

    def _run_rounds() -> None:
        """The fleet's round driver: scanned blocks in the steady state
        (``[controller] scan_block`` — one dispatch advances all
        tenants K rounds), the per-round multiplexed path otherwise,
        with PR 9's drain discipline: any round the scan cannot honor —
        churn, a non-closed breaker, a dark/re-mask tenant, an
        incompatible backend, a tail shorter than one block — runs
        ``round_once`` and counts ``scan_drains_total{reason}``."""
        static_reason = scan_static_reason() if scan_k else None
        rnd = 1
        while rnd <= config.max_rounds:
            if scan_k:
                reason = static_reason
                if reason is None:
                    # the solo loop's taxonomy: breaker events file under
                    # "breaker", re-mask debt under "churn", and a tenant
                    # that has never produced a snapshot (dark backend)
                    # under "backend" — an operator alerting on breaker
                    # drains must not see healthy-run noise
                    if any(t.breaker.state != "closed" for t in tenants):
                        reason = "breaker"
                    elif any(t.state is None for t in tenants):
                        reason = "backend"
                    elif any(t.remask for t in tenants):
                        reason = "churn"
                    elif config.max_rounds - rnd + 1 < scan_k:
                        reason = "tail"
                if reason is None:
                    consumed = scan_block(rnd, scan_k)
                    rnd += consumed
                    if consumed < scan_k:
                        # a tripwire truncated the block: the earliest
                        # tripped round re-runs per-round under its own
                        # counted drain reason (progress is guaranteed
                        # even when the trip lands on block round 0)
                        scan_mod.count_scan_drain(registry, "tripwire")
                        if ops is not None:
                            ops.observe_scan_drain("tripwire")
                        round_once(rnd)
                        rnd += 1
                    continue
                scan_mod.count_scan_drain(registry, reason)
                if ops is not None:
                    ops.observe_scan_drain(reason)
            round_once(rnd)
            rnd += 1

    # the always-on crash-dump path (the solo loop's contract):
    # whatever escapes the multiplexed loop leaves a flight-recorder
    # bundle behind before propagating
    try:
        _run_rounds()
    except BaseException as e:
        if ops is not None:
            ops.on_crash(e)
        raise
    finally:
        if pool is not None:
            pool.shutdown(wait=True)
        if prev_logger_state is not None:
            logger.registry, logger.max_records_per_tenant = (
                prev_logger_state
            )

    for t in tenants:
        t.result.breaker_transitions = list(t.breaker.transitions)
        t.result.boundary_failures = t.boundary.total_failures
        result.results[t.name] = t.result
    return result
