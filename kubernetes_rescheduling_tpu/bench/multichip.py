"""The measured multichip harness: ``fleet_scan_rounds`` × the dp mesh.

PR 9's scan fused K rounds of every tenant into one compiled dispatch
(``bench.scan.fleet_scan_rounds``); PR 14's dp plane sharded the
per-round fleet kernels one-tenant-block-per-device
(``parallel.fleet``). This module composes the two — the scan body runs
UNDER ``shard_map``, so one dispatch advances every tenant K rounds
with each dp device scanning only its own tenant block — and measures
the composition as the repo's first *measured* MULTICHIP record:

- :func:`fleet_scan_rounds_dp` — the composed kernel. The shard body IS
  ``bench.scan._fleet_scan_rounds`` over the shard's tenant block (no
  collectives: tenants are independent clusters), so the dp plane is
  decision-identical to the single-device scan by construction — and
  test-pinned bit-exact, telemetry on or off.
- :func:`decode_fleet_block_dp` — the dp bundle decode.
  ``out_specs=P("dp")`` concatenates each shard's flat bundle along the
  leading axis, so the global bundle is dp per-block bundles
  back-to-back: re-split per shard, decode each with the single-device
  ``decode_fleet_block``, merge on the tenant axis.
- :func:`bench_multichip` — the MULTICHIP_r06+ harness
  (``BENCH_SCENARIO=multichip``): timed scan blocks over the dp mesh,
  ONE counted ``round_end`` pull per block (zero new per-round
  transfers — ``scripts/check_apply_boundary.py`` pins this module
  sync-free), per-device step attribution through
  ``telemetry.mesh.MeshPlane``, and the
  ``fleet_scan_rounds_per_sec`` headline. On a dev box the same cell
  runs under ``--xla_force_host_platform_device_count=8`` (the bench
  driver forces it via ``__graft_entry__._force_virtual_devices``); on
  a real slice it runs unchanged — the perf ledger keys the two apart
  by ``device_kind`` (``cpux8`` vs ``tpux8``) so their baselines never
  compare.
"""

from __future__ import annotations

import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from kubernetes_rescheduling_tpu.bench import scan as scan_mod
from kubernetes_rescheduling_tpu.parallel.compat import shard_map
from kubernetes_rescheduling_tpu.parallel.fleet import (
    _fleet_mesh,
    dp_device_names,
)
from kubernetes_rescheduling_tpu.telemetry.accounting import instrument_jit
from kubernetes_rescheduling_tpu.telemetry.registry import get_registry

# jitted shard-mapped scan kernels keyed by (mesh, rounds, pinned) — the
# scan twin of parallel.fleet._FLEET_SHARD_CACHE (rounds/pinned are
# static in the scan body, so they belong in the cache key, not in a
# fresh closure per call)
_FLEET_SCAN_SHARD_CACHE: dict = {}


def _fleet_scan_shard(mesh: Mesh, rounds: int, pinned: bool):
    key = (mesh, rounds, pinned)
    fn = _FLEET_SCAN_SHARD_CACHE.get(key)
    if fn is None:

        @partial(
            shard_map,
            mesh=mesh,
            in_specs=(P("dp"), P("dp"), P(), P(), P("dp"), P()),
            out_specs=P("dp"),
            check_vma=False,
        )
        def run_shard(states, graphs, policy_id, threshold, keys, start):
            # the shard body IS the single-device fleet scan over this
            # shard's tenant block — decide, sim-twin apply, metrics,
            # all K rounds inside one lax.scan, no collectives
            return scan_mod._fleet_scan_rounds(
                states,
                graphs,
                policy_id,
                threshold,
                keys,
                start,
                rounds=rounds,
                pinned=pinned,
            )

        fn = instrument_jit(run_shard, name="fleet_scan_rounds_dp")
        _FLEET_SCAN_SHARD_CACHE[key] = fn
    return fn


def fleet_scan_rounds_dp(
    states,
    graphs,
    policy_id: jax.Array,
    threshold: jax.Array,
    tenant_keys: jax.Array,
    start_round: jax.Array,
    *,
    rounds: int,
    pinned: bool = True,
    mesh: Mesh | None = None,
):
    """:func:`bench.scan.fleet_scan_rounds` with the tenant axis sharded
    over the mesh's ``dp`` dimension — ONE dispatch advances every
    tenant ``rounds`` rounds, each device scanning its own tenant block.

    ``states``/``graphs`` are the stacked tenant pytrees
    (:func:`solver.fleet.stack_tenants`); the tenant count must divide
    the mesh's dp extent (:func:`parallel.fleet._fleet_mesh` auto-shapes
    one when none is given, degenerating to the single-device scan on
    one chip). Returns the flat device bundle —
    :func:`decode_fleet_block_dp` unpacks it."""
    mesh = _fleet_mesh(int(tenant_keys.shape[0]), mesh)
    return _fleet_scan_shard(mesh, int(rounds), bool(pinned))(
        states, graphs, policy_id, threshold, tenant_keys, start_round
    )


def decode_fleet_block_dp(
    flat,
    *,
    rounds: int,
    tenants: int,
    num_nodes: int,
    dp: int,
):
    """Decode the dp plane's bundle: each dp shard emitted the
    single-device fleet-scan layout over ITS tenant block
    (rounds-leading), concatenated along the flat axis by
    ``out_specs=P("dp")`` — re-split per shard, decode each, merge on
    the tenant axis. Same return shape as
    :func:`bench.scan.decode_fleet_block`: ``(decisions i64[K,T,4],
    hazard bool[K,T,N], landed i64[K,T], metrics f32[K,T,2])``."""
    flat = np.asarray(flat, dtype=np.float32)
    if tenants % dp:
        raise ValueError(f"tenants {tenants} not divisible by dp={dp}")
    per = tenants // dp
    block = flat.reshape(dp, -1)
    parts = [
        scan_mod.decode_fleet_block(
            block[d], rounds=rounds, tenants=per, num_nodes=num_nodes
        )
        for d in range(dp)
    ]
    return tuple(
        np.concatenate([p[i] for p in parts], axis=1) for i in range(4)
    )


def _rtt_ms(reps: int = 7) -> float:
    """Host↔device round-trip floor (bench.py's measure_rtt_ms, local so
    the harness is importable without the top-level script)."""

    @jax.jit
    def tick(x):
        return x + 1.0

    float(tick(jnp.float32(0)))  # compile
    times = []
    for i in range(reps):
        t0 = time.perf_counter()
        float(tick(jnp.float32(i)))
        times.append(time.perf_counter() - t0)
    return sorted(times)[len(times) // 2] * 1e3


def bench_multichip(
    tenants: int = 16,
    n_services: int = 2000,
    n_nodes: int = 256,
    rounds: int = 8,
    reps: int = 3,
    *,
    registry=None,
    rtt_ms: float | None = None,
) -> dict:
    """The measured MULTICHIP cell: ``fleet_scan_rounds`` composed with
    the dp mesh over ``tenants`` same-shaped power-law tenants, timed as
    whole fenced blocks (dispatch → K scanned rounds on every device →
    ONE ``round_end`` pull). Headline: ``fleet_scan_rounds_per_sec`` —
    fleet rounds committed per wall second, median over ``reps`` blocks.

    Every block feeds ``telemetry.mesh.MeshPlane`` (dispatch-wall
    attribution weighted by each shard's pulled comm-cost column — an
    attribution, not a per-device clock), so the record carries the
    per-device step-time rollup and the imbalance ratio alongside the
    throughput. The nested ``device_step_reading`` is its own ledger
    series (``multichip_device_step_ms_p99``, better: lower)."""
    from kubernetes_rescheduling_tpu.backends.base import device_kind
    from kubernetes_rescheduling_tpu.bench.harness import make_fleet_problem
    from kubernetes_rescheduling_tpu.policies import POLICY_IDS
    from kubernetes_rescheduling_tpu.solver.fleet import stack_tenants
    from kubernetes_rescheduling_tpu.telemetry.mesh import MeshPlane

    registry = registry if registry is not None else get_registry()
    reps = max(1, int(reps))
    mesh = _fleet_mesh(int(tenants), None)
    dp = mesh.shape["dp"]
    names = dp_device_names(mesh)
    plane = MeshPlane(registry, device_names=names)
    if rtt_ms is None:
        rtt_ms = _rtt_ms()

    states, graphs = make_fleet_problem(
        tenants=tenants, n_services=n_services, n_nodes=n_nodes
    )
    st, gr = stack_tenants(states), stack_tenants(graphs)
    pid = jnp.asarray(POLICY_IDS["communication"])
    thr = jnp.asarray(30.0)
    tenant_keys = jnp.stack(
        [
            jax.random.fold_in(jax.random.PRNGKey(0), t)
            for t in range(tenants)
        ]
    )

    def block(start: int):
        flat = scan_mod.pull_block(
            fleet_scan_rounds_dp(
                st,
                gr,
                pid,
                thr,
                tenant_keys,
                jnp.asarray(start, jnp.int32),
                rounds=rounds,
                mesh=mesh,
            ),
            registry=registry,
        )
        return flat

    flat = block(0)  # compile outside the timed blocks
    times = []
    for i in range(reps):
        t0 = time.perf_counter()
        flat = block((i + 1) * rounds)
        elapsed = time.perf_counter() - t0
        times.append(elapsed)
        _dec, _hz, _landed, metrics = decode_fleet_block_dp(
            flat, rounds=rounds, tenants=tenants, num_nodes=n_nodes, dp=dp
        )
        # per-tenant comm cost summed over the block's rounds — tenant
        # block i's share of the fence lands on device i (the same
        # weights the live fleet loop feeds observe_mesh)
        summary, _event = plane.observe_block(
            dispatch_s=elapsed,
            transfer_bytes=int(flat.nbytes),
            weights=metrics[..., 0].sum(axis=0),
            rounds=rounds,
            round=(i + 1) * rounds,
        )
        scan_mod.count_scan_block(registry, rounds)

    block_s = sorted(times)[len(times) // 2]
    rounds_per_sec = rounds / max(block_s, 1e-9)
    # trace accounting lives in the default registry (instrument_jit
    # wraps at module import, before any injected registry exists)
    traces = int(
        get_registry()
        .counter("jax_traces_total", labelnames=("fn",))
        .labels(fn="fleet_scan_rounds_dp")
        .value
    )
    step = plane.health_block()["step_ms"]
    kind = device_kind(dp)
    base_extra = {
        "scenario": "multichip",
        "tenants": tenants,
        "n_devices": dp,
        "device_kind": kind,
        "devices": list(names),
    }
    return {
        "metric": "fleet_scan_rounds_per_sec",
        "value": round(rounds_per_sec, 3),
        "unit": "rounds/s",
        "better": "higher",
        "extra": {
            **base_extra,
            "services_per_tenant": n_services,
            "nodes_per_tenant": n_nodes,
            "dp": dp,
            "rounds_per_block": rounds,
            "reps": reps,
            "block_ms": round(block_s * 1e3, 3),
            # fenced ≈ rtt + device + dispatch: the attribution the
            # measured record owes the reader (a tunneled rig's RTT can
            # dominate the block wall)
            "rtt_ms": round(rtt_ms, 3),
            "dispatch_frac": round(
                min(1.0, (rtt_ms / 1e3) / max(block_s, 1e-9)), 4
            ),
            "step_ms_p50": round(step["p50"], 4),
            "step_ms_p99": round(step["p99"], 4),
            "step_ms_max": round(step["max"], 4),
            "imbalance_ratio": round(summary["ratio"], 4),
            "worst_device": summary["worst_device"],
            # one trace for the whole run — the composed kernel pays its
            # compile once (the multichip trace pin)
            "fleet_scan_rounds_dp_traces": traces,
        },
        # the per-device rollup as its own ledger series (better: lower)
        # so a device-imbalance regression trends independently of the
        # throughput headline
        "device_step_reading": {
            "metric": "multichip_device_step_ms_p99",
            "value": round(step["p99"], 4),
            "unit": "ms",
            "better": "lower",
            "extra": {
                **base_extra,
                "step_ms_p50": round(step["p50"], 4),
                "step_ms_max": round(step["max"], 4),
                "imbalance_ratio": round(summary["ratio"], 4),
            },
        },
    }
