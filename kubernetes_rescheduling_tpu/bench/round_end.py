"""Single-bundle round-end transfers for the live control loop.

BENCH_r04/r05 put the live plane's wall-clock round at 4-5x device time,
and most of the gap is device<->host round trips: before this module the
controller paid up to SIX per executed round — two uncounted scalar
reads (``communication_cost`` / ``load_std``), plus one counted ``pull``
each for ``decision_explain``, ``attribution``, the forecast diag, and
``solver_objectives``. This module folds all of them into ONE round-end
bundle:

- :func:`round_end_metrics` — the device half: one compiled program
  (``controller_round_end``, instrumented — the 1-steady-state-trace
  invariant applies) producing ``[communication_cost, load_std]`` and,
  when attribution is on, the flat attribution bundle, in a single flat
  f32 vector.
- :class:`RoundCloser` — the host half: a per-round accumulator of
  device-resident diagnostic pieces (the metrics vector, explain
  bundles, the forecast diag, solver objectives). :meth:`RoundCloser.flush`
  concatenates the pieces on device, pulls them in ONE counted transfer
  (``site="round_end"``), slices them back out host-side, and runs each
  piece's decode callback in registration order.

Degraded rounds (a failed post-move monitor) historically re-ran the
metric kernels on the carried snapshot and re-pulled values bit-equal to
the previous round's — now they reuse the cached host values (or the
still-unpulled device bundle of a mid-round probe/remask snapshot), so a
degraded round costs at most one transfer and often zero.

:func:`fence` is the apply boundary: the ONE place the round functions
materialize decision outputs on the host (``jax.device_get`` of the
whole tuple — one batched host read instead of per-element ``int()`` /
``bool()`` syncs). ``scripts/check_apply_boundary.py`` statically pins
``block_until_ready``/``pull``/``device_get`` in the controller modules
to this module's designated sites.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_rescheduling_tpu.objectives.metrics import (
    communication_cost,
    communication_cost_attribution,
    communication_cost_edges,
    load_std,
)
from kubernetes_rescheduling_tpu.telemetry import instrument_jit, pull
from kubernetes_rescheduling_tpu.telemetry.registry import MetricsRegistry

ROUND_END_SITE = "round_end"

# layout of the metrics head inside the round-end vector
METRIC_COST = 0
METRIC_LOAD_STD = 1
METRIC_HEAD = 2


def round_end_metrics(state, graph, *, top_k: int = 0, edges=None) -> jax.Array:
    """Everything the host needs to close a round's reporting, in one
    compiled program: ``[communication_cost, load_std]`` followed — when
    ``top_k > 0`` — by the flat attribution bundle
    (``objectives.metrics.communication_cost_attribution``; per-edge
    contributions sum back to the scalar recorded two slots earlier, so
    the ``attribution_consistent`` invariant holds by construction).

    ``edges`` (a precomputed ``objectives.metrics.comm_edge_list``) is
    the attribution-off fast path: the cost scalar contracts over the
    graph's actual edges in O(E·N) instead of the dense O(S²·N)
    quadratic form — on CPU sim at powerlaw scale the difference
    between the metrics kernel dominating the round and vanishing into
    it. With ``top_k > 0`` the dense S×S work is needed for the
    attribution bundle anyway, so the scalar stays on the dense kernel
    (keeping the sum-consistency invariant's summation order); callers
    must pick ONE formulation per run — the controller's round-end
    protocol and the scanned schedule share this choice, which is what
    keeps their records bit-identical."""
    if top_k > 0 or edges is None:
        cost = communication_cost(state, graph)
    else:
        cost = communication_cost_edges(state, graph.num_services, edges)
    head = jnp.stack(
        [cost.astype(jnp.float32), load_std(state).astype(jnp.float32)]
    )
    if top_k > 0:
        return jnp.concatenate(
            [head, communication_cost_attribution(state, graph, top_k=top_k)]
        )
    return head


# one dispatch per fresh snapshot; same steady-state contract as the
# decision kernels — jax_traces_total{fn="controller_round_end"} == 1 per
# (shape, top_k) signature plus counted bucket promotions
_round_end = instrument_jit(
    round_end_metrics, name="controller_round_end", static_argnames=("top_k",)
)


def dispatch_round_end(state, graph, *, top_k: int = 0, edges=None) -> jax.Array:
    """Async dispatch of the round-end kernel (no host sync)."""
    return _round_end(state, graph, top_k=top_k, edges=edges)


def fence(tree):
    """The apply boundary: materialize device outputs on the host as ONE
    batched read (``jax.device_get`` fences and transfers the whole
    pytree together — never per-element ``int()``/``bool()`` syncs)."""
    return jax.device_get(tree)


def block(tree):
    """Completion fence WITHOUT a host transfer
    (``jax.block_until_ready``) — the timing boundary for fenced device
    measurements (the fleet loop's batched solve). Like :func:`fence`,
    this is a designated apply-boundary site for
    ``scripts/check_apply_boundary.py``."""
    return jax.block_until_ready(tree)


class RoundCloser:
    """One per round: device-resident diagnostics in, ONE transfer out.

    ``defer(arr, decode)`` registers a device array (any shape/dtype —
    flattened to f32 on device) plus a host callback receiving the
    decoded ``np.ndarray`` reshaped to the original shape. ``flush()``
    pulls every pending piece as a single counted ``round_end`` transfer
    and runs the decodes in registration order; pure-host callbacks
    registered via ``defer_host`` interleave at their registered
    position (a degraded round's cached metric values ride this path,
    costing no transfer)."""

    def __init__(self, registry: MetricsRegistry | None = None) -> None:
        self.registry = registry
        # (dev_flat | None, shape, dtype, decode) in registration order;
        # dev_flat None = host-only callback (no transfer contribution)
        self._pieces: list[tuple[Any, tuple, Any, Callable]] = []
        self.flushed = False

    def defer(self, arr: jax.Array, decode: Callable[[np.ndarray], None]) -> None:
        if self.flushed:
            raise RuntimeError("RoundCloser already flushed")
        shape = tuple(arr.shape)
        self._pieces.append(
            (jnp.ravel(arr).astype(jnp.float32), shape, arr.dtype, decode)
        )

    def defer_host(self, decode: Callable[[], None]) -> None:
        """A host-side finalize step with no device payload."""
        if self.flushed:
            raise RuntimeError("RoundCloser already flushed")
        self._pieces.append((None, (), None, decode))

    @property
    def has_device_pieces(self) -> bool:
        return any(dev is not None for dev, *_ in self._pieces)

    def flush(self) -> None:
        """Close the round: ONE pull for every device piece, then the
        decode callbacks in order. A round with no device pieces (a
        degraded round closing on cached values) pulls nothing and
        counts nothing — the transfer counter reports what actually
        crossed."""
        if self.flushed:
            raise RuntimeError("RoundCloser already flushed")
        self.flushed = True
        dev = [p[0] for p in self._pieces if p[0] is not None]
        flat = None
        if dev:
            bundle = dev[0] if len(dev) == 1 else jnp.concatenate(dev)
            flat = pull(bundle, site=ROUND_END_SITE, registry=self.registry)
        off = 0
        for dev_flat, shape, dtype, decode in self._pieces:
            if dev_flat is None:
                decode()
                continue
            n = int(dev_flat.shape[0])
            piece = np.asarray(flat[off : off + n])
            off += n
            if dtype is not None and np.dtype("float32") != np.dtype(dtype):
                piece = piece.astype(dtype)
            decode(piece.reshape(shape))
