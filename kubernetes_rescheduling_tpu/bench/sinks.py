"""Metric sinks.

CSV files keep the reference's exact schemas so existing analysis tooling
works unchanged: ``node_std.csv`` with ``timestamp,cpu_std`` (reference
nodemonitor.py:59-73) and ``communication_cost.csv`` with ``timestamp,cost``
(reference communicationcost.py:52-64). JSONL is the structured superset.
"""

from __future__ import annotations

import csv
import json
import os
from dataclasses import dataclass
from datetime import datetime
from pathlib import Path
from typing import Any


@dataclass
class CsvSink:
    """Append-only CSV with a header row on first write (reference
    nodemonitor.py:63-73 semantics)."""

    path: str | Path
    columns: tuple[str, ...] = ("timestamp", "value")

    def append(self, *values: Any) -> None:
        p = Path(self.path)
        p.parent.mkdir(parents=True, exist_ok=True)
        exists = p.is_file()
        with p.open("a", newline="") as f:
            w = csv.writer(f)
            if not exists:
                w.writerow(self.columns)
            ts = datetime.now().strftime("%Y-%m-%d %H:%M:%S")
            w.writerow([ts, *values])


@dataclass
class JsonlSink:
    """One JSON object per line."""

    path: str | Path

    def append(self, record: dict[str, Any]) -> None:
        p = Path(self.path)
        p.parent.mkdir(parents=True, exist_ok=True)
        with p.open("a") as f:
            f.write(json.dumps(record, default=float) + "\n")


def node_std_sink(directory: str | Path) -> CsvSink:
    return CsvSink(Path(directory) / "node_std.csv", ("timestamp", "cpu_std"))


def communication_cost_sink(directory: str | Path) -> CsvSink:
    return CsvSink(Path(directory) / "communication_cost.csv", ("timestamp", "cost"))
