"""Request-level load generation.

The reference validates placements with a fleet of ~1000 concurrent curl
clients hammering the µBench entry service for 180 s, reporting success and
error counts plus min/avg/max latency (reference release1.sh:7-10, 29-42,
74-117) and sustaining the same load while the rescheduling loop runs
(reference release2.sh:50-59). Round 1 replaced all of that with a
four-constant analytic formula; this module replaces the formula with an
actual simulated request stream, so response-time results come from
per-request dynamics, not curve-fitting.

Model
-----
A request enters at the entry service (µBench ``s0`` behind the NodePort,
reference release1.sh:7) and fans out along the *directed* call graph —
each request to a service issues one sub-request to every callee
(workmodelC.json ``external_services`` semantics). End-to-end latency is the
recursive sum over the call DAG::

    L(s) = proc(s) · q(node(s)) + Σ_{c ∈ callees(s)} [ hop(s, c) + L(c) ]

- ``proc(s)``: base service time, inflated by an M/M/1-shaped queueing
  factor ``q = 1/(1-ρ)`` of the replica's node — overloaded nodes answer
  slowly (the "Before" state's signature, SURVEY.md §6).
- ``hop(s, c)``: cheap if caller and callee replicas share a node, a
  network round-trip over the CNI if not — the quantity CAR minimizes.
- Each request picks one replica per service uniformly at random (k8s
  Service load balancing, simplified to one draw per request rather than
  per sub-request — connection reuse within a request); latency also
  carries multiplicative lognormal jitter.

Errors come from two sources, mirroring the reference's counters:

- **outage**: a Deployment being torn down and re-created serves nothing
  (the reference polls up to 180 s for the 404, delete_replaced_pod.py:8-22);
  requests that traverse it during the window fail. This is the simulated
  analogue of the reference's container-restart accounting
  (release1.sh:101-102) — disruption now has a visible cost.
- **overload**: a node driven past 100% CPU drops a utilization-dependent
  fraction of the requests it serves.

TPU-first shape
---------------
The hot path is one jitted kernel over a fixed-size request chunk: the call
graph is an **edge list** (``src[E]``, ``dst[E]``), latency propagation is
``depth`` rounds of gather + scatter-add (depth = longest path in the
cycle-broken DAG, computed host-side), and everything is batched over the
chunk — no Python per request, no retracing across segments (shapes are
static). The same kernel serves 20-service µBench and 10k-service synthetic
meshes; memory is O(chunk · E), never O(S²).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Mapping, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_rescheduling_tpu.core.state import ClusterState
from kubernetes_rescheduling_tpu.core.workmodel import (
    Workmodel,
    kahn_traversal,
    propagate_entry_rate,
)


@dataclass(frozen=True)
class LoadGenConfig:
    """Knobs for the simulated client fleet (reference release1.sh:7-10).

    The reference's ~1000 concurrent clients show up in two places here:
    the *offered CPU load* is the sim backend's ``LoadModel.entry_rps``,
    and the *measurement sample* is ``requests_per_phase`` requests drawn
    uniformly over ``duration_s``.
    """

    duration_s: float = 180.0      # load duration (release1.sh:8)
    requests_per_phase: int = 8192 # sampled requests per measurement phase
    chunk: int = 1024              # requests per kernel invocation (static shape)
    entry_service: str = "s0"      # NodePort target (release1.sh:7)
    proc_ms: float = 1.5           # base per-service processing time
    hop_local_ms: float = 0.2      # same-node call
    hop_remote_ms: float = 3.0     # cross-node call over the CNI
    queue_rho_cap: float = 0.95    # ρ clamp for the 1/(1-ρ) factor
    jitter_sigma: float = 0.15     # lognormal latency jitter
    drop_rho: float = 1.0          # nodes past this utilization drop requests
    max_drop_p: float = 0.95       # per-service drop probability ceiling


@dataclass(frozen=True)
class RequestStats:
    """The reference's client-side stat block (release1.sh:74-117)."""

    sent: int
    ok: int
    err_outage: int
    err_overload: int
    duration_s: float
    latency_min_ms: float
    latency_avg_ms: float
    latency_max_ms: float
    latency_p50_ms: float
    latency_p95_ms: float
    latency_p99_ms: float
    # pods recreated by Deployment moves (every replica of a moved service
    # restarts) — the disruption the RESCHEDULER causes. Same semantics on
    # sim (event log) and live (replicas of moved services).
    restarts: int = 0
    # measured container-crash delta over the window (the reference's
    # restartCount metric, release1.sh:101-102 — delete+recreate does NOT
    # count here; crashes do). None = backend could not measure it.
    container_crashes: int | None = None

    @property
    def errors(self) -> int:
        return self.err_outage + self.err_overload

    @property
    def error_rate(self) -> float:
        return self.errors / self.sent if self.sent else 0.0

    def as_dict(self) -> dict:
        return {
            "sent": self.sent,
            "ok": self.ok,
            "errors": self.errors,
            "err_outage": self.err_outage,
            "err_overload": self.err_overload,
            "error_rate": self.error_rate,
            "duration_s": self.duration_s,
            "latency_min_ms": self.latency_min_ms,
            "latency_avg_ms": self.latency_avg_ms,
            "latency_max_ms": self.latency_max_ms,
            "latency_p50_ms": self.latency_p50_ms,
            "latency_p95_ms": self.latency_p95_ms,
            "latency_p99_ms": self.latency_p99_ms,
            "restarts": self.restarts,
            "container_crashes": self.container_crashes,
        }


@dataclass(frozen=True)
class CallPlan:
    """Host-side precomputation of the call DAG (static across a phase)."""

    names: tuple[str, ...]
    entry: int
    src: np.ndarray          # i32[E] caller service index per edge
    dst: np.ndarray          # i32[E] callee service index per edge
    reach: np.ndarray        # bool[S] reachable from entry (cycle-broken DAG)
    depth: int               # longest entry-reachable path, in edges

    @property
    def num_services(self) -> int:
        return len(self.reach)


def build_call_plan(
    relation: Mapping[str, Sequence[str]],
    names: Sequence[str],
    entry_service: str,
) -> CallPlan:
    """Extract the cycle-broken edge list + entry reachability/depth.

    Uses the shared :func:`core.workmodel.kahn_traversal`, so latency and
    CPU-load propagation agree on which edges exist in a cyclic mesh.
    """
    names = tuple(names)
    index = {n: i for i, n in enumerate(names)}
    S = len(names)

    order, name_edges = kahn_traversal(relation, names)
    edges = [(index[s], index[d]) for s, d in name_edges]
    src = np.asarray([e[0] for e in edges], dtype=np.int32)
    dst = np.asarray([e[1] for e in edges], dtype=np.int32)

    reach = np.zeros(S, dtype=bool)
    depth = 0
    if entry_service in index:
        reach[index[entry_service]] = True
        out_edges: dict[int, list[int]] = {}
        for s, d in edges:
            out_edges.setdefault(s, []).append(d)
        # propagate reachability + longest path in topological order
        dist = np.full(S, -1, dtype=np.int64)
        dist[index[entry_service]] = 0
        for svc in order:
            i = index[svc]
            if dist[i] < 0:
                continue
            for d in out_edges.get(i, ()):
                if dist[d] < dist[i] + 1:
                    dist[d] = dist[i] + 1
                    reach[d] = True
        depth = int(dist.max()) if (dist >= 0).any() else 0
    return CallPlan(
        names=names,
        entry=index.get(entry_service, -1),
        src=src,
        dst=dst,
        reach=reach,
        depth=max(depth, 1),
    )


@functools.partial(jax.jit, static_argnames=("depth", "chunk"))
def _request_chunk(
    key: jax.Array,
    src: jax.Array,            # i32[E]
    dst: jax.Array,            # i32[E]
    entry: jax.Array,          # i32 scalar
    proc_ms: jax.Array,        # f32[S]
    replica_nodes: jax.Array,  # i32[S, Rmax] node of each replica (pad = 0)
    replica_counts: jax.Array, # i32[S] placed replicas (0 = unavailable)
    node_rho: jax.Array,       # f32[N] utilization fraction
    outage_frac: jax.Array,    # f32[S, 2] outage window as fractions of phase
    edge_p: jax.Array,         # f32[E] per-edge call probability
    n_valid: jax.Array,        # i32 scalar: real requests in this chunk
    cfg_vec: jax.Array,        # f32[6] local, remote, rho_cap, jitter,
                               #        drop_rho, max_drop_p
    *,
    depth: int,
    chunk: int,
):
    """Simulate one fixed-size chunk of requests. Returns per-request
    ``(latency_ms, ok, err_outage, err_overload)`` plus the per-edge
    traversal count over the chunk's first ``n_valid`` requests — the
    observed-traffic signal the weight estimator aggregates."""
    local_ms, remote_ms, rho_cap, jitter, drop_rho, max_drop_p = (
        cfg_vec[0], cfg_vec[1], cfg_vec[2], cfg_vec[3],
        cfg_vec[4], cfg_vec[5],
    )
    S = proc_ms.shape[0]
    k_rep, k_t, k_jit, k_drop, k_edge = jax.random.split(key, 5)

    # each sub-request picks a replica uniformly (k8s Service balancing)
    u = jax.random.uniform(k_rep, (chunk, S))
    ridx = jnp.minimum(
        (u * jnp.maximum(replica_counts, 1)).astype(jnp.int32),
        jnp.maximum(replica_counts - 1, 0),
    )
    svc_node = replica_nodes[jnp.arange(S)[None, :], ridx]  # i32[chunk, S]

    # sample this request's call tree: each kept edge fires with its own
    # probability (uniform fanout_frac unless the caller supplied per-edge
    # probabilities — actual deployed traffic need not match the declared
    # call graph)
    E = src.shape[0]
    active = jax.random.uniform(k_edge, (chunk, E)) < edge_p[None, :]

    # queue-inflated processing time per (request, service)
    rho = jnp.clip(node_rho, 0.0, rho_cap)
    q = 1.0 / (1.0 - rho)                                # f32[N]
    proc_q = proc_ms[None, :] * q[svc_node]              # f32[chunk, S]

    # per-edge hop cost: local if caller/callee replicas share a node
    n_src = svc_node[:, src]                             # [chunk, E]
    n_dst = svc_node[:, dst]
    hop = jnp.where(n_src == n_dst, local_ms, remote_ms)
    af = active.astype(proc_q.dtype)

    # latency: depth rounds of L = proc·q + scatter-add of active sub-calls
    def lat_step(lat, _):
        lat = proc_q.at[:, src].add(af * (hop + lat[:, dst]))
        return lat, None

    lat, _ = jax.lax.scan(lat_step, proc_q, None, length=depth)
    latency = lat[:, entry]
    latency = latency * jnp.exp(
        jitter * jax.random.normal(k_jit, (chunk,))
    )

    # which services this request's sampled call tree actually visits
    entry_visit = jnp.zeros((chunk, S), bool).at[:, entry].set(True)

    def visit_step(v, _):
        v = entry_visit.at[:, dst].max(active & v[:, src])
        return v, None

    visited, _ = jax.lax.scan(visit_step, entry_visit, None, length=depth)

    # outage: arrival time falls inside a visited service's teardown window
    t = jax.random.uniform(k_t, (chunk,))                # phase-fraction arrivals
    down = (t[:, None] >= outage_frac[None, :, 0]) & (t[:, None] < outage_frac[None, :, 1])
    unavailable = replica_counts[None, :] == 0
    err_outage = jnp.any(visited & (down | unavailable), axis=1)

    # overload: each visited service on a >drop_rho node drops requests
    rho_at = node_rho[svc_node]                          # [chunk, S]
    p_drop = jnp.clip(1.0 - drop_rho / jnp.maximum(rho_at, 1e-6), 0.0, max_drop_p)
    p_drop = jnp.where(visited, p_drop, 0.0)
    log_survive = jnp.sum(jnp.log1p(-p_drop), axis=1)
    survive = jnp.exp(log_survive)
    err_overload = (~err_outage) & (
        jax.random.uniform(k_drop, (chunk,)) > survive
    )

    ok = ~(err_outage | err_overload)

    # observed traffic: an edge is traversed when its caller is visited and
    # the edge fired; only the chunk's real (non-padding) rows count
    rowmask = jnp.arange(chunk) < n_valid
    edge_count = jnp.sum(
        active & visited[:, src] & rowmask[:, None], axis=0
    ).astype(jnp.int32)
    return latency, ok, err_outage, err_overload, edge_count


@dataclass
class _Samples:
    """Accumulated per-request outcomes across chunks/segments."""

    latencies: list[np.ndarray] = field(default_factory=list)
    sent: int = 0
    err_outage: int = 0
    err_overload: int = 0
    sim_s: float = 0.0
    restarts: int = 0
    container_crashes: int | None = None
    # per-edge traversal totals (aligned with the generator's CallPlan edge
    # list) — the observed-traffic signal for weight estimation
    edge_counts: np.ndarray | None = None

    def extend(self, latency, ok, e_out, e_over, n: int, edge_count=None) -> None:
        lat = np.asarray(latency[:n])
        okm = np.asarray(ok[:n])
        self.latencies.append(lat[okm])
        self.sent += n
        self.err_outage += int(np.asarray(e_out[:n]).sum())
        self.err_overload += int(np.asarray(e_over[:n]).sum())
        if edge_count is not None:
            ec = np.asarray(edge_count, dtype=np.int64)
            if self.edge_counts is None:
                self.edge_counts = ec.copy()
            else:
                self.edge_counts += ec

    def stats(self) -> RequestStats:
        lat = (
            np.concatenate(self.latencies)
            if self.latencies
            else np.zeros(0, dtype=np.float32)
        )
        have = lat.size > 0
        return RequestStats(
            sent=self.sent,
            ok=int(lat.size),
            err_outage=self.err_outage,
            err_overload=self.err_overload,
            duration_s=self.sim_s,
            latency_min_ms=float(lat.min()) if have else 0.0,
            latency_avg_ms=float(lat.mean()) if have else 0.0,
            latency_max_ms=float(lat.max()) if have else 0.0,
            latency_p50_ms=float(np.percentile(lat, 50)) if have else 0.0,
            latency_p95_ms=float(np.percentile(lat, 95)) if have else 0.0,
            latency_p99_ms=float(np.percentile(lat, 99)) if have else 0.0,
            restarts=self.restarts,
            container_crashes=self.container_crashes,
        )


class LoadGenerator:
    """Simulated client fleet over a workmodel + placements.

    Reusable across phases and segments: the call plan and kernel compile
    once per (workmodel, chunk) pair; each :meth:`run` re-binds placement,
    utilization, and outage windows (cheap device transfers).
    """

    def __init__(
        self,
        workmodel: Workmodel,
        cfg: LoadGenConfig | None = None,
        *,
        fanout_frac: float = 1.0,
        edge_probs: Mapping[tuple[str, str], float] | None = None,
    ):
        """``fanout_frac`` is the per-edge call probability and MUST come
        from the same place the CPU-load model reads it
        (``backends.sim.LoadModel.fanout_frac``) — it is a constructor
        argument rather than a config field precisely so callers pass the
        backend's value instead of maintaining a second copy.

        ``edge_probs`` overrides the probability of individual directed
        edges ``(caller, callee)`` — how ACTUAL traffic diverges from the
        declared call graph (a canary taking most of the traffic, a
        feature-flagged path going cold). The weight estimator recovers
        these from observed traversal counts."""
        self.cfg = cfg or LoadGenConfig()
        self.workmodel = workmodel
        self.fanout_frac = fanout_frac
        names = workmodel.names
        self.plan = build_call_plan(
            workmodel.directed_relation(), names, self.cfg.entry_service
        )
        self._svc_index = {n: i for i, n in enumerate(names)}
        c = self.cfg
        self._cfg_vec = jnp.asarray(
            [c.hop_local_ms, c.hop_remote_ms, c.queue_rho_cap,
             c.jitter_sigma, c.drop_rho, c.max_drop_p],
            jnp.float32,
        )
        edge_p = np.full(len(self.plan.src), fanout_frac, dtype=np.float32)
        for (a, b), p in (edge_probs or {}).items():
            ia, ib = self._svc_index.get(a), self._svc_index.get(b)
            if ia is None or ib is None:
                continue
            hit = (self.plan.src == ia) & (self.plan.dst == ib)
            edge_p[hit] = p
        self._edge_p = jnp.asarray(edge_p)
        # static across phases/segments: ship to device once
        self._src = jnp.asarray(self.plan.src)
        self._dst = jnp.asarray(self.plan.dst)
        self._entry = jnp.asarray(self.plan.entry, jnp.int32)
        # per-service base service time: cfg.proc_ms scaled by the
        # workmodel's cpu_stress-derived relative cost (workmodelC.json
        # gives every service its OWN stress parameters — a heavy s3 on a
        # hot node must dominate latency, not average away)
        self._proc_ms = jnp.asarray(
            [c.proc_ms * s.proc_cost for s in workmodel.services], jnp.float32
        )

    def _placement_arrays(self, state: ClusterState):
        """Per-service replica→node tables from a cluster snapshot."""
        S = self.plan.num_services
        pod_svc = np.asarray(state.pod_service)
        pod_node = np.asarray(state.pod_node)
        valid = np.asarray(state.pod_valid) & (pod_node >= 0)
        by_svc: list[list[int]] = [[] for _ in range(S)]
        for i in np.flatnonzero(valid):
            s = int(pod_svc[i])
            if 0 <= s < S:
                by_svc[s].append(int(pod_node[i]))
        rmax = max(1, max((len(v) for v in by_svc), default=1))
        nodes = np.zeros((S, rmax), dtype=np.int32)
        counts = np.zeros(S, dtype=np.int32)
        for s, v in enumerate(by_svc):
            counts[s] = len(v)
            for r, n in enumerate(v):
                nodes[s, r] = n
        return nodes, counts

    def run(
        self,
        state: ClusterState,
        key: jax.Array,
        *,
        duration_s: float | None = None,
        n_requests: int | None = None,
        outages: Sequence[tuple[str, float, float]] = (),
        samples: _Samples | None = None,
    ) -> _Samples:
        """Simulate one phase/segment of load against a placement snapshot.

        ``outages``: (service, start_s, end_s) windows within the phase
        during which that service's Deployment serves nothing — at most one
        window per service (duplicates raise rather than silently merging).
        Pass ``samples`` to accumulate across segments (phase r2).
        """
        cfg = self.cfg
        duration = cfg.duration_s if duration_s is None else duration_s
        total = cfg.requests_per_phase if n_requests is None else n_requests
        samples = samples if samples is not None else _Samples()
        if total <= 0 or self.plan.entry < 0:
            samples.sim_s += duration
            return samples

        nodes, counts = self._placement_arrays(state)
        S = self.plan.num_services
        outage = np.zeros((S, 2), dtype=np.float32)
        seen_outage: set[int] = set()
        for svc, start, end in outages:
            i = self._svc_index.get(svc)
            if i is None or duration <= 0:
                continue
            if i in seen_outage:
                raise ValueError(
                    f"multiple outage windows for {svc!r}; split the phase "
                    "into segments instead (one window per service each)"
                )
            seen_outage.add(i)
            outage[i] = (start / duration, end / duration)

        rho = np.asarray(state.node_cpu_pct(), dtype=np.float32) / 100.0
        head = (
            self._src,
            self._dst,
            self._entry,
            self._proc_ms,
            jnp.asarray(nodes),
            jnp.asarray(counts),
            jnp.asarray(rho),
            jnp.asarray(outage),
            self._edge_p,
        )
        done = 0
        chunk_i = 0
        while done < total:
            n = min(cfg.chunk, total - done)
            sub = jax.random.fold_in(key, chunk_i)
            latency, ok, e_out, e_over, edge_count = _request_chunk(
                sub, *head, jnp.asarray(n, jnp.int32), self._cfg_vec,
                depth=self.plan.depth, chunk=cfg.chunk,
            )
            samples.extend(latency, ok, e_out, e_over, n, edge_count)
            done += n
            chunk_i += 1
        samples.sim_s += duration
        return samples

    def measure(
        self,
        state: ClusterState,
        key: jax.Array,
        *,
        duration_s: float | None = None,
        outages: Sequence[tuple[str, float, float]] = (),
    ) -> RequestStats:
        """One self-contained measurement phase (reference release1.sh)."""
        return self.run(
            state, key, duration_s=duration_s, outages=outages
        ).stats()

    def observed_weights(
        self, edge_counts: np.ndarray, sent: int
    ) -> dict[tuple[str, str], float]:
        """Symmetric pair weights from OBSERVED traversal counts: expected
        traversals per request per service pair.

        The reference's objective is defined on actual deployed traffic
        (reference README.md:47, communicationcost.py:40-45) — a declared
        workmodel whose call graph has drifted from reality silently
        misdirects the solver; these weights ground it in what the request
        stream really did.
        """
        out: dict[tuple[str, str], float] = {}
        if sent <= 0:
            return out
        names = self.plan.names
        for e in range(len(self.plan.src)):
            a = names[int(self.plan.src[e])]
            b = names[int(self.plan.dst[e])]
            pair = (a, b) if a <= b else (b, a)
            out[pair] = out.get(pair, 0.0) + float(edge_counts[e]) / sent
        return out

    def observed_graph(
        self,
        edge_counts: np.ndarray | None,
        sent: int,
        base,
        *,
        prior_requests: float = 50.0,
    ):
        """``base`` CommGraph with its edge weights replaced by observed
        traffic rates (untraversed declared edges drop toward 0 — stale
        topology stops steering the solver).

        Observed rates are blended with the declared weight through a
        pseudo-count prior: ``(count + prior_requests·declared) /
        (sent + prior_requests)`` — a genuinely live low-rate edge is not
        hard-zeroed by a small sample (zero traversals out of 50 requests
        is weak evidence; out of 50k it isn't); the declared weight decays
        only as evidence accumulates. ``prior_requests=0`` restores the
        raw observed rates. Declared pairs the request model can never
        traverse (cycle-broken back-edges dropped by ``kahn_traversal``)
        are zeroed regardless — no amount of traffic can ever produce
        evidence for them, so the prior would pin them at the declared
        weight forever. Returns ``base`` unchanged when there is nothing
        observed yet."""
        from kubernetes_rescheduling_tpu.bench.trace import with_weights

        if edge_counts is None or sent <= 0:
            return base
        declared = self._declared_pairs(base)
        k = max(float(prior_requests), 0.0)
        updates = {
            pair: (rate * sent + k * declared.get(pair, 0.0)) / (sent + k)
            for pair, rate in self.observed_weights(edge_counts, sent).items()
        }
        for pair in declared:
            updates.setdefault(pair, 0.0)
        return with_weights(base, updates)

    def _declared_pairs(self, base) -> dict[tuple[str, str], float]:
        """The base graph's nonzero pairs (with their declared weights —
        the blending prior), enumerated ONCE per graph object and cached —
        the streaming estimator calls observed_graph every controller
        round against the same declared graph, and re-pulling the S×S
        adjacency to host each round would dominate the loop."""
        cached = getattr(self, "_declared_cache", None)
        if cached is not None and cached[0] is base:
            return cached[1]
        adj = np.asarray(base.adj)
        names = list(base.names)
        pairs = {
            tuple(sorted((names[int(i)], names[int(j)]))): float(adj[i, j])
            for i, j in np.argwhere(adj > 0)
            if i < j
        }
        self._declared_cache = (base, pairs)
        return pairs


@dataclass(frozen=True)
class RateProfile:
    """Per-service offered request-rate series over a run's horizon —
    the signal the elastic autoscaler consumes (Autopilot-style: replica
    targets follow traffic, not the other way around).

    ``base_rps`` is each service's steady-state rate from the SAME
    directed-call-graph propagation the simulator's CPU-load model uses
    (``backends.sim.LoadModel.service_rps``), so autoscaling and offered
    load agree on which services are hot. ``shape`` is a multiplicative
    time profile sampled at ``len(shape)`` points across the horizon;
    ``phase_offsets`` de-synchronizes services (seeded) so a mesh does
    not autoscale in lockstep.

    **Resampled, not truncated**: the series is indexed by *phase
    fraction* (``round_i / num_rounds``) with linear interpolation over
    the shape — a 30-round run over an 8-point shape sweeps the WHOLE
    profile, and a mid-run horizon change re-stretches it. The older
    array-indexing idiom (``shape[:rounds]``) silently played only the
    profile's head; regression-tested in tests/test_elastic.py.
    """

    names: tuple[str, ...]
    base_rps: np.ndarray          # f32[S] steady per-service total rate
    shape: np.ndarray             # f32[T] multiplicative profile
    phase_offsets: np.ndarray     # f32[S] per-service phase shift in [0, 1)

    def _factor_at(self, phase: np.ndarray) -> np.ndarray:
        """Linear interpolation of ``shape`` at wrapped phases — the
        resampling rule (never an array slice)."""
        t = np.mod(np.asarray(phase, dtype=np.float64), 1.0)
        grid = np.linspace(0.0, 1.0, len(self.shape), endpoint=False)
        # wrap-around interpolation: append the first point at phase 1.0
        xs = np.concatenate([grid, [1.0]])
        ys = np.concatenate([self.shape, self.shape[:1]])
        return np.interp(t, xs, ys)

    def factors(self, round_i: int, num_rounds: int) -> dict[str, float]:
        """Per-service rate factor (1.0 = steady) for one round."""
        phase = (round_i - 1) / max(num_rounds, 1) + self.phase_offsets
        f = self._factor_at(phase)
        return {name: float(f[i]) for i, name in enumerate(self.names)}

    def at(self, round_i: int, num_rounds: int) -> dict[str, float]:
        """Per-service TOTAL offered rate (rps) for one round."""
        phase = (round_i - 1) / max(num_rounds, 1) + self.phase_offsets
        f = self._factor_at(phase)
        return {
            name: float(self.base_rps[i] * f[i])
            for i, name in enumerate(self.names)
        }

    def per_replica(
        self, round_i: int, num_rounds: int, replicas: Mapping[str, int]
    ) -> dict[str, float]:
        """Per-REPLICA rate under the CURRENT live replica counts: the
        total series divides by whatever is deployed right now, so a
        mid-run scale-up halves per-pod rate instead of replaying a
        stale fixed-replica series (the truncation bug class this
        profile exists to avoid)."""
        total = self.at(round_i, num_rounds)
        return {
            name: rate / max(int(replicas.get(name, 1)), 1)
            for name, rate in total.items()
        }


def service_rate_series(
    workmodel: Workmodel,
    *,
    entry_rps: float = 100.0,
    fanout_frac: float = 1.0,
    entry_service: str = "s0",
    amplitude: float = 2.0,
    steps: int = 48,
    phase_jitter: float = 0.15,
    seed: int = 0,
) -> RateProfile:
    """Build the per-service request-rate series for a workmodel.

    Base rates propagate ``entry_rps`` through the cycle-broken directed
    call graph (one source of truth with the sim's CPU model:
    :func:`core.workmodel.kahn_traversal`); the time shape is a diurnal
    sinusoid swinging ×1/amplitude–×amplitude across the horizon, with a
    small seeded per-service phase offset.
    """
    if amplitude <= 0:
        raise ValueError(f"amplitude must be > 0, got {amplitude}")
    names = workmodel.names
    rng = np.random.default_rng(seed)
    # ONE propagation rule with the simulator's CPU-load model
    # (core.workmodel.propagate_entry_rate — LoadModel.service_rps calls
    # the same function): autoscaling can never disagree with offered
    # load about which services are hot
    rps = propagate_entry_rate(
        workmodel,
        entry_service=entry_service,
        entry_rps=entry_rps,
        fanout_frac=fanout_frac,
    )
    base = np.asarray([rps[n] for n in names], dtype=np.float64)
    t = np.linspace(0.0, 1.0, max(int(steps), 2), endpoint=False)
    shape = np.power(float(amplitude), np.sin(2.0 * np.pi * t))
    offsets = rng.uniform(0.0, max(phase_jitter, 0.0), size=len(names))
    return RateProfile(
        names=tuple(names),
        base_rps=base,
        shape=shape,
        phase_offsets=offsets,
    )


def open_loop_arrivals(
    rate_rps: float, n: int, seed: int = 0
) -> np.ndarray:
    """Seeded open-loop arrival offsets: ``n`` cumulative exponential
    inter-arrival gaps at mean rate ``rate_rps`` — a Poisson arrival
    process, f64[n] seconds from stream start.

    Open-loop means arrival times are drawn INDEPENDENTLY of service
    completions (the reference's curl fleet fires on its own clock,
    release1.sh:29-42): a slow server faces a growing queue instead of a
    politely backing-off client, which is exactly the regime where
    coordinated-omission-free tail latency and counted shedding are
    measured. The serving bench cell and the concurrency soak both drive
    :class:`~kubernetes_rescheduling_tpu.serving.ServingEngine` with
    this schedule."""
    if rate_rps <= 0:
        raise ValueError(f"rate_rps must be > 0, got {rate_rps}")
    if n < 0:
        raise ValueError(f"n must be >= 0, got {n}")
    rng = np.random.default_rng(seed)
    return np.cumsum(rng.exponential(1.0 / float(rate_rps), size=int(n)))


def new_samples() -> _Samples:
    """Fresh accumulator for a multi-segment phase (reference release2.sh)."""
    return _Samples()
