"""Backend-driven control loop.

The live counterpart of ``solver.run_rounds``: the same device kernels
(detect → victim → choose) run one round at a time, with cluster I/O between
rounds going through a ``Backend``. This is the loop the reference runs
against a real cluster (main.py:56-112); here it works identically against
the simulator — which is how the whole experiment matrix becomes hermetic.

The ``global`` algorithm routes through the batched solver instead of the
one-deployment greedy: one solve, then every service whose node changed is
moved (SURVEY.md §7 '--moves-per-round all' mode).
"""

from __future__ import annotations

import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_rescheduling_tpu.backends.base import Backend, MoveRequest
from kubernetes_rescheduling_tpu.backends.chaos import with_chaos
from kubernetes_rescheduling_tpu.backends.k8s import PlacementMechanism
from kubernetes_rescheduling_tpu.bench.admission import AdmissionGuard
from kubernetes_rescheduling_tpu.bench.boundary import (
    HALF_OPEN,
    OPEN,
    BoundaryClient,
    CircuitBreaker,
)
from kubernetes_rescheduling_tpu.bench.reconcile import (
    KIND_UNKNOWN_LANDING,
    IntentLedger,
    count_divergence,
    move_intent,
    reconcile_round_block,
)
from kubernetes_rescheduling_tpu.bench.round_end import (
    METRIC_COST,
    METRIC_HEAD,
    METRIC_LOAD_STD,
    RoundCloser,
    dispatch_round_end,
    fence,
)
from kubernetes_rescheduling_tpu.config import RescheduleConfig
from kubernetes_rescheduling_tpu.elastic.buckets import (
    device_graph,
    device_view,
)
from kubernetes_rescheduling_tpu.policies import POLICY_IDS
from kubernetes_rescheduling_tpu.policies.proactive import scoring_policy
from kubernetes_rescheduling_tpu.telemetry import (
    get_registry,
    instrument_jit,
    span,
)
from kubernetes_rescheduling_tpu.telemetry import attribution as attribution_mod
from kubernetes_rescheduling_tpu.telemetry import costmodel
from kubernetes_rescheduling_tpu.telemetry.explain import (
    greedy_explanation,
    solver_explanation,
)
from kubernetes_rescheduling_tpu.utils.checkpoint import CheckpointManager
from kubernetes_rescheduling_tpu.utils.logging import StructuredLogger
from kubernetes_rescheduling_tpu.utils.profiling import LatencyHistogram
from kubernetes_rescheduling_tpu.parallel.sharded import solve_with_restarts
from kubernetes_rescheduling_tpu.solver.global_solver import (
    GlobalSolverConfig,
    pct_balance_terms,
)
from kubernetes_rescheduling_tpu.solver.round_loop import (
    decide,
    decide_explain,
    decide_explain_with_forecast,
    decide_with_forecast,
)


@dataclass
class RoundRecord:
    round: int
    moved: bool
    most_hazard: str | None
    service: str | None
    target: str | None  # node the first move actually landed on
    communication_cost: float
    load_std: float
    services_moved: tuple[str, ...] = ()  # every Deployment recreated this round
    decision_latencies_s: tuple[float, ...] = ()  # one sample per decide/solve
    # global rounds: the solver's own before/after accounting (its info
    # dict), surfaced instead of dropped — None on greedy rounds
    objective_before: float | None = None
    objective_after: float | None = None
    solver_improved: bool | None = None
    # resilience: the breaker state the round ran under, whether the round
    # finished on a stale snapshot (post-move monitor failed), and how many
    # boundary failures it burned
    breaker_state: str = "closed"
    degraded: bool = False
    boundary_failures: int = 0
    # decision explainability: one DecisionExplanation dict per decide/
    # solve this round (telemetry.explain) — empty when explain is off
    explanations: tuple[dict, ...] = ()
    # every move that LANDED this round as (service, landed_node) pairs —
    # the provenance tracker's input (services_moved keeps only names)
    applied_moves: tuple[tuple[str, str], ...] = ()
    # cost attribution (telemetry.attribution): per-edge/per-node-pair
    # decomposition of communication_cost plus move provenance — None
    # when attribution is off
    attribution: dict | None = None
    # elastic topologies (elastic/engine.py): the churn applied before
    # this round — events, live S/N/P counts, the current shape buckets,
    # and the cumulative promotion count — None on static runs
    churn: dict | None = None
    # forecast plane (forecast/): the proactive round's model state —
    # skill vs the persistence baseline, running MAEs, and which path
    # the round took (cold/predictive/degraded) — None on reactive runs
    forecast: dict | None = None
    # reconciliation & admission (bench/admission.py + bench/reconcile.py):
    # the round's admission quarantine/reject counts, classified
    # intent-vs-observed divergences, issued corrective moves, and the
    # pods still diverged after repairs — None when the round was clean
    # (so a fault-free run's records stay identical to a run with the
    # plane disabled, the golden-pin contract)
    reconcile: dict | None = None
    # shadow mode (bench/shadow.py): the round's head-to-head against
    # the replayed trace's actual scheduler — counterfactual cost/
    # load-std, delta, running win-rate, and (with attribution on) the
    # twin's sum-consistent attribution + per-edge deltas — None
    # outside shadow runs and on unscored (degraded) rounds
    shadow: dict | None = None
    # wall-clock lifecycle of the round (timing field — excluded from
    # the pipelined-vs-sequential bit-identity comparison): execute
    # start to record finalize
    wall_s: float = 0.0
    # pipelined-schedule telemetry (timing field): depth, the fraction of
    # background boundary time hidden behind foreground work, and the
    # raw background/blocked seconds — None on sequentially-scheduled
    # rounds (including drained rounds of a pipelined run)
    pipeline: dict | None = None

    @property
    def decision_latency_s(self) -> float:
        """Total device-side decision time this round (no cluster I/O)."""
        return sum(self.decision_latencies_s)

    @property
    def decisions(self) -> int:
        return len(self.decision_latencies_s)

    def as_dict(self) -> dict:
        return {
            **self.__dict__,
            "decision_latency_s": self.decision_latency_s,
            "decisions": self.decisions,
        }


@dataclass
class ControllerResult:
    rounds: list[RoundRecord] = field(default_factory=list)
    resumed_from_round: int = 0  # >0 when a checkpoint resume skipped rounds
    # resilience accounting: rounds the open breaker froze (counted, never
    # silently lost — max_rounds == len(rounds) + skipped_rounds), the
    # breaker's transition log, and total boundary failures absorbed
    skipped_rounds: int = 0
    breaker_transitions: list[dict] = field(default_factory=list)
    boundary_failures: int = 0

    @property
    def degraded_rounds(self) -> int:
        return sum(1 for r in self.rounds if r.degraded)

    @property
    def decisions_per_sec(self) -> float:
        lat = sum(r.decision_latency_s for r in self.rounds)
        n = sum(r.decisions for r in self.rounds if r.decision_latency_s > 0)
        return n / lat if lat > 0 else 0.0

    @property
    def moves(self) -> int:
        return sum(1 for r in self.rounds if r.moved)

    def latency_summary(self) -> dict[str, float]:
        """Per-decision latency distribution (utils.profiling histogram),
        built from the real per-decision samples — a round's compile-heavy
        first decide shows up in max/p99 instead of being averaged away."""
        hist = LatencyHistogram()
        for r in self.rounds:
            for s in r.decision_latencies_s:
                hist.add(s)
        return hist.summary()


# the same decision kernel the scanned loop uses (solver.round_loop.decide),
# jitted for one-round-at-a-time use against a live backend. Instrumented:
# jax_traces_total{fn="controller_decide"} must stay at 1 across a
# steady-state run — a second trace means some argument went
# shape-polymorphic and every round is paying a recompile.
_decide = instrument_jit(decide, name="controller_decide")

# the explain twin: the same decision (shared policy_scores + lex argmax —
# bit-identical by construction) plus the compact explanation bundle the
# host pulls in ONE transfer. Separate fn label, same steady-state
# invariant: 1 trace per (shape, top_k) signature.
_decide_explain = instrument_jit(
    decide_explain, name="controller_decide_explain",
    static_argnames=("top_k",),
)

# NOTE: the per-round cost/attribution kernels now live in
# bench/round_end.py (``controller_round_end``): one compiled program
# computes the comm-cost/load-std pair AND the flat attribution bundle,
# and the host pulls it — together with every other diagnostic the round
# deferred (explain bundles, forecast diag, solver objectives) — as ONE
# counted ``round_end`` transfer per executed round.

# the proactive decision kernels: the SAME decide/decide_explain
# machinery run against the predicted next-window state (the forecast
# delta folded into node_base_cpu inside the trace). Own fn labels, same
# steady-state invariant: jax_traces_total == 1 + counted bucket
# promotions per (shape, top_k) signature.
#
# None of the decide kernels donate their snapshot argument
# (donate_argnums): their outputs — index scalars and a bool hazard
# mask — can alias none of the f32/i32 snapshot buffers, so XLA would
# warn per compile and reuse nothing. The donated carries live where
# aliasing is total: the global solver's placement carry
# (solver.global_solver.global_assign_donated) and the forecast plane's
# RLS state (forecast.plane).
_decide_proactive = instrument_jit(
    decide_with_forecast, name="controller_decide_proactive"
)
_decide_proactive_explain = instrument_jit(
    decide_explain_with_forecast, name="controller_decide_proactive_explain",
    static_argnames=("top_k",),
)


def _emit_round_metrics(registry, algorithm: str, record: "RoundRecord") -> None:
    """One metric sample set per completed round — the registry twin of
    the logger's per-round event (one definition; the counts the
    one-event-per-round test pins come from here)."""
    lab = {"algorithm": algorithm}
    registry.counter(
        "rounds_total", "rescheduling rounds executed", labelnames=("algorithm",)
    ).labels(**lab).inc()
    registry.counter(
        "services_moved_total",
        "deployments recreated by rescheduling moves",
        labelnames=("algorithm",),
    ).labels(**lab).inc(len(record.services_moved))
    hist = registry.histogram(
        "decision_seconds",
        "device-side decision latency per decide/solve",
        labelnames=("algorithm",),
    ).labels(**lab)
    for s in record.decision_latencies_s:
        hist.observe(s)
    registry.gauge(
        "communication_cost",
        "communication cost after the most recent round",
        labelnames=("algorithm",),
    ).labels(**lab).set(record.communication_cost)
    registry.gauge(
        "load_std",
        "node CPU-% standard deviation after the most recent round",
        labelnames=("algorithm",),
    ).labels(**lab).set(record.load_std)
    # some restart paths report only one of the two objectives — gate each
    # gauge on its own field so the other still surfaces
    if record.objective_before is not None:
        registry.gauge(
            "solver_objective_before",
            "solver objective of the incoming placement (global rounds)",
            labelnames=("algorithm",),
        ).labels(**lab).set(record.objective_before)
    if record.objective_after is not None:
        registry.gauge(
            "solver_objective_after",
            "solver objective of the adopted placement (global rounds)",
            labelnames=("algorithm",),
        ).labels(**lab).set(record.objective_after)


# wall-clock round-latency buckets (milliseconds): the live plane's
# rounds span sub-ms sim rounds to multi-second paced live rounds
_WALL_MS_BUCKETS = (
    0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0, 5000.0, 10000.0,
)


def observe_wall_round(registry, mode: str, wall_s: float) -> None:
    """THE `wall_round_ms` declaration (one definition — the solo loop's
    schedules and the fleet loop share name/help/buckets through here,
    so the series can never fork)."""
    registry.histogram(
        "wall_round_ms",
        "wall-clock lifecycle of one executed controller round "
        "(execute start to record finalize), by schedule",
        labelnames=("mode",),
        buckets=_WALL_MS_BUCKETS,
    ).labels(mode=mode).observe(wall_s * 1e3)


def pipeline_depth_gauge(registry):
    """THE `pipeline_depth` declaration (set only by pipelined runs)."""
    return registry.gauge(
        "pipeline_depth",
        "configured software-pipeline depth of the control loop "
        "(0/absent = sequential)",
    )


def pipeline_overlap_gauge(registry):
    """THE `pipeline_overlap_ratio` declaration (set only by pipelined
    runs — a sequential run must not export a stray zero series)."""
    return registry.gauge(
        "pipeline_overlap_ratio",
        "fraction of the background boundary (advance+monitor) time "
        "hidden behind foreground work, most recent pipelined round",
    )


class _Runtime:
    """The control loop's shared machinery: boundary, breaker, churn,
    forecast plane, explain/attribution gates, the round-end bundle
    protocol, and the per-round helpers both schedules compose.

    The SEQUENTIAL schedule (``sequential_round``) is the historical
    loop re-expressed over the single-bundle round-end protocol; the
    PIPELINED schedule (``_pipelined_loop``) interleaves the same helper
    calls so the previous round's flush + host tail overlap the current
    round's device compute and the post-move monitor runs in a
    background thread — with the backend seeing the exact sequential
    call order, which is what makes the two schedules bit-identical on
    the sim backend.
    """

    def __init__(
        self,
        backend,
        config,
        *,
        key,
        on_round,
        checkpoint_dir,
        logger,
        graph,
        registry,
        ops,
        churn,
    ):
        self.config = config
        self.registry = registry
        self.key = key
        self.on_round = on_round
        self.logger = logger
        self.ops = ops

        if config.chaos.profile != "none":
            backend = with_chaos(
                backend, config.chaos.profile, seed=config.chaos.seed,
                registry=registry,
            )
        self.breaker = CircuitBreaker(
            max_consecutive_failures=config.max_consecutive_failures,
            cooldown_rounds=config.breaker_cooldown_rounds,
            logger=logger,
            registry=registry,
        )
        self.boundary = BoundaryClient(
            backend,
            policy=config.retry,
            breaker=self.breaker,
            failure_budget_per_round=config.failure_budget_per_round,
            logger=logger,
            registry=registry,
        )
        # the reconciliation & admission plane (config.reconcile): every
        # monitor() result passes the admission guard before it can touch
        # device state (monitor_admitted — statically enforced), and the
        # intent ledger closes the loop on this controller's own moves
        self.guard = (
            AdmissionGuard(
                config.reconcile,
                registry=registry,
                logger=logger,
                on_reject=self.boundary.admission_reject,
            )
            if config.reconcile.admission
            else None
        )
        self.ledger = (
            IntentLedger(
                config.reconcile,
                registry=registry,
                logger=logger,
                # an advisory-only backend (shadow replay) makes the
                # snapshot stream ground truth: diffs adopt, never charge
                adopt_observed=getattr(
                    self.boundary.raw_backend, "advisory_only", False
                ),
            )
            if config.reconcile.enabled
            else None
        )
        if churn is None and config.elastic.profile != "none":
            from kubernetes_rescheduling_tpu.elastic.engine import ChurnEngine

            churn = ChurnEngine(
                config.elastic.profile,
                seed=config.elastic.seed,
                bucket_floor=config.elastic.bucket_floor,
                registry=registry,
            )
        self.churn = churn
        self.shadow = None
        if config.shadow.enabled:
            # the shadow plane: recommendations land in a shadow ledger
            # (the replay backend records, never applies) and a
            # counterfactual twin scores our cumulative placement vs the
            # trace's actual one, riding the round-end bundle. Lazy
            # import — live runs never touch the shadow module.
            from kubernetes_rescheduling_tpu.bench.shadow import ShadowPlane

            self.shadow = ShadowPlane(
                config.shadow, registry=registry, logger=logger
            )
        self.forecast_plane = None
        if config.algorithm == "proactive":
            # the forecast plane: one online forecaster per run, one kernel
            # dispatch per round whose diag rides the round-end bundle.
            # Lazy import — reactive runs never touch the forecast package.
            from kubernetes_rescheduling_tpu.forecast.plane import ForecastPlane

            self.forecast_plane = ForecastPlane(config.forecast, registry=registry)
        if churn is not None:
            # the churn feed flows through the boundary's backend passthrough
            # (like apply_pod_moves): chaos wrappers and the raw simulator see
            # one stream, and bind() pushes the initial bucket capacities so
            # even round 1's snapshot is bucket-padded
            churn.bind(self.boundary, config.max_rounds, registry=registry)
        if ops is not None:
            ops.bind(breaker=self.breaker, logger=logger, algorithm=config.algorithm)
            self.breaker.on_transition = ops.on_breaker_transition
        # decision explainability: on when configured AND someone is listening
        # (a structured logger or the ops plane) — the bare loop stays exactly
        # the historical decision kernel
        self.explain_k = (
            config.obs.explain_top_k
            if config.obs.explain and (ops is not None or logger is not None)
            else 0
        )
        # cost attribution rides the same gate; when on, the attribution
        # bundle rides the round-end transfer the loop pays anyway
        self.attr_k = (
            config.obs.attribution_top_k
            if config.obs.attribution and (ops is not None or logger is not None)
            else 0
        )
        self.timeline = attribution_mod.PlacementTimeline() if self.attr_k > 0 else None
        # in-block tripwires (telemetry.tripwire): device-side health
        # predicates inside the scan body; the latest tripped block's
        # decoded report lives in scan_trip until _scanned_loop drains it
        self.scan_tripwire = bool(
            config.controller.scan_block
            and getattr(config.obs, "scan_tripwires", True)
        )
        self.scan_trip = None
        # decisions may run on an estimated graph; TELEMETRY always reports on
        # the backend's declared graph so round costs stay comparable across
        # configurations (and with the harness's before/after metrics)
        self.metric_graph = self.boundary.comm_graph()
        self.graph_static = graph is None or not callable(graph)
        if graph is None:
            self.graph_src = lambda: self.metric_graph
        elif callable(graph):
            self.graph_src = graph
        else:
            self.graph_src = lambda: graph
        self.result = ControllerResult()

        # per-round device observability: which instrumented kernel this run's
        # rounds dispatch (preference order — the roofline publishes for the
        # first label with a captured cost snapshot)
        if config.algorithm == "global" or config.moves_per_round == "all":
            # prefer THIS run's solver family: the cost book is process-global,
            # so a dense-first list would publish the dense kernel's static
            # cost against a sparse round's latency in a mixed bench session.
            # The dense labels stay as FALLBACK on the sparse path because
            # global_assign_sparse genuinely routes small graphs through the
            # dense kernel — there the dense attribution is the true one.
            if config.solver_backend == "sparse":
                self.roofline_fns = (
                    "global_assign_sparse", "sharded_restarts_sparse",
                    "global_assign", "sharded_restarts_dense",
                )
            else:
                self.roofline_fns = ("global_assign", "sharded_restarts_dense")
        elif self.forecast_plane is not None:
            self.roofline_fns = (
                ("controller_decide_proactive_explain",)
                if self.explain_k > 0
                else ("controller_decide_proactive",)
            )
        elif self.explain_k > 0:
            self.roofline_fns = ("controller_decide_explain",)
        else:
            self.roofline_fns = ("controller_decide",)

        self.mgr = CheckpointManager(checkpoint_dir) if checkpoint_dir else None
        # carry donation (config.controller.donate_carry): the global
        # solver's donated state carry is only legal when NOTHING outside
        # this loop can touch the pre-solve snapshot's device buffers
        # afterwards — a checkpoint manager re-serializes the carried
        # snapshot on degraded/skipped rounds, on_round hands it to
        # arbitrary sinks, and the ops plane digests it per round, so any
        # of them forces the defensive-copy path instead
        self.donate_ok = (
            config.controller.donate_carry
            and self.mgr is None
            and on_round is None
            and ops is None
        )
        self.start_round = 1
        resumed_pending_churn: list[dict] = []
        if self.mgr is not None:
            latest = self.mgr.latest()
            if latest is not None:
                done_round, saved_state, _extra = latest
                if churn is not None:
                    # fast-forward the churn stream over the already-completed
                    # rounds: the event schedule depends only on (profile,
                    # seed, round, topology) — never on controller moves — so
                    # replaying it on the freshly built backend reconstructs
                    # the checkpoint-time topology AND positions the churn rng
                    # exactly where the uninterrupted run had it. Without
                    # this, a resumed churn run would silently restart from
                    # the initial topology with a rewound event stream.
                    # (Replayed events re-count in churn_events_total when the
                    # resume shares a registry with the original run.)
                    for past in range(1, done_round + 1):
                        churn.step(past)
                    # the metric graph read above predates the replayed
                    # events — re-read it so resumed rounds report against
                    # the same topology the uninterrupted run saw
                    self.metric_graph = self.boundary.comm_graph()
                restore = getattr(backend, "restore_placement", None)
                if restore is not None:
                    restore(saved_state)
                if self.ledger is not None:
                    # adopt the checkpointed intent: the first admitted
                    # snapshot below is RECONCILED against it instead of
                    # trusted blindly — whatever moved while the
                    # controller was down becomes a counted, repairable
                    # divergence rather than silently becoming truth
                    self.ledger.restore(_extra.get("reconcile"))
                # a checkpoint written by a SKIPPED round carries churn
                # events applied in its preamble that no record has
                # flushed yet — restore the debt, or the first executed
                # round's record (and the intent ledger's diff) would
                # never see them and legitimate churn would read as
                # phantom/missing divergences
                resumed_pending_churn = [
                    dict(e) for e in _extra.get("pending_churn") or []
                ]
                self.start_round = done_round + 1
                self.result.resumed_from_round = self.start_round
                if logger is not None:
                    logger.info(
                        "resume", round=self.start_round, checkpoint=done_round
                    )

        # churn bookkeeping that must SURVIVE skipped rounds (see the
        # sequential loop's historical comments): a round whose churn was
        # applied but never re-monitored leaves these set, and the next
        # executed round settles the debt before deciding
        self.remask_needed = False
        self.rebind_timeline = False
        # starts with any debt a skip-round checkpoint persisted (the
        # resume's initial monitor below already sees the post-churn
        # topology, so the events owe only a flush and a ledger consume,
        # never a re-mask)
        self.pending_churn: list[dict] = resumed_pending_churn
        # the previous round's unrepaired-drift count: a convergence round
        # must still carry one explicit drift_pods=0 block (see
        # _reconcile_round) so the watchdog rule can clear
        self._last_drift = 0

        # one snapshot per round: the post-move snapshot provides this round's
        # metrics AND the next round's state. Startup has no last-good
        # snapshot to degrade to, so the initial monitor gets its own bounded
        # probe loop on top of the per-call retries; only a backend that
        # stays dark through all of it raises.
        self.state = None
        self._pending_end: dict | None = None
        for _ in range(max(3, config.max_consecutive_failures + 1)):
            probe = self.monitor_admitted()
            if probe is not None:
                self.note_fresh_snapshot(probe)
                break
        if self.state is None:
            raise ConnectionError(
                "backend unavailable: initial monitor() failed after retries "
                "(no last good snapshot to degrade to)"
            )
        if self.timeline is not None:
            # provenance model: the initial residency collapse (host-side,
            # once per run) the per-move cost deltas telescope from
            self.timeline.bind(self.state, self.metric_graph)
        if self.ledger is not None:
            if not self.ledger.intent:
                # startup baseline: intent := the first admitted snapshot
                # (a checkpoint-restored intent instead reconciles at the
                # first observe — see the resume path above)
                self.ledger.rebase(
                    self.state, service_names=self.metric_graph.names
                )
            self._ledger_snap = self.ledger.snapshot()
        if self.shadow is not None:
            # twin := the first admitted snapshot's recorded placement;
            # the guard's already-pulled host arrays mean no extra
            # transfer (shadow validation requires admission on)
            self.shadow.bind(
                self.state,
                self.metric_graph,
                self.guard.host_arrays(self.state)
                if self.guard is not None
                else None,
            )

    # ---- snapshot admission ----

    def monitor_admitted(self):
        """THE monitor wrapper both schedules use: every snapshot passes
        the admission guard before it can touch device state (statically
        enforced by ``scripts/check_snapshot_admission.py`` — this is the
        solo loop's only legal ``.monitor()`` call site). A rejection
        returns ``None``, the protocol's existing failure signal, after
        charging the boundary (``admission_reject``)."""
        out = self.boundary.monitor()
        if self.guard is not None:
            out = self.guard.admit(out)
        return out

    def ckpt_extra(self, **extra) -> dict:
        """Checkpoint sidecar payload: the algorithm tag (and any
        caller fields) plus the intent ledger as of the LAST CLOSED round
        — resume reconciles against it instead of trusting the first
        snapshot blindly. Churn events no record has flushed yet (a
        skip-round save — executed rounds always flush first) ride along
        so resume owes the same record flush + ledger consume the
        uninterrupted run would have performed."""
        extra["algorithm"] = self.config.algorithm
        if self.ledger is not None:
            extra["reconcile"] = self._ledger_snap
        if self.pending_churn:
            extra["pending_churn"] = [dict(e) for e in self.pending_churn]
        return extra

    # ---- round-end bundle protocol ----

    def metric_edges(self):
        """The round-end fast path's precomputed edge list (attribution
        off only — the dense S×S work is needed for the bundle anyway
        when it is on), cached per metric-graph OBJECT so churn's graph
        refreshes rebuild it and steady state never does. Shadow runs
        stay dense too: the shadow plane dispatches the SAME
        ``controller_round_end`` kernel for its counterfactual twin
        without an edge list, so taking the fast path here would fork
        the compiled signature (breaking the 1-trace pin) AND score the
        head-to-head's two sides under different f32 summation orders."""
        if self.attr_k > 0 or self.shadow is not None:
            return None
        graph = self.metric_graph
        cached = getattr(self, "_edge_cache", None)
        if cached is None or cached[0] is not graph:
            from kubernetes_rescheduling_tpu.objectives.metrics import (
                comm_edge_list,
            )

            self._edge_cache = (graph, comm_edge_list(graph))
        return self._edge_cache[1]

    def note_fresh_snapshot(self, state) -> None:
        """Adopt a fresh monitor snapshot and dispatch its round-end
        bundle (async, never pulled unless it closes a record): the
        post-move snapshot's bundle closes its own round; a startup/
        probe/remask snapshot's bundle is the degraded-close fallback —
        exactly the state the historical loop measured on, at the same
        transfer cost, without re-running kernels on a carried state."""
        self.state = state
        ctx = {
            "node_names": state.node_names,
            "svc_names": self.metric_graph.names,
            "num_nodes": state.num_nodes,
            "num_services": self.metric_graph.num_services,
        }
        dev = dispatch_round_end(
            device_view(state), device_graph(self.metric_graph),
            top_k=self.attr_k, edges=self.metric_edges(),
        )
        self._pending_end = {"dev": dev, "ctx": ctx}

    def _apply_round_metrics(
        self, rnd: int, record: RoundRecord, cost: float, lstd: float,
        attr_flat, ctx: dict,
    ) -> None:
        """Land a round's closing metrics on its record: cost/load-std
        plus — with attribution on — the decoded bundle, provenance
        deltas, gauges, and the attribution book. ONE definition for the
        per-round protocol (``_attach_metrics``) and the scanned
        schedule's block decode, so the two paths can never diverge in
        what a closed record carries."""
        record.communication_cost = cost
        record.load_std = lstd
        if self.attr_k > 0:
            attr = attribution_mod.decode_attribution(
                attr_flat,
                node_names=ctx["node_names"],
                service_names=ctx["svc_names"],
                top_k=self.attr_k,
                num_nodes=ctx["num_nodes"],
                num_services=ctx["num_services"],
            )
            attr["round"] = rnd
            attr["algorithm"] = self.config.algorithm
            attr.update(
                self.timeline.observe_round(
                    rnd,
                    record.applied_moves,
                    pod_level=self.config.placement_unit == "pod",
                )
            )
            record.attribution = attr
            attribution_mod.publish_attribution(
                self.registry, attr, top_k=self.attr_k
            )
            attribution_mod.get_attribution_book().update(
                self.config.algorithm, rnd, attr
            )

    def _attach_metrics(self, rnd: int, record: RoundRecord, closer: RoundCloser) -> None:
        """Register the record's closing metrics (cost/load-std +
        attribution) on the closer: the pending snapshot bundle when it
        is still device-resident, the cached host values otherwise (a
        degraded round closing on an already-pulled snapshot costs no
        transfer — the historical loop re-pulled bit-equal values)."""
        pend = self._pending_end
        ctx = pend["ctx"]

        def apply_vals(cost: float, lstd: float, attr_flat) -> None:
            self._apply_round_metrics(rnd, record, cost, lstd, attr_flat, ctx)

        if "host" in pend:
            h = pend["host"]
            closer.defer_host(
                lambda: apply_vals(h["cost"], h["lstd"], h["attr"])
            )
            return

        dev = pend.pop("dev")

        def decode(flat) -> None:
            cost = float(flat[METRIC_COST])
            lstd = float(flat[METRIC_LOAD_STD])
            attr_flat = flat[METRIC_HEAD:] if self.attr_k > 0 else None
            # cache for a following degraded round (bit-equal to re-running
            # the kernels on the same snapshot, which is what the
            # historical loop did)
            pend["host"] = {"cost": cost, "lstd": lstd, "attr": attr_flat}
            apply_vals(cost, lstd, attr_flat)

        closer.defer(dev, decode)

    def begin_close(self, rnd: int, record: RoundRecord, closer: RoundCloser, new_state) -> None:
        """Round-close bookkeeping that must precede the NEXT round's
        ``begin_round`` (it reads the breaker/failure counters) and the
        flush: adopt or degrade the snapshot, attach the metrics piece."""
        if self.churn is not None:
            # pending_churn, not this round's events only: skipped rounds'
            # events flush into the first record that can carry them
            record.churn = self.churn.round_info(self.pending_churn)
            self.pending_churn = []
        if new_state is None:
            # post-move snapshot failed: finish the round DEGRADED on the
            # last good snapshot instead of crashing (metrics below are
            # stale but labeled as such via record.degraded)
            record.degraded = True
        else:
            self.note_fresh_snapshot(new_state)
        self._reconcile_round(record, fresh=new_state is not None)
        # snapshot the counters AFTER the reconcile repairs: a corrective
        # move is a boundary move like any other, so a failed one must
        # show in this round's record, not vanish into the next reset
        record.breaker_state = self.breaker.state
        record.boundary_failures = self.boundary.round_failures
        self._attach_metrics(rnd, record, closer)
        if self.shadow is not None:
            # AFTER the metrics piece: decode order inside the single
            # flush guarantees the actual cost is on the record before
            # the shadow decode scores against it — and the twin's
            # bundle rides the SAME round_end transfer
            self.shadow.observe_round(
                rnd, record, self.state, self.metric_graph, closer,
                arrays=(
                    self.guard.host_arrays(self.state)
                    if self.guard is not None
                    else None
                ),
                fresh=new_state is not None,
                top_k=self.attr_k,
            )

    def _reconcile_round(self, record: RoundRecord, *, fresh: bool) -> None:
        """The reconciliation plane's per-round step — delegates to the
        shared :func:`reconcile_round_block` (one implementation for the
        solo and fleet loops). A degraded round (``fresh=False``) has no
        admitted snapshot to diff — it carries only the admission counts
        (the rejection that degraded it) and the standing drift debt,
        while its churn events wait in the ledger for the next fresh
        diff."""
        record.reconcile, self._last_drift = reconcile_round_block(
            self.guard,
            self.ledger,
            state=self.state,
            service_names=self.metric_graph.names,
            churn_events=(record.churn or {}).get("events") or (),
            fresh=fresh,
            last_drift=self._last_drift,
            boundary=self.boundary,
            repair_budget=self.config.reconcile.repair_budget_per_round,
        )
        if self.ledger is not None:
            self._ledger_snap = self.ledger.snapshot()

    # ---- per-round helpers ----

    def record_intents(self, intents) -> None:
        """Ledger capture for a round's applied moves. An advisory-only
        backend (the shadow plane's replay backend) makes every intent
        advisory regardless of mechanism: a recommendation is
        definitionally advisory, and the ledger then adopts the observed
        (recorded) placement at the next diff instead of charging the
        real scheduler's choices as lost moves or drift."""
        if not intents:
            return
        if getattr(self.boundary.raw_backend, "advisory_only", False):
            intents = [(*i[:4], True) for i in intents]
        self.ledger.record_moves(intents)

    def skip_round(self, rnd: int) -> None:
        """Safe mode: the open breaker froze this round — count it, pace,
        checkpoint the carried-over snapshot so resume semantics hold."""
        self.result.skipped_rounds += 1
        self.registry.counter(
            "rounds_skipped_total",
            "rounds frozen by the open circuit breaker",
            labelnames=("algorithm",),
        ).labels(algorithm=self.config.algorithm).inc()
        # a rejection during THIS round's preamble (probe/re-mask) belongs
        # to this skip, not to the next executed round's record — drain it
        # onto the skip event (the registry counters are the durable half)
        adm = self.guard.take_info() if self.guard is not None else {}
        if self.logger is not None:
            self.logger.info(
                "round_skipped",
                round=rnd,
                breaker=self.breaker.state,
                consecutive_failures=self.breaker.consecutive_failures,
                **({"admission": adm} if adm else {}),
            )
        if self.ops is not None:
            self.ops.observe_skip(rnd, breaker_state=self.breaker.state)
        self.boundary.advance(self.config.sleep_after_action_s)
        if self.mgr is not None:
            self.mgr.save(rnd, self.state, extra=self.ckpt_extra(skipped=True))

    def preamble(self, rnd: int) -> bool:
        """Everything before a round may decide: churn events, the
        breaker gate, the half-open probe, the churn re-mask. Returns
        False when the round was a counted skip."""
        if self.churn is not None:
            # the cluster churns whether or not the breaker lets this
            # round run — events apply first, exactly like real
            # deploys/autoscaling happening under an ailing controller
            events = self.churn.step(rnd)
            if events:
                self.pending_churn.extend(events)
                self.remask_needed = True
                if self.churn.graph_changed:
                    self.metric_graph = self.boundary.comm_graph()
                    self.rebind_timeline = True
        mode = self.boundary.begin_round(rnd)
        if mode == OPEN:
            self.skip_round(rnd)
            return False
        refreshed = False
        if mode == HALF_OPEN:
            # one probe before trusting the backend with a full round; a
            # success closes the breaker AND refreshes the stale snapshot
            probe = self.monitor_admitted()
            if probe is None:
                self.skip_round(rnd)
                return False
            self.note_fresh_snapshot(probe)
            refreshed = True
        if self.remask_needed and not refreshed:
            # re-mask: the carried snapshot predates some applied churn —
            # one fresh monitor realigns pod sets and validity masks with
            # the mutated cluster (shapes stay in-bucket, so the decision
            # kernels do not retrace); a dark backend makes this a counted
            # skip and the debt carries to the next executed round
            fresh = self.monitor_admitted()
            if fresh is None:
                self.skip_round(rnd)
                return False
            self.note_fresh_snapshot(fresh)
            refreshed = True
        if refreshed:
            self.remask_needed = False
        if self.rebind_timeline and self.timeline is not None:
            # the provenance model is defined over a fixed service set —
            # re-anchor it at the post-churn snapshot (move deltas
            # telescope within a churn epoch)
            self.timeline = attribution_mod.PlacementTimeline()
            self.timeline.bind(self.state, self.metric_graph)
        self.rebind_timeline = False
        return True

    def execute_round(self, rnd: int, closer: RoundCloser, pre_fence_hook=None) -> RoundRecord:
        """Dispatch and apply one round's decisions (no advance/monitor —
        the schedules own those). ``pre_fence_hook`` runs after the first
        async kernel dispatch, before the apply-boundary fence — the
        pipelined schedule's overlap window."""
        sub = jax.random.fold_in(self.key, rnd)
        graph = self.graph_src()  # fresh estimate per round when streaming
        config = self.config
        # intent capture: every boundary move this round as (service,
        # pod, requested, landed) — recorded on the ledger AT APPLY TIME,
        # so the next admitted snapshot's observe() diffs against what
        # this round actually asked for
        intents: list | None = [] if self.ledger is not None else None
        if config.algorithm == "global" or config.moves_per_round == "all":
            carry: dict = {}
            record = _global_round(
                self.boundary, self.state, graph, config, sub, rnd,
                logger=self.logger, explain=self.explain_k > 0,
                closer=closer, pre_fence_hook=pre_fence_hook,
                donate=self.donate_ok, carry=carry, intents=intents,
            )
            if carry.get("state") is not None:
                # the donated solve consumed the snapshot's buffers; adopt
                # the bit-equal resurrected copy so a failed post-move
                # monitor (or a breaker skip) can still carry it forward
                self.state = carry["state"]
            if intents:
                self.record_intents(intents)
            return record
        forecast_delta = None
        forecast_latency = 0.0
        if self.forecast_plane is not None:
            # fold this round's observed loads into the online model and
            # predict the next window — one instrumented dispatch,
            # name-stripped view (same jit-key rule as the decision
            # kernels); the diag vector rides the round-end bundle
            t_fc = time.perf_counter()
            with span("controller/forecast", round=rnd):
                forecast_delta = self.forecast_plane.observe_and_predict(
                    device_view(self.state), closer=closer
                )
            forecast_latency = time.perf_counter() - t_fc
        record = _greedy_round(
            self.boundary, self.state, graph, config, sub, rnd,
            logger=self.logger, explain_k=self.explain_k,
            forecast_delta=forecast_delta,
            closer=closer, pre_fence_hook=pre_fence_hook,
            registry=self.registry, intents=intents,
        )
        if intents:
            self.record_intents(intents)
        if self.forecast_plane is not None:
            # the forecast dispatch is decision work: count it in the
            # round's device latency budget so decisions/sec and the
            # bench cells price the proactive path honestly
            record.decision_latencies_s = (
                forecast_latency,
            ) + record.decision_latencies_s
            plane, registry = self.forecast_plane, self.registry

            def _finish_forecast() -> None:
                record.forecast = plane.round_info()
                plane.publish(registry)

            closer.defer_host(_finish_forecast)
        return record

    def emit(self, rnd: int, record: RoundRecord, mode: str = "sequential") -> None:
        """The record's host tail: result stream, metrics, roofline,
        logger, ops plane, on_round. Runs strictly after the flush."""
        config, registry = self.config, self.registry
        self.result.rounds.append(record)
        _emit_round_metrics(registry, config.algorithm, record)
        observe_wall_round(registry, mode, record.wall_s)
        # device-side observability: live memory_stats gauges plus the
        # round's achieved-FLOP/s / bytes/s roofline against the
        # decision kernel's captured static cost
        costmodel.observe_round_device(
            registry,
            fn_labels=self.roofline_fns,
            seconds=record.decision_latency_s,
        )
        if record.degraded:
            registry.counter(
                "degraded_rounds_total",
                "rounds completed on a stale snapshot after boundary failure",
                labelnames=("algorithm",),
            ).labels(algorithm=config.algorithm).inc()
        round_event = dict(
            round=rnd,
            moved=record.moved,
            services=list(record.services_moved),
            most_hazard=record.most_hazard,
            communication_cost=record.communication_cost,
            load_std=record.load_std,
            decision_latency_s=record.decision_latency_s,
            objective_before=record.objective_before,
            objective_after=record.objective_after,
            breaker=record.breaker_state,
            degraded=record.degraded,
            boundary_failures=record.boundary_failures,
        )
        if self.logger is not None:
            self.logger.info("round", **round_event)
        if self.ops is not None:
            self.ops.observe_round(
                record,
                self.state,
                events=[
                    {"event": "decision", **e} for e in record.explanations
                ] + [{"event": "round", **round_event}],
            )
        if self.on_round is not None:
            self.on_round(record, self.state)

    def sequential_round(self, rnd: int) -> None:
        """One full round on the historical schedule (also the pipelined
        loop's drained path): preamble, execute, advance+monitor, close,
        flush, emit, checkpoint — in exactly the historical order."""
        if not self.preamble(rnd):
            return
        t0 = time.perf_counter()
        closer = RoundCloser(self.registry)
        with span("controller/round", round=rnd, algorithm=self.config.algorithm):
            record = self.execute_round(rnd, closer)
            self.boundary.advance(self.config.sleep_after_action_s)
            with span("backend/monitor"):
                new_state = self.monitor_admitted()
        self.begin_close(rnd, record, closer, new_state)
        closer.flush()
        record.wall_s = time.perf_counter() - t0
        self.emit(rnd, record)
        # checkpoint LAST: a crash inside on_round (sinks, load segment)
        # replays this round on resume instead of leaving a hole in its
        # outputs; replaying a move is idempotent (same pin, same target)
        if self.mgr is not None:
            self.mgr.save(rnd, self.state, extra=self.ckpt_extra())

    def _advance_and_monitor(self):
        """The background half of a pipelined round: pace, then the
        post-move monitor — the same boundary calls in the same order the
        sequential loop issues, just off the main thread. Returns the
        snapshot (or None) plus the wall time the pair took."""
        t0 = time.perf_counter()
        self.boundary.advance(self.config.sleep_after_action_s)
        out = self.monitor_admitted()
        return out, time.perf_counter() - t0

    # ---- the scanned schedule (bench/scan.py) ----

    def scan_static_reason(self) -> str | None:
        """Run-level conditions the scanned schedule can never honor —
        checked once (config.validate() already rejected the config-level
        incompatibilities: pipeline, non-pinning algorithms, shadow).
        Returns the drain-reason label, or None when blocks may run."""
        from kubernetes_rescheduling_tpu.backends.sim_device import (
            scan_compatible,
        )

        if self.on_round is not None:
            # on_round mutates backend load mid-run (the harness's
            # sustained-load hook) — the twin's placement-pure monitor
            # assumption would silently break
            return "on-round"
        if not scan_compatible(self.boundary.backend):
            # the OUTERMOST backend, wrappers included (raw_backend would
            # see through a chaos layer): chaos wrappers, replay
            # backends, live adapters, or a noisy load model — only the
            # per-round path can honor their faults
            return "backend"
        if self.mgr is not None:
            # the sequential loop checkpoints every round; a scan block
            # cannot (resume would land mid-block)
            return "checkpoint"
        if not self.graph_static:
            return "streaming-graph"
        return None

    def scan_block_rounds(self, start: int, rounds: int) -> int:
        """One scan block: dispatch the fused K-round kernel, pull the
        whole block's diagnostics in ONE counted ``round_end`` transfer,
        then replay the decided moves into the backend through the
        boundary — the EXACT per-round call order the sequential loop
        issues (begin_round, apply, advance), minus the K-1 intermediate
        monitors the steady state never needed. Decoded rounds emit
        ordinary ``RoundRecord``s (explain, attribution, reconcile,
        watchdog all served), bit-identical to the sequential loop's
        (test-pinned). Returns the number of rounds consumed (< rounds
        only if a replayed landing diverged from the twin — impossible
        on a scan-compatible backend, handled defensively — or if the
        in-block tripwire latched: the replay then commits exactly the
        rounds BEFORE the trip, the trip report lands in
        ``self.scan_trip``, and ``_scanned_loop`` drains the tripped
        round to the per-round path under reason ``tripwire``)."""
        from kubernetes_rescheduling_tpu.bench import scan as scan_mod
        from kubernetes_rescheduling_tpu.telemetry import tripwire as tripwire_mod

        config = self.config
        graph = self.graph_src()
        scoring = scoring_policy(config.algorithm, config.forecast)
        mech = PlacementMechanism[scoring]
        pid = jnp.asarray(POLICY_IDS[scoring])
        thr = jnp.asarray(config.hazard_threshold_pct)
        state0 = self.state
        ctx = {
            "node_names": state0.node_names,
            "svc_names": self.metric_graph.names,
            "num_nodes": state0.num_nodes,
            "num_services": self.metric_graph.num_services,
        }
        if self.ops is not None:
            # K rounds of healthy silence follow: scale the /healthz
            # staleness budget so a long block never spuriously 503s
            self.ops.health.mark_block_inflight(rounds)
        t0 = time.perf_counter()
        with span(
            "controller/scan_block", round=start, rounds=rounds,
            algorithm=config.algorithm,
        ):
            flat_dev = scan_mod.scan_rounds(
                device_view(state0),
                device_graph(graph),
                device_graph(self.metric_graph),
                pid,
                thr,
                self.key,
                jnp.asarray(start, jnp.int32),
                self.metric_edges(),
                (
                    tripwire_mod.trip_config_array(config.obs)
                    if self.scan_tripwire
                    else None
                ),
                rounds=rounds,
                pinned=True,
                explain_k=self.explain_k,
                attr_k=self.attr_k,
                tripwire=self.scan_tripwire,
            )
            flat = scan_mod.pull_block(flat_dev, self.registry)
        fence_s = time.perf_counter() - t0
        scan_mod.count_scan_block(self.registry, rounds)
        self.scan_trip = None
        trip = None
        if self.scan_tripwire:
            flat, trip = tripwire_mod.split_tripwire(flat, rounds=rounds)
        views = scan_mod.decode_block(
            flat,
            rounds=rounds,
            num_nodes=state0.num_nodes,
            explain_k=self.explain_k,
        )
        if trip is not None and trip.tripped:
            # the trip round's decision was made against the state the
            # rules judged unhealthy — commit only the rounds BEFORE it
            # and leave the trip report for _scanned_loop's drain
            views = views[: trip.trip_round]
            tripwire_mod.count_tripwire(self.registry, trip.rules)
            self.scan_trip = {
                "round": start + trip.trip_round,
                "block_start": start,
                "block_round": trip.trip_round,
                "rules": list(trip.rules),
                "mask": trip.trip_mask,
            }
            if self.logger is not None:
                self.logger.warn("scan_tripwire", **self.scan_trip)

        consumed = 0
        for i, v in enumerate(views):
            rnd = start + i
            t_r = time.perf_counter()
            self.boundary.begin_round(rnd)  # CLOSED stays CLOSED
            service_name = graph.names[v.service] if v.victim >= 0 else None
            target_name = (
                state0.node_names[v.target] if v.target >= 0 else None
            )
            hazard_node = (
                state0.node_names[v.most] if v.most >= 0 else None
            )
            landed_name: str | None = None
            diverged = False
            # attempted == the sequential loop's apply condition (a
            # decided victim with a decided target); the twin's landed
            # flag must agree with what the backend then reports
            attempted = v.victim >= 0 and v.target >= 0
            if attempted:
                hazard_names = tuple(
                    state0.node_names[j]
                    for j in range(state0.num_nodes)
                    if bool(v.hazard[j])
                )
                landed_name = self.boundary.apply_move(
                    MoveRequest(
                        service=service_name,
                        target_node=target_name,
                        hazard_nodes=hazard_names,
                        mechanism=mech,
                    )
                )
                if self.ledger is not None:
                    self.record_intents(
                        [move_intent(mech, service_name, target_name,
                                     landed_name)]
                    )
                expected = (
                    state0.node_names[v.landed] if v.landed >= 0 else None
                )
                if landed_name != expected:
                    # the backend disagreed with the twin about where
                    # this move landed — every later scanned decision
                    # was made against a diverged state. Finish THIS
                    # round degraded, resync on a fresh monitor, and
                    # hand the remaining rounds back to the per-round
                    # path (defensive: a scan-compatible backend cannot
                    # reach this — parity is oracle-pinned)
                    diverged = True
                    count_divergence(self.registry, KIND_UNKNOWN_LANDING)
                    if self.logger is not None:
                        self.logger.warn(
                            "scan_twin_divergence",
                            round=rnd,
                            service=service_name,
                            expected=expected,
                            landed=landed_name,
                        )
            moved = attempted and landed_name is not None
            record = RoundRecord(
                round=rnd,
                moved=moved,
                most_hazard=hazard_node,
                service=service_name if moved else None,
                target=landed_name if moved else None,
                communication_cost=0.0,  # filled from the block bundle
                load_std=0.0,
                services_moved=(service_name,) if moved else (),
                decision_latencies_s=(fence_s / rounds,),
                applied_moves=(
                    ((service_name, landed_name),) if moved else ()
                ),
                degraded=diverged,
            )
            if v.explain is not None:
                expl = greedy_explanation(
                    v.explain,
                    state0.node_names,
                    round=rnd,
                    seq=0,
                    policy=config.algorithm,
                    service=service_name,
                    hazard_node=hazard_node,
                    chosen=target_name if v.victim >= 0 else None,
                )
                if attempted:
                    # the apply outcome, exactly as the sequential
                    # loop's deferred decode patches it in
                    expl["landed"] = landed_name
                    expl["applied"] = landed_name is not None
                    if landed_name is None:
                        expl["stop"] = "boundary move failed"
                        expl["why"] += " (boundary move failed)"
                record.explanations = (expl,)
                if self.logger is not None:
                    self.logger.info("decision", **expl)
            self.boundary.advance(config.sleep_after_action_s)
            last = i == len(views) - 1 or diverged
            fresh = False
            if last:
                # block boundary: ONE admitted monitor realigns the
                # controller with the backend (bit-equal to the twin's
                # final state on a scan-compatible backend) and arms the
                # degraded-close fallback for any following drain round
                with span("backend/monitor"):
                    new_state = self.monitor_admitted()
                if new_state is None:
                    record.degraded = True
                else:
                    self.note_fresh_snapshot(new_state)
                    fresh = True
            self._reconcile_round(record, fresh=fresh)
            record.breaker_state = self.breaker.state
            record.boundary_failures = self.boundary.round_failures
            self._apply_round_metrics(
                rnd, record, v.cost, v.load_std, v.attr_flat, ctx
            )
            record.wall_s = (
                fence_s / rounds + time.perf_counter() - t_r
            )
            self.emit(rnd, record, mode="scanned")
            consumed += 1
            if diverged:
                break
        if self.ops is not None:
            # every block reports — a clean one clears the scan_tripwire
            # SLO rule and the in-flight staleness scaling; a tripped one
            # flips /healthz and dumps a bundle scoped to the partial
            # block
            self.ops.observe_scan_block(rounds=rounds, trip=self.scan_trip)
        return consumed


def _sequential_loop(rt: _Runtime) -> None:
    for rnd in range(rt.start_round, rt.config.max_rounds + 1):
        rt.sequential_round(rnd)


def _pipelined_loop(rt: _Runtime) -> None:
    """The software-pipelined schedule (``--pipeline``): per steady-state
    round the previous round's single-bundle flush + record finalize +
    ``on_round`` overlap this round's decision kernel executing on
    device, and the post-move ``advance`` + ``monitor`` run in a
    background thread overlapping the checkpoint write and the next
    iteration's bookkeeping. The backend observes the EXACT sequential
    call order — apply(r), advance, monitor(r), [load mutations from
    on_round(r)], apply(r+1), ... — which is why the schedules are
    bit-identical on the sim backend (test-pinned).

    Rounds that cannot pipeline — churn pending (the sequential loop
    re-masks before deciding), a streaming callable decision graph (the
    estimator updates in ``on_round`` must precede the graph read), or a
    breaker that is not CLOSED — drain the pipeline (the pending round
    finishes fully) and run the sequential path, so skip/degraded/remask
    accounting stays exact: ``max_rounds == records + skipped``.
    """
    cfg = rt.config
    depth = cfg.controller.depth
    pipeline_depth_gauge(rt.registry).set(depth)
    overlap_gauge = pipeline_overlap_gauge(rt.registry)
    ex = ThreadPoolExecutor(max_workers=1, thread_name_prefix="krt-boundary")
    pend: dict | None = None  # the one in-flight round (depth-2 pipeline)
    mon_future = None

    def finish(p: dict, end_t: float | None = None) -> None:
        if p["closed"]:
            return
        p["closed"] = True
        rec = p["record"]
        bg, blocked = p["bg_s"], p["blocked_s"]
        hidden = max(bg - blocked, 0.0)
        ratio = hidden / bg if bg > 1e-9 else 0.0
        rec.pipeline = {
            "depth": depth,
            "overlap_ratio": ratio,
            "background_s": bg,
            "blocked_s": blocked,
        }
        overlap_gauge.set(ratio)
        p["closer"].flush()
        # wall = round start to the NEXT round's start when pipelined
        # (end_t — the steady-state throughput quantity: per-round walls
        # sum to the loop's total instead of double-counting the overlap
        # windows shared with adjacent rounds); drain/tail closes fall
        # back to "now"
        rec.wall_s = (
            end_t if end_t is not None else time.perf_counter()
        ) - p["t0"]
        rt.emit(p["rnd"], rec, mode="pipelined")

    def checkpoint(p: dict) -> None:
        if rt.mgr is not None:
            rt.mgr.save(p["rnd"], p["state"], extra=rt.ckpt_extra())

    def settle(p: dict, future) -> None:
        """Join the pending round's in-flight advance+monitor and run its
        close bookkeeping — ONE definition for the loop-top and tail
        sites, so the final round can never close differently from
        steady-state rounds."""
        t_w = time.perf_counter()
        new_state, bg_s = future.result()
        p["blocked_s"] = time.perf_counter() - t_w
        p["bg_s"] = bg_s
        rt.begin_close(p["rnd"], p["record"], p["closer"], new_state)
        p["state"] = rt.state

    try:
        for rnd in range(rt.start_round, cfg.max_rounds + 1):
            if mon_future is not None:
                # settle the in-flight monitor of the pending round BEFORE
                # this round's begin_round resets the failure counters
                settle(pend, mon_future)
                mon_future = None
            can_pipeline = (
                rt.churn is None
                and rt.graph_static
                and rt.breaker.state == "closed"
            )
            if pend is not None and not can_pipeline:
                # drain: an open/half-open breaker (or any condition the
                # overlapped schedule cannot honor) finishes the pending
                # round completely and falls back to the sequential path
                finish(pend)
                checkpoint(pend)
                pend = None
            if not can_pipeline:
                rt.sequential_round(rnd)
                continue
            rt.boundary.begin_round(rnd)  # CLOSED stays CLOSED
            t0 = time.perf_counter()
            closer = RoundCloser(rt.registry)
            hook = None
            if pend is not None:
                prev = pend

                def hook(prev=prev, end_t=t0):
                    finish(prev, end_t)

            with span("controller/round", round=rnd, algorithm=cfg.algorithm):
                record = rt.execute_round(rnd, closer, pre_fence_hook=hook)
            if pend is not None:
                # a round body that never reached its fence (e.g. zero
                # decides) still owes the previous round its close
                finish(pend)
            prev_pend = pend
            mon_future = ex.submit(rt._advance_and_monitor)
            if prev_pend is not None:
                # the checkpoint write overlaps the background
                # advance+monitor (host IO only — resume replays at most
                # one extra round, and per-round keys make that replay
                # bit-deterministic)
                checkpoint(prev_pend)
            pend = {
                "rnd": rnd,
                "record": record,
                "closer": closer,
                "t0": t0,
                "closed": False,
                "bg_s": 0.0,
                "blocked_s": 0.0,
                "state": rt.state,
            }
        # drain the tail: the final round's monitor + close
        if mon_future is not None:
            settle(pend, mon_future)
        if pend is not None:
            finish(pend)
            checkpoint(pend)
    finally:
        ex.shutdown(wait=True)


def _scanned_loop(rt: _Runtime) -> None:
    """The device-resident scanned schedule (``--scan-block K`` /
    ``[controller] scan_block``): steady-state rounds advance K at a
    time through ONE compiled ``lax.scan`` dispatch and ONE counted
    ``round_end`` transfer per block (``bench/scan.py``), with the
    decided moves replayed into the backend afterwards in the exact
    sequential call order. Any round the scan cannot honor — a pending
    churn event or re-mask debt, a breaker that is not CLOSED, a
    checkpoint manager (it saves per round), an incompatible backend
    (chaos wrapper, replay, live adapter, noisy load model), a
    streaming decision graph, an ``on_round`` load hook, or a tail
    shorter than one block — DRAINS to the per-round sequential path
    (PR 9's discipline), counted as ``scan_drains_total{reason}``.
    Records and event streams are bit-identical to the sequential loop
    modulo timing fields (test-pinned)."""
    from kubernetes_rescheduling_tpu.bench.scan import count_scan_drain

    cfg = rt.config
    k = cfg.controller.scan_block
    static_reason = rt.scan_static_reason()
    rnd = rt.start_round
    while rnd <= cfg.max_rounds:
        reason = static_reason
        if reason is None:
            if (
                rt.churn is not None
                or rt.pending_churn
                or rt.remask_needed
                or rt.rebind_timeline
            ):
                reason = "churn"
            elif rt.breaker.state != "closed":
                reason = "breaker"
            elif cfg.max_rounds - rnd + 1 < k:
                # a partial block would be a new static (rounds=...)
                # signature — a retrace per distinct tail length; the
                # tail runs per-round instead, keeping the 1-trace pin
                reason = "tail"
        if reason is not None:
            count_scan_drain(rt.registry, reason)
            if rt.ops is not None:
                rt.ops.observe_scan_drain(reason)
            rt.sequential_round(rnd)
            rnd += 1
            continue
        rnd += rt.scan_block_rounds(rnd, k)
        if rt.scan_trip is not None:
            # the tripwire latched mid-block: the replay committed the
            # rounds before the trip; the tripped round itself re-runs
            # on the per-round path (bit-identical decision by key
            # parity) under its own counted drain reason — guaranteed
            # progress even when the trip lands on block round 0
            count_scan_drain(rt.registry, "tripwire")
            if rt.ops is not None:
                rt.ops.observe_scan_drain("tripwire")
            rt.scan_trip = None
            rt.sequential_round(rnd)
            rnd += 1


def run_controller(
    backend: Backend,
    config: RescheduleConfig,
    *,
    key: jax.Array | None = None,
    on_round=None,
    checkpoint_dir: str | None = None,
    logger: StructuredLogger | None = None,
    graph=None,
    registry=None,
    ops=None,
    churn=None,
) -> ControllerResult:
    """Run ``config.max_rounds`` rounds against a backend.

    ``graph`` overrides the backend's declared comm graph for the DECISION
    kernels — the harness passes traffic-estimated weights here
    (``LoadGenerator.observed_graph``) so the solver optimizes what the
    request stream actually does, not what the workmodel claims. A
    zero-arg CALLABLE is re-evaluated at every round, so an estimator fed
    by the sustained load keeps the decision graph tracking the traffic
    as it drifts (shapes are static — no retrace).

    ``on_round(record, state)`` — if given — is called after each round with
    the completed record and the post-move snapshot; the harness uses it to
    sustain simulated request load while the loop runs (reference
    release2.sh:50-59).

    ``checkpoint_dir`` enables crash-resume: the post-move snapshot is saved
    every round, and on start the latest checkpoint (if any) restores the
    backend placement (``restore_placement``, sim only — a live cluster IS
    its own state) and skips the already-completed rounds. Per-round keys
    derive from ``fold_in(key, round)`` so a resumed run makes the same
    decisions the uninterrupted run would have.

    ``logger`` records one structured event per round (SURVEY §5.5 gap).

    ``registry`` (default: the process registry) receives one metric
    sample set per round — counters ``rounds_total``/
    ``services_moved_total``, the ``decision_seconds`` histogram, the
    ``wall_round_ms`` lifecycle histogram, and cost/objective gauges —
    alongside the spans the loop emits.

    Resilience: ``config.chaos`` optionally wraps the backend in the
    fault-injecting ``ChaosBackend``; either way every boundary call goes
    through a ``BoundaryClient`` (retry + circuit breaker — see
    ``bench/boundary.py``). When the breaker opens, the loop enters safe
    mode: moves freeze, the last good snapshot is reused, and each frozen
    round is a COUNTED skip (``result.skipped_rounds``; never a silent
    hole — ``max_rounds == len(result.rounds) + result.skipped_rounds``).

    ``ops`` (a ``telemetry.server.OpsPlane``) attaches the live ops
    plane: /healthz reads the breaker and SLO watchdog in real time, the
    flight recorder rings the last N rounds and dumps a bundle on
    breaker-open / crash / SIGUSR1, and each round feeds the watchdog.
    Decision explainability is on whenever ``config.obs.explain`` and a
    logger or ops plane is attached: rounds carry ``DecisionExplanation``
    dicts (``record.explanations``) and emit ``decision`` events.

    Elastic topologies: ``churn`` (an ``elastic.engine.ChurnEngine``, or
    built automatically from ``config.elastic``) applies seeded churn
    events between rounds THROUGH the boundary's backend passthrough —
    services deploy/tear down, replicas autoscale, nodes drain/join.
    Snapshots stay padded to quantized shape buckets, device kernels see
    name-stripped views, and the loop re-reads the comm graph + re-masks
    via a fresh snapshot only on rounds that actually churned — so steady
    state stays at exactly 1 trace per kernel across arbitrary churn
    within a bucket (retrace only on a counted bucket promotion).
    Churn lands on ``RoundRecord.churn`` → rounds.jsonl.

    Round-end transfers: every executed round closes its reporting —
    comm cost, load std, the attribution bundle, explain bundles, the
    forecast diag, solver objectives — through ONE counted device→host
    transfer (``device_transfers_total{site="round_end"}``;
    ``bench/round_end.py``). A degraded round closing on an
    already-measured snapshot reuses the cached values and costs at most
    the transfer for its fresh per-round diagnostics.

    Reconciliation & admission (``config.reconcile``): every monitor
    snapshot passes the admission guard (``bench/admission.py``) before
    touching device state — poisoned readings are quarantined to
    last-good values, structurally broken snapshots degrade the round —
    and the intent ledger (``bench/reconcile.py``) diffs each admitted
    snapshot against the controller's recorded intent, classifying and
    counting divergences (lost moves, wrong-node landings, external
    drift) and issuing up to ``reconcile.repair_budget_per_round``
    corrective moves per round until observed state converges back to
    intent. Rounds with any such activity carry a ``reconcile`` block;
    a clean run is bit-identical to the plane-off controller
    (golden-pinned). The ledger persists through checkpoints, so resume
    reconciles instead of trusting the first snapshot blindly.

    ``config.controller.pipeline`` selects the software-pipelined
    schedule: the same helper calls interleaved so the previous round's
    flush + host tail overlap this round's device compute, with the
    post-move monitor in a background thread. Decisions, records, and
    all accounting are bit-identical to the sequential schedule on the
    sim backend (test-pinned); rounds the pipeline cannot honor (open
    breaker, pending churn, streaming graph) drain and run sequentially.

    ``config.controller.scan_block`` selects the third schedule — the
    device-resident round scan (``bench/scan.py``): K steady-state
    rounds fuse decide → sim-twin apply → monitor → round-end metrics
    into ONE compiled ``lax.scan`` dispatch with ONE counted
    ``round_end`` transfer per block, the decided moves replayed into
    the backend afterwards in the sequential call order. Rounds the scan
    cannot honor drain to the per-round path
    (``scan_drains_total{reason}``); records stay bit-identical modulo
    timing fields (test-pinned). Requires a raw noise-free sim backend —
    anything else drains every round.
    """
    config = config.validate()
    registry = registry if registry is not None else get_registry()
    key = key if key is not None else jax.random.PRNGKey(config.seed)
    rt = _Runtime(
        backend,
        config,
        key=key,
        on_round=on_round,
        checkpoint_dir=checkpoint_dir,
        logger=logger,
        graph=graph,
        registry=registry,
        ops=ops,
        churn=churn,
    )
    try:
        if config.controller.scan_block:
            _scanned_loop(rt)
        elif config.controller.pipeline:
            _pipelined_loop(rt)
        else:
            _sequential_loop(rt)
    except BaseException as e:
        # the always-on crash-dump path: whatever escapes the loop leaves
        # a flight-recorder bundle behind before propagating
        if ops is not None:
            ops.on_crash(e)
        raise
    rt.result.breaker_transitions = list(rt.breaker.transitions)
    rt.result.boundary_failures = rt.boundary.total_failures
    return rt.result


def _greedy_round(
    boundary, state, graph, config, key, rnd, *, logger=None, explain_k=0,
    forecast_delta=None, closer=None, pre_fence_hook=None,
    registry=None, intents=None,
) -> RoundRecord:
    """Up to ``config.moves_per_round`` greedy moves: after each move the
    working snapshot is edited in place (the moved service's pods re-homed —
    reference main.py:73's ``edit_cluster`` intent, done correctly), so the
    next decision sees the drained hazard node and stops early once nothing
    is hazardous anymore.

    With ``explain_k > 0`` each decide runs the explain twin of the
    decision kernel (bit-identical choice) and records a
    ``DecisionExplanation`` — top-k hazard nodes, top-k candidate targets
    with score margins, chosen target and why. The bundle stays
    device-resident on ``closer`` and rides the round's single
    ``round_end`` transfer; the decode (and the ``decision`` event) runs
    at flush, in decide order, before the round event.

    ``forecast_delta`` (proactive rounds) routes every decide through the
    forecast-aware kernels: the same scoring policy (the forecast
    config's base policy — reactive CAR by default) evaluated against
    the PREDICTED next-window state. A zero delta reproduces the
    reactive decisions bit-for-bit.

    ``pre_fence_hook`` (the pipelined schedule) runs once, after the
    first decide has been dispatched and before its apply-boundary
    fence — the window where the previous round's flush and host tail
    hide behind this round's device compute."""
    scoring = scoring_policy(config.algorithm, config.forecast)
    pid = jnp.asarray(POLICY_IDS[scoring])
    k_moves = config.moves_per_round
    first_hazard: str | None = None
    moved_names: list[str] = []
    applied_moves: list[tuple[str, str]] = []
    first_target: str | None = None
    latencies: list[float] = []
    explanations: list[dict] = []
    unknown_landing = False

    def defer_explanation(bundle, meta):
        """Register the explain bundle's decode on the round closer: the
        DecisionExplanation is built host-side at flush time from the
        pulled rows plus the apply outcome recorded in ``meta`` during
        the round (landed/stop patches — the historical emit())."""

        def decode(flat):
            expl = greedy_explanation(
                flat,
                meta["node_names"],
                round=meta["round"],
                seq=meta["seq"],
                policy=meta["policy"],
                service=meta["service"],
                hazard_node=meta["hazard_node"],
                chosen=meta["chosen"],
            )
            if meta.get("applied_known"):
                expl["landed"] = meta["landed"]
                expl["applied"] = meta["landed"] is not None
            stop = meta.get("stop")
            if stop is not None:
                expl["stop"] = stop
                expl["why"] += f" ({stop})"
            explanations.append(expl)
            if logger is not None:
                logger.info("decision", **expl)

        closer.defer(bundle, decode)

    for i in range(k_moves):
        key, sub = jax.random.split(key)
        t0 = time.perf_counter()
        with span("controller/decide", round=rnd):
            # name-stripped device views (elastic.buckets): the kernels
            # never read the static name tuples, and keeping them out of
            # the jit key is what lets pod/node churn reuse one compiled
            # program (names stay on the full state for the host side)
            dev_state, dev_graph = device_view(state), device_graph(graph)
            thr = jnp.asarray(config.hazard_threshold_pct)
            if explain_k > 0:
                if forecast_delta is not None:
                    out = _decide_proactive_explain(
                        dev_state, dev_graph, pid, thr, sub, forecast_delta,
                        top_k=explain_k,
                    )
                else:
                    out = _decide_explain(
                        dev_state, dev_graph, pid, thr, sub, top_k=explain_k,
                    )
                decision_dev, bundle = out[:5], out[5]
            else:
                bundle = None
                if forecast_delta is not None:
                    decision_dev = _decide_proactive(
                        dev_state, dev_graph, pid, thr, sub, forecast_delta
                    )
                else:
                    decision_dev = _decide(dev_state, dev_graph, pid, thr, sub)
            if pre_fence_hook is not None:
                # the pipelined overlap window: the previous round's
                # single-bundle pull + host tail run while this decide
                # executes on device
                pre_fence_hook()
                pre_fence_hook = None
            # the apply boundary: ONE batched host read of the decision
            # tuple (never per-element int()/bool() syncs)
            most, hazard_mask, victim, svc, target = fence(decision_dev)
        latencies.append(time.perf_counter() - t0)

        most_i, victim_i, target_i = int(most), int(victim), int(target)
        service_name = graph.names[int(svc)] if victim_i >= 0 else None
        target_name = state.node_names[target_i] if target_i >= 0 else None
        meta = None
        if bundle is not None:
            meta = {
                "node_names": state.node_names,
                "round": rnd,
                "seq": i,
                "policy": config.algorithm,
                "service": service_name,
                "hazard_node": state.node_names[most_i] if most_i >= 0 else None,
                "chosen": target_name if victim_i >= 0 else None,
            }
            defer_explanation(bundle, meta)
        if first_hazard is None and most_i >= 0:
            first_hazard = state.node_names[most_i]
        if most_i < 0 or victim_i < 0 or target_i < 0:
            break  # no hazard left (or nowhere to go): the round is done
        if service_name in moved_names:
            # the drain has started ping-ponging (the move made the target
            # the new hazard node and elected the same service back) —
            # further moves this round are churn, not progress
            if meta is not None:
                meta["stop"] = "ping-pong stop: service already moved this round"
            break
        hazard_names = tuple(
            state.node_names[j]
            for j in range(state.num_nodes)
            if bool(hazard_mask[j])
        )
        landed = boundary.apply_move(
            MoveRequest(
                service=service_name,
                target_node=target_name,
                hazard_nodes=hazard_names,
                # proactive resolves to its base policy's mechanism (the
                # forecast changes the state scored, not how the move pins)
                mechanism=PlacementMechanism[scoring],
            )
        )
        if intents is not None:
            # the advisory/pinning intent rule lives in move_intent —
            # ONE definition shared with the fleet loop
            intents.append(
                move_intent(
                    PlacementMechanism[scoring],
                    service_name,
                    target_name,
                    landed,
                )
            )
        if meta is not None:
            meta["applied_known"] = True
            meta["landed"] = landed
            if landed is None:
                meta["stop"] = "boundary move failed"
        if landed is None:
            break
        moved_names.append(service_name)
        applied_moves.append((service_name, landed))
        if first_target is None:
            first_target = landed
        if landed not in state.node_names:
            # the move landed on a node the working snapshot does not even
            # KNOW (drained mid-flight under churn + a wrong-node landing):
            # patching pod_node with the stale target index would lie to
            # every following decide this round. Count the divergence,
            # stop the round, and finish it DEGRADED — the next monitor
            # realigns the truth, and the reconcile plane repairs the pod
            count_divergence(registry, KIND_UNKNOWN_LANDING)
            unknown_landing = True
            if meta is not None:
                meta["stop"] = "landed on a node unknown to the snapshot"
            if logger is not None:
                logger.warn(
                    "unknown_landing",
                    round=rnd,
                    service=service_name,
                    landed=landed,
                )
            break
        if i + 1 < k_moves:
            # re-home the moved service in the working snapshot — to where
            # it actually LANDED (the scheduler may have overridden the
            # advisory target under the affinityOnly mechanism)
            landed_i = state.node_names.index(landed)
            svc_pods = (state.pod_service == int(svc)) & state.pod_valid
            state = state.replace(
                pod_node=jnp.where(svc_pods, landed_i, state.pod_node)
            )

    record = RoundRecord(
        round=rnd,
        moved=bool(moved_names),
        most_hazard=first_hazard,
        service=moved_names[0] if moved_names else None,
        target=first_target,
        communication_cost=0.0,  # filled at the round-end flush
        load_std=0.0,
        services_moved=tuple(moved_names),
        decision_latencies_s=tuple(latencies),
        applied_moves=tuple(applied_moves),
        # an unknown landing means the working snapshot could not follow
        # the cluster mid-round: the round closes on honest-but-stale
        # bookkeeping, labeled exactly like a failed post-move monitor
        degraded=unknown_landing,
    )
    if explain_k > 0:
        # the deferred decodes above fill `explanations` at flush time —
        # materialize them onto the record after the last decode runs
        closer.defer_host(
            lambda: setattr(record, "explanations", tuple(explanations))
        )
    return record


def _move_scoring_env(state, graph, solver_cfg):
    """Host-side scoring context over one snapshot — the shared setup for
    the wave-cap selection (``_top_gain_moves``) and the per-move gain
    scores the ``global`` DecisionExplanation records."""
    import types

    S = graph.num_services
    svc_arr = np.asarray(state.pod_service)
    valid = np.asarray(state.pod_valid)
    old_nodes = np.asarray(state.pod_node)
    pod_cpu = np.asarray(state.pod_cpu)
    pod_mem = np.asarray(state.pod_mem)
    svc_node = np.full(S, -1, dtype=np.int64)
    svc_cpu = np.zeros(S)
    svc_mem = np.zeros(S)
    for i in np.flatnonzero(valid):
        s = int(svc_arr[i])
        if 0 <= s < S:
            if svc_node[s] < 0:
                svc_node[s] = old_nodes[i]
            svc_cpu[s] += float(pod_cpu[i])
            svc_mem[s] += float(pod_mem[i])
    replicas = np.bincount(svc_arr[valid & (svc_arr >= 0) & (svc_arr < S)], minlength=S)
    adj = np.asarray(graph.adj)
    placed = svc_node >= 0

    node_valid = np.asarray(state.node_valid)
    ow = solver_cfg.overload_weight if solver_cfg.enforce_capacity else 0.0
    cap = np.where(
        np.asarray(state.node_cpu_cap) > 0, np.asarray(state.node_cpu_cap), 1.0
    ) * solver_cfg.capacity_frac
    mem_cap_raw = np.asarray(state.node_mem_cap)
    mem_cap = (
        np.where(mem_cap_raw > 0, mem_cap_raw, np.inf)
        * solver_cfg.capacity_frac
    )
    used = np.asarray(state.node_cpu_used())
    mem_used = np.asarray(state.node_mem_used())

    def balance_terms(loads):
        # the solver's OWN expression, evaluated host-side (xp=np)
        return float(
            pct_balance_terms(
                loads, cap, node_valid, solver_cfg.balance_weight, ow, xp=np
            )
        )

    return types.SimpleNamespace(
        svc_node=svc_node, svc_cpu=svc_cpu, svc_mem=svc_mem,
        replicas=replicas, adj=adj, placed=placed,
        cap=cap, mem_cap=mem_cap, used=used, mem_used=mem_used,
        enforce_capacity=solver_cfg.enforce_capacity,
        balance_terms=balance_terms,
    )


def _move_gain(env, work_node, loads, mem_loads, bal_now, s, t):
    """(gain, feasible) of relocating service ``s`` to ``t`` at the given
    working state — the solver's own accounting (comm cut + balance terms,
    capacity feasibility when enforced)."""
    w = env.adj[s] * env.replicas[s] * env.replicas
    cut_before = float(np.sum(w[env.placed & (work_node != work_node[s])]))
    cut_after = float(np.sum(w[env.placed & (work_node != t)]))
    new_loads = loads.copy()
    if 0 <= work_node[s] < len(new_loads):
        new_loads[work_node[s]] -= env.svc_cpu[s]
    new_loads[t] += env.svc_cpu[s]
    feasible = not (
        env.enforce_capacity
        and t != work_node[s]
        and (
            new_loads[t] > env.cap[t]
            or mem_loads[t] + env.svc_mem[s] > env.mem_cap[t]
        )
    )
    gain = cut_before - cut_after + bal_now - env.balance_terms(new_loads)
    return gain, feasible


def _individual_move_gains(
    changed: list[tuple[int, int]], state=None, graph=None, solver_cfg=None,
    *, env=None,
) -> list[tuple[int, int, float]]:
    """Each candidate move's INDIVIDUAL gain at the round-start state
    (every other service held in place) — what the uncapped global
    round's explanation records as candidate scores. ``env`` (a prebuilt
    ``_move_scoring_env``) lets the donated-carry global round collapse
    the snapshot host-side BEFORE the solver consumes its buffers."""
    if env is None:
        env = _move_scoring_env(state, graph, solver_cfg)
    work_node = env.svc_node.copy()
    loads = env.used.copy()
    mem_loads = env.mem_used.copy()
    bal_now = env.balance_terms(loads)
    return [
        (s, t, _move_gain(env, work_node, loads, mem_loads, bal_now, s, t)[0])
        for s, t in changed
    ]


def _top_gain_moves(
    changed: list[tuple[int, int]], state=None, graph=None, solver_cfg=None,
    k: int = 0, *, env=None,
) -> list[tuple[int, int, float]]:
    """≤``k`` strictly-improving moves selected GREEDILY AND SEQUENTIALLY,
    using the SOLVER's own accounting (``solver_cfg`` is the round's
    GlobalSolverConfig): comm + λ·std of CPU-% **of the packing budget**
    (``capacity_frac``-scaled, exactly as the solver's objective measures
    load) + the over-budget repulsion term when capacity is enforced.

    Each accepted move updates the working placement and node loads, and
    every remaining candidate is re-scored against that updated state —
    so the wave is jointly consistent: two moves cannot cumulatively
    over-budget one node (while capacity is enforced, a candidate whose
    target would newly exceed the CPU or memory budget is skipped — the
    solver's own feasibility rule), and a move the solver admitted only
    because an earlier move vacates its target is scored with that
    vacancy visible.

    Comm gain of relocating service ``s`` to ``t`` with every *unmoved*
    service fixed: ``Σ_j W[s,j]·([node_j ≠ cur_s] − [node_j ≠ t])`` on the
    replica-weighted pair matrix (row-wise host-side — only the changed
    services' adjacency rows are touched). Candidates whose gain at their
    evaluation state is ≤ 0 are never selected — they only pay off in
    combination with moves this wave did not take, and applying them alone
    is churn (the convergence criterion: a capped loop stops when no
    single next move helps).

    Returns ``(service, target, gain)`` triples — the gain at each move's
    EVALUATION state, which the ``global`` DecisionExplanation records as
    the candidate score. ``env`` (a prebuilt ``_move_scoring_env``) lets
    the donated-carry global round collapse the snapshot host-side
    BEFORE the solver consumes its buffers."""
    if env is None:
        env = _move_scoring_env(state, graph, solver_cfg)
    work_node = env.svc_node.copy()
    loads = env.used.copy()
    mem_loads = env.mem_used.copy()
    picked: list[int] = []
    gains: dict[int, float] = {}
    remaining = list(range(len(changed)))
    for _ in range(min(k, len(changed))):
        bal_now = env.balance_terms(loads)
        best_i, best_gain = None, 1e-9
        for i in remaining:
            s, t = changed[i]
            gain, feasible = _move_gain(
                env, work_node, loads, mem_loads, bal_now, s, t
            )
            if not feasible:
                continue  # would newly exceed a budget at the CURRENT loads
            # strict >: ties go to the earliest candidate (lower position)
            if gain > best_gain:
                best_i, best_gain = i, gain
        if best_i is None:
            break  # no remaining move helps on its own — wave converged
        s, t = changed[best_i]
        if 0 <= work_node[s] < len(loads):
            loads[work_node[s]] -= env.svc_cpu[s]
            mem_loads[work_node[s]] -= env.svc_mem[s]
        loads[t] += env.svc_cpu[s]
        mem_loads[t] += env.svc_mem[s]
        work_node[s] = t
        picked.append(best_i)
        gains[best_i] = best_gain
        remaining.remove(best_i)
    return [(*changed[i], gains[i]) for i in sorted(picked)]


def _defer_solver_objectives(closer, info, apply_cb) -> None:
    """Defer the solver's before/after accounting onto the round closer:
    the values ride the round's single ``round_end`` transfer instead of
    their own counted pull. Some restart paths omit
    ``objective_before``/``improved`` — absent keys decode to None rather
    than forcing every solver to grow them. ``apply_cb(before, after,
    improved)`` runs at flush, before the record is emitted."""
    keys = [
        k for k in ("objective_before", "objective_after", "improved")
        if k in info
    ]
    if not keys:
        closer.defer_host(lambda: apply_cb(None, None, None))
        return
    piece = jnp.stack([jnp.asarray(info[k], jnp.float32) for k in keys])

    def decode(flat) -> None:
        d = dict(zip(keys, flat))
        apply_cb(
            float(d["objective_before"]) if "objective_before" in d else None,
            float(d["objective_after"]) if "objective_after" in d else None,
            bool(d["improved"]) if "improved" in d else None,
        )

    closer.defer(piece, decode)


def _pod_round(
    boundary, state, graph, config, cfg, key, rnd, *, logger=None,
    explain=False, closer=None, pre_fence_hook=None, intents=None,
) -> RoundRecord:
    """Per-replica global round: solve on the expanded pod graph, apply
    per-pod moves (MoveRequest.pod). The pod graph is cached per
    (declared graph, pod set) — pod churn or a re-estimated graph
    rebuilds it."""
    from kubernetes_rescheduling_tpu.solver.pod_mode import (
        global_assign_pods,
        pod_level_graph,
    )

    t0 = time.perf_counter()
    # host-side copies of the incoming placement BEFORE the solve (the
    # donated-carry discipline of the dense global path, kept symmetric
    # here even though the pod solver does not donate yet)
    old_nodes = np.asarray(state.pod_node)
    valid = np.asarray(state.pod_valid)
    svc_arr = np.asarray(state.pod_service)
    sig = (svc_arr.tobytes(), valid.tobytes())
    # tenant-aware slot on the RAW backend (boundary.solver_cache): keyed
    # past this run's wrappers so repeated runs keep the reuse, and past
    # the tenant so fleet multiplexing neither cross-pollinates nor
    # rebuilds per round
    cache = boundary.solver_cache("pod_graph")
    if cache.get("graph") is not graph or cache.get("sig") != sig:
        # build BEFORE keying: a failed build must not leave a matching
        # key over a stale value (the backend — and so the slot — can
        # outlive this run and be retried)
        value = pod_level_graph(state, graph)
        cache["graph"], cache["sig"], cache["value"] = graph, sig, value
    pod_graph = cache["value"]
    with span("controller/pod_solve", round=rnd):
        # name-stripped device views (elastic.buckets): the solver never
        # reads the static name tuples (the pod graph above is built from
        # the FULL state), and keeping them out of the jit key lets churn
        # reuse the compiled program — the greedy path's rule, same here
        new_state, info = global_assign_pods(
            device_view(state), device_graph(graph), key, cfg,
            pod_graph=pod_graph,
            n_restarts=config.solver_restarts,
            tp=config.solver_tp,
        )
        if pre_fence_hook is not None:
            # the pipelined overlap window: the previous round's flush +
            # host tail run while the solve executes on device
            pre_fence_hook()
        # the apply boundary: ONE batched host read of the new placement
        new_nodes = fence(new_state.pod_node)
    latency = time.perf_counter() - t0

    moves: list[MoveRequest] = []
    for i in np.flatnonzero(valid & (old_nodes != new_nodes)):
        moves.append(
            MoveRequest(
                service=graph.names[int(svc_arr[i])],
                pod=state.pod_names[int(i)],
                # index into the FULL state's names — the solver ran on
                # the name-stripped view (same node axis)
                target_node=state.node_names[int(new_nodes[i])],
                mechanism=PlacementMechanism["global"],
            )
        )
    # batch path: one reconcile wave for the whole round's replica moves
    # (per-call apply_move would scan the pod table and advance the sim
    # clock once PER REPLICA); backends without it get individual calls.
    # The batch call passes through the boundary un-retried (sim-only —
    # the simulator's batch wave cannot transiently fail).
    batch = getattr(boundary, "apply_pod_moves", None)
    moved_services: set[str] = set()
    landed_moves: list[MoveRequest] = []
    applied_moves: list[tuple[str, str]] = []  # (service, LANDED node)
    if batch is not None:
        # the wave reports where each pod actually LANDED (pod -> node):
        # a chaos wrong-node redirect overrides the requested target on
        # this path too, and the intent ledger needs the true claim to
        # classify it wrong_node rather than external_drift
        landed_of = dict(batch(moves)) if moves else {}
        landed_moves = [mv for mv in moves if mv.pod in landed_of]
        applied_moves = [
            (mv.service, landed_of[mv.pod]) for mv in landed_moves
        ]
        if intents is not None:
            intents.extend(
                move_intent(
                    mv.mechanism,
                    mv.service,
                    mv.target_node,
                    landed_of.get(mv.pod),
                    pod=mv.pod,
                )
                for mv in moves
            )
    else:
        for mv in moves:
            landed_node = boundary.apply_move(mv)
            if intents is not None:
                intents.append(
                    move_intent(
                        mv.mechanism,
                        mv.service,
                        mv.target_node,
                        landed_node,
                        pod=mv.pod,
                    )
                )
            if landed_node is not None:
                landed_moves.append(mv)
                # record where the move actually LANDED (a scheduler —
                # or an injected fault — may override the target)
                applied_moves.append((mv.service, landed_node))
    moved_services = {mv.service for mv in landed_moves}
    moved_any = bool(moved_services)

    # services_moved carries the SERVICE names of moves that LANDED: its
    # consumers — the harness's teardown-outage injection and restart
    # accounting — are service-granular, and a pod name (or a move a dead
    # node rejected) would charge disruption that never happened
    record = RoundRecord(
        round=rnd,
        moved=moved_any,
        most_hazard=None,
        service=None,
        target=None,
        communication_cost=0.0,  # filled at the round-end flush
        load_std=0.0,
        services_moved=tuple(sorted(moved_services)) if moved_any else (),
        decision_latencies_s=(latency,),
        # pod-level provenance: each landed REPLICA hop (a service may
        # appear once per pod) — the timeline records residency without
        # service-collapsed cost deltas for these
        applied_moves=tuple(applied_moves),
    )

    def _apply_objectives(obj_before, obj_after, improved) -> None:
        record.objective_before = obj_before
        record.objective_after = obj_after
        record.solver_improved = improved
        if not explain:
            return
        # per-service candidates scored by replicas relocated — the pod
        # round's unit of disruption; chosen = the most-relocated service
        per_svc: dict[str, dict] = {}
        for mv in landed_moves:
            d = per_svc.setdefault(
                mv.service,
                {"service": mv.service, "node": mv.target_node,
                 "node_index": None, "score": 0.0, "applied": True},
            )
            d["score"] += 1.0
        expl = solver_explanation(
            kind="pod",
            round=rnd,
            policy=config.algorithm,
            candidates=sorted(per_svc.values(), key=lambda d: d["service"]),
            objective_before=obj_before,
            objective_after=obj_after,
            applied=len(landed_moves),
            proposed=len(moves),
        )
        if logger is not None:
            logger.info("decision", **expl)
        record.explanations = (expl,)

    # the solver's before/after accounting rides the round-end bundle
    _defer_solver_objectives(closer, info, _apply_objectives)
    return record


def _global_round(
    boundary, state, graph, config, key, rnd, *, logger=None, explain=False,
    closer=None, pre_fence_hook=None, donate=False, carry=None, intents=None,
) -> RoundRecord:
    cfg = GlobalSolverConfig(
        sweeps=config.global_solver_iters,
        balance_weight=config.balance_weight,
        enforce_capacity=config.enforce_capacity,
        capacity_frac=config.capacity_frac,
        move_cost=config.move_cost,
    )
    if config.placement_unit == "pod":
        return _pod_round(
            boundary, state, graph, config, cfg, key, rnd,
            logger=logger, explain=explain,
            closer=closer, pre_fence_hook=pre_fence_hook, intents=intents,
        )
    t0 = time.perf_counter()
    sparse_graph = None
    if config.solver_backend == "sparse":
        # block-local pair weights. The SparseCommGraph is cached per
        # (backend, tenant, graph): the controller re-solves the same
        # declared graph every round, and the host-side build pulls the
        # full adjacency; streaming re-estimated graphs rebuild each
        # round (boundary.solver_cache — tenant-keyed so fleet
        # multiplexing cannot cross-pollinate or thrash the slot).
        from kubernetes_rescheduling_tpu.core import sparsegraph

        cache = boundary.solver_cache("sparse_graph")
        if cache.get("graph") is not graph:
            # build BEFORE keying (see the pod-graph cache note)
            value = sparsegraph.from_comm_graph(graph)
            cache["graph"], cache["value"] = graph, value
        sparse_graph = cache["value"]
    # EVERYTHING the host needs from the incoming placement is read
    # BEFORE the solve: with ``donate`` the dense solver consumes the
    # snapshot's device buffers (the output placement aliases them), so
    # post-solve host reads of the input state would touch freed memory.
    # The move-scoring env (an O(P) host collapse) pre-builds only on
    # the donated path for the same reason — undonated rounds keep the
    # historical lazy build inside the gain helpers (an explain round
    # with zero proposed moves never pays it)
    old_nodes = np.asarray(state.pod_node)
    valid = np.asarray(state.pod_valid)
    svc_arr = np.asarray(state.pod_service)
    cap = config.global_moves_cap
    env = (
        _move_scoring_env(state, graph, cfg)
        if donate and (isinstance(cap, int) or explain)
        else None
    )
    with span("controller/global_solve", round=rnd):
        # name-stripped device views, like the greedy path: the sparse
        # graph above is built from the FULL graph; the solver itself
        # only ever reads arrays, so stripping keeps churned pod/node
        # names out of the jit key (1 trace + promotions holds for
        # global rounds too — regression-tested)
        new_state, info = solve_with_restarts(
            device_view(state),
            device_graph(graph),
            key,
            n_restarts=config.solver_restarts,
            config=cfg,
            tp=config.solver_tp,
            sparse_graph=sparse_graph,
            donate=donate,
        )
        if pre_fence_hook is not None:
            # the pipelined overlap window: the previous round's flush +
            # host tail run while the solve executes on device
            pre_fence_hook()
        # the apply boundary: ONE batched host read of the new placement
        new_nodes = fence(new_state.pod_node)
    latency = time.perf_counter() - t0

    if info.pop("donated", False) and carry is not None:
        # the solver consumed the snapshot's device buffers — but the
        # loop's degraded/skip paths may still need the PRE-solve
        # snapshot (a failed post-move monitor carries it into the next
        # round's decide). Resurrect it bit-exactly: every non-pod_node
        # leaf of the output is a pass-through alias of the input, and
        # the old placement was host-read above — one small i32[P]
        # re-upload, off the critical path
        import dataclasses as _dc

        updates = {
            f.name: getattr(new_state, f.name)
            for f in _dc.fields(new_state)
            if f.name not in ("node_names", "pod_names")
        }
        updates["pod_node"] = jnp.asarray(old_nodes)
        carry["state"] = state.replace(**updates)

    changed: list[tuple[int, int]] = []  # (service, target node)
    seen: set[int] = set()
    for i in np.flatnonzero(valid & (old_nodes != new_nodes)):
        s = int(svc_arr[i])
        if s in seen:
            continue
        seen.add(s)
        changed.append((s, int(new_nodes[i])))

    proposed = len(changed)
    gains: dict[tuple[int, int], float] = {}
    if isinstance(cap, int):
        # wave cap: apply only the k moves whose INDIVIDUAL relocation
        # (others held at their old nodes) most improves the solver's
        # objective (comm + balance), and only strictly-improving ones —
        # the rest of the solve is re-derived next round, so the optimum
        # is still pursued k Deployments at a time, and once no single
        # move helps on its own the loop is converged instead of churning
        # (the full solution may keep shifting under annealing noise)
        scored = _top_gain_moves(changed, state, graph, cfg, cap, env=env)
        changed = [(s, t) for s, t, _ in scored]
        gains = {(s, t): g for s, t, g in scored}
    elif explain and changed:
        # uncapped rounds never score moves for selection — score them
        # once at the start state so the explanation still carries why
        gains = {
            (s, t): g
            for s, t, g in _individual_move_gains(
                changed, state, graph, cfg, env=env
            )
        }

    moved_any = False
    moved_names: list[str] = []
    applied_moves: list[tuple[str, str]] = []
    for s, target in changed:
        landed = boundary.apply_move(
            MoveRequest(
                service=graph.names[s],
                # FULL state's node names (the solver ran name-stripped)
                target_node=state.node_names[target],
                mechanism=PlacementMechanism["global"],
            )
        )
        if intents is not None:
            intents.append(
                move_intent(
                    PlacementMechanism["global"],
                    graph.names[s],
                    state.node_names[target],
                    landed,
                )
            )
        moved_any = moved_any or landed is not None
        if landed is not None:
            moved_names.append(graph.names[s])
            applied_moves.append((graph.names[s], landed))

    record = RoundRecord(
        round=rnd,
        moved=moved_any,
        most_hazard=None,
        service=None,
        target=None,
        communication_cost=0.0,  # filled at the round-end flush
        load_std=0.0,
        services_moved=tuple(moved_names),
        decision_latencies_s=(latency,),
        applied_moves=tuple(applied_moves),
    )

    candidates = [
        {
            "service": graph.names[s],
            "node": state.node_names[t],
            "node_index": int(t),
            "score": float(gains.get((s, t), 0.0)),
            "applied": graph.names[s] in moved_names,
        }
        for s, t in changed
    ]

    def _apply_objectives(obj_before, obj_after, improved) -> None:
        record.objective_before = obj_before
        record.objective_after = obj_after
        record.solver_improved = improved
        if not explain:
            return
        expl = solver_explanation(
            kind="global",
            round=rnd,
            policy=config.algorithm,
            candidates=candidates,
            objective_before=obj_before,
            objective_after=obj_after,
            applied=len(moved_names),
            proposed=proposed,
        )
        if logger is not None:
            logger.info("decision", **expl)
        record.explanations = (expl,)

    # the solver's before/after accounting rides the round-end bundle
    _defer_solver_objectives(closer, info, _apply_objectives)
    return record
