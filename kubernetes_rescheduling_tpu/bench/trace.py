"""Trace replay: streaming communication-matrix updates + online rescheduling.

BASELINE.md config 5 — the scenario the reference cannot express: its
relation graph is a hardcoded constant (reference main.py:31-52,
communicationcost.py:69-88), so traffic shifts (canary rollouts, diurnal
load) are invisible to CAR. Here the comm graph is data: edge weights stream
in over time, the same compiled solver re-runs per step (static shapes — no
retrace), and the replay records how placement tracks the moving objective.

Ships a Bookinfo-style topology (productpage → details/reviews, reviews →
ratings, three review versions) and a canary trace that shifts traffic
v1 → v2 → v3, the classic Istio demo traffic pattern.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from pathlib import Path

import jax
import numpy as np

from kubernetes_rescheduling_tpu.core.state import ClusterState, CommGraph
from kubernetes_rescheduling_tpu.core.workmodel import ServiceSpec, Workmodel
from kubernetes_rescheduling_tpu.objectives.metrics import communication_cost, load_std
from kubernetes_rescheduling_tpu.solver.global_solver import GlobalSolverConfig
from kubernetes_rescheduling_tpu.telemetry.accounting import instrument_jit
from kubernetes_rescheduling_tpu.telemetry.spans import span


@dataclass(frozen=True)
class TraceStep:
    """One streaming update: new weights for a set of service pairs."""

    t: float
    weights: dict[tuple[str, str], float] = field(default_factory=dict)


def with_weights(
    graph: CommGraph,
    updates: dict[tuple[str, str], float],
    *,
    registry=None,
    logger=None,
) -> CommGraph:
    """New CommGraph with the given symmetric edge weights applied.

    Updates naming a service the graph does not know are DROPPED — but
    never silently: each is counted (``trace_unknown_refs_total``) and
    the batch logs one structured ``swallowed_ref`` event, so a
    malformed trace reads as a visible stream of swallowed updates
    instead of an inexplicably static replay."""
    adj = np.asarray(graph.adj).copy()
    index = {n: i for i, n in enumerate(graph.names)}
    swallowed: list[tuple[str, str]] = []
    for (a, b), w in updates.items():
        if a not in index or b not in index:
            swallowed.append((a, b))
            continue
        i, j = index[a], index[b]
        adj[i, j] = w
        adj[j, i] = w
    if swallowed:
        from kubernetes_rescheduling_tpu.telemetry.registry import get_registry
        from kubernetes_rescheduling_tpu.utils.logging import get_logger

        reg = registry if registry is not None else get_registry()
        reg.counter(
            "trace_unknown_refs_total",
            "streaming-trace weight updates dropped because a service "
            "name is not in the comm graph (a malformed trace stays "
            "visible, never a silent no-op)",
        ).inc(len(swallowed))
        (logger if logger is not None else get_logger("trace")).warn(
            "swallowed_ref",
            dropped=len(swallowed),
            refs=[f"{a}~{b}" for a, b in swallowed[:8]],
        )
    import jax.numpy as jnp

    return graph.replace(adj=jnp.asarray(adj))


def bookinfo_workmodel(replicas: int = 1) -> Workmodel:
    """Istio Bookinfo: productpage → details + reviews-v{1,2,3};
    reviews-v{2,3} → ratings."""
    return Workmodel(
        services=(
            ServiceSpec(
                name="productpage",
                callees=("details", "reviews-v1", "reviews-v2", "reviews-v3"),
                replicas=replicas,
            ),
            ServiceSpec(name="details", replicas=replicas),
            ServiceSpec(name="reviews-v1", replicas=replicas),
            ServiceSpec(name="reviews-v2", callees=("ratings",), replicas=replicas),
            ServiceSpec(name="reviews-v3", callees=("ratings",), replicas=replicas),
            ServiceSpec(name="ratings", replicas=replicas),
        ),
        source="builtin:bookinfo",
    )


def canary_trace(steps: int = 12) -> list[TraceStep]:
    """Traffic shifting v1 → v2 → v3: the productpage→reviews edge weights
    move in thirds over the trace, and each reviews→ratings edge carries its
    version's share."""
    out: list[TraceStep] = []
    for k in range(steps):
        frac = k / max(steps - 1, 1)
        v1 = max(0.0, 1.0 - 2 * frac)
        v3 = max(0.0, 2 * frac - 1.0)
        v2 = 1.0 - v1 - v3
        out.append(
            TraceStep(
                t=float(k),
                weights={
                    ("productpage", "reviews-v1"): v1,
                    ("productpage", "reviews-v2"): v2,
                    ("productpage", "reviews-v3"): v3,
                    ("reviews-v2", "ratings"): v2,
                    ("reviews-v3", "ratings"): v3,
                },
            )
        )
    return out


def load_trace(path: str | Path) -> list[TraceStep]:
    """Parse an EXTERNAL trace stream: JSONL, one step per line::

        {"t": 1.0, "weights": [["productpage", "reviews-v2", 0.9], ...]}

    ``weights`` entries are ``[service_a, service_b, weight]`` (symmetric
    pairs — JSON objects cannot key on tuples). Missing ``t`` defaults to
    the line index. This is how measured traffic from an external system
    (a mesh telemetry export, a replayed incident) drives the online
    resolver — BASELINE config 5 as a usable input, not a builtin demo."""
    steps: list[TraceStep] = []
    for line in Path(path).read_text().splitlines():
        line = line.strip()
        if not line:
            continue
        d = json.loads(line)
        steps.append(
            TraceStep(
                t=float(d.get("t", len(steps))),
                weights={
                    (str(a), str(b)): float(w)
                    for a, b, w in d.get("weights", [])
                },
            )
        )
    return steps


def observed_step(t: float, loadgen, samples) -> TraceStep:
    """A :class:`TraceStep` whose weights are the load generator's OBSERVED
    per-pair traffic (``LoadGenerator.observed_weights``) — streaming
    measured traffic into :func:`replay` instead of hand-written weight
    schedules closes the loop between what the request stream does and
    what the solver optimizes (reference README.md:47)."""
    return TraceStep(
        t=t,
        weights=loadgen.observed_weights(samples.edge_counts, samples.sent),
    )


@dataclass
class ReplayRecord:
    t: float
    cost_before_solve: float  # under the NEW weights, old placement
    cost_after_solve: float
    load_std_before: float
    load_std_after: float
    moves: int


def replay(
    state: ClusterState,
    graph: CommGraph,
    trace: list[TraceStep],
    *,
    key: jax.Array,
    config: GlobalSolverConfig = GlobalSolverConfig(sweeps=4),
    restarts: int = 1,
) -> tuple[ClusterState, list[ReplayRecord]]:
    """Online rescheduling over a streaming trace.

    Every step reuses the same compiled solver (weights are data, shapes are
    static), so per-step latency is one device round, not a recompile.
    ``restarts > 1`` runs each step as a best-of-N solve over the device
    mesh (``parallel.solve_with_restarts``).
    """
    from kubernetes_rescheduling_tpu.parallel.sharded import solve_with_restarts

    # a typo'd service name would otherwise replay as a silent no-op
    # (with_weights skips unknown pairs): say so once, up front
    known = set(graph.names)
    unknown = sorted(
        {n for step in trace for pair in step.weights for n in pair} - known
    )
    if unknown:
        import warnings

        warnings.warn(
            f"trace weights reference services not in the workmodel "
            f"(ignored): {unknown[:10]}{'…' if len(unknown) > 10 else ''}",
            stacklevel=2,
        )

    records: list[ReplayRecord] = []
    for step in trace:
        graph = with_weights(graph, step.weights)
        before = float(communication_cost(state, graph))
        key, sub = jax.random.split(key)
        # solve_with_restarts degrades to the plain single solve at
        # n_restarts<=1 — one dispatch path, same key derivation as the
        # controller's global rounds
        with span("trace/step", t=step.t):
            new_state, _ = solve_with_restarts(
                state, graph, sub, n_restarts=restarts, config=config
            )
        after = float(communication_cost(new_state, graph))
        moves = int(
            np.sum(
                np.asarray(state.pod_valid)
                & (np.asarray(state.pod_node) != np.asarray(new_state.pod_node))
            )
        )
        records.append(
            ReplayRecord(
                t=step.t,
                cost_before_solve=before,
                cost_after_solve=after,
                load_std_before=float(load_std(state)),
                load_std_after=float(load_std(new_state)),
                moves=moves,
            )
        )
        state = new_state
    return state, records


def drift_multipliers(
    graph: CommGraph, steps: int, *, sigma: float = 0.5, seed: int = 0
):
    """Synthetic traffic drift at scale: per-step lognormal multipliers for
    every declared pair. Returns ``(ii, jj, mults[steps, E])`` — the raw
    material for :func:`replay_on_device`. Mean-one multipliers keep total
    traffic stationary while individual edges heat and cool, the regime
    where a placement tuned to last step's weights goes stale."""
    adj = np.asarray(graph.adj)
    ii, jj = np.nonzero(np.triu(adj, k=1))
    rng = np.random.default_rng(seed)
    mults = np.exp(
        rng.normal(-0.5 * sigma * sigma, sigma, size=(steps, len(ii)))
    ).astype(np.float32)
    return ii.astype(np.int32), jj.astype(np.int32), mults


def _replay_run(st0, graph, ii, jj, mults, key0, config):
    from kubernetes_rescheduling_tpu.solver.global_solver import global_assign

    base_adj = graph.adj

    def step(st, xs):
        m, k = xs
        w = base_adj[ii, jj] * m
        adj_t = base_adj.at[ii, jj].set(w).at[jj, ii].set(w)
        g = graph.replace(adj=adj_t)
        st_n, inf = global_assign(st, g, k, config)
        # the solve's own incoming-placement evaluation under the NEW
        # weights — the same record the sparse replay emits, so dense/
        # sparse tracking numbers stay comparable (both include the
        # configured balance/overload terms)
        return st_n, (inf["objective_after"], inf["objective_before"])

    keys = jax.random.split(key0, mults.shape[0])
    st_f, (objs, befores) = jax.lax.scan(step, st0, (mults, keys))
    return st_f, objs, befores


# module-level jit: repeated calls with the same shapes hit the cache —
# a per-call closure would retrace the whole k-step scan every call, and
# the benchmark's timed reps would silently include full recompiles.
# instrument_jit makes that guarantee OBSERVABLE: a second
# jax_traces_total{fn="replay_run"} increment in a steady-shape run means
# the timings silently include a recompile
_replay_run_jit = instrument_jit(
    _replay_run, name="replay_run", static_argnames=("config",)
)


def drift_multipliers_sparse(
    sgraph, steps: int, *, sigma: float = 0.5, seed: int = 0
):
    """Sparse twin of :func:`drift_multipliers`: per-step mean-one
    lognormal multipliers for every undirected edge of a
    ``SparseCommGraph``, plus the trace-reordered graph and its
    canonical :class:`TraceLocator` (``reorder_for_trace`` — the
    per-step COO update then needs no scatter). Works at scales where
    the dense adjacency cannot exist (50k services). Returns
    ``(sgraph_reordered, locator, mults)``; replay with the REORDERED
    graph."""
    from kubernetes_rescheduling_tpu.core.sparsegraph import reorder_for_trace

    sg2, loc = reorder_for_trace(sgraph)
    rng = np.random.default_rng(seed)
    mults = np.exp(
        rng.normal(-0.5 * sigma * sigma, sigma, size=(steps, loc.num_edges))
    ).astype(np.float32)
    return sg2, loc, mults


def _replay_sparse_run(st0, sgraph, loc, mults, key0, config):
    from kubernetes_rescheduling_tpu.core.sparsegraph import with_edge_weights
    from kubernetes_rescheduling_tpu.solver.sparse_solver import (
        _global_assign_sparse,
    )

    def step(st, xs):
        m, k = xs
        # static structure + dynamic weights: the per-step update is one
        # small strip scatter + a COO concat — no dense [S, S] rebuild
        # (the dense path's measured ~9 ms/step streaming premium at 10k)
        sg_t = with_edge_weights(sgraph, loc, loc.base_w * m)
        st_n, inf = _global_assign_sparse(st, sg_t, k, config)
        # the solve itself evaluates the incoming placement under the NEW
        # weights (its adopt gate's reference point) — reuse it as the
        # tracking record instead of paying a second full pod-comm pass
        # per step (tens of ms at 50k)
        return st_n, (inf["objective_after"], inf["objective_before"])

    keys = jax.random.split(key0, mults.shape[0])
    st_f, (objs, befores) = jax.lax.scan(step, st0, (mults, keys))
    return st_f, objs, befores


_replay_sparse_jit = instrument_jit(
    _replay_sparse_run, name="replay_sparse_run", static_argnames=("config",)
)


def replay_on_device_sparse(
    state: ClusterState,
    sgraph,
    loc,
    mults,
    key: jax.Array,
    config: GlobalSolverConfig = GlobalSolverConfig(),
):
    """Sparse-solver streaming replay: ALL steps inside one compiled
    ``lax.scan``; per step the undirected-edge weights are scattered into
    the block-local strips and COO list through the static
    :class:`TraceLocator` and the SAME compiled sparse solve consumes the
    previous step's placement. Requires a multi-block graph (the
    single-block case belongs to the dense replay). Returns
    ``(final_state, objs[steps], costs_before[steps])``."""
    import jax.numpy as jnp

    if sgraph.num_blocks <= 1:
        raise ValueError(
            "single-block sparse graphs delegate to the dense solver — "
            "use replay_on_device with the dense graph instead"
        )
    return _replay_sparse_jit(
        state, sgraph, loc, jnp.asarray(mults), key, config
    )


def replay_on_device(
    state: ClusterState,
    graph: CommGraph,
    ii,
    jj,
    mults,
    key: jax.Array,
    config: GlobalSolverConfig = GlobalSolverConfig(),
):
    """The streaming-trace benchmark path: ALL steps run inside one jitted
    ``lax.scan`` on the device — per step, the edge weights are updated by
    that step's multipliers (a scatter into the base adjacency; weights
    are data, shapes are static, so the solver never retraces) and the
    same compiled solve consumes the previous step's placement.

    This is BASELINE.md config 5 at full scale: the reference cannot
    express it at all (its relation graph is a hardcoded constant), and a
    host-side replay loop would pay a tunnel round trip per step. Returns
    ``(final_state, objs[steps], costs_before[steps])`` — the tracking
    record: cost under each step's NEW weights before and after its solve.
    """
    import jax.numpy as jnp

    return _replay_run_jit(
        state,
        graph,
        jnp.asarray(ii),
        jnp.asarray(jj),
        jnp.asarray(mults),
        key,
        config,
    )
