"""The controller's resilient boundary: retry wrapper + circuit breaker.

``run_controller`` never touches ``backend.monitor()`` /
``backend.apply_move()`` directly (statically enforced by
``scripts/check_boundary_retry.py``); every boundary call routes through a
:class:`BoundaryClient`, which

- retries transient failures under a :class:`~utils.retry.RetryPolicy`
  (backoff sleeps go through the BACKEND's own ``advance`` by default, so
  a simulated cluster waits on the simulated clock and a live one really
  sleeps);
- converts exhausted calls into the protocol's failure signals
  (``monitor() -> None`` / ``apply_move() -> None``) instead of crashing
  the loop;
- feeds every outcome to a :class:`CircuitBreaker`, the controller's
  degradation state machine.

Transient means transient: connection/timeout/OS errors (which include
the chaos backend's injected :class:`ChaosError` /
:class:`ChaosTimeoutError`) and API exceptions carrying a throttling or
server-side ``status`` (429/5xx — the kubernetes client's
``ApiException`` shape) are absorbed. A ``TypeError`` — or any other
programming error — still crashes, as it must.

Breaker states (the classic three):

- **closed** — healthy; every success resets the consecutive-failure count.
- **open** — ``max_consecutive_failures`` boundary failures in a row; the
  controller freezes moves and reuses its last good snapshot for
  ``cooldown_rounds`` rounds (the skipped rounds are counted, never
  silently lost).
- **half_open** — cooldown elapsed; ONE probe ``monitor()`` is allowed.
  Success closes the breaker, failure re-opens it (fresh cooldown).

Transitions are triple-recorded: a structured ``breaker`` event, a
``circuit_breaker_transitions_total{to=...}`` counter, and the
``circuit_breaker_state`` gauge (0=closed, 1=half_open, 2=open).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Callable

from kubernetes_rescheduling_tpu.backends.base import Backend, MoveRequest
from kubernetes_rescheduling_tpu.telemetry.registry import (
    MetricsRegistry,
    get_registry,
)
from kubernetes_rescheduling_tpu.utils.logging import StructuredLogger
from kubernetes_rescheduling_tpu.utils.retry import (
    RetryPolicy,
    call_with_retry,
    is_transient,
)

# What the boundary absorbs is utils.retry.is_transient — one shared
# predicate with the k8s adapter. ChaosError subclasses ConnectionError
# and ChaosTimeoutError subclasses TimeoutError, so injected faults need
# no special-casing; everything non-transient propagates.

CLOSED, HALF_OPEN, OPEN = "closed", "half_open", "open"
_STATE_CODE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


@dataclass
class CircuitBreaker:
    """Consecutive-failure breaker with a cooldown-then-probe reopen path.

    ``max_consecutive_failures=0`` disables the machine entirely (the
    breaker never leaves ``closed``) — the loop keeps the reference's
    skip-the-round behavior with retries only.
    """

    max_consecutive_failures: int = 5
    cooldown_rounds: int = 2
    logger: StructuredLogger | None = None
    registry: MetricsRegistry | None = None
    # observer hook (the live ops plane): called AFTER a transition is
    # recorded/counted/logged, with the transition record — the flight
    # recorder dumps its bundle from here on close→open
    on_transition: Callable[[dict], None] | None = None

    state: str = CLOSED
    consecutive_failures: int = 0
    opened_at_round: int = 0
    round: int = 0
    transitions: list[dict] = field(default_factory=list)

    def _reg(self) -> MetricsRegistry:
        return self.registry if self.registry is not None else get_registry()

    def _transition(self, to: str, **fields: Any) -> None:
        if to == self.state:
            return
        rec = {"round": self.round, "from": self.state, "to": to, **fields}
        self.transitions.append(rec)
        self.state = to
        reg = self._reg()
        reg.counter(
            "circuit_breaker_transitions_total",
            "circuit breaker state transitions",
            labelnames=("to",),
        ).labels(to=to).inc()
        reg.gauge(
            "circuit_breaker_state",
            "breaker state (0=closed, 1=half_open, 2=open)",
        ).set(_STATE_CODE[to])
        if self.logger is not None:
            self.logger.info("breaker", **rec)
        if self.on_transition is not None:
            self.on_transition(rec)

    @property
    def enabled(self) -> bool:
        return self.max_consecutive_failures > 0

    def on_round_start(self, rnd: int) -> str:
        """Advance the per-round clock; OPEN moves to HALF_OPEN once the
        cooldown has elapsed. Returns the state the round runs under."""
        self.round = rnd
        if (
            self.state == OPEN
            and rnd - self.opened_at_round >= self.cooldown_rounds
        ):
            self._transition(HALF_OPEN)
        return self.state

    def record_success(self) -> None:
        self.consecutive_failures = 0
        if self.state in (HALF_OPEN, OPEN):
            # OPEN normally sees no calls (the controller skips the round),
            # but the startup probe loop can succeed while OPEN — a real
            # success is stronger evidence than a half-open probe, so the
            # breaker must not stay open over a healthy backend
            self._transition(CLOSED)

    def record_failure(self) -> None:
        self.consecutive_failures += 1
        if self.state == HALF_OPEN or (
            self.enabled
            and self.state == CLOSED
            and self.consecutive_failures >= self.max_consecutive_failures
        ):
            self.opened_at_round = self.round
            self._transition(
                OPEN, consecutive_failures=self.consecutive_failures
            )


class BoundaryClient:
    """The controller's only path to the cluster.

    ``monitor()`` returns ``None`` instead of raising once retries are
    exhausted; ``apply_move()`` likewise (the protocol's existing skip
    signal). A ``None`` return counts as a failure BY DESIGN even though
    the protocol cannot distinguish a transient loss from a deterministic
    rejection: a backend that persistently rejects every move is sick from
    the controller's perspective, and the breaker's cooldown + half-open
    probe (a monitor, which succeeds on such a backend) recovers cheaply
    from the false-positive case. A per-round failure budget freezes
    further MOVES for the round once crossed — monitors stay allowed
    (they are how the breaker's probe and the loop's snapshot recovery
    work).
    """

    def __init__(
        self,
        backend: Backend,
        *,
        policy: RetryPolicy | None = None,
        breaker: CircuitBreaker | None = None,
        failure_budget_per_round: int = 0,
        logger: StructuredLogger | None = None,
        registry: MetricsRegistry | None = None,
        sleeper: Callable[[float], None] | None = None,
        tenant: str | None = None,
    ):
        self.backend = backend
        # fleet mode: which tenant this boundary fronts — part of the
        # solver-cache key (see solver_cache) so multiplexed tenants
        # sharing host plumbing neither cross-pollinate nor thrash
        self.tenant = tenant
        self.policy = (policy or RetryPolicy()).validate()
        # every boundary call treats a None return as transient (the
        # protocol's "failed, skip" signal) — precomputed once
        self._policy_retry_none = dataclasses.replace(
            self.policy, retry_none=True
        )
        self.breaker = breaker or CircuitBreaker(registry=registry, logger=logger)
        self.failure_budget_per_round = failure_budget_per_round
        self.logger = logger
        self.registry = registry
        # backoff waits on the backend's own clock: simulated time for the
        # simulator, ``time.sleep`` (via K8sBackend.sleeper) for a cluster
        self.sleeper = sleeper if sleeper is not None else backend.advance
        self.round_failures = 0
        self.total_failures = 0

    # ---- per-round bookkeeping ----

    def begin_round(self, rnd: int) -> str:
        self.round_failures = 0
        return self.breaker.on_round_start(rnd)

    @property
    def moves_frozen(self) -> bool:
        """Moves stop for the round when the breaker is open or the round
        has spent its failure budget."""
        return self.breaker.state == OPEN or (
            self.failure_budget_per_round > 0
            and self.round_failures >= self.failure_budget_per_round
        )

    def _failed(self, call: str, exc: BaseException | None) -> None:
        self.round_failures += 1
        self.total_failures += 1
        self.breaker.record_failure()
        if self.logger is not None:
            self.logger.warn(
                "boundary_failure",
                call=call,
                error=repr(exc) if exc is not None else "returned None",
                breaker=self.breaker.state,
                consecutive=self.breaker.consecutive_failures,
            )

    def _call(self, call: str, fn: Callable[[], Any]):
        try:
            out = call_with_retry(
                fn,
                policy=self._policy_retry_none,
                label=call,
                retryable=is_transient,
                sleeper=self.sleeper,
                registry=self.registry,
            )
        except Exception as e:  # noqa: BLE001 — non-transient re-raises
            if not is_transient(e):
                raise
            self._failed(call, e)
            return None
        if out is None:
            self._failed(call, None)
            return None
        self.breaker.record_success()
        return out

    # ---- boundary surface ----

    def monitor(self):
        return self._call("monitor", self.backend.monitor)

    def admission_reject(self, reason: str) -> None:
        """An admission-guard rejection (``bench/admission.py``): the
        monitor call SUCCEEDED at the transport level but its payload was
        unusable — duplicate pods, unknown node references, a
        mostly-garbage metrics wave. Charged through ``_failed`` so the
        PR-2 machinery takes over: the round's failure budget burns, the
        failure is logged/counted, and the caller treats the snapshot as
        the protocol's existing ``None`` signal (degraded round on the
        last good snapshot). Note the transport success that delivered
        the garbage already reset the breaker's consecutive count — a
        backend that is reachable but lying reads as degraded service
        (counted degraded rounds), not as dead (open breaker), which is
        the honest verdict."""
        self._failed(f"admission:{reason}", None)

    def apply_move(self, move: MoveRequest) -> str | None:
        if self.moves_frozen:
            return None  # safe mode: the round's remaining moves are dropped
        return self._call("apply_move", lambda: self.backend.apply_move(move))

    def comm_graph(self):
        return self.backend.comm_graph()

    @property
    def raw_backend(self):
        """The innermost backend (unwrapping chaos layers): the host for
        per-backend caches that must outlive this run's wrappers."""
        b = self.backend
        while hasattr(b, "inner"):
            b = b.inner
        return b

    def solver_cache(self, name: str) -> dict:
        """A named, TENANT-AWARE mutable cache slot on the raw backend.

        The controller's per-round solver caches (sparse graph, pod
        graph) historically hung as single attributes on the raw backend
        — one slot per backend instance. Under fleet multiplexing that
        key is wrong twice over: two tenants routed over shared host
        plumbing would cross-pollinate one slot, and alternating tenants
        would evict each other every round, silently rebuilding a
        per-round cost the cache exists to remove. The slot is therefore
        keyed ``(name, tenant)`` on the raw backend (still surviving this
        run's chaos wrappers, the PR-2 contract); callers own the dict's
        contents and their own invalidation rule."""
        host = self.raw_backend
        caches = getattr(host, "_solver_caches", None)
        if caches is None:
            caches = {}
            host._solver_caches = caches
        return caches.setdefault((name, self.tenant), {})

    def evict_solver_caches(self, *, reason: str = "teardown") -> int:
        """Drop EVERY solver-cache slot keyed to this boundary's tenant.

        The slots deliberately outlive a run (the PR-6 reuse contract),
        which is also how they leak: a fleet tenant whose graph a churn
        wave just rewrote — or whose backend is being torn down — leaves
        its ``(name, tenant)`` slots holding the OLD graph's derived
        values (a SparseCommGraph is tens of MB at bench scale), and a
        long deploy-waves soak accretes one stale generation per churned
        tenant with nothing ever reclaiming them. Eviction is counted
        (``solver_cache_evictions_total{reason}``) so soaks can alert on
        an eviction rate that implies cache-defeating churn. Returns the
        number of slots dropped."""
        host = self.raw_backend
        caches = getattr(host, "_solver_caches", None)
        if not caches:
            return 0
        doomed = [k for k in caches if k[1] == self.tenant]
        for k in doomed:
            del caches[k]
        if doomed and self.registry is not None:
            self.registry.counter(
                "solver_cache_evictions_total",
                "tenant solver-cache slots dropped (churn rewrote the "
                "tenant's graph, or the tenant was torn down) — stale "
                "derived graphs must not accrete across a long soak",
                labelnames=("reason",),
            ).labels(reason=reason).inc(len(doomed))
        return len(doomed)

    def advance(self, seconds: float) -> None:
        self.backend.advance(seconds)

    def __getattr__(self, name: str) -> Any:
        # sim-only extensions (apply_pod_moves, restore_placement, events,
        # …) pass through un-wrapped; per-round caches the round functions
        # hang on the boundary live on the wrapper itself (plain setattr)
        return getattr(self.backend, name)
