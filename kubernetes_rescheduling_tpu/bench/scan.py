"""Device-resident round scan: K controller rounds per dispatch.

RESULTS.md's 50k fixed-cost hunt found the honest wall is op-dispatch
glue plus the tunnel RTT — costs the pipelined loop (PR 9) can only
HIDE, because it still pays one Python round trip per round. For the
overwhelmingly common steady-state round (no churn, no breaker event,
no checkpoint due, a noise-free hermetic simulator) nothing in that trip
needs the host: the decide kernel, the simulator's round update
(``backends.sim_device`` — pure array math), and the round-end metrics
are all jittable. This module fuses them:

- :func:`scan_rounds` — ONE compiled ``lax.scan`` over K rounds of
  decide → apply-to-sim-state → monitor → round-end metrics
  (``instrument_jit`` label ``scan_rounds``; the usual steady-state
  invariant applies: ``jax_traces_total{fn="scan_rounds"} == 1`` plus
  counted bucket promotions). Per-round keys derive in-trace exactly as
  the sequential loop derives them (``split(fold_in(key, round))[1]``),
  so the scanned decisions are bit-identical by construction.
- :func:`fleet_scan_rounds` — the fleet composition: the same body with
  the decide/apply/metrics stages vmapped over the leading tenant axis
  (``solver.fleet``'s kernels), so ONE scan dispatch advances every
  tenant K rounds.
- The whole block's diagnostics — decisions, landings, hazard masks,
  optional explain bundles, and the per-round metrics vectors — come
  home as ONE flat f32 bundle pulled through :func:`pull_block`, the
  module's designated transfer site (``site="round_end"``, statically
  enforced by ``scripts/check_apply_boundary.py``): exactly one counted
  ``round_end`` transfer per K rounds.

The host half (:func:`decode_block` / :func:`decode_fleet_block`)
slices the bundle back into per-round views the controller replays into
ordinary ``RoundRecord``s — rounds.jsonl, explain, attribution, and the
watchdog see per-round data indistinguishable from the sequential
loop's (bit-identity test-pinned in tests/test_scan.py).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from kubernetes_rescheduling_tpu.backends.sim_device import apply_decision
from kubernetes_rescheduling_tpu.bench.round_end import (
    METRIC_COST,
    METRIC_HEAD,
    METRIC_LOAD_STD,
    ROUND_END_SITE,
    round_end_metrics,
)
from kubernetes_rescheduling_tpu.solver.fleet import (
    ROW_MOST,
    ROW_SERVICE,
    ROW_TARGET,
    ROW_VICTIM,
    _fleet_decide,
    _fleet_metrics,
)
from kubernetes_rescheduling_tpu.solver.round_loop import (
    decide,
    decide_explain,
)
from kubernetes_rescheduling_tpu.telemetry import instrument_jit, pull
from kubernetes_rescheduling_tpu.telemetry.fleet_rollup import (
    rollup_matrix,
    rollup_size,
)
from kubernetes_rescheduling_tpu.telemetry.tripwire import (
    fleet_tripwire_step,
    tripwire_init,
    tripwire_step,
)

# columns of the per-round decision row inside the block bundle
DEC_MOST, DEC_VICTIM, DEC_SERVICE, DEC_TARGET, DEC_LANDED = range(5)
DEC_COLS = 5


def _round_key(key: jax.Array, rnd: jax.Array) -> jax.Array:
    """The sequential loop's per-round decide key, derived in-trace:
    ``execute_round`` folds the round index into the run key and
    ``_greedy_round`` splits once per move — with ``moves_per_round=1``
    the decide key is exactly ``split(fold_in(key, round))[1]`` (the
    fleet loop's ``_round_keys`` derivation, one definition away)."""
    return jax.random.split(jax.random.fold_in(key, rnd))[1]


def _scan_rounds(
    state,
    dec_graph,
    metric_graph,
    policy_id,
    threshold,
    key,
    start_round,
    edges=None,
    trip_cfg=None,
    *,
    rounds: int,
    pinned: bool,
    explain_k: int,
    attr_k: int,
    tripwire: bool = False,
):
    """The fused K-round body (see module docstring). Returns ONE flat
    f32 vector: per-round decision rows, hazard masks, optional explain
    bundles, and round-end metrics vectors, concatenated in that order
    (each piece stacked rounds-leading) — the single-transfer layout
    :func:`decode_block` unpacks. With ``tripwire`` (static) the carry
    grows the in-block tripwire state (``telemetry.tripwire``): each
    round's post-apply health bits are judged in-trace against the
    block-start baselines riding the carry; once a rule trips, the latch
    masks every later round's decide outputs to the apply's ``-1`` no-op
    sentinel — the remaining rounds are identity rounds — and the
    per-round bits plus the final (trip round, trip mask) append to the
    SAME bundle (``split_tripwire`` strips them; the transfer count is
    unchanged). Tripwire off is the pre-tripwire program verbatim."""

    def body(carry, rnd):
        if tripwire:
            st, trip = carry
        else:
            st = carry
        sub = _round_key(key, rnd)
        if explain_k > 0:
            most, hazard, victim, svc, target, bundle = decide_explain(
                st, dec_graph, policy_id, threshold, sub, top_k=explain_k
            )
        else:
            most, hazard, victim, svc, target = decide(
                st, dec_graph, policy_id, threshold, sub
            )
            bundle = None
        if tripwire:
            # latched ⇒ identity round: -1 victim/target is the apply's
            # no-op sentinel (where(False, ...) is value-exact, so a
            # trip-free block's outputs match tripwire-off bit for bit)
            latched = trip[0]
            most = jnp.where(latched, -1, most)
            victim = jnp.where(latched, -1, victim)
            target = jnp.where(latched, -1, target)
            hazard = jnp.where(latched, False, hazard)
        new_st, landed, _moved = apply_decision(
            st, victim, svc, target, hazard, pinned=pinned
        )
        metrics = round_end_metrics(
            new_st, metric_graph, top_k=attr_k, edges=edges
        )
        row = jnp.stack(
            [most, victim, svc, target, landed]
        ).astype(jnp.float32)
        outs = (row, hazard.astype(jnp.float32), metrics)
        if bundle is not None:
            outs = outs + (bundle,)
        if tripwire:
            trip, bits = tripwire_step(
                trip,
                new_st,
                metrics[METRIC_COST],
                metrics[METRIC_LOAD_STD],
                most,
                trip_cfg,
            )
            return (new_st, trip), outs + (bits.astype(jnp.float32),)
        return new_st, outs

    rnds = start_round + jnp.arange(rounds, dtype=jnp.int32)
    if tripwire:
        # block-start baselines (head-only metrics: no attribution work)
        base = round_end_metrics(state, metric_graph, top_k=0, edges=edges)
        carry0 = (
            state,
            tripwire_init(base[METRIC_COST], base[METRIC_LOAD_STD]),
        )
        final, outs = lax.scan(body, carry0, rnds)
        *core, bits = outs
        if explain_k > 0:
            rows, hazard, metrics, bundles = core
            pieces = (rows, hazard, bundles, metrics)
        else:
            rows, hazard, metrics = core
            pieces = (rows, hazard, metrics)
        trip = final[1]
        tail = jnp.stack([trip[1], trip[2]]).astype(jnp.float32)
        return jnp.concatenate(
            [jnp.ravel(p) for p in pieces] + [jnp.ravel(bits), tail]
        )
    _final, outs = lax.scan(body, state, rnds)
    if explain_k > 0:
        rows, hazard, metrics, bundles = outs
        pieces = (rows, hazard, bundles, metrics)
    else:
        rows, hazard, metrics = outs
        pieces = (rows, hazard, metrics)
    return jnp.concatenate([jnp.ravel(p) for p in pieces])


# ONE compiled program per (shape, rounds, explain/attr config)
# signature: the whole point of the scan is paying dispatch + transfer
# once per K rounds, so a silent retrace would be the old per-round cost
# in disguise — jax_traces_total{fn="scan_rounds"} == 1 + promotions is
# the test-pinned invariant, exactly like the per-round decision kernels
scan_rounds = instrument_jit(
    _scan_rounds,
    name="scan_rounds",
    static_argnames=("rounds", "pinned", "explain_k", "attr_k", "tripwire"),
)


def _fleet_scan_rounds(
    states,
    graphs,
    policy_id,
    threshold,
    tenant_keys,
    start_round,
    drift=None,
    trip_cfg=None,
    *,
    rounds: int,
    pinned: bool,
    rollup_k: int = 0,
    tripwire: bool = False,
):
    """The fleet composition: one scan advancing every tenant K rounds —
    the solo body with decide (``solver.fleet._fleet_decide``), the sim
    twin's apply, and the metrics pair vmapped over the leading tenant
    axis. Flat layout: decisions ``[K,T,4]``, hazard ``[K,T,N]``,
    landings ``[K,T]``, metrics ``[K,T,2]`` (rounds-leading, raveled in
    that order), then — with ``rollup_k > 0`` — per-round fleet rollups
    ``[K, rollup_size(rollup_k)]`` (``telemetry.fleet_rollup``: the
    device-side tenant observability riding the block's ONE transfer).
    ``drift`` is the host's per-tenant reconcile-drift vector AT BLOCK
    START (f32[T], constant across the block: the replay's reconcile
    runs host-side after this dispatch returns, so a block's rollups
    carry drift at most one block stale — the per-round records stay
    exact); degraded/skipped flags are zero inside a scan by
    construction (anything that degrades or skips drains the block).
    With ``tripwire`` (static) the carry grows PER-TENANT tripwire state
    (``telemetry.tripwire``, vmapped): each tenant latches alone — one
    bad tenant freezes only its own lane — and the bundle grows bits
    ``[K,T]`` plus per-tenant (trip round, trip mask), stripped by
    ``split_fleet_tripwire`` before the ordinary decode."""
    T = tenant_keys.shape[0]
    mask = jnp.ones((T,), dtype=bool)

    def body(carry, rnd):
        if tripwire:
            sts, trip = carry
        else:
            sts = carry
        keys = jax.vmap(lambda k: _round_key(k, rnd))(tenant_keys)
        decisions, hazard = _fleet_decide(
            sts, graphs, policy_id, threshold, keys, mask
        )
        if tripwire:
            # latched tenants run identity rounds: their whole decision
            # row masks to the apply's -1 no-op sentinel
            latched = trip[0]
            decisions = jnp.where(latched[:, None], -1, decisions)
            hazard = jnp.where(latched[:, None], False, hazard)
        new_sts, landed, _moved = jax.vmap(
            lambda s, v, sv, t, h: apply_decision(s, v, sv, t, h, pinned=pinned)
        )(
            sts,
            decisions[:, ROW_VICTIM],
            decisions[:, ROW_SERVICE],
            decisions[:, ROW_TARGET],
            hazard,
        )
        metrics = _fleet_metrics(new_sts, graphs)
        outs = (
            decisions.astype(jnp.float32),
            hazard.astype(jnp.float32),
            landed.astype(jnp.float32),
            metrics,
        )
        if rollup_k > 0:
            flags = jnp.concatenate(
                [
                    jnp.zeros((T, 2), jnp.float32),  # degraded, skipped
                    (
                        jnp.zeros((T,), jnp.float32)
                        if drift is None
                        else drift.astype(jnp.float32)
                    )[:, None],
                ],
                axis=1,
            )
            matrix = jnp.concatenate([metrics, flags], axis=1)
            outs = outs + (rollup_matrix(matrix, top_k=rollup_k),)
        if tripwire:
            trip, bits = fleet_tripwire_step(
                trip, new_sts, metrics, decisions[:, ROW_MOST], trip_cfg
            )
            return (new_sts, trip), outs + (bits.astype(jnp.float32),)
        return new_sts, outs

    rnds = start_round + jnp.arange(rounds, dtype=jnp.int32)
    if tripwire:
        base = _fleet_metrics(states, graphs)  # per-tenant block-start
        carry0 = (states, tripwire_init(base[:, 0], base[:, 1]))
        final, outs = lax.scan(body, carry0, rnds)
        trip = final[1]
        return jnp.concatenate(
            [jnp.ravel(p) for p in outs]
            + [
                trip[1].astype(jnp.float32),
                trip[2].astype(jnp.float32),
            ]
        )
    _final, outs = lax.scan(body, states, rnds)
    return jnp.concatenate([jnp.ravel(p) for p in outs])


fleet_scan_rounds = instrument_jit(
    _fleet_scan_rounds,
    name="fleet_scan_rounds",
    static_argnames=("rounds", "pinned", "rollup_k", "tripwire"),
)


def pull_block(flat_dev, registry=None) -> np.ndarray:
    """THE scan module's designated device→host transfer: one counted
    ``round_end`` pull per scan block — K rounds of diagnostics in one
    crossing (``scripts/check_apply_boundary.py`` statically pins every
    other sync out of this module and the control loops)."""
    return pull(flat_dev, site=ROUND_END_SITE, registry=registry)


@dataclass(frozen=True)
class RoundView:
    """One scanned round, decoded: the sequential loop's per-round
    quantities as plain host scalars/arrays."""

    most: int
    victim: int
    service: int
    target: int
    landed: int
    hazard: np.ndarray            # bool[N]
    cost: float
    load_std: float
    attr_flat: np.ndarray | None  # the flat attribution bundle (attr_k>0)
    explain: np.ndarray | None    # f32[6, explain_k] (explain_k>0)

    @property
    def moved(self) -> bool:
        return self.landed >= 0


def decode_block(
    flat: np.ndarray,
    *,
    rounds: int,
    num_nodes: int,
    explain_k: int,
) -> list[RoundView]:
    """Unpack one pulled block bundle into per-round views. The metrics
    vector's width is derived from the residual length (attribution's
    flat size depends on top_k × topology — the decode must not
    re-implement that formula)."""
    flat = np.asarray(flat, dtype=np.float32)
    # decide_explain clamps its bundle to min(top_k, num_nodes) columns
    # — the decode must apply the same clamp or a cluster smaller than
    # explain_top_k shifts every later slice
    explain_k = min(explain_k, num_nodes)
    n_dec = rounds * DEC_COLS
    n_hz = rounds * num_nodes
    n_ex = rounds * 6 * explain_k
    n_metrics = flat.size - n_dec - n_hz - n_ex
    if n_metrics < rounds * METRIC_HEAD or n_metrics % rounds:
        raise ValueError(
            f"scan block bundle of {flat.size} values does not decode at "
            f"rounds={rounds}, num_nodes={num_nodes}, explain_k={explain_k}"
        )
    h = n_metrics // rounds
    dec = flat[:n_dec].reshape(rounds, DEC_COLS).astype(np.int64)
    hazard = flat[n_dec : n_dec + n_hz].reshape(rounds, num_nodes) > 0.5
    off = n_dec + n_hz
    explain = (
        flat[off : off + n_ex].reshape(rounds, 6, explain_k)
        if explain_k > 0
        else None
    )
    off += n_ex
    metrics = flat[off:].reshape(rounds, h)
    out: list[RoundView] = []
    for r in range(rounds):
        out.append(
            RoundView(
                most=int(dec[r, DEC_MOST]),
                victim=int(dec[r, DEC_VICTIM]),
                service=int(dec[r, DEC_SERVICE]),
                target=int(dec[r, DEC_TARGET]),
                landed=int(dec[r, DEC_LANDED]),
                hazard=hazard[r],
                cost=float(metrics[r, METRIC_COST]),
                load_std=float(metrics[r, METRIC_LOAD_STD]),
                attr_flat=(
                    metrics[r, METRIC_HEAD:] if h > METRIC_HEAD else None
                ),
                explain=explain[r] if explain is not None else None,
            )
        )
    return out


def decode_fleet_block(
    flat: np.ndarray,
    *,
    rounds: int,
    tenants: int,
    num_nodes: int,
    rollup_k: int = 0,
):
    """Unpack one fleet scan bundle: ``(decisions i64[K,T,4],
    hazard bool[K,T,N], landed i64[K,T], metrics f32[K,T,2])`` plus —
    when the block carried rollups (``rollup_k > 0``) — a fifth
    ``f32[K, rollup_size(rollup_k)]`` array of per-round fleet
    rollups (``telemetry.fleet_rollup.decode_rollup`` unpacks each)."""
    flat = np.asarray(flat, dtype=np.float32)
    k, t, n = rounds, tenants, num_nodes
    roll = rollup_size(rollup_k) if rollup_k > 0 else 0
    sizes = (k * t * 4, k * t * n, k * t, k * t * 2, k * roll)
    if flat.size != sum(sizes):
        raise ValueError(
            f"fleet scan bundle of {flat.size} values does not decode at "
            f"rounds={k}, tenants={t}, num_nodes={n}, rollup_k={rollup_k}"
        )
    o1, o2, o3, o4 = np.cumsum(sizes)[:4]
    decisions = flat[:o1].reshape(k, t, 4).astype(np.int64)
    hazard = flat[o1:o2].reshape(k, t, n) > 0.5
    landed = flat[o2:o3].reshape(k, t).astype(np.int64)
    metrics = flat[o3:o4].reshape(k, t, 2)
    if rollup_k <= 0:
        return decisions, hazard, landed, metrics
    rollups = flat[o4:].reshape(k, roll)
    return decisions, hazard, landed, metrics, rollups


# ---- scan-plane accounting (OBSERVABILITY.md "Round scan") ----


def count_scan_block(registry, rounds: int) -> None:
    """One scan dispatch landed: count the block and publish how many
    rounds it advanced per dispatch (the amortization headline)."""
    registry.counter(
        "scan_blocks_total",
        "device-resident scan blocks dispatched (each advances "
        "scan_rounds_per_dispatch rounds in one compiled program)",
    ).inc()
    registry.gauge(
        "scan_rounds_per_dispatch",
        "rounds advanced by the most recent scan-block dispatch",
    ).set(rounds)


def count_scan_drain(registry, reason: str) -> None:
    """A round executed on the per-round path while the scanned schedule
    was configured — the drain discipline's visible half."""
    registry.counter(
        "scan_drains_total",
        "rounds drained from the scanned schedule to the per-round path, "
        "by reason",
        labelnames=("reason",),
    ).labels(reason=reason).inc()
