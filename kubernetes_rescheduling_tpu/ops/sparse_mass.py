"""Neighbor-mass kernels over block-local sparse pair weights.

The dense solver's hot step is ``M = W[chunk rows] @ one_hot(assign)`` — an
MXU matmul with contraction length SP (ops/fused_admission.py,
``fused_neighbor_mass``). With the block-local storage of
``core.sparsegraph`` the contraction shrinks to each block's distinct
neighbor set: for a 256-row block b,

    M_b = w_local[b] @ (one_hot(tgt_b) · rv_u_b)        # [256, U_b] @ [U_b, N]

where ``tgt_b = assign[u_ids[b]]`` and ``rv_u`` carries the neighbor
replica counts (the row-side replica factor is applied by the caller; the
pair weight ``adj·rv_s·rv_t`` factorizes). The one-hot tile is regenerated
in VMEM from ``tgt`` exactly like the dense inline-mass kernel — it never
exists in HBM.

The caller pre-gathers ``tgt`` CHUNK-LOCALLY: XLA's TPU gather runs
element-at-a-time (~12 ns/element measured), so gathering the full
neighbor table per chunk costs more than every matmul combined (0.63 ms
for 52k entries at 10k services — the round-4 ablation that motivated
this layout). Regular blocks have a uniform column width, so a chunk's id
columns are KB contiguous slices of ``u_ids`` (cheap DMA), and only the
resulting few-thousand-entry slab hits the gather path. The kernels
therefore take chunk-local ``tgt``/``rvu`` slabs indexed directly by grid
position; only the (large, weight-carrying) W tiles are gathered by id
via scalar prefetch.

Two kernels, one body:

- ``sparse_neighbor_mass`` — the per-chunk kernel. Grid ``(KB, reg_tiles)``
  over the chunk's (traced) regular block ids; a scalar-prefetched offset
  table locates each block's uniform-width column strip of W.
- ``hub_neighbor_mass`` — the once-per-sweep hub pass. Hub blocks (the few
  degree-sorted leading blocks whose neighbor sets exceed the regular
  width) have *static* ids, so their ragged tile list is flattened at
  build time into (W column-tile, local column-tile, output-block,
  is-first) arrays and the grid walks it 1D with zero wasted steps.

``reference_sparse_mass`` / ``reference_hub_mass`` are the plain-XLA twins
(production path on CPU, parity oracle for the kernels).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from kubernetes_rescheduling_tpu.core.sparsegraph import BLOCK_R
from kubernetes_rescheduling_tpu.ops.fused_admission import score_core


def _mass_body(w_ref, tgt_ref, rvu_ref, m_ref, *, first):
    """Shared accumulate step: one ``[256, BU] @ [BU, N]`` tile."""
    bu = w_ref.shape[1]
    n = m_ref.shape[1]
    tgt = tgt_ref[:].reshape(bu, 1)
    rvu = rvu_ref[:].reshape(bu, 1)
    col = jax.lax.broadcasted_iota(jnp.int32, (bu, n), 1)
    # the one-hot occupancy tile scaled by neighbor replicas, in VMEM only.
    # rv values are small integers — exact in bf16 (≤ 256), and padding
    # columns carry rvu = 0 so they contribute nothing.
    oh = jnp.where(tgt == col, rvu, 0.0).astype(w_ref.dtype)
    acc = jnp.dot(w_ref[:], oh, preferred_element_type=jnp.float32)

    @pl.when(first)
    def _():
        m_ref[:] = acc

    @pl.when(jnp.logical_not(first))
    def _():
        m_ref[:] += acc


def _chunk_kernel(blocks_ref, toff_ref, w_ref, tgt_ref, rvu_ref, m_ref):
    del blocks_ref, toff_ref  # consumed by the index_map
    _mass_body(w_ref, tgt_ref, rvu_ref, m_ref, first=pl.program_id(1) == 0)


def _hub_kernel(
    tcol_ref, tlcol_ref, tout_ref, tfirst_ref, w_ref, tgt_ref, rvu_ref, m_ref
):
    del tcol_ref, tlcol_ref, tout_ref
    first = tfirst_ref[pl.program_id(0)] == 1
    _mass_body(w_ref, tgt_ref, rvu_ref, m_ref, first=first)


@functools.partial(
    jax.jit, static_argnames=("num_nodes", "bu", "reg_tiles", "interpret")
)
def sparse_neighbor_mass(
    w_mm,     # [256, TU] block-local weights in matmul dtype
    tgt_c,    # i32[KB·u_reg] CHUNK-LOCAL assign[u_ids] slab, block-major
    rvu_c,    # f32[KB·u_reg] chunk-local neighbor replicas (0 on padding)
    blocks,   # i32[KB] chunk's block ids (regular or dummy)
    toff,     # i32[NBX] per-block first W column tile (incl. dummy entries)
    *,
    num_nodes: int,
    bu: int,
    reg_tiles: int,
    interpret: bool = False,
):
    """``M[KB·256, N]`` for one chunk of regular-width blocks."""
    KB = blocks.shape[0]
    N = int(num_nodes)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(KB, reg_tiles),
        in_specs=[
            pl.BlockSpec(
                (BLOCK_R, bu), lambda i, j, blocks, toff: (0, toff[blocks[i]] + j)
            ),
            # chunk-local slabs: block slot i's tiles sit at i·reg_tiles + j
            pl.BlockSpec(
                (1, bu), lambda i, j, blocks, toff: (0, i * reg_tiles + j)
            ),
            pl.BlockSpec(
                (1, bu), lambda i, j, blocks, toff: (0, i * reg_tiles + j)
            ),
        ],
        out_specs=pl.BlockSpec((BLOCK_R, N), lambda i, j, blocks, toff: (i, 0)),
    )
    return pl.pallas_call(
        _chunk_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((KB * BLOCK_R, N), jnp.float32),
        interpret=interpret,
    )(
        blocks.astype(jnp.int32),
        toff.astype(jnp.int32),
        w_mm,
        tgt_c.reshape(1, -1).astype(jnp.int32),
        rvu_c.reshape(1, -1).astype(jnp.float32),
    )


@functools.partial(
    jax.jit, static_argnames=("num_nodes", "num_hub_blocks", "bu", "interpret")
)
def hub_neighbor_mass(
    w_mm,        # [256, TU]
    tgt_l,       # i32[W_g] GROUP-LOCAL assign[u_ids] slab (static columns)
    rvu_l,       # f32[W_g]
    tile_col,    # i32[T] static flattened hub tile list: W column tile
    tile_lcol,   # i32[T] group-local column tile (into tgt_l/rvu_l)
    tile_out,    # i32[T] output block slot (0..NHB-1), block-major order
    tile_first,  # i32[T] 1 on each output block's first tile
    *,
    num_nodes: int,
    num_hub_blocks: int,
    bu: int,
    interpret: bool = False,
):
    """``M[NHB·256, N]`` for a (static) group of hub blocks — ragged widths
    walked as a flat 1D tile list, zero wasted grid steps."""
    T = tile_col.shape[0]
    N = int(num_nodes)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(T,),
        in_specs=[
            pl.BlockSpec((BLOCK_R, bu), lambda t, tc, tl, to, tf: (0, tc[t])),
            pl.BlockSpec((1, bu), lambda t, tc, tl, to, tf: (0, tl[t])),
            pl.BlockSpec((1, bu), lambda t, tc, tl, to, tf: (0, tl[t])),
        ],
        out_specs=pl.BlockSpec(
            (BLOCK_R, N), lambda t, tc, tl, to, tf: (to[t], 0)
        ),
    )
    return pl.pallas_call(
        _hub_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(
            (num_hub_blocks * BLOCK_R, N), jnp.float32
        ),
        interpret=interpret,
    )(
        tile_col.astype(jnp.int32),
        tile_lcol.astype(jnp.int32),
        tile_out.astype(jnp.int32),
        tile_first.astype(jnp.int32),
        w_mm,
        tgt_l.reshape(1, -1).astype(jnp.int32),
        rvu_l.reshape(1, -1).astype(jnp.float32),
    )


def _chunk_mass_score_kernel(
    blocks_ref,     # scalar prefetch i32[KB]
    toff_ref,       # scalar prefetch i32[NBX]
    lam_ref,        # SMEM (1, 1) f32
    ow_ref,         # SMEM (1, 1) f32
    temp_ref,       # SMEM (1, 1) f32
    seed_ref,       # SMEM (1, 1) i32
    w_ref,          # VMEM (256, bu) W tile (gathered via index_map)
    tgt_ref,        # VMEM (1, bu) chunk-local assign slab tile
    rvu_ref,        # VMEM (1, bu) chunk-local neighbor-replica tile
    rvrow_ref,      # VMEM (BLOCK_R, 1) f32 row replica factor, block i
    cur_ref,        # VMEM (BLOCK_R, 1) i32
    home_ref,       # VMEM (BLOCK_R, 1) i32
    pen_ref,        # VMEM (BLOCK_R, 1) f32
    c_cpu_ref,      # VMEM (BLOCK_R, 1) f32
    c_mem_ref,      # VMEM (BLOCK_R, 1) f32
    valid_ref,      # VMEM (BLOCK_R, 1) i32
    cpu_load_ref,   # VMEM (1, N) f32
    mem_load_ref,   # VMEM (1, N) f32
    cap_ref,        # VMEM (1, N) f32
    mem_cap_ref,    # VMEM (1, N) f32
    node_valid_ref, # VMEM (1, N) i32
    prop_ref,       # out VMEM (BLOCK_R, 1) i32
    gain_ref,       # out VMEM (BLOCK_R, 1) f32
    wants_ref,      # out VMEM (BLOCK_R, 1) i32
    slack_cpu_ref,  # out VMEM (BLOCK_R, 1) f32
    slack_mem_ref,  # out VMEM (BLOCK_R, 1) f32
    m_scr,          # scratch VMEM (BLOCK_R, N) f32 — the mass accumulator
    *,
    reg_tiles: int,
    enforce_capacity: bool,
    use_noise: bool,
    use_move_pen: bool,
    noise_impl: str,
):
    del blocks_ref, toff_ref  # consumed by the index_map
    # hoisted out of the pl.when bodies: program_id inside a when-region
    # does not survive the cond sub-jaxpr on the interpret lowering
    i = pl.program_id(0)
    j = pl.program_id(1)
    # the same accumulate step as the two-kernel path — bit-parity with
    # sparse_neighbor_mass is structural, not a copy
    _mass_body(w_ref, tgt_ref, rvu_ref, m_scr, first=j == 0)

    @pl.when(j == reg_tiles - 1)
    def _():
        # the block's mass is complete — run the score reductions while
        # M is still in VMEM (it never exists in HBM on this path). Same
        # f32 ops in the same order as the two-kernel path: bit-identical
        # decisions (with noise the seed offset is the block index, same
        # stream law as the standalone score kernel's program_id).
        m = m_scr[:] * rvrow_ref[:]
        prop, gain, wants, slack_cpu, slack_mem = score_core(
            m, cur_ref[:], home_ref[:], pen_ref[:],
            c_cpu_ref[:], c_mem_ref[:], valid_ref[:],
            cpu_load_ref[:], mem_load_ref[:], cap_ref[:], mem_cap_ref[:],
            node_valid_ref[:],
            lam_ref[0, 0], ow_ref[0, 0], temp_ref[0, 0],
            seed_ref[0, 0] + i,
            enforce_capacity=enforce_capacity,
            use_noise=use_noise,
            use_move_pen=use_move_pen,
            noise_impl=noise_impl,
        )
        prop_ref[:] = prop
        gain_ref[:] = gain
        wants_ref[:] = wants
        slack_cpu_ref[:] = slack_cpu
        slack_mem_ref[:] = slack_mem


@functools.partial(
    jax.jit,
    static_argnames=(
        "num_nodes", "bu", "reg_tiles", "enforce_capacity", "use_noise",
        "interpret", "noise_impl",
    ),
)
def sparse_mass_score(
    w_mm,     # [256, TU] block-local weights in matmul dtype
    tgt_c,    # i32[KB·u_reg] chunk-local assign slab, block-major
    rvu_c,    # f32[KB·u_reg] chunk-local neighbor replicas
    blocks,   # i32[KB] chunk's block ids
    toff,     # i32[NBX] per-block first W column tile
    rv_row,   # f32[C] row replica factor (C = KB·256)
    cur,      # i32[C]
    home,     # i32[C] move-cost anchor (pass cur when pricing is off)
    move_pen, # f32[C] | None — None keeps the exact pre-pricing kernel
    c_cpu,    # f32[C]
    c_mem,    # f32[C]
    valid_c,  # bool[C]
    cpu_load, mem_load, cap, mem_cap, node_valid,   # [N] tables
    lam, temp, seed,                                # scalars
    overload_weight=0.0,
    *,
    num_nodes: int,
    bu: int,
    reg_tiles: int,
    enforce_capacity: bool,
    use_noise: bool,
    interpret: bool = False,
    noise_impl: str = "tpu",
):
    """Fused mass+score for one regular chunk: accumulates each block's
    neighbor mass in a VMEM scratch and reduces it to the score stage's
    ``(prop, gain, wants, slack_cpu, slack_mem)`` in the SAME kernel —
    one launch per chunk instead of two, and the [C, N] mass block never
    round-trips HBM. Decisions are bit-identical to
    ``sparse_neighbor_mass`` → ``fused_score_admission``'s score stage
    (shared ``score_core``); feed the outputs to ``admission_stage``."""
    KB = blocks.shape[0]
    C = KB * BLOCK_R
    N = int(num_nodes)
    use_move_pen = move_pen is not None
    if move_pen is None:
        move_pen = jnp.zeros((C,), jnp.float32)

    col_i32 = lambda x: x.reshape(C, 1).astype(jnp.int32)
    col_f32 = lambda x: x.reshape(C, 1).astype(jnp.float32)
    row_f32 = lambda x: x.reshape(1, N).astype(jnp.float32)
    row_i32 = lambda x: x.reshape(1, N).astype(jnp.int32)

    smem = pl.BlockSpec(
        (1, 1), lambda i, j, blocks, toff: (0, 0), memory_space=pltpu.SMEM
    )
    cvec = pl.BlockSpec(
        (BLOCK_R, 1), lambda i, j, blocks, toff: (i, 0),
        memory_space=pltpu.VMEM,
    )
    nvec = pl.BlockSpec(
        (1, N), lambda i, j, blocks, toff: (0, 0), memory_space=pltpu.VMEM
    )
    out_c = jax.ShapeDtypeStruct((C, 1), jnp.float32)
    out_ci = jax.ShapeDtypeStruct((C, 1), jnp.int32)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(KB, reg_tiles),
        in_specs=[
            smem, smem, smem, smem,
            pl.BlockSpec(
                (BLOCK_R, bu),
                lambda i, j, blocks, toff: (0, toff[blocks[i]] + j),
            ),
            pl.BlockSpec(
                (1, bu), lambda i, j, blocks, toff: (0, i * reg_tiles + j)
            ),
            pl.BlockSpec(
                (1, bu), lambda i, j, blocks, toff: (0, i * reg_tiles + j)
            ),
            cvec, cvec, cvec, cvec, cvec, cvec, cvec,
            nvec, nvec, nvec, nvec, nvec,
        ],
        out_specs=[cvec, cvec, cvec, cvec, cvec],
        scratch_shapes=[pltpu.VMEM((BLOCK_R, N), jnp.float32)],
    )
    return pl.pallas_call(
        functools.partial(
            _chunk_mass_score_kernel,
            reg_tiles=reg_tiles,
            enforce_capacity=enforce_capacity,
            use_noise=use_noise,
            use_move_pen=use_move_pen,
            noise_impl=noise_impl,
        ),
        grid_spec=grid_spec,
        out_shape=[out_ci, out_c, out_ci, out_c, out_c],
        interpret=interpret,
    )(
        blocks.astype(jnp.int32),
        toff.astype(jnp.int32),
        jnp.asarray(lam, jnp.float32).reshape(1, 1),
        jnp.asarray(overload_weight, jnp.float32).reshape(1, 1),
        jnp.asarray(temp, jnp.float32).reshape(1, 1),
        jnp.asarray(seed, jnp.int32).reshape(1, 1),
        w_mm,
        tgt_c.reshape(1, -1).astype(jnp.int32),
        rvu_c.reshape(1, -1).astype(jnp.float32),
        col_f32(rv_row),
        col_i32(cur),
        col_i32(home),
        col_f32(move_pen),
        col_f32(c_cpu),
        col_f32(c_mem),
        col_i32(valid_c),
        row_f32(cpu_load),
        row_f32(mem_load),
        row_f32(cap),
        row_f32(mem_cap),
        row_i32(node_valid),
    )


def chunk_local_slabs(u_ids, rvu, starts, width: int):
    """Slice a chunk's neighbor-id and replica columns out of the full
    table as KB contiguous slices (regular blocks share ``width``), ready
    for the small chunk-local gather. Returns ``(u_c[KB·width],
    rvu_c[KB·width])``."""
    u_c = jax.vmap(
        lambda s: lax.dynamic_slice(u_ids, (s,), (width,))
    )(starts)
    rvu_c = jax.vmap(
        lambda s: lax.dynamic_slice(rvu, (s,), (width,))
    )(starts)
    return u_c.reshape(-1), rvu_c.reshape(-1)


def reference_sparse_mass(
    w_mm, tgt_c, rvu_c, blocks, toff, *, num_nodes: int, bu: int,
    reg_tiles: int, col_offset=0,
):
    """Plain-XLA twin of :func:`sparse_neighbor_mass` (gather + matmul —
    no scatter, so it is TPU- and vmap-safe). Term-for-term the same f32
    operation order as the kernel body. ``col_offset`` shifts the node
    columns (the node-sharded solver computes M for its shard's columns:
    ``num_nodes`` = local width, offset = ``shard · Nl``)."""
    U = reg_tiles * bu
    N = int(num_nodes)
    KB = blocks.shape[0]
    tgt_b = tgt_c.reshape(KB, U)
    rvu_b = rvu_c.reshape(KB, U)
    cols = col_offset + jnp.arange(N, dtype=jnp.int32)

    def per_block(b, tgt, rv):
        start = toff[b] * bu
        wb = lax.dynamic_slice(w_mm, (0, start), (BLOCK_R, U))
        oh = jnp.where(
            tgt[:, None] == cols[None, :],
            rv[:, None],
            0.0,
        ).astype(w_mm.dtype)
        return jnp.dot(wb, oh, preferred_element_type=jnp.float32)

    M = jax.vmap(per_block)(blocks, tgt_b, rvu_b)
    return M.reshape(KB * BLOCK_R, N)


def reference_hub_mass(
    sgraph, w_mm, tgt_l, rvu_l, *, num_nodes: int, blocks=None, col_offset=0
):
    """Plain-XLA twin of :func:`hub_neighbor_mass` — hub offsets/widths are
    static, so this is a Python loop over static slices of the group-local
    slab. ``col_offset`` as in :func:`reference_sparse_mass`."""
    N = int(num_nodes)
    cols = col_offset + jnp.arange(N, dtype=jnp.int32)
    outs = []
    lo = 0
    for b in blocks if blocks is not None else sgraph.hub_blocks:
        width = sgraph.block_ntiles[b] * sgraph.bu
        tgt = tgt_l[lo : lo + width]
        rv = rvu_l[lo : lo + width]
        off = sgraph.block_toff[b] * sgraph.bu
        wb = w_mm[:, off : off + width]
        lo += width
        oh = jnp.where(
            tgt[:, None] == cols[None, :],
            rv[:, None],
            0.0,
        ).astype(w_mm.dtype)
        outs.append(jnp.dot(wb, oh, preferred_element_type=jnp.float32))
    return jnp.concatenate(outs, axis=0)


def hub_tile_arrays(sgraph, blocks=None):
    """Flatten hub blocks' ragged tile lists into the static
    (W column-tile, group-local column-tile, output-slot, is-first) arrays
    the 1D hub grid walks, in output-block-major order (accumulation
    revisits each output block consecutively). ``blocks`` selects a subset
    (the solver processes hubs in chunk-sized groups so the admission race
    never exceeds the regular chunk width)."""
    import numpy as np

    cols, lcols, outs, firsts = [], [], [], []
    lcol = 0
    for slot, b in enumerate(blocks if blocks is not None else sgraph.hub_blocks):
        for j in range(sgraph.block_ntiles[b]):
            cols.append(sgraph.block_toff[b] + j)
            lcols.append(lcol)
            outs.append(slot)
            firsts.append(1 if j == 0 else 0)
            lcol += 1
    return (
        jnp.asarray(np.asarray(cols, dtype=np.int32)),
        jnp.asarray(np.asarray(lcols, dtype=np.int32)),
        jnp.asarray(np.asarray(outs, dtype=np.int32)),
        jnp.asarray(np.asarray(firsts, dtype=np.int32)),
    )
