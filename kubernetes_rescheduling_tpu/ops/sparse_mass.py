"""Neighbor-mass kernels over block-local sparse pair weights.

The dense solver's hot step is ``M = W[chunk rows] @ one_hot(assign)`` — an
MXU matmul with contraction length SP (ops/fused_admission.py,
``fused_neighbor_mass``). With the block-local storage of
``core.sparsegraph`` the contraction shrinks to each block's distinct
neighbor set: for a 256-row block b,

    M_b = w_local[b] @ (one_hot(tgt_b) · rv_u_b)        # [256, U_b] @ [U_b, N]

where ``tgt_b = assign[u_ids[b]]`` (pre-gathered in XLA — a few hundred KB
per chunk) and ``rv_u`` carries the neighbor replica counts (the row-side
replica factor is applied by the caller; the pair weight
``adj·rv_s·rv_t`` factorizes). The one-hot tile is regenerated in VMEM
from ``tgt`` exactly like the dense inline-mass kernel — it never exists
in HBM.

Two kernels, one body:

- ``sparse_neighbor_mass`` — the per-chunk kernel. Grid ``(KB, reg_tiles)``
  over the chunk's (traced) regular block ids; a scalar-prefetched offset
  table locates each block's uniform-width column strip. No ragged
  bookkeeping in the hot loop — regular blocks share one width by
  construction.
- ``hub_neighbor_mass`` — the once-per-sweep hub pass. Hub blocks (the few
  degree-sorted leading blocks whose neighbor sets exceed the regular
  width) have *static* ids, so their ragged tile list is flattened at
  build time into (column-tile, output-block, is-first) arrays and the
  grid walks it 1D with zero wasted steps.

``reference_sparse_mass`` / ``reference_hub_mass`` are the plain-XLA twins
(production path on CPU, parity oracle for the kernels).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from kubernetes_rescheduling_tpu.core.sparsegraph import BLOCK_R


def _mass_body(w_ref, tgt_ref, rvu_ref, m_ref, *, first):
    """Shared accumulate step: one ``[256, BU] @ [BU, N]`` tile."""
    bu = w_ref.shape[1]
    n = m_ref.shape[1]
    tgt = tgt_ref[:].reshape(bu, 1)
    rvu = rvu_ref[:].reshape(bu, 1)
    col = jax.lax.broadcasted_iota(jnp.int32, (bu, n), 1)
    # the one-hot occupancy tile scaled by neighbor replicas, in VMEM only.
    # rv values are small integers — exact in bf16 (≤ 256), and padding
    # columns carry rvu = 0 so they contribute nothing.
    oh = jnp.where(tgt == col, rvu, 0.0).astype(w_ref.dtype)
    acc = jnp.dot(w_ref[:], oh, preferred_element_type=jnp.float32)

    @pl.when(first)
    def _():
        m_ref[:] = acc

    @pl.when(jnp.logical_not(first))
    def _():
        m_ref[:] += acc


def _chunk_kernel(blocks_ref, toff_ref, w_ref, tgt_ref, rvu_ref, m_ref):
    del blocks_ref, toff_ref  # consumed by the index_map
    _mass_body(w_ref, tgt_ref, rvu_ref, m_ref, first=pl.program_id(1) == 0)


def _hub_kernel(tcol_ref, tout_ref, tfirst_ref, w_ref, tgt_ref, rvu_ref, m_ref):
    del tcol_ref, tout_ref
    first = tfirst_ref[pl.program_id(0)] == 1
    _mass_body(w_ref, tgt_ref, rvu_ref, m_ref, first=first)


@functools.partial(
    jax.jit, static_argnames=("num_nodes", "bu", "reg_tiles", "interpret")
)
def sparse_neighbor_mass(
    w_mm,     # [256, TU] block-local weights in matmul dtype
    tgt_u,    # i32[TU] assign[u_ids] (pre-gathered, padding → anything)
    rvu,      # f32[TU] replica count per neighbor column (0 on padding)
    blocks,   # i32[KB] chunk's block ids (regular or dummy)
    toff,     # i32[NBX] per-block first column tile (incl. dummy entries)
    *,
    num_nodes: int,
    bu: int,
    reg_tiles: int,
    interpret: bool = False,
):
    """``M[KB·256, N]`` for one chunk of regular-width blocks."""
    TU = w_mm.shape[1]
    KB = blocks.shape[0]
    N = int(num_nodes)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(KB, reg_tiles),
        in_specs=[
            pl.BlockSpec(
                (BLOCK_R, bu), lambda i, j, blocks, toff: (0, toff[blocks[i]] + j)
            ),
            pl.BlockSpec((1, bu), lambda i, j, blocks, toff: (0, toff[blocks[i]] + j)),
            pl.BlockSpec((1, bu), lambda i, j, blocks, toff: (0, toff[blocks[i]] + j)),
        ],
        out_specs=pl.BlockSpec((BLOCK_R, N), lambda i, j, blocks, toff: (i, 0)),
    )
    return pl.pallas_call(
        _chunk_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((KB * BLOCK_R, N), jnp.float32),
        interpret=interpret,
    )(
        blocks.astype(jnp.int32),
        toff.astype(jnp.int32),
        w_mm,
        tgt_u.reshape(1, TU).astype(jnp.int32),
        rvu.reshape(1, TU).astype(jnp.float32),
    )


@functools.partial(
    jax.jit, static_argnames=("num_nodes", "num_hub_blocks", "bu", "interpret")
)
def hub_neighbor_mass(
    w_mm,        # [256, TU]
    tgt_u,       # i32[TU]
    rvu,         # f32[TU]
    tile_col,    # i32[T] static flattened hub tile list: column tile
    tile_out,    # i32[T] output block slot (0..NHB-1), block-major order
    tile_first,  # i32[T] 1 on each output block's first tile
    *,
    num_nodes: int,
    num_hub_blocks: int,
    bu: int,
    interpret: bool = False,
):
    """``M[NHB·256, N]`` for the (static) hub blocks — ragged widths walked
    as a flat 1D tile list, zero wasted grid steps."""
    TU = w_mm.shape[1]
    T = tile_col.shape[0]
    N = int(num_nodes)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(T,),
        in_specs=[
            pl.BlockSpec((BLOCK_R, bu), lambda t, tc, to, tf: (0, tc[t])),
            pl.BlockSpec((1, bu), lambda t, tc, to, tf: (0, tc[t])),
            pl.BlockSpec((1, bu), lambda t, tc, to, tf: (0, tc[t])),
        ],
        out_specs=pl.BlockSpec((BLOCK_R, N), lambda t, tc, to, tf: (to[t], 0)),
    )
    return pl.pallas_call(
        _hub_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(
            (num_hub_blocks * BLOCK_R, N), jnp.float32
        ),
        interpret=interpret,
    )(
        tile_col.astype(jnp.int32),
        tile_out.astype(jnp.int32),
        tile_first.astype(jnp.int32),
        w_mm,
        tgt_u.reshape(1, TU).astype(jnp.int32),
        rvu.reshape(1, TU).astype(jnp.float32),
    )


def reference_sparse_mass(
    w_mm, tgt_u, rvu, blocks, toff, *, num_nodes: int, bu: int, reg_tiles: int
):
    """Plain-XLA twin of :func:`sparse_neighbor_mass` (gather + matmul —
    no scatter, so it is TPU- and vmap-safe). Term-for-term the same f32
    operation order as the kernel body."""
    U = reg_tiles * bu
    N = int(num_nodes)

    def per_block(b):
        start = toff[b] * bu
        wb = lax.dynamic_slice(w_mm, (0, start), (BLOCK_R, U))
        tgt = lax.dynamic_slice(tgt_u, (start,), (U,))
        rv = lax.dynamic_slice(rvu, (start,), (U,))
        oh = jnp.where(
            tgt[:, None] == jnp.arange(N, dtype=jnp.int32)[None, :],
            rv[:, None],
            0.0,
        ).astype(w_mm.dtype)
        return jnp.dot(wb, oh, preferred_element_type=jnp.float32)

    M = jax.vmap(per_block)(blocks)
    return M.reshape(blocks.shape[0] * BLOCK_R, N)


def reference_hub_mass(sgraph, w_mm, tgt_u, rvu, *, num_nodes: int, blocks=None):
    """Plain-XLA twin of :func:`hub_neighbor_mass` — hub offsets/widths are
    static, so this is a Python loop over static slices."""
    N = int(num_nodes)
    outs = []
    for b in blocks if blocks is not None else sgraph.hub_blocks:
        off = sgraph.block_toff[b] * sgraph.bu
        width = sgraph.block_ntiles[b] * sgraph.bu
        wb = w_mm[:, off : off + width]
        tgt = tgt_u[off : off + width]
        rv = rvu[off : off + width]
        oh = jnp.where(
            tgt[:, None] == jnp.arange(N, dtype=jnp.int32)[None, :],
            rv[:, None],
            0.0,
        ).astype(w_mm.dtype)
        outs.append(jnp.dot(wb, oh, preferred_element_type=jnp.float32))
    return jnp.concatenate(outs, axis=0)


def hub_tile_arrays(sgraph, blocks=None):
    """Flatten hub blocks' ragged tile lists into the static
    (column-tile, output-slot, is-first) arrays the 1D hub grid walks,
    in output-block-major order (accumulation revisits each output block
    consecutively). ``blocks`` selects a subset (the solver processes
    hubs in chunk-sized groups so the admission race never exceeds the
    regular chunk width)."""
    import numpy as np

    cols, outs, firsts = [], [], []
    for slot, b in enumerate(blocks if blocks is not None else sgraph.hub_blocks):
        for j in range(sgraph.block_ntiles[b]):
            cols.append(sgraph.block_toff[b] + j)
            outs.append(slot)
            firsts.append(1 if j == 0 else 0)
    return (
        jnp.asarray(np.asarray(cols, dtype=np.int32)),
        jnp.asarray(np.asarray(outs, dtype=np.int32)),
        jnp.asarray(np.asarray(firsts, dtype=np.int32)),
    )
