"""Fused score → argmax → capacity-admission for one solver chunk step.

Semantics (identical to the XLA path in ``solver.global_solver.chunk_step``,
which remains the reference implementation and the fallback):

1. ``score[c, n] = M[c, n] − λ·proj_pct − ow·relu(proj_pct − 100) (+ gumbel)``
   where ``proj_pct`` is the node's CPU load in % of the packing budget if
   service c landed on n, and ``ow`` repels over-budget residency.
2. Feasibility: fits capacity (or is the current node), node valid.
3. ``prop[c]`` = first-max feasible node; ``gain`` vs the current node.
4. Admission: a proposal lands only if the target's free capacity covers
   every strictly-higher-priority same-target arrival plus itself
   (priority = greater gain, ties → lower chunk index — the stable-sort
   order of the reference path).

Two kernels:

- ``_score_kernel`` — grid over C tiles; per tile the [BC, N] score block
  lives only in VMEM (never HBM), reduced on the fly to per-service
  ``prop / gain / wants / slack`` vectors.
- ``_admission_kernel`` — one program; the pairwise priority race as a
  [C, C] MXU matmul against the per-service move masses.

Gumbel noise uses the TPU core PRNG (`pltpu.prng_seed` / ``prng_random_bits``)
seeded per (chunk, tile), so the fused path is deterministic for a fixed
seed but samples a different stream than ``jax.random.gumbel`` — annealing
noise has no parity requirement (the XLA reference path is compared against
this path with ``temp = 0``).

On non-TPU backends the kernels run only under ``interpret=True`` (tests);
production CPU solves use the XLA path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = float("-inf")


def _stateless_uniform(seed, shape):
    """Deterministic per-(seed, row, col) uniform in (0, 1) from a u32
    finalizer-style mixer — plain vector ops, so it lowers everywhere the
    kernels do (including interpret mode, where the TPU core PRNG has no
    lowering). Noise quality is annealing-grade, not cryptographic; its
    real job is making the SEED-OFFSET LAW testable off-hardware: the
    fused mass+score kernel offsets ``seed`` by the 256-row block index,
    the standalone score kernel by ``program_id`` over ``block_c``-row
    tiles, and the two streams coincide exactly when ``block_c ==
    BLOCK_R`` — the parity the noise-on tests pin."""
    r = jax.lax.broadcasted_iota(jnp.uint32, shape, 0)
    c = jax.lax.broadcasted_iota(jnp.uint32, shape, 1)
    x = seed.astype(jnp.uint32) * jnp.uint32(0x9E3779B9)
    x = x ^ (r * jnp.uint32(0x85EBCA6B)) ^ (c * jnp.uint32(0xC2B2AE35))
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x7FEB352D)
    x = x ^ (x >> 15)
    x = x * jnp.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    mant = (x & jnp.uint32(0x7FFFFF)).astype(jnp.float32)
    return (mant + 0.5) * (1.0 / 8388608.0)


def score_core(
    m, cur, home, pen, c_cpu, c_mem, valid,
    cpu_load, mem_load, cap, mem_cap, node_valid,
    lam, ow, temp, seed,
    *,
    enforce_capacity: bool,
    use_noise: bool,
    use_move_pen: bool,
    noise_impl: str = "tpu",
):
    """The chunk score → first-max proposal → per-row reductions as pure
    array math on VMEM-resident values — the SINGLE definition shared by
    the standalone score kernel and the sparse fused mass+score kernel
    (``ops.sparse_mass.sparse_mass_score``). Bit-parity between the two
    lowerings is structural: both call exactly this.

    Shapes: ``m`` (BC, N); ``cur/home/pen/c_cpu/c_mem/valid`` (BC, 1);
    ``cpu_load/mem_load/cap/mem_cap/node_valid`` (1, N); scalars traced.
    Returns ``(prop, gain, wants_i32, slack_cpu, slack_mem)``, all (BC, 1).
    """
    bc, n = m.shape
    col = jax.lax.broadcasted_iota(jnp.int32, (bc, n), 1)
    is_cur = col == cur                                   # (BC, N)

    proj_cpu = cpu_load + jnp.where(is_cur, 0.0, c_cpu)
    proj_pct = proj_cpu / cap * 100.0
    score = m - lam * proj_pct - ow * jnp.maximum(proj_pct - 100.0, 0.0)
    if use_move_pen:
        # disruption cost: residency anywhere but the round-start node
        # costs pen (staying moved keeps paying; moving back recovers it),
        # so a relocation must beat home by more than its restart cost.
        # Static flag (like use_noise): zero-cost callers keep the exact
        # pre-pricing kernel.
        score = score - jnp.where(col == home, 0.0, pen)
    if use_noise:
        if noise_impl == "tpu":
            pltpu.prng_seed(seed)
            bits = pltpu.prng_random_bits((bc, n))
            # uniform in (0, 1): keep 23 low bits — sign-safe whatever the
            # carrier dtype (a plain uint32→f32 convert can go through a signed
            # path and yield negatives, turning the log-log below into NaNs)
            mant = (bits & 0x7FFFFF).astype(jnp.float32)
            u = (mant + 0.5) * (1.0 / 8388608.0)
        elif noise_impl == "stateless":
            u = _stateless_uniform(seed, (bc, n))
        else:
            raise ValueError(f"unknown noise_impl {noise_impl!r}")
        score = score + temp * (-jnp.log(-jnp.log(u)))

    if enforce_capacity:
        proj_mem = mem_load + jnp.where(is_cur, 0.0, c_mem)
        fits = (proj_cpu <= cap) & (proj_mem <= mem_cap)
        feasible = (fits | is_cur) & (node_valid != 0)
    else:
        feasible = jnp.broadcast_to(node_valid != 0, (bc, n))

    masked = jnp.where(feasible, score, _NEG_INF)
    prop_score = jnp.max(masked, axis=1, keepdims=True)   # (BC, 1)
    # first-max parity with jnp.argmax: lowest column index among maxima
    at_max = masked == prop_score
    big = jnp.int32(n)
    prop = jnp.min(jnp.where(at_max, col, big), axis=1, keepdims=True)
    prop = jnp.minimum(prop, big - 1)
    cur_score = jnp.sum(jnp.where(is_cur, score, 0.0), axis=1, keepdims=True)
    gain = prop_score - cur_score
    wants = (valid != 0) & (gain > 0) & (prop != cur)

    is_prop = col == prop
    load_p = jnp.sum(jnp.where(is_prop, cpu_load, 0.0), axis=1, keepdims=True)
    cap_p = jnp.sum(jnp.where(is_prop, cap, 0.0), axis=1, keepdims=True)
    mload_p = jnp.sum(jnp.where(is_prop, mem_load, 0.0), axis=1, keepdims=True)
    mcap_p = jnp.sum(jnp.where(is_prop, mem_cap, 0.0), axis=1, keepdims=True)
    return (
        prop,
        gain,
        wants.astype(jnp.int32),
        cap_p - load_p - c_cpu,
        mcap_p - mload_p - c_mem,
    )


def _score_kernel(
    lam_ref,        # SMEM (1, 1) f32
    ow_ref,         # SMEM (1, 1) f32 — over-budget repulsion weight
    temp_ref,       # SMEM (1, 1) f32
    seed_ref,       # SMEM (1, 1) i32
    m_ref,          # VMEM (BC, N) f32 — neighbor mass for this C tile
    cur_ref,        # VMEM (BC, 1) i32
    home_ref,       # VMEM (BC, 1) i32 — ROUND-START node (move-cost anchor)
    pen_ref,        # VMEM (BC, 1) f32 — move cost (comm units per restart
                    # × restarts) charged at every node except home
    c_cpu_ref,      # VMEM (BC, 1) f32
    c_mem_ref,      # VMEM (BC, 1) f32
    valid_ref,      # VMEM (BC, 1) i32
    cpu_load_ref,   # VMEM (1, N) f32
    mem_load_ref,   # VMEM (1, N) f32
    cap_ref,        # VMEM (1, N) f32
    mem_cap_ref,    # VMEM (1, N) f32
    node_valid_ref, # VMEM (1, N) i32
    prop_ref,       # out VMEM (BC, 1) i32
    gain_ref,       # out VMEM (BC, 1) f32
    wants_ref,      # out VMEM (BC, 1) i32
    slack_cpu_ref,  # out VMEM (BC, 1) f32
    slack_mem_ref,  # out VMEM (BC, 1) f32
    *,
    enforce_capacity: bool,
    use_noise: bool,
    use_move_pen: bool,
    noise_impl: str,
):
    prop, gain, wants, slack_cpu, slack_mem = score_core(
        m_ref[:], cur_ref[:], home_ref[:], pen_ref[:],
        c_cpu_ref[:], c_mem_ref[:], valid_ref[:],
        cpu_load_ref[:], mem_load_ref[:], cap_ref[:], mem_cap_ref[:],
        node_valid_ref[:],
        lam_ref[0, 0], ow_ref[0, 0], temp_ref[0, 0],
        seed_ref[0, 0] + pl.program_id(0),
        enforce_capacity=enforce_capacity,
        use_noise=use_noise,
        use_move_pen=use_move_pen,
        noise_impl=noise_impl,
    )
    prop_ref[:] = prop
    gain_ref[:] = gain
    wants_ref[:] = wants
    slack_cpu_ref[:] = slack_cpu
    slack_mem_ref[:] = slack_mem


def _admission_kernel(
    prop_ref,       # VMEM (BC, 1) i32 — this row tile
    gain_ref,       # VMEM (BC, 1) f32
    wants_ref,      # VMEM (BC, 1) i32
    cur_ref,        # VMEM (BC, 1) i32
    valid_ref,      # VMEM (BC, 1) i32
    c_cpu_ref,      # VMEM (BC, 1) f32
    c_mem_ref,      # VMEM (BC, 1) f32
    slack_cpu_ref,  # VMEM (BC, 1) f32
    slack_mem_ref,  # VMEM (BC, 1) f32
    prop_row_ref,   # VMEM (1, C) i32 — full vectors, every tile
    gain_row_ref,   # VMEM (1, C) f32
    wants_row_ref,  # VMEM (1, C) i32
    moving_cpu_ref, # VMEM (C, 1) f32: c_cpu where wants else 0
    moving_mem_ref, # VMEM (C, 1) f32
    new_node_ref,   # out VMEM (BC, 1) i32
    admitted_ref,   # out VMEM (BC, 1) i32
    d_cpu_ref,      # out VMEM (1, N) f32: net load delta, grid-accumulated
    d_mem_ref,      # out VMEM (1, N) f32
    x_rows_ref=None,  # out VMEM (BC, N) x_dtype: one-hot(new_node)·valid —
                      # only when the caller maintains an occupancy matrix
                      # (the inline-mass solver path regenerates occupancy
                      # from `assign` on the fly and skips this write)
    *,
    enforce_capacity: bool,
):
    bc = prop_ref.shape[0]
    c = prop_row_ref.shape[1]
    n = d_cpu_ref.shape[1]
    wants = wants_ref[:] != 0
    if enforce_capacity:
        gw = jnp.where(wants, gain_ref[:], _NEG_INF)          # (BC, 1)
        gw_row = jnp.where(wants_row_ref[:] != 0, gain_row_ref[:], _NEG_INF)
        ridx = pl.program_id(0) * bc + jax.lax.broadcasted_iota(
            jnp.int32, (bc, c), 0
        )
        cidx = jax.lax.broadcasted_iota(jnp.int32, (bc, c), 1)
        before = (gw_row > gw) | ((gw_row == gw) & (cidx < ridx))
        pri = (
            before
            & (wants_row_ref[:] != 0)
            & (prop_row_ref[:] == prop_ref[:])
        ).astype(jnp.float32)                                 # (BC, C)
        # HIGHEST precision: a default bf16-demoted matmul could round a
        # landing mass down and admit a move the exact check would reject
        land_cpu = jnp.dot(
            pri, moving_cpu_ref[:],
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        land_mem = jnp.dot(
            pri, moving_mem_ref[:],
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        ok = (land_cpu <= slack_cpu_ref[:]) & (land_mem <= slack_mem_ref[:])
        admitted = wants & ok
    else:
        admitted = wants
    new_node = jnp.where(admitted, prop_ref[:], cur_ref[:])
    new_node_ref[:] = new_node
    admitted_ref[:] = admitted.astype(jnp.int32)

    # the commit arithmetic, fused: the service's new occupancy row and the
    # tile's net per-node load delta (moves in minus moves out)
    ncol = jax.lax.broadcasted_iota(jnp.int32, (bc, n), 1)
    is_new = ncol == new_node
    if x_rows_ref is not None:
        x_rows_ref[:] = jnp.where(
            is_new & (valid_ref[:] != 0), 1.0, 0.0
        ).astype(x_rows_ref.dtype)
    # mask the last tile's padding rows: per-row outputs beyond C are
    # discarded by Pallas, but these (1, N) reductions would fold the
    # padding rows' unspecified inputs into the accumulated deltas
    in_range = (
        pl.program_id(0) * bc
        + jax.lax.broadcasted_iota(jnp.int32, (bc, 1), 0)
    ) < c
    a_cpu = jnp.where(admitted & in_range, c_cpu_ref[:], 0.0)
    a_mem = jnp.where(admitted & in_range, c_mem_ref[:], 0.0)
    is_old = ncol == cur_ref[:]
    tile_d_cpu = jnp.sum(
        jnp.where(is_new, a_cpu, 0.0) - jnp.where(is_old, a_cpu, 0.0),
        axis=0, keepdims=True,
    )
    tile_d_mem = jnp.sum(
        jnp.where(is_new, a_mem, 0.0) - jnp.where(is_old, a_mem, 0.0),
        axis=0, keepdims=True,
    )

    @pl.when(pl.program_id(0) == 0)
    def _():
        d_cpu_ref[:] = jnp.zeros_like(d_cpu_ref)
        d_mem_ref[:] = jnp.zeros_like(d_mem_ref)

    d_cpu_ref[:] += tile_d_cpu
    d_mem_ref[:] += tile_d_mem


@functools.partial(
    jax.jit,
    static_argnames=(
        "enforce_capacity", "use_noise", "interpret", "block_c", "x_dtype",
        "emit_x_rows", "noise_impl",
    ),
)
def fused_score_admission(
    M,            # f32[C, N] neighbor mass (kept-local comm weight per node)
    cur,          # i32[C] current node per service
    c_cpu,        # f32[C]
    c_mem,        # f32[C]
    valid_c,      # bool[C]
    cpu_load,     # f32[N]
    mem_load,     # f32[N]
    cap,          # f32[N]
    mem_cap,      # f32[N]
    node_valid,   # bool[N]
    lam,          # f32 scalar: balance weight
    temp,         # f32 scalar: gumbel temperature
    seed,         # i32 scalar: PRNG seed for this chunk
    overload_weight=0.0,  # f32 scalar: repulsion per % beyond the budget
    home=None,    # i32[C] round-start node (move-cost anchor; default cur)
    move_pen=None,  # f32[C] disruption cost charged off-home (default 0)
    *,
    enforce_capacity: bool,
    use_noise: bool,
    interpret: bool = False,
    block_c: int = 256,
    x_dtype=jnp.bfloat16,
    emit_x_rows: bool = True,
    noise_impl: str = "tpu",
):
    """Returns ``(new_node i32[C], admitted bool[C], x_rows x_dtype[C, N],
    d_cpu f32[N], d_mem f32[N])`` — the chunk step's decision plus its
    commit arithmetic (new occupancy rows and net per-node load deltas),
    fused into two Pallas calls. With ``emit_x_rows=False`` the occupancy
    rows are neither computed nor written (the inline-mass solver path
    regenerates occupancy from ``assign`` on the fly) and the return is
    ``(new_node, admitted, d_cpu, d_mem)``."""
    C, N = M.shape
    bc = min(block_c, C)
    grid = (pl.cdiv(C, bc),)
    use_move_pen = move_pen is not None
    if home is None:
        home = cur
    if move_pen is None:
        move_pen = jnp.zeros((C,), jnp.float32)

    col_i32 = lambda x: x.reshape(C, 1).astype(jnp.int32)
    col_f32 = lambda x: x.reshape(C, 1).astype(jnp.float32)
    row_f32 = lambda x: x.reshape(1, N).astype(jnp.float32)
    row_i32 = lambda x: x.reshape(1, N).astype(jnp.int32)

    smem = pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM)
    cvec = pl.BlockSpec((bc, 1), lambda i: (i, 0), memory_space=pltpu.VMEM)
    nvec = pl.BlockSpec((1, N), lambda i: (0, 0), memory_space=pltpu.VMEM)

    out_c = jax.ShapeDtypeStruct((C, 1), jnp.float32)
    out_ci = jax.ShapeDtypeStruct((C, 1), jnp.int32)

    prop, gain, wants, slack_cpu, slack_mem = pl.pallas_call(
        functools.partial(
            _score_kernel, enforce_capacity=enforce_capacity,
            use_noise=use_noise, use_move_pen=use_move_pen,
            noise_impl=noise_impl,
        ),
        grid=grid,
        in_specs=[
            smem, smem, smem, smem,
            pl.BlockSpec((bc, N), lambda i: (i, 0), memory_space=pltpu.VMEM),
            cvec, cvec, cvec, cvec, cvec, cvec,
            nvec, nvec, nvec, nvec, nvec,
        ],
        out_specs=[cvec, cvec, cvec, cvec, cvec],
        out_shape=[out_ci, out_c, out_ci, out_c, out_c],
        interpret=interpret,
    )(
        jnp.asarray(lam, jnp.float32).reshape(1, 1),
        jnp.asarray(overload_weight, jnp.float32).reshape(1, 1),
        jnp.asarray(temp, jnp.float32).reshape(1, 1),
        jnp.asarray(seed, jnp.int32).reshape(1, 1),
        M.astype(jnp.float32),
        col_i32(cur),
        col_i32(home),
        col_f32(move_pen),
        col_f32(c_cpu),
        col_f32(c_mem),
        col_i32(valid_c),
        row_f32(cpu_load),
        row_f32(mem_load),
        row_f32(cap),
        row_f32(mem_cap),
        row_i32(node_valid),
    )

    return admission_stage(
        prop, gain, wants, slack_cpu, slack_mem, cur, valid_c, c_cpu, c_mem,
        num_nodes=N,
        enforce_capacity=enforce_capacity,
        interpret=interpret,
        block_c=bc,
        x_dtype=x_dtype,
        emit_x_rows=emit_x_rows,
    )


def admission_stage(
    prop, gain, wants, slack_cpu, slack_mem,  # [C, 1] score-stage outputs
    cur, valid_c, c_cpu, c_mem,               # [C]-shaped chunk vectors
    *,
    num_nodes: int,
    enforce_capacity: bool,
    interpret: bool = False,
    block_c: int = 256,
    x_dtype=jnp.bfloat16,
    emit_x_rows: bool,
):
    """The admission-race half of :func:`fused_score_admission`, callable
    on any score stage's outputs (the standalone score kernel or the
    sparse fused mass+score kernel). Admission tiled over C rows: the
    (BC, C) priority block stays small while the full priority matrix
    would not fit VMEM at C ≥ ~1000. The (1, N) load-delta outputs map
    every tile to the same block and accumulate across the sequential
    grid.

    ``emit_x_rows`` is keyword-REQUIRED and has no default: it changes the
    return ARITY (5-tuple with occupancy rows vs 4-tuple without), and
    :func:`fused_score_admission` defaults the flag the other way — every
    caller must state which contract it is unpacking."""
    C = prop.shape[0]
    N = int(num_nodes)
    bc = min(block_c, C)
    grid = (pl.cdiv(C, bc),)

    col_i32 = lambda x: x.reshape(C, 1).astype(jnp.int32)
    col_f32 = lambda x: x.reshape(C, 1).astype(jnp.float32)
    cvec = pl.BlockSpec((bc, 1), lambda i: (i, 0), memory_space=pltpu.VMEM)
    out_ci = jax.ShapeDtypeStruct((C, 1), jnp.int32)

    crow = pl.BlockSpec((1, C), lambda i: (0, 0), memory_space=pltpu.VMEM)
    cfull = pl.BlockSpec((C, 1), lambda i: (0, 0), memory_space=pltpu.VMEM)
    nacc = pl.BlockSpec((1, N), lambda i: (0, 0), memory_space=pltpu.VMEM)
    wants_b = wants != 0
    out_specs = [cvec, cvec, nacc, nacc]
    out_shape = [
        out_ci, out_ci,
        jax.ShapeDtypeStruct((1, N), jnp.float32),
        jax.ShapeDtypeStruct((1, N), jnp.float32),
    ]
    if emit_x_rows:
        out_specs.append(
            pl.BlockSpec((bc, N), lambda i: (i, 0), memory_space=pltpu.VMEM)
        )
        out_shape.append(jax.ShapeDtypeStruct((C, N), x_dtype))
    outs = pl.pallas_call(
        functools.partial(_admission_kernel, enforce_capacity=enforce_capacity),
        grid=grid,
        in_specs=[cvec, cvec, cvec, cvec, cvec, cvec, cvec, cvec, cvec,
                  crow, crow, crow, cfull, cfull],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(
        prop,
        gain,
        wants,
        col_i32(cur),
        col_i32(valid_c),
        col_f32(c_cpu),
        col_f32(c_mem),
        slack_cpu,
        slack_mem,
        prop.reshape(1, C),
        gain.reshape(1, C),
        wants.reshape(1, C),
        jnp.where(wants_b, col_f32(c_cpu), 0.0),
        jnp.where(wants_b, col_f32(c_mem), 0.0),
    )
    if emit_x_rows:
        new_node, admitted, d_cpu, d_mem, x_rows = outs
        return (
            new_node[:, 0], admitted[:, 0] != 0, x_rows, d_cpu[0], d_mem[0]
        )
    new_node, admitted, d_cpu, d_mem = outs
    return new_node[:, 0], admitted[:, 0] != 0, d_cpu[0], d_mem[0]


def _mass_kernel(
    blocks_ref,  # scalar-prefetch i32[KB]: W row-block id per chunk block
    w_ref,       # VMEM (B, BJ) W row-block tile (gathered by the index_map)
    assign_ref,  # VMEM (1, BJ) i32 current node per service (canonical order)
    valid_ref,   # VMEM (1, BJ) i32 service validity
    m_ref,       # out VMEM (B, N) f32, accumulated over the j grid axis
):
    del blocks_ref  # consumed by the index_map, not the body
    n = m_ref.shape[1]
    bj = w_ref.shape[1]
    a = assign_ref[:].reshape(bj, 1)
    v = valid_ref[:].reshape(bj, 1) != 0
    col = jax.lax.broadcasted_iota(jnp.int32, (bj, n), 1)
    # the occupancy tile, regenerated in VMEM: X[j, n] = [assign_j == n]·valid
    oh = ((a == col) & v).astype(w_ref.dtype)
    acc = jnp.dot(w_ref[:], oh, preferred_element_type=jnp.float32)

    @pl.when(pl.program_id(1) == 0)
    def _():
        m_ref[:] = acc

    @pl.when(pl.program_id(1) > 0)
    def _():
        m_ref[:] += acc


@functools.partial(
    jax.jit,
    static_argnames=("num_nodes", "block_b", "block_j", "interpret"),
)
def fused_neighbor_mass(
    W,          # [SP, SP] weight matrix, CANONICAL order (never permuted)
    assign,     # i32[SP] current node per service, canonical order
    svc_valid,  # bool[SP]
    block_ids,  # i32[KB]: which B-row blocks of W form this chunk, in order
    *,
    num_nodes: int,
    block_b: int = 256,
    block_j: int = 1024,
    interpret: bool = False,
):
    """``M = W[chunk rows] @ (one_hot(assign)·valid)`` where the chunk's rows
    are the ``block_ids`` B-row blocks of the CANONICAL W — gathered by the
    Pallas index_map (scalar prefetch), so no per-sweep W permute/copy ever
    touches HBM — and the occupancy matrix is generated ON THE FLY in VMEM —
    X never exists in HBM, the chunk step carries no occupancy state and
    commits no [C, N] scatter; ``assign`` (a few KB) is the only coupling
    between chunks. Returns ``f32[KB·block_b, N]``.
    """
    SP = W.shape[0]
    N = int(num_nodes)
    KB = block_ids.shape[0]
    if SP % block_j or SP % block_b:
        # flooring the grid would silently DROP the trailing service
        # columns/rows from the contraction — wrong M, no shape error
        raise ValueError(
            f"SP={SP} must be divisible by block_j={block_j} and "
            f"block_b={block_b}"
        )
    nj = SP // block_j
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(KB, nj),
        in_specs=[
            pl.BlockSpec(
                (block_b, block_j),
                lambda i, j, blocks_ref: (blocks_ref[i], j),
            ),
            pl.BlockSpec((1, block_j), lambda i, j, blocks_ref: (0, j)),
            pl.BlockSpec((1, block_j), lambda i, j, blocks_ref: (0, j)),
        ],
        out_specs=pl.BlockSpec(
            (block_b, N), lambda i, j, blocks_ref: (i, 0)
        ),
    )
    return pl.pallas_call(
        _mass_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((KB * block_b, N), jnp.float32),
        interpret=interpret,
    )(
        block_ids.astype(jnp.int32),
        W,
        assign.reshape(1, SP).astype(jnp.int32),
        svc_valid.reshape(1, SP).astype(jnp.int32),
    )


def pairwise_admission(gain, prop, wants, c_cpu, c_mem, slack_cpu, slack_mem):
    """The sort-free within-chunk capacity race on replicated vectors —
    shared by the XLA reference twin and the node-sharded solver (the
    Pallas kernel carries the same math; keep all in lockstep).

    A proposal is admitted iff the target's slack covers every
    higher-priority (greater gain, ties → lower index) same-target
    arrival plus itself."""
    C = gain.shape[0]
    cidx = jnp.arange(C)
    gain_w = jnp.where(wants, gain, -jnp.inf)
    before = (gain_w[None, :] > gain_w[:, None]) | (
        (gain_w[None, :] == gain_w[:, None]) & (cidx[None, :] < cidx[:, None])
    )
    pri = (before & wants[None, :] & (prop[None, :] == prop[:, None])).astype(
        jnp.float32
    )
    land_cpu = jnp.dot(
        pri, jnp.where(wants, c_cpu, 0.0),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    land_mem = jnp.dot(
        pri, jnp.where(wants, c_mem, 0.0),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    return wants & (land_cpu <= slack_cpu) & (land_mem <= slack_mem)


def reference_score_admission(
    M, cur, c_cpu, c_mem, valid_c, cpu_load, mem_load, cap, mem_cap,
    node_valid, lam, noise=None, overload_weight=0.0, home=None,
    move_pen=None, *, enforce_capacity: bool,
):
    """Plain-XLA twin of :func:`fused_score_admission` — and the solver's
    production XLA epilogue (one implementation, two lowerings).

    Expressions mirror the kernel term for term (same f32 operation order),
    so exact-equality parity between the two paths is structural, not a
    rounding coincidence. ``noise`` is a caller-supplied [C, N] additive
    score perturbation (the fused path samples the TPU core PRNG instead —
    annealing noise carries no parity requirement).
    """
    C, N = M.shape
    is_cur = jnp.arange(N)[None, :] == cur[:, None]
    proj_cpu = cpu_load[None, :] + jnp.where(is_cur, 0.0, c_cpu[:, None])
    proj_pct = proj_cpu / cap[None, :] * 100.0
    score = (
        M - lam * proj_pct
        - overload_weight * jnp.maximum(proj_pct - 100.0, 0.0)
    )
    if move_pen is not None:
        anchor = cur if home is None else home
        score = score - jnp.where(
            jnp.arange(N)[None, :] == anchor[:, None], 0.0, move_pen[:, None]
        )
    if noise is not None:
        score = score + noise
    if enforce_capacity:
        proj_mem = mem_load[None, :] + jnp.where(is_cur, 0.0, c_mem[:, None])
        fits = (proj_cpu <= cap[None, :]) & (proj_mem <= mem_cap[None, :])
        feasible = (fits | is_cur) & node_valid[None, :]
    else:
        feasible = jnp.broadcast_to(node_valid[None, :], score.shape)
    masked = jnp.where(feasible, score, -jnp.inf)
    prop = jnp.argmax(masked, axis=1).astype(jnp.int32)
    prop_score = jnp.take_along_axis(masked, prop[:, None], axis=1)[:, 0]
    cur_score = jnp.take_along_axis(score, cur[:, None], axis=1)[:, 0]
    gain = prop_score - cur_score
    wants = valid_c & (gain > 0) & (prop != cur)
    if enforce_capacity:
        admitted = pairwise_admission(
            gain, prop, wants, c_cpu, c_mem,
            cap[prop] - cpu_load[prop] - c_cpu,
            mem_cap[prop] - mem_load[prop] - c_mem,
        )
    else:
        admitted = wants
    return jnp.where(admitted, prop, cur), admitted
