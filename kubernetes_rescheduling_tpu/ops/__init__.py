"""Pallas TPU kernels for the solver's hot ops.

The global solver's chunk step is launch-bound: after the neighbor-mass
matmul, XLA runs a dependent chain of ~15 small ops (score, feasibility,
argmax, pairwise admission) whose per-kernel overhead dominates at
C = 1024, N = 1024. These kernels fuse that epilogue into two Pallas
calls so each chunk step is matmul + 2 kernels + a couple of scatters.
"""

from kubernetes_rescheduling_tpu.ops.fused_admission import (
    fused_score_admission,
    reference_score_admission,
)

__all__ = ["fused_score_admission", "reference_score_admission"]
