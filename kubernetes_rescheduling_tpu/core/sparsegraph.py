"""Sparse service-communication graph — breaks the dense-W scale wall.

The dense solver stores pair weights as an SP×SP matrix (bf16 matmul copy +
f32 adjacency ≈ 6 bytes/pair), which hard-fails around ~46k services on a
16 GB chip. But the reference objective is defined on a sparse relation dict
(reference communicationcost.py:40-45) and the flagship power-law meshes run
at mean degree ~4 — the adjacency is ~99.9% zeros at 10k services. This
module stores the graph the way the solver consumes it:

**Degree-sorted block-local adjacency.** Services are relabeled by
descending neighbor count and grouped into blocks of ``BLOCK_R=256`` rows
(the solver's chunk-composition granularity). Each block stores a small
dense matrix over its own *distinct neighbor set*:

    w_local[b]  : [256, U_b]  pair weights, columns = the block's neighbors
    u_ids[b]    : [U_b]       sorted-space service id per local column

so the solver's neighbor-mass step stays an MXU matmul —
``M = w_local[b] @ one_hot(assign[u_ids[b]])`` — with a contraction length
of U_b (the union of 256 services' neighbor lists, ~1k for mean-degree-4
graphs) instead of SP. Degree sorting is what makes this work: it
concentrates the hubs (whose neighbor sets are huge) into a few leading
*hub blocks*, leaving every other block with a small, uniform neighbor set.

Layout: all blocks' ``w_local`` are column-concatenated into one
``[256, TU]`` array. Regular blocks are padded to a uniform
``U_REG = reg_tiles·bu`` columns (static offsets, no ragged bookkeeping in
the hot loop); blocks needing more columns become hub blocks with ragged
widths and a statically flattened tile list (they are few, and their ids
are known at build time). A trailing all-zero strip backs the dummy blocks
the solver pads chunks with.

The exact objective does not need any of this: it is a direct cut-sum over
a symmetric COO edge list (also stored here), matching the dense solver's
``exact_comm_cost`` semantics.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from flax import struct

from kubernetes_rescheduling_tpu.core.state import CommGraph

BLOCK_R = 256  # rows per block — must equal solver COMPOSITION_BLOCK


@struct.dataclass
class SparseCommGraph:
    """Block-local sparse pair-weight storage (see module docstring).

    All ids in device arrays are *sorted-space* (degree-sorted, padded to
    ``SP = NB·256``); ``perm``/``inv`` map to/from the original service ids
    used by ``ClusterState.pod_service`` and ``CommGraph``.
    """

    # [256, TU] column-concatenated block-local pair weights (f32; the
    # solver converts its matmul copy once per solve)
    w_local: jax.Array
    # i32[TU] sorted-space neighbor id per local column; SP = padding sentinel
    u_ids: jax.Array
    # symmetric COO edge list in sorted space (each undirected edge twice)
    edges_src: jax.Array  # i32[E2]
    edges_dst: jax.Array  # i32[E2]
    edges_w: jax.Array    # f32[E2]
    perm: jax.Array       # i32[SP] sorted slot -> original id (S = padding)
    inv: jax.Array        # i32[S]  original id -> sorted slot
    service_valid: jax.Array  # bool[SP] sorted-space validity
    # ORIGINAL-space dense adjacency, carried ONLY for single-block graphs
    # (≤ 256 services): the sparse chunked search degenerates there (one
    # chunk per sweep — no Gauss-Seidel sequencing), so the solver
    # delegates to the dense form, and this field lets that happen inside
    # a jit trace (host-side to_dense() cannot run on tracers)
    dense_adj: jax.Array | None = None
    # ---- static metadata (part of the jit cache key; one graph per run) ----
    # per-block first column tile (units of `bu` columns), len NB
    block_toff: tuple[int, ...] = struct.field(pytree_node=False, default=())
    # per-block tile count (regular blocks: reg_tiles; hubs: ragged), len NB
    block_ntiles: tuple[int, ...] = struct.field(pytree_node=False, default=())
    hub_blocks: tuple[int, ...] = struct.field(pytree_node=False, default=())
    regular_blocks: tuple[int, ...] = struct.field(pytree_node=False, default=())
    zero_toff: int = struct.field(pytree_node=False, default=0)
    bu: int = struct.field(pytree_node=False, default=512)
    reg_tiles: int = struct.field(pytree_node=False, default=2)
    num_services: int = struct.field(pytree_node=False, default=0)
    names: tuple[str, ...] = struct.field(pytree_node=False, default=())

    @property
    def sp(self) -> int:
        """Padded sorted-space service count (NB·256)."""
        return int(self.perm.shape[0])

    @property
    def num_blocks(self) -> int:
        return self.sp // BLOCK_R

    @property
    def u_reg(self) -> int:
        """Uniform column width of regular blocks."""
        return self.reg_tiles * self.bu

    def weight_bytes(self) -> int:
        """Live bytes of the pair-weight storage (f32 + the solver's
        mm-dtype copy at 2 bytes) — the number the dense formulation's
        ``check_weight_budget`` compares against SP²·6."""
        return int(self.w_local.size) * 6

    # ---- converters ----

    def to_dense(self) -> CommGraph:
        """Dense adjacency in ORIGINAL id space (small graphs / parity
        tests). Reconstructed from the COO list, which carries every edge
        exactly twice."""
        S = self.num_services
        adj = np.zeros((S, S), dtype=np.float32)
        src = np.asarray(self.edges_src)
        dst = np.asarray(self.edges_dst)
        w = np.asarray(self.edges_w)
        perm = np.asarray(self.perm)
        osrc = perm[src]
        odst = perm[dst]
        keep = (osrc < S) & (odst < S)
        adj[osrc[keep], odst[keep]] = w[keep]
        valid = np.zeros((S,), dtype=bool)
        valid[:S] = True
        return CommGraph(
            adj=jnp.asarray(adj),
            service_valid=jnp.asarray(valid),
            names=self.names,
        )


def from_edges(
    src,
    dst,
    w,
    num_services: int,
    *,
    names: tuple[str, ...] = (),
    bu: int = 512,
    reg_tiles: int = 2,
    degree_sort: bool = True,
    symmetric_input: bool = False,
) -> SparseCommGraph:
    """Build from an edge list in original id space.

    ``src/dst/w`` are directed edges (symmetrized here, duplicate pairs
    accumulated, self-loops dropped) unless ``symmetric_input`` says the
    list already carries each undirected edge twice. ``degree_sort=False``
    keeps original ids (identity relabeling) — used by parity tests that
    need bit-equal decisions against the dense solver.
    """
    S = int(num_services)
    src = np.asarray(src, dtype=np.int64)
    dst = np.asarray(dst, dtype=np.int64)
    w = np.asarray(w, dtype=np.float64)
    keep = src != dst
    src, dst, w = src[keep], dst[keep], w[keep]
    if not symmetric_input:
        src, dst = np.concatenate([src, dst]), np.concatenate([dst, src])
        w = np.concatenate([w, w])
    # accumulate duplicate pairs into one weight
    pair = src * S + dst
    order = np.argsort(pair, kind="stable")
    pair, src, dst, w = pair[order], src[order], dst[order], w[order]
    uniq, first = np.unique(pair, return_index=True)
    w = np.add.reduceat(w, first) if len(first) else w
    src, dst = src[first], dst[first]

    # distinct-neighbor count is what drives a block's local width U_b —
    # sort on it so hub rows cluster into few (ragged) hub blocks
    deg = np.bincount(src, minlength=S)
    if degree_sort:
        order = np.argsort(-deg, kind="stable").astype(np.int64)
    else:
        order = np.arange(S, dtype=np.int64)
    pos = np.empty(S, dtype=np.int64)
    pos[order] = np.arange(S)

    NB = max(1, -(-S // BLOCK_R))
    SP = NB * BLOCK_R
    rs = pos[src]
    rt = pos[dst]

    u_reg = reg_tiles * bu
    strips: list[np.ndarray] = []
    uids: list[np.ndarray] = []
    toff: list[int] = []
    ntiles: list[int] = []
    hub: list[int] = []
    regular: list[int] = []
    # edges sorted by row block for one-pass slicing
    border = np.argsort(rs // BLOCK_R, kind="stable")
    rs_b, rt_b, w_b = rs[border], rt[border], w[border]
    block_of = rs_b // BLOCK_R
    starts = np.searchsorted(block_of, np.arange(NB))
    ends = np.searchsorted(block_of, np.arange(NB), side="right")
    col_cursor = 0
    for b in range(NB):
        s, e = starts[b], ends[b]
        tgts = rt_b[s:e]
        u = np.unique(tgts)  # ascending sorted-space ids
        width = max(u_reg, -(-max(len(u), 1) // bu) * bu)
        wl = np.zeros((BLOCK_R, width), dtype=np.float32)
        if len(u):
            lcol = np.searchsorted(u, tgts)
            np.add.at(wl, (rs_b[s:e] % BLOCK_R, lcol), w_b[s:e])
        ui = np.full((width,), SP, dtype=np.int32)
        ui[: len(u)] = u
        strips.append(wl)
        uids.append(ui)
        toff.append(col_cursor // bu)
        nt = width // bu
        ntiles.append(nt)
        (hub if nt > reg_tiles else regular).append(b)
        col_cursor += width
    # trailing zero strip for the solver's dummy (chunk-padding) blocks
    strips.append(np.zeros((BLOCK_R, u_reg), dtype=np.float32))
    uids.append(np.full((u_reg,), SP, dtype=np.int32))
    zero_toff = col_cursor // bu

    perm = np.full((SP,), S, dtype=np.int32)
    perm[:S] = order
    valid = np.zeros((SP,), dtype=bool)
    valid[:S] = True

    dense_adj = None
    if NB <= 1:
        da = np.zeros((S, S), dtype=np.float32)
        da[src, dst] = w  # sym list: both directions present
        dense_adj = jnp.asarray(da)

    return SparseCommGraph(
        w_local=jnp.asarray(np.concatenate(strips, axis=1)),
        u_ids=jnp.asarray(np.concatenate(uids)),
        edges_src=jnp.asarray(rs.astype(np.int32)),
        edges_dst=jnp.asarray(rt.astype(np.int32)),
        edges_w=jnp.asarray(w.astype(np.float32)),
        perm=jnp.asarray(perm),
        inv=jnp.asarray(pos.astype(np.int32)),
        service_valid=jnp.asarray(valid),
        dense_adj=dense_adj,
        block_toff=tuple(toff),
        block_ntiles=tuple(ntiles),
        hub_blocks=tuple(hub),
        regular_blocks=tuple(regular),
        zero_toff=int(zero_toff),
        bu=int(bu),
        reg_tiles=int(reg_tiles),
        num_services=S,
        names=tuple(names),
    )


def from_comm_graph(
    graph: CommGraph, *, bu: int = 512, reg_tiles: int = 2,
    degree_sort: bool = True,
) -> SparseCommGraph:
    """Convert a dense CommGraph (uses the upper triangle; adj must be
    symmetric, which CommGraph construction guarantees)."""
    adj = np.asarray(graph.adj)
    valid = np.asarray(graph.service_valid)
    S = int(valid.sum())
    a = adj[:S, :S]
    iu, ju = np.nonzero(np.triu(a, k=1))
    return from_edges(
        iu, ju, a[iu, ju], S,
        names=graph.names, bu=bu, reg_tiles=reg_tiles, degree_sort=degree_sort,
    )


def from_workmodel(wm, *, bu: int = 512, reg_tiles: int = 2) -> SparseCommGraph:
    """Build directly from a workmodel's call graph WITHOUT materializing
    the dense adjacency — the only viable path at 50k+ services, where the
    dense [S, S] array wouldn't fit in host memory either."""
    index = {s.name: i for i, s in enumerate(wm.services)}
    src: list[int] = []
    dst: list[int] = []
    for i, svc in enumerate(wm.services):
        for callee in svc.callees:
            j = index.get(callee)
            if j is not None and j != i:
                src.append(i)
                dst.append(j)
    return from_edges(
        np.asarray(src), np.asarray(dst), np.ones(len(src)), len(wm.services),
        names=wm.names, bu=bu, reg_tiles=reg_tiles,
    )


@struct.dataclass
class TraceLocator:
    """Static positions of every undirected edge's weight in a
    ``SparseCommGraph`` — the bridge between streaming traces and the
    block-local form. The sparse layout is *static structure + dynamic
    weights*: each undirected edge lives at exactly two COO slots and two
    ``w_local`` cells (row i / col j and row j / col i), all computed once
    at build time, so a per-step weight update is one small scatter
    instead of a dense [S, S] rebuild (bench/trace.py round-4 measured
    that rebuild as the ~9 ms/step streaming premium of the dense path).

    ``E`` is the undirected edge count; all arrays are device-resident so
    the updater runs inside jit."""

    coo: jax.Array      # i32[2E] COO indices (forward then reverse slots)
    w_rows: jax.Array   # i32[2E] w_local row per slot
    w_cols: jax.Array   # i32[2E] w_local column per slot
    base_w: jax.Array   # f32[E] build-time weight per undirected edge
    # True when the graph's COO list has been reordered into the
    # locator's canonical [forward..., reverse...] order
    # (:func:`reorder_for_trace`): the per-step edges_w update is then a
    # plain concat instead of a 2E-element scatter — TPU scatters run
    # element-at-a-time (~12 ns/el), so at 10k services the scatter was
    # most of the streaming premium
    canonical: bool = struct.field(pytree_node=False, default=False)

    @property
    def num_edges(self) -> int:
        return int(self.base_w.shape[0])


def trace_locator(sgraph: SparseCommGraph) -> TraceLocator:
    """Precompute a :class:`TraceLocator` (host-side, once per graph)."""
    src = np.asarray(sgraph.edges_src).astype(np.int64)
    dst = np.asarray(sgraph.edges_dst).astype(np.int64)
    w = np.asarray(sgraph.edges_w)
    E2 = len(src)
    SP = sgraph.sp
    bu = sgraph.bu

    # w_local cell per directed COO entry: the row's block strip, column =
    # position of dst in the block's ascending distinct-neighbor list
    rows = (src % BLOCK_R).astype(np.int64)
    cols = np.empty(E2, dtype=np.int64)
    u_all = np.asarray(sgraph.u_ids)
    blk = src // BLOCK_R
    for b in np.unique(blk):
        m = blk == b
        lo = sgraph.block_toff[b] * bu
        width = sgraph.block_ntiles[b] * bu
        u = u_all[lo : lo + width]
        nu = int(np.searchsorted(u, SP))  # distinct count (SP-padded tail)
        cols[m] = lo + np.searchsorted(u[:nu], dst[m])

    # pair the two directed slots of each undirected edge
    lo_id = np.minimum(src, dst)
    hi_id = np.maximum(src, dst)
    key = lo_id * SP + hi_id
    order = np.argsort(key, kind="stable")
    fwd, rev = order[0::2], order[1::2]
    if not np.array_equal(key[fwd], key[rev]):
        raise AssertionError(
            "COO list does not carry each undirected edge exactly twice"
        )
    both = np.concatenate([fwd, rev])
    return TraceLocator(
        coo=jnp.asarray(both.astype(np.int32)),
        w_rows=jnp.asarray(rows[both].astype(np.int32)),
        w_cols=jnp.asarray(cols[both].astype(np.int32)),
        base_w=jnp.asarray(w[fwd].astype(np.float32)),
    )


def reorder_for_trace(
    sgraph: SparseCommGraph,
) -> tuple[SparseCommGraph, TraceLocator]:
    """Prepare a graph for streaming: permute its COO list into the
    locator's canonical [forward..., reverse...] order (every consumer of
    the edge list — exact objectives, shard args — is order-independent,
    so this is free) and return the matching canonical locator. The
    per-step ``edges_w`` update then needs NO scatter at all."""
    loc = trace_locator(sgraph)
    coo = np.asarray(loc.coo)
    sg2 = sgraph.replace(
        edges_src=sgraph.edges_src[coo],
        edges_dst=sgraph.edges_dst[coo],
        edges_w=sgraph.edges_w[coo],
    )
    E2 = coo.shape[0]
    return sg2, loc.replace(
        coo=jnp.arange(E2, dtype=jnp.int32), canonical=True
    )


def with_edge_weights(
    sgraph: SparseCommGraph, loc: TraceLocator, new_w: jax.Array
) -> SparseCommGraph:
    """New graph with per-undirected-edge weights ``new_w`` (f32[E], in
    the locator's canonical edge order) — a 2E-element scatter into the
    block-local strips, and either a plain concat (canonical locator,
    :func:`reorder_for_trace`) or a 2E scatter into the COO list;
    jit-safe (static structure, dynamic weights)."""
    if sgraph.dense_adj is not None:
        # single-block graphs carry a dense twin for the solver's
        # delegation path; updating only the sparse storage would leave
        # that twin stale and the solver silently optimizing OLD weights.
        # Streaming at <=256 services belongs to the dense replay anyway.
        raise ValueError(
            "with_edge_weights does not support single-block graphs "
            "(their dense_adj delegation twin would go stale) — use the "
            "dense trace path (bench.trace.replay_on_device) at this size"
        )
    w2 = jnp.concatenate([new_w, new_w])
    return sgraph.replace(
        w_local=sgraph.w_local.at[loc.w_rows, loc.w_cols].set(w2),
        edges_w=w2 if loc.canonical else sgraph.edges_w.at[loc.coo].set(w2),
    )


def rv_weighted_edge_w(
    sgraph: SparseCommGraph, rv_sorted: jax.Array
) -> jax.Array:
    """Per-edge rv-weighted weight ``(w·rv_s)·rv_t`` — THE canonical
    product grouping of the exact cut-sum, shared by
    :func:`sparse_pair_comm_cost` and both sparse solvers' per-sweep
    objectives (which precompute it once per solve: rv is fixed across
    sweeps, so each sweep gathers only the two assign columns). One
    definition keeps the single-chip ↔ node-sharded objective
    bit-identical by construction, not by copy."""
    s, t = sgraph.edges_src, sgraph.edges_dst
    return sgraph.edges_w * rv_sorted[s] * rv_sorted[t]


def edge_cut_sum(
    sgraph: SparseCommGraph, e_rvw: jax.Array, assign_sorted: jax.Array
) -> jax.Array:
    """``0.5·Σ_e e_rvw·[a_s≠a_t]`` over the symmetric COO list (each
    undirected edge appears twice, hence the 0.5) — the per-sweep half
    of the exact cut-sum; ``e_rvw`` from :func:`rv_weighted_edge_w`."""
    cut = (
        assign_sorted[sgraph.edges_src] != assign_sorted[sgraph.edges_dst]
    ).astype(jnp.float32)
    return 0.5 * jnp.sum(e_rvw * cut)


def sparse_pair_comm_cost(
    sgraph: SparseCommGraph, assign_sorted: jax.Array, rv_sorted: jax.Array
) -> jax.Array:
    """Exact pair-weighted cut ``0.5·Σ_e w_e·rv_s·rv_t·[a_s≠a_t]`` — the
    sparse twin of the dense solver's ``exact_comm_cost`` (a direct sum, so
    error scales with the cut, not with ulp(ΣW))."""
    return edge_cut_sum(
        sgraph, rv_weighted_edge_w(sgraph, rv_sorted), assign_sorted
    )
