"""Array-world cluster state — the TPU-native replacement for the reference's
``cluster_monitoring`` dict (reference podmonitor.py:17-37).

Design notes (TPU-first):

- **Fixed capacity + validity masks.** Pods appear and disappear between
  rounds; dynamic shapes would retrace every ``jit``. All arrays are padded to
  static capacities ``N`` (nodes), ``P`` (pods), ``S`` (services) with boolean
  validity masks, so a single compiled program serves every round.
- **Assignment vector, not nested dicts.** The reference stores a per-node
  list of pod dicts; we store ``pod_node: i32[P]`` (and ``pod_service``),
  which turns every policy question ("how many pods on node n?", "how many
  related pods on node n?") into a one-hot matmul or segment-sum — MXU food.
- **Derived, not stored.** Node usage = base (system/background) + sum of
  tracked pod usage, recomputed on device each round instead of being a
  second source of truth.
- **Lexicographic ranks.** The reference breaks ties on node *names*
  (min name for spread, reference rescheduling.py:101; max name for binpack,
  reference rescheduling.py:133). Strings don't exist on device, so each node
  carries ``node_lex_rank`` — its rank in the sorted-name order — computed
  host-side once at state construction.
"""

from __future__ import annotations

from typing import Mapping, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

UNASSIGNED = -1  # pod_node value for a pod not placed on any node


@struct.dataclass
class CommGraph:
    """Service↔service communication graph.

    The undirected closure of the workload's directed call graph — the
    reference hardcodes this closure as a dict (reference main.py:31-52,
    duplicated at communicationcost.py:69-88); we derive it from a workmodel
    file (see ``core.workmodel``) into a dense symmetric adjacency, which is
    what both the comm-cost objective (a masked quadratic form) and the CAR
    affinity score (a row gather + matmul) want on TPU.

    Attributes:
      adj: f32[S, S] symmetric weights; adj[i, j] > 0 iff services i and j
        communicate. Diagonal is zero.
      service_valid: bool[S] — padding mask.
      names: static tuple of service names, index-aligned with ``adj``.
    """

    adj: jax.Array
    service_valid: jax.Array
    names: tuple[str, ...] = struct.field(pytree_node=False, default=())

    @property
    def num_services(self) -> int:
        return int(self.adj.shape[0])

    def service_index(self, name: str) -> int:
        return self.names.index(name)

    @classmethod
    def from_relation(
        cls,
        relation: Mapping[str, Sequence[str]],
        *,
        capacity: int | None = None,
        names: Sequence[str] | None = None,
    ) -> "CommGraph":
        """Build from a ``{service: [related services]}`` dict.

        Symmetrizes (undirected closure — matches how reference main.py:31-52
        closes workmodelC.json's directed edges) and pads to ``capacity``.
        """
        if names is None:
            seen: dict[str, None] = {}
            for k, vs in relation.items():
                seen.setdefault(k)
                for v in vs:
                    seen.setdefault(v)
            names = list(seen)
        n = len(names)
        cap = capacity or n
        if cap < n:
            raise ValueError(f"capacity {cap} < number of services {n}")
        index = {name: i for i, name in enumerate(names)}
        adj = np.zeros((cap, cap), dtype=np.float32)
        for src, dsts in relation.items():
            if src not in index:
                raise ValueError(
                    f"relation source {src!r} not in service names {names[:8]}..."
                )
            i = index[src]
            for dst in dsts:
                if dst not in index:
                    # callee with no service of its own (external endpoint):
                    # not placeable, so it cannot contribute to placement cost
                    continue
                j = index[dst]
                if i != j:
                    adj[i, j] = 1.0
                    adj[j, i] = 1.0
        valid = np.zeros((cap,), dtype=bool)
        valid[:n] = True
        return cls(adj=jnp.asarray(adj), service_valid=jnp.asarray(valid), names=tuple(names))

    def to_relation(self) -> dict[str, list[str]]:
        """Back to the reference's dict form (for oracles and live adapters)."""
        adj = np.asarray(self.adj)
        valid = np.asarray(self.service_valid)
        out: dict[str, list[str]] = {}
        for i, name in enumerate(self.names):
            if not valid[i]:
                continue
            out[name] = [
                self.names[j]
                for j in range(len(self.names))
                if valid[j] and adj[i, j] > 0
            ]
        return out


@struct.dataclass
class ClusterState:
    """Padded array snapshot of a cluster.

    Same information content as the reference's ``cluster_monitoring`` dict
    (reference podmonitor.py:17-37: per-node cpu/mem capacity+usage+pct and
    per-node pod list with per-pod usage and owning deployment), laid out as
    flat arrays keyed by node index and pod index.

    Units follow the reference: CPU in millicores, memory in bytes
    (reference unit_convertion.py:1-32).

    Attributes:
      node_cpu_cap:  f32[N] millicores     (reference get_resource_usage.py:5-16)
      node_mem_cap:  f32[N] bytes
      node_base_cpu: f32[N] millicores of background usage not attributable to
        tracked pods (system daemons; lets derived node usage match a
        metrics-server reading).
      node_base_mem: f32[N] bytes
      node_valid:    bool[N]
      node_lex_rank: i32[N] rank of the node's name in sorted order
        (tie-break parity, see module docstring).
      pod_node:      i32[P] node index or UNASSIGNED.
      pod_service:   i32[P] service index into a CommGraph.
      pod_cpu:       f32[P] millicores    (reference get_resource_usage.py:48-68)
      pod_mem:       f32[P] bytes
      pod_valid:     bool[P]
      node_names / pod_names: static name tuples (host-side bookkeeping only).
    """

    node_cpu_cap: jax.Array
    node_mem_cap: jax.Array
    node_base_cpu: jax.Array
    node_base_mem: jax.Array
    node_valid: jax.Array
    node_lex_rank: jax.Array
    pod_node: jax.Array
    pod_service: jax.Array
    pod_cpu: jax.Array
    pod_mem: jax.Array
    pod_valid: jax.Array
    node_names: tuple[str, ...] = struct.field(pytree_node=False, default=())
    pod_names: tuple[str, ...] = struct.field(pytree_node=False, default=())

    @property
    def num_nodes(self) -> int:
        return int(self.node_cpu_cap.shape[0])

    @property
    def num_pods(self) -> int:
        return int(self.pod_node.shape[0])

    # ---- derived quantities (all jit-able) ----

    def pod_on_node(self) -> jax.Array:
        """bool[P, N] — one-hot of assignment, masked by pod validity."""
        n = self.num_nodes
        return (
            jax.nn.one_hot(self.pod_node, n, dtype=jnp.float32)
            * self.pod_valid[:, None]
        )

    def node_pod_count(self) -> jax.Array:
        """f32[N] — number of valid pods per node (len of the reference's
        per-node pod list, reference rescheduling.py:95)."""
        assign = jnp.where(self.pod_valid, self.pod_node, self.num_nodes)
        counts = jnp.zeros((self.num_nodes + 1,), jnp.float32).at[assign].add(1.0)
        return counts[: self.num_nodes]

    def node_cpu_used(self) -> jax.Array:
        """f32[N] millicores — base + sum of tracked pod CPU."""
        assign = jnp.where(self.pod_valid, self.pod_node, self.num_nodes)
        used = (
            jnp.zeros((self.num_nodes + 1,), jnp.float32)
            .at[assign]
            .add(jnp.where(self.pod_valid, self.pod_cpu, 0.0))
        )
        return self.node_base_cpu + used[: self.num_nodes]

    def node_mem_used(self) -> jax.Array:
        assign = jnp.where(self.pod_valid, self.pod_node, self.num_nodes)
        used = (
            jnp.zeros((self.num_nodes + 1,), jnp.float32)
            .at[assign]
            .add(jnp.where(self.pod_valid, self.pod_mem, 0.0))
        )
        return self.node_base_mem + used[: self.num_nodes]

    def node_cpu_pct(self) -> jax.Array:
        """f32[N] — CPU usage percent, 0 for invalid/zero-cap nodes
        (reference get_resource_usage.py:37)."""
        cap = jnp.where(self.node_cpu_cap > 0, self.node_cpu_cap, 1.0)
        pct = self.node_cpu_used() / cap * 100.0
        return jnp.where(self.node_valid & (self.node_cpu_cap > 0), pct, 0.0)

    def node_mem_pct(self) -> jax.Array:
        cap = jnp.where(self.node_mem_cap > 0, self.node_mem_cap, 1.0)
        pct = self.node_mem_used() / cap * 100.0
        return jnp.where(self.node_valid & (self.node_mem_cap > 0), pct, 0.0)

    def node_cpu_free(self) -> jax.Array:
        """f32[N] millicores remaining — the CAR tie-break quantity
        (reference rescheduling.py:206-208)."""
        return self.node_cpu_cap - self.node_cpu_used()

    def service_node_counts(self, num_services: int) -> jax.Array:
        """f32[S, N] — occupancy matrix: pods of service s on node n.

        The core data structure of the batched solver: built by scatter-add,
        consumed by the affinity matmul ``adj @ occ``.
        """
        n = self.num_nodes
        svc = jnp.where(self.pod_valid, self.pod_service, num_services)
        node = jnp.clip(jnp.where(self.pod_valid, self.pod_node, n), -1, n)
        occ = (
            jnp.zeros((num_services + 1, n + 1), jnp.float32)
            .at[svc, node]
            .add(1.0)
        )
        return occ[:num_services, :n]

    # ---- host-side constructors ----

    @classmethod
    def build(
        cls,
        *,
        node_names: Sequence[str],
        node_cpu_cap: Sequence[float],
        node_mem_cap: Sequence[float],
        pod_services: Sequence[int],
        pod_nodes: Sequence[int],
        pod_cpu: Sequence[float],
        pod_mem: Sequence[float],
        pod_names: Sequence[str] | None = None,
        node_base_cpu: Sequence[float] | None = None,
        node_base_mem: Sequence[float] | None = None,
        node_alive: Sequence[bool] | None = None,
        node_capacity: int | None = None,
        pod_capacity: int | None = None,
    ) -> "ClusterState":
        """Build a padded state from host lists (the adapter's entry point)."""
        n_real = len(node_names)
        p_real = len(pod_services)
        n_cap = node_capacity or n_real
        p_cap = pod_capacity or p_real
        if n_cap < n_real or p_cap < p_real:
            raise ValueError("capacity smaller than real counts")

        def pad(x, cap, fill=0.0, dtype=np.float32):
            a = np.full((cap,), fill, dtype=dtype)
            a[: len(x)] = np.asarray(x, dtype=dtype)
            return a

        order = np.argsort(np.asarray(node_names, dtype=object))
        lex_rank = np.zeros((n_cap,), dtype=np.int32)
        lex_rank[order] = np.arange(n_real, dtype=np.int32)

        node_valid = np.zeros((n_cap,), dtype=bool)
        # a known-but-dead node (failed/cordoned) is not a placement candidate
        node_valid[:n_real] = (
            np.asarray(node_alive, dtype=bool) if node_alive is not None else True
        )
        pod_valid = np.zeros((p_cap,), dtype=bool)
        pod_valid[:p_real] = True

        return cls(
            node_cpu_cap=jnp.asarray(pad(node_cpu_cap, n_cap)),
            node_mem_cap=jnp.asarray(pad(node_mem_cap, n_cap)),
            node_base_cpu=jnp.asarray(
                pad(node_base_cpu if node_base_cpu is not None else [0.0] * n_real, n_cap)
            ),
            node_base_mem=jnp.asarray(
                pad(node_base_mem if node_base_mem is not None else [0.0] * n_real, n_cap)
            ),
            node_valid=jnp.asarray(node_valid),
            node_lex_rank=jnp.asarray(lex_rank),
            pod_node=jnp.asarray(pad(pod_nodes, p_cap, fill=UNASSIGNED, dtype=np.int32)),
            pod_service=jnp.asarray(pad(pod_services, p_cap, fill=0, dtype=np.int32)),
            pod_cpu=jnp.asarray(pad(pod_cpu, p_cap)),
            pod_mem=jnp.asarray(pad(pod_mem, p_cap)),
            pod_valid=jnp.asarray(pod_valid),
            node_names=tuple(node_names),
            pod_names=tuple(pod_names) if pod_names is not None else tuple(f"pod{i}" for i in range(p_real)),
        )
