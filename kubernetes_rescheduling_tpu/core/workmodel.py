"""µBench workmodel parsing.

The reference consumes a µBench ``workmodelC.json`` (20 services s0–s19, each
with an ``external_services`` list of downstream callees and a ``cpu-requests``
quantity) but then *hardcodes* the undirected closure of its call graph in two
places (reference main.py:31-52, communicationcost.py:69-88). Here the
workmodel file is the single source of truth: we parse it into a
:class:`~kubernetes_rescheduling_tpu.core.state.CommGraph` (undirected
closure) plus per-service resource demands.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Mapping

from kubernetes_rescheduling_tpu.core.quantities import cpu_to_millicores, mem_to_bytes
from kubernetes_rescheduling_tpu.core.state import CommGraph


@dataclass(frozen=True)
class ServiceSpec:
    """One service from a workmodel: name, callees, resource requests, replicas."""

    name: str
    callees: tuple[str, ...] = ()
    cpu_request_millicores: int = 100
    mem_request_bytes: int = 0
    replicas: int = 1
    # Relative per-request processing cost, derived from the µBench
    # cpu_stress parameters (reference workmodelC.json:16-24: each request
    # runs `trials` loops at `range_complexity` over `thread_pool_size`
    # threads). 1.0 = the builtin workmodelC loader (complexity 100 ×
    # 10 trials, 1 thread); a service with heavier stress parameters costs
    # proportionally more CPU per request AND takes proportionally longer
    # to answer — consumed by both the simulator's CPU-load model and the
    # request-level load generator, so the two stay consistent.
    proc_cost: float = 1.0


@dataclass(frozen=True)
class Workmodel:
    """Parsed workmodel: ordered services + derived communication graph."""

    services: tuple[ServiceSpec, ...]
    source: str = "<memory>"

    @property
    def names(self) -> tuple[str, ...]:
        return tuple(s.name for s in self.services)

    def directed_relation(self) -> dict[str, list[str]]:
        """The raw (directed) call graph: ``{caller: [callees]}``."""
        return {s.name: list(s.callees) for s in self.services}

    def relation(self) -> dict[str, list[str]]:
        """Undirected closure of the call graph.

        Matches how reference main.py:31-52 closes workmodelC.json's directed
        edges (e.g. the JSON has s0→s1; the dict also lists s0 under s1), with
        each neighbor list ordered by global service index — the ordering of
        the hand-written reference dict.
        """
        rel: dict[str, set[str]] = {s.name: set(s.callees) for s in self.services}
        for s in self.services:
            for callee in s.callees:
                rel.setdefault(callee, set()).add(s.name)
        order = {name: i for i, name in enumerate(self.names)}
        return {
            name: sorted(rel.get(name, ()), key=lambda n: order.get(n, len(order)))
            for name in self.names
        }

    def comm_graph(self, capacity: int | None = None) -> CommGraph:
        return CommGraph.from_relation(
            self.relation(), capacity=capacity, names=list(self.names)
        )

    @classmethod
    def from_dict(cls, data: Mapping[str, Any], source: str = "<memory>") -> "Workmodel":
        """Parse a µBench workmodel dict.

        Grammar (observed in reference workmodelC.json): top level maps
        service name → stanza; ``external_services`` is a list of groups,
        each with a ``services`` list of callee names; ``cpu-requests`` /
        ``memory-requests`` are Kubernetes quantities; ``replicas``
        optional; ``internal_service.loader.cpu_stress`` gives the
        per-request processing parameters (range_complexity, trials,
        thread_pool_size — reference workmodelC.json:16-24), parsed into
        the relative ``proc_cost``.
        """
        services = []
        for name, stanza in data.items():
            if not isinstance(stanza, Mapping):
                continue
            callees: list[str] = []
            for group in stanza.get("external_services", []) or []:
                for callee in group.get("services", []) or []:
                    if callee != name and callee not in callees:
                        callees.append(callee)
            cpu = stanza.get("cpu-requests", "100m")
            mem = stanza.get("memory-requests", "0")
            services.append(
                ServiceSpec(
                    name=name,
                    callees=tuple(callees),
                    cpu_request_millicores=cpu_to_millicores(cpu),
                    mem_request_bytes=mem_to_bytes(mem),
                    replicas=int(stanza.get("replicas", 1)),
                    proc_cost=_parse_proc_cost(stanza),
                )
            )
        return cls(services=tuple(services), source=source)

    @classmethod
    def from_file(cls, path: str | Path) -> "Workmodel":
        p = Path(path)
        return cls.from_dict(json.loads(p.read_text()), source=str(p))


# the builtin workmodelC loader: 100 complexity × 10 trials / 1 thread —
# proc_cost is normalized so that stanza scores 1.0
_BASELINE_STRESS = 100.0 * 10.0


def _parse_proc_cost(stanza: Mapping[str, Any]) -> float:
    """Relative per-request CPU cost from a µBench stanza's cpu_stress.

    ``mean(range_complexity) · trials / thread_pool_size``, normalized to
    the builtin workmodelC loader (= 1.0). A stanza without the loader
    keeps the default 1.0; one whose cpu_stress is disabled (``run:
    false``) gets a small floor (pass-through services still parse and
    serialize requests, they are not free).
    """
    stress = _get_path(stanza, "internal_service", "loader", "cpu_stress")
    if not isinstance(stress, Mapping):
        return 1.0
    if not stress.get("run", True):
        return 0.05
    rc = stress.get("range_complexity", [100, 100]) or [100, 100]
    try:
        complexity = (float(rc[0]) + float(rc[-1])) / 2.0
    except (TypeError, ValueError, IndexError):
        complexity = 100.0
    trials = float(stress.get("trials", 10) or 10)
    threads = max(float(stress.get("thread_pool_size", 1) or 1), 1.0)
    return max(complexity * trials / threads / _BASELINE_STRESS, 0.05)


def _get_path(obj: Any, *names: str):
    for name in names:
        if not isinstance(obj, Mapping):
            return None
        obj = obj.get(name)
    return obj


def kahn_traversal(
    relation: Mapping[str, Sequence[str]], names: Sequence[str]
) -> tuple[list[str], list[tuple[str, str]]]:
    """Cycle-broken topological traversal of a directed call graph.

    Returns ``(order, edges)``: a processing order covering every service,
    and the kept caller→callee edges. Edges that would close a cycle are
    dropped (visit-once on the node at pop time); services left in a cyclic
    remainder are appended in name order with the same edge-keeping rule.
    ``order`` is a valid topological order of the kept edges.

    Single source of truth for *which edges exist* in a cyclic mesh — CPU
    load propagation (``backends.sim.LoadModel.service_rps``) and request
    latency propagation (``bench.loadgen``) both build on it, so the two
    models can never disagree.
    """
    names = list(names)
    index = set(names)
    indeg = {n: 0 for n in names}
    for src, dsts in relation.items():
        for d in dsts:
            if d in indeg:
                indeg[d] += 1
    ready = [n for n in names if indeg[n] == 0]
    order: list[str] = []
    done: set[str] = set()
    edges: list[tuple[str, str]] = []
    while ready:
        svc = ready.pop()
        if svc in done:
            continue
        done.add(svc)
        order.append(svc)
        for callee in relation.get(svc, []):
            if callee not in index or callee in done:
                continue  # cycle-closing edge: drop
            edges.append((svc, callee))
            indeg[callee] -= 1
            if indeg[callee] == 0:
                ready.append(callee)
    for svc in names:  # cyclic remainder (indeg never hit 0), name order
        if svc in done:
            continue
        done.add(svc)
        order.append(svc)
        for callee in relation.get(svc, []):
            if callee in index and callee not in done:
                edges.append((svc, callee))
    return order, edges


def propagate_entry_rate(
    workmodel: "Workmodel",
    *,
    entry_service: str,
    entry_rps: float,
    fanout_frac: float = 1.0,
) -> dict[str, float]:
    """Propagate an entry request rate through the directed call graph:
    each request to a service triggers ``fanout_frac`` requests to each
    callee, accumulated in the cycle-broken topological order of
    :func:`kahn_traversal`.

    THE single source of truth for per-service offered rates — the
    simulator's CPU-load model (``backends.sim.LoadModel.service_rps``)
    and the load generator's autoscaling rate series
    (``bench.loadgen.service_rate_series``) both call it, so traffic and
    autoscaling can never disagree on which services are hot.
    """
    rps = {name: 0.0 for name in workmodel.names}
    if entry_service not in rps:
        return rps
    rps[entry_service] = float(entry_rps)
    order, edges = kahn_traversal(workmodel.directed_relation(), workmodel.names)
    out_edges: dict[str, list[str]] = {}
    for s, d in edges:
        out_edges.setdefault(s, []).append(d)
    for svc in order:
        for callee in out_edges.get(svc, ()):
            rps[callee] += rps[svc] * fanout_frac
    return rps


def mubench_workmodel_c() -> Workmodel:
    """The reference's s0–s19 topology, reconstructed from its call graph.

    This is the *directed* graph whose undirected closure is the dict at
    reference main.py:31-52 (derived from workmodelC.json
    ``external_services``): s0→{s1,s3,s7,s16}, s1→{s2,s4,s13,s15},
    s3→{s5,s6,s8,s9,s12}, s5→s14, s6→{s10,s17}, s7→s19, s9→s11, s15→s18.
    Every service requests 100m CPU (workmodelC.json ``cpu-requests``).
    """
    edges: dict[str, tuple[str, ...]] = {
        "s0": ("s1", "s3", "s7", "s16"),
        "s1": ("s2", "s4", "s13", "s15"),
        "s3": ("s5", "s6", "s8", "s9", "s12"),
        "s5": ("s14",),
        "s6": ("s10", "s17"),
        "s7": ("s19",),
        "s9": ("s11",),
        "s15": ("s18",),
    }
    services = tuple(
        ServiceSpec(
            name=f"s{i}",
            callees=edges.get(f"s{i}", ()),
            cpu_request_millicores=100,
        )
        for i in range(20)
    )
    return Workmodel(services=services, source="builtin:workmodelC")
