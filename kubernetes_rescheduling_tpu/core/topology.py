"""Synthetic topologies and initial cluster states.

Covers the five benchmark configs from BASELINE.json:
  1. µBench workmodelC (s0–s19, 3 worker nodes) — reference-faithful,
  2. dense 200-pod / 20-node random service mesh,
  3. 2k-pod / 200-node power-law microservice DAG,
  4. 10k-pod / 1k-node CPU+mem-constrained bin-packing,
  5. Bookinfo-style trace replay (see ``bench.trace``).

Also provides the **imbalance injector**: the reference creates its "Before"
state by cordoning workers 2–3 so every pod starts on worker1
(reference auto_full_pipeline_repeat.sh:48-51); ``inject_imbalance`` does the
same to an array state.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from kubernetes_rescheduling_tpu.core.state import ClusterState, CommGraph
from kubernetes_rescheduling_tpu.core.workmodel import Workmodel, mubench_workmodel_c


@dataclass(frozen=True)
class Scenario:
    """A ready-to-run benchmark scenario: state + communication graph."""

    name: str
    state: ClusterState
    graph: CommGraph


def state_from_workmodel(
    wm: Workmodel,
    *,
    node_names: list[str] | None = None,
    node_cpu_cap_m: float = 20_000.0,
    node_mem_cap_b: float = 32 * 1024**3,
    pod_cpu_m: float | None = None,
    all_on_node: int | None = None,
    seed: int = 0,
    node_capacity: int | None = None,
    pod_capacity: int | None = None,
) -> ClusterState:
    """Instantiate a cluster state from a workmodel.

    Each service contributes ``replicas`` pods. Placement: uniform random by
    default, or all on one node when ``all_on_node`` is given (the cordon
    trick, reference auto_full_pipeline_repeat.sh:48-51).
    """
    node_names = node_names or ["worker1", "worker2", "worker3"]
    rng = np.random.default_rng(seed)
    services: list[int] = []
    cpus: list[float] = []
    mems: list[float] = []
    pnames: list[str] = []
    for idx, svc in enumerate(wm.services):
        for r in range(svc.replicas):
            services.append(idx)
            cpus.append(float(pod_cpu_m if pod_cpu_m is not None else svc.cpu_request_millicores))
            mems.append(float(svc.mem_request_bytes))
            pnames.append(f"{svc.name}-{r}")
    n_pods = len(services)
    if all_on_node is not None:
        nodes = [all_on_node] * n_pods
    else:
        nodes = rng.integers(0, len(node_names), size=n_pods).tolist()
    return ClusterState.build(
        node_names=node_names,
        node_cpu_cap=[node_cpu_cap_m] * len(node_names),
        node_mem_cap=[node_mem_cap_b] * len(node_names),
        pod_services=services,
        pod_nodes=nodes,
        pod_cpu=cpus,
        pod_mem=mems,
        pod_names=pnames,
        node_capacity=node_capacity,
        pod_capacity=pod_capacity,
    )


def inject_imbalance(state: ClusterState, node_index: int = 0) -> ClusterState:
    """Move every valid pod onto one node — the reference's cordon-induced
    'Before' state (reference auto_full_pipeline_repeat.sh:48-51)."""
    import jax.numpy as jnp

    return state.replace(
        pod_node=jnp.where(state.pod_valid, node_index, state.pod_node)
    )


def mubench_scenario(*, imbalanced: bool = True, seed: int = 0) -> Scenario:
    """Config 1: the reference's own setup — 20 µBench services, 3 workers,
    everything initially on worker1."""
    wm = mubench_workmodel_c()
    state = state_from_workmodel(
        wm,
        all_on_node=0 if imbalanced else None,
        seed=seed,
        # i9-10900K: 20 hyperthreads → 20000m; 32 GB RAM (reference README.md:44-46)
        node_cpu_cap_m=20_000.0,
        node_mem_cap_b=32 * 1024**3,
    )
    return Scenario(name="mubench-workmodelC", state=state, graph=wm.comm_graph())


def _random_workmodel(
    n_services: int,
    rng: np.random.Generator,
    *,
    powerlaw: bool,
    mean_degree: float = 4.0,
    replicas: int = 1,
    cpu_m: int = 100,
) -> Workmodel:
    from kubernetes_rescheduling_tpu.core.workmodel import ServiceSpec

    # Call direction is earlier→later service (each new service i is *called
    # by* k existing services j < i), so s0 is the call-graph root — every
    # service is reachable from the entry, like µBench's s0 fan-out. The
    # undirected closure (what placement cost sees) is unaffected.
    if powerlaw:
        # Barabási–Albert-style preferential attachment → power-law degree DAG.
        # Sampling uniformly from the endpoint list is equivalent to
        # degree-proportional sampling and keeps generation O(n·m) — the
        # 10k-service benchmark topology builds in well under a second.
        m = max(1, int(round(mean_degree / 2)))
        targets: list[list[str]] = [[] for _ in range(n_services)]
        endpoints: list[int] = [0]
        for i in range(1, n_services):
            k = min(i, m)
            picks: set[int] = set()
            draws = rng.integers(0, len(endpoints), size=4 * k + 8)
            for d in draws:
                picks.add(endpoints[d])
                if len(picks) >= k:
                    break
            while len(picks) < k:  # rare fallback: fill uniformly
                picks.add(int(rng.integers(0, i)))
            for j in picks:
                targets[j].append(f"s{i}")
                endpoints.append(j)
                endpoints.append(i)
    else:
        # Dense Erdős–Rényi mesh, plus one guaranteed caller per service so
        # the whole mesh stays reachable from the s0 entry (ER alone leaves
        # a service caller-less with probability (1-p)^i).
        p = min(1.0, mean_degree / max(1, n_services - 1))
        targets = [[] for _ in range(n_services)]
        for i in range(1, n_services):
            called = False
            for j in range(i):
                if rng.random() < p:
                    targets[j].append(f"s{i}")
                    called = True
            if not called:
                targets[int(rng.integers(0, i))].append(f"s{i}")
    services = tuple(
        ServiceSpec(
            name=f"s{i}",
            callees=tuple(targets[i]),
            cpu_request_millicores=cpu_m,
            replicas=replicas,
        )
        for i in range(n_services)
    )
    return Workmodel(services=services, source="synthetic")


def synthetic_scenario(
    *,
    n_pods: int,
    n_nodes: int,
    powerlaw: bool = False,
    replicas: int = 1,
    mean_degree: float = 6.0,
    seed: int = 0,
    imbalance_frac: float = 0.25,
    node_cpu_cap_m: float = 20_000.0,
) -> Scenario:
    """Configs 2–4: synthetic service meshes at increasing scale.

    ``n_pods = n_services * replicas``. Initial placement is random but
    skewed: a fraction of pods is piled on the first node so hazard
    detection has something to do.
    """
    if n_pods % replicas:
        raise ValueError("n_pods must be divisible by replicas")
    n_services = n_pods // replicas
    rng = np.random.default_rng(seed)
    wm = _random_workmodel(
        n_services, rng, powerlaw=powerlaw, mean_degree=mean_degree, replicas=replicas
    )
    node_names = [f"worker{i:04d}" for i in range(n_nodes)]
    state = state_from_workmodel(
        wm,
        node_names=node_names,
        node_cpu_cap_m=node_cpu_cap_m,
        seed=seed,
    )
    if imbalance_frac > 0:
        import jax.numpy as jnp

        k = int(n_pods * imbalance_frac)
        mask = np.zeros(state.num_pods, dtype=bool)
        mask[:k] = True
        state = state.replace(
            pod_node=jnp.where(jnp.asarray(mask), 0, state.pod_node)
        )
    kind = "powerlaw" if powerlaw else "dense"
    return Scenario(name=f"synthetic-{kind}-{n_pods}x{n_nodes}", state=state, graph=wm.comm_graph())


def dense_200x20(seed: int = 0) -> Scenario:
    return synthetic_scenario(n_pods=200, n_nodes=20, powerlaw=False, mean_degree=8.0, seed=seed)


def powerlaw_2000x200(seed: int = 0) -> Scenario:
    return synthetic_scenario(n_pods=2000, n_nodes=200, powerlaw=True, mean_degree=4.0, seed=seed)


def large_10000x1000(seed: int = 0) -> Scenario:
    """Config 4: the north-star scale — 10k pods / 1k nodes with CPU+mem
    headroom tight enough that capacity constraints bind."""
    return synthetic_scenario(
        n_pods=10_000,
        n_nodes=1_000,
        powerlaw=True,
        mean_degree=4.0,
        seed=seed,
        # ~10 pods/node avg at 100m each; 2000m caps keep feasibility tight.
        node_cpu_cap_m=2_000.0,
    )
