"""Array-world cluster state, quantity parsing, workload models, topologies."""

from kubernetes_rescheduling_tpu.core.state import ClusterState, CommGraph
from kubernetes_rescheduling_tpu.core.sparsegraph import SparseCommGraph
from kubernetes_rescheduling_tpu.core.quantities import cpu_to_millicores, mem_to_bytes

__all__ = [
    "ClusterState",
    "CommGraph",
    "SparseCommGraph",
    "cpu_to_millicores",
    "mem_to_bytes",
]
