"""Kubernetes resource-quantity parsing.

Host-side (never traced) parsing of the quantity strings the Kubernetes API
returns for CPU and memory. Semantics match the reference's converters
(reference unit_convertion.py:1-32) on every input the reference handles, and
extend them to the full Kubernetes quantity grammar (decimal SI suffixes,
exponent notation) so a live adapter never crashes on a legal quantity:

- CPU → integer millicores: ``"53m" -> 53``, ``"2" -> 2000``,
  ``"1500000n" -> 2`` (rounded), ``"1500u" -> 2`` (rounded)
  (reference unit_convertion.py:1-13).
- Memory → integer bytes: binary suffixes Ki..Ei
  (reference unit_convertion.py:15-32), plus decimal k/M/G/T/P/E and
  bare/exponent numbers.
"""

from __future__ import annotations

_BINARY_UNITS = {
    "Ki": 1024,
    "Mi": 1024**2,
    "Gi": 1024**3,
    "Ti": 1024**4,
    "Pi": 1024**5,
    "Ei": 1024**6,
}

# Kubernetes decimal SI suffixes (resource.Quantity): lowercase k, uppercase rest.
_DECIMAL_UNITS = {
    "k": 10**3,
    "M": 10**6,
    "G": 10**9,
    "T": 10**12,
    "P": 10**15,
    "E": 10**18,
}


def cpu_to_millicores(cpu: str | int | float) -> int:
    """Parse a CPU quantity into integer millicores.

    Mirrors reference unit_convertion.py:1-13: ``m`` passes through (truncated
    to int), ``n`` divides by 1e6 (rounded), ``u`` divides by 1e3 (rounded),
    a bare number is cores and multiplies by 1000 (rounded).
    """
    s = str(cpu).strip()
    if not s:
        raise ValueError("empty CPU quantity")
    if s.endswith("m"):
        return int(float(s[:-1]))
    if s.endswith("n"):
        return int(round(float(s[:-1]) / 1_000_000))
    if s.endswith("u"):
        return int(round(float(s[:-1]) / 1_000))
    if s.endswith("k"):
        return int(round(float(s[:-1]) * 1_000_000))
    return int(round(float(s) * 1000))


def mem_to_bytes(mem: str | int | float) -> int:
    """Parse a memory quantity into integer bytes.

    Mirrors reference unit_convertion.py:15-32 for the binary suffixes
    (``536Mi`` → bytes); additionally accepts decimal SI suffixes and
    exponent notation, which the Kubernetes API may legally emit.
    """
    s = str(mem).strip()
    if not s:
        raise ValueError("empty memory quantity")
    unit2 = s[-2:]
    if unit2 in _BINARY_UNITS:
        return int(float(s[: -len(unit2)]) * _BINARY_UNITS[unit2])
    unit1 = s[-1:]
    if unit1 in _DECIMAL_UNITS and not s[-1].isdigit():
        return int(float(s[:-1]) * _DECIMAL_UNITS[unit1])
    # metrics-server is known to emit milli/micro-byte quantities for memory
    # (e.g. "3988799488m"); round up to whole bytes.
    if unit1 == "m":
        return int(round(float(s[:-1]) / 1_000))
    if unit1 == "u":
        return int(round(float(s[:-1]) / 1_000_000))
    if unit1 == "n":
        return int(round(float(s[:-1]) / 1_000_000_000))
    return int(float(s))


def format_millicores(m: int | float) -> str:
    """``1234 -> "1234m"`` (reference unit_convertion.py:35-36)."""
    return f"{int(m)}m"


def format_bytes_as_mi(b: int | float) -> str:
    """``b -> "<rounded Mi>Mi"`` (reference unit_convertion.py:38-39)."""
    return f"{int(round(b / (1024 * 1024)))}Mi"
