"""Controller, harness, sinks, CLI — the experiment layer end to end."""

import csv
import json

import numpy as np
import pytest

from kubernetes_rescheduling_tpu.backends.sim import SimBackend
from kubernetes_rescheduling_tpu.bench.controller import run_controller
from kubernetes_rescheduling_tpu.bench.harness import (
    ExperimentConfig,
    make_backend,
    run_experiment,
)
from kubernetes_rescheduling_tpu.bench.loadgen import LoadGenConfig
from kubernetes_rescheduling_tpu.bench.sinks import CsvSink, JsonlSink
from kubernetes_rescheduling_tpu.cli import main as cli_main
from kubernetes_rescheduling_tpu.config import RescheduleConfig
from kubernetes_rescheduling_tpu.core.workmodel import mubench_workmodel_c
from kubernetes_rescheduling_tpu.objectives import communication_cost


def test_headline_bench_env_parsing_names_the_variable(monkeypatch):
    """bench.py's integer env knobs fail with the VARIABLE named instead
    of a bare ValueError traceback (and blank values mean default)."""
    import importlib.util
    import sys
    from pathlib import Path

    path = Path(__file__).resolve().parent.parent / "bench.py"
    spec = importlib.util.spec_from_file_location("headline_bench", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules.setdefault("headline_bench", mod)
    spec.loader.exec_module(mod)

    monkeypatch.delenv("BENCH_RESTARTS", raising=False)
    assert mod._env_int("BENCH_RESTARTS", 1) == 1
    monkeypatch.setenv("BENCH_RESTARTS", "  ")
    assert mod._env_int("BENCH_RESTARTS", 1) == 1
    monkeypatch.setenv("BENCH_RESTARTS", "4")
    assert mod._env_int("BENCH_RESTARTS", 1) == 4
    monkeypatch.setenv("BENCH_RESTARTS", "two")
    with pytest.raises(SystemExit, match="BENCH_RESTARTS.*'two'"):
        mod._env_int("BENCH_RESTARTS", 1)
    monkeypatch.setenv("BENCH_SWEEPS", "9.5")
    with pytest.raises(SystemExit, match="BENCH_SWEEPS"):
        mod._env_int("BENCH_SWEEPS", 9)


def test_controller_greedy_reduces_comm_cost():
    backend = make_backend("mubench", seed=1)
    backend.inject_imbalance("worker1")
    graph = backend.comm_graph()
    before = float(communication_cost(backend.monitor(), graph))
    cfg = RescheduleConfig(
        algorithm="communication", max_rounds=8, sleep_after_action_s=0.0, seed=1
    )
    result = run_controller(backend, cfg)
    assert len(result.rounds) == 8
    assert result.moves >= 1
    assert result.decisions_per_sec > 0
    # moves happened and telemetry recorded the cluster's response
    assert all(r.communication_cost >= 0 for r in result.rounds)


@pytest.mark.slow  # global-through-the-controller stays exercised fast by
# test_telemetry.test_run_controller_global_objectives_surface,
# test_costmodel.test_global_round_captures_solver_cost, and the harness
# matrix's global cells; the never-worse invariant itself is pinned at
# solver level by test_global_solver.test_never_worse_than_input — this
# variant re-proves the composition with its own ~27 s solver compile
def test_controller_global_mode():
    backend = make_backend("mubench", seed=2)
    graph = backend.comm_graph()
    before = float(communication_cost(backend.monitor(), graph))
    cfg = RescheduleConfig(
        algorithm="global", max_rounds=2, sleep_after_action_s=0.0, seed=2
    )
    result = run_controller(backend, cfg)
    after = float(communication_cost(backend.monitor(), graph))
    assert after <= before


def test_harness_matrix(tmp_path):
    # two algorithms cover both controller routes (greedy + global —
    # the global cells are test_controller_global_mode's surviving fast
    # pin) × two repeats for the per-run seeding/aggregate machinery;
    # a third greedy policy re-proves nothing the policy suite doesn't
    cfg = ExperimentConfig(
        algorithms=("communication", "global"),
        repeats=2,
        rounds=3,
        scenario="mubench",
        out_dir=str(tmp_path),
        seed=3,
    )
    summary = run_experiment(cfg)
    assert len(summary["runs"]) == 4
    assert set(summary["aggregate"]) == {"communication", "global"}
    sessions = list(tmp_path.glob("session_*"))
    assert len(sessions) == 1
    run_dir = sessions[0] / "communication" / "run_1"
    assert (run_dir / "node_std.csv").is_file()
    assert (run_dir / "communication_cost.csv").is_file()
    assert (run_dir / "rounds.jsonl").is_file()
    with (run_dir / "node_std.csv").open() as f:
        rows = list(csv.reader(f))
    assert rows[0] == ["timestamp", "cpu_std"]  # reference nodemonitor.py:70
    assert len(rows) == 1 + 1 + 3  # header + before + per-round
    loaded = json.loads((sessions[0] / "summary.json").read_text())
    assert loaded["aggregate"].keys() == summary["aggregate"].keys()


def test_moves_per_round_drains_hazard_faster():
    """k=3 moves per round resolves the pile-up in fewer rounds than the
    reference-faithful one-per-round loop, moving distinct services."""
    def run(k):
        backend = make_backend("mubench", seed=4)
        backend.inject_imbalance("worker1")
        cfg = RescheduleConfig(
            algorithm="communication", max_rounds=6,
            sleep_after_action_s=0.0, moves_per_round=k, seed=4,
        )
        return run_controller(backend, cfg)

    single = run(1)
    multi = run(3)
    n_single = sum(len(r.services_moved) for r in single.rounds)
    n_multi = sum(len(r.services_moved) for r in multi.rounds)
    assert n_multi > n_single
    # a k-round moves distinct deployments
    for r in multi.rounds:
        assert len(set(r.services_moved)) == len(r.services_moved)
        assert len(r.services_moved) <= 3


@pytest.mark.slow  # the global-round machinery this routes into stays pinned fast by test_telemetry.test_run_controller_global_objectives_surface and the harness matrix's global cells; the moves_per_round="all" spelling shares the controller's algorithm=="global" branch and its config acceptance is pinned fast by test_moves_per_round_validation below — this variant re-proves the composition with its own ~20 s solver compile
def test_moves_per_round_all_routes_to_global_solver():
    from kubernetes_rescheduling_tpu.objectives import load_std

    backend = make_backend("mubench", seed=5)
    backend.inject_imbalance("worker1")
    graph = backend.comm_graph()
    lam = 0.5
    st0 = backend.monitor()
    before = float(communication_cost(st0, graph)) + lam * float(load_std(st0))
    cfg = RescheduleConfig(
        algorithm="communication", max_rounds=1,
        sleep_after_action_s=0.0, moves_per_round="all",
        balance_weight=lam, seed=5,
    )
    result = run_controller(backend, cfg)
    st1 = backend.monitor()
    after = float(communication_cost(st1, graph)) + lam * float(load_std(st1))
    # the solver optimizes comm + lambda*std; the piled-up Before state has
    # comm cost 0 by construction, so only the combined objective can drop
    assert after <= before
    # the global solve moves many services at once, beyond any greedy round
    assert len(result.rounds[0].services_moved) > 1


def test_config_from_toml(tmp_path):
    p = tmp_path / "cfg.toml"
    p.write_text(
        'algorithm = "communication"\nmax_rounds = 5\n'
        'moves_per_round = "all"\ncapacity_frac = 0.5\n'
    )
    cfg = RescheduleConfig.from_toml(p)
    assert cfg.max_rounds == 5
    assert cfg.moves_per_round == "all"
    assert cfg.capacity_frac == 0.5
    bad = tmp_path / "bad.toml"
    bad.write_text("nope = 1\n")
    with pytest.raises(ValueError, match="unknown config keys"):
        RescheduleConfig.from_toml(bad)


def test_moves_per_round_validation():
    with pytest.raises(ValueError):
        RescheduleConfig(moves_per_round=0).validate()
    with pytest.raises(ValueError):
        RescheduleConfig(moves_per_round="some").validate()
    RescheduleConfig(moves_per_round="all").validate()
    RescheduleConfig(moves_per_round=4).validate()


def test_harness_reports_request_stats(tmp_path):
    """summary.json carries the reference's client-side stat block
    (release1.sh:74-117): success/error counts, min/avg/max latency,
    restart totals — from simulated requests, per phase."""
    cfg = ExperimentConfig(
        algorithms=("communication",),
        repeats=1,
        rounds=3,
        scenario="mubench",
        out_dir=str(tmp_path),
        seed=7,
        load=LoadGenConfig(requests_per_phase=512, chunk=256),
    )
    summary = run_experiment(cfg)
    run = summary["runs"][0]
    for phase in ("before", "during", "after"):
        stats = run["load"][phase]
        assert stats["sent"] > 0
        assert stats["sent"] == stats["ok"] + stats["errors"]
        assert (
            stats["latency_min_ms"]
            <= stats["latency_avg_ms"]
            <= stats["latency_max_ms"]
        )
    # response_time_ms is now the measured average, not a constant model
    assert run["before"]["response_time_ms"] == run["load"]["before"]["latency_avg_ms"]
    # moves happened -> teardown windows existed -> disruption is accounted
    assert run["load"]["during"]["restarts"] >= run["moves"]
    agg = summary["aggregate"]["communication"]
    assert "error_rate_during" in agg and "restarts" in agg


def test_sinks(tmp_path):
    c = CsvSink(tmp_path / "x.csv", ("timestamp", "v"))
    c.append(1.5)
    c.append(2.5)
    rows = list(csv.reader((tmp_path / "x.csv").open()))
    assert rows[0] == ["timestamp", "v"]
    assert len(rows) == 3
    j = JsonlSink(tmp_path / "x.jsonl")
    j.append({"a": 1})
    assert json.loads((tmp_path / "x.jsonl").read_text()) == {"a": 1}


def test_cli_reschedule(capsys):
    rc = cli_main(
        [
            "reschedule",
            "--algorithm", "car",        # alias accepted (quirk-6 fix)
            "--backend", "sim",
            "--rounds", "2",
            "--seed", "1",
            "--imbalance",
        ]
    )
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["algorithm"] == "communication"
    assert len(out["rounds"]) == 2


def test_cli_solve(capsys):
    rc = cli_main(["solve", "--scenario", "mubench", "--sweeps", "4"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["communication_cost_after"] <= out["communication_cost_before"]
    assert out["restarts"] == 1


@pytest.mark.slow  # the restarts CLI route: plain `solve` stays pinned
# fast by test_cli_solve, and restart selection semantics by
# test_parallel.test_parallel_restarts_beats_or_matches_single — this
# variant only re-proves their composition through argparse (~16 s)
def test_cli_solve_restarts(capsys):
    rc = cli_main(["solve", "--scenario", "mubench", "--sweeps", "4",
                   "--restarts", "4"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["restarts"] == 4
    assert len(out["restart_objectives"]) == 4
    assert out["communication_cost_after"] <= out["communication_cost_before"]


def test_harness_kill_and_resume(tmp_path, monkeypatch):
    """Crash the matrix mid-run, re-invoke with the same session name:
    the finished cell reloads, the crashed cell resumes from its latest
    checkpoint and completes, and decisions match the uninterrupted run
    (VERDICT r1 item 8)."""
    from kubernetes_rescheduling_tpu.backends.sim import SimBackend

    base = dict(
        algorithms=("spread", "communication"),
        repeats=1,
        rounds=4,
        scenario="mubench",
        seed=11,
        load=LoadGenConfig(requests_per_phase=256, chunk=256),
    )

    # uninterrupted reference run (separate dir, same seeds)
    clean = run_experiment(
        ExperimentConfig(out_dir=str(tmp_path / "clean"), **base)
    )

    # crash during the second cell's third move
    calls = {"n": 0}
    real_apply = SimBackend.apply_move

    def crashing_apply(self, move):
        calls["n"] += 1
        if calls["n"] == 7:  # past cell 1 (<=4 moves) and into cell 2
            raise RuntimeError("simulated crash")
        return real_apply(self, move)

    monkeypatch.setattr(SimBackend, "apply_move", crashing_apply)
    cfg = ExperimentConfig(
        out_dir=str(tmp_path / "resumable"), session_name="killtest", **base
    )
    with pytest.raises(RuntimeError, match="simulated crash"):
        run_experiment(cfg)
    monkeypatch.setattr(SimBackend, "apply_move", real_apply)

    # resume: completes, and at least one cell actually resumed mid-loop
    resumed = run_experiment(cfg)
    assert len(resumed["runs"]) == 2
    assert any(r["resumed_from_round"] > 1 for r in resumed["runs"])
    # per-round structured logs exist, including the resume event
    sessions = list((tmp_path / "resumable").glob("session_killtest"))
    assert len(sessions) == 1
    logs = (sessions[0] / "communication" / "run_1" / "log.jsonl").read_text()
    events = [json.loads(l)["event"] for l in logs.splitlines()]
    assert "resume" in events and "round" in events

    # the resumed matrix reaches the same final placements; a resumed cell's
    # own record covers only post-resume rounds, so move counts are compared
    # only for cells that completed before the crash
    for got, exp in zip(resumed["runs"], clean["runs"]):
        assert got["algorithm"] == exp["algorithm"]
        assert got["after"]["communication_cost"] == exp["after"]["communication_cost"]
        assert got["after"]["load_std"] == exp["after"]["load_std"]
        if got["resumed_from_round"] == 0:
            assert got["moves"] == exp["moves"]


def test_cli_workmodel_file_reproduces_builtin(tmp_path, capsys):
    """--workmodel with a µBench-format JSON of the s0-s19 call graph gives
    the same decisions as the builtin topology (reference externalizes the
    workload as workmodelC.json)."""
    wm = mubench_workmodel_c()
    stanza = {
        s.name: {
            "external_services": [{"services": list(s.callees)}],
            "cpu-requests": "100m",
        }
        for s in wm.services
    }
    path = tmp_path / "workmodel.json"
    path.write_text(json.dumps(stanza))

    args = ["reschedule", "--algorithm", "communication", "--backend", "sim",
            "--rounds", "3", "--seed", "9", "--imbalance"]
    assert cli_main(args) == 0
    builtin = json.loads(capsys.readouterr().out)
    assert cli_main(args + ["--workmodel", str(path)]) == 0
    external = json.loads(capsys.readouterr().out)

    timing_fields = {
        "decision_latency_s", "decision_latencies_s", "wall_s", "pipeline",
    }

    def decisions(out):  # strip wall-clock timing, keep every decision
        return [
            {k: v for k, v in r.items() if k not in timing_fields}
            for r in out["rounds"]
        ]

    assert decisions(external) == decisions(builtin)
    assert external["moves"] == builtin["moves"]


def test_cli_trace(capsys):
    rc = cli_main(["trace", "--steps", "5", "--sweeps", "2"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert len(out["steps"]) == 5
    lam = out["balance_weight"]
    # the solver's guarantee is on the COMBINED objective under the new
    # weights (comm + lambda*std); comm alone may trade against balance
    for s in out["steps"]:
        before = s["cost_before_solve"] + lam * s["load_std_before"]
        after = s["cost_after_solve"] + lam * s["load_std_after"]
        assert after <= before + 1e-4


def test_cli_bench(tmp_path, capsys):
    rc = cli_main(
        [
            "bench",
            "--algorithms", "communication",
            "--repeats", "1",
            "--rounds", "2",
            "--out", str(tmp_path),
        ]
    )
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert "aggregate" in out


def test_harness_observe_weights(tmp_path):
    """--observe-weights: phase-r1 traversal counts are persisted and the
    controller receives the traffic-estimated graph."""
    cfg = ExperimentConfig(
        algorithms=("global",),
        repeats=1,
        rounds=2,
        scenario="mubench",
        out_dir=str(tmp_path),
        session_name="obs",
        observe_weights=True,
        seed=4,
    )
    summary = run_experiment(cfg)
    assert len(summary["runs"]) == 1
    phase1 = json.loads(
        (tmp_path / "session_obs" / "global" / "run_1" / "phase1.json").read_text()
    )
    assert phase1["obs_sent"] > 0
    assert phase1["edge_counts"] is not None
    assert sum(phase1["edge_counts"]) > 0
    # resumable: re-running the session reloads the counts without error
    summary2 = run_experiment(cfg)
    assert len(summary2["runs"]) == 1


def test_global_moves_cap_limits_wave_and_converges():
    """V5: global with a wave cap never recreates more than k Deployments
    per round; each wave is jointly-consistent improving moves (so the
    solver objective decreases monotonically round over round), and the
    loop CONVERGES — a final round with an empty wave, because no single
    move helps on its own. The converged point sits at a coarser local
    optimum than the uncapped solve (single-move gain depth cannot see
    pair-dependent improvements; gap measured at 3.0 objective units on
    this instance) — the disruption/quality trade the operator buys with
    the cap."""
    def run(cap, rounds):
        backend = make_backend("mubench", seed=2)
        backend.inject_imbalance("worker1")
        cfg = RescheduleConfig(
            algorithm="global",
            max_rounds=rounds,
            sleep_after_action_s=0.0,
            balance_weight=0.5,
            global_moves_cap=cap,
            seed=2,
        )
        return run_controller(backend, cfg)

    capped = run(2, 12)
    uncapped = run("all", 6)
    assert all(len(r.services_moved) <= 2 for r in capped.rounds)
    assert any(len(r.services_moved) > 2 for r in uncapped.rounds)
    # waves only apply moves that improve the solver objective at the
    # state they are applied in -> monotone descent (comm alone may rise
    # transiently while balance dominates the gain; λ=0.5,
    # capacity_frac=1 so RoundRecord.load_std is the objective's std)
    objs = [r.communication_cost + 0.5 * r.load_std for r in capped.rounds]
    assert all(b <= a + 1e-5 for a, b in zip(objs, objs[1:]))
    # converged: the last waves are empty (no single move helps)
    assert capped.rounds[-1].services_moved == ()
    # and lands within the measured single-move-depth gap of the uncapped
    # final objective
    unc = uncapped.rounds[-1]
    assert objs[-1] <= (
        unc.communication_cost + 0.5 * unc.load_std + 3.5
    )


def test_top_gain_moves_ranks_by_comm_gain():
    """The wave cap picks the moves that individually cut the most
    replica-weighted communication cost."""
    from kubernetes_rescheduling_tpu.bench.controller import _top_gain_moves
    from kubernetes_rescheduling_tpu.core.state import ClusterState, CommGraph

    # a-b heavy edge split across nodes; c-d light edge split; moving a to
    # b's node gains 5, moving c to d's node gains 1
    graph = CommGraph.from_relation(
        {"a": ["b"], "b": ["a"], "c": ["d"], "d": ["c"]},
        names=["a", "b", "c", "d"],
    )
    import jax.numpy as jnp

    graph = graph.replace(adj=graph.adj * jnp.asarray([
        [0.0, 5.0, 0, 0], [5.0, 0, 0, 0], [0, 0, 0, 1.0], [0, 0, 1.0, 0],
    ]))
    state = ClusterState.build(
        node_names=["n0", "n1"],
        node_cpu_cap=[1000.0] * 2,
        node_mem_cap=[2**30] * 2,
        pod_services=[0, 1, 2, 3],
        pod_nodes=[0, 1, 0, 1],
        pod_cpu=[10.0] * 4,
        pod_mem=[0.0] * 4,
        pod_names=["a-0", "b-0", "c-0", "d-0"],
    )
    from kubernetes_rescheduling_tpu.solver import GlobalSolverConfig

    cfg = GlobalSolverConfig(balance_weight=0.0, enforce_capacity=False)
    changed = [(0, 1), (2, 1)]  # move a -> n1 (gain 5), c -> n1 (gain 1)
    top1 = _top_gain_moves(changed, state, graph, cfg, 1)
    assert [(s, t) for s, t, _ in top1] == [(0, 1)]
    # the returned gain is the move's comm cut at its evaluation state —
    # what the global DecisionExplanation records as the candidate score
    assert top1[0][2] == pytest.approx(5.0)
    # non-improving moves are dropped even under the cap: moving b ONTO
    # a's node after a left would cut nothing extra (gain 0 from n1 -> n1
    # is excluded by construction; use a genuinely zero-gain move)
    zero = [(2, 0)]  # c joins a's old node: d stays remote, gain <= 0
    assert _top_gain_moves(zero, state, graph, cfg, 5) == []


@pytest.mark.slow  # the CLI latency-budget autotune route; plain CLI
# global solves stay pinned fast by test_cli_solve/test_cli_solve_restarts
def test_cli_reschedule_budgeted_global(capsys):
    """V7: the live control-loop entry point can use the capacity budget,
    best-of-N restarts, and the wave cap — no longer bench/solve-only."""
    rc = cli_main(
        [
            "reschedule",
            "--algorithm", "global",
            "--backend", "sim",
            "--rounds", "2",
            "--imbalance",
            "--balance-weight", "0.5",
            "--capacity-frac", "0.5",
            "--restarts", "2",
            "--global-moves-cap", "3",
            "--seed", "1",
        ]
    )
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert len(out["rounds"]) == 2
    # the wave cap is honored by every round
    assert all(len(r["services_moved"]) <= 3 for r in out["rounds"])


def test_cli_trace_external_workmodel_and_trace(tmp_path, capsys):
    """V7: replaying an EXTERNAL trace over an EXTERNAL workmodel from the
    CLI (BASELINE config 5 as a usable input, not a closed demo)."""
    wm = {
        "a": {"external_services": [{"services": ["b", "c"]}],
              "cpu-requests": "100m"},
        "b": {"cpu-requests": "100m"},
        "c": {"cpu-requests": "100m"},
    }
    (tmp_path / "wm.json").write_text(json.dumps(wm))
    trace_lines = [
        {"t": 0.0, "weights": [["a", "b", 1.0], ["a", "c", 0.0]]},
        {"t": 1.0, "weights": [["a", "b", 0.0], ["a", "c", 1.0]]},
    ]
    (tmp_path / "trace.jsonl").write_text(
        "\n".join(json.dumps(s) for s in trace_lines)
    )
    rc = cli_main(
        [
            "trace",
            "--workmodel", str(tmp_path / "wm.json"),
            "--trace", str(tmp_path / "trace.jsonl"),
            "--nodes", "2",
            "--sweeps", "3",
            # single solve: THIS pin is the external-file route; restart
            # composition keeps its own pins (test_parallel, and the slow
            # CLI twin test_cli_solve_restarts) — --restarts 2 here only
            # re-paid an extra shard-map compile
            "--seed", "0",
        ]
    )
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["workmodel"].endswith("wm.json")
    assert out["trace"].endswith("trace.jsonl")
    assert len(out["steps"]) == 2
    # the online solver tracks the moving objective: after each step the
    # solved cost is <= the cost the new weights found it at
    for s in out["steps"]:
        assert s["cost_after_solve"] <= s["cost_before_solve"] + 1e-6


def test_observe_weights_streams_per_round(monkeypatch, tmp_path):
    """The decision graph is RE-estimated every round from the accumulated
    traffic (phase r1 + the sustained load), not frozen at phase r1."""
    import kubernetes_rescheduling_tpu.bench.loadgen as lg

    calls = {"n": 0}
    real = lg.LoadGenerator.observed_graph

    def counting(self, counts, sent, base):
        calls["n"] += 1
        return real(self, counts, sent, base)

    monkeypatch.setattr(lg.LoadGenerator, "observed_graph", counting)
    cfg = ExperimentConfig(
        algorithms=("global",),
        repeats=1,
        rounds=3,
        scenario="mubench",
        out_dir=str(tmp_path),
        observe_weights=True,
        seed=5,
    )
    summary = run_experiment(cfg)
    assert len(summary["runs"]) == 1
    # one estimate per round (3), each folding in the traffic so far
    assert calls["n"] >= 3


@pytest.mark.slow  # the sparse ROUTE + per-backend graph cache stay pinned
# fast by test_fleet.py::test_sparse_graph_cache_not_rebuilt_per_round (a
# 2-round sparse controller run counting graph builds), and the improving
# behavior by test_sparse_solver.py::test_sparse_solver_never_worse_and_improves
def test_controller_sparse_backend_routes_and_improves():
    """solver_backend='sparse' drives global rounds through the block-local
    solver (graph cached per backend) with the same improving behavior."""
    from kubernetes_rescheduling_tpu.core.topology import _random_workmodel
    from kubernetes_rescheduling_tpu.objectives import load_std

    rng = np.random.default_rng(5)
    # 300 services / 2 rounds: the pin is the ROUTE (sparse solver +
    # per-backend graph cache + improvement), not scale — the sparse
    # solver's compile dominates this test whatever the problem size, and
    # scale behavior has its own pins in test_sparse_solver
    wm = _random_workmodel(300, rng, powerlaw=True, mean_degree=4.0)
    backend = SimBackend(
        workmodel=wm,
        node_names=[f"w{i}" for i in range(8)],
        node_cpu_cap_m=20_000.0,
        seed=5,
    )
    backend.inject_imbalance("w0")
    graph = backend.comm_graph()
    st0 = backend.monitor()
    before = float(communication_cost(st0, graph)) + 0.5 * float(load_std(st0))
    cfg = RescheduleConfig(
        algorithm="global",
        max_rounds=2,
        sleep_after_action_s=0.0,
        balance_weight=0.5,
        solver_backend="sparse",
        seed=5,
    )
    res = run_controller(backend, cfg)
    assert any(r.services_moved for r in res.rounds)
    # the sparse graph was built once and cached on the backend (the
    # tenant-aware solver-cache slot; tenant None = the solo controller)
    caches = getattr(backend, "_solver_caches", None)
    assert caches is not None and caches[("sparse_graph", None)].get("value") is not None
    # objective (comm + λ·std) improves vs the piled-up Before state
    last = res.rounds[-1]
    assert last.communication_cost + 0.5 * last.load_std < before


def test_config_sparse_composition_rules():
    # sparse composes with restarts, tp, and (round 5) both at once —
    # dp restarts OF tp-sharded sparse solves
    RescheduleConfig(
        algorithm="global", solver_backend="sparse", solver_restarts=2
    ).validate()
    RescheduleConfig(
        algorithm="global", solver_backend="sparse", solver_tp=4
    ).validate()
    RescheduleConfig(
        algorithm="global", solver_backend="sparse",
        solver_restarts=2, solver_tp=4,
    ).validate()
    with pytest.raises(ValueError, match="solver_backend"):
        RescheduleConfig(algorithm="global", solver_backend="bogus").validate()


def test_experiment_config_rejects_invalid_combo_early():
    """Invalid combinations fail at construction, not after minutes of
    phase-r1 load simulation; every (sparse, dp, tp) combination is now a
    supported composition."""
    ExperimentConfig(solver_backend="sparse", solver_restarts=4, solver_tp=2)
    ExperimentConfig(solver_backend="sparse", solver_restarts=4)
    ExperimentConfig(solver_backend="sparse", solver_tp=2)
    with pytest.raises(ValueError, match="placement_unit"):
        ExperimentConfig(placement_unit="bogus")
