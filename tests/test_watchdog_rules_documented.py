"""CI twin of ``scripts/check_watchdog_rules_documented.py``: every
``RULE_*`` constant in the watchdog/SLO modules has a row in
OBSERVABILITY.md's "SLO watchdog" table, and every documented rule is
still registered — plus synthetic drift cases proving the checker bites
in both directions."""

import importlib.util
import sys
from pathlib import Path


def _load_checker():
    path = (
        Path(__file__).resolve().parent.parent
        / "scripts"
        / "check_watchdog_rules_documented.py"
    )
    spec = importlib.util.spec_from_file_location(
        "check_watchdog_rules_documented", path
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules.setdefault("check_watchdog_rules_documented", mod)
    spec.loader.exec_module(mod)
    return mod


DOC = """
## SLO watchdog

| rule | what |
| --- | --- |
| `round_latency_p95` | p95 over threshold |
| `slo_fast_burn` | burn page |

## other section

| `ghost_rule_elsewhere` | rows outside the section never count |
"""

SRC = '''
RULE_LATENCY = "round_latency_p95"
RULE_FAST_BURN = "slo_fast_burn"
NOT_A_RULE = "lowercase_binding_ignored"
'''


def test_checked_in_inventory_is_clean():
    checker = _load_checker()
    assert checker.violations() == []


def test_registered_rules_regex():
    checker = _load_checker()
    assert checker.registered_rules([SRC]) == {
        "round_latency_p95",
        "slo_fast_burn",
    }


def test_documented_rules_scoped_to_section():
    checker = _load_checker()
    assert checker.documented_rules(DOC) == {
        "round_latency_p95",
        "slo_fast_burn",
    }


def test_synthetic_inventory_is_clean():
    checker = _load_checker()
    assert checker.violations(sources=[SRC], doc_text=DOC) == []


def test_undocumented_rule_is_caught():
    checker = _load_checker()
    src = SRC + '\nRULE_NEW = "brand_new_rule"\n'
    bad = checker.violations(sources=[src], doc_text=DOC)
    assert any("brand_new_rule" in v and "no row" in v for v in bad)


def test_ghost_row_is_caught():
    checker = _load_checker()
    doc = DOC.replace(
        "| `slo_fast_burn` | burn page |",
        "| `slo_fast_burn` | burn page |\n| `renamed_away` | ghost |",
    )
    bad = checker.violations(sources=[SRC], doc_text=doc)
    assert any("renamed_away" in v and "ghost row" in v for v in bad)


def test_empty_rule_set_is_a_violation():
    checker = _load_checker()
    bad = checker.violations(sources=["# no constants"], doc_text=DOC)
    assert any("no RULE_* constants" in v for v in bad)
