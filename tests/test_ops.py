"""Fused Pallas chunk-step kernels vs the plain-XLA reference twin.

Runs in Pallas interpret mode on the CPU mesh; the real-TPU path is
exercised by bench.py. Noise is off for parity (the fused path samples the
TPU core PRNG, a different stream by construction)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_rescheduling_tpu.ops import (
    fused_score_admission,
    reference_score_admission,
)


def random_instance(seed, C=64, N=128, tight=False):
    rng = np.random.default_rng(seed)
    M = jnp.asarray(
        rng.integers(0, 6, size=(C, N)).astype(np.float32)
    )  # small-int masses -> frequent exact ties
    cur = jnp.asarray(rng.integers(0, N, size=C), jnp.int32)
    c_cpu = jnp.asarray(rng.integers(1, 5, size=C) * 100.0, jnp.float32)
    c_mem = jnp.asarray(rng.integers(0, 3, size=C) * 1e6, jnp.float32)
    valid_c = jnp.asarray(rng.random(C) < 0.9)
    cap_val = 2_000.0 if tight else 50_000.0
    cap = jnp.full((N,), cap_val, jnp.float32)
    cpu_load = jnp.asarray(rng.uniform(0, cap_val * 0.8, N), jnp.float32)
    mem_cap = jnp.full((N,), 1e9, jnp.float32)
    mem_load = jnp.asarray(rng.uniform(0, 1e8, N), jnp.float32)
    node_valid = jnp.asarray(rng.random(N) < 0.95)
    return (M, cur, c_cpu, c_mem, valid_c, cpu_load, mem_load, cap, mem_cap,
            node_valid)


@pytest.mark.parametrize("seed", range(8))
@pytest.mark.parametrize("tight", [False, True])
# block_c=48 does not divide C=64: exercises the last partial tile, whose
# padding rows must not leak into the accumulated load deltas
@pytest.mark.parametrize("block_c", [32, 48])
def test_fused_matches_reference(seed, tight, block_c):
    args = random_instance(seed, tight=tight)
    got_node, got_adm, x_rows, d_cpu, d_mem = fused_score_admission(
        *args, 0.5, 0.0, seed, interpret=True, block_c=block_c,
        enforce_capacity=True, use_noise=False,
    )
    exp_node, exp_adm = reference_score_admission(
        *args, 0.5, None, enforce_capacity=True
    )
    np.testing.assert_array_equal(np.asarray(got_adm), np.asarray(exp_adm))
    np.testing.assert_array_equal(np.asarray(got_node), np.asarray(exp_node))
    # fused commit outputs: occupancy rows and net per-node load deltas
    (M, cur, c_cpu, c_mem, valid_c, cpu_load, *_rest) = args
    N = M.shape[1]
    exp_rows = jax.nn.one_hot(exp_node, N) * np.asarray(valid_c)[:, None]
    np.testing.assert_array_equal(
        np.asarray(x_rows, dtype=np.float32), np.asarray(exp_rows)
    )
    for got_delta, per_svc in ((d_cpu, c_cpu), (d_mem, c_mem)):
        moved = np.where(np.asarray(exp_adm), np.asarray(per_svc), 0.0)
        exp_d = np.zeros(N)
        np.add.at(exp_d, np.asarray(exp_node), moved)
        np.add.at(exp_d, np.asarray(cur), -moved)
        np.testing.assert_allclose(np.asarray(got_delta), exp_d, atol=1e-4)


@pytest.mark.parametrize("seed", range(4))
def test_fused_overload_term_parity(seed):
    """Over-budget repulsion: loads scaled past capacity so the relu term
    is live, fused vs reference exactly equal."""
    args = list(random_instance(seed, tight=True))
    args[5] = args[5] * 1.6  # cpu_load: push part of the mesh over budget
    got_node, got_adm, *_ = fused_score_admission(
        *args, 0.5, 0.0, seed, overload_weight=10.0,
        interpret=True, block_c=32, enforce_capacity=True, use_noise=False,
    )
    exp_node, exp_adm = reference_score_admission(
        *args, 0.5, None, overload_weight=10.0, enforce_capacity=True
    )
    np.testing.assert_array_equal(np.asarray(got_node), np.asarray(exp_node))
    np.testing.assert_array_equal(np.asarray(got_adm), np.asarray(exp_adm))


def test_fused_no_capacity_mode():
    args = random_instance(3)
    got_node, got_adm, *_ = fused_score_admission(
        *args, 0.0, 0.0, 3, enforce_capacity=False, use_noise=False,
        interpret=True, block_c=32,
    )
    exp_node, exp_adm = reference_score_admission(
        *args, 0.0, None, enforce_capacity=False
    )
    np.testing.assert_array_equal(np.asarray(got_node), np.asarray(exp_node))
    np.testing.assert_array_equal(np.asarray(got_adm), np.asarray(exp_adm))


def test_admission_respects_capacity_race():
    """Two proposals race for one nearly-full node: only the higher-gain
    one lands (the other is deferred)."""
    C, N = 8, 128
    M = jnp.zeros((C, N), jnp.float32)
    # services 0 and 1 both strongly prefer node 5
    M = M.at[0, 5].set(10.0).at[1, 5].set(20.0)
    cur = jnp.asarray([1, 2] + [0] * (C - 2), jnp.int32)
    c_cpu = jnp.full((C,), 300.0)
    c_mem = jnp.zeros((C,))
    valid_c = jnp.asarray([True, True] + [False] * (C - 2))
    cpu_load = jnp.zeros((N,)).at[5].set(500.0)
    cap = jnp.full((N,), 1000.0)  # node 5 fits ONE 300m service, not two
    mem_load = jnp.zeros((N,))
    mem_cap = jnp.full((N,), 1e9)
    node_valid = jnp.ones((N,), bool)
    new_node, admitted, *_ = fused_score_admission(
        M, cur, c_cpu, c_mem, valid_c, cpu_load, mem_load, cap, mem_cap,
        node_valid, 0.0, 0.0, 0,
        enforce_capacity=True, use_noise=False, interpret=True, block_c=8,
    )
    assert bool(admitted[1]) and int(new_node[1]) == 5   # higher gain wins
    assert not bool(admitted[0])                         # loser deferred
    assert int(new_node[0]) == 1                         # stays put


@pytest.mark.slow  # fused-vs-XLA solver parity stays pinned fast by
# test_solver_inline_mass_matches_xla_path (which also asserts the
# inline path actually engaged)
def test_solver_fused_epilogue_matches_xla_path():
    """The whole global solver, fused epilogue (interpret) vs XLA path.

    Per-chunk decisions are exactly equal for equal inputs (the kernel test
    above), but the two paths accumulate load commits in different f32
    association (scatter-add vs tile-reduced deltas), so after the first
    commit an exact ulp-tie could in principle diverge — objectives must
    agree tightly, placements near-identically."""
    from kubernetes_rescheduling_tpu.core.topology import synthetic_scenario
    from kubernetes_rescheduling_tpu.solver import GlobalSolverConfig, global_assign

    scn = synthetic_scenario(n_pods=256, n_nodes=128, seed=9, mean_degree=4.0)
    key = jax.random.PRNGKey(4)
    base = dict(sweeps=3, noise_temp=0.0, balance_weight=0.5)
    st_fused, info_fused = global_assign(
        scn.state, scn.graph, key,
        GlobalSolverConfig(**base, fused_epilogue="interpret"),
    )
    st_xla, info_xla = global_assign(
        scn.state, scn.graph, key,
        GlobalSolverConfig(**base, fused_epilogue="off"),
    )
    same = np.asarray(st_fused.pod_node) == np.asarray(st_xla.pod_node)
    assert same.mean() > 0.99
    assert float(info_fused["objective_after"]) == pytest.approx(
        float(info_xla["objective_after"]), rel=1e-3
    )


def test_fused_neighbor_mass_matches_matmul():
    """The inline-mass kernel (W row-blocks gathered by id, occupancy
    regenerated in VMEM) equals the materialized-X matmul for arbitrary
    block compositions."""
    from kubernetes_rescheduling_tpu.ops.fused_admission import fused_neighbor_mass

    rng = np.random.default_rng(0)
    SP, N, B = 128, 64, 16
    W = jnp.asarray(
        rng.integers(0, 5, size=(SP, SP)).astype(np.float32)
    ).astype(jnp.bfloat16)
    assign = jnp.asarray(rng.integers(0, N, size=SP), jnp.int32)
    valid = jnp.asarray(rng.random(SP) < 0.9)
    X = jax.nn.one_hot(assign, N, dtype=jnp.bfloat16) * valid[:, None]
    for blocks in ([0, 1], [7, 2], [3, 0, 5, 6]):
        ids = (np.asarray(blocks)[:, None] * B + np.arange(B)[None, :]).reshape(-1)
        got = fused_neighbor_mass(
            W, assign, valid, jnp.asarray(blocks, jnp.int32),
            num_nodes=N, block_b=B, block_j=32, interpret=True,
        )
        want = jnp.matmul(W[ids], X, preferred_element_type=jnp.float32)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want))


def test_solver_inline_mass_matches_xla_path():
    """The no-occupancy-matrix fused path (inline mass kernel + x_rows-free
    admission + loads carried across sweeps) vs the XLA path: same perm and
    chunk keys, M exact for integer weights — placements must agree
    near-identically, objectives tightly."""
    from kubernetes_rescheduling_tpu.core.topology import synthetic_scenario
    from kubernetes_rescheduling_tpu.solver import GlobalSolverConfig, global_assign

    scn = synthetic_scenario(n_pods=256, n_nodes=128, seed=9, mean_degree=4.0)
    key = jax.random.PRNGKey(4)
    # chunk_size=256 makes C and SP multiples of the 256 composition block,
    # so the interpret run takes the inline-mass sweep (asserted via
    # objective agreement with the XLA path, which is
    # chunk-composition-identical)
    base = dict(sweeps=3, noise_temp=0.0, balance_weight=0.5, chunk_size=256)
    st_fused, info_fused = global_assign(
        scn.state, scn.graph, key,
        GlobalSolverConfig(**base, fused_epilogue="interpret"),
    )
    # guard against silent fallback: if a gate change stops the inline path
    # from engaging here, this test would quietly re-test the materialized
    # path and the production inline sweep would ship uncovered
    assert bool(info_fused["inline_mass"])
    st_xla, info_xla = global_assign(
        scn.state, scn.graph, key,
        GlobalSolverConfig(**base, fused_epilogue="off"),
    )
    assert not bool(info_xla["inline_mass"])
    same = np.asarray(st_fused.pod_node) == np.asarray(st_xla.pod_node)
    assert same.mean() > 0.99
    assert float(info_fused["objective_after"]) == pytest.approx(
        float(info_xla["objective_after"]), rel=1e-3
    )


def test_fused_noise_is_deterministic_per_seed():
    """TPU-only: the annealing-noise branch (what production 'auto' mode
    runs). The TPU core PRNG has no interpret lowering on ANY platform, so
    this must compile for real (bench.py exercises it at scale too)."""
    if jax.devices()[0].platform != "tpu":
        pytest.skip("TPU core PRNG needs a real TPU (no interpret lowering)")
    args = random_instance(5)
    kw = dict(enforce_capacity=True, use_noise=True, interpret=False, block_c=32)
    a1 = fused_score_admission(*args, 0.5, 1.0, 42, **kw)
    a2 = fused_score_admission(*args, 0.5, 1.0, 42, **kw)
    b = fused_score_admission(*args, 0.5, 1.0, 43, **kw)
    np.testing.assert_array_equal(np.asarray(a1[0]), np.asarray(a2[0]))
    assert not np.array_equal(np.asarray(a1[0]), np.asarray(b[0])) or not (
        np.array_equal(np.asarray(a1[1]), np.asarray(b[1]))
    )


def _sparse_chunk_instance():
    """One two-regular-block sparse chunk with all the score-stage
    operands — shared by the bit-parity test and the noise seed-offset-law
    tests. Returns a namespace of arrays plus the chunk geometry."""
    import types

    from kubernetes_rescheduling_tpu.core import sparsegraph
    from kubernetes_rescheduling_tpu.core.sparsegraph import BLOCK_R
    from kubernetes_rescheduling_tpu.core.topology import synthetic_scenario
    from kubernetes_rescheduling_tpu.ops.sparse_mass import chunk_local_slabs

    scn = synthetic_scenario(n_pods=1024, n_nodes=128, powerlaw=True, seed=5)
    adj = np.asarray(scn.graph.adj)
    iu, ju = np.nonzero(np.triu(adj, k=1))
    sg = sparsegraph.from_edges(
        iu, ju, adj[iu, ju], adj.shape[0], names=scn.graph.names,
        bu=128, reg_tiles=4,
    )
    rng = np.random.default_rng(0)
    SP, N = sg.sp, 128
    KB = 2
    blocks = jnp.asarray(sg.regular_blocks[:KB], jnp.int32)
    ids = (np.asarray(blocks)[:, None] * BLOCK_R + np.arange(BLOCK_R)).reshape(-1)
    C = KB * BLOCK_R
    assign = jnp.asarray(rng.integers(0, N, size=SP), jnp.int32)
    rv = jnp.asarray(rng.integers(1, 3, size=SP).astype(np.float32))
    rvu = jnp.where(sg.u_ids < SP, rv[jnp.clip(sg.u_ids, 0, SP - 1)], 0.0)
    w_mm = sg.w_local.astype(jnp.float32)
    toff = jnp.asarray(sg.block_toff, jnp.int32)
    starts = toff[blocks] * sg.bu
    u_c, rvu_c = chunk_local_slabs(sg.u_ids, rvu, starts, sg.u_reg)
    tgt_c = assign[jnp.clip(u_c, 0, SP - 1)]
    return types.SimpleNamespace(
        sg=sg, blocks=blocks, ids=ids, C=C, N=N, BLOCK_R=BLOCK_R,
        assign=assign, rv=rv, w_mm=w_mm, toff=toff, tgt_c=tgt_c, rvu_c=rvu_c,
        cur=assign[jnp.asarray(ids)],
        c_cpu=jnp.asarray(rng.integers(1, 5, size=C) * 10.0, jnp.float32),
        c_mem=jnp.zeros((C,), jnp.float32),
        valid_c=jnp.asarray(rng.random(C) < 0.9),
        cap=jnp.full((N,), 900.0, jnp.float32),
        cpu_load=jnp.asarray(rng.uniform(0, 800.0, N), jnp.float32),
        mem_cap=jnp.full((N,), 1e9, jnp.float32),
        mem_load=jnp.zeros((N,), jnp.float32),
        node_valid=jnp.asarray(rng.random(N) < 0.95),
        rng=rng,
    )


def test_sparse_mass_score_matches_two_kernel_path():
    """The round-5 fused mass+score kernel (one launch, M in VMEM
    scratch) must reproduce the two-kernel path bit for bit: same mass
    accumulation order, same shared score_core, fed through the same
    admission stage."""
    from kubernetes_rescheduling_tpu.ops.fused_admission import admission_stage
    from kubernetes_rescheduling_tpu.ops.sparse_mass import (
        sparse_mass_score,
        sparse_neighbor_mass,
    )

    inst = _sparse_chunk_instance()
    sg, blocks, ids, C, N = inst.sg, inst.blocks, inst.ids, inst.C, inst.N
    assign, rv, w_mm, toff = inst.assign, inst.rv, inst.w_mm, inst.toff
    tgt_c, rvu_c = inst.tgt_c, inst.rvu_c
    cur, c_cpu, c_mem, valid_c = inst.cur, inst.c_cpu, inst.c_mem, inst.valid_c
    cap, cpu_load = inst.cap, inst.cpu_load
    mem_cap, mem_load, node_valid = inst.mem_cap, inst.mem_load, inst.node_valid
    rng = inst.rng
    lam = 0.5

    for mc_pen in (None, jnp.asarray(rng.random(C), jnp.float32)):
        home = cur if mc_pen is None else jnp.asarray(
            rng.integers(0, N, size=C), jnp.int32
        )
        # two-kernel path: mass kernel -> HBM -> score+admission
        M = sparse_neighbor_mass(
            w_mm, tgt_c, rvu_c, blocks, toff,
            num_nodes=N, bu=sg.bu, reg_tiles=sg.reg_tiles, interpret=True,
        ) * rv[jnp.asarray(ids)][:, None]
        exp_node, exp_adm, exp_dc, exp_dm = fused_score_admission(
            M, cur, c_cpu, c_mem, valid_c,
            cpu_load, mem_load, cap, mem_cap, node_valid,
            lam, 0.0, 0,
            overload_weight=10.0, home=home, move_pen=mc_pen,
            enforce_capacity=True, use_noise=False, interpret=True,
            emit_x_rows=False,
        )
        # fused path: mass accumulated in VMEM scratch, scored in-kernel
        prop, gain, wants, s_cpu, s_mem = sparse_mass_score(
            w_mm, tgt_c, rvu_c, blocks, toff, rv[jnp.asarray(ids)],
            cur, home, mc_pen, c_cpu, c_mem, valid_c,
            cpu_load, mem_load, cap, mem_cap, node_valid,
            lam, 0.0, 0, 10.0,
            num_nodes=N, bu=sg.bu, reg_tiles=sg.reg_tiles,
            enforce_capacity=True, use_noise=False, interpret=True,
        )
        got_node, got_adm, got_dc, got_dm = admission_stage(
            prop, gain, wants, s_cpu, s_mem, cur, valid_c, c_cpu, c_mem,
            num_nodes=N, enforce_capacity=True, interpret=True,
            emit_x_rows=False,
        )
        np.testing.assert_array_equal(np.asarray(got_node), np.asarray(exp_node))
        np.testing.assert_array_equal(np.asarray(got_adm), np.asarray(exp_adm))
        np.testing.assert_array_equal(np.asarray(got_dc), np.asarray(exp_dc))
        np.testing.assert_array_equal(np.asarray(got_dm), np.asarray(exp_dm))


def _noise_paths(inst, seed, *, block_c, noise_impl="stateless", temp=0.7):
    """(fused mass+score, two-kernel) outputs for the SAME chunk with
    annealing noise ON — the cross-lowering stream comparison."""
    from kubernetes_rescheduling_tpu.ops.fused_admission import admission_stage
    from kubernetes_rescheduling_tpu.ops.sparse_mass import (
        sparse_mass_score,
        sparse_neighbor_mass,
    )

    sg = inst.sg
    common = dict(
        enforce_capacity=True, use_noise=True, interpret=True,
        noise_impl=noise_impl,
    )
    prop, gain, wants, s_cpu, s_mem = sparse_mass_score(
        inst.w_mm, inst.tgt_c, inst.rvu_c, inst.blocks, inst.toff,
        inst.rv[jnp.asarray(inst.ids)],
        inst.cur, inst.cur, None, inst.c_cpu, inst.c_mem, inst.valid_c,
        inst.cpu_load, inst.mem_load, inst.cap, inst.mem_cap,
        inst.node_valid,
        0.5, temp, seed, 10.0,
        num_nodes=inst.N, bu=sg.bu, reg_tiles=sg.reg_tiles, **common,
    )
    fused = admission_stage(
        prop, gain, wants, s_cpu, s_mem, inst.cur, inst.valid_c,
        inst.c_cpu, inst.c_mem,
        num_nodes=inst.N, enforce_capacity=True, interpret=True,
        emit_x_rows=False,
    )
    M = sparse_neighbor_mass(
        inst.w_mm, inst.tgt_c, inst.rvu_c, inst.blocks, inst.toff,
        num_nodes=inst.N, bu=sg.bu, reg_tiles=sg.reg_tiles, interpret=True,
    ) * inst.rv[jnp.asarray(inst.ids)][:, None]
    two_kernel = fused_score_admission(
        M, inst.cur, inst.c_cpu, inst.c_mem, inst.valid_c,
        inst.cpu_load, inst.mem_load, inst.cap, inst.mem_cap,
        inst.node_valid,
        0.5, temp, seed,
        overload_weight=10.0, block_c=block_c, emit_x_rows=False, **common,
    )
    return fused, two_kernel


def test_sparse_mass_score_noise_seed_law_interpret():
    """NOISE-ON cross-lowering parity (the ADVICE round-5 gap): the fused
    mass+score kernel offsets its PRNG seed by the BLOCK_R-row block
    index, the standalone score kernel by program_id over block_c-row
    tiles. With block_c == BLOCK_R and the same base seed the streams
    coincide — bit-identical decisions; any other tiling de-synchronizes
    them. The TPU core PRNG has no interpret lowering, so this locks the
    seed-offset LAW via the stateless noise impl (same offset plumbing,
    interpret-safe); the hardware stream itself is pinned by the
    TPU-gated variant below."""
    inst = _sparse_chunk_instance()
    fused, aligned = _noise_paths(inst, seed=123, block_c=inst.BLOCK_R)
    for got, exp in zip(fused, aligned):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(exp))
    # same seed, same path: deterministic
    fused2, _ = _noise_paths(inst, seed=123, block_c=inst.BLOCK_R)
    np.testing.assert_array_equal(np.asarray(fused[0]), np.asarray(fused2[0]))
    # a different base seed draws a different stream (decisions shift)
    fused3, _ = _noise_paths(inst, seed=124, block_c=inst.BLOCK_R)
    assert not np.array_equal(np.asarray(fused[0]), np.asarray(fused3[0]))
    # and a mis-tiled score kernel (block_c != BLOCK_R) breaks the law:
    # program_id advances twice per 256 rows, so block 1's rows see a
    # different seed offset than the fused kernel gave them
    _, misaligned = _noise_paths(inst, seed=123, block_c=inst.BLOCK_R // 2)
    assert not np.array_equal(np.asarray(fused[0]), np.asarray(misaligned[0]))


def test_sparse_mass_score_noise_seed_law_tpu():
    """TPU-only twin: the same seed-offset law under the real TPU core
    PRNG (compiled, not interpret)."""
    if jax.devices()[0].platform != "tpu":
        pytest.skip("TPU core PRNG needs a real TPU (no interpret lowering)")
    inst = _sparse_chunk_instance()

    from kubernetes_rescheduling_tpu.ops.fused_admission import admission_stage
    from kubernetes_rescheduling_tpu.ops.sparse_mass import (
        sparse_mass_score,
        sparse_neighbor_mass,
    )

    sg = inst.sg
    common = dict(enforce_capacity=True, use_noise=True, interpret=False)
    prop, gain, wants, s_cpu, s_mem = sparse_mass_score(
        inst.w_mm, inst.tgt_c, inst.rvu_c, inst.blocks, inst.toff,
        inst.rv[jnp.asarray(inst.ids)],
        inst.cur, inst.cur, None, inst.c_cpu, inst.c_mem, inst.valid_c,
        inst.cpu_load, inst.mem_load, inst.cap, inst.mem_cap,
        inst.node_valid,
        0.5, 0.7, 42, 10.0,
        num_nodes=inst.N, bu=sg.bu, reg_tiles=sg.reg_tiles, **common,
    )
    fused = admission_stage(
        prop, gain, wants, s_cpu, s_mem, inst.cur, inst.valid_c,
        inst.c_cpu, inst.c_mem,
        num_nodes=inst.N, enforce_capacity=True, interpret=False,
        emit_x_rows=False,
    )
    M = sparse_neighbor_mass(
        inst.w_mm, inst.tgt_c, inst.rvu_c, inst.blocks, inst.toff,
        num_nodes=inst.N, bu=sg.bu, reg_tiles=sg.reg_tiles, interpret=False,
    ) * inst.rv[jnp.asarray(inst.ids)][:, None]
    two_kernel = fused_score_admission(
        M, inst.cur, inst.c_cpu, inst.c_mem, inst.valid_c,
        inst.cpu_load, inst.mem_load, inst.cap, inst.mem_cap,
        inst.node_valid,
        0.5, 0.7, 42,
        overload_weight=10.0, block_c=inst.BLOCK_R, emit_x_rows=False,
        **common,
    )
    for got, exp in zip(fused, two_kernel):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(exp))


def test_solver_score_block_pins_seed_offset_law():
    """The production guard the ADVICE asked for: the sparse solver's
    score-kernel tile size must equal BLOCK_R, or noise-on decisions
    would silently diverge between its two lowerings of the same sweep."""
    from kubernetes_rescheduling_tpu.core.sparsegraph import BLOCK_R
    from kubernetes_rescheduling_tpu.solver import sparse_solver

    assert sparse_solver._SCORE_BLOCK_C == BLOCK_R
