"""Fused Pallas chunk-step kernels vs the plain-XLA reference twin.

Runs in Pallas interpret mode on the CPU mesh; the real-TPU path is
exercised by bench.py. Noise is off for parity (the fused path samples the
TPU core PRNG, a different stream by construction)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_rescheduling_tpu.ops import (
    fused_score_admission,
    reference_score_admission,
)


def random_instance(seed, C=64, N=128, tight=False):
    rng = np.random.default_rng(seed)
    M = jnp.asarray(
        rng.integers(0, 6, size=(C, N)).astype(np.float32)
    )  # small-int masses -> frequent exact ties
    cur = jnp.asarray(rng.integers(0, N, size=C), jnp.int32)
    c_cpu = jnp.asarray(rng.integers(1, 5, size=C) * 100.0, jnp.float32)
    c_mem = jnp.asarray(rng.integers(0, 3, size=C) * 1e6, jnp.float32)
    valid_c = jnp.asarray(rng.random(C) < 0.9)
    cap_val = 2_000.0 if tight else 50_000.0
    cap = jnp.full((N,), cap_val, jnp.float32)
    cpu_load = jnp.asarray(rng.uniform(0, cap_val * 0.8, N), jnp.float32)
    mem_cap = jnp.full((N,), 1e9, jnp.float32)
    mem_load = jnp.asarray(rng.uniform(0, 1e8, N), jnp.float32)
    node_valid = jnp.asarray(rng.random(N) < 0.95)
    return (M, cur, c_cpu, c_mem, valid_c, cpu_load, mem_load, cap, mem_cap,
            node_valid)


@pytest.mark.parametrize("seed", range(8))
@pytest.mark.parametrize("tight", [False, True])
# block_c=48 does not divide C=64: exercises the last partial tile, whose
# padding rows must not leak into the accumulated load deltas
@pytest.mark.parametrize("block_c", [32, 48])
def test_fused_matches_reference(seed, tight, block_c):
    args = random_instance(seed, tight=tight)
    got_node, got_adm, x_rows, d_cpu, d_mem = fused_score_admission(
        *args, 0.5, 0.0, seed, interpret=True, block_c=block_c,
        enforce_capacity=True, use_noise=False,
    )
    exp_node, exp_adm = reference_score_admission(
        *args, 0.5, None, enforce_capacity=True
    )
    np.testing.assert_array_equal(np.asarray(got_adm), np.asarray(exp_adm))
    np.testing.assert_array_equal(np.asarray(got_node), np.asarray(exp_node))
    # fused commit outputs: occupancy rows and net per-node load deltas
    (M, cur, c_cpu, c_mem, valid_c, cpu_load, *_rest) = args
    N = M.shape[1]
    exp_rows = jax.nn.one_hot(exp_node, N) * np.asarray(valid_c)[:, None]
    np.testing.assert_array_equal(
        np.asarray(x_rows, dtype=np.float32), np.asarray(exp_rows)
    )
    for got_delta, per_svc in ((d_cpu, c_cpu), (d_mem, c_mem)):
        moved = np.where(np.asarray(exp_adm), np.asarray(per_svc), 0.0)
        exp_d = np.zeros(N)
        np.add.at(exp_d, np.asarray(exp_node), moved)
        np.add.at(exp_d, np.asarray(cur), -moved)
        np.testing.assert_allclose(np.asarray(got_delta), exp_d, atol=1e-4)


@pytest.mark.parametrize("seed", range(4))
def test_fused_overload_term_parity(seed):
    """Over-budget repulsion: loads scaled past capacity so the relu term
    is live, fused vs reference exactly equal."""
    args = list(random_instance(seed, tight=True))
    args[5] = args[5] * 1.6  # cpu_load: push part of the mesh over budget
    got_node, got_adm, *_ = fused_score_admission(
        *args, 0.5, 0.0, seed, overload_weight=10.0,
        interpret=True, block_c=32, enforce_capacity=True, use_noise=False,
    )
    exp_node, exp_adm = reference_score_admission(
        *args, 0.5, None, overload_weight=10.0, enforce_capacity=True
    )
    np.testing.assert_array_equal(np.asarray(got_node), np.asarray(exp_node))
    np.testing.assert_array_equal(np.asarray(got_adm), np.asarray(exp_adm))


def test_fused_no_capacity_mode():
    args = random_instance(3)
    got_node, got_adm, *_ = fused_score_admission(
        *args, 0.0, 0.0, 3, enforce_capacity=False, use_noise=False,
        interpret=True, block_c=32,
    )
    exp_node, exp_adm = reference_score_admission(
        *args, 0.0, None, enforce_capacity=False
    )
    np.testing.assert_array_equal(np.asarray(got_node), np.asarray(exp_node))
    np.testing.assert_array_equal(np.asarray(got_adm), np.asarray(exp_adm))


def test_admission_respects_capacity_race():
    """Two proposals race for one nearly-full node: only the higher-gain
    one lands (the other is deferred)."""
    C, N = 8, 128
    M = jnp.zeros((C, N), jnp.float32)
    # services 0 and 1 both strongly prefer node 5
    M = M.at[0, 5].set(10.0).at[1, 5].set(20.0)
    cur = jnp.asarray([1, 2] + [0] * (C - 2), jnp.int32)
    c_cpu = jnp.full((C,), 300.0)
    c_mem = jnp.zeros((C,))
    valid_c = jnp.asarray([True, True] + [False] * (C - 2))
    cpu_load = jnp.zeros((N,)).at[5].set(500.0)
    cap = jnp.full((N,), 1000.0)  # node 5 fits ONE 300m service, not two
    mem_load = jnp.zeros((N,))
    mem_cap = jnp.full((N,), 1e9)
    node_valid = jnp.ones((N,), bool)
    new_node, admitted, *_ = fused_score_admission(
        M, cur, c_cpu, c_mem, valid_c, cpu_load, mem_load, cap, mem_cap,
        node_valid, 0.0, 0.0, 0,
        enforce_capacity=True, use_noise=False, interpret=True, block_c=8,
    )
    assert bool(admitted[1]) and int(new_node[1]) == 5   # higher gain wins
    assert not bool(admitted[0])                         # loser deferred
    assert int(new_node[0]) == 1                         # stays put


def test_solver_fused_epilogue_matches_xla_path():
    """The whole global solver, fused epilogue (interpret) vs XLA path.

    Per-chunk decisions are exactly equal for equal inputs (the kernel test
    above), but the two paths accumulate load commits in different f32
    association (scatter-add vs tile-reduced deltas), so after the first
    commit an exact ulp-tie could in principle diverge — objectives must
    agree tightly, placements near-identically."""
    from kubernetes_rescheduling_tpu.core.topology import synthetic_scenario
    from kubernetes_rescheduling_tpu.solver import GlobalSolverConfig, global_assign

    scn = synthetic_scenario(n_pods=256, n_nodes=128, seed=9, mean_degree=4.0)
    key = jax.random.PRNGKey(4)
    base = dict(sweeps=3, noise_temp=0.0, balance_weight=0.5)
    st_fused, info_fused = global_assign(
        scn.state, scn.graph, key,
        GlobalSolverConfig(**base, fused_epilogue="interpret"),
    )
    st_xla, info_xla = global_assign(
        scn.state, scn.graph, key,
        GlobalSolverConfig(**base, fused_epilogue="off"),
    )
    same = np.asarray(st_fused.pod_node) == np.asarray(st_xla.pod_node)
    assert same.mean() > 0.99
    assert float(info_fused["objective_after"]) == pytest.approx(
        float(info_xla["objective_after"]), rel=1e-3
    )


def test_fused_neighbor_mass_matches_matmul():
    """The inline-mass kernel (W row-blocks gathered by id, occupancy
    regenerated in VMEM) equals the materialized-X matmul for arbitrary
    block compositions."""
    from kubernetes_rescheduling_tpu.ops.fused_admission import fused_neighbor_mass

    rng = np.random.default_rng(0)
    SP, N, B = 128, 64, 16
    W = jnp.asarray(
        rng.integers(0, 5, size=(SP, SP)).astype(np.float32)
    ).astype(jnp.bfloat16)
    assign = jnp.asarray(rng.integers(0, N, size=SP), jnp.int32)
    valid = jnp.asarray(rng.random(SP) < 0.9)
    X = jax.nn.one_hot(assign, N, dtype=jnp.bfloat16) * valid[:, None]
    for blocks in ([0, 1], [7, 2], [3, 0, 5, 6]):
        ids = (np.asarray(blocks)[:, None] * B + np.arange(B)[None, :]).reshape(-1)
        got = fused_neighbor_mass(
            W, assign, valid, jnp.asarray(blocks, jnp.int32),
            num_nodes=N, block_b=B, block_j=32, interpret=True,
        )
        want = jnp.matmul(W[ids], X, preferred_element_type=jnp.float32)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want))


def test_solver_inline_mass_matches_xla_path():
    """The no-occupancy-matrix fused path (inline mass kernel + x_rows-free
    admission + loads carried across sweeps) vs the XLA path: same perm and
    chunk keys, M exact for integer weights — placements must agree
    near-identically, objectives tightly."""
    from kubernetes_rescheduling_tpu.core.topology import synthetic_scenario
    from kubernetes_rescheduling_tpu.solver import GlobalSolverConfig, global_assign

    scn = synthetic_scenario(n_pods=256, n_nodes=128, seed=9, mean_degree=4.0)
    key = jax.random.PRNGKey(4)
    # chunk_size=256 makes C and SP multiples of the 256 composition block,
    # so the interpret run takes the inline-mass sweep (asserted via
    # objective agreement with the XLA path, which is
    # chunk-composition-identical)
    base = dict(sweeps=3, noise_temp=0.0, balance_weight=0.5, chunk_size=256)
    st_fused, info_fused = global_assign(
        scn.state, scn.graph, key,
        GlobalSolverConfig(**base, fused_epilogue="interpret"),
    )
    # guard against silent fallback: if a gate change stops the inline path
    # from engaging here, this test would quietly re-test the materialized
    # path and the production inline sweep would ship uncovered
    assert bool(info_fused["inline_mass"])
    st_xla, info_xla = global_assign(
        scn.state, scn.graph, key,
        GlobalSolverConfig(**base, fused_epilogue="off"),
    )
    assert not bool(info_xla["inline_mass"])
    same = np.asarray(st_fused.pod_node) == np.asarray(st_xla.pod_node)
    assert same.mean() > 0.99
    assert float(info_fused["objective_after"]) == pytest.approx(
        float(info_xla["objective_after"]), rel=1e-3
    )


def test_fused_noise_is_deterministic_per_seed():
    """TPU-only: the annealing-noise branch (what production 'auto' mode
    runs). The TPU core PRNG has no interpret lowering on ANY platform, so
    this must compile for real (bench.py exercises it at scale too)."""
    if jax.devices()[0].platform != "tpu":
        pytest.skip("TPU core PRNG needs a real TPU (no interpret lowering)")
    args = random_instance(5)
    kw = dict(enforce_capacity=True, use_noise=True, interpret=False, block_c=32)
    a1 = fused_score_admission(*args, 0.5, 1.0, 42, **kw)
    a2 = fused_score_admission(*args, 0.5, 1.0, 42, **kw)
    b = fused_score_admission(*args, 0.5, 1.0, 43, **kw)
    np.testing.assert_array_equal(np.asarray(a1[0]), np.asarray(a2[0]))
    assert not np.array_equal(np.asarray(a1[0]), np.asarray(b[0])) or not (
        np.array_equal(np.asarray(a1[1]), np.asarray(b[1]))
    )


def test_sparse_mass_score_matches_two_kernel_path():
    """The round-5 fused mass+score kernel (one launch, M in VMEM
    scratch) must reproduce the two-kernel path bit for bit: same mass
    accumulation order, same shared score_core, fed through the same
    admission stage."""
    from kubernetes_rescheduling_tpu.core import sparsegraph
    from kubernetes_rescheduling_tpu.core.sparsegraph import BLOCK_R
    from kubernetes_rescheduling_tpu.core.topology import synthetic_scenario
    from kubernetes_rescheduling_tpu.ops.fused_admission import admission_stage
    from kubernetes_rescheduling_tpu.ops.sparse_mass import (
        chunk_local_slabs,
        sparse_mass_score,
        sparse_neighbor_mass,
    )

    scn = synthetic_scenario(n_pods=1024, n_nodes=128, powerlaw=True, seed=5)
    adj = np.asarray(scn.graph.adj)
    iu, ju = np.nonzero(np.triu(adj, k=1))
    sg = sparsegraph.from_edges(
        iu, ju, adj[iu, ju], adj.shape[0], names=scn.graph.names,
        bu=128, reg_tiles=4,
    )
    rng = np.random.default_rng(0)
    SP, N = sg.sp, 128
    KB = 2
    blocks = jnp.asarray(sg.regular_blocks[:KB], jnp.int32)
    ids = (np.asarray(blocks)[:, None] * BLOCK_R + np.arange(BLOCK_R)).reshape(-1)
    C = KB * BLOCK_R
    assign = jnp.asarray(rng.integers(0, N, size=SP), jnp.int32)
    rv = jnp.asarray(rng.integers(1, 3, size=SP).astype(np.float32))
    rvu = jnp.where(sg.u_ids < SP, rv[jnp.clip(sg.u_ids, 0, SP - 1)], 0.0)
    w_mm = sg.w_local.astype(jnp.float32)
    toff = jnp.asarray(sg.block_toff, jnp.int32)
    starts = toff[blocks] * sg.bu
    u_c, rvu_c = chunk_local_slabs(sg.u_ids, rvu, starts, sg.u_reg)
    tgt_c = assign[jnp.clip(u_c, 0, SP - 1)]

    cur = assign[jnp.asarray(ids)]
    c_cpu = jnp.asarray(rng.integers(1, 5, size=C) * 10.0, jnp.float32)
    c_mem = jnp.zeros((C,), jnp.float32)
    valid_c = jnp.asarray(rng.random(C) < 0.9)
    cap = jnp.full((N,), 900.0, jnp.float32)
    cpu_load = jnp.asarray(rng.uniform(0, 800.0, N), jnp.float32)
    mem_cap = jnp.full((N,), 1e9, jnp.float32)
    mem_load = jnp.zeros((N,), jnp.float32)
    node_valid = jnp.asarray(rng.random(N) < 0.95)
    lam = 0.5

    for mc_pen in (None, jnp.asarray(rng.random(C), jnp.float32)):
        home = cur if mc_pen is None else jnp.asarray(
            rng.integers(0, N, size=C), jnp.int32
        )
        # two-kernel path: mass kernel -> HBM -> score+admission
        M = sparse_neighbor_mass(
            w_mm, tgt_c, rvu_c, blocks, toff,
            num_nodes=N, bu=sg.bu, reg_tiles=sg.reg_tiles, interpret=True,
        ) * rv[jnp.asarray(ids)][:, None]
        exp_node, exp_adm, exp_dc, exp_dm = fused_score_admission(
            M, cur, c_cpu, c_mem, valid_c,
            cpu_load, mem_load, cap, mem_cap, node_valid,
            lam, 0.0, 0,
            overload_weight=10.0, home=home, move_pen=mc_pen,
            enforce_capacity=True, use_noise=False, interpret=True,
            emit_x_rows=False,
        )
        # fused path: mass accumulated in VMEM scratch, scored in-kernel
        prop, gain, wants, s_cpu, s_mem = sparse_mass_score(
            w_mm, tgt_c, rvu_c, blocks, toff, rv[jnp.asarray(ids)],
            cur, home, mc_pen, c_cpu, c_mem, valid_c,
            cpu_load, mem_load, cap, mem_cap, node_valid,
            lam, 0.0, 0, 10.0,
            num_nodes=N, bu=sg.bu, reg_tiles=sg.reg_tiles,
            enforce_capacity=True, use_noise=False, interpret=True,
        )
        got_node, got_adm, got_dc, got_dm = admission_stage(
            prop, gain, wants, s_cpu, s_mem, cur, valid_c, c_cpu, c_mem,
            num_nodes=N, enforce_capacity=True, interpret=True,
            emit_x_rows=False,
        )
        np.testing.assert_array_equal(np.asarray(got_node), np.asarray(exp_node))
        np.testing.assert_array_equal(np.asarray(got_adm), np.asarray(exp_adm))
        np.testing.assert_array_equal(np.asarray(got_dc), np.asarray(exp_dc))
        np.testing.assert_array_equal(np.asarray(got_dm), np.asarray(exp_dm))
