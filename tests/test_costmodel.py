"""Device-side cost observability (ISSUE 4 tentpole, layer 1): compiled
cost/HBM capture at first compile (exactly once, never on cache hits),
roofline publication, device-memory sampling, and the manifest /
/metrics / flight-recorder surfacing — all under JAX_PLATFORMS=cpu,
where cost_analysis/memory_analysis answer like any other backend."""

import json

import jax
import jax.numpy as jnp
import pytest

from kubernetes_rescheduling_tpu.backends.sim import LoadModel, SimBackend
from kubernetes_rescheduling_tpu.bench.controller import run_controller
from kubernetes_rescheduling_tpu.config import RescheduleConfig
from kubernetes_rescheduling_tpu.core.workmodel import mubench_workmodel_c
from kubernetes_rescheduling_tpu.telemetry import (
    FlightRecorder,
    MetricsRegistry,
    get_costbook,
    instrument_jit,
    run_manifest,
    set_registry,
)
from kubernetes_rescheduling_tpu.telemetry import costmodel


@pytest.fixture
def registry():
    reg = MetricsRegistry()
    prev = set_registry(reg)
    yield reg
    set_registry(prev)


def _gauge(reg, name, fn):
    return reg.gauge(name, labelnames=("fn",)).labels(fn=fn).value


def test_capture_is_nonempty_and_exactly_once(registry):
    """The satellite contract: an instrumented kernel yields a non-empty
    cost snapshot at FIRST compile, and cache hits / later retraces never
    re-capture (no second AOT compile)."""

    def f(x, w):
        return jnp.tanh(x @ w).sum()

    g = instrument_jit(f, name="cap_once")
    x = jnp.ones((8, 16))
    w = jnp.ones((16, 4))
    for _ in range(3):  # cache hits after the first
        jax.block_until_ready(g(x, w))
    snap = get_costbook().get("cap_once")
    assert snap is not None
    assert snap["flops"] > 0
    assert snap["bytes_accessed"] > 0
    assert snap["argument_bytes"] > 0
    assert snap["output_bytes"] > 0
    captures = registry.counter("jax_cost_captures_total", labelnames=("fn",))
    assert captures.labels(fn="cap_once").value == 1
    # the gauges carry the snapshot
    assert _gauge(registry, "jax_cost_flops", "cap_once") == snap["flops"]
    assert (
        _gauge(registry, "jax_hbm_argument_bytes", "cap_once")
        == snap["argument_bytes"]
    )
    # a RETRACE (new shape) recompiles but does not re-capture
    jax.block_until_ready(g(jnp.ones((4, 16)), w))
    assert g.traces() == 2
    assert captures.labels(fn="cap_once").value == 1


def test_capture_republishes_into_swapped_registry(registry):
    """A kernel compiled under one registry keeps its gauges visible
    after the process default is swapped (bench cells, tests)."""

    def f(x):
        return (x * 3.0).sum()

    g = instrument_jit(f, name="cap_repub")
    jax.block_until_ready(g(jnp.arange(32.0)))
    fresh = MetricsRegistry()
    prev = set_registry(fresh)
    try:
        jax.block_until_ready(g(jnp.arange(32.0)))  # steady-state call
    finally:
        set_registry(prev)
    assert 'jax_cost_flops{fn="cap_repub"}' in fresh.expose()
    # republish sets gauges only — the capture counter stays in the
    # registry that saw the compile
    assert "jax_cost_captures_total" not in fresh.expose()


def test_capture_skips_tracer_args(registry):
    """An instrumented kernel first dispatched INSIDE an outer trace must
    not attempt an AOT compile of tracer avals; the next concrete call
    captures instead."""

    def inner(x):
        return x * 2.0

    g = instrument_jit(inner, name="cap_traced")

    @jax.jit
    def outer(x):
        return g(x) + 1.0

    jax.block_until_ready(outer(jnp.arange(4.0)))
    assert get_costbook().get("cap_traced") is None
    jax.block_until_ready(g(jnp.arange(4.0)))  # concrete call captures
    assert get_costbook().get("cap_traced") is not None


def test_roofline_and_device_memory(registry):
    def f(x):
        return (x @ x.T).sum()

    g = instrument_jit(f, name="roofline_fn")
    jax.block_until_ready(g(jnp.ones((16, 16))))
    out = costmodel.publish_roofline(registry, "roofline_fn", seconds=0.5)
    snap = get_costbook().get("roofline_fn")
    assert out is not None
    assert out["achieved_flops_per_s"] == pytest.approx(snap["flops"] / 0.5)
    assert out["achieved_bytes_per_s"] == pytest.approx(
        snap["bytes_accessed"] / 0.5
    )
    assert out["arithmetic_intensity"] == pytest.approx(
        snap["flops"] / snap["bytes_accessed"]
    )
    assert _gauge(registry, "jax_achieved_flops_per_s", "roofline_fn") > 0
    # unknown label / zero timing publish nothing
    assert costmodel.publish_roofline(registry, "nope", 0.5) is None
    assert costmodel.publish_roofline(registry, "roofline_fn", 0.0) is None
    # CPU devices expose no memory_stats — sampling is a clean no-op
    assert costmodel.sample_device_memory(registry) == []


def _controller_backend(n_nodes=7):
    """7 nodes — a shape unique to this module so the decision kernel
    compiles fresh here whatever ran before (cost capture is per-process;
    the REGISTRY gauges must still appear via republish either way)."""
    backend = SimBackend(
        workmodel=mubench_workmodel_c(),
        node_names=[f"w{i}" for i in range(n_nodes)],
        node_cpu_cap_m=20_000.0,
        seed=0,
        load=LoadModel(entry_rps=100.0, cost_per_req_m=8.0, idle_m=50.0),
    )
    backend.inject_imbalance(backend.node_names[0])
    return backend


def test_controller_round_exposes_cost_gauges_and_roofline(registry):
    """The acceptance path: after a controller run on CPU the decision
    kernel's jax_cost_*/jax_hbm_* gauges are non-zero in /metrics text,
    and the per-round roofline gauges materialized."""
    cfg = RescheduleConfig(
        algorithm="communication", max_rounds=3, sleep_after_action_s=0.0,
    )
    result = run_controller(_controller_backend(), cfg)
    assert len(result.rounds) == 3
    text = registry.expose()
    label = "controller_decide"  # bare loop (no logger) = plain kernel
    snap = get_costbook().get(label)
    assert snap is not None and snap["flops"] > 0
    # every documented cost/HBM gauge is present for the kernel (the
    # COST_GAUGES tuple is the field→gauge contract)
    for _field, gauge, _help in costmodel.COST_GAUGES:
        line = f'{gauge}{{fn="{label}"}}'
        assert line in text, f"{line} missing from /metrics"
    assert _gauge(registry, "jax_cost_flops", label) > 0
    assert _gauge(registry, "jax_hbm_argument_bytes", label) > 0
    # the fenced round latency fed the roofline
    assert _gauge(registry, "jax_achieved_flops_per_s", label) > 0
    assert _gauge(registry, "jax_arithmetic_intensity", label) > 0


def test_global_solver_capture_and_roofline(registry):
    """The batched solver is an instrumented kernel too: its compiled
    cost lands in the book (captured by whatever global solve compiled
    first — one direct solve here if this test runs in isolation), and
    the controller's global-round label preference publishes its
    roofline. Cheap by design: in the full suite the earlier bench tests
    already paid the solver compile, and the book dedup means this test
    never re-pays it."""
    if get_costbook().get("global_assign") is None:
        import jax

        from kubernetes_rescheduling_tpu.bench.harness import make_backend
        from kubernetes_rescheduling_tpu.solver import (
            GlobalSolverConfig,
            global_assign,
        )

        backend = make_backend("mubench", seed=0)
        jax.block_until_ready(
            global_assign(
                backend.monitor(), backend.comm_graph(),
                jax.random.PRNGKey(0), GlobalSolverConfig(sweeps=1),
            )
        )
    snap = get_costbook().get("global_assign")
    assert snap is not None and snap["flops"] > 0
    assert snap["argument_bytes"] > 0
    # the controller's global-round hook: candidate labels in preference
    # order, first captured label wins the roofline
    costmodel.observe_round_device(
        registry,
        fn_labels=(
            "global_assign", "global_assign_sparse",
            "sharded_restarts_dense", "sharded_restarts_sparse",
        ),
        seconds=0.025,
    )
    assert _gauge(registry, "jax_achieved_flops_per_s", "global_assign") == (
        pytest.approx(snap["flops"] / 0.025)
    )


def test_manifest_and_bundle_carry_device_costs(registry, tmp_path):
    def f(x):
        return x.sum()

    g = instrument_jit(f, name="prov_fn")
    jax.block_until_ready(g(jnp.arange(8.0)))
    m = run_manifest()
    assert "prov_fn" in m["device_costs"]["kernels"]
    assert m["device_costs"]["kernels"]["prov_fn"]["flops"] >= 0
    assert isinstance(m["device_costs"]["device_memory"], list)

    fr = FlightRecorder(capacity=2, bundle_dir=tmp_path, registry=registry)
    fr.record_round(round=1, record={"round": 1})
    bundle = json.loads(fr.dump("crash").read_text())
    assert "prov_fn" in bundle["device_costs"]
