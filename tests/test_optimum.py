"""Optimality-gap oracles: brute force and MILP agree with each other,
and the global solver's gap against the TRUE optimum is pinned."""

import numpy as np
import jax
import pytest

from kubernetes_rescheduling_tpu.core.state import ClusterState, CommGraph
from kubernetes_rescheduling_tpu.oracle.optimum import (
    brute_force_optimum,
    milp_optimum,
)
from kubernetes_rescheduling_tpu.solver import GlobalSolverConfig, global_assign
from kubernetes_rescheduling_tpu.solver.global_solver import exact_comm_cost


def _tiny_instance(S, N, seed, cap_m=1e9):
    rng = np.random.default_rng(seed)
    rel = {
        f"s{i}": [f"s{j}" for j in range(S) if j != i and rng.random() < 0.5]
        for i in range(S)
    }
    graph = CommGraph.from_relation(rel, names=[f"s{i}" for i in range(S)])
    state = ClusterState.build(
        node_names=[f"n{i}" for i in range(N)],
        node_cpu_cap=[cap_m] * N,
        node_mem_cap=[2**33] * N,
        pod_services=list(range(S)),
        pod_nodes=rng.integers(0, N, S).tolist(),
        pod_cpu=[100.0] * S,
        pod_mem=[0.0] * S,
        pod_names=[f"s{i}-0" for i in range(S)],
    )
    return state, graph


def test_brute_force_matches_milp_on_comm():
    for seed in range(4):
        state, graph = _tiny_instance(7, 3, seed)
        _, bf = brute_force_optimum(
            state, graph, balance_weight=0.0, overload_weight=0.0
        )
        milp, proven = milp_optimum(state, graph)
        assert proven
        assert bf == pytest.approx(milp, abs=1e-6)


def test_brute_force_capacity_binding():
    # 6 services x 100m, nodes cap 250m -> min 3 nodes needed; the
    # unconstrained optimum (all on one node, cut 0) must be excluded
    state, graph = _tiny_instance(6, 3, seed=1, cap_m=250.0)
    a, obj = brute_force_optimum(
        state, graph, balance_weight=0.0, overload_weight=0.0
    )
    loads = np.bincount(a, weights=np.full(6, 100.0), minlength=3)
    assert (loads <= 250.0).all()
    assert obj > 0.0
    milp, proven = milp_optimum(state, graph)
    assert proven
    assert obj == pytest.approx(milp, abs=1e-6)


def _gap_over_seeds(seeds):
    """(total_solver, total_opt, exact_hits) across tiny instances —
    shared by the fast tier-1 pin and the full slow statistical pin."""
    total_solver = 0.0
    total_opt = 0.0
    exact_hits = 0
    for seed in seeds:
        state, graph = _tiny_instance(8, 3, seed, cap_m=350.0)
        cfg = GlobalSolverConfig(sweeps=9, balance_weight=0.0)
        new_state, info = global_assign(
            state, graph, jax.random.PRNGKey(seed), cfg
        )
        # service-level comm of the solver result
        S = graph.num_services
        svc = np.asarray(new_state.pod_service)
        node = np.asarray(new_state.pod_node)
        assign = np.zeros(S, dtype=np.int64)
        for i in range(S):
            assign[svc[i]] = node[i]
        rv = np.ones(S, dtype=np.float32)
        solver_cost = float(
            exact_comm_cost(
                graph.adj[:S, :S], jax.numpy.asarray(rv),
                jax.numpy.asarray(assign),
            )
        )
        _, opt = brute_force_optimum(
            state, graph, balance_weight=0.0, overload_weight=0.0,
        )
        assert solver_cost >= opt - 1e-6  # sanity: oracle really is a bound
        total_solver += solver_cost
        total_opt += opt
        if solver_cost <= opt + 1e-6:
            exact_hits += 1
    return total_solver, total_opt, exact_hits


def test_solver_gap_small_instances_fast():
    """Tier-1 pin of solution quality vs the true optimum: 4 tiny
    instances, aggregate gap <= 5%, most exactly optimal (the round-5
    swap phase hits 4/4 on these seeds; >= 3 tolerates one regression
    without flaking)."""
    total_solver, total_opt, exact_hits = _gap_over_seeds(range(4))
    assert total_solver <= total_opt * 1.05
    assert exact_hits >= 3


@pytest.mark.slow  # the full statistical pin; tier-1 keeps the 4-seed fast
# variant above, which covers the same invariant at the same thresholds
def test_solver_gap_small_instances():
    """Regression pin: across 10 tiny instances the solver's comm cost is
    within 5% of the true optimum in aggregate (and never worse than the
    input, which is separately guaranteed). Round 4 measured >=5/10 exact
    and <=10% aggregate; round 5's pairwise-swap phase lifted that to
    9/10 exact and 0.7% aggregate — the pin tightens accordingly."""
    total_solver, total_opt, exact_hits = _gap_over_seeds(range(10))
    assert total_solver <= total_opt * 1.05
    assert exact_hits >= 8
