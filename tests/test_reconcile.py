"""The reconciliation & admission plane (PR: close the loop).

Four suites:

- ``TestAdmissionGuard`` — the snapshot admission guard's classify →
  action → metric contract, per edge class (NaN/Inf/negative/
  over-capacity quarantine; duplicate-pod/unknown-node/overflow reject),
  as a seeded property-style sweep (plain seeded loops, the suite's
  convention — no hypothesis).
- ``TestIntentLedger`` — divergence classification (wrong-node, lost
  move, external drift, phantom/missing with debounce), churn-event
  consumption, rate-limited repairs, checkpoint snapshot/restore.
- ``TestControllerReconcile`` — the plane wired into ``run_controller``:
  the no-fault golden pin (admission+reconcile leave a clean run
  bit-identical to the plane-off trajectory), the seeded 30-round chaos
  acceptance soak (corrupt metrics + drift + lost/wrong-node moves +
  node flap: every fault detected and classified, convergence, finite
  costs, 1-trace, exact round accounting), pipelined bit-identity under
  the same faults, the unknown-landing regression, and crash-resume
  reconciliation against a backend that is its own state.
- ``TestFleetReconcile`` — per-tenant guards/ledgers with chaos
  isolation.

Node counts here stay in the 17-19 range for the trace-pinned soaks
(fresh compiles in THIS file's registry) and at 8 for everything else
(shared jit cache, cheap).
"""

import dataclasses
import math
import random

import jax
import numpy as np
import pytest

from kubernetes_rescheduling_tpu.backends.base import MoveRequest
from kubernetes_rescheduling_tpu.backends.chaos import with_chaos
from kubernetes_rescheduling_tpu.backends.fleet import FleetBackend
from kubernetes_rescheduling_tpu.backends.sim import LoadModel, SimBackend
from kubernetes_rescheduling_tpu.bench.admission import (
    REASON_INF,
    REASON_NAN,
    REASON_NEGATIVE,
    REASON_OVER_CAPACITY,
    AdmissionGuard,
)
from kubernetes_rescheduling_tpu.bench.controller import (
    RoundRecord,
    run_controller,
)
from kubernetes_rescheduling_tpu.bench.fleet import run_fleet_controller
from kubernetes_rescheduling_tpu.bench.reconcile import (
    KIND_EXTERNAL_DRIFT,
    KIND_LOST_MOVE,
    KIND_MISSING_POD,
    KIND_PHANTOM_POD,
    KIND_WRONG_NODE,
    IntentLedger,
    reconcile_round_block,
)
from kubernetes_rescheduling_tpu.config import (
    ChaosConfig,
    ControllerConfig,
    FleetConfig,
    ReconcileConfig,
    RescheduleConfig,
)
from kubernetes_rescheduling_tpu.core.workmodel import mubench_workmodel_c
from kubernetes_rescheduling_tpu.telemetry.registry import (
    MetricsRegistry,
    get_registry,
    set_registry,
)
from kubernetes_rescheduling_tpu.telemetry.watchdog import (
    RULE_RECONCILE,
    SLORules,
    Watchdog,
)


@pytest.fixture()
def registry():
    prev = set_registry(MetricsRegistry())
    try:
        yield get_registry()
    finally:
        set_registry(prev)


def _backend(n_nodes: int = 8, seed: int = 1) -> SimBackend:
    b = SimBackend(
        workmodel=mubench_workmodel_c(),
        node_names=[f"rc{i}" for i in range(n_nodes)],
        node_cpu_cap_m=20_000.0,
        seed=seed,
        load=LoadModel(entry_rps=100.0, cost_per_req_m=8.0, idle_m=50.0),
    )
    b.inject_imbalance(b.node_names[0])
    return b


def _counter(registry, name: str, **labels) -> float:
    for rec in registry.snapshot():
        if rec["metric"] == name and all(
            rec["labels"].get(k) == v for k, v in labels.items()
        ):
            return rec["value"]
    return 0.0


def _guard(registry, **cfg_kw) -> AdmissionGuard:
    rejects: list[str] = []
    g = AdmissionGuard(
        ReconcileConfig(**cfg_kw),
        registry=registry,
        on_reject=rejects.append,
    )
    g.rejects = rejects
    return g


# ---------------- admission: classify -> action -> metric ----------------


class TestAdmissionGuard:
    def test_clean_snapshot_returns_same_object(self, registry):
        g = _guard(registry)
        state = _backend().monitor()
        assert g.admit(state) is state  # the bit-identity contract
        assert g.admit(None) is None  # boundary failure passes through
        assert g.take_info() == {}

    def test_quarantine_sweep_pins_class_action_metric(self, registry):
        """Seeded property-style sweep: every poison class on every pod
        field is repaired to the LAST-GOOD value (0 for never-seen), and
        each repair counts under exactly its (field, reason) label."""
        g = _guard(registry)
        backend = _backend()
        baseline = g.admit(backend.monitor())  # prime last-good
        poisons = {
            REASON_NAN: lambda v: np.nan,
            REASON_INF: lambda v: np.inf,
            REASON_NEGATIVE: lambda v: -abs(v) - 1.0,
        }
        rng = random.Random(7)
        expected_counts: dict[tuple, int] = {}
        for trial in range(12):
            field = rng.choice(["pod_cpu", "pod_mem"])
            reason = rng.choice(sorted(poisons))
            state = backend.monitor()
            arr = np.asarray(getattr(state, field)).copy()
            valid = np.flatnonzero(np.asarray(state.pod_valid))
            hit = rng.sample(list(valid), k=rng.randint(1, 3))
            for i in hit:
                arr[i] = poisons[reason](arr[i])
            admitted = g.admit(state.replace(**{field: arr}))
            assert admitted is not None
            out = np.asarray(getattr(admitted, field))
            good = np.asarray(getattr(baseline, field))
            assert np.all(np.isfinite(out)) and np.all(out >= 0.0)
            for i in hit:
                # repaired to the pod's last ADMITTED reading, by name
                assert out[i] == good[i]
            key = (field, reason)
            expected_counts[key] = expected_counts.get(key, 0) + len(hit)
            info = g.take_info()
            assert info == {f"{field}:{reason}": len(hit)}
            # last-good must NOT absorb this trial's repairs as new truth
            # beyond what admission produced (the repaired values ARE the
            # last-good values, so the baseline stays fixed)
            baseline = admitted
        for (field, reason), n in expected_counts.items():
            assert (
                _counter(
                    registry,
                    "admission_quarantined_total",
                    field=field,
                    reason=reason,
                )
                == n
            )

    def test_over_capacity_clamps_to_biggest_node(self, registry):
        g = _guard(registry)
        backend = _backend()
        g.admit(backend.monitor())
        state = backend.monitor()
        cap = float(np.max(np.asarray(state.node_cpu_cap)))
        cpu = np.asarray(state.pod_cpu).copy()
        i = int(np.flatnonzero(np.asarray(state.pod_valid))[0])
        cpu[i] = cap * 50.0
        admitted = g.admit(state.replace(pod_cpu=cpu))
        assert float(np.asarray(admitted.pod_cpu)[i]) == cap
        assert g.take_info() == {f"pod_cpu:{REASON_OVER_CAPACITY}": 1}
        assert (
            _counter(
                registry,
                "admission_quarantined_total",
                field="pod_cpu",
                reason=REASON_OVER_CAPACITY,
            )
            == 1
        )

    def test_node_field_quarantine_reuses_last_good(self, registry):
        g = _guard(registry)
        backend = _backend()
        g.admit(backend.monitor())
        state = backend.monitor()
        caps = np.asarray(state.node_cpu_cap).copy()
        good = float(caps[2])
        caps[2] = np.nan
        admitted = g.admit(state.replace(node_cpu_cap=caps))
        assert float(np.asarray(admitted.node_cpu_cap)[2]) == good
        assert g.take_info() == {f"node_cpu_cap:{REASON_NAN}": 1}

    def test_quarantine_replacement_honors_shrunken_ceiling(self, registry):
        """Regression: a last-good value admitted under a LARGER node
        pool must be re-clamped when churn has shrunk the capacity
        ceiling — the guard cannot admit a replacement it would reject
        as a raw reading."""
        g = _guard(registry)
        backend = _backend()
        state = backend.monitor()
        cpu = np.asarray(state.pod_cpu).copy()
        i = int(np.flatnonzero(np.asarray(state.pod_valid))[0])
        cpu[i] = 18_000.0  # legal under the 20k caps -> stored last-good
        assert g.admit(state.replace(pod_cpu=cpu)) is not None
        state = backend.monitor()
        caps = np.full_like(np.asarray(state.node_cpu_cap), 10_000.0)
        cpu = np.asarray(state.pod_cpu).copy()
        cpu[i] = np.nan  # quarantine -> last-good (18k) > new ceiling
        admitted = g.admit(state.replace(node_cpu_cap=caps, pod_cpu=cpu))
        assert float(np.asarray(admitted.pod_cpu)[i]) == 10_000.0
        valid = np.asarray(admitted.pod_valid)
        assert float(
            np.max(np.asarray(admitted.pod_cpu)[valid], initial=0.0)
        ) <= 10_000.0
        # still one reading, one count — under its nan reason
        assert g.take_info() == {f"pod_cpu:{REASON_NAN}": 1}

    def test_duplicate_pod_rejects_and_charges(self, registry):
        g = _guard(registry)
        state = _backend().monitor()
        names = list(state.pod_names)
        names[1] = names[0]  # two pods claiming one identity
        assert g.admit(state.replace(pod_names=tuple(names))) is None
        assert g.rejects == ["duplicate_pod"]
        assert (
            _counter(
                registry, "admission_rejected_total", reason="duplicate_pod"
            )
            == 1
        )

    def test_unknown_node_reference_rejects(self, registry):
        g = _guard(registry)
        state = _backend().monitor()
        nodes = np.asarray(state.pod_node).copy()
        nodes[0] = state.num_nodes + 3  # beyond the node table
        assert g.admit(state.replace(pod_node=nodes)) is None
        assert g.rejects == ["unknown_node"]
        assert (
            _counter(
                registry, "admission_rejected_total", reason="unknown_node"
            )
            == 1
        )

    def test_padded_slot_node_reference_rejects(self, registry):
        # regression: bucketed capacity pads node arrays beyond the name
        # table, so a ref into a padded slot is in-bounds for the arrays
        # but names NO node — it must reject exactly like one past the
        # array (the old check compared against the padded capacity)
        g = _guard(registry)
        state = _backend().monitor()
        nodes = np.asarray(state.pod_node).copy()
        nodes[0] = state.num_nodes - 1  # in-bounds for the padded arrays
        state = state.replace(
            pod_node=nodes, node_names=state.node_names[:-1]
        )
        assert g.admit(state) is None
        assert g.rejects == ["unknown_node"]

    def test_quarantine_overflow_rejects_whole_snapshot(self, registry):
        g = _guard(registry, max_quarantine_frac=0.25)
        backend = _backend()
        g.admit(backend.monitor())
        state = backend.monitor()
        cpu = np.asarray(state.pod_cpu).copy()
        valid = np.flatnonzero(np.asarray(state.pod_valid))
        for i in valid[: max(2, int(len(valid) * 0.5))]:
            cpu[i] = np.nan  # a mostly-fabricated metrics wave
        assert g.admit(state.replace(pod_cpu=cpu)) is None
        assert g.rejects == ["quarantine_overflow"]
        # a rejected snapshot must not half-count its planned quarantines
        assert _counter(
            registry, "admission_quarantined_total", field="pod_cpu"
        ) == 0

    def test_sim_name_tuples_are_identity_stable(self, registry):
        # regression: the guard's O(1)-clean-path memos (duplicate scan,
        # name->index maps) key on tuple IDENTITY — the sim used to build
        # fresh tuples every monitor, so the memos never hit and every
        # admit rebuilt O(P) python state
        backend = _backend()
        s1, s2 = backend.monitor(), backend.monitor()
        assert s1.pod_names is s2.pod_names
        assert s1.node_names is s2.node_names
        # a workload mutation yields the CORRECT tuple (content-compared,
        # so there is no invalidation hook to miss)
        svc = backend.workmodel.services[0].name
        backend.teardown_service(svc)
        s3 = backend.monitor()
        assert s3.pod_names != s1.pod_names
        assert all(not p.startswith(f"{svc}-") for p in s3.pod_names)

    def test_disabled_guard_is_passthrough(self, registry):
        g = AdmissionGuard(
            ReconcileConfig(admission=False), registry=registry
        )
        state = _backend().monitor()
        poisoned = state.replace(
            pod_cpu=np.full_like(np.asarray(state.pod_cpu), np.nan)
        )
        assert g.admit(poisoned) is poisoned

    def test_host_arrays_handoff_matches_fresh_pull(self, registry):
        # the ledger's observe() reuses the guard's already-pulled host
        # arrays (one transfer per round, not two) — identity-gated, and
        # bit-equal to pulling fresh
        g = _guard(registry)
        backend = _backend()
        state = g.admit(backend.monitor())
        arrays = g.host_arrays(state)
        assert arrays is not None
        for field in ("pod_valid", "pod_node", "pod_service", "node_valid"):
            np.testing.assert_array_equal(
                arrays[field], np.asarray(getattr(state, field))
            )
        # a different snapshot object (even an identical one) never matches
        assert g.host_arrays(backend.monitor()) is None
        led = _ledger(registry)
        graph = backend.comm_graph()
        led.rebase(state, service_names=graph.names)
        out = led.observe(
            state, service_names=graph.names, host_arrays=arrays
        )
        assert out["divergences"] == []


# ---------------- the intent ledger ----------------


def _ledger(registry, **cfg_kw) -> IntentLedger:
    return IntentLedger(ReconcileConfig(**cfg_kw), registry=registry)


class TestIntentLedger:
    def test_wrong_node_and_lost_move_classification(self, registry):
        backend = _backend()
        led = _ledger(registry)
        graph = backend.comm_graph()
        led.rebase(backend.monitor(), service_names=graph.names)
        pod = backend.monitor().pod_names[0]
        svc = graph.names[0]
        # wrong node: boundary CLAIMS it landed somewhere != requested
        led.record_moves([(svc, pod, "rc3", "rc5")])
        backend.apply_move(
            MoveRequest(service=svc, pod=pod, target_node="rc5")
        )
        out = led.observe(backend.monitor(), service_names=graph.names)
        kinds = {d["kind"] for d in out["divergences"]}
        assert kinds == {KIND_WRONG_NODE}
        assert led.drift_pods >= 1  # repair queued toward rc3
        # lost move: claimed landed == requested but nothing moved
        led.rebase(backend.monitor(), service_names=graph.names)
        led.record_moves([(svc, pod, "rc6", "rc6")])
        out = led.observe(backend.monitor(), service_names=graph.names)
        kinds = {d["kind"] for d in out["divergences"]}
        assert kinds == {KIND_LOST_MOVE}
        assert (
            _counter(
                registry,
                "reconcile_divergences_total",
                kind=KIND_LOST_MOVE,
            )
            == 1
        )

    def test_external_drift_detected_and_repaired(self, registry):
        backend = _backend()
        led = _ledger(registry)
        graph = backend.comm_graph()
        led.rebase(backend.monitor(), service_names=graph.names)
        moved = backend.external_move_random(random.Random(3))
        assert moved is not None
        out = led.observe(backend.monitor(), service_names=graph.names)
        assert [d["kind"] for d in out["divergences"]] == [
            KIND_EXTERNAL_DRIFT
        ]
        assert out["divergences"][0]["pod"] == moved["pod"]

        class _Boundary:
            def apply_move(self, move):
                return backend.apply_move(move)

        issued = led.issue_repairs(_Boundary(), budget=2)
        assert [r["pod"] for r in issued] == [moved["pod"]]
        # the corrective move landed: the next observe sees convergence
        out = led.observe(backend.monitor(), service_names=graph.names)
        assert out["divergences"] == [] and led.drift_pods == 0
        assert (
            _counter(
                registry,
                "reconcile_repair_moves_total",
                kind=KIND_EXTERNAL_DRIFT,
            )
            == 1
        )

    def test_repair_budget_rate_limits_and_failures_requeue(self, registry):
        backend = _backend()
        led = _ledger(registry)
        graph = backend.comm_graph()
        led.rebase(backend.monitor(), service_names=graph.names)
        rng = random.Random(5)
        drifted = {backend.external_move_random(rng)["pod"] for _ in range(4)}
        led.observe(backend.monitor(), service_names=graph.names)
        assert led.drift_pods == len(drifted)

        class _DarkBoundary:
            calls = 0

            def apply_move(self, move):
                type(self).calls += 1
                return None  # boundary failure: the repair must re-queue

        issued = led.issue_repairs(_DarkBoundary(), budget=2)
        assert len(issued) == 2 and _DarkBoundary.calls == 2
        assert led.drift_pods == len(drifted)  # failed repairs kept
        assert led.issue_repairs(_DarkBoundary(), budget=0) == []

    def test_pending_divergence_counted_once_and_keeps_kind(self, registry):
        """Regression: a divergence awaiting repair budget (or running
        detect-and-count-only) is ONE fault — re-observing the same
        unrepaired state must not re-count it, and must not reclassify a
        wrong_node to external_drift once the in-flight move meta is
        gone (the queued repair keeps the kind it was detected with)."""
        backend = _backend()
        led = _ledger(registry)
        graph = backend.comm_graph()
        led.rebase(backend.monitor(), service_names=graph.names)
        moved = backend.external_move_random(random.Random(7))
        for _ in range(3):  # budget never granted: the drift persists
            led.observe(backend.monitor(), service_names=graph.names)
        assert (
            _counter(
                registry,
                "reconcile_divergences_total",
                kind=KIND_EXTERNAL_DRIFT,
            )
            == 1
        )
        assert led.drift_pods == 1  # the repair stays queued
        # wrong_node awaiting budget: kind survives to the issued repair
        led.rebase(backend.monitor(), service_names=graph.names)
        pod = backend.monitor().pod_names[0]
        svc = graph.names[0]
        led.record_moves([(svc, pod, "rc3", "rc5")])
        backend.apply_move(
            MoveRequest(service=svc, pod=pod, target_node="rc5")
        )
        for _ in range(2):
            led.observe(backend.monitor(), service_names=graph.names)
        assert (
            _counter(
                registry,
                "reconcile_divergences_total",
                kind=KIND_WRONG_NODE,
            )
            == 1
        )

        class _Boundary:
            def apply_move(self, move):
                return backend.apply_move(move)

        issued = led.issue_repairs(_Boundary(), budget=4)
        assert {r["kind"] for r in issued} >= {KIND_WRONG_NODE}
        assert (
            _counter(
                registry,
                "reconcile_repair_moves_total",
                kind=KIND_WRONG_NODE,
            )
            == 1
        )

    def test_phantom_and_missing_pods_debounce(self, registry):
        backend = _backend()
        led = _ledger(registry)
        graph = backend.comm_graph()
        state = backend.monitor()
        led.rebase(state, service_names=graph.names)
        # missing: drop one pod's validity — one sighting is a lagging
        # watch cache (no charge), the second is a divergence
        valid = np.asarray(state.pod_valid).copy()
        gone = int(np.flatnonzero(valid)[0])
        valid[gone] = False
        # two DISTINCT partial snapshots: the ledger skips a re-served
        # identical object (a stale monitor is one read, not two)
        out = led.observe(
            state.replace(pod_valid=valid), service_names=graph.names
        )
        assert out["divergences"] == []
        out = led.observe(
            state.replace(pod_valid=valid), service_names=graph.names
        )
        assert [d["kind"] for d in out["divergences"]] == [KIND_MISSING_POD]
        assert state.pod_names[gone] not in led.intent  # re-anchored
        # phantom: the pod coming back is unknown to intent now — same
        # debounce, then adopted (fresh monitors: distinct objects)
        out = led.observe(backend.monitor(), service_names=graph.names)
        assert out["divergences"] == []
        out = led.observe(backend.monitor(), service_names=graph.names)
        assert [d["kind"] for d in out["divergences"]] == [KIND_PHANTOM_POD]
        assert state.pod_names[gone] in led.intent

    def test_churn_events_are_consumed_before_drift(self, registry):
        backend = _backend()
        led = _ledger(registry)
        graph = backend.comm_graph()
        led.rebase(backend.monitor(), service_names=graph.names)
        moved = backend.external_move_random(random.Random(3))
        # the same placement change, but a churn event explains the node:
        # re-placement after drain rescheduling is NOT drift
        out = led.observe(
            backend.monitor(),
            service_names=graph.names,
            churn_events=[{"kind": "node_add", "node": moved["to"]}],
        )
        assert out["divergences"] == [] and led.drift_pods == 0
        assert led.intent[moved["pod"]] == moved["to"]  # adopted

    def test_lost_repair_classified_as_lost_move_not_drift(self, registry):
        # regression: intent already equals the repair target, so without
        # the repair's true origin a swallowed corrective move would
        # re-classify as external_drift on every retry
        backend = _backend()
        led = _ledger(registry)
        graph = backend.comm_graph()
        led.rebase(backend.monitor(), service_names=graph.names)
        moved = backend.external_move_random(random.Random(3))
        led.observe(backend.monitor(), service_names=graph.names)
        assert led.drift_pods == 1

        class _LyingBoundary:  # acknowledges the move, moves nothing
            def apply_move(self, move):
                return move.target_node

        issued = led.issue_repairs(_LyingBoundary(), budget=1)
        assert issued[0]["from"] == moved["to"]
        out = led.observe(backend.monitor(), service_names=graph.names)
        assert [d["kind"] for d in out["divergences"]] == [KIND_LOST_MOVE]
        assert (
            _counter(
                registry, "reconcile_divergences_total", kind=KIND_LOST_MOVE
            )
            == 1
        )
        # the re-queued repair still aims at the original intent
        assert led.repairs[moved["pod"]]["target"] == moved["from"]

    def test_stale_snapshot_not_rediffed(self, registry):
        # regression: the chaos monitor_stale fault re-serves the SAME
        # state object the wrapper last returned; re-diffing it showed
        # the pre-move placement again, so every in-flight move misread
        # as lost_move and repair budget burned on pods already at
        # intent
        backend = _backend()
        led = _ledger(registry)
        graph = backend.comm_graph()
        led.rebase(backend.monitor(), service_names=graph.names)
        s1 = backend.monitor()
        led.observe(s1, service_names=graph.names)
        pod, svc = s1.pod_names[0], graph.names[0]
        backend.apply_move(
            MoveRequest(service=svc, pod=pod, target_node="rc5")
        )
        led.record_moves([(svc, pod, "rc5", "rc5")])
        out = led.observe(s1, service_names=graph.names)  # stale re-serve
        assert out["divergences"] == [] and led.drift_pods == 0
        assert pod in led.moves  # meta waits for the next real read
        out = led.observe(backend.monitor(), service_names=graph.names)
        assert out["divergences"] == []  # the move HAD landed
        # a re-serve from SEVERAL reads back (corrupt/partial rounds sat
        # between the stale cache and now) is still recognized: the
        # identity ring holds more than one recent snapshot
        out = led.observe(s1, service_names=graph.names)
        assert out["divergences"] == []
        assert (
            _counter(
                registry, "reconcile_divergences_total", kind=KIND_LOST_MOVE
            )
            == 0
        )

    def test_move_meta_survives_missing_debounce(self, registry):
        # regression: observe() consumed the whole in-flight move dict
        # even for pods absent under the missing debounce, so the meta
        # (advisory flag, true old node) was gone by the first diff that
        # could use it — an advisory pod re-created one snapshot later
        # read as external_drift and was force-pinned against the
        # scheduler, and a lost pinning move misread as drift
        backend = _backend()
        led = _ledger(registry)
        graph = backend.comm_graph()
        state = backend.monitor()
        led.rebase(state, service_names=graph.names)
        pod = state.pod_names[0]
        svc = graph.names[0]
        # advisory move claimed rc3; the pod is mid-re-create (absent
        # from the next snapshot), then lands on the scheduler's rc5
        led.record_moves([(svc, pod, "rc3", "rc3", True)])
        valid = np.asarray(state.pod_valid).copy()
        valid[0] = False
        out = led.observe(
            state.replace(pod_valid=valid), service_names=graph.names
        )
        assert out["divergences"] == []  # debounced, meta retained
        backend.apply_move(
            MoveRequest(service=svc, pod=pod, target_node="rc5")
        )
        out = led.observe(backend.monitor(), service_names=graph.names)
        assert out["divergences"] == [] and led.drift_pods == 0
        assert led.intent[pod] == "rc5"  # adopted, not fought
        # same window for a PINNING move that was lost: still lost_move
        led.rebase(backend.monitor(), service_names=graph.names)
        led.record_moves([(svc, pod, "rc6", "rc6")])
        state = backend.monitor()
        valid = np.asarray(state.pod_valid).copy()
        valid[0] = False
        led.observe(
            state.replace(pod_valid=valid), service_names=graph.names
        )
        out = led.observe(backend.monitor(), service_names=graph.names)
        assert [d["kind"] for d in out["divergences"]] == [KIND_LOST_MOVE]

    def test_repairs_scope_to_service_without_pod_moves(self, registry):
        # regression: the k8s Deployment mechanism rejects pod-granular
        # moves with ValueError (a non-transient error the boundary
        # re-raises — the run would crash); a backend advertising
        # supports_pod_moves=False must get Deployment-scoped repairs
        backend = _backend()
        led = _ledger(registry)
        graph = backend.comm_graph()
        led.rebase(backend.monitor(), service_names=graph.names)
        moved = backend.external_move_random(random.Random(3))
        led.observe(backend.monitor(), service_names=graph.names)
        assert led.drift_pods == 1

        class _NoPodMoves:  # the k8s contract, sim-backed
            supports_pod_moves = False

            def apply_move(self, move):
                assert move.pod is None, (
                    "per-pod move reached a no-pod-move backend"
                )
                return backend.apply_move(move)

        class _Boundary:
            raw_backend = _NoPodMoves()

            def apply_move(self, move):
                return self.raw_backend.apply_move(move)

        issued = led.issue_repairs(_Boundary(), budget=2)
        assert [r["pod"] for r in issued] == [moved["pod"]]
        # the Deployment-wide pin re-homed every replica of the service;
        # record_moves(pod=None) re-intended them all — convergence
        out = led.observe(backend.monitor(), service_names=graph.names)
        assert out["divergences"] == [] and led.drift_pods == 0

    def test_advisory_move_override_adopted_not_drift(self, registry):
        # regression: the k8s backend can only echo the advisory target
        # at apply time (landed == requested), so a scheduler override
        # is observable only at the next monitor — it must be ADOPTED
        # there, never classified external_drift and force-pinned
        # against the live scheduler every round
        backend = _backend()
        led = _ledger(registry)
        graph = backend.comm_graph()
        led.rebase(backend.monitor(), service_names=graph.names)
        pod = backend.monitor().pod_names[0]
        svc = graph.names[0]
        # the boundary CLAIMED the advisory target rc3; the scheduler
        # actually placed the pod on rc5
        led.record_moves([(svc, pod, "rc3", "rc3", True)])
        backend.apply_move(
            MoveRequest(service=svc, pod=pod, target_node="rc5")
        )
        out = led.observe(backend.monitor(), service_names=graph.names)
        assert out["divergences"] == [] and led.drift_pods == 0
        assert led.intent[pod] == "rc5"  # the scheduler's pick, adopted
        assert (
            _counter(
                registry,
                "reconcile_divergences_total",
                kind=KIND_EXTERNAL_DRIFT,
            )
            == 0
        )

    def test_degraded_round_churn_events_survive_to_next_observe(
        self, registry
    ):
        # regression: a churn event carried by a DEGRADED round (no
        # admitted snapshot to diff) must wait in the ledger until the
        # next fresh observe — dropping it would let the teardown's pods
        # pass the debounce and read as missing_pod divergences
        backend = _backend()
        led = _ledger(registry)
        graph = backend.comm_graph()
        state = backend.monitor()
        led.rebase(state, service_names=graph.names)
        valid = np.asarray(state.pod_valid).copy()
        gone = int(np.flatnonzero(valid)[0])
        valid[gone] = False
        partial = state.replace(pod_valid=valid)
        svc = led.pod_service[state.pod_names[gone]]
        # the degraded round notes the teardown but cannot observe
        block, drift = reconcile_round_block(
            None,
            led,
            state=state,
            service_names=graph.names,
            churn_events=[{"kind": "service_teardown", "service": svc}],
            fresh=False,
            last_drift=0,
            boundary=None,
            repair_budget=0,
        )
        assert block is None and drift == 0
        assert led.pending_events  # the debt survives the round
        # two fresh rounds would beat the debounce if the event were lost
        for _ in range(2):
            block, _ = reconcile_round_block(
                None,
                led,
                state=partial,
                service_names=graph.names,
                churn_events=(),
                fresh=True,
                last_drift=0,
                boundary=None,
                repair_budget=0,
            )
            assert block is None
        assert led.pending_events == []  # consumed at the first fresh diff
        assert (
            _counter(
                registry, "reconcile_divergences_total", kind=KIND_MISSING_POD
            )
            == 0
        )

    def test_pending_events_survive_checkpoint_roundtrip(self, registry):
        backend = _backend()
        led = _ledger(registry)
        graph = backend.comm_graph()
        state = backend.monitor()
        led.rebase(state, service_names=graph.names)
        gone = int(np.flatnonzero(np.asarray(state.pod_valid))[0])
        svc = led.pod_service[state.pod_names[gone]]
        led.note_churn([{"kind": "service_teardown", "service": svc}])
        # a checkpoint taken on the degraded round carries the debt
        led2 = _ledger(registry)
        led2.restore(led.snapshot())
        assert led2.pending_events == led.pending_events
        valid = np.asarray(state.pod_valid).copy()
        valid[gone] = False
        partial = state.replace(pod_valid=valid)
        for _ in range(2):
            out = led2.observe(partial, service_names=graph.names)
            assert out["divergences"] == []

    def test_snapshot_restore_roundtrip(self, registry):
        backend = _backend()
        led = _ledger(registry)
        graph = backend.comm_graph()
        led.rebase(backend.monitor(), service_names=graph.names)
        snap = led.snapshot()
        led2 = _ledger(registry)
        led2.restore(snap)
        assert led2.intent == led.intent
        assert led2.pod_service == led.pod_service
        # a restored ledger observes instead of rebasing: drift while the
        # controller was down is a counted divergence, not adopted truth
        moved = backend.external_move_random(random.Random(9))
        out = led2.observe(backend.monitor(), service_names=graph.names)
        assert [d["kind"] for d in out["divergences"]] == [
            KIND_EXTERNAL_DRIFT
        ]
        assert out["divergences"][0]["pod"] == moved["pod"]
        led3 = _ledger(registry)
        led3.restore(None)  # pre-plane checkpoints carry no intent
        led3.restore({})


# ---------------- the controller integration ----------------

# timing-only fields (the pipelined/sequential comparison convention)
TIMING_FIELDS = {
    "decision_latencies_s", "decision_latency_s", "wall_s", "pipeline",
}


def _strip(rec) -> dict:
    return {k: v for k, v in rec.as_dict().items() if k not in TIMING_FIELDS}


def _run(
    *, n_nodes=8, rounds=12, algo="communication", chaos="none",
    chaos_seed=3, reconcile=None, pipeline=False, seed=0, backend=None,
    checkpoint_dir=None, moves_per_round=1, global_moves_cap="all",
):
    cfg = RescheduleConfig(
        algorithm=algo,
        max_rounds=rounds,
        moves_per_round=moves_per_round,
        global_moves_cap=global_moves_cap,
        sleep_after_action_s=0.0,
        seed=seed,
        chaos=ChaosConfig(profile=chaos, seed=chaos_seed),
        reconcile=reconcile if reconcile is not None else ReconcileConfig(),
        controller=ControllerConfig(pipeline=pipeline),
    )
    return run_controller(
        backend if backend is not None else _backend(n_nodes, seed=1),
        cfg,
        key=jax.random.PRNGKey(seed),
        checkpoint_dir=checkpoint_dir,
    )


class TestControllerReconcile:
    def test_clean_run_golden_pin(self, registry):
        """The no-fault golden pin: admission + reconcile leave a clean
        run bit-identical to the plane-off trajectory (the pre-PR
        records), and every record's reconcile block stays None."""
        on = _run(reconcile=ReconcileConfig())
        off = _run(reconcile=ReconcileConfig(admission=False, enabled=False))
        assert [_strip(a) for a in on.rounds] == [
            _strip(b) for b in off.rounds
        ]
        assert all(r.reconcile is None for r in on.rounds)

    @pytest.mark.parametrize(
        "algo",
        [
            "communication",
            pytest.param(
                "global",
                marks=pytest.mark.slow,  # heavy solver variant; the reconcile acceptance invariants keep their fast tier-1 pin in the communication case above
            ),
            pytest.param(
                "proactive",
                marks=pytest.mark.slow,  # heavy forecast variant; same fast pin as above (communication case)
            ),
        ],
    )
    def test_reconcile_soak_acceptance(self, registry, algo):
        """THE acceptance soak: 30 seeded rounds under the `reconcile`
        chaos profile (corrupt metrics + external drift + lost/wrong-node
        moves + node flap). Never raises; every injected fault is
        detected (wrapper fault_counts == registry, each reconcile-plane
        kind observed); divergences are classified and repaired back to
        convergence; no non-finite value ever reaches a kernel; round
        accounting is exact; steady state stays at 1 trace."""
        n_nodes = {"communication": 17, "global": 18, "proactive": 19}[algo]
        chaos = with_chaos(
            _backend(n_nodes, seed=1), "reconcile", seed=3, registry=registry
        )
        # the global solver's uncapped wave proposes many moves per round
        # — at the profile's 30% wrong-node/lost rates that divergence
        # inflow outruns any sane repair budget, so the global variant
        # runs the wave-capped mode (cap 2) with a matched budget; the
        # greedy variants keep the defaults
        res = _run(
            algo=algo, rounds=30, backend=chaos, chaos="none",
            global_moves_cap=2 if algo == "global" else "all",
            reconcile=(
                ReconcileConfig(repair_budget_per_round=4)
                if algo == "global"
                else None
            ),
        )
        # exact accounting: no silently lost rounds
        assert len(res.rounds) + res.skipped_rounds == 30
        # the wrapper's own counts == the registry (telemetry end to end)
        assert chaos.fault_counts
        for kind, n in chaos.fault_counts.items():
            assert _counter(registry, "chaos_faults_total", kind=kind) == n
        # the reconcile-plane fault kinds all fired at these rates
        for kind in ("monitor_corrupt", "external_drift", "move_lost"):
            assert chaos.fault_counts.get(kind, 0) >= 1, kind
        # ... and were detected: admission quarantined the corrupt
        # readings, the ledger classified the placement divergences
        assert _counter(registry, "admission_quarantined_total") >= 1
        seen = {
            d["kind"]
            for r in res.rounds
            for d in (r.reconcile or {}).get("divergences", ())
        }
        assert {KIND_WRONG_NODE, KIND_EXTERNAL_DRIFT} <= seen
        assert _counter(registry, "reconcile_repair_moves_total") >= 1
        # convergence: corrective moves brought observed back to intent
        # within the per-round budget — no standing drift at the end
        assert _counter(registry, "reconcile_drift_pods") == 0
        # no non-finite value ever reached a kernel: every recorded
        # metric the round-end kernels computed is finite
        for r in res.rounds:
            assert math.isfinite(r.communication_cost)
            assert math.isfinite(r.load_std)
        # 1-trace steady state (fresh shapes for this file): no kernel
        # re-traced across 30 faulted rounds
        for rec in registry.snapshot():
            if rec["metric"] == "jax_traces_total":
                assert rec["value"] == 1, rec["labels"]

    def test_pipelined_soak_bit_identical_to_sequential(self, registry):
        """The pipelined schedule under the full reconcile fault menu —
        same divergences, same repairs, same records modulo timing."""
        seq = _run(chaos="reconcile", rounds=12)
        pl = _run(chaos="reconcile", rounds=12, pipeline=True)
        assert [_strip(a) for a in seq.rounds] == [
            _strip(b) for b in pl.rounds
        ]
        assert seq.skipped_rounds == pl.skipped_rounds

    def test_unknown_landing_regression(self, registry):
        """The greedy landed-node patch (bench/controller.py): a move
        that lands on a node NOT in ``state.node_names`` — a
        cluster-autoscaler node appearing mid-flight, here injected by a
        wrapper under node-flap chaos — must not silently patch the
        working snapshot with the stale target index: it is a counted
        ``unknown_landing`` divergence and the round finishes degraded.
        (Elastic churn cannot express a never-seen node — bucket
        capacity is a hard invariant and node growth routes through the
        churn engine — so the wrapper plays the autoscaler.)"""

        class AutoscaleLanding:
            def __init__(self, inner):
                self.inner = inner
                self.fired = False

            def apply_move(self, move):
                if not self.fired:
                    self.fired = True
                    self.inner.add_node("autoscaled-x")
                    return self.inner.apply_move(
                        dataclasses.replace(move, target_node="autoscaled-x")
                    )
                return self.inner.apply_move(move)

            def __getattr__(self, name):
                return getattr(self.inner, name)

        wrapped = AutoscaleLanding(_backend(8, seed=1))
        res = _run(
            backend=wrapped, chaos="node-flap", chaos_seed=2, rounds=6,
            moves_per_round=2,
        )
        assert wrapped.fired
        assert (
            _counter(
                registry, "reconcile_divergences_total",
                kind="unknown_landing",
            )
            == 1
        )
        assert res.rounds[0].degraded  # honest-but-stale close, counted
        assert len(res.rounds) + res.skipped_rounds == 6  # and no crash

    def test_advisory_override_is_not_drift(self, registry):
        """Advisory moves (affinityOnly — the kubescheduling algorithm)
        leave the landing to the scheduler: an override is legitimate
        placement the ledger adopts as intent at apply time, NEVER a
        ``wrong_node`` divergence to count or repair. The wrapper plays
        a scheduler whose view disagrees with the advisory target every
        single round; the reconcile plane must stay silent — no
        divergences, no repair moves fighting the scheduler."""

        class SchedulerOverride:
            def __init__(self, inner):
                self.inner = inner
                self.overrode = 0

            def apply_move(self, move):
                if move.mechanism == "affinityOnly":
                    other = next(
                        n
                        for n in self.inner.alive_node_names()
                        if n != move.target_node
                    )
                    self.overrode += 1
                    return self.inner.apply_move(
                        dataclasses.replace(
                            move, mechanism="nodeSelector", target_node=other
                        )
                    )
                return self.inner.apply_move(move)

            def __getattr__(self, name):
                return getattr(self.inner, name)

        wrapped = SchedulerOverride(_backend(8, seed=1))
        res = _run(backend=wrapped, algo="kubescheduling", rounds=6)
        assert wrapped.overrode > 0  # the disagreement actually happened
        for kind in ("wrong_node", "external_drift", "lost_move"):
            assert (
                _counter(registry, "reconcile_divergences_total", kind=kind)
                == 0
            )
        assert _counter(registry, "reconcile_repair_moves_total") == 0
        # the plane saw nothing to do: every round's block is clean
        assert all(r.reconcile is None for r in res.rounds)

    def test_admission_reject_degrades_round(self, registry):
        """A structurally broken snapshot (duplicate pod) is rejected
        whole: the boundary is charged, the round degrades on the last
        good snapshot, and the loop keeps going."""

        class DuplicatePodOnce:
            def __init__(self, inner):
                self.inner = inner
                self.calls = 0

            def monitor(self):
                state = self.inner.monitor()
                self.calls += 1
                if self.calls == 3:  # round 2's post-move snapshot
                    names = list(state.pod_names)
                    names[1] = names[0]
                    return state.replace(pod_names=tuple(names))
                return state

            def __getattr__(self, name):
                return getattr(self.inner, name)

        res = _run(backend=DuplicatePodOnce(_backend(8, seed=1)), rounds=4)
        assert (
            _counter(
                registry, "admission_rejected_total", reason="duplicate_pod"
            )
            == 1
        )
        degraded = [r for r in res.rounds if r.degraded]
        assert len(degraded) == 1
        assert degraded[0].reconcile["admission"] == {
            "rejected:duplicate_pod": 1
        }
        assert len(res.rounds) == 4  # no round lost to the garbage

    def test_checkpoint_resume_reconciles_drift(self, registry, tmp_path):
        """A backend that IS its own state (no ``restore_placement`` —
        the live-cluster resume semantics): a pod drifting while the
        controller is down is a counted divergence against the
        checkpointed intent on resume, then repaired — never silently
        adopted as truth."""
        sim = _backend(8, seed=1)

        class LiveCluster:
            """The k8s surface only: no sim-side restore/batch escape
            hatches, so resume must trust the LEDGER, not a rewind."""

            def __init__(self, inner):
                self.monitor = inner.monitor
                self.comm_graph = inner.comm_graph
                self.apply_move = inner.apply_move
                self.advance = inner.advance

        _run(
            backend=LiveCluster(sim), rounds=4,
            checkpoint_dir=str(tmp_path),
        )
        moved = sim.external_move_random(random.Random(0))
        res = _run(
            backend=LiveCluster(sim), rounds=6,
            checkpoint_dir=str(tmp_path),
        )
        assert res.resumed_from_round == 5
        divergences = [
            d
            for r in res.rounds
            for d in (r.reconcile or {}).get("divergences", ())
        ]
        assert any(
            d["kind"] == KIND_EXTERNAL_DRIFT and d["pod"] == moved["pod"]
            for d in divergences
        )
        # the repair landed: the drifted pod is back where intent says
        state = sim.monitor()
        i = state.pod_names.index(moved["pod"])
        landed = state.node_names[int(np.asarray(state.pod_node)[i])]
        assert landed == moved["from"]

    def test_skip_round_checkpoint_keeps_churn_events_for_resume(
        self, registry, tmp_path
    ):
        """Regression: a checkpoint written by a SKIPPED round carries
        churn events applied in its preamble that no record has flushed
        yet — resume must restore the debt so the first executed round's
        record carries them and the intent ledger consumes them (a
        teardown while the breaker was open must never read as
        missing_pod divergences after resume)."""
        from kubernetes_rescheduling_tpu.elastic.engine import ChurnEngine
        from kubernetes_rescheduling_tpu.elastic.events import ServiceTeardown

        class _FlakyMonitor:
            """Delegating wrapper whose monitor() can be switched off —
            drives the breaker open mid-run, deterministically."""

            def __init__(self, inner):
                self._inner = inner
                self.fail = False

            def monitor(self):
                if self.fail:
                    raise ConnectionError("monitor window down")
                return self._inner.monitor()

            def __getattr__(self, name):
                return getattr(self._inner, name)

        class _TeardownAt:
            """Stateless stub profile: one teardown at a fixed round, so
            the resume fast-forward replays the identical stream."""

            def __init__(self, svc, rnd):
                self.svc, self.rnd = svc, rnd

            def events(self, rng, rnd, horizon, view):
                return (
                    [ServiceTeardown(service=self.svc)]
                    if rnd == self.rnd
                    else []
                )

        def engine(svc):
            eng = ChurnEngine("steady", seed=0, registry=registry)
            eng.profile = _TeardownAt(svc, 5)  # fires while breaker OPEN
            return eng

        svc = _backend(8, seed=1).comm_graph().names[-1]
        cfg = RescheduleConfig(
            algorithm="communication",
            max_rounds=8,
            sleep_after_action_s=0.0,
            seed=3,
            max_consecutive_failures=2,
            reconcile=ReconcileConfig(),
        )
        flaky = _FlakyMonitor(_backend(8, seed=1))

        def arm(rec, _state):
            if rec.round == 2:
                flaky.fail = True  # post-move monitors fail from round 3

        res = run_controller(
            flaky, cfg, key=jax.random.PRNGKey(3), registry=registry,
            checkpoint_dir=str(tmp_path), churn=engine(svc), on_round=arm,
        )
        assert res.skipped_rounds > 0  # breaker opened; skip saves ran

        resumed = run_controller(
            _FlakyMonitor(_backend(8, seed=1)),
            dataclasses.replace(cfg, max_rounds=10),
            key=jax.random.PRNGKey(3), registry=registry,
            checkpoint_dir=str(tmp_path), churn=engine(svc),
        )
        assert resumed.resumed_from_round == 9
        assert len(resumed.rounds) == 2
        # the skipped rounds' teardown flushed into the first resumed
        # record, and the ledger consumed it — no false divergences
        first = resumed.rounds[0]
        assert any(
            e["kind"] == "service_teardown" and e["service"] == svc
            for e in (first.churn or {}).get("events", ())
        )
        for kind in (KIND_MISSING_POD, KIND_PHANTOM_POD):
            assert (
                _counter(registry, "reconcile_divergences_total", kind=kind)
                == 0
            )

    def test_watchdog_reconcile_divergence_rule(self, registry):
        wd = Watchdog(
            SLORules(reconcile_max_drift_pods=1), registry=registry
        )

        def rec(reconcile):
            return RoundRecord(
                round=1, moved=False, most_hazard=None, service=None,
                target=None, communication_cost=1.0, load_std=0.0,
                reconcile=reconcile,
            )

        assert wd.observe_round(rec(None)) == []  # no reconcile data: mute
        raised = wd.observe_round(rec({"drift_pods": 2}))
        assert [v["rule"] for v in raised] == [RULE_RECONCILE]
        assert not wd.healthy
        assert (
            _counter(registry, "slo_violations_total", rule=RULE_RECONCILE)
            == 1
        )
        # the convergence round carries an explicit drift_pods=0 block —
        # that is what clears the rule (see _Runtime._reconcile_round)
        assert wd.observe_round(rec({"drift_pods": 0})) == []
        assert wd.healthy

    def test_watchdog_reconcile_rule_is_per_tenant(self, registry):
        # regression: the rule used to judge the single LATEST reconcile
        # block across all tenants — a clean tenant's drift_pods=0 round
        # observed after a drifting tenant's round masked the violation
        # (or flapped it violation->recovered every fleet round)
        wd = Watchdog(
            SLORules(reconcile_max_drift_pods=1), registry=registry
        )

        def rec(reconcile):
            return RoundRecord(
                round=1, moved=False, most_hazard=None, service=None,
                target=None, communication_cost=1.0, load_std=0.0,
                reconcile=reconcile,
            )

        raised = wd.observe_round(rec({"drift_pods": 3}), tenant="t-drift")
        assert [v["rule"] for v in raised] == [RULE_RECONCILE]
        assert raised[0]["tenant"] == "t-drift"
        # the clean tenant's round must NOT clear the drifting tenant's
        # violation — no flap, no re-count
        assert wd.observe_round(rec({"drift_pods": 0}), tenant="t-clean") == []
        assert not wd.healthy
        assert (
            _counter(registry, "slo_violations_total", rule=RULE_RECONCILE)
            == 1
        )
        # only the drifting tenant's own convergence clears it
        assert wd.observe_round(rec({"drift_pods": 0}), tenant="t-drift") == []
        assert wd.healthy


# ---------------- the fleet integration ----------------


class TestFleetReconcile:
    def test_per_tenant_ledgers_and_isolation(self, registry):
        """Reconcile-profile chaos on tenant 0 only: tenant 0 detects
        and repairs its divergences, tenant 1 sees none, and the drift
        gauge is tenant-labeled."""
        fleet = FleetBackend(
            [_backend(8, seed=1), _backend(8, seed=2)],
            tenant_names=("t-chaos", "t-clean"),
        )
        cfg = RescheduleConfig(
            algorithm="communication",
            max_rounds=12,
            sleep_after_action_s=0.0,
            chaos=ChaosConfig(profile="reconcile", seed=3),
            fleet=FleetConfig(tenants=2, chaos_tenants=(0,)),
        )
        res = run_fleet_controller(fleet, cfg, key=jax.random.PRNGKey(0))
        div = {
            name: [
                d
                for rec in r.rounds
                for d in (rec.reconcile or {}).get("divergences", ())
            ]
            for name, r in res.results.items()
        }
        assert div["t-chaos"]  # faults detected on the chaotic tenant
        assert div["t-clean"] == []  # and ONLY there
        for name in ("t-chaos", "t-clean"):
            assert (
                _counter(
                    registry, "fleet_reconcile_drift_pods", tenant=name
                )
                == 0
            )  # both tenants converged (repairs ran through the budget)
