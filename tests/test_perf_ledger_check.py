"""CI twin of ``scripts/check_perf_ledger.py``: ledger JSONL files keep
their schema (required keys, finite values, strictly monotone seq) —
validated against a synthetic ledger written through ``PerfLedger`` AND
one built from the checked-in ``BENCH_r*.json`` history, plus pinned
rejection of each corruption class."""

import importlib.util
import json
import sys
from pathlib import Path

import pytest

from kubernetes_rescheduling_tpu.telemetry.perf_ledger import (
    PerfLedger,
    ingest_history,
)

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(scope="module")
def checker():
    path = REPO / "scripts" / "check_perf_ledger.py"
    spec = importlib.util.spec_from_file_location("check_perf_ledger", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules.setdefault("check_perf_ledger", mod)
    spec.loader.exec_module(mod)
    return mod


def _synthetic(path):
    led = PerfLedger(path)
    for i, v in enumerate((10.0, 9.0, 11.0)):
        led.append(
            metric="decisions_per_sec", value=v, unit="1/s",
            scenario="t", device_kind="cpu", digest="d", better="higher",
            run=i,
        )
    return path


def test_synthetic_ledger_validates(checker, tmp_path):
    path = _synthetic(tmp_path / "ok.jsonl")
    assert checker.check_ledger_file(path) == []


def test_ledger_from_checked_in_bench_history_validates(checker, tmp_path):
    history = sorted(REPO.glob("BENCH_r0*.json")) + sorted(
        REPO.glob("MULTICHIP_r0*.json")
    )
    assert history, "checked-in bench snapshots are part of this pin"
    path = tmp_path / "hist.jsonl"
    ingest_history(history, PerfLedger(path))
    assert checker.check_ledger_file(path) == []


@pytest.mark.parametrize(
    "mutate, expect",
    [
        (lambda r: r.pop("metric"), "missing key 'metric'"),
        (lambda r: r.update(value=float("nan")), "non-finite"),
        (lambda r: r.update(value="fast"), "must be a number"),
        (lambda r: r.update(seq=0), "not monotone"),
        (lambda r: r.update(better="sideways"), "better must be"),
    ],
)
def test_corruptions_are_rejected(checker, tmp_path, mutate, expect):
    path = _synthetic(tmp_path / "bad.jsonl")
    recs = [json.loads(ln) for ln in path.read_text().splitlines()]
    mutate(recs[-1])
    path.write_text("".join(json.dumps(r) + "\n" for r in recs))
    bad = checker.check_ledger_file(path)
    assert any(expect in v for v in bad), bad


def test_non_json_and_missing_files_flagged(checker, tmp_path):
    p = tmp_path / "junk.jsonl"
    p.write_text("{broken\n")
    assert any("not JSON" in v for v in checker.check_ledger_file(p))
    assert checker.check_ledger_file(tmp_path / "nope.jsonl")
    empty = tmp_path / "empty.jsonl"
    empty.write_text("\n")
    assert any("no ledger records" in v for v in checker.check_ledger_file(empty))


def test_script_self_check_passes(checker):
    assert checker.self_check() == []
    assert checker.main([]) == 0
