"""CI twin of ``scripts/check_bench_schema.py``: the checked-in driver
snapshots (``BENCH_r*.json`` / ``MULTICHIP_r*.json``) carry the record
keys perf-ledger ingestion series on — and the checker actually catches
each corruption class that would otherwise be dropped silently
(``ingest_bench_file`` is lenient by design; this is the loud half)."""

import importlib.util
import json
import sys
from pathlib import Path


def _load_checker():
    path = (
        Path(__file__).resolve().parent.parent
        / "scripts"
        / "check_bench_schema.py"
    )
    spec = importlib.util.spec_from_file_location("check_bench_schema", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules.setdefault("check_bench_schema", mod)
    spec.loader.exec_module(mod)
    return mod


GOOD = {
    "n": 6,
    "cmd": "python bench.py",
    "rc": 0,
    "tail": "{...}",
    "parsed": {
        "metric": "device_round_ms_large",
        "value": 26.776,
        "unit": "ms",
        "vs_baseline": 3.735,
        "extra": {"scenario": "large"},
    },
}


def test_checked_in_history_is_clean():
    checker = _load_checker()
    assert checker.violations() == []


def test_good_record_passes(tmp_path):
    checker = _load_checker()
    f = tmp_path / "BENCH_r99.json"
    f.write_text(json.dumps(GOOD))
    assert checker.check_file(f) == []


def test_corruption_classes_are_caught(tmp_path):
    """Five pinned corruption classes, each of which ingest_bench_file
    would swallow into zero records (or a broken series) without a word."""
    checker = _load_checker()

    def corrupt(name, mutate):
        doc = json.loads(json.dumps(GOOD))
        mutate(doc)
        f = tmp_path / name
        f.write_text(json.dumps(doc))
        return checker.check_file(f)

    # 1. no parsed block at all — the whole snapshot vanishes from history
    bad = corrupt("BENCH_r90.json", lambda d: d.pop("parsed"))
    assert any("no parsed headline" in v for v in bad)
    # 2. non-finite value — would poison the detector's baseline math
    bad = corrupt(
        "BENCH_r91.json", lambda d: d["parsed"].__setitem__("value", "fast")
    )
    assert any("finite number" in v for v in bad)
    # 3. missing metric name — the series key collapses
    bad = corrupt(
        "BENCH_r92.json", lambda d: d["parsed"].__setitem__("metric", "")
    )
    assert any("metric" in v for v in bad)
    # 4. extra not a dict — scenario/device attribution is lost
    bad = corrupt(
        "BENCH_r93.json", lambda d: d["parsed"].__setitem__("extra", [1])
    )
    assert any("extra" in v for v in bad)
    # 5. invalid JSON — unreadable snapshot
    f = tmp_path / "BENCH_r94.json"
    f.write_text("{not json")
    assert any("invalid JSON" in v for v in checker.check_file(f))


def test_multichip_shape(tmp_path):
    checker = _load_checker()
    ok = tmp_path / "MULTICHIP_r99.json"
    ok.write_text(json.dumps({"n_devices": 4, "ok": True, "rc": 0}))
    assert checker.check_file(ok) == []
    bad = tmp_path / "MULTICHIP_r98.json"
    bad.write_text(json.dumps({"n_devices": "four", "ok": 1, "rc": None}))
    out = checker.check_file(bad)
    assert len(out) == 3


def _multichip_like():
    """bench.multichip.bench_multichip's measured envelope (r06+): the
    driver keys plus device_kind and the parsed throughput headline
    nesting its per-device rollup sibling."""
    return {
        "n_devices": 8,
        "device_kind": "cpux8",
        "rc": 0,
        "ok": True,
        "measured": True,
        "cmd": "BENCH_SCENARIO=multichip python bench.py",
        "tail": "{...}",
        "parsed": {
            "metric": "fleet_scan_rounds_per_sec",
            "value": 163.9,
            "unit": "rounds/s",
            "better": "higher",
            "extra": {
                "scenario": "multichip",
                "tenants": 16,
                "n_devices": 8,
                "device_kind": "cpux8",
                "rounds_per_block": 8,
            },
            "device_step_reading": {
                "metric": "multichip_device_step_ms_p99",
                "value": 0.33,
                "unit": "ms",
                "better": "lower",
                "extra": {"scenario": "multichip", "n_devices": 8},
            },
        },
    }


def test_multichip_measured_shape(tmp_path):
    """The measured MULTICHIP record (r06+) passes, and each pinned
    corruption class — a record the legacy 3-key check would wave
    through — is flagged."""
    checker = _load_checker()
    ok = tmp_path / "MULTICHIP_r97.json"
    ok.write_text(json.dumps(_multichip_like()))
    assert checker.check_file(ok) == []

    def corrupt(name, mutate):
        doc = json.loads(json.dumps(_multichip_like()))
        mutate(doc)
        f = tmp_path / name
        f.write_text(json.dumps(doc))
        return checker.check_file(f)

    # 1. missing device_kind — forced-host and real-slice runs would
    # share a trend series
    bad = corrupt("MULTICHIP_r96.json", lambda d: d.pop("device_kind"))
    assert any("device_kind" in v for v in bad)
    # 2. non-finite headline value
    bad = corrupt(
        "MULTICHIP_r95.json",
        lambda d: d["parsed"].__setitem__("value", float("nan")),
    )
    assert any("finite" in v for v in bad)
    # 3. throughput direction lost — a rounds/sec gain would trend as a
    # regression
    bad = corrupt(
        "MULTICHIP_r94.json", lambda d: d["parsed"].pop("better")
    )
    assert any("better='higher'" in v for v in bad)
    # 4. wrong unit on the headline
    bad = corrupt(
        "MULTICHIP_r93.json",
        lambda d: d["parsed"].__setitem__("unit", "ms"),
    )
    assert any("unit='rounds/s'" in v for v in bad)
    # 5. per-device rollup sibling dropped — throughput without the
    # device axis is half the record
    bad = corrupt(
        "MULTICHIP_r92.json",
        lambda d: d["parsed"].pop("device_step_reading"),
    )
    assert any("device_step_reading" in v for v in bad)
    # 6. the nested device series with a flipped direction
    bad = corrupt(
        "MULTICHIP_r91.json",
        lambda d: d["parsed"]["device_step_reading"].__setitem__(
            "better", "higher"
        ),
    )
    assert any("better='lower'" in v for v in bad)
    # 7. extra.n_devices not an int — the ledger's mesh-identity key
    bad = corrupt(
        "MULTICHIP_r90.json",
        lambda d: d["parsed"]["extra"].__setitem__("n_devices", "8"),
    )
    assert any("n_devices" in v for v in bad)
    # 8. a measured record whose headline is some other metric
    bad = corrupt(
        "MULTICHIP_r89.json",
        lambda d: d["parsed"].__setitem__("metric", "scan_rounds_per_sec"),
    )
    assert any("fleet_scan_rounds_per_sec" in v for v in bad)


def test_multichip_measured_ledger_ingestion(tmp_path):
    """A measured record ingests as TWO series (headline + device
    rollup), both keyed by the mesh identity — never the legacy BENCH
    branch's first-device-name key or hardcoded better='lower' — and
    the legacy dryrun shape still ingests byte-identically."""
    from kubernetes_rescheduling_tpu.telemetry.perf_ledger import (
        config_digest,
        ingest_bench_file,
    )

    f = tmp_path / "MULTICHIP_r06.json"
    f.write_text(json.dumps(_multichip_like()))
    recs = ingest_bench_file(f)
    assert [r["metric"] for r in recs] == [
        "fleet_scan_rounds_per_sec",
        "multichip_device_step_ms_p99",
    ]
    for r in recs:
        assert r["device_kind"] == "cpux8"
        assert r["config_digest"] == config_digest({"n_devices": 8})
        assert r["extra"]["n_devices"] == 8
    assert recs[0]["better"] == "higher"
    assert recs[1]["better"] == "lower"

    legacy = tmp_path / "MULTICHIP_r05.json"
    legacy.write_text(
        json.dumps({"n_devices": 8, "rc": 0, "ok": True, "tail": "..."})
    )
    (rec,) = ingest_bench_file(legacy)
    assert rec["metric"] == "multichip_dryrun_ok"
    assert rec["device_kind"] == "mesh"
    assert rec["value"] == 1.0
    assert rec["better"] == "higher"


def test_fleet_headline_conforms():
    """The new fleet cell's result dict (bench.bench_fleet's shape)
    satisfies the same parsed-record schema the history is held to —
    schema and producer cannot drift apart silently."""
    checker = _load_checker()
    fleet_like = {
        "metric": "device_round_ms_fleet_per_tenant",
        "value": 0.42,
        "unit": "ms",
        "vs_baseline": 238.0,
        "extra": {"scenario": "fleet", "tenants": 16, "vs_solo": 8.5},
    }
    assert checker.check_parsed(fleet_like, "fleet") == []


def test_fleet_rollup_reading_conforms():
    """The fleet cell's second ledger series — steady-state rounds/sec
    with the tenant-rollup plane on (better: higher) — satisfies the
    same parsed-record schema as the headline."""
    checker = _load_checker()
    rollup_like = {
        "metric": "fleet_rounds_per_sec_rollup",
        "value": 83.1,
        "unit": "rounds/s",
        "better": "higher",
        "extra": {
            "scenario": "fleet",
            "tenants": 16,
            "rollup_top_k": 3,
            "rollup_off_rounds_per_sec": 85.0,
        },
    }
    assert checker.check_parsed(rollup_like, "fleet-rollup") == []


def test_pipeline_headline_conforms():
    """The pipeline cell's result dict (bench.bench_pipeline's shape —
    the wall_round_ms perf-ledger series) satisfies the same
    parsed-record schema the history is held to."""
    checker = _load_checker()
    pipeline_like = {
        "metric": "wall_round_ms",
        "value": 41.2,
        "unit": "ms",
        "vs_baseline": 2.43,
        "extra": {
            "scenario": "pipeline",
            "rounds": 12,
            "sequential_wall_round_ms": 139.0,
            "device_ms_per_round": 26.8,
            "wall_vs_device": 1.54,
            "speedup_vs_sequential": 3.37,
            "rtt_ms": 25.0,
            "overlap_ratio_mean": 0.82,
            "bit_identical": True,
        },
    }
    assert checker.check_parsed(pipeline_like, "pipeline") == []


def test_scan_headline_conforms():
    """The scan cell's result dict (bench.bench_scan's shape — the
    scan_rounds_per_sec perf-ledger series, the first throughput series
    with ``better: higher``) satisfies the same parsed-record schema the
    history is held to."""
    checker = _load_checker()
    scan_like = {
        "metric": "scan_rounds_per_sec",
        "value": 183.4,
        "unit": "rounds/s",
        "better": "higher",
        "vs_baseline": 18.3,
        "extra": {
            "scenario": "scan",
            "rounds": 48,
            "scan_block": 16,
            "scan_blocks_total": 4,
            "sequential_rounds_per_sec": 30.1,
            "pipelined_rounds_per_sec": 31.9,
            "whole_loop_rounds_per_sec": {
                "sequential": 27.2, "pipelined": 27.3, "scanned": 105.4,
            },
            "speedup_vs_pipelined": 5.74,
            "speedup_vs_sequential": 6.1,
            "bit_identical": True,
            "scan_traces": 1,
            "traces_pinned": True,
        },
    }
    assert checker.check_parsed(scan_like, "scan") == []


def _serve_like():
    """bench.bench_serve's paired shape: the placements/sec headline
    nesting its p99 latency and error-budget-burn siblings."""
    return {
        "metric": "serving_placements_per_sec",
        "value": 355.3,
        "unit": "req/s",
        "better": "higher",
        "vs_baseline": 0.888,
        "extra": {"scenario": "serve", "requests": 64, "max_batch": 8},
        "p99_reading": {
            "metric": "serving_p99_ms",
            "value": 15.4,
            "unit": "ms",
            "better": "lower",
            "vs_baseline": 16.2,
            "extra": {"scenario": "serve"},
        },
        "slo_reading": {
            "metric": "slo_budget_burn_frac",
            "value": 0.31,
            "unit": "frac",
            "better": "lower",
            "vs_baseline": 3.2,
            "extra": {"scenario": "serve", "objective": 0.99,
                      "good": 62, "bad": 2},
        },
    }


def test_serve_headline_pair_conforms():
    """The serve cell's result dict (bench.bench_serve's shape — the
    repo's first request-latency pair: placements/sec with its nested
    p99-ms sibling) satisfies the parsed-record schema."""
    checker = _load_checker()
    assert checker.check_parsed(_serve_like(), "serve") == []


def test_serve_pair_corruptions_are_caught():
    """The serve-specific rules actually bite: a throughput series that
    forgets its direction, loses its p99 sibling, or a p99 series with
    the wrong direction or unit is flagged, not silently ingested."""
    checker = _load_checker()

    def corrupt(mutate):
        doc = json.loads(json.dumps(_serve_like()))
        mutate(doc)
        return checker.check_parsed(doc, "serve")

    bad = corrupt(lambda d: d.pop("better"))
    assert any("better='higher'" in v for v in bad)
    bad = corrupt(lambda d: d.pop("p99_reading"))
    assert any("p99_reading" in v for v in bad)
    bad = corrupt(lambda d: d["p99_reading"].__setitem__("better", "higher"))
    assert any("better='lower'" in v for v in bad)
    bad = corrupt(lambda d: d["p99_reading"].__setitem__("unit", "s"))
    assert any("unit='ms'" in v for v in bad)
    # the nested sibling is itself a ledger record: a non-finite value
    # inside it must be caught by the recursive *_reading walk
    bad = corrupt(lambda d: d["p99_reading"].__setitem__("value", None))
    assert any("p99_reading" in v and "finite" in v for v in bad)
    # the error-budget sibling has its own pinned corruption classes: a
    # serve cell that drops budget accounting, flips the direction, or
    # drifts the unit must be flagged, not silently ingested
    bad = corrupt(lambda d: d.pop("slo_reading"))
    assert any("slo_reading" in v for v in bad)
    bad = corrupt(lambda d: d["slo_reading"].__setitem__("better", "higher"))
    assert any("better='lower'" in v and "budget" in v for v in bad)
    bad = corrupt(lambda d: d["slo_reading"].__setitem__("unit", "pct"))
    assert any("unit='frac'" in v for v in bad)
