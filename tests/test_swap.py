"""Pairwise-exchange (swap) phase: deadlock escape, oscillation safety,
capacity preservation, and lowering parity.

The scenarios pin the three properties that make swaps safe to default-on:
the phase breaks single-move capacity deadlocks (its reason to exist),
the cross-swap interaction term prevents synchronous pair rotations from
undoing each other, and admitted swaps never violate node budgets."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_rescheduling_tpu.core.state import ClusterState, CommGraph
from kubernetes_rescheduling_tpu.objectives.metrics import communication_cost
from kubernetes_rescheduling_tpu.solver.global_solver import (
    GlobalSolverConfig,
    global_assign,
)


def deadlock_scenario():
    """Two full nodes; s0@n0 pairs with s2@n1, s1@n1 pairs with s3@n0.
    Every improving single move busts a budget — only the s0<->s1
    exchange (cost 20 -> 0) is feasible, and it needs to be atomic."""
    state = ClusterState.build(
        node_names=["n0", "n1"],
        node_cpu_cap=[200.0, 200.0],
        node_mem_cap=[1e9, 1e9],
        pod_services=[0, 1, 2, 3],
        pod_nodes=[0, 1, 1, 0],
        pod_cpu=[100.0] * 4,
        pod_mem=[1.0] * 4,
    )
    adj = np.zeros((4, 4), np.float32)
    adj[0, 2] = adj[2, 0] = 10.0
    adj[1, 3] = adj[3, 1] = 10.0
    graph = CommGraph(
        adj=jnp.asarray(adj),
        service_valid=jnp.ones(4, bool),
        names=("s0", "s1", "s2", "s3"),
    )
    return state, graph


class TestDeadlockEscape:
    def test_single_moves_stuck(self):
        state, graph = deadlock_scenario()
        cfg = GlobalSolverConfig(
            sweeps=9, swap_every=0, noise_temp=0.0, chunk_size=4
        )
        _, info = global_assign(state, graph, jax.random.PRNGKey(0), cfg)
        assert float(info["objective_after"]) == 20.0

    @pytest.mark.parametrize("noise", [0.0, 1.0])
    def test_swap_reaches_optimum(self, noise):
        state, graph = deadlock_scenario()
        cfg = GlobalSolverConfig(
            sweeps=9, swap_every=1, noise_temp=noise, chunk_size=4
        )
        new_state, info = global_assign(state, graph, jax.random.PRNGKey(0), cfg)
        assert float(info["objective_after"]) == 0.0
        assert float(communication_cost(new_state, graph)) == 0.0
        assert int(np.sum(np.asarray(info["swaps_per_sweep"]))) >= 1
        # budgets still respected after the exchange
        assert np.all(
            np.asarray(new_state.node_cpu_used())
            <= np.asarray(new_state.node_cpu_cap) + 1e-6
        )

    def test_default_config_escapes(self):
        # swap_every=3 is the default — sweeps 2, 5, 8 carry the phase
        state, graph = deadlock_scenario()
        cfg = GlobalSolverConfig(sweeps=9, noise_temp=0.0, chunk_size=4)
        _, info = global_assign(state, graph, jax.random.PRNGKey(0), cfg)
        assert float(info["objective_after"]) == 0.0
        sw = np.asarray(info["swaps_per_sweep"])
        assert sw[0] == 0 and sw[1] == 0  # non-swap sweeps really skip


class TestOscillationSafety:
    def test_symmetric_pairs_converge(self):
        """Two tied symmetric exchange pairs: admitting both rotates the
        whole placement and gains nothing (each pair's gain assumed the
        other stayed). The interaction term must serialize them — the
        objective lands at 0, not back at 20."""
        state, graph = deadlock_scenario()
        cfg = GlobalSolverConfig(
            sweeps=2, swap_every=1, noise_temp=0.0, chunk_size=4
        )
        new_state, info = global_assign(state, graph, jax.random.PRNGKey(0), cfg)
        assert float(info["objective_after"]) == 0.0
        # exactly one pair swaps on the first swap sweep (the other is
        # interaction-rejected); the second sweep finds nothing left
        sw = np.asarray(info["swaps_per_sweep"])
        assert sw[0] == 1


class TestCapacitySafety:
    @pytest.mark.parametrize("seed", range(4))
    def test_random_instances_stay_feasible_and_never_worse(self, seed):
        rng = np.random.default_rng(seed)
        S, N = 24, 4
        cap = 600.0
        cpu = rng.integers(1, 4, S) * 100.0
        # random feasible-ish start: spread round-robin by size
        order = np.argsort(-cpu)
        nodes = np.zeros(S, np.int64)
        loads = np.zeros(N)
        for s in order:
            n = int(np.argmin(loads))
            nodes[s] = n
            loads[n] += cpu[s]
        adj = np.triu(rng.random((S, S)) < 0.2, 1).astype(np.float32)
        adj = adj + adj.T
        state = ClusterState.build(
            node_names=[f"n{i}" for i in range(N)],
            node_cpu_cap=[cap] * N,
            node_mem_cap=[1e9] * N,
            pod_services=list(range(S)),
            pod_nodes=nodes.tolist(),
            pod_cpu=cpu.tolist(),
            pod_mem=[1.0] * S,
        )
        graph = CommGraph(
            adj=jnp.asarray(adj),
            service_valid=jnp.ones(S, bool),
            names=tuple(f"s{i}" for i in range(S)),
        )
        feasible_in = bool(np.all(loads <= cap))
        cfg = GlobalSolverConfig(
            sweeps=6, swap_every=1, noise_temp=1.0, chunk_size=12
        )
        new_state, info = global_assign(state, graph, jax.random.PRNGKey(seed), cfg)
        assert float(info["objective_after"]) <= float(info["objective_before"]) + 1e-4
        if feasible_in:
            assert np.all(
                np.asarray(new_state.node_cpu_used())
                <= np.asarray(new_state.node_cpu_cap) + 1e-3
            )


class TestLoweringParity:
    def test_interpret_kernels_match_xla(self):
        """The fused (interpret) and plain-XLA lowerings must make the
        same decisions with noise off — including through the swap phase
        (which runs in XLA on both, fed by each lowering's M)."""
        rng = np.random.default_rng(7)
        S, N = 32, 4
        cpu = rng.integers(1, 3, S) * 100.0
        nodes = rng.integers(0, N, S)
        adj = np.triu(rng.random((S, S)) < 0.3, 1).astype(np.float32) * (
            rng.integers(1, 5, (S, S))
        )
        adj = adj + adj.T
        state = ClusterState.build(
            node_names=[f"n{i}" for i in range(N)],
            node_cpu_cap=[900.0] * N,
            node_mem_cap=[1e9] * N,
            pod_services=list(range(S)),
            pod_nodes=nodes.tolist(),
            pod_cpu=cpu.tolist(),
            pod_mem=[1.0] * S,
        )
        graph = CommGraph(
            adj=jnp.asarray(adj),
            service_valid=jnp.ones(S, bool),
            names=tuple(f"s{i}" for i in range(S)),
        )
        kw = dict(
            sweeps=4, swap_every=1, noise_temp=0.0, chunk_size=16,
            matmul_dtype="float32",
        )
        st_x, _ = global_assign(
            state, graph, jax.random.PRNGKey(3),
            GlobalSolverConfig(fused_epilogue="off", **kw),
        )
        st_k, _ = global_assign(
            state, graph, jax.random.PRNGKey(3),
            GlobalSolverConfig(fused_epilogue="interpret", **kw),
        )
        assert np.array_equal(np.asarray(st_x.pod_node), np.asarray(st_k.pod_node))


class TestMoveCostInteraction:
    def test_expensive_swaps_refused(self):
        """With a restart bill above the exchange's comm gain, the swap
        phase must leave the deadlock in place (2 pods restart for a gain
        of 20 -> any move_cost > 10 is a net loss)."""
        state, graph = deadlock_scenario()
        cfg = GlobalSolverConfig(
            sweeps=9, swap_every=1, noise_temp=0.0, chunk_size=4,
            move_cost=11.0,
        )
        new_state, info = global_assign(state, graph, jax.random.PRNGKey(0), cfg)
        assert float(info["objective_after"]) == 20.0
        assert np.array_equal(
            np.asarray(new_state.pod_node), np.asarray(state.pod_node)
        )

    def test_cheap_swaps_accepted_and_billed(self):
        state, graph = deadlock_scenario()
        cfg = GlobalSolverConfig(
            sweeps=9, swap_every=1, noise_temp=0.0, chunk_size=4,
            move_cost=2.0,
        )
        _, info = global_assign(state, graph, jax.random.PRNGKey(0), cfg)
        assert float(info["objective_after"]) == 0.0
        assert float(info["move_penalty"]) == 4.0  # 2 pods x cost 2


@pytest.mark.slow  # swap lowering parity stays pinned fast by
# TestLoweringParity.test_interpret_kernels_match_xla
def test_topk_subset_parity_single_vs_sharded():
    """The desire-ranked top-k candidate subset (k < chunk width — only
    live past ~2.5k services) must select and decide identically on the
    single-chip and node-sharded paths: replicated desire -> replicated
    top_k -> exact one-hot contractions."""
    from kubernetes_rescheduling_tpu.core.topology import synthetic_scenario
    from kubernetes_rescheduling_tpu.parallel import make_mesh
    from kubernetes_rescheduling_tpu.parallel.sharded_solver import (
        sharded_global_assign,
    )

    scn = synthetic_scenario(
        n_pods=4096, n_nodes=16, powerlaw=True, seed=13,
        node_cpu_cap_m=30_000.0,
    )
    cfg = GlobalSolverConfig(
        sweeps=2, noise_temp=0.0, balance_weight=0.0, swap_every=1,
    )
    # the subset path must actually engage: chunk width > swap_k
    from kubernetes_rescheduling_tpu.solver.global_solver import auto_chunk

    assert auto_chunk(4096) > cfg.swap_k
    key = jax.random.PRNGKey(9)
    st_1c, info_1c = global_assign(scn.state, scn.graph, key, cfg)
    mesh = make_mesh(8, shape=(2, 4))
    st_tp, _ = sharded_global_assign(scn.state, scn.graph, key, mesh, cfg)
    np.testing.assert_array_equal(
        np.asarray(st_1c.pod_node), np.asarray(st_tp.pod_node)
    )
