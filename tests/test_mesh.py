"""Mesh & device plane (``telemetry.mesh``): device-axis rollups
re-derived against a numpy twin, the dispatch-attribution contract, the
``DeviceSeries`` cardinality budget, the ``mesh_imbalance`` watchdog
rule, the ``/devices`` + ``/profile`` ops endpoints, and the
``ProfilerGate`` hard caps (capture count, one-in-flight, artifact
size). The profiler's backend seams are monkeypatched — no real
``jax.profiler`` trace is taken, so the file stays fast and
device-independent."""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from kubernetes_rescheduling_tpu.config import ObsConfig
from kubernetes_rescheduling_tpu.telemetry import (
    DeviceSeries,
    MeshPlane,
    MetricsRegistry,
    OpsPlane,
    OpsServer,
    ProfilerGate,
    SLORules,
    Watchdog,
    get_registry,
    set_registry,
)
from kubernetes_rescheduling_tpu.telemetry.mesh import (
    DEVICE_DIMS,
    DEVICE_QUANTS,
    ProfilerBusy,
    ProfilerExhausted,
    attribute_dispatch,
    decode_device_rollup,
    device_rollup_event,
    device_rollup_matrix,
    device_rollup_size,
)
from kubernetes_rescheduling_tpu.telemetry.watchdog import RULE_MESH
from kubernetes_rescheduling_tpu.utils.logging import StructuredLogger


@pytest.fixture()
def registry():
    prev = set_registry(MetricsRegistry())
    try:
        yield get_registry()
    finally:
        set_registry(prev)


def _get(port, path):
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10
        ) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def _post(port, path, body: bytes):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=body, method="POST"
    )
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


# ---------------- rollup math vs a numpy twin ----------------


def _nearest_rank(col, p):
    """Independent nearest-rank quantile: value at ceil(p·n) in the
    sorted column (1-indexed), clamped into range."""
    s = np.sort(col)
    n = len(s)
    i = min(n - 1, max(0, int(np.ceil(p * n)) - 1))
    return s[i]


def test_device_rollup_matches_numpy_twin(registry):
    rng = np.random.default_rng(7)
    n, k = 8, 3
    m = rng.uniform(0.1, 50.0, size=(n, len(DEVICE_DIMS))).astype(np.float32)
    flat = device_rollup_matrix(m, worst_k=k)
    assert flat.size == device_rollup_size(k)
    roll = decode_device_rollup(flat, worst_k=k)
    pcts = {"p50": 0.5, "p90": 0.9, "p99": 0.99, "max": 1.0}
    for d, dim in enumerate(DEVICE_DIMS):
        col = m[:, d]
        got = roll["dims"][dim]
        for q in DEVICE_QUANTS:
            assert got["quantiles"][q] == pytest.approx(
                float(_nearest_rank(col, pcts[q])), rel=1e-6
            )
        assert got["sum"] == pytest.approx(float(col.sum()), rel=1e-5)
        # worst-k: the k largest values, descending, with the device
        # index each came from
        order = np.argsort(-col, kind="stable")[:k]
        for rank, row in enumerate(got["worst"]):
            assert row["device"] == int(order[rank])
            assert row["value"] == pytest.approx(
                float(col[order[rank]]), rel=1e-6
            )


def test_device_rollup_tie_order_is_stable(registry):
    # ties resolve to the LOWER device index (stable argsort) — the
    # worst-device name in events must not flap between equal devices
    m = np.zeros((4, len(DEVICE_DIMS)), np.float32)
    m[:, 0] = [5.0, 5.0, 1.0, 5.0]
    roll = decode_device_rollup(
        device_rollup_matrix(m, worst_k=3), worst_k=3
    )
    assert [r["device"] for r in roll["dims"]["step_ms"]["worst"]] == [0, 1, 3]


def test_device_rollup_shape_errors(registry):
    with pytest.raises(ValueError, match="n_devices"):
        device_rollup_matrix(np.zeros((4, 2), np.float32), worst_k=2)
    with pytest.raises(ValueError, match="worst_k"):
        device_rollup_matrix(
            np.zeros((4, len(DEVICE_DIMS)), np.float32), worst_k=5
        )
    with pytest.raises(ValueError, match="does not decode"):
        decode_device_rollup(np.zeros(7, np.float32), worst_k=2)


def test_attribute_dispatch_weighted_and_fallbacks():
    # blockwise weighted split conserves the total: tenants map
    # blockwise to shards, so per-tenant weights fold per shard
    w = np.array([1.0, 1.0, 3.0, 3.0, 2.0, 2.0, 1.0, 1.0])  # T=8 over n=4
    out = attribute_dispatch(16.0, w, n=4)
    assert out.sum() == pytest.approx(16.0)
    folded = w.reshape(4, -1).sum(axis=1)  # [2, 6, 4, 2]
    assert out == pytest.approx(16.0 * folded / folded.sum())
    # every degenerate weight column falls back to uniform, never raises
    for bad in (
        None,
        np.ones(3),              # size < n
        np.ones(9),              # size % n != 0
        np.array([1.0, np.nan, 1.0, 1.0]),
        np.array([-1.0, 1.0, 1.0, 1.0]),
        np.zeros(4),
    ):
        out = attribute_dispatch(8.0, bad, n=4)
        assert out == pytest.approx([2.0, 2.0, 2.0, 2.0])
    with pytest.raises(ValueError):
        attribute_dispatch(1.0, None, n=0)


# ---------------- the DeviceSeries budget gate ----------------


def test_device_series_budget_gates_and_counts(registry):
    under = DeviceSeries(registry, devices=4, budget=8)
    assert under.enabled
    under.gauge_set("mesh_device_step_ms", "h", "cpu:0", 1.5)
    under.counter_inc("mesh_device_transfer_mb_total", "h", "cpu:0", 2.0)
    snap = registry.snapshot()
    assert any(
        r["metric"] == "mesh_device_step_ms"
        and r.get("labels") == {"device": "cpu:0"}
        for r in snap
    )

    over = DeviceSeries(registry, devices=16, budget=8)
    assert not over.enabled
    over.gauge_set("mesh_device_step_ms", "h", "cpu:9", 1.0)
    over.gauge_set("mesh_device_step_ms", "h", "cpu:10", 1.0)
    over.counter_inc("mesh_device_transfer_mb_total", "h", "cpu:9", 1.0)
    sup = registry.counter(
        "device_series_suppressed_total", labelnames=("family",)
    )
    assert sup.labels(family="mesh_device_step_ms").value == 2
    assert sup.labels(family="mesh_device_transfer_mb_total").value == 1
    # the suppressed devices created NO per-device series
    snap = registry.snapshot()
    assert not any(
        (r.get("labels") or {}).get("device") in ("cpu:9", "cpu:10")
        for r in snap
    )


# ---------------- MeshPlane ----------------


def _feed(plane, *, dispatch_s=0.08, transfer_bytes=1 << 20, weights=None,
          rounds=1, round=None):
    return plane.observe_block(
        dispatch_s=dispatch_s,
        transfer_bytes=transfer_bytes,
        weights=weights,
        rounds=rounds,
        round=round,
    )


def test_mesh_plane_publishes_bounded_rollup(registry):
    names = [f"dev:{i}" for i in range(4)]
    plane = MeshPlane(registry, device_names=names, sample_memory=False)
    w = np.array([1.0, 1.0, 1.0, 5.0])  # device 3 is the straggler
    summary, event = _feed(plane, dispatch_s=0.08, weights=w, round=7)
    assert summary["n_devices"] == 4
    assert summary["worst_device"] == "dev:3"
    assert summary["ratio"] > 1.0
    assert summary["round"] == 7
    # per-round normalization: 80 ms over 4 devices, uniform would be
    # 20 ms each; device 3 carries 5/8 of the weight = 50 ms
    assert summary["step_ms_max"] == pytest.approx(50.0, rel=1e-4)
    # the event carries device NAMES; worst rank 0 on step_ms is dev:3
    worst0 = [
        r for r in event["worst"] if r["dim"] == "step_ms" and r["rank"] == 0
    ]
    assert worst0[0]["device"] == "dev:3"
    # bounded families published, ratio gauge matches the summary
    g = registry.gauge("mesh_imbalance_ratio")
    assert g.value == pytest.approx(summary["ratio"])
    assert registry.gauge("mesh_devices").value == 4
    q = registry.gauge("mesh_step_ms_quantile", labelnames=("q",))
    assert q.labels(q="max").value == pytest.approx(50.0, rel=1e-4)
    # under-budget mesh: the per-device series exist with names
    s = registry.gauge("mesh_device_step_ms", labelnames=("device",))
    assert s.labels(device="dev:3").value == pytest.approx(50.0, rel=1e-4)


def test_mesh_plane_health_and_overview_accumulate(registry):
    plane = MeshPlane(
        registry, device_names=["a", "b"], sample_memory=False
    )
    _feed(plane, transfer_bytes=2 << 20, rounds=4, round=0)
    _feed(plane, transfer_bytes=2 << 20, rounds=4, round=4)
    hb = plane.health_block()
    assert hb["devices"] == 2 and hb["rounds"] == 8 and hb["blocks"] == 2
    assert set(hb["step_ms"]) == set(DEVICE_QUANTS)
    ov = plane.overview()
    assert [d["device"] for d in ov["devices"]] == ["a", "b"]
    # transfers accumulate across blocks: 2 MiB/block uniform over 2
    # devices = 1 MiB each, twice
    assert ov["devices"][0]["transfer_mb_total"] == pytest.approx(2.0)
    assert ov["rollup"]["worst_k"] == plane.worst_k


def test_mesh_plane_over_budget_suppresses_device_series(registry):
    plane = MeshPlane(
        registry,
        device_names=[f"d{i}" for i in range(8)],
        budget=4,
        sample_memory=False,
    )
    _feed(plane)
    sup = registry.counter(
        "device_series_suppressed_total", labelnames=("family",)
    )
    assert sup.labels(family="mesh_device_step_ms").value == 8
    # the bounded rollup families still publish for the over-budget mesh
    assert registry.gauge("mesh_devices").value == 8
    snap = registry.snapshot()
    assert not any(
        (r.get("labels") or {}).get("device", "").startswith("d")
        for r in snap
        if r["metric"] == "mesh_device_step_ms"
    )


def test_event_payload_is_json_serializable(registry):
    plane = MeshPlane(
        registry, device_names=["x", "y", "z"], sample_memory=False
    )
    _, event = _feed(plane, weights=np.array([1.0, 2.0, 3.0]), round=3)
    json.dumps(event)  # device names + floats only, no numpy scalars
    rebuilt = device_rollup_event(
        plane.overview()["rollup"] and decode_device_rollup(
            device_rollup_matrix(
                np.stack(
                    [
                        np.asarray(
                            [d["step_ms"] for d in plane.overview()["devices"]]
                        ),
                        np.zeros(3),
                        np.zeros(3),
                    ],
                    axis=1,
                ),
                worst_k=plane.worst_k,
            ),
            worst_k=plane.worst_k,
        ),
        plane.device_names,
    )
    json.dumps(rebuilt)


# ---------------- the mesh_imbalance watchdog rule ----------------


def _mesh_summary(ratio, n=4):
    return {
        "n_devices": n,
        "ratio": ratio,
        "worst_device": "dev:3",
        "step_ms_p50": 10.0,
        "step_ms_max": 10.0 * ratio,
    }


def test_mesh_imbalance_rule_fires_and_recovers(registry):
    wd = Watchdog(
        SLORules(min_samples=1, mesh_imbalance_ratio=2.0),
        registry=registry,
    )
    assert wd.observe_mesh(_mesh_summary(1.5)) == []
    raised = wd.observe_mesh(_mesh_summary(3.0))
    assert [v["rule"] for v in raised] == [RULE_MESH]
    v = raised[0]
    assert v["ratio"] == pytest.approx(3.0)
    assert v["threshold_ratio"] == pytest.approx(2.0)
    assert v["worst_device"] == "dev:3"
    assert v["n_devices"] == 4
    assert not wd.status()["healthy"]
    # a balanced round recovers the rule
    assert wd.observe_mesh(_mesh_summary(1.2)) == []
    assert wd.status()["healthy"]
    viols = registry.counter("slo_violations_total", labelnames=("rule",))
    assert viols.labels(rule=RULE_MESH).value == 1


def test_mesh_imbalance_rule_ignores_single_device_and_off(registry):
    wd = Watchdog(
        SLORules(min_samples=1, mesh_imbalance_ratio=2.0),
        registry=registry,
    )
    # a 1-device mesh has no imbalance to judge, whatever the ratio says
    assert wd.observe_mesh(_mesh_summary(9.0, n=1)) == []
    assert wd.status()["healthy"]
    off = Watchdog(SLORules(min_samples=1), registry=registry)
    assert off.observe_mesh(_mesh_summary(9.0)) == []
    assert off.status()["healthy"]


def test_mesh_imbalance_rule_clears_on_rebase(registry):
    wd = Watchdog(
        SLORules(min_samples=1, mesh_imbalance_ratio=2.0),
        registry=registry,
    )
    wd.observe_mesh(_mesh_summary(5.0))
    assert not wd.status()["healthy"]
    wd.rebase()
    assert wd.status()["healthy"]


def test_mesh_imbalance_threshold_validates():
    with pytest.raises(ValueError, match="mesh_imbalance_ratio"):
        SLORules(mesh_imbalance_ratio=0.5).validate()
    SLORules(mesh_imbalance_ratio=0.0).validate()
    SLORules(mesh_imbalance_ratio=1.5).validate()
    cfg = ObsConfig(slo_mesh_imbalance_ratio=0.5)
    with pytest.raises(ValueError, match="mesh_imbalance"):
        cfg.validate()


# ---------------- /devices and /profile endpoints ----------------


class TestMeshEndpoints:
    def test_devices_404_until_mesh_bound_then_serves(self, registry):
        plane = OpsPlane.from_config(
            ObsConfig().validate(), registry=registry
        )
        srv = OpsServer(
            port=0, registry=registry, devices_source=plane._devices
        )
        port = srv.start()
        try:
            code, body = _get(port, "/devices")
            assert code == 404
            assert b"no mesh plane" in body
            mesh = MeshPlane(
                registry, device_names=["a", "b"], sample_memory=False
            )
            _feed(mesh)
            plane.bind_mesh(mesh)
            code, body = _get(port, "/devices")
            assert code == 200
            doc = json.loads(body)
            assert [d["device"] for d in doc["devices"]] == ["a", "b"]
            assert doc["rounds"] == 1
        finally:
            srv.stop()

    def test_profile_get_is_405_post_arms(self, registry, tmp_path):
        plane = OpsPlane.from_config(
            ObsConfig().validate(),
            registry=registry,
            bundle_dir=str(tmp_path),
        )
        srv = OpsServer(
            port=0, registry=registry, profile_sink=plane._profile
        )
        port = srv.start()
        try:
            code, _ = _get(port, "/profile")
            assert code == 405
            code, body = _post(port, "/profile", b'{"rounds": 3}')
            assert code == 200
            doc = json.loads(body)
            assert doc["armed"] is True and doc["rounds"] == 3
            # second arm while pending: 409 with the gate's status
            code, body = _post(port, "/profile", b"{}")
            assert code == 409
            assert json.loads(body)["status"]["pending_rounds"] == 3
        finally:
            srv.stop()

    def test_profile_post_validates_rounds(self, registry, tmp_path):
        plane = OpsPlane.from_config(
            ObsConfig().validate(),
            registry=registry,
            bundle_dir=str(tmp_path),
        )
        srv = OpsServer(
            port=0, registry=registry, profile_sink=plane._profile
        )
        port = srv.start()
        try:
            for payload in (b'{"rounds": 0}', b'{"rounds": true}',
                            b'{"rounds": "three"}', b"not json"):
                code, _ = _post(port, "/profile", payload)
                assert code == 400, payload
            # defaults to one round on an empty body
            code, body = _post(port, "/profile", b"")
            assert code == 200
            assert json.loads(body)["rounds"] == 1
        finally:
            srv.stop()

    def test_profile_503_without_gate(self, registry):
        srv = OpsServer(port=0, registry=registry)
        port = srv.start()
        try:
            code, body = _post(port, "/profile", b"{}")
            assert code == 503
            assert b"no profiler" in body
        finally:
            srv.stop()


# ---------------- healthz mesh stanza via the ops plane ----------------


def test_observe_device_rollup_feeds_health_and_watchdog(registry):
    obs = ObsConfig(slo_mesh_imbalance_ratio=2.0, slo_min_samples=1)
    plane = OpsPlane.from_config(obs.validate(), registry=registry)
    mesh = MeshPlane(
        registry, device_names=["a", "b", "c", "d"], sample_memory=False
    )
    plane.bind_mesh(mesh)
    summary, event = _feed(
        mesh, weights=np.array([1.0, 1.0, 1.0, 9.0]), round=1
    )
    plane.observe_device_rollup(summary, event=event)
    snap, _healthy = plane.health.snapshot()
    assert snap["mesh"]["devices"] == 4
    assert snap["mesh"]["worst_device"] == "d"
    assert snap["mesh"]["imbalance_ratio"] == pytest.approx(
        summary["ratio"], rel=1e-3
    )
    # ratio 3.0 > threshold 2.0: the rule is active on /healthz
    assert not plane.watchdog.status()["healthy"]


# ---------------- ProfilerGate ----------------


class _FakeBackend:
    """Monkeypatch seams: capture goes to a dir we fill ourselves."""

    def __init__(self, gate, payload_bytes=16):
        self.gate = gate
        self.payload_bytes = payload_bytes
        self.dirs = []
        gate._start_backend = self.start
        gate._stop_backend = self.stop

    def start(self, log_dir):
        self.dirs.append(log_dir)

    def stop(self):
        import os

        d = self.dirs[-1]
        with open(os.path.join(d, "trace.bin"), "wb") as f:
            f.write(b"\0" * self.payload_bytes)


def test_profiler_gate_lifecycle_and_caps(registry, tmp_path):
    class Rec:
        def __init__(self):
            self.dumps = []

        def dump(self, reason, **extra):
            self.dumps.append((reason, extra))

    rec = Rec()
    logger = StructuredLogger(name="t")
    gate = ProfilerGate(
        registry,
        artifact_dir=str(tmp_path),
        max_captures=2,
        max_mb=1.0,
        recorder=rec,
        logger=logger,
    )
    fake = _FakeBackend(gate)
    # nothing armed: maybe_start is a no-op
    assert gate.maybe_start(label="fleet_rounds") is False
    out = gate.request(rounds=2)
    assert out["armed"] and out["captures_left"] == 2
    with pytest.raises(ProfilerBusy):
        gate.request(rounds=1)
    with pytest.raises(ValueError):
        gate.request(rounds=0)
    assert gate.maybe_start(label="fleet_rounds", round=5) is True
    gate.advance(1)
    assert gate.status()["active"]["rounds_left"] == 1
    gate.advance(1)
    st = gate.status()
    assert st["active"] is None
    (cap,) = st["captures"]
    assert cap["status"] == "ok"
    assert cap["rounds"] == 2 and cap["start_round"] == 5
    assert cap["bytes"] == 16
    assert (tmp_path / "profile_000" / "trace.bin").is_file()
    ok = registry.counter(
        "profile_captures_total", labelnames=("status",)
    )
    assert ok.labels(status="ok").value == 1
    # the flight-recorder bundle references the capture
    assert rec.dumps and rec.dumps[0][0] == "profile_capture"
    assert rec.dumps[0][1]["profile"]["dir"] == str(tmp_path / "profile_000")
    assert any(r["event"] == "profile_capture" for r in logger.records)

    # second capture spends the budget; the third is exhausted
    gate.request(rounds=1)
    gate.maybe_start(label="fleet_rounds")
    gate.advance(1)
    with pytest.raises(ProfilerExhausted):
        gate.request(rounds=1)


def test_profiler_gate_oversize_artifact_is_deleted(registry, tmp_path):
    gate = ProfilerGate(
        registry, artifact_dir=str(tmp_path), max_captures=4, max_mb=1.0
    )
    _FakeBackend(gate, payload_bytes=2 << 20)  # 2 MiB > 1 MB cap
    gate.request(rounds=1)
    gate.maybe_start(label="fleet_scan_block", rounds=1)
    gate.advance(1)
    (cap,) = gate.status()["captures"]
    assert cap["status"] == "oversize"
    assert not (tmp_path / "profile_000").exists()
    c = registry.counter("profile_captures_total", labelnames=("status",))
    assert c.labels(status="oversize").value == 1
    # the budget is still spent — a runaway trace must not retry free
    assert gate.status()["max_captures"] - 1 == 3


def test_profiler_gate_start_failure_counts_error(registry, tmp_path):
    gate = ProfilerGate(
        registry, artifact_dir=str(tmp_path), max_captures=4
    )

    def boom(log_dir):
        raise RuntimeError("no profiler on this backend")

    gate._start_backend = boom
    gate.request(rounds=1)
    assert gate.maybe_start(label="fleet_rounds") is False
    (cap,) = gate.status()["captures"]
    assert cap["status"] == "error" and "no profiler" in cap["error"]
    c = registry.counter("profile_captures_total", labelnames=("status",))
    assert c.labels(status="error").value == 1
    # the failed slot is spent (seq advanced), the gate is idle again
    assert gate.status()["active"] is None
    assert gate.status()["pending_rounds"] == 0


def test_scan_block_rounds_up_capture_span(registry, tmp_path):
    # a scan block is atomic: maybe_start's rounds override widens the
    # requested 1-round capture to the whole k-round block
    gate = ProfilerGate(registry, artifact_dir=str(tmp_path))
    _FakeBackend(gate)
    gate.request(rounds=1)
    assert gate.maybe_start(label="fleet_scan_block", rounds=16, round=0)
    gate.advance(16)
    (cap,) = gate.status()["captures"]
    assert cap["status"] == "ok" and cap["rounds"] == 16


def test_from_config_arms_profile_rounds(registry, tmp_path):
    obs = ObsConfig(
        profile_rounds=4, profile_max_captures=2, bundle_dir=str(tmp_path)
    ).validate()
    plane = OpsPlane.from_config(obs, registry=registry)
    gate = plane.profiler
    assert gate is not None
    assert gate.status()["pending_rounds"] == 4
    assert gate.max_captures == 2
    # the artifact dir IS the flight-recorder bundle dir
    assert gate.artifact_dir == str(tmp_path)
