"""Test harness: force an 8-device virtual CPU platform.

Multi-chip TPU hardware is not available in CI; all sharding tests run on a
virtual 8-device CPU mesh. Must run before jax is imported anywhere.
"""

import os

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
flags = os.environ["XLA_FLAGS"]
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_threefry_partitionable", True)
