"""Test harness: force an 8-device virtual CPU platform.

Multi-chip TPU hardware is not available in CI; all sharding tests run on a
virtual 8-device CPU mesh. The environment may pre-import jax and pin an
accelerator platform (e.g. a tunneled TPU) via sitecustomize, so the env-var
route alone is not enough — we also override through jax.config, which takes
effect as long as no backend has been initialized yet.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

assert jax.devices()[0].platform == "cpu", (
    "tests must run on the virtual CPU mesh; a device backend was already "
    f"initialized: {jax.devices()}"
)

# Deterministic hypothesis examples: by default hypothesis draws NEW random
# examples every run, so a suite that is green here could flake in someone
# else's run by discovering a novel falsifying input. Derandomizing makes
# every run explore the same (still diverse) examples — property coverage
# without nondeterministic CI. Override locally with
# HYPOTHESIS_PROFILE=explore to hunt for new counterexamples.
# hypothesis is optional: environments without it still run the rest of
# the suite (the property-based module alone fails collection there).
try:
    from hypothesis import settings  # noqa: E402
except ModuleNotFoundError:
    pass
else:
    settings.register_profile("ci", derandomize=True, deadline=None)
    settings.register_profile("explore", deadline=None)
    settings.load_profile(os.environ.get("HYPOTHESIS_PROFILE", "ci"))


# XLA CPU accumulates compiled-executable state across the ~400-test
# suite; past ~340 compilations in one process the compiler segfaults
# deterministically (observed at an innocuous jnp.sum compile — a
# compiler-state issue, not a semantics one; every file passes in
# isolation). Clearing JAX's caches at module boundaries bounds the
# accumulation; cross-module cache reuse was negligible anyway (each
# file compiles its own shapes).
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: heavy redundant variants excluded from the tier-1 "
        "`-m 'not slow'` run; every invariant they cover keeps at least "
        "one fast representative",
    )


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_per_module():
    yield
    jax.clear_caches()
