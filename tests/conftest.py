"""Test harness: force an 8-device virtual CPU platform.

Multi-chip TPU hardware is not available in CI; all sharding tests run on a
virtual 8-device CPU mesh. The environment may pre-import jax and pin an
accelerator platform (e.g. a tunneled TPU) via sitecustomize, so the env-var
route alone is not enough — we also override through jax.config, which takes
effect as long as no backend has been initialized yet.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

assert jax.devices()[0].platform == "cpu", (
    "tests must run on the virtual CPU mesh; a device backend was already "
    f"initialized: {jax.devices()}"
)
