"""Streaming trace replay (BASELINE config 5)."""

import jax
import numpy as np
import pytest

from kubernetes_rescheduling_tpu.bench.trace import (
    bookinfo_workmodel,
    canary_trace,
    replay,
    with_weights,
)
from kubernetes_rescheduling_tpu.core.topology import state_from_workmodel
from kubernetes_rescheduling_tpu.objectives import communication_cost
from kubernetes_rescheduling_tpu.solver import GlobalSolverConfig


def test_bookinfo_graph():
    wm = bookinfo_workmodel()
    rel = wm.relation()
    assert set(rel["productpage"]) == {"details", "reviews-v1", "reviews-v2", "reviews-v3"}
    assert rel["ratings"] == ["reviews-v2", "reviews-v3"]


def test_with_weights_symmetric():
    wm = bookinfo_workmodel()
    g = wm.comm_graph()
    g2 = with_weights(g, {("productpage", "reviews-v1"): 0.25})
    i = g.names.index("productpage")
    j = g.names.index("reviews-v1")
    assert float(g2.adj[i, j]) == 0.25
    assert float(g2.adj[j, i]) == 0.25
    # unknown names leave the adjacency untouched
    g3 = with_weights(g, {("nope", "ratings"): 5.0})
    np.testing.assert_array_equal(np.asarray(g3.adj), np.asarray(g.adj))


def test_with_weights_counts_swallowed_refs():
    """A malformed trace is visible, never a silent no-op: dropped
    updates count in trace_unknown_refs_total and emit one structured
    swallowed_ref event per batch."""
    from kubernetes_rescheduling_tpu.telemetry.registry import (
        MetricsRegistry,
        set_registry,
    )
    from kubernetes_rescheduling_tpu.utils.logging import StructuredLogger

    wm = bookinfo_workmodel()
    g = wm.comm_graph()
    prev = set_registry(MetricsRegistry())
    try:
        from kubernetes_rescheduling_tpu.telemetry.registry import get_registry

        logger = StructuredLogger(name="t")
        g2 = with_weights(
            g,
            {
                ("nope", "ratings"): 5.0,
                ("details", "ghost"): 2.0,
                ("productpage", "details"): 0.5,  # known: applied
            },
            logger=logger,
        )
        i = g.names.index("productpage")
        j = g.names.index("details")
        assert float(g2.adj[i, j]) == 0.5
        counts = {
            rec["metric"]: rec.get("value")
            for rec in get_registry().snapshot()
        }
        assert counts.get("trace_unknown_refs_total") == 2
        events = [r for r in logger.records if r["event"] == "swallowed_ref"]
        assert len(events) == 1
        assert events[0]["dropped"] == 2
        assert "nope~ratings" in events[0]["refs"]
    finally:
        set_registry(prev)


def test_canary_trace_shifts_traffic():
    tr = canary_trace(steps=11)
    first, last = tr[0].weights, tr[-1].weights
    assert first[("productpage", "reviews-v1")] == 1.0
    assert first[("productpage", "reviews-v3")] == 0.0
    assert last[("productpage", "reviews-v1")] == 0.0
    assert last[("productpage", "reviews-v3")] == 1.0


def test_replay_tracks_moving_objective():
    wm = bookinfo_workmodel(replicas=2)
    state = state_from_workmodel(
        wm, node_names=["w1", "w2"], node_cpu_cap_m=500.0, seed=0
    )
    graph = wm.comm_graph()
    final, records = replay(
        state,
        graph,
        canary_trace(steps=8),
        key=jax.random.PRNGKey(0),
        config=GlobalSolverConfig(sweeps=4, chunk_size=2),
    )
    assert len(records) == 8
    # the solver never leaves the placement worse than it found it (per step)
    for r in records:
        assert r.cost_after_solve <= r.cost_before_solve + 1e-5
    # at least one step adapts the placement as traffic shifts
    assert any(r.moves > 0 for r in records)


def test_observed_step_streams_measured_traffic():
    """Trace replay on OBSERVED weights: the canary's real traffic split
    becomes a TraceStep without any hand-written weight schedule."""
    import jax
    from kubernetes_rescheduling_tpu.bench.loadgen import LoadGenConfig, LoadGenerator
    from kubernetes_rescheduling_tpu.bench.trace import bookinfo_workmodel, observed_step
    from kubernetes_rescheduling_tpu.core.topology import state_from_workmodel

    wm = bookinfo_workmodel()
    state = state_from_workmodel(wm, node_names=["n0", "n1"], seed=0)
    gen = LoadGenerator(
        wm,
        LoadGenConfig(requests_per_phase=2048, chunk=512, entry_service="productpage"),
        edge_probs={
            ("productpage", "reviews-v1"): 0.1,
            ("productpage", "reviews-v2"): 0.9,
        },
    )
    samples = gen.run(state, jax.random.PRNGKey(0))
    step = observed_step(1.0, gen, samples)
    w = step.weights
    key_v1 = tuple(sorted(("productpage", "reviews-v1")))
    key_v2 = tuple(sorted(("productpage", "reviews-v2")))
    assert w[key_v2] > 5 * w[key_v1]  # the canary shift is visible


@pytest.mark.slow  # the on-device streaming tracking contract (per-step
# solve never worse than the drifted weights' incoming cost) stays pinned
# fast by the sparse twin test_replay_on_device_sparse_tracks_drift (same
# scan machinery + the locator path on top); this dense variant re-proves
# it with its own full solver compile (~14 s)
def test_replay_on_device_tracks_drift():
    """The fully-on-device streaming replay: per step the solve is never
    worse than the drifted weights' cost of the incoming placement."""
    import jax
    import numpy as np

    from kubernetes_rescheduling_tpu.bench.trace import (
        drift_multipliers,
        replay_on_device,
    )
    from kubernetes_rescheduling_tpu.core.topology import synthetic_scenario
    from kubernetes_rescheduling_tpu.solver import GlobalSolverConfig

    scn = synthetic_scenario(n_pods=128, n_nodes=8, powerlaw=True, seed=2)
    ii, jj, mults = drift_multipliers(scn.graph, steps=4, seed=1)
    assert len(ii) > 0 and mults.shape == (4, len(ii))
    final, objs, befores = replay_on_device(
        scn.state, scn.graph, ii, jj, mults,
        jax.random.PRNGKey(0), GlobalSolverConfig(sweeps=3),
    )
    assert objs.shape == (4,)
    assert (np.asarray(objs) <= np.asarray(befores) + 1e-3).all()
    # drift actually changed the weights (multipliers are not all 1)
    assert float(np.abs(mults - 1.0).max()) > 0.1


def test_trace_locator_scatter_matches_rebuild():
    """with_edge_weights through the static locator must produce exactly
    the graph a from-scratch rebuild with the new weights would: the
    block-local strips and COO list stay consistent (structure is static,
    only weights move)."""
    import jax.numpy as jnp
    import numpy as np

    from kubernetes_rescheduling_tpu.core import sparsegraph
    from kubernetes_rescheduling_tpu.core.sparsegraph import (
        trace_locator,
        with_edge_weights,
    )
    from kubernetes_rescheduling_tpu.core.topology import synthetic_scenario

    scn = synthetic_scenario(n_pods=600, n_nodes=8, powerlaw=True, seed=5)
    sg = sparsegraph.from_comm_graph(scn.graph)
    assert sg.num_blocks > 1
    loc = trace_locator(sg)
    rng = np.random.default_rng(0)
    new_w = np.asarray(loc.base_w) * rng.uniform(
        0.2, 3.0, loc.num_edges
    ).astype(np.float32)
    sg_up = with_edge_weights(sg, loc, jnp.asarray(new_w))
    # reference: rebuild from the updated dense adjacency (degree order is
    # structure-driven, so the rebuild lands in the same layout)
    dense_up = sg_up.to_dense()
    sg_ref = sparsegraph.from_comm_graph(dense_up)
    np.testing.assert_array_equal(
        np.asarray(sg_up.u_ids), np.asarray(sg_ref.u_ids)
    )
    np.testing.assert_allclose(
        np.asarray(sg_up.w_local), np.asarray(sg_ref.w_local), rtol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(sg_up.edges_w), np.asarray(sg_ref.edges_w), rtol=1e-6
    )


def test_replay_on_device_sparse_tracks_drift():
    """The sparse streaming replay: same tracking contract as the dense
    one (per-step solve never worse than the drifted cost of the incoming
    placement), at the block-local form."""
    import jax
    import numpy as np

    from kubernetes_rescheduling_tpu.bench.trace import (
        drift_multipliers_sparse,
        replay_on_device_sparse,
    )
    from kubernetes_rescheduling_tpu.core import sparsegraph
    from kubernetes_rescheduling_tpu.core.topology import synthetic_scenario
    from kubernetes_rescheduling_tpu.solver import GlobalSolverConfig

    scn = synthetic_scenario(n_pods=600, n_nodes=8, powerlaw=True, seed=3)
    sg = sparsegraph.from_comm_graph(scn.graph)
    sg, loc, mults = drift_multipliers_sparse(sg, steps=4, seed=1)
    final, objs, befores = replay_on_device_sparse(
        scn.state, sg, loc, mults,
        jax.random.PRNGKey(0), GlobalSolverConfig(sweeps=3),
    )
    assert objs.shape == (4,)
    assert (np.asarray(objs) <= np.asarray(befores) + 1e-3).all()
