"""CI twin of ``scripts/check_slow_justified.py``: every
slow marker must carry the justification comment naming its
surviving fast pin (the PR 3–4 convention, now enforced) — validated
over the checked-in suite plus pinned acceptance/rejection of the
comment shapes the convention allows."""

import importlib.util
import sys
import textwrap
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(scope="module")
def checker():
    path = REPO / "scripts" / "check_slow_justified.py"
    spec = importlib.util.spec_from_file_location("check_slow_justified", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules.setdefault("check_slow_justified", mod)
    spec.loader.exec_module(mod)
    return mod


# assembled so this file's own fixtures never contain the literal
# marker the checker greps for
MARK = "@pytest." + "mark." + "slow"


def _write(tmp_path, body):
    p = tmp_path / "test_x.py"
    p.write_text(textwrap.dedent(body).replace("@SLOW", MARK))
    return p


def test_checked_in_suite_is_justified(checker):
    """The no-args self-check: the repo's own tests satisfy the
    convention the checker documents."""
    assert checker.violations() == []
    assert checker.main([]) == 0


def test_same_line_plus_continuation_accepted(checker, tmp_path):
    p = _write(
        tmp_path,
        """\
        import pytest

        @SLOW  # parity stays pinned fast by
        # test_fast_twin_case below
        def test_heavy():
            pass
        """,
    )
    assert checker.check_file(p) == []


def test_bare_marker_rejected(checker, tmp_path):
    p = _write(
        tmp_path,
        """\
        import pytest

        @SLOW
        def test_heavy():
            pass
        """,
    )
    bad = checker.check_file(p)
    assert len(bad) == 1 and "without a same-line" in bad[0]
    assert checker.main([str(p)]) == 1


def test_parametrize_and_module_level_forms_are_caught(checker, tmp_path):
    """Non-decorator spellings remove tier-1 coverage just the same —
    the checker must not let them bypass the convention."""
    p = _write(
        tmp_path,
        """\
        import pytest

        @pytest.mark.parametrize("n", [
            pytest.param(10_000, marks=@SLOW),
        ])
        def test_scale(n):
            pass
        """.replace("marks=@SLOW", "marks=" + MARK.lstrip("@")),
    )
    bad = checker.check_file(p)
    assert len(bad) == 1 and "without a same-line" in bad[0]
    p2 = _write(
        tmp_path,
        """\
        import pytest

        pytestmark = @SLOW  # whole module redundant; stays pinned fast by
        # test_fast_module's cases
        """,
    )
    assert checker.check_file(p2) == []


def test_comment_without_survival_claim_rejected(checker, tmp_path):
    p = _write(
        tmp_path,
        """\
        import pytest

        @SLOW  # this one is just heavy
        def test_heavy():
            pass
        """,
    )
    bad = checker.check_file(p)
    assert len(bad) == 1 and "stays pinned fast" in bad[0]


def test_comment_without_named_pin_rejected(checker, tmp_path):
    p = _write(
        tmp_path,
        """\
        import pytest

        @SLOW  # redundant; coverage stays pinned fast elsewhere
        def test_heavy():
            pass
        """,
    )
    bad = checker.check_file(p)
    assert len(bad) == 1 and "NAME the surviving fast pin" in bad[0]


def test_continuation_stops_at_code(checker, tmp_path):
    """A comment AFTER the def is not a continuation — the marker line
    itself must justify."""
    p = _write(
        tmp_path,
        """\
        import pytest

        @SLOW  # heavy variant
        def test_heavy():
            # fast pin: test_fast_twin (this comment must NOT count)
            pass
        """,
    )
    bad = checker.check_file(p)
    assert len(bad) == 1


def test_fast_tests_unconstrained(checker, tmp_path):
    p = _write(
        tmp_path,
        """\
        import pytest

        @pytest.mark.parametrize("x", [1, 2])
        def test_fast(x):
            pass
        """,
    )
    assert checker.check_file(p) == []
