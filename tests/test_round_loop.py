"""The scanned round loop vs an oracle-driven loop with identical (fixed)
semantics, plus behavioral checks on the reference's own scenario."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from kubernetes_rescheduling_tpu.core.state import UNASSIGNED
from kubernetes_rescheduling_tpu.core.topology import mubench_scenario, state_from_workmodel
from kubernetes_rescheduling_tpu.core.workmodel import mubench_workmodel_c
from kubernetes_rescheduling_tpu import oracle
from kubernetes_rescheduling_tpu.objectives import communication_cost
from kubernetes_rescheduling_tpu.policies import POLICY_IDS
from kubernetes_rescheduling_tpu.solver import run_rounds


def oracle_loop(state, graph, relation, policy, rounds, threshold=30.0):
    """Reference-semantics loop in numpy/dict world (same deliberate fixes
    as solver.round_loop: real snapshot edit, skip instead of crash)."""
    trace = []
    for _ in range(rounds):
        snap = oracle.to_snapshot(state, graph)
        most, hazard = oracle.detection(snap, threshold)
        if not most:
            trace.append(None)
            continue
        victim = oracle.pick_max_pod(snap, most)
        if victim is None:
            trace.append(None)
            continue
        svc = victim.service
        svc_idx = graph.names.index(svc)
        group = np.asarray(state.pod_valid) & (
            np.asarray(state.pod_service) == svc_idx
        )
        removed = state.replace(
            pod_node=jnp.where(jnp.asarray(group), UNASSIGNED, state.pod_node)
        )
        snap2 = oracle.to_snapshot(removed, graph)
        if len(hazard) == len(snap.nodes_name):
            trace.append(None)
            continue
        if policy == "spread":
            target = oracle.choose_spread(snap2, hazard)
        elif policy == "binpack":
            target = oracle.choose_binpack(snap2, hazard)
        elif policy == "kubescheduling":
            target = oracle.choose_kubescheduling(snap2, hazard)
        elif policy == "communication":
            target = oracle.choose_communication(snap2, relation, svc, hazard)
        else:
            raise ValueError(policy)
        t_idx = state.node_names.index(target)
        state = removed.replace(
            pod_node=jnp.where(jnp.asarray(group), t_idx, removed.pod_node)
        )
        trace.append((most, victim.index, svc, target))
    return state, trace


@pytest.mark.parametrize("policy", ["spread", "binpack", "kubescheduling", "communication"])
def test_round_loop_matches_oracle(policy):
    wm = mubench_workmodel_c()
    scn = mubench_scenario(imbalanced=True)
    rounds = 6
    final, tel = run_rounds(
        scn.state,
        scn.graph,
        jnp.asarray(POLICY_IDS[policy]),
        jax.random.PRNGKey(0),
        rounds=rounds,
    )
    exp_final, exp_trace = oracle_loop(
        scn.state, scn.graph, wm.relation(), policy, rounds
    )
    np.testing.assert_array_equal(
        np.asarray(final.pod_node), np.asarray(exp_final.pod_node)
    )
    # telemetry matches the oracle trace step for step
    for r, step in enumerate(exp_trace):
        if step is None:
            assert not bool(tel.moved[r])
        else:
            most, victim_idx, svc, target = step
            assert bool(tel.moved[r])
            assert scn.state.node_names[int(tel.most_hazard[r])] == most
            assert int(tel.victim[r]) == victim_idx
            assert scn.graph.names[int(tel.service[r])] == svc
            assert scn.state.node_names[int(tel.target[r])] == target


def test_car_reduces_comm_cost_from_random_start():
    wm = mubench_workmodel_c()
    state = state_from_workmodel(wm, seed=7, node_cpu_cap_m=2000.0)
    graph = wm.comm_graph()
    before = float(communication_cost(state, graph))
    final, tel = run_rounds(
        state, graph, jnp.asarray(POLICY_IDS["communication"]),
        jax.random.PRNGKey(0), rounds=10,
    )
    after = float(communication_cost(final, graph))
    assert bool(tel.moved.any())
    assert after <= before


def test_stable_cluster_is_noop():
    # Big caps -> no node over 30% -> all rounds no-op (reference main.py:109-112)
    wm = mubench_workmodel_c()
    state = state_from_workmodel(wm, seed=1, node_cpu_cap_m=1e6)
    graph = wm.comm_graph()
    final, tel = run_rounds(
        state, graph, jnp.asarray(POLICY_IDS["communication"]),
        jax.random.PRNGKey(0), rounds=5,
    )
    assert not bool(tel.moved.any())
    np.testing.assert_array_equal(
        np.asarray(final.pod_node), np.asarray(state.pod_node)
    )


def test_all_hazard_skips_moves():
    # tiny caps -> every node hazardous -> skip, deployments kept
    wm = mubench_workmodel_c()
    state = state_from_workmodel(wm, seed=1, node_cpu_cap_m=300.0)
    graph = wm.comm_graph()
    final, tel = run_rounds(
        state, graph, jnp.asarray(POLICY_IDS["spread"]),
        jax.random.PRNGKey(0), rounds=3,
    )
    assert not bool(tel.moved.any())
    assert int(np.asarray(final.pod_valid).sum()) == int(
        np.asarray(state.pod_valid).sum()
    )


def test_random_policy_runs_and_respects_hazard():
    scn = mubench_scenario(imbalanced=True)
    final, tel = run_rounds(
        scn.state, scn.graph, jnp.asarray(POLICY_IDS["random"]),
        jax.random.PRNGKey(42), rounds=10,
    )
    moved_rounds = np.asarray(tel.moved)
    hazard_nodes = np.asarray(tel.most_hazard)
    targets = np.asarray(tel.target)
    for r in range(10):
        if moved_rounds[r]:
            assert targets[r] != hazard_nodes[r]
