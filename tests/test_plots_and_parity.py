"""Chart regeneration + qualitative parity with the reference's headline
result: CAR (communication) achieves the lowest communication cost and
response time across the policy matrix (SURVEY.md §6)."""

import numpy as np
import pytest

from kubernetes_rescheduling_tpu.bench.harness import ExperimentConfig, run_experiment
from kubernetes_rescheduling_tpu.bench.plots import plot_summary


@pytest.fixture(scope="module")
def matrix_summary(tmp_path_factory):
    out = tmp_path_factory.mktemp("matrix")
    cfg = ExperimentConfig(
        algorithms=("spread", "binpack", "random", "kubescheduling", "communication"),
        repeats=3,
        rounds=10,
        scenario="mubench",
        out_dir=str(out),
        seed=11,
    )
    return run_experiment(cfg)


def test_plot_summary_writes_charts(matrix_summary, tmp_path):
    written = plot_summary(matrix_summary, tmp_path)
    names = sorted(p.name for p in written)
    assert names == [
        "communication_cost.png",   # the reference's three charts...
        "disruption.png",           # ...plus the request-level stats the
        "node_standard.png",        # reference only logs as text
        "responsetime.png",         # (release1.sh:74-117)
        "tail_latency.png",
    ]
    for p in written:
        assert p.stat().st_size > 5_000  # a real rendered image


def test_car_wins_comm_cost_and_response_time(matrix_summary):
    agg = matrix_summary["aggregate"]
    car_cost = agg["communication"]["communication_cost"]
    car_rt = agg["communication"]["response_time_ms"]
    for algo in ("spread", "binpack", "random", "kubescheduling"):
        assert car_cost <= agg[algo]["communication_cost"] + 1e-6, (
            f"CAR comm cost {car_cost} worse than {algo}: {agg[algo]}"
        )
    assert car_rt == min(a["response_time_ms"] for a in agg.values())


def test_rescheduling_improves_over_before(matrix_summary):
    # every policy should reduce response time vs the imbalanced Before state
    runs = matrix_summary["runs"]
    before_rt = np.mean([r["before"]["response_time_ms"] for r in runs])
    car_rt = matrix_summary["aggregate"]["communication"]["response_time_ms"]
    assert car_rt <= before_rt


def test_merge_summaries_labels_config_variants(matrix_summary, tmp_path):
    """The wave-capped configuration appears as its own labeled bars in
    every chart (V5: disruption chart must include the capped config)."""
    from kubernetes_rescheduling_tpu.bench.plots import merge_summaries

    capped = {
        "runs": [
            {**r, "seed": r["seed"] + 1000}
            for r in matrix_summary["runs"]
            if r["algorithm"] == "communication"
        ]
    }
    merged = merge_summaries(matrix_summary, [("k=2", capped)])
    labels = {r["algorithm"] for r in merged["runs"]}
    assert "communication k=2" in labels
    written = plot_summary(merged, tmp_path / "merged")
    assert any(p.name == "disruption.png" for p in written)
    assert all(p.is_file() for p in written)
