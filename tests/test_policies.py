"""Policy kernels vs the oracle: decision-for-decision parity on randomized
states, including tie-break cases (SURVEY.md §7 'exact tie-break parity')."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from kubernetes_rescheduling_tpu.core.state import ClusterState
from kubernetes_rescheduling_tpu.core.workmodel import mubench_workmodel_c
from kubernetes_rescheduling_tpu import oracle
from kubernetes_rescheduling_tpu.policies import (
    POLICY_IDS,
    choose_node,
    deployment_group,
    detect_hazard,
    lex_argmax,
    pick_victim,
)


def random_state(seed, n_nodes=4, n_services=20, quantize=True):
    """Random cluster with quantized pod CPU (forces frequent ties).

    Capacity is sized so mean node utilization sits under the 30% hazard
    threshold (~28%): hazard nodes are common (the loop has work) but a
    non-hazard candidate almost always exists, so the parity tests below
    actually run instead of skipping (VERDICT r1 weak #3)."""
    rng = np.random.default_rng(seed)
    n_pods = n_services  # one replica per service, like workmodelC
    pod_cpu = rng.integers(1, 8, size=n_pods) * 50.0 if quantize else rng.uniform(10, 400, n_pods)
    # shuffled node names so lexicographic order != index order
    names = [f"w{c}" for c in rng.permutation([chr(ord('a') + i) for i in range(n_nodes)])]
    return ClusterState.build(
        node_names=names,
        node_cpu_cap=[4000.0] * n_nodes,
        node_mem_cap=[1e9] * n_nodes,
        pod_services=list(range(n_services)),
        pod_nodes=rng.integers(0, n_nodes, size=n_pods).tolist(),
        pod_cpu=pod_cpu.tolist(),
        pod_mem=[0.0] * n_pods,
        pod_names=[f"s{i}-0" for i in range(n_services)],
    )


def test_parity_generator_rarely_saturates():
    """Guard on the generator itself: <10% of seeds may be all-hazardous
    (those parity cases skip), so tie-break coverage stays real."""
    saturated = 0
    for seed in range(15):
        state = random_state(seed)
        _, mask = detect_hazard(state, threshold=30.0)
        saturated += bool(np.asarray(mask).all())
    assert saturated / 15 < 0.1


@pytest.fixture(scope="module")
def wm():
    return mubench_workmodel_c()


@pytest.mark.parametrize("seed", range(20))
def test_hazard_detection_parity(seed, wm):
    state = random_state(seed)
    graph = wm.comm_graph()
    snap = oracle.to_snapshot(state, graph)
    exp_most, exp_hazard = oracle.detection(snap, threshold=30.0)
    most, mask = detect_hazard(state, threshold=30.0)
    got_hazard = [state.node_names[i] for i in range(state.num_nodes) if bool(mask[i])]
    assert got_hazard == exp_hazard
    got_most = state.node_names[int(most)] if int(most) >= 0 else ""
    assert got_most == exp_most


@pytest.mark.parametrize("seed", range(20))
def test_victim_parity(seed, wm):
    state = random_state(seed)
    graph = wm.comm_graph()
    snap = oracle.to_snapshot(state, graph)
    for node_idx, node_name in enumerate(state.node_names):
        exp = oracle.pick_max_pod(snap, node_name)
        got = int(pick_victim(state, jnp.asarray(node_idx)))
        if exp is None:
            assert got == -1
        else:
            assert got == exp.index


def test_deployment_group_moves_all_replicas():
    state = ClusterState.build(
        node_names=["n0", "n1"],
        node_cpu_cap=[1000, 1000],
        node_mem_cap=[1e9, 1e9],
        pod_services=[0, 0, 1],
        pod_nodes=[0, 1, 0],
        pod_cpu=[100, 100, 100],
        pod_mem=[0, 0, 0],
    )
    group = deployment_group(state, jnp.asarray(0))
    assert list(np.asarray(group)) == [True, True, False]
    empty = deployment_group(state, jnp.asarray(-1))
    assert not np.asarray(empty).any()


def _oracle_choice(policy, snap, hazard, relation, service):
    if policy == "spread":
        return oracle.choose_spread(snap, hazard)
    if policy == "binpack":
        return oracle.choose_binpack(snap, hazard)
    if policy == "kubescheduling":
        return oracle.choose_kubescheduling(snap, hazard)
    if policy == "communication":
        return oracle.choose_communication(snap, relation, service, hazard)
    raise ValueError(policy)


@pytest.mark.parametrize("policy", ["spread", "binpack", "kubescheduling", "communication"])
@pytest.mark.parametrize("seed", range(15))
def test_deterministic_policy_parity(policy, seed, wm):
    state = random_state(seed)
    graph = wm.comm_graph()
    snap = oracle.to_snapshot(state, graph)
    _, mask = detect_hazard(state, threshold=30.0)
    hazard = [state.node_names[i] for i in range(state.num_nodes) if bool(mask[i])]
    if len(hazard) == state.num_nodes:
        pytest.skip("all nodes hazardous")
    svc_idx = seed % 20
    exp = _oracle_choice(policy, snap, hazard, wm.relation(), f"s{svc_idx}")
    got = choose_node(
        jnp.asarray(POLICY_IDS[policy]),
        state,
        graph,
        jnp.asarray(svc_idx),
        mask,
        jax.random.PRNGKey(0),
    )
    assert state.node_names[int(got)] == exp


def _tie_state(names, pod_nodes, pod_cpu):
    """Hand-built cluster for constructed-tie cases: pod i = service s{i},
    4000m nodes (low enough usage that nothing is hazardous)."""
    n = len(names)
    return ClusterState.build(
        node_names=names,
        node_cpu_cap=[4000.0] * n,
        node_mem_cap=[1e9] * n,
        pod_services=list(range(len(pod_nodes))),
        pod_nodes=pod_nodes,
        pod_cpu=pod_cpu,
        pod_mem=[0.0] * len(pod_nodes),
        pod_names=[f"s{i}-0" for i in range(len(pod_nodes))],
    )


def _device_and_oracle(policy, state, wm, svc_idx=0):
    graph = wm.comm_graph()
    _, mask = detect_hazard(state, threshold=30.0)
    assert not np.asarray(mask).any(), "tie fixtures must be hazard-free"
    got = choose_node(
        jnp.asarray(POLICY_IDS[policy]),
        state, graph, jnp.asarray(svc_idx), mask, jax.random.PRNGKey(0),
    )
    snap = oracle.to_snapshot(state, graph)
    exp = _oracle_choice(policy, snap, [], wm.relation(), f"s{svc_idx}")
    return state.node_names[int(got)], exp


def test_spread_tie_lexicographic_min(wm):
    """Equal pod counts on every node -> lexicographic-min name
    (reference rescheduling.py:101)."""
    state = _tie_state(["wc", "wa", "wd", "wb"], [0, 1, 2, 3], [100.0] * 4)
    got, exp = _device_and_oracle("spread", state, wm)
    assert got == exp == "wa"


def test_binpack_tie_lexicographic_max(wm):
    """Equal cpu_pct on every node -> lexicographic-max name
    (reference rescheduling.py:133)."""
    state = _tie_state(["wc", "wa", "wd", "wb"], [0, 1, 2, 3], [400.0] * 4)
    got, exp = _device_and_oracle("binpack", state, wm)
    assert got == exp == "wd"


def test_communication_tie_max_remaining_cpu(wm):
    """Equal related-pod counts -> max remaining CPU wins
    (reference rescheduling.py:202-212). s0's relations are s1/s3/s7/s16:
    wa and wb hold 2 each; wb carries less load, so wb wins."""
    pod_nodes = [3] * 20
    pod_cpu = [50.0] * 20
    for svc, node in ((1, 0), (7, 0), (3, 1), (16, 1)):
        pod_nodes[svc] = node
    pod_cpu[1] = 200.0   # wa used: 250
    pod_cpu[7] = 50.0
    pod_cpu[3] = 50.0    # wb used: 100
    pod_cpu[16] = 50.0
    state = _tie_state(["wa", "wb", "wc", "wd"], pod_nodes, pod_cpu)
    got, exp = _device_and_oracle("communication", state, wm, svc_idx=0)
    assert got == exp == "wb"


def test_kubescheduling_tie_first_in_node_order(wm):
    """Equal free fraction everywhere -> first node in state order
    (our documented least-allocated model, oracle self-consistency)."""
    state = _tie_state(["wc", "wa", "wd", "wb"], [0, 1, 2, 3], [100.0] * 4)
    got, exp = _device_and_oracle("kubescheduling", state, wm)
    assert got == exp == "wc"


def test_random_policy_uniform_over_candidates(wm):
    state = random_state(3)
    graph = wm.comm_graph()
    _, mask = detect_hazard(state, threshold=30.0)
    cand = [i for i in range(state.num_nodes) if not bool(mask[i])]
    if len(cand) < 2:
        pytest.skip("not enough candidates")
    keys = jax.random.split(jax.random.PRNGKey(0), 300)
    picks = jax.vmap(
        lambda k: choose_node(
            jnp.asarray(POLICY_IDS["random"]), state, graph, jnp.asarray(0), mask, k
        )
    )(keys)
    picks = np.asarray(picks)
    counts = {i: int((picks == i).sum()) for i in set(picks.tolist())}
    assert set(counts) == set(cand)  # only candidates, never hazard nodes
    # roughly uniform: every candidate gets at least half its fair share
    for c in cand:
        assert counts[c] > 300 / len(cand) / 2


def test_choose_node_all_hazard_returns_minus_one(wm):
    state = random_state(0)
    graph = wm.comm_graph()
    all_hazard = jnp.ones((state.num_nodes,), bool)
    got = choose_node(
        jnp.asarray(POLICY_IDS["spread"]),
        state, graph, jnp.asarray(0), all_hazard, jax.random.PRNGKey(0),
    )
    assert int(got) == -1


def test_lex_argmax_tiebreaks():
    mask = jnp.ones((4,), bool)
    k1 = jnp.asarray([1.0, 2.0, 2.0, 0.0])
    k2 = jnp.asarray([9.0, 1.0, 5.0, 9.0])
    assert int(lex_argmax([k1, k2], mask)) == 2
    assert int(lex_argmax([k1], mask)) == 1  # first max wins
    assert int(lex_argmax([k1], jnp.zeros((4,), bool))) == -1
