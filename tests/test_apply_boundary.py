"""CI twin of ``scripts/check_apply_boundary.py``: the control loops
fence device work and pull diagnostics only at the designated apply-
boundary / round-end sites (``bench.round_end``) — a stray
``block_until_ready``/``device_get``/``pull`` in a round helper would
silently re-introduce the per-round RTTs the single-bundle round-end
protocol removed."""

import importlib.util
import sys
from pathlib import Path


def _load_checker():
    path = (
        Path(__file__).resolve().parent.parent
        / "scripts"
        / "check_apply_boundary.py"
    )
    spec = importlib.util.spec_from_file_location("check_apply_boundary", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules.setdefault("check_apply_boundary", mod)
    spec.loader.exec_module(mod)
    return mod


def test_controller_has_no_raw_device_syncs():
    checker = _load_checker()
    assert checker.violations() == []


def test_checker_catches_raw_syncs(tmp_path):
    checker = _load_checker()
    f = tmp_path / "mod.py"
    f.write_text(
        "import jax\n"
        "def round_helper(out, closer):\n"
        "    jax.block_until_ready(out)\n"        # raw fence: flagged
        "    x = jax.device_get(out)\n"           # raw transfer: flagged
        "    y = pull(out, site='x')\n"           # raw counted pull: flagged
        "    closer.flush()\n"                    # designated site: allowed
        "    return fence(out)\n"                 # designated wrapper: allowed
        "def _pull_round_bundle(arr, site):\n"
        "    return pull(arr, site=site)\n"       # the allowlisted home
    )
    lines = sorted(line for line, _ in checker.find_raw_syncs(f))
    assert lines == [3, 4, 5]


def test_checker_flags_module_level_calls(tmp_path):
    checker = _load_checker()
    f = tmp_path / "mod.py"
    f.write_text("import jax\nx = jax.device_get(1)\n")
    assert [line for line, _ in checker.find_raw_syncs(f)] == [2]


def test_scan_module_is_checked_with_its_own_allowlist(tmp_path):
    """ISSUE 12: the scan module is covered with ``pull_block`` as its
    ONLY designated sync site — 'one transfer per K scanned rounds' is a
    static property, not a convention. Per-file allowlists must not
    leak: the fleet helper's name does not legalize a sync in
    controller.py, and vice versa."""
    checker = _load_checker()
    # key by package-relative path: PR 15 added forecast/fleet.py to
    # CHECKED, so bare basenames collide (two fleet.py entries)
    by_name = {
        p.relative_to(checker.PACKAGE).as_posix(): allowed
        for p, allowed in checker.CHECKED.items()
    }
    assert by_name["bench/scan.py"] == frozenset({"pull_block"})
    assert by_name["bench/fleet.py"] == frozenset({"_pull_round_bundle"})
    assert by_name["bench/controller.py"] == frozenset()
    assert by_name["forecast/fleet.py"] == frozenset()
    # a pull anywhere else in a scan-shaped module is flagged
    f = tmp_path / "scan.py"
    f.write_text(
        "def pull_block(arr):\n"
        "    return pull(arr, site='round_end')\n"   # designated: allowed
        "def decode_block(flat):\n"
        "    return pull(flat, site='oops')\n"        # stray: flagged
    )
    hits = checker.find_raw_syncs(f, by_name["bench/scan.py"])
    assert [line for line, _ in hits] == [4]
    # the fleet allowlist does NOT legalize scan.py's site (and the
    # union default would — per-file scoping is the point)
    hits_fleet = checker.find_raw_syncs(f, by_name["bench/fleet.py"])
    assert [line for line, _ in hits_fleet] == [2, 4]
