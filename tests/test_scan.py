"""ISSUE 12: device-resident round scan — K rounds per dispatch with a
jittable sim twin.

The contracts under test:

- **Bit-parity oracle** — seeded multi-round trajectories through the
  jitted ``backends.sim_device.sim_step`` and the Python ``SimBackend``
  produce bit-identical placements and loads (placement sha1 pinned
  equal per round), including moves that land on over-capacity nodes,
  moves targeting dead nodes (no-ops on both sides), and the
  ``affinityOnly`` scheduler-choice fallback. The shared
  ``workload_layout`` keeps post-churn twins aligned with the backend.
- **Scanned schedule** (``[controller] scan_block``) — records and
  event streams bit-identical to the sequential loop modulo timing
  fields, on static AND chaos-drain soaks; exactly ONE counted
  ``round_end`` transfer per scan block; ``jax_traces_total
  {fn="scan_rounds"} == 1`` in steady state; every per-round-path
  fallback counted in ``scan_drains_total{reason}``.
- **Fleet composition** — one ``fleet_scan_rounds`` dispatch advances
  every tenant K rounds, per-tenant streams bit-identical to the
  sequential fleet loop.

Node counts in this file stay in the 16-23 range (prefix ``sn``) so the
module-level kernels compile fresh here — trace pins cannot be
satisfied by another test file's cache entries, and each pin test uses
its own count so it cannot be satisfied by a sibling test's.
"""

import hashlib
import io
import json
import contextlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_rescheduling_tpu.backends.base import MoveRequest
from kubernetes_rescheduling_tpu.backends.k8s import PlacementMechanism
from kubernetes_rescheduling_tpu.backends.sim import (
    LoadModel,
    SimBackend,
    workload_layout,
)
from kubernetes_rescheduling_tpu.backends.sim_device import (
    apply_decision,
    scan_compatible,
    scheduler_choice,
    sim_step,
    twin_of,
)
from kubernetes_rescheduling_tpu.bench.controller import run_controller
from kubernetes_rescheduling_tpu.config import (
    SCAN_POLICIES,
    POLICIES,
    ChaosConfig,
    ControllerConfig,
    ElasticConfig,
    RescheduleConfig,
)
from kubernetes_rescheduling_tpu.core.workmodel import (
    ServiceSpec,
    Workmodel,
    mubench_workmodel_c,
)
from kubernetes_rescheduling_tpu.telemetry import get_registry
from kubernetes_rescheduling_tpu.telemetry.registry import (
    MetricsRegistry,
    set_registry,
)
from kubernetes_rescheduling_tpu.utils.logging import StructuredLogger
from kubernetes_rescheduling_tpu.utils.retry import RetryPolicy


@pytest.fixture()
def registry():
    prev = set_registry(MetricsRegistry())
    try:
        yield get_registry()
    finally:
        set_registry(prev)


def _backend(n_nodes: int, seed: int = 0, cap_m: float = 20_000.0) -> SimBackend:
    backend = SimBackend(
        workmodel=mubench_workmodel_c(),
        node_names=[f"sn{i}" for i in range(n_nodes)],
        node_cpu_cap_m=cap_m,
        seed=seed,
        load=LoadModel(entry_rps=100.0, cost_per_req_m=8.0, idle_m=50.0),
    )
    backend.inject_imbalance(backend.node_names[0])
    return backend


# timing-only fields: everything else must be bit-equal (the pipeline
# suite's convention)
TIMING_FIELDS = {
    "decision_latencies_s", "decision_latency_s", "wall_s", "pipeline",
}


def _strip(rec) -> dict:
    return {k: v for k, v in rec.as_dict().items() if k not in TIMING_FIELDS}


def _events(log):
    out = []
    for r in log.records:
        if r["event"] in ("decision", "round"):
            out.append({
                k: v for k, v in r.items()
                if k not in ("ts", "decision_latency_s")
            })
    return out


def _run(
    *, scan_block: int, n_nodes: int, rounds: int,
    algo: str = "communication", chaos_profile: str = "none",
    churn_profile: str = "none",
    retry: RetryPolicy | None = None, max_consecutive_failures: int = 5,
    with_logger: bool = True, seed: int = 0, checkpoint_dir=None,
):
    cfg = RescheduleConfig(
        algorithm=algo,
        max_rounds=rounds,
        sleep_after_action_s=0.0,
        seed=seed,
        chaos=ChaosConfig(profile=chaos_profile, seed=seed),
        elastic=ElasticConfig(profile=churn_profile, seed=0),
        max_consecutive_failures=max_consecutive_failures,
        retry=retry if retry is not None else RetryPolicy(),
        controller=ControllerConfig(scan_block=scan_block),
    )
    logger = StructuredLogger(name="t") if with_logger else None
    result = run_controller(
        _backend(n_nodes, seed=seed), cfg,
        key=jax.random.PRNGKey(seed), logger=logger,
        checkpoint_dir=checkpoint_dir,
    )
    return result, logger


# ---------------- the bit-parity oracle: jitted sim_step vs SimBackend ---


def _digest(state) -> str:
    return hashlib.sha1(
        np.asarray(state.pod_node).tobytes()
        + np.asarray(state.pod_valid).tobytes()
    ).hexdigest()


def test_sim_step_oracle_parity(registry):
    """Seeded 24-round trajectory driven through BOTH halves: the jitted
    twin and the Python simulator stay bit-identical — placements
    (sha1) and loads — across pinned moves, moves that land on full
    (over-capacity) nodes, moves targeting a dead node (no-ops on both
    sides), and the affinityOnly scheduler-choice fallback."""
    backend = _backend(16, seed=7, cap_m=700.0)  # tiny caps: nodes run full
    backend.kill_node(backend.node_names[5])     # a dead target to aim at
    state, graph = twin_of(backend)
    assert np.array_equal(
        np.asarray(state.pod_node), np.asarray(backend.monitor().pod_node)
    )
    step = jax.jit(sim_step, static_argnames=("pinned",))
    rng = np.random.default_rng(7)
    svc_arr = np.asarray(state.pod_service)
    valid = np.asarray(state.pod_valid)
    n = state.num_nodes
    for rnd in range(24):
        svc = int(rng.integers(len(backend.workmodel.services)))
        pods = np.flatnonzero(valid & (svc_arr == svc))
        victim = int(pods[0])
        # every 4th move targets the dead node; every 3rd goes through
        # the scheduler-choice fallback with a random hazard set
        target = 5 if rnd % 4 == 3 else int(rng.integers(n))
        affinity = rnd % 3 == 1
        hazard = np.zeros(n, dtype=bool)
        hazard[rng.choice(n, size=4, replace=False)] = True
        mech = "affinityOnly" if affinity else "nodeName"
        new_state, snap = step(
            state,
            (jnp.asarray(victim), jnp.asarray(svc), jnp.asarray(target),
             jnp.asarray(hazard)),
            pinned=not affinity,
        )
        backend.apply_move(
            MoveRequest(
                service=backend.workmodel.services[svc].name,
                target_node=backend.node_names[target],
                hazard_nodes=tuple(
                    backend.node_names[j] for j in np.flatnonzero(hazard)
                ),
                mechanism=mech,
            )
        )
        observed = backend.monitor()
        assert _digest(snap) == _digest(observed), f"round {rnd} diverged"
        np.testing.assert_array_equal(
            np.asarray(snap.pod_node), np.asarray(observed.pod_node)
        )
        np.testing.assert_array_equal(
            np.asarray(snap.pod_cpu), np.asarray(observed.pod_cpu)
        )
        state = new_state


def test_sim_step_post_churn_parity(registry):
    """Satellite 6: twin construction and ``SimBackend._refresh_workload``
    share ONE ``workload_layout`` — after deploys, teardowns (index
    compaction), and autoscaling under a padded service bucket, a
    rebuilt twin still tracks the backend bit-for-bit."""
    wm = Workmodel(
        services=(
            ServiceSpec(name="a", callees=("b",), replicas=2),
            ServiceSpec(name="b", callees=("c",)),
            ServiceSpec(name="c"),
        )
    )
    backend = SimBackend(
        workmodel=wm,
        node_names=[f"sn{i}" for i in range(4)],
        seed=3,
        load=LoadModel(entry_service="a"),
        service_capacity=8,
        pod_capacity=32,
    )
    backend.deploy_service(ServiceSpec(name="d", callees=("a",), replicas=2))
    backend.teardown_service("b")   # compacts every later service index
    backend.scale_replicas("a", 3)
    state, graph = twin_of(backend)
    # the layout the twin sees IS the layout the backend serves
    g2, idx = workload_layout(backend.workmodel, backend.service_capacity)
    assert graph.names == backend.comm_graph().names == g2.names
    assert graph.num_services == backend.comm_graph().num_services
    step = jax.jit(sim_step, static_argnames=("pinned",))
    svc_arr = np.asarray(state.pod_service)
    valid = np.asarray(state.pod_valid)
    for rnd, name in enumerate(("a", "c", "d")):
        svc = idx[name]
        victim = int(np.flatnonzero(valid & (svc_arr == svc))[0])
        target = rnd % len(backend.node_names)
        hazard = np.zeros(state.num_nodes, dtype=bool)
        state, snap = step(
            state,
            (jnp.asarray(victim), jnp.asarray(svc), jnp.asarray(target),
             jnp.asarray(hazard)),
            pinned=True,
        )
        backend.apply_move(
            MoveRequest(
                service=name,
                target_node=backend.node_names[target],
                hazard_nodes=(),
                mechanism="nodeName",
            )
        )
        assert _digest(snap) == _digest(backend.monitor())


def test_scheduler_choice_matches_python(registry):
    """The device scheduler-choice twin picks exactly the node the
    Python ``_scheduler_choice`` would — least allocated CPU among
    alive non-excluded nodes, tie → first in node order."""
    backend = _backend(17, seed=1)
    backend.kill_node(backend.node_names[3])
    state, _ = twin_of(backend)
    for excl in ((), (0, 1), (0, 1, 2, 4)):
        hazard = np.zeros(state.num_nodes, dtype=bool)
        hazard[list(excl)] = True
        want = backend._scheduler_choice(
            exclude=tuple(backend.node_names[j] for j in excl)
        )
        got = int(jax.jit(scheduler_choice)(state, jnp.asarray(hazard)))
        assert got == want
    # nothing eligible -> -1 (the Python None path)
    all_h = np.ones(state.num_nodes, dtype=bool)
    assert int(jax.jit(scheduler_choice)(state, jnp.asarray(all_h))) == -1


def test_scan_policy_registry_mirrors_mechanism_table():
    """SCAN_POLICIES (config-side mirror, import-light) must equal the
    greedy policies whose PlacementMechanism pins the landing node."""
    assert set(SCAN_POLICIES) == {
        p for p in POLICIES if PlacementMechanism[p] != "affinityOnly"
    }
    assert scan_compatible(_backend(4)) is True
    noisy = _backend(4)
    noisy.load.noise_frac = 0.1
    assert scan_compatible(noisy) is False


# ---------------- scanned schedule: bit-identity + transfer/trace pins ---


def test_scanned_bit_identical_to_sequential_acceptance(registry):
    """THE acceptance soak (tier-1): scanned records and event streams
    bit-identical to the sequential loop (explain + attribution live),
    exactly ONE counted round_end transfer per scan block, 1 steady-
    state trace of the fused kernel, and tail rounds drained+counted."""
    rounds, block = 8, 3
    fam = registry.counter("device_transfers_total", labelnames=("site",))
    seq, seq_log = _run(scan_block=0, n_nodes=18, rounds=rounds)
    assert fam.labels(site="round_end").value == rounds
    sc, sc_log = _run(scan_block=block, n_nodes=18, rounds=rounds)
    # 2 full blocks (1 transfer each) + 2 drained tail rounds (1 each)
    assert fam.labels(site="round_end").value == rounds + 4
    assert len(seq.rounds) == len(sc.rounds) == rounds
    for a, b in zip(seq.rounds, sc.rounds):
        assert _strip(a) == _strip(b)
    assert _events(seq_log) == _events(sc_log)
    traces = registry.counter("jax_traces_total", labelnames=("fn",))
    assert traces.labels(fn="scan_rounds").value == 1
    assert registry.counter("scan_blocks_total").value == 2
    assert registry.gauge("scan_rounds_per_dispatch").value == block
    drains = registry.counter("scan_drains_total", labelnames=("reason",))
    assert drains.labels(reason="tail").value == 2


def test_scanned_chaos_drain_soak_bit_identical(registry):
    """Chaos wraps the backend, so the scanned schedule must drain EVERY
    round to the per-round path (reason="backend") and remain
    bit-identical to the sequential chaos run — skips, breaker
    transitions, and records included."""
    kwargs = dict(
        n_nodes=19, rounds=14, chaos_profile="soak",
        retry=RetryPolicy(max_attempts=1), max_consecutive_failures=2,
    )
    seq, _ = _run(scan_block=0, **kwargs)
    sc, _ = _run(scan_block=4, **kwargs)
    assert len(sc.rounds) + sc.skipped_rounds == 14
    assert sc.skipped_rounds == seq.skipped_rounds > 0
    assert [t["to"] for t in sc.breaker_transitions] == [
        t["to"] for t in seq.breaker_transitions
    ]
    for a, b in zip(seq.rounds, sc.rounds):
        assert _strip(a) == _strip(b)
    drains = registry.counter("scan_drains_total", labelnames=("reason",))
    assert drains.labels(reason="backend").value == 14
    assert registry.counter("scan_blocks_total").value == 0


def test_scanned_drain_reasons_checkpoint_and_churn(registry, tmp_path):
    """A checkpoint manager (per-round saves) and a churn engine (events
    the scan cannot foresee) each force the per-round path, counted
    under their own reasons — and the runs still complete exactly."""
    res, _ = _run(
        scan_block=2, n_nodes=20, rounds=2,
        checkpoint_dir=str(tmp_path / "ckpt"),
    )
    assert len(res.rounds) == 2
    drains = registry.counter("scan_drains_total", labelnames=("reason",))
    assert drains.labels(reason="checkpoint").value == 2
    res2, _ = _run(
        scan_block=2, n_nodes=20, rounds=2,
        churn_profile="steady",
    )
    assert len(res2.rounds) + res2.skipped_rounds == 2
    assert drains.labels(reason="churn").value == 2
    assert registry.counter("scan_blocks_total").value == 0


@pytest.mark.slow  # 40-round greedy scan soak: the scanned-vs-sequential invariant stays pinned fast by test_scanned_bit_identical_to_sequential_acceptance above — this is the long-horizon redundant variant
def test_scanned_long_soak_bit_identical(registry):
    rounds, block = 40, 8
    seq, seq_log = _run(scan_block=0, n_nodes=22, rounds=rounds)
    sc, sc_log = _run(scan_block=block, n_nodes=22, rounds=rounds)
    for a, b in zip(seq.rounds, sc.rounds):
        assert _strip(a) == _strip(b)
    assert _events(seq_log) == _events(sc_log)
    assert registry.counter("scan_blocks_total").value == rounds // block
    traces = registry.counter("jax_traces_total", labelnames=("fn",))
    assert traces.labels(fn="scan_rounds").value == 1


@pytest.mark.slow  # spread/binpack/random scanned parity: the scanned schedule's bit-identity stays pinned fast by the communication-policy acceptance soak above — these are the per-policy redundant variants
@pytest.mark.parametrize("algo", ["spread", "binpack", "random"])
def test_scanned_bit_identical_other_policies(registry, algo):
    seq, _ = _run(scan_block=0, n_nodes=23, rounds=6, algo=algo)
    sc, _ = _run(scan_block=3, n_nodes=23, rounds=6, algo=algo)
    for a, b in zip(seq.rounds, sc.rounds):
        assert _strip(a) == _strip(b)


# ---------------- bare loop: edge-list metrics + transfer budget ---------


def test_scanned_bare_loop_edge_metrics_bit_identical(registry):
    """The bare loop (no logger → attribution off) routes the round-end
    cost scalar over the precomputed edge list in BOTH schedules — the
    records must still agree bit-for-bit, at one transfer per block."""
    rounds, block = 4, 2
    seq, _ = _run(scan_block=0, n_nodes=21, rounds=rounds, with_logger=False)
    fam = registry.counter("device_transfers_total", labelnames=("site",))
    assert fam.labels(site="round_end").value == rounds
    sc, _ = _run(
        scan_block=block, n_nodes=21, rounds=rounds, with_logger=False
    )
    assert fam.labels(site="round_end").value == rounds + 2
    for a, b in zip(seq.rounds, sc.rounds):
        assert _strip(a) == _strip(b)
    assert all(np.isfinite(r.communication_cost) for r in sc.rounds)


def test_edge_list_cost_matches_dense_kernel(registry):
    """``communication_cost_edges`` computes the same quantity as the
    dense quadratic form — exactly on integer-weighted graphs (mubench)
    and to f32 tolerance in general."""
    from kubernetes_rescheduling_tpu.objectives.metrics import (
        comm_edge_list,
        communication_cost,
        communication_cost_edges,
    )

    backend = _backend(16, seed=2)
    state = backend.monitor()
    graph = backend.comm_graph()
    edges = comm_edge_list(graph)
    dense = float(communication_cost(state, graph))
    sparse = float(
        communication_cost_edges(state, graph.num_services, edges)
    )
    assert sparse == dense  # integer-valued at mubench scale: exact
    # E pads to the power-of-two bucket with INERT zero-weight edges:
    # small edge-count churn must reuse one compiled signature, and the
    # padding must not move the scalar
    src, dst, w = edges
    assert src.shape[0] >= 8 and src.shape[0] & (src.shape[0] - 1) == 0
    trimmed = (src[:-1], dst[:-1], w[:-1])
    if float(w[-1]) == 0.0:  # the padded tail really is inert
        assert float(
            communication_cost_edges(state, graph.num_services, trimmed)
        ) == sparse
    # a graph-changing churn event within the same bucket must land in
    # the SAME compiled signature: dropping one edge keeps E's padded
    # shape (the round-end kernel's 1-trace invariant under churn)
    adj2 = np.asarray(graph.adj).copy()
    i, j = int(src[0]), int(dst[0])
    adj2[i, j] = adj2[j, i] = 0.0
    fewer = comm_edge_list(graph.replace(adj=jnp.asarray(adj2)))
    assert fewer[0].shape == src.shape
    # empty graph -> all-padding list, zero cost
    empty = graph.replace(adj=jnp.zeros_like(graph.adj))
    esrc, _edst, ew = comm_edge_list(empty)
    assert esrc.shape[0] == 8 and float(np.sum(np.asarray(ew))) == 0.0
    assert float(
        communication_cost_edges(state, graph.num_services, (esrc, _edst, ew))
    ) == 0.0


def test_scanned_explain_clamp_on_tiny_cluster(registry):
    """``decide_explain`` clamps its bundle to min(top_k, num_nodes)
    columns; the block decode must apply the same clamp — a cluster
    with fewer nodes than explain_top_k previously shifted every later
    slice (confirmed decode crash)."""
    backend = SimBackend(
        workmodel=mubench_workmodel_c(),
        node_names=["sn0", "sn1"],  # 2 < the default explain_top_k of 3
        node_cpu_cap_m=20_000.0,
        seed=0,
        load=LoadModel(entry_rps=100.0, cost_per_req_m=8.0, idle_m=50.0),
    )
    backend.inject_imbalance("sn0")

    def run(scan_block):
        cfg = RescheduleConfig(
            algorithm="communication", max_rounds=4,
            sleep_after_action_s=0.0, seed=0,
            controller=ControllerConfig(scan_block=scan_block),
        )
        b = SimBackend(
            workmodel=mubench_workmodel_c(),
            node_names=["sn0", "sn1"],
            node_cpu_cap_m=20_000.0,
            seed=0,
            load=LoadModel(entry_rps=100.0, cost_per_req_m=8.0, idle_m=50.0),
        )
        b.inject_imbalance("sn0")
        return run_controller(
            b, cfg, key=jax.random.PRNGKey(0),
            logger=StructuredLogger(name="t"),
        )

    seq = run(0)
    sc = run(2)
    assert len(sc.rounds) == 4
    for a, b in zip(seq.rounds, sc.rounds):
        assert _strip(a) == _strip(b)


# ---------------- config / CLI surfaces ----------------------------------


def test_scan_config_validation():
    ok = RescheduleConfig(
        algorithm="communication",
        controller=ControllerConfig(scan_block=8),
    ).validate()
    assert ok.controller.scan_block == 8
    with pytest.raises(ValueError):
        ControllerConfig(scan_block=-1).validate()
    with pytest.raises(ValueError):
        ControllerConfig(scan_block=4, pipeline=True).validate()
    for bad in (
        dict(algorithm="kubescheduling"),   # affinityOnly landing
        dict(algorithm="global"),           # solver decides outside scan
        dict(algorithm="proactive"),        # forecast outside scan
        dict(algorithm="communication", moves_per_round=2),
        dict(algorithm="communication", backend="k8s"),
    ):
        with pytest.raises(ValueError):
            RescheduleConfig(
                controller=ControllerConfig(scan_block=4), **bad
            ).validate()


def test_scan_block_from_toml(tmp_path):
    cfg_file = tmp_path / "scan.toml"
    cfg_file.write_text(
        "algorithm = 'communication'\n"
        "[controller]\nscan_block = 16\n"
    )
    cfg = RescheduleConfig.from_toml(cfg_file)
    assert cfg.controller.scan_block == 16


def test_cli_scan_smoke(registry):
    from kubernetes_rescheduling_tpu.cli import main as cli_main

    out = io.StringIO()
    with contextlib.redirect_stdout(out):
        rc = cli_main([
            "reschedule", "--scan-block", "2", "--rounds", "2",
            "--scenario", "mubench", "--imbalance",
        ])
    assert rc == 0
    payload = json.loads(out.getvalue())
    assert len(payload["rounds"]) == 2


# ---------------- fleet composition --------------------------------------


def _fleet_run(scan_block: int):
    from kubernetes_rescheduling_tpu.backends.fleet import make_fleet
    from kubernetes_rescheduling_tpu.bench.fleet import run_fleet_controller
    from kubernetes_rescheduling_tpu.config import FleetConfig

    fleet = make_fleet("mubench", 3, seed=5)
    fleet.inject_imbalance()
    cfg = RescheduleConfig(
        algorithm="communication",
        max_rounds=6,
        sleep_after_action_s=0.0,
        fleet=FleetConfig(tenants=3),
        controller=ControllerConfig(scan_block=scan_block),
    )
    return run_fleet_controller(fleet, cfg, key=jax.random.PRNGKey(5))


def test_fleet_scan_bit_identical_per_tenant(registry):
    """One scan dispatch advances ALL tenants K rounds: per-tenant round
    streams bit-identical to the sequential fleet loop, one round_end
    transfer per block (the per-round fleet_decision/fleet_metrics
    sites stay silent on scanned rounds), 1 steady-state trace."""
    seq = _fleet_run(0)
    fam = registry.counter("device_transfers_total", labelnames=("site",))
    seq_dec = fam.labels(site="fleet_decision").value
    sc = _fleet_run(3)
    assert fam.labels(site="fleet_decision").value == seq_dec  # no new ones
    assert fam.labels(site="round_end").value == 2  # 6 rounds / block of 3
    assert seq.tenants == sc.tenants
    for name in seq.tenants:
        a, b = seq.results[name], sc.results[name]
        assert len(a.rounds) == len(b.rounds) == 6
        assert a.skipped_rounds == b.skipped_rounds == 0
        for ra, rb in zip(a.rounds, b.rounds):
            assert _strip(ra) == _strip(rb)
    traces = registry.counter("jax_traces_total", labelnames=("fn",))
    assert traces.labels(fn="fleet_scan_rounds").value == 1
    assert registry.counter("scan_blocks_total").value == 2
    # one dispatch per block on the fleet accounting too
    assert sc.batched_solves == 2 and seq.batched_solves == 6
