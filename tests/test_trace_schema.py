"""CI twin of ``scripts/check_trace_schema.py``: the checked-in fixture
traces satisfy the ClusterTrace JSONL schema (finite values, monotone
timestamps, known record kinds, declared node references), and the
checker flags every pinned corruption class — the loud half of the
corpus loader's deliberate leniency (``check_bench_schema.py``
convention, including the no-args self-check)."""

import importlib.util
import json
import sys
from pathlib import Path


def _load_checker():
    path = (
        Path(__file__).resolve().parent.parent
        / "scripts"
        / "check_trace_schema.py"
    )
    spec = importlib.util.spec_from_file_location("check_trace_schema", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules.setdefault("check_trace_schema", mod)
    spec.loader.exec_module(mod)
    return mod


def test_checked_in_fixtures_are_clean():
    checker = _load_checker()
    assert checker.violations() == []


def _write(tmp_path, rows):
    p = tmp_path / "t.trace.jsonl"
    p.write_text(
        "\n".join(r if isinstance(r, str) else json.dumps(r) for r in rows)
        + "\n"
    )
    return p


_NODE = {"kind": "node", "t": 0.0, "node": "n1", "cpu_cap_m": 1000.0}


def test_checker_flags_non_monotone_timestamps(tmp_path):
    checker = _load_checker()
    p = _write(
        tmp_path,
        [
            _NODE,
            {"kind": "pod", "t": 5.0, "pod": "p", "service": "s", "node": "n1"},
            {"kind": "pod", "t": 1.0, "pod": "q", "service": "s", "node": "n1"},
        ],
    )
    assert any("monotone" in v for v in checker.check_file(p))


def test_checker_flags_non_finite_values(tmp_path):
    checker = _load_checker()
    p = _write(
        tmp_path,
        [
            _NODE,
            {"kind": "pod", "t": 0.0, "pod": "p", "service": "s",
             "node": "n1", "cpu_m": float("nan")},
        ],
    )
    assert any("non-finite value" in v for v in checker.check_file(p))


def test_checker_flags_unknown_kind_and_missing_fields(tmp_path):
    checker = _load_checker()
    p = _write(
        tmp_path,
        [
            _NODE,
            {"kind": "teleport", "t": 0.0},
            {"kind": "pod", "t": 0.0, "pod": "p"},
            "{broken",
        ],
    )
    bad = checker.check_file(p)
    assert any("unknown kind" in v for v in bad)
    assert any("missing" in v for v in bad)
    assert any("broken JSON" in v for v in bad)


def test_checker_flags_undeclared_node_reference(tmp_path):
    checker = _load_checker()
    p = _write(
        tmp_path,
        [
            _NODE,
            {"kind": "pod", "t": 0.0, "pod": "p", "service": "s",
             "node": "ghost"},
        ],
    )
    assert any("undeclared node" in v for v in checker.check_file(p))


def test_checker_flags_an_empty_trace(tmp_path):
    checker = _load_checker()
    p = _write(tmp_path, [])
    assert any("no snapshot windows" in v for v in checker.check_file(p))
