"""Request-level load generator: the properties the reference measures with
its curl fleet (release1.sh:29-42, 74-117; release2.sh:50-59), each isolated.

The constructed-placement tests hold everything else fixed and vary one
term — cross-node edges, node utilization, outage windows — so they cannot
be flipped by an unrelated term dominating (the round-1 failure mode)."""

import jax
import numpy as np
import pytest

from kubernetes_rescheduling_tpu.backends.sim import SimBackend
from kubernetes_rescheduling_tpu.bench.loadgen import (
    LoadGenConfig,
    LoadGenerator,
    build_call_plan,
    new_samples,
)
from kubernetes_rescheduling_tpu.core.state import ClusterState
from kubernetes_rescheduling_tpu.core.workmodel import (
    ServiceSpec,
    Workmodel,
    mubench_workmodel_c,
)

CFG = LoadGenConfig(requests_per_phase=1024, chunk=512, jitter_sigma=0.0)


def chain_workmodel(n=4):
    """s0 -> s1 -> ... -> s(n-1), one pod each."""
    return Workmodel(
        services=tuple(
            ServiceSpec(name=f"s{i}", callees=(f"s{i+1}",) if i < n - 1 else ())
            for i in range(n)
        )
    )


def place(wm, pod_nodes, node_cpu=None, n_nodes=3, cap=10_000.0):
    """ClusterState with explicit per-service placement and node usage."""
    names = [f"n{i}" for i in range(n_nodes)]
    return ClusterState.build(
        node_names=names,
        node_cpu_cap=[cap] * n_nodes,
        node_mem_cap=[2**30] * n_nodes,
        node_alive=[True] * n_nodes,
        pod_services=list(range(len(wm.names))),
        pod_nodes=list(pod_nodes),
        pod_cpu=list(node_cpu) if node_cpu else [100.0] * len(wm.names),
        pod_mem=[0.0] * len(wm.names),
        pod_names=[f"{n}-0" for n in wm.names],
    )


def test_call_plan_mubench():
    wm = mubench_workmodel_c()
    plan = build_call_plan(wm.directed_relation(), wm.names, "s0")
    assert len(plan.src) == 19          # tree: 20 services, 19 call edges
    assert plan.depth == 3              # s0 -> s3 -> s9 -> s11
    assert plan.reach.sum() == 20       # all services reachable from s0
    assert plan.entry == 0


def test_call_plan_breaks_cycles():
    wm = Workmodel(
        services=(
            ServiceSpec(name="a", callees=("b",)),
            ServiceSpec(name="b", callees=("c",)),
            ServiceSpec(name="c", callees=("a",)),  # cycle back
        )
    )
    plan = build_call_plan(wm.directed_relation(), wm.names, "a")
    assert len(plan.src) == 2           # a->b, b->c kept; c->a dropped
    assert plan.depth == 2


def test_latency_increases_with_cross_node_edges():
    """Equal load, equal node utilization — only the placement's cross-node
    edge count differs. The network term must be visible on its own."""
    wm = chain_workmodel(4)
    gen = LoadGenerator(wm, CFG)
    key = jax.random.PRNGKey(0)
    # both placements use 2 pods per node on the same nodes -> same rho
    colocated = place(wm, [0, 0, 1, 1])     # one cross edge (s1->s2)
    alternating = place(wm, [0, 1, 0, 1])   # three cross edges
    lat_co = gen.measure(colocated, key).latency_avg_ms
    lat_alt = gen.measure(alternating, key).latency_avg_ms
    expected_gap = 2 * (CFG.hop_remote_ms - CFG.hop_local_ms)
    assert lat_alt > lat_co
    assert lat_alt - lat_co == pytest.approx(expected_gap, rel=0.01)


def test_latency_increases_with_utilization():
    """Same placement, hotter node -> queueing inflates service time."""
    wm = chain_workmodel(4)
    gen = LoadGenerator(wm, CFG)
    key = jax.random.PRNGKey(0)
    cool = place(wm, [0, 0, 0, 0], node_cpu=[100.0] * 4)       # 4% rho
    hot = place(wm, [0, 0, 0, 0], node_cpu=[2000.0] * 4)       # 80% rho
    assert gen.measure(hot, key).latency_avg_ms > gen.measure(cool, key).latency_avg_ms


def test_outage_window_fails_requests_proportionally():
    wm = chain_workmodel(3)
    gen = LoadGenerator(wm, CFG)
    key = jax.random.PRNGKey(1)
    st = place(wm, [0, 0, 0])
    clean = gen.measure(st, key)
    assert clean.errors == 0
    # s1 down for 25% of the phase: every request traverses s1 -> ~25% fail
    down = gen.measure(st, key, outages=[("s1", 0.0, 45.0)])
    assert down.err_outage == pytest.approx(0.25 * down.sent, rel=0.15)
    assert down.ok + down.errors == down.sent


def test_unplaced_service_errors_all_requests():
    wm = chain_workmodel(3)
    gen = LoadGenerator(wm, CFG)
    st = place(wm, [0, 0, -1])  # s2 has no running pod
    stats = gen.measure(st, jax.random.PRNGKey(0))
    assert stats.err_outage == stats.sent


def test_overload_drops_requests():
    wm = chain_workmodel(3)
    gen = LoadGenerator(wm, CFG)
    key = jax.random.PRNGKey(2)
    ok_state = place(wm, [0, 0, 0], node_cpu=[1000.0] * 3)      # 30% rho
    sat_state = place(wm, [0, 0, 0], node_cpu=[5000.0] * 3)     # 150% rho
    assert gen.measure(ok_state, key).err_overload == 0
    sat = gen.measure(sat_state, key)
    assert sat.err_overload > 0.3 * sat.sent


def test_deterministic_given_key():
    wm = mubench_workmodel_c()
    cfg = LoadGenConfig(requests_per_phase=512, chunk=256)  # jitter on
    gen = LoadGenerator(wm, cfg)
    backend = SimBackend(
        workmodel=wm, node_names=["w1", "w2", "w3"], seed=3
    )
    st = backend.monitor()
    a = gen.measure(st, jax.random.PRNGKey(42))
    b = gen.measure(st, jax.random.PRNGKey(42))
    assert a == b
    c = gen.measure(st, jax.random.PRNGKey(43))
    assert c.latency_avg_ms != a.latency_avg_ms


def test_multi_segment_accumulation():
    """Phase r2 semantics: segments with different placements accumulate
    into one stat block (reference release2.sh sustains load across the
    whole rescheduling run)."""
    wm = chain_workmodel(4)
    gen = LoadGenerator(wm, CFG)
    key = jax.random.PRNGKey(0)
    samples = new_samples()
    gen.run(place(wm, [0, 0, 0, 0]), key, duration_s=18.0, n_requests=100,
            samples=samples)
    gen.run(place(wm, [0, 1, 0, 1]), jax.random.fold_in(key, 1),
            duration_s=18.0, n_requests=100,
            outages=[("s1", 0.0, 3.0)], samples=samples)
    stats = samples.stats()
    assert stats.sent == 200
    assert stats.duration_s == pytest.approx(36.0)
    assert stats.err_outage > 0                # outage segment contributed
    assert stats.ok + stats.errors == stats.sent


def test_replica_load_balancing_mixes_hops():
    """A callee with replicas on two nodes: some requests hit the local
    replica, some the remote one — avg sits strictly between."""
    wm = Workmodel(
        services=(
            ServiceSpec(name="a", callees=("b",)),
            ServiceSpec(name="b", replicas=2),
        )
    )
    gen = LoadGenerator(wm, LoadGenConfig(
        requests_per_phase=2048, chunk=512, jitter_sigma=0.0, entry_service="a",
    ))
    names = ["n0", "n1"]
    st = ClusterState.build(
        node_names=names,
        node_cpu_cap=[10_000.0] * 2,
        node_mem_cap=[2**30] * 2,
        node_alive=[True] * 2,
        pod_services=[0, 1, 1],
        pod_nodes=[0, 0, 1],          # a on n0; b replicas on n0 and n1
        pod_cpu=[100.0] * 3,
        pod_mem=[0.0] * 3,
        pod_names=["a-0", "b-0", "b-1"],
    )
    stats = gen.measure(st, jax.random.PRNGKey(0))
    lo = stats.latency_min_ms
    hi = stats.latency_max_ms
    assert hi - lo == pytest.approx(
        gen.cfg.hop_remote_ms - gen.cfg.hop_local_ms, rel=0.01
    )
    assert lo < stats.latency_avg_ms < hi


def test_per_service_proc_cost_dominates_latency():
    """V4 (workmodelC.json:16-24): a service with 10x cpu_stress dominates
    end-to-end latency relative to a uniform-cost mesh."""
    def chain(costly):
        return Workmodel(
            services=(
                ServiceSpec(name="a", callees=("b",)),
                ServiceSpec(name="b", proc_cost=10.0 if costly else 1.0),
            )
        )

    st = ClusterState.build(
        node_names=["n0"],
        node_cpu_cap=[10_000.0],
        node_mem_cap=[2**30],
        node_alive=[True],
        pod_services=[0, 1],
        pod_nodes=[0, 0],
        pod_cpu=[100.0, 100.0],
        pod_mem=[0.0, 0.0],
        pod_names=["a-0", "b-0"],
    )
    cfg = LoadGenConfig(
        requests_per_phase=512, chunk=512, jitter_sigma=0.0, entry_service="a"
    )
    uniform = LoadGenerator(chain(False), cfg).measure(st, jax.random.PRNGKey(0))
    heavy = LoadGenerator(chain(True), cfg).measure(st, jax.random.PRNGKey(0))
    # b's base time goes 1.5 -> 15 ms: the extra 13.5 ms shows up 1:1,
    # inflated by the node's M/M/1 factor (rho = 200m/10000m -> 1/0.98)
    assert heavy.latency_avg_ms - uniform.latency_avg_ms == pytest.approx(
        9.0 * cfg.proc_ms / (1.0 - 0.02), rel=0.001
    )


def test_edge_probs_and_observed_weights_recover_actual_traffic():
    """V3: per-edge call probabilities diverge from the declared graph; the
    traversal counts recover the actual rates."""
    wm = Workmodel(
        services=(
            ServiceSpec(name="s0", callees=("s1", "s2")),
            ServiceSpec(name="s1"),
            ServiceSpec(name="s2"),
        )
    )
    st = ClusterState.build(
        node_names=["n0"],
        node_cpu_cap=[10_000.0],
        node_mem_cap=[2**30],
        node_alive=[True],
        pod_services=[0, 1, 2],
        pod_nodes=[0, 0, 0],
        pod_cpu=[100.0] * 3,
        pod_mem=[0.0] * 3,
        pod_names=["s0-0", "s1-0", "s2-0"],
    )
    gen = LoadGenerator(
        wm,
        LoadGenConfig(requests_per_phase=4096, chunk=1024, entry_service="s0"),
        edge_probs={("s0", "s1"): 0.05, ("s0", "s2"): 1.0},
    )
    samples = gen.run(st, jax.random.PRNGKey(1))
    w = gen.observed_weights(samples.edge_counts, samples.sent)
    assert w[("s0", "s2")] == pytest.approx(1.0, abs=0.01)
    assert w[("s0", "s1")] == pytest.approx(0.05, abs=0.02)
    # graph built from observation replaces the declared 1.0 weights
    est = gen.observed_graph(samples.edge_counts, samples.sent, wm.comm_graph())
    import jax.numpy as jnp
    i = {n: k for k, n in enumerate(est.names)}
    assert float(est.adj[i["s0"], i["s2"]]) == pytest.approx(1.0, abs=0.01)
    assert float(est.adj[i["s0"], i["s1"]]) < 0.1


def test_estimated_weights_beat_declared_on_measured_latency():
    """V3 headline (reference README.md:47): when declared topology and
    actual traffic disagree, the solve on traffic-estimated weights yields
    a measurably faster placement than the solve on declared weights."""
    from kubernetes_rescheduling_tpu.bench.trace import with_weights
    from kubernetes_rescheduling_tpu.core.topology import state_from_workmodel
    from kubernetes_rescheduling_tpu.solver import GlobalSolverConfig, global_assign

    wm = Workmodel(
        services=(
            ServiceSpec(name="s0", callees=("s1", "s2")),
            ServiceSpec(name="s1"),
            ServiceSpec(name="s2"),
        )
    )
    # DECLARED: s0-s1 is claimed hot (weight 3). ACTUAL: s0->s1 is nearly
    # dead (p=.05), s0->s2 carries everything.
    declared = with_weights(wm.comm_graph(), {("s0", "s1"): 3.0})
    gen = LoadGenerator(
        wm,
        LoadGenConfig(requests_per_phase=4096, chunk=1024,
                      jitter_sigma=0.0, entry_service="s0"),
        edge_probs={("s0", "s1"): 0.05, ("s0", "s2"): 1.0},
    )
    state = state_from_workmodel(
        wm, node_names=["n0", "n1"], node_cpu_cap_m=20_000.0, seed=3
    )
    # budget: 220m per node -> at most two 100m services colocate
    cfg = GlobalSolverConfig(
        sweeps=4, noise_temp=0.0, enforce_capacity=True, capacity_frac=0.011
    )
    key = jax.random.PRNGKey(0)
    st_declared, _ = global_assign(state, declared, key, cfg)
    samples = gen.run(state, jax.random.PRNGKey(1))
    estimated = gen.observed_graph(samples.edge_counts, samples.sent, declared)
    st_estimated, _ = global_assign(state, estimated, key, cfg)

    def node_of(st, svc):
        ps = np.asarray(st.pod_service); pn = np.asarray(st.pod_node)
        return int(pn[np.flatnonzero(ps == svc)[0]])

    # declared colocates the claimed-hot pair; estimation fixes it
    assert node_of(st_declared, 0) == node_of(st_declared, 1)
    assert node_of(st_estimated, 0) == node_of(st_estimated, 2)
    lat_declared = gen.measure(st_declared, jax.random.PRNGKey(2)).latency_avg_ms
    lat_estimated = gen.measure(st_estimated, jax.random.PRNGKey(2)).latency_avg_ms
    assert lat_estimated < lat_declared


@pytest.mark.slow  # sensitivity-corner sweep; policy ordering stays
# pinned fast by test_estimated_weights_beat_declared_on_measured_latency
def test_constant_extremes_preserve_policy_ordering():
    """The latency claims rest on ORDERINGS (optimized < pile-up and
    optimized < random), not on the loadgen's absolute milliseconds. Pin
    the ordering at the constant grid's extreme corners — the full 54-
    corner sweep (scripts/loadgen_sensitivity.py, 0 violations measured)
    is the slow version of this test. Placements are monitored through
    the sim backend so utilization couples to placement, exactly like the
    harness."""
    import jax

    from kubernetes_rescheduling_tpu.bench.harness import (
        mubench_reference_placements,
    )
    from kubernetes_rescheduling_tpu.bench.loadgen import (
        LoadGenConfig,
        LoadGenerator,
    )
    from kubernetes_rescheduling_tpu.core.workmodel import mubench_workmodel_c

    states = mubench_reference_placements()

    wm = mubench_workmodel_c()
    corners = [
        dict(proc_ms=0.5, hop_remote_ms=1.0, jitter_sigma=0.05, drop_rho=0.7),
        dict(proc_ms=0.5, hop_remote_ms=10.0, jitter_sigma=0.5, drop_rho=1.0),
        dict(proc_ms=5.0, hop_remote_ms=1.0, jitter_sigma=0.5, drop_rho=0.7),
        dict(proc_ms=5.0, hop_remote_ms=10.0, jitter_sigma=0.05, drop_rho=1.0),
    ]
    for corner in corners:
        gen = LoadGenerator(
            wm, LoadGenConfig(requests_per_phase=4000, **corner)
        )
        lat = {
            k: gen.measure(st, jax.random.PRNGKey(2)).latency_avg_ms
            for k, st in states.items()
        }
        assert lat["global"] < lat["pileup"], (corner, lat)
        assert lat["global"] < lat["random"], (corner, lat)
