"""Global solver: monotone improvement, capacity feasibility, and beating
greedy CAR on communication cost (the north-star claim, BASELINE.md)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from kubernetes_rescheduling_tpu.core.topology import (
    state_from_workmodel,
    synthetic_scenario,
)
from kubernetes_rescheduling_tpu.core.workmodel import mubench_workmodel_c
from kubernetes_rescheduling_tpu.objectives import (
    capacity_violation,
    communication_cost,
)
from kubernetes_rescheduling_tpu.policies import POLICY_IDS
from kubernetes_rescheduling_tpu.solver import (
    GlobalSolverConfig,
    global_assign,
    run_rounds,
)


def test_never_worse_than_input():
    wm = mubench_workmodel_c()
    state = state_from_workmodel(wm, seed=11)
    graph = wm.comm_graph()
    before = float(communication_cost(state, graph))
    new_state, info = global_assign(
        state, graph, jax.random.PRNGKey(0), GlobalSolverConfig(sweeps=4)
    )
    after = float(communication_cost(new_state, graph))
    assert after <= before
    assert float(info["objective_after"]) <= float(info["objective_before"]) + 1e-5


@pytest.mark.slow  # solution quality vs the true optimum stays pinned
# fast by test_optimum's gap tests and test_beats_greedy_car
def test_reaches_zero_cost_when_capacity_allows():
    # loose capacity -> optimum is everything on one node (cost 0)
    wm = mubench_workmodel_c()
    state = state_from_workmodel(wm, seed=3, node_cpu_cap_m=1e6)
    graph = wm.comm_graph()
    new_state, info = global_assign(
        state, graph, jax.random.PRNGKey(0), GlobalSolverConfig(sweeps=16)
    )
    assert float(communication_cost(new_state, graph)) == 0.0


def test_respects_capacity():
    scn = synthetic_scenario(
        n_pods=60, n_nodes=6, seed=5, node_cpu_cap_m=1500.0, imbalance_frac=0.5
    )
    # start may violate capacity (imbalance); solver must not increase violation
    v_before = float(capacity_violation(scn.state))
    new_state, _ = global_assign(
        scn.state, scn.graph, jax.random.PRNGKey(1),
        GlobalSolverConfig(sweeps=6),
    )
    v_after = float(capacity_violation(new_state))
    assert v_after <= v_before + 1e-3


@pytest.mark.slow  # solution quality stays pinned fast (and STRONGER) by
# test_optimum.test_solver_gap_small_instances_fast — global within a
# measured gap of the TRUE optimum; the head-to-head against greedy CAR
# re-proves a weaker claim at the price of two more full compiles (~16 s)
def test_beats_greedy_car():
    scn = synthetic_scenario(n_pods=100, n_nodes=8, seed=9, mean_degree=6.0)
    greedy_final, _ = run_rounds(
        scn.state, scn.graph, jnp.asarray(POLICY_IDS["communication"]),
        jax.random.PRNGKey(0), rounds=10,
    )
    greedy_cost = float(communication_cost(greedy_final, scn.graph))
    global_final, _ = global_assign(
        scn.state, scn.graph, jax.random.PRNGKey(0),
        GlobalSolverConfig(sweeps=8),
    )
    global_cost = float(communication_cost(global_final, scn.graph))
    assert global_cost <= greedy_cost


@pytest.mark.slow  # heavy dense-mesh scenario variant: capacity stays
# pinned fast by test_respects_capacity above (no-new-violation from an
# imbalanced pile) and by the sharded capacity run in
# test_parallel.test_sharded_global_assign_with_capacity_and_noise
def test_capacity_frac_breaks_up_dense_pile():
    """On a dense mesh the comm objective prefers total colocation at any
    moderate lambda, leaving a piled-up node saturated; a packing budget
    (capacity_frac) is what forces it apart — comm cost minimized within
    the budget instead of globally."""
    import jax.numpy as jnp

    from kubernetes_rescheduling_tpu.bench.harness import make_backend

    backend = make_backend("dense", seed=3)
    backend.inject_imbalance(backend.node_names[0])
    state = backend.monitor()
    graph = backend.comm_graph()

    free = global_assign(
        state, graph, jax.random.PRNGKey(0),
        GlobalSolverConfig(sweeps=4, balance_weight=0.5),
    )[0]
    # without a budget the pile survives (colocation is comm-optimal)
    assert float(jnp.max(free.node_cpu_pct())) > 40.0

    budget = 0.20
    capped = global_assign(
        state, graph, jax.random.PRNGKey(0),
        GlobalSolverConfig(
            sweeps=4, balance_weight=0.5,
            enforce_capacity=True, capacity_frac=budget,
        ),
    )[0]
    pct = jnp.asarray(capped.node_cpu_pct())[: capped.num_nodes]
    # every node that started within budget stays within it
    start_pct = jnp.asarray(state.node_cpu_pct())[: state.num_nodes]
    ok0 = start_pct <= budget * 100.0
    import numpy as np

    assert (np.asarray(pct)[np.asarray(ok0)] <= budget * 100.0 + 1e-3).all()
    # the pile node itself must have been drained below the raw saturation
    assert float(pct[0]) < float(start_pct[0])


@pytest.mark.slow  # λ's load-balance term stays exercised fast by
# test_capacity_frac_breaks_up_dense_pile below (balance_weight=0.5 in both
# solves) and the tp-parity cases in test_parallel.py; this is the heavy
# two-compile λ=0-vs-50 monotonicity variant
def test_balance_weight_tradeoff():
    wm = mubench_workmodel_c()
    state = state_from_workmodel(wm, seed=3, node_cpu_cap_m=4000.0)
    graph = wm.comm_graph()
    from kubernetes_rescheduling_tpu.objectives import load_std

    packed, _ = global_assign(
        state, graph, jax.random.PRNGKey(0),
        GlobalSolverConfig(sweeps=6, balance_weight=0.0),
    )
    balanced, _ = global_assign(
        state, graph, jax.random.PRNGKey(0),
        GlobalSolverConfig(sweeps=6, balance_weight=50.0),
    )
    assert float(load_std(balanced)) <= float(load_std(packed)) + 1e-4


@pytest.mark.slow  # masked-slot inertness through the global solver keeps
# two fast pins: the static mask-threading gate (global_assign is an
# ENTRY_POINT held by test_mask_threading's checker twin) and the
# masked-tenant no-moves assert in test_fleet_global_solve_bit_exact_vs_solo;
# this is the direct solo dynamic variant with its own ~20 s compile
def test_invalid_pods_untouched():
    wm = mubench_workmodel_c()
    state = state_from_workmodel(wm, seed=2, pod_capacity=40)
    graph = wm.comm_graph(capacity=32)
    new_state, _ = global_assign(state, graph, jax.random.PRNGKey(0))
    pv = np.asarray(state.pod_valid)
    np.testing.assert_array_equal(
        np.asarray(new_state.pod_node)[~pv], np.asarray(state.pod_node)[~pv]
    )


def test_no_improvement_keeps_split_replicas_untouched():
    # replicas of one service spread across nodes can't be represented in a
    # service-level assignment; with zero sweeps the solver must return the
    # input placement unchanged instead of collapsing replicas onto one node
    scn = synthetic_scenario(n_pods=40, n_nodes=4, replicas=4, seed=6)
    new_state, info = global_assign(
        scn.state, scn.graph, jax.random.PRNGKey(0), GlobalSolverConfig(sweeps=1, noise_temp=0.0)
    )
    before = float(communication_cost(scn.state, scn.graph))
    after = float(communication_cost(new_state, scn.graph))
    assert after <= before
    assert float(info["objective_before"]) == pytest.approx(before)
    if not bool(info["improved"]):
        np.testing.assert_array_equal(
            np.asarray(new_state.pod_node), np.asarray(scn.state.pod_node)
        )


def test_weight_budget_raises_clear_sizing_error():
    """V9: past the dense-W budget the solver raises a sizing error naming
    the knob — never a mid-compile OOM."""
    import jax
    import pytest

    from kubernetes_rescheduling_tpu.core.topology import synthetic_scenario
    from kubernetes_rescheduling_tpu.parallel import make_mesh, sharded_global_assign
    from kubernetes_rescheduling_tpu.solver import GlobalSolverConfig, global_assign

    scn = synthetic_scenario(n_pods=64, n_nodes=8, seed=0, mean_degree=4.0)
    tiny = GlobalSolverConfig(max_weight_bytes=1024)  # ~anything trips it
    with pytest.raises(ValueError, match="max_weight_bytes"):
        global_assign(scn.state, scn.graph, jax.random.PRNGKey(0), tiny)
    # W is replicated under tp — the sharded solver must refuse identically
    with pytest.raises(ValueError, match="max_weight_bytes"):
        sharded_global_assign(
            scn.state, scn.graph, jax.random.PRNGKey(0),
            make_mesh(8, shape=(2, 4)), tiny,
        )
    # the default budget admits the north-star scale (10240 padded:
    # 0.20 GiB bf16 matmul copy; the f32 W is never materialized)
    from kubernetes_rescheduling_tpu.solver.global_solver import check_weight_budget

    check_weight_budget(10240, GlobalSolverConfig())
    check_weight_budget(20480, GlobalSolverConfig())
    with pytest.raises(ValueError):
        check_weight_budget(90_000, GlobalSolverConfig())
    # float32 matmuls hit the wall sooner (4 bytes vs 2 per pair)
    with pytest.raises(ValueError):
        check_weight_budget(60_000, GlobalSolverConfig(matmul_dtype="float32"))


def test_pct_balance_terms_np_jnp_agree():
    """One balance/overload definition serves the traced solver (jnp) and
    the wave-cap's host-side ranking (np) — they must agree numerically."""
    import jax.numpy as jnp
    import numpy as np

    from kubernetes_rescheduling_tpu.solver.global_solver import pct_balance_terms

    rng = np.random.default_rng(0)
    loads = rng.random(16).astype(np.float32) * 200
    cap = np.full(16, 150.0, np.float32)
    valid = rng.random(16) < 0.9
    a = float(pct_balance_terms(loads, cap, valid, 0.5, 10.0, xp=np))
    b = float(pct_balance_terms(
        jnp.asarray(loads), jnp.asarray(cap), jnp.asarray(valid), 0.5, 10.0
    ))
    assert a == pytest.approx(b, rel=1e-6)
    assert a > 0


@pytest.mark.slow  # the blocking direction of the move-cost gate stays
# pinned fast by test_sharded_sparse.test_move_cost_parity_and_gate (the
# gate itself) and test_move_cost_accepts_profitable_moves... (the adopt
# side + penalty accounting); this dense-only variant re-proves it with
# two extra full solver compiles (~28 s)
def test_move_cost_blocks_unprofitable_moves():
    """With disruption pricing above the available comm gain, the solver
    stays put: zero moves adopted, objective unchanged, and the raw
    objective is still never worse."""
    from kubernetes_rescheduling_tpu.core.topology import synthetic_scenario

    scn = synthetic_scenario(n_pods=100, n_nodes=8, seed=9, mean_degree=4.0)
    free_state, free_info = global_assign(
        scn.state, scn.graph, jax.random.PRNGKey(0),
        GlobalSolverConfig(sweeps=6, move_cost=0.0),
    )
    free_gain = float(free_info["objective_before"]) - float(
        free_info["objective_after"]
    )
    assert free_gain > 0  # there IS improvement available on this instance
    # price each restart above the total available gain: nothing can pay
    priced_state, priced_info = global_assign(
        scn.state, scn.graph, jax.random.PRNGKey(0),
        GlobalSolverConfig(sweeps=6, move_cost=free_gain + 1.0),
    )
    np.testing.assert_array_equal(
        np.asarray(priced_state.pod_node), np.asarray(scn.state.pod_node)
    )
    assert not bool(priced_info["improved"])
    assert float(priced_info["move_penalty"]) == 0.0


@pytest.mark.slow  # the accept direction of the move-cost gate (profitable
# moves clear the restart bill, penalty reported) stays pinned fast by
# test_sharded_sparse.py::test_move_cost_parity_and_gate; the blocking
# direction keeps its own fast pin above
def test_move_cost_accepts_profitable_moves_and_reports_penalty():
    """A modest move price still lets high-value moves through; the
    adopted improvement exceeds the restart bill, and fewer pods restart
    than in the free solve (the emergent move budget)."""
    from kubernetes_rescheduling_tpu.core.topology import synthetic_scenario

    scn = synthetic_scenario(n_pods=200, n_nodes=8, seed=3, mean_degree=6.0)
    key = jax.random.PRNGKey(1)
    free_state, free_info = global_assign(
        scn.state, scn.graph, key, GlobalSolverConfig(sweeps=6)
    )
    moved_free = int(
        np.sum(
            (np.asarray(free_state.pod_node) != np.asarray(scn.state.pod_node))
            & np.asarray(scn.state.pod_valid)
        )
    )
    # measured frontier on this instance: cost 0 -> 136 pods move,
    # 4.0 -> 44, 8.0 -> nothing pays; 4.0 sits mid-frontier
    priced_state, priced_info = global_assign(
        scn.state, scn.graph, key, GlobalSolverConfig(sweeps=6, move_cost=4.0)
    )
    moved_priced = int(
        np.sum(
            (np.asarray(priced_state.pod_node) != np.asarray(scn.state.pod_node))
            & np.asarray(scn.state.pod_valid)
        )
    )
    assert bool(priced_info["improved"])
    pen = float(priced_info["move_penalty"])
    assert pen == pytest.approx(4.0 * moved_priced, rel=1e-5)
    # improvement covers the restart bill (the adopt gate's contract)
    assert (
        float(priced_info["objective_before"])
        - float(priced_info["objective_after"])
    ) > pen
    # pricing restarts shrinks the wave
    assert 0 < moved_priced < moved_free
    # raw objective still never worse
    assert float(
        communication_cost(priced_state, scn.graph)
    ) <= float(communication_cost(scn.state, scn.graph))


@pytest.mark.slow  # sparse/dense move-cost parity stays pinned fast by
# test_sharded_sparse.test_move_cost_parity_and_gate and
# test_parallel's restart-selection-under-move-cost case
def test_move_cost_sparse_matches_dense_semantics():
    """Sparse solver honors disruption pricing the same way."""
    from kubernetes_rescheduling_tpu.core import sparsegraph
    from kubernetes_rescheduling_tpu.core.topology import synthetic_scenario
    from kubernetes_rescheduling_tpu.solver import global_assign_sparse

    scn = synthetic_scenario(n_pods=512, n_nodes=8, powerlaw=True, seed=6)
    sg = sparsegraph.from_comm_graph(scn.graph)
    st, info = global_assign_sparse(
        scn.state, sg, jax.random.PRNGKey(0),
        GlobalSolverConfig(sweeps=4, move_cost=1e9),
    )
    np.testing.assert_array_equal(
        np.asarray(st.pod_node), np.asarray(scn.state.pod_node)
    )
    st2, info2 = global_assign_sparse(
        scn.state, sg, jax.random.PRNGKey(0),
        GlobalSolverConfig(sweeps=4, move_cost=0.1),
    )
    if bool(info2["improved"]):
        gain = float(info2["objective_before"]) - float(info2["objective_after"])
        assert gain > float(info2["move_penalty"])


def test_prepared_weights_identical_solve():
    """Injecting prepare_weights' matrix gives bit-identical decisions to
    the self-built path (it IS the same matrix)."""
    from kubernetes_rescheduling_tpu.solver.global_solver import prepare_weights

    # EXACTLY the (shape, config) signature test_never_worse_than_input
    # already compiled global_assign at — config is a static jit arg, so
    # the identical signature keeps this test's no-w_mm solve off the
    # tier-1 compile bill (the parity claim itself is size-independent;
    # only the w_mm variant's distinct trace compiles here)
    wm = mubench_workmodel_c()
    scn_state = state_from_workmodel(wm, seed=12)
    scn_graph = wm.comm_graph()
    cfg = GlobalSolverConfig(sweeps=4)
    key = jax.random.PRNGKey(2)
    w_mm = prepare_weights(scn_state, scn_graph, cfg)
    st_a, info_a = global_assign(scn_state, scn_graph, key, cfg)
    st_b, info_b = global_assign(scn_state, scn_graph, key, cfg, w_mm=w_mm)
    np.testing.assert_array_equal(
        np.asarray(st_a.pod_node), np.asarray(st_b.pod_node)
    )
    assert float(info_a["objective_after"]) == float(info_b["objective_after"])


def test_input_comm_cost_fast_and_slow_branches_agree():
    """The dense collapsed fast path (round 5) must agree with the
    occ@occᵀ quadratic form on both branch predicates: a split placement
    (slow) and its per-service collapse (fast)."""
    from kubernetes_rescheduling_tpu.objectives import communication_cost
    from kubernetes_rescheduling_tpu.solver.global_solver import (
        input_comm_cost,
    )

    scn = synthetic_scenario(
        n_pods=240, n_nodes=8, powerlaw=True, seed=12, replicas=3
    )
    rng = np.random.default_rng(2)
    nodes = rng.integers(0, 8, size=scn.state.num_pods)
    nodes[rng.random(scn.state.num_pods) < 0.1] = -1  # unplaced pods:
    # excluded from the accounting by BOTH branches (and by the metric)
    split = scn.state.replace(pod_node=jnp.asarray(nodes, jnp.int32))
    assert float(input_comm_cost(split, scn.graph)) == pytest.approx(
        float(communication_cost(split, scn.graph)), rel=1e-6
    )
    svc_first = np.full(scn.graph.num_services, -1, np.int64)
    pn = np.asarray(split.pod_node)
    ps = np.asarray(split.pod_service)
    for p in range(scn.state.num_pods):
        if svc_first[ps[p]] < 0:
            svc_first[ps[p]] = pn[p]
    collapsed = split.replace(pod_node=jnp.asarray(svc_first[ps], jnp.int32))
    assert float(input_comm_cost(collapsed, scn.graph)) == pytest.approx(
        float(communication_cost(collapsed, scn.graph)), rel=1e-6
    )


def test_split_invalid_service_cannot_defeat_collapsed_fast_path():
    """Regression (ADVICE round 5): an INVALID service contributes zero
    to both branches of `input_comm_cost`, so its pods being split
    across nodes must not flip the collapse predicate — that would
    silently route every chained production solve to the ~4 ms
    quadratic form."""
    from kubernetes_rescheduling_tpu.solver.global_solver import (
        comm_cost_collapse,
        input_comm_cost,
    )

    scn = synthetic_scenario(
        n_pods=240, n_nodes=8, powerlaw=True, seed=12, replicas=3
    )
    ps = np.asarray(scn.state.pod_service)
    # collapse every service onto one node...
    svc_first = np.arange(scn.graph.num_services) % 8
    nodes = svc_first[ps].astype(np.int64)
    # ...then invalidate one replicated service and split its pods
    victim = int(ps[0])
    graph = scn.graph.replace(
        service_valid=scn.graph.service_valid.at[victim].set(False)
    )
    victim_pods = np.flatnonzero(ps == victim)
    assert victim_pods.size >= 2, "need a replicated service to split"
    nodes[victim_pods] = np.arange(victim_pods.size) % 8
    state = scn.state.replace(pod_node=jnp.asarray(nodes, jnp.int32))

    _, _, collapsed = comm_cost_collapse(state, graph)
    assert bool(collapsed), (
        "split pods of an invalid service defeated the collapsed fast path"
    )
    # and a split VALID service still routes to the general form
    _, _, collapsed_valid = comm_cost_collapse(state, scn.graph)
    assert not bool(collapsed_valid)
    # value parity holds on both graphs regardless of routing
    assert float(input_comm_cost(state, graph)) == pytest.approx(
        float(communication_cost(state, graph)), rel=1e-6
    )
    assert float(input_comm_cost(state, scn.graph)) == pytest.approx(
        float(communication_cost(state, scn.graph)), rel=1e-6
    )
