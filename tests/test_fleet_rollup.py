"""Fleet-scale observability: device-side tenant rollups, the
cardinality budget, and the bounded live plane.

The invariants pinned here are the fleet-observability contract:

- the device rollup kernel re-derives against a host-side numpy twin
  within f32 tolerance (same nearest-rank quantiles, same tie order);
- an at-budget fleet's legacy per-tenant series and /healthz fleet
  block are BIT-IDENTICAL to the pre-budget plane (golden-pinned from
  the pre-PR code);
- an over-budget fleet's registry series count is independent of T —
  no tenant label keys exist anywhere, suppressions are counted, and
  the T=256 soak closes each round in the same ONE counted transfer
  (per K-round block under scan) with 1 steady-state trace per kernel;
- the watchdog prunes per-tenant state under churn and judges the p99
  cost rollup (fleet_tail_cost);
- the shared event ring is fair across tenants, with counted drops.
"""

import json
import math
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_rescheduling_tpu.backends.fleet import make_fleet
from kubernetes_rescheduling_tpu.bench.fleet import run_fleet_controller
from kubernetes_rescheduling_tpu.config import (
    ChaosConfig,
    ControllerConfig,
    FleetConfig,
    ObsConfig,
    RescheduleConfig,
)
from kubernetes_rescheduling_tpu.telemetry.fleet_rollup import (
    DIMS,
    NUM_DIMS,
    QUANTS,
    TenantSeries,
    TenantSummaryRing,
    decode_rollup,
    fleet_health_block,
    publish_rollup,
    rollup_event,
    rollup_matrix,
    rollup_numpy,
    rollup_size,
)
from kubernetes_rescheduling_tpu.telemetry.registry import (
    MetricsRegistry,
    set_registry,
)
from kubernetes_rescheduling_tpu.telemetry.server import OpsPlane
from kubernetes_rescheduling_tpu.telemetry.watchdog import (
    RULE_FLEET_TAIL,
    SLORules,
    Watchdog,
)
from kubernetes_rescheduling_tpu.utils.logging import StructuredLogger
from kubernetes_rescheduling_tpu.utils.retry import RetryPolicy


@pytest.fixture()
def registry():
    reg = MetricsRegistry()
    prev = set_registry(reg)
    yield reg
    set_registry(prev)


# ---------------- kernel vs numpy twin ----------------


@pytest.mark.parametrize("tenants,top_k", [(5, 1), (64, 3), (256, 4)])
def test_rollup_matrix_matches_numpy_twin(tenants, top_k):
    """The jitted device rollup and the host recompute agree: quantile
    values within f32 tolerance, worst-tenant indices exactly (distinct
    column values — ties are pinned separately)."""
    rng = np.random.default_rng(7 + tenants)
    matrix = rng.uniform(0.0, 100.0, size=(tenants, NUM_DIMS)).astype(
        np.float32
    )
    dev = np.asarray(jax.jit(
        lambda m: rollup_matrix(m, top_k=top_k)
    )(jnp.asarray(matrix)))
    host = rollup_numpy(matrix, top_k=top_k)
    assert dev.shape == host.shape == (rollup_size(top_k),)
    nq = NUM_DIMS * len(QUANTS)
    np.testing.assert_allclose(dev[:nq], host[:nq], rtol=1e-6)
    # sums: f32 accumulation order may differ — tolerance
    np.testing.assert_allclose(
        dev[nq : nq + NUM_DIMS], host[nq : nq + NUM_DIMS], rtol=1e-5
    )
    off = nq + NUM_DIMS
    np.testing.assert_allclose(
        dev[off : off + NUM_DIMS * top_k],
        host[off : off + NUM_DIMS * top_k],
        rtol=1e-6,
    )
    # indices: exact (values are distinct with probability 1)
    np.testing.assert_array_equal(
        dev[off + NUM_DIMS * top_k :], host[off + NUM_DIMS * top_k :]
    )


def test_rollup_tie_order_is_lowest_index_first():
    """Equal values (a fleet of identical tenants — the common mubench
    case) rank by tenant index on BOTH halves, so the worst-k rows stay
    deterministic and comparable."""
    matrix = np.ones((6, NUM_DIMS), np.float32)
    dev = np.asarray(
        jax.jit(lambda m: rollup_matrix(m, top_k=3))(jnp.asarray(matrix))
    )
    host = rollup_numpy(matrix, top_k=3)
    np.testing.assert_array_equal(dev, host)
    decoded = decode_rollup(dev, top_k=3)
    assert [r["tenant"] for r in decoded["dims"]["cost"]["worst"]] == [0, 1, 2]


def test_decode_rollup_roundtrip_and_errors():
    matrix = np.arange(4 * NUM_DIMS, dtype=np.float32).reshape(4, NUM_DIMS)
    flat = rollup_numpy(matrix, top_k=2)
    d = decode_rollup(flat, top_k=2)
    assert set(d["dims"]) == set(DIMS)
    cost = d["dims"]["cost"]
    assert set(cost["quantiles"]) == set(QUANTS)
    assert cost["quantiles"]["max"] == matrix[:, 0].max()
    assert cost["sum"] == pytest.approx(matrix[:, 0].sum())
    assert cost["worst"][0]["tenant"] == 3  # highest cost row
    with pytest.raises(ValueError, match="does not decode"):
        decode_rollup(flat[:-1], top_k=2)


# ---------------- the budget gate ----------------


def test_tenant_series_budget_gate(registry):
    under = TenantSeries(registry, tenants=3, budget=4)
    under.counter_inc("fleet_rounds_total", "h", "t0")
    under.gauge_set("fleet_communication_cost", "h", "t0", 5.0)
    c = registry.counter("fleet_rounds_total", labelnames=("tenant",))
    assert c.labels(tenant="t0").value == 1

    over = TenantSeries(registry, tenants=5, budget=4)
    assert not over.enabled
    over.counter_inc("fleet_moves_total", "h", "t1")
    over.gauge_set("fleet_load_std", "h", "t1", 1.0)
    snap = registry.snapshot()
    assert not any(r["metric"] == "fleet_moves_total" for r in snap)
    sup = registry.counter(
        "tenant_series_suppressed_total", labelnames=("family",)
    )
    assert sup.labels(family="fleet_moves_total").value == 1
    assert sup.labels(family="fleet_load_std").value == 1

    unlimited = TenantSeries(registry, tenants=10_000, budget=None)
    assert unlimited.enabled  # the solo ledger's ungated path


def test_tenant_summary_ring_bounded_and_lru():
    ring = TenantSummaryRing(cost_window=2, max_tenants=3)
    for i in range(5):
        ring.observe(
            f"t{i}",
            record={"round": 1, "communication_cost": float(i),
                    "degraded": False, "moved": True},
            breaker="closed",
            drift=i,
        )
    assert len(ring) == 3 and ring.evicted == 2
    assert ring.detail("t0") is None  # LRU-evicted
    d = ring.detail("t4")
    assert d["drift"] == 4 and d["costs"] == [4.0]
    ring.observe("t4", record={"communication_cost": 9.0})
    ring.observe("t4", record={"communication_cost": 8.0})
    assert ring.detail("t4")["costs"] == [9.0, 8.0]  # window capped at 2
    rows = ring.overview()
    assert [r["tenant"] for r in rows] == ["t2", "t3", "t4"]
    ring.observe("t2", skipped=True, breaker="open")
    assert ring.detail("t2")["skipped_rounds"] == 1
    assert ring.overview()[-1]["tenant"] == "t2"  # moved to MRU


def test_fleet_health_block_budget_gate():
    rows = {
        f"t{i}": {"breaker": "closed", "rounds": 2, "skipped_rounds": 0,
                  "degraded_rounds": 0}
        for i in range(4)
    }
    assert fleet_health_block(rows, budget=4) is rows  # bit-identical
    rows["t0"]["breaker"] = "open"
    out = fleet_health_block(rows, budget=3)
    assert out["suppressed"] and out["tenants"] == 4
    assert out["breaker_states"] == {"closed": 3, "open": 1}
    assert out["rounds"] == 8
    matrix = np.arange(4 * NUM_DIMS, dtype=np.float32).reshape(4, NUM_DIMS)
    rollup = decode_rollup(rollup_numpy(matrix, top_k=2), top_k=2)
    out = fleet_health_block(
        rows, budget=3, event=rollup_event(rollup, list(rows))
    )
    assert out["worst"][0]["tenant"] == "t3"
    assert set(out["quantiles"]) == set(DIMS)


# ---------------- watchdog: fleet_tail_cost + tenant pruning ----------------


def _rollup_with_p99(p99: float) -> dict:
    matrix = np.zeros((4, NUM_DIMS), np.float32)
    matrix[:, 0] = [1.0, 1.0, 1.0, p99]  # max == p99 position at T=4
    return decode_rollup(rollup_numpy(matrix, top_k=1), top_k=1)


def test_watchdog_fleet_tail_rule_fires_and_recovers(registry):
    wd = Watchdog(
        SLORules(window=8, min_samples=2, fleet_tail_frac=0.5),
        registry=registry,
    )
    for _ in range(3):
        assert wd.observe_fleet_rollup(_rollup_with_p99(10.0)) == []
    raised = wd.observe_fleet_rollup(_rollup_with_p99(20.0))
    assert [r["rule"] for r in raised] == [RULE_FLEET_TAIL]
    assert raised[0]["p99_cost"] == 20.0 and raised[0]["baseline"] == 10.0
    assert not wd.healthy
    # recovery: the tail drops back under threshold
    wd.observe_fleet_rollup(_rollup_with_p99(10.0))
    assert wd.healthy
    # rebase clears the window (a new run's cost scale is not judged
    # against the old run's)
    wd.observe_fleet_rollup(_rollup_with_p99(1.0))
    wd.rebase()
    assert wd.observe_fleet_rollup(_rollup_with_p99(100.0)) == []


class _Rec:
    def __init__(self, rnd, tenant_drift=None):
        self.round = rnd
        self.decision_latency_s = 0.001
        self.communication_cost = 1.0
        self.reconcile = (
            {"drift_pods": tenant_drift} if tenant_drift is not None else None
        )


def test_watchdog_prunes_churned_tenant_state(registry):
    """Regression (satellite): per-tenant windows grew without bound
    under tenant churn — unseen tenants now prune after
    tenant_ttl_rounds, counted, and a retired tenant's stale drift can
    no longer hold the reconcile rule in violation forever."""
    wd = Watchdog(
        SLORules(window=4, tenant_ttl_rounds=10, reconcile_max_drift_pods=1),
        registry=registry,
    )
    # 60 churning tenants, each seen exactly once at round r
    for r in range(1, 61):
        wd.observe_round(_Rec(r, tenant_drift=1), tenant=f"t{r}")
    assert len(wd._reconcile) <= 12  # bounded by the TTL, not by churn
    pruned = registry.counter("watchdog_tenants_pruned_total")
    assert pruned.value == 60 - len(wd._reconcile)
    # a persistent tenant is never pruned
    wd2 = Watchdog(SLORules(tenant_ttl_rounds=5), registry=registry)
    for r in range(1, 31):
        wd2.observe_round(_Rec(r, tenant_drift=0), tenant="steady")
    assert "steady" in wd2._reconcile
    # ttl=0 disables pruning
    wd3 = Watchdog(SLORules(tenant_ttl_rounds=0), registry=registry)
    for r in range(1, 31):
        wd3.observe_round(_Rec(r, tenant_drift=0), tenant=f"t{r}")
    assert len(wd3._reconcile) == 30


# ---------------- event-ring fairness ----------------


def test_logger_ring_fairness_caps_chatty_tenant(registry):
    log = StructuredLogger(
        max_records=16, max_records_per_tenant=4, registry=registry
    )
    for i in range(40):
        log.info("spam", tenant="chatty", i=i)
    log.info("quiet_event", tenant="quiet")
    for i in range(40):
        log.info("spam", tenant="chatty", i=i)
    recs = log.records
    # the chatty tenant evicted ITS OWN oldest events, never quiet's
    assert sum(1 for r in recs if r.get("tenant") == "chatty") == 4
    assert any(r.get("tenant") == "quiet" for r in recs)
    drops = registry.counter(
        "fleet_events_dropped_total", labelnames=("reason",)
    )
    assert drops.labels(reason="tenant_cap").value == 76
    assert log.dropped_by_tenant["chatty"] == 76
    assert log.dropped_by_tenant["quiet"] == 0


def test_logger_ring_full_evictions_are_counted(registry):
    log = StructuredLogger(max_records=4, registry=registry)
    for i in range(4):
        log.info("e", tenant=f"t{i}")
    log.info("no_tenant_event")  # evicts t0 — counted
    drops = registry.counter(
        "fleet_events_dropped_total", labelnames=("reason",)
    )
    assert drops.labels(reason="ring_full").value == 1
    assert log.dropped_by_tenant["t0"] == 1
    log.info("another")  # evicts t1
    assert drops.labels(reason="ring_full").value == 2


def test_fleet_chaos_soak_ring_fairness(registry):
    """The satellite's pin: a seeded chaos soak makes one tenant chatty
    (boundary failures, skips, breaker events) on a SMALL shared ring —
    every healthy tenant's events survive, and the chatty tenant's
    overflow is counted drops, not other tenants' silence."""
    fleet = make_fleet("mubench", 4, seed=0)
    cfg = RescheduleConfig(
        algorithm="communication",
        max_rounds=14,
        sleep_after_action_s=0.0,
        retry=RetryPolicy(max_attempts=1, base_delay_s=0.01),
        max_consecutive_failures=2,
        breaker_cooldown_rounds=2,
        chaos=ChaosConfig(profile="soak", seed=5),
        fleet=FleetConfig(tenants=4, chaos_tenants=(3,)),
    )
    logger = StructuredLogger(max_records=24)
    run_fleet_controller(
        fleet, cfg, key=jax.random.PRNGKey(0), registry=registry,
        logger=logger,
    )
    # the fleet loop armed fairness FOR THE RUN and restored the
    # logger's own config on exit (loggers are process-cached)
    assert logger.max_records_per_tenant == 0
    assert logger.registry is None
    by_tenant = {}
    for r in logger.records:
        if r.get("tenant"):
            by_tenant.setdefault(r["tenant"], []).append(r)
    for name in ("tenant0", "tenant1", "tenant2"):
        assert by_tenant.get(name), f"{name} evicted from the ring"
    drops = registry.counter(
        "fleet_events_dropped_total", labelnames=("reason",)
    )
    total_drops = sum(
        drops.labels(reason=reason).value
        for reason in ("tenant_cap", "ring_full")
    )
    assert total_drops > 0
    assert sum(logger.dropped_by_tenant.values()) == total_drops


# ---------------- at-budget bit-identity (golden) ----------------

LEGACY_FAMILIES = (
    "fleet_tenants",
    "fleet_rounds_total",
    "fleet_rounds_skipped_total",
    "fleet_degraded_rounds_total",
    "fleet_moves_total",
    "fleet_communication_cost",
    "fleet_load_std",
    "fleet_reconcile_drift_pods",
)


def _legacy_lines(registry) -> list[str]:
    out = []
    for line in registry.expose().splitlines():
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            name = line.split(" ")[2]
        else:
            name = line.split("{")[0].split(" ")[0]
        if name in LEGACY_FAMILIES:
            out.append(line)
    return out


def test_at_budget_fleet_matches_pre_budget_golden(registry, request):
    """An at-budget fleet's per-tenant series and /healthz fleet block
    are BYTE-IDENTICAL to the pre-PR plane (fixture captured from the
    pre-budget code on this exact seeded run)."""
    golden = json.loads(
        (request.config.rootpath / "tests" / "fixtures"
         / "fleet_legacy_golden.json").read_text()
    )
    fleet = make_fleet("mubench", 3, seed=0)
    fleet.inject_imbalance()
    cfg = RescheduleConfig(
        algorithm="communication", max_rounds=3, sleep_after_action_s=0.0,
        fleet=FleetConfig(tenants=3),
    )
    ops = OpsPlane.from_config(
        ObsConfig(serve_port=None), registry=registry
    ).start()
    try:
        run_fleet_controller(
            fleet, cfg, key=jax.random.PRNGKey(0), registry=registry,
            ops=ops,
        )
        payload, healthy = ops.health.snapshot()
    finally:
        ops.close()
    assert healthy
    assert _legacy_lines(registry) == golden["exposition"]
    assert payload["fleet"] == golden["healthz_fleet"]


# ---------------- over-budget: series count independent of T ----------------


def _fleet_series_keys(registry):
    return sorted(
        (r["metric"], tuple(sorted((r.get("labels") or {}).items())))
        for r in registry.snapshot()
        if r["metric"].startswith("fleet_")
        or r["metric"] == "tenant_series_suppressed_total"
    )


def _run_over_budget(tenants: int, registry) -> None:
    fleet = make_fleet("mubench", tenants, seed=0)
    fleet.inject_imbalance()
    cfg = RescheduleConfig(
        algorithm="communication", max_rounds=2, sleep_after_action_s=0.0,
        fleet=FleetConfig(tenants=tenants),
        obs=ObsConfig(tenant_label_budget=4),
    )
    run_fleet_controller(
        fleet, cfg, key=jax.random.PRNGKey(0), registry=registry
    )


def test_over_budget_series_set_is_independent_of_tenant_count():
    """The cardinality-budget pin: two over-budget fleets of different
    sizes produce the SAME fleet-family series set — growing T grows no
    series, and no series anywhere carries a tenant label key."""
    reg_a, reg_b = MetricsRegistry(), MetricsRegistry()
    prev = set_registry(reg_a)
    try:
        _run_over_budget(10, reg_a)
        set_registry(reg_b)
        _run_over_budget(14, reg_b)
    finally:
        set_registry(prev)
    keys_a, keys_b = _fleet_series_keys(reg_a), _fleet_series_keys(reg_b)
    assert keys_a == keys_b
    for reg in (reg_a, reg_b):
        assert not any(
            "tenant" in (r.get("labels") or {}) for r in reg.snapshot()
        )
        sup = reg.counter(
            "tenant_series_suppressed_total", labelnames=("family",)
        )
        assert sup.labels(family="fleet_rounds_total").value > 0


# ---------------- the T=256 acceptance soaks ----------------

ROLLUP_SERIES_BUDGET = (
    1            # fleet_tenants
    + 3 * 4      # cost/load_std/drift quantile families
    + 3          # degraded/skipped tenants + drift_pods totals
    + 5 * 3      # fleet_worst_tenant{rank,dim} at top_k=3
    + len(LEGACY_FAMILIES)  # suppression counters, one per family max
)


def _recompute_matrix(res, rnd: int, tenants: int) -> np.ndarray:
    """Rebuild the round's per-tenant metric matrix from the recorded
    per-tenant RoundRecords — the host-side oracle."""
    matrix = np.zeros((tenants, NUM_DIMS), np.float32)
    for t_idx in range(tenants):
        rec = next(
            r for r in res.results[f"tenant{t_idx}"].rounds
            if r.round == rnd
        )
        matrix[t_idx, 0] = rec.communication_cost
        matrix[t_idx, 1] = rec.load_std
        matrix[t_idx, 2] = 1.0 if rec.degraded else 0.0
        drift = (rec.reconcile or {}).get("drift_pods") or 0
        matrix[t_idx, 4] = float(drift)
    return matrix


def _check_rollup_events_vs_numpy(events, res, tenants, top_k):
    assert events, "no fleet_rollup events recorded"
    for ev in events:
        rnd = ev["round"]
        matrix = _recompute_matrix(res, rnd, tenants)
        oracle = decode_rollup(
            rollup_numpy(matrix, top_k=top_k), top_k=top_k
        )
        for dim in DIMS:
            for q in QUANTS:
                assert ev["quantiles"][dim][q] == pytest.approx(
                    oracle["dims"][dim]["quantiles"][q], rel=1e-5, abs=1e-5
                ), (rnd, dim, q)
            assert ev["sums"][dim] == pytest.approx(
                oracle["dims"][dim]["sum"], rel=1e-4, abs=1e-4
            )
        got_worst = {
            (w["dim"], w["rank"]): w["value"] for w in ev["worst"]
        }
        for dim in DIMS:
            for rank, row in enumerate(oracle["dims"][dim]["worst"]):
                assert got_worst[(dim, rank)] == pytest.approx(
                    row["value"], rel=1e-5, abs=1e-5
                )


def test_fleet_rollup_acceptance_t256_per_round(registry):
    """THE acceptance soak, per-round path: a 256-tenant fleet holds the
    series budget (independent of T), matches the numpy rollup oracle
    every round, closes each round in the same ONE counted metrics
    transfer, and runs 1 steady-state trace per kernel."""
    tenants = 256
    rounds = 3
    fleet = make_fleet("mubench", tenants, seed=0)
    fleet.inject_imbalance()
    cfg = RescheduleConfig(
        algorithm="communication", max_rounds=rounds,
        sleep_after_action_s=0.0,
        fleet=FleetConfig(tenants=tenants),
        obs=ObsConfig(tenant_label_budget=64),
    )
    logger = StructuredLogger(max_records=4096)
    res = run_fleet_controller(
        fleet, cfg, key=jax.random.PRNGKey(0), registry=registry,
        logger=logger,
    )
    assert res.total_rounds == tenants * rounds
    # cardinality: bounded independent of T, zero tenant label keys
    snap = registry.snapshot()
    assert not any("tenant" in (r.get("labels") or {}) for r in snap)
    fleet_series = [
        r for r in snap
        if r["metric"].startswith("fleet_")
        or r["metric"] == "tenant_series_suppressed_total"
    ]
    assert len(fleet_series) <= ROLLUP_SERIES_BUDGET
    # one counted decision transfer + one counted metrics transfer per
    # round — the rollup added ZERO
    pulls = registry.counter(
        "device_transfers_total", labelnames=("site",)
    )
    assert pulls.labels(site="fleet_decision").value == rounds
    assert pulls.labels(site="fleet_metrics").value == rounds
    # 1 steady-state trace per kernel
    traces = registry.counter("jax_traces_total", labelnames=("fn",))
    assert traces.labels(fn="fleet_solve").value == 1
    assert traces.labels(fn="fleet_round_bundle").value == 1
    # the device rollup re-derives from the recorded per-tenant rounds
    events = [r for r in logger.records if r["event"] == "fleet_rollup"]
    assert len(events) == rounds
    _check_rollup_events_vs_numpy(events, res, tenants, top_k=3)


def test_fleet_rollup_acceptance_t256_scan_block(registry):
    """THE acceptance soak, scan path: the same 256-tenant fleet
    advanced by ONE scan dispatch per K-round block — rollups ride the
    block's single counted round_end transfer, per-round rollups still
    match the oracle, and per-tenant streams match the per-round loop."""
    tenants = 256
    k = 3
    fleet = make_fleet("mubench", tenants, seed=0)
    fleet.inject_imbalance()
    cfg = RescheduleConfig(
        algorithm="communication", max_rounds=k,
        sleep_after_action_s=0.0,
        fleet=FleetConfig(tenants=tenants),
        obs=ObsConfig(tenant_label_budget=64),
        controller=ControllerConfig(scan_block=k),
    )
    logger = StructuredLogger(max_records=4096)
    res = run_fleet_controller(
        fleet, cfg, key=jax.random.PRNGKey(0), registry=registry,
        logger=logger,
    )
    assert res.total_rounds == tenants * k
    assert registry.counter("scan_blocks_total").value == 1
    # the whole block came home in ONE counted transfer
    pulls = registry.counter(
        "device_transfers_total", labelnames=("site",)
    )
    assert pulls.labels(site="round_end").value == 1
    assert pulls.labels(site="fleet_decision").value == 0
    traces = registry.counter("jax_traces_total", labelnames=("fn",))
    assert traces.labels(fn="fleet_scan_rounds").value == 1
    snap = registry.snapshot()
    assert not any("tenant" in (r.get("labels") or {}) for r in snap)
    events = [r for r in logger.records if r["event"] == "fleet_rollup"]
    assert len(events) == k
    _check_rollup_events_vs_numpy(events, res, tenants, top_k=3)


# ---------------- live plane: /tenants + breaker bundles ----------------


def _get(port, path):
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}")
    try:
        with urllib.request.urlopen(req, timeout=5) as resp:
            return resp.status, json.loads(resp.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())


def test_tenants_endpoints_serve_bounded_drilldown(registry):
    fleet = make_fleet("mubench", 3, seed=0)
    fleet.inject_imbalance()
    cfg = RescheduleConfig(
        algorithm="communication", max_rounds=2, sleep_after_action_s=0.0,
        fleet=FleetConfig(tenants=3),
        obs=ObsConfig(serve_port=0, tenant_label_budget=1),
    )
    ops = OpsPlane.from_config(cfg.obs, registry=registry).start()
    try:
        port = ops.server.port
        # before any fleet run: no drill-down
        status, body = _get(port, "/tenants")
        assert status == 404
        run_fleet_controller(
            fleet, cfg, key=jax.random.PRNGKey(0), registry=registry,
            ops=ops,
        )
        status, rows = _get(port, "/tenants")
        assert status == 200
        assert {r["tenant"] for r in rows} == {
            "tenant0", "tenant1", "tenant2"
        }
        assert all(r["rounds"] == 2 for r in rows)
        status, detail = _get(port, "/tenants/tenant1")
        assert status == 200
        assert detail["tenant"] == "tenant1"
        assert len(detail["costs"]) == 2
        assert detail["last"]["round"] == 2
        status, err = _get(port, "/tenants/nope")
        assert status == 404 and "unknown tenant" in err["error"]
        # the over-budget /healthz block is the bounded summary
        status, health = _get(port, "/healthz")
        assert health["fleet"]["suppressed"]
        assert health["fleet"]["tenants"] == 3
        assert health["fleet"]["worst"]
        # request accounting normalized the drill-down path (no
        # per-tenant endpoint label values)
        c = registry.counter(
            "ops_http_requests_total", labelnames=("endpoint",)
        )
        assert c.labels(endpoint="/tenants/<name>").value == 2
    finally:
        ops.close()


def test_breaker_open_bundle_scopes_to_offending_tenant(
    tmp_path, registry
):
    """A tenant breaker opening dumps a bundle carrying the latest
    fleet rollup plus ONLY the offending tenant's summary ring — never
    all T tenants' state for one tenant's incident."""
    fleet = make_fleet("mubench", 4, seed=0)
    cfg = RescheduleConfig(
        algorithm="communication",
        max_rounds=14,
        sleep_after_action_s=0.0,
        retry=RetryPolicy(max_attempts=1, base_delay_s=0.01),
        max_consecutive_failures=2,
        breaker_cooldown_rounds=2,
        chaos=ChaosConfig(profile="soak", seed=5),
        fleet=FleetConfig(tenants=4, chaos_tenants=(3,)),
        obs=ObsConfig(serve_port=None, bundle_dir=str(tmp_path)),
    )
    ops = OpsPlane.from_config(cfg.obs, registry=registry).start()
    try:
        res = run_fleet_controller(
            fleet, cfg, key=jax.random.PRNGKey(0), registry=registry,
            ops=ops,
        )
    finally:
        ops.close()
    assert any(
        tr["to"] == "open"
        for tr in res.results["tenant3"].breaker_transitions
    )
    bundles = sorted(tmp_path.glob("flight_*_breaker_open.json"))
    assert bundles
    bundle = json.loads(bundles[-1].read_text())
    assert bundle["transition"]["tenant"] == "tenant3"
    assert bundle["tenant_summary"]["tenant"] == "tenant3"
    assert bundle["fleet_rollup"]["worst"]
    assert set(bundle["fleet_rollup"]["quantiles"]) == set(DIMS)


# ---------------- the CLI report ----------------


def test_telemetry_fleet_report_renders(tmp_path, registry, capsys):
    fleet = make_fleet("mubench", 5, seed=0)
    fleet.inject_imbalance()
    events = tmp_path / "events.jsonl"
    logger = StructuredLogger(max_records=512, path=events)
    cfg = RescheduleConfig(
        algorithm="communication", max_rounds=3, sleep_after_action_s=0.0,
        fleet=FleetConfig(tenants=5),
        obs=ObsConfig(tenant_label_budget=2),
    )
    run_fleet_controller(
        fleet, cfg, key=jax.random.PRNGKey(0), registry=registry,
        logger=logger,
    )
    from kubernetes_rescheduling_tpu.cli import main as cli_main

    rc = cli_main(["telemetry", "fleet", str(events)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "fleet rollups: 3 rounds" in out
    assert "worst offenders" in out
    assert "cost" in out and "drift" in out


# ---------------- config & publish surfaces ----------------


def test_obs_config_fleet_rollup_validation():
    ObsConfig(tenant_label_budget=0, fleet_rollup_top_k=1).validate()
    with pytest.raises(ValueError, match="tenant_label_budget"):
        ObsConfig(tenant_label_budget=-1).validate()
    with pytest.raises(ValueError, match="fleet_rollup_top_k"):
        ObsConfig(fleet_rollup_top_k=0).validate()
    with pytest.raises(ValueError, match="slo_fleet_tail_frac"):
        ObsConfig(slo_fleet_tail_frac=-0.1).validate()
    with pytest.raises(ValueError, match="fleet_tail_frac"):
        SLORules(fleet_tail_frac=-1).validate()
    with pytest.raises(ValueError, match="tenant_ttl_rounds"):
        SLORules(tenant_ttl_rounds=-1).validate()


def test_obs_toml_fleet_block(tmp_path):
    p = tmp_path / "cfg.toml"
    p.write_text(
        "[obs]\n"
        "tenant_label_budget = 8\n"
        "fleet_rollup = false\n"
        "fleet_rollup_top_k = 5\n"
        "slo_fleet_tail_frac = 0.25\n"
    )
    cfg = RescheduleConfig.from_toml(p)
    assert cfg.obs.tenant_label_budget == 8
    assert cfg.obs.fleet_rollup is False
    assert cfg.obs.fleet_rollup_top_k == 5
    assert cfg.obs.slo_fleet_tail_frac == 0.25


def test_rollup_off_keeps_legacy_metrics_kernel(registry):
    """obs.fleet_rollup=False restores the historical fleet_metrics
    closer exactly: no rollup families, no fleet_round_bundle kernel."""
    fleet = make_fleet("mubench", 3, seed=0)
    fleet.inject_imbalance()
    cfg = RescheduleConfig(
        algorithm="communication", max_rounds=2, sleep_after_action_s=0.0,
        fleet=FleetConfig(tenants=3),
        obs=ObsConfig(fleet_rollup=False),
    )
    run_fleet_controller(
        fleet, cfg, key=jax.random.PRNGKey(0), registry=registry
    )
    snap = registry.snapshot()
    assert not any(
        r["metric"].startswith("fleet_cost_quantile") for r in snap
    )
    traces = registry.counter("jax_traces_total", labelnames=("fn",))
    assert traces.labels(fn="fleet_metrics").value == 1
    assert traces.labels(fn="fleet_round_bundle").value == 0


def test_exposition_conformance_fleet_rollup_families(registry):
    """Strict-parser pass over the rollup families as a live fleet
    emits them across rounds (the PR 5 conformance convention)."""
    from tests.test_observability import assert_exposition_conformant

    rng = np.random.default_rng(3)
    for _ in range(3):
        matrix = rng.uniform(0, 50, size=(8, NUM_DIMS)).astype(np.float32)
        publish_rollup(
            registry,
            decode_rollup(rollup_numpy(matrix, top_k=2), top_k=2),
        )
    families, samples = assert_exposition_conformant(registry.expose())
    for fam in (
        "fleet_cost_quantile",
        "fleet_load_std_quantile",
        "fleet_drift_quantile",
        "fleet_worst_tenant",
        "fleet_degraded_tenants",
        "fleet_skipped_tenants",
        "fleet_drift_pods",
    ):
        assert families[fam]["type"] == "gauge"
    # label budget: 4 q-points per quantile family, rank×dim for worst
    q_series = [k for k in samples if k[0] == "fleet_cost_quantile"]
    assert len(q_series) == 4
    worst_series = [k for k in samples if k[0] == "fleet_worst_tenant"]
    assert len(worst_series) == 2 * NUM_DIMS


def test_rollup_event_names_tenants():
    matrix = np.zeros((3, NUM_DIMS), np.float32)
    matrix[:, 0] = [1.0, 9.0, 5.0]
    rollup = decode_rollup(rollup_numpy(matrix, top_k=2), top_k=2)
    ev = rollup_event(rollup, ["a", "b", "c"], round=7)
    assert ev["round"] == 7
    cost_rows = [w for w in ev["worst"] if w["dim"] == "cost"]
    assert [w["tenant"] for w in cost_rows] == ["b", "c"]
